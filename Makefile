# dragonboat_tpu developer entry points (reference Makefile roles:
# test / monkey-test / benchmark — docs/test.md)

PY ?= python

.PHONY: test test-all test-kernels test-obs test-trace test-warmup \
	test-hostplane test-hostproc test-lease test-devsm test-health \
	test-repltrace test-devprof test-mesh test-recovery test-hiercommit \
	native soak soak-smoke soak-churn soak-churn-smoke \
	bench dryrun perf-ledger perf-ledger-check

test: native
	$(PY) -m pytest tests/ -x -q -m "not slow"

# fast local gate for kernel changes: the device-engine differential
# suites (fused ≡ single-round ≡ scalar oracle, incl. the read plane)
# standalone on the cpu backend — run this before the full tier-1 sweep
# whenever ops/kernels.py, ops/state.py, or ops/engine.py change
test-kernels:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_ops_quorum.py \
	    tests/test_multiround.py tests/test_read_confirm.py -q

# fast cpu gate for the observability plane (mirrors test-kernels): the
# flight recorder, Prometheus exposition round-trip, obs on/off engine
# parity and the stall-watchdog auto-dump — run before the full tier-1
# sweep whenever obs/, events.py, or the engine/coordinator hooks change
test-obs:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_obs.py tests/test_events.py -q

# fast cpu gate for cross-plane request tracing (ISSUE 9): trace-off
# structural identity (compartments on/off), stage-chain completeness on
# the scalar/tpu/fused paths incl. a membership recycle mid-trace, the
# stage-level stall watchdog (ErrorFS WAL stall), and the Perfetto
# export — run before the full tier-1 sweep whenever obs/trace.py,
# requests.py, or the node/engine/coordinator trace hooks change
test-trace:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_trace.py -q

# fast cpu gate for replication-path tracing + commit quorum attribution
# (ISSUE 14): trace-off structural identity on the chan AND tcp wires
# (codec byte-identity included), leader→follower→leader stage
# completeness, quorum-closing-peer vs the scalar kth-ack oracle under
# an injected slow peer, term-pinned records across leadership
# transfer, the multi-host Perfetto merge, and the transport/latency
# introspection satellites — run before the full tier-1 sweep whenever
# obs/replattr.py, wire/codec.py's trace carriage, transport metrics or
# the raft ack/commit hooks change
test-repltrace:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_repltrace.py -q

# fast cpu gate for the AOT warm-compile + persistent compilation cache
# (ISSUE 7): warmup against a temp cache dir asserts (a) a second enable
# is cache-hot (zero recompiles after jax.clear_caches) and (b)
# proposals issued during warmup never block on compilation — plus the
# live K-batched ≡ single-round ≡ scalar differential
test-warmup:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_warmup.py \
	    tests/test_live_fused.py -q

# fast cpu gate for the compartmentalized host plane (ISSUE 8): the
# batched-ingress ≡ direct-propose differential, SystemBusy/PayloadTooBig
# semantics, group-commit merge/error-propagation, ErrorFS flusher
# crash-durability (nothing acked before its fsync), journal replay, and
# the compartments-off structural bit-identity — run before the full
# tier-1 sweep whenever hostplane.py, engine.py, requests.py, queue.py
# or logdb/{kv,sharded,journal}.py change
test-hostplane:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_hostplane.py -q

# fast cpu gate for the multi-process host plane (ISSUE 12): shm-ring
# wraparound/backpressure units, the encode-worker ≡ inline oracle, the
# ProcStateMachine differential (incl. kill -9 exactly-once fallback and
# self-rebase), WAL-worker durability (injected fsync failure fails the
# whole flush cycle; dead worker degrades in-process), the rdbcache
# failed-commit invalidation, and the workers-off structural identity —
# run before the full tier-1 sweep whenever hostproc/, hostplane.py,
# logdb/{journal,rdb,sharded}.py or the nodehost wiring change
test-hostproc:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_hostproc.py -q

# fast cpu gate for the device state machine (ISSUE 11): the device KV
# apply ≡ scalar-oracle differential (kernel + engine level), the
# recycle/transition/snapshot semantics, the devsm-off structural
# identity, and the live single-node + 3-node failover paths — run
# before the full tier-1 sweep whenever ops/kernels.py's kv plane,
# ops/state.py's kv arrays, devsm/, or the coordinator/raft devsm hooks
# change
test-devsm:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_devsm.py -q

# fast cpu gate for the cluster health plane (ISSUE 13): health-off
# structural identity, the detector fault-injection suite (ErrorFS WAL
# stall -> commit_stall, netsplit -> quorum_at_risk, kill -9 ->
# worker_flap with measured recovery), the detector unit semantics on
# synthetic samples, and the /metrics + /healthz endpoint round-trip —
# run before the full tier-1 sweep whenever obs/health.py,
# obs/instruments.py, the nodehost health wiring or the plane
# health_snapshot accessors change
test-health:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_health.py -q

# fast cpu gate for the closed-loop recovery plane (ISSUE 17): the
# actuation matrix on a scripted NodeHost stub (quorum_at_risk ->
# evict+promote, leader_flap -> transfer with the hold-when-all-flapped
# rule, devsm_rebind -> force release, commit_stall -> fast-lane
# redrive, worker_flap observe-only), every guardrail (rate limit,
# cooldown, strike suppression, not_leader retries, dry run), the
# recovery-off structural identity and the live netsplit MTTR A/B —
# test_health runs FIRST (the recovery suite mutates the default
# detector registry; alphabetical tier-1 order already guarantees this)
# — run before the full tier-1 sweep whenever obs/recovery.py,
# obs/health.py's subscription API or the nodehost recovery wiring
# change
test-recovery:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_health.py \
	    tests/test_recovery.py -q

# fast cpu gate for the device capacity & profiling plane (ISSUE 15):
# profile-off structural identity, the HBM ledger ≡ live-array bytes
# differential, the capacity model's no-drift assertions against the
# shared upload accounting, warm-set program-registry coverage,
# padding-waste accounting and the /debug/devprof + capture-window
# lifecycle — run before the full tier-1 sweep whenever obs/devprof.py,
# the engine's dispatch accounting or ops/state.py's layout change
test-devprof:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_devprof.py -q

# fast cpu gate for the mesh-sharded dispatch plane (ISSUE 16): the
# mesh ≡ single-device ≡ scalar-oracle commit/read differentials, live
# migration with watermark preservation + the quiescence refusal,
# cost-driven rebalancing, verifiably-overlapping per-shard dispatch
# spans (the no-global-mutex proof), mesh warmup readiness, and the
# full 3-NodeHost sharded stack — run before the full tier-1 sweep
# whenever ops/mesh.py, ops/engine.py's dispatch path, the coordinator
# mesh branch or the placement/rebalance logic change
test-mesh:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_mesh_dispatch.py \
	    tests/test_sharding.py -q

# fast cpu gate for the leader-lease read plane (ISSUE 10): the
# lease ≡ ReadIndex ≡ scalar-oracle differential, the invalidation
# matrix (expiry/transfer-cede/membership/term), clock-jump fault
# injection caught by the linearizability checker, the cross-domain
# live-stack reads and the lease metric families — run before the full
# tier-1 sweep whenever lease.py, raft/raft.py's read path,
# transport/latency.py or the coordinator lease table change
test-lease:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_lease.py -q

# fast cpu gate for the hierarchical commit plane (ISSUE 18): sub-quorum
# ≡ classic differentials, the fused class-mask rule vs the scalar
# oracle, leader-change intersection safety and far-read batching — run
# before the full tier-1 sweep whenever raft/hier.py, the raft commit or
# vote paths, or the engine's hier fold change
test-hiercommit:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_hiercommit.py -q

# fast cpu gate for the device telemetry fold (ISSUE 20): the fold ≡
# host-oracle differential (sparse/fused/mesh paths, mid-block recycle,
# migration), stalled-watermark and top-K tie semantics, the telem-off
# structural identity, the aggregate sampler's drill-down walk +
# hysteresis units, the busy-row degradation counters, and the chunked
# /metrics + /debug/telem endpoints — run before the full tier-1 sweep
# whenever ops/kernels.py's telem fold, ops/state.py's telem plane,
# obs/health.py's aggregate mode or the engine/mesh harvest change
test-telem:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_telem.py -q

# parallel run: heavy multi-NodeHost modules carry
# xdist_group("heavy-multiprocess") and serialize on one worker while
# the light majority fans out (4 workers x multiprocess clusters
# starve each other on the 8-vCPU box otherwise).  Known residual race:
# tests allocate ephemeral ports via bind(0)+close before NodeHost
# rebinds them, so a concurrent worker can steal a just-released port —
# rare (not observed across repeated runs) and absent from the serial
# CI gate the driver uses.
test-par: native
	$(PY) -m pytest tests/ -q -n auto --dist loadgroup

test-all: native
	$(PY) -m pytest tests/ -x -q

native:
	$(MAKE) -C dragonboat_tpu/native

# race-detection gate for the C++ engine (the reference's RACE=1 make
# test role, docs Makefile:122-127): native suites under ThreadSanitizer.
# Scoped to the timing-robust modules — TSAN's 5-15x slowdown makes the
# enrollment-pacing chaos tests assert on scheduling, not races.
TSAN_RT := $(shell $(CXX) -print-file-name=libtsan.so)
TSAN_ENV = DBTPU_NATIVE_LIB_DIR=$(CURDIR)/dragonboat_tpu/native/tsan \
	LD_PRELOAD=$(TSAN_RT) \
	TSAN_OPTIONS="halt_on_error=0 report_thread_leaks=0 exitcode=66"
test-tsan:
	test -f "$(TSAN_RT)"  # libtsan runtime must exist
	$(MAKE) -C dragonboat_tpu/native tsan
	# the targeted suites skip themselves when the libs fail to load —
	# assert loadability FIRST so a broken TSAN env can't pass vacuously
	$(TSAN_ENV) $(PY) -c "from dragonboat_tpu.native import natraft, natsm, available; \
	    assert available() and natraft.available() and natsm.available(), \
	    'TSAN native libs failed to load'"
	$(TSAN_ENV) $(PY) -m pytest tests/test_natsm.py tests/test_partition_tcp.py \
	    tests/test_nativekv.py -q

# Drummer-analog chaos soak (docs/test.md:6-36): kill -9/restart churn,
# continuous cross-replica hash checks, linearizability on sampled keys
soak: native
	$(PY) soak.py --minutes 10 --groups 16

soak-smoke: native
	$(PY) soak.py --minutes 1 --groups 8

# native-plane soak: C-ABI KV + native exactly-once session store under
# the same churn — session-managed history clients retry unknown
# outcomes against the dedup store (at-most-once apply), and session
# hashes join the cross-replica convergence check
soak-native: native
	SOAK_NATIVE_SM=1 SOAK_SESSIONS=1 $(PY) soak.py --minutes 10 --groups 16

soak-native-smoke: native
	SOAK_NATIVE_SM=1 SOAK_SESSIONS=1 $(PY) soak.py --minutes 1 --groups 8

# BlackWater churn soak (ISSUE 17): 100 witness-heavy groups over 4
# hosts under leader-flap storms, netsplit holds, SIGSTOP stalls,
# kill -9 restarts and membership recycles — run twice with the same
# seed (once plain, once --recover) to reproduce the MTTR A/B the
# bench's churn_soak axis scores
soak-churn: native
	$(PY) soak.py --churn --minutes 1 --groups 100 --seed 7
	$(PY) soak.py --churn --minutes 1 --groups 100 --seed 7 --recover

soak-churn-smoke: native
	$(PY) soak.py --churn --minutes 0.1 --groups 20 --seed 7
	$(PY) soak.py --churn --minutes 0.1 --groups 20 --seed 7 --recover

bench: native
	$(PY) bench.py

# per-subsystem micro-benchmarks (reference `make benchmark`,
# benchmark_test.go families)
bench-micro: native
	$(PY) bench_micro.py

# regenerate the PERF.md A/B ledger tables from the committed bench
# artifact (VERDICT r5 item 4: every headline claim traceable to
# BENCH_DETAIL.json — run after each bench capture)
perf-ledger:
	$(PY) tools/perf_ledger.py

perf-ledger-check:
	$(PY) tools/perf_ledger.py --check

dryrun:
	$(PY) __graft_entry__.py
