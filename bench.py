"""Benchmark: batched quorum engine write throughput.

Headline metric (BASELINE.json): writes/sec through the quorum path at 16B
payload vs active group count.  The reference's published peak is 9M
writes/sec over 48 groups on a 3-node cluster (README Performance,
SURVEY.md §6).

Here G concurrent groups each commit one write per engine round
(leader self-ack + follower ack, quorum 2-of-3).  The host stages R rounds
of ingested event batches and the device scans them in ONE fused dispatch
(``quorum_multistep``) — the pipelined operating mode that amortizes
host↔device latency, mirroring the reference's accept-while-in-flight
pipelining (``execengine.go:954-966``).  Each dispatch pays the full
upload → R×step → commit-watermark readback cycle.

Prints one JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
from __future__ import annotations

import json
import os
import time

import numpy as np

import jax
import jax.numpy as jnp

BASELINE_WRITES_PER_SEC = 9_000_000.0


def build_state(n_groups: int, event_cap: int, n_peers: int = 3):
    from dragonboat_tpu.ops.engine import BatchedQuorumEngine

    eng = BatchedQuorumEngine(n_groups, n_peers, event_cap=event_cap)
    peers = list(range(1, n_peers + 1))
    for cid in range(1, n_groups + 1):
        eng.add_group(cid, node_ids=peers, self_id=1)
        eng.set_leader(cid, term=1, term_start=1, last_index=1)
    eng._upload_dirty()
    return eng


def main() -> None:
    from dragonboat_tpu.ops.kernels import quorum_multistep

    n_groups = int(os.environ.get("BENCH_GROUPS", "8192"))
    rounds = int(os.environ.get("BENCH_ROUNDS", "64"))      # R per dispatch
    dispatches = int(os.environ.get("BENCH_DISPATCHES", "20"))
    warmup = 3

    cap = 2 * n_groups  # self-ack + follower ack per group per round
    eng = build_state(n_groups, cap)
    st = eng.dev

    rows = np.arange(n_groups, dtype=np.int32)
    ack_g = np.broadcast_to(
        np.concatenate([rows, rows]), (rounds, cap)
    ).copy()
    ack_p = np.broadcast_to(
        np.concatenate([np.zeros(n_groups, np.int32), np.ones(n_groups, np.int32)]),
        (rounds, cap),
    ).copy()
    ack_valid = jnp.asarray(np.ones((rounds, cap), bool))
    zeros_i32 = jnp.asarray(np.zeros((rounds, cap), np.int32))
    zeros_i8 = jnp.asarray(np.zeros((rounds, cap), np.int8))
    zeros_b = jnp.asarray(np.zeros((rounds, cap), bool))
    ack_g_d = jnp.asarray(ack_g)
    ack_p_d = jnp.asarray(ack_p)

    def dispatch(st, base_index):
        # round r acks the entry appended that round: index base+r+1
        vals = (base_index + 1 + np.arange(rounds, dtype=np.int32))[:, None]
        ack_val = np.broadcast_to(vals, (rounds, cap)).copy()
        t0 = time.perf_counter()
        out = quorum_multistep(
            st,
            ack_g_d,
            ack_p_d,
            jnp.asarray(ack_val),
            ack_valid,
            zeros_i32,
            zeros_i32,
            zeros_i8,
            zeros_b,
            do_tick=True,
        )
        committed = np.asarray(out.committed)  # egress readback (blocks)
        return out.state, committed, time.perf_counter() - t0

    base = 1  # groups start with noop at index 1 committed? (committed=0, last=1)
    for _ in range(warmup):
        st, committed, _ = dispatch(st, base)
        base += rounds
    assert committed[0] == base, (committed[:4], base)

    times = []
    t0 = time.perf_counter()
    for _ in range(dispatches):
        st, committed, dt = dispatch(st, base)
        times.append(dt)
        base += rounds
    elapsed = time.perf_counter() - t0
    assert committed[0] == base

    writes = n_groups * rounds * dispatches
    writes_per_sec = writes / elapsed
    p99_dispatch_ms = float(np.percentile(np.array(times) * 1e3, 99))
    print(
        json.dumps(
            {
                "metric": "quorum_engine_writes_per_sec",
                "value": round(writes_per_sec, 1),
                "unit": "writes/s",
                "vs_baseline": round(writes_per_sec / BASELINE_WRITES_PER_SEC, 4),
                "detail": {
                    "groups": n_groups,
                    "rounds_per_dispatch": rounds,
                    "dispatches": dispatches,
                    "dispatch_p99_ms": round(p99_dispatch_ms, 3),
                    "platform": jax.devices()[0].platform,
                },
            }
        )
    )


if __name__ == "__main__":
    main()
