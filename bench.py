"""Benchmark: batched quorum engine write throughput.

Headline metric (BASELINE.json): writes/sec through the quorum path at 16B
payload vs active group count.  The reference's published peak is 9M
writes/sec over 48 groups on a 3-node cluster (README Performance,
SURVEY.md §6).

Two operating points are measured, mirroring the reference's own
throughput-vs-latency trade (`docs/test.md:40-53` tables):

* **pipelined** — G groups each commit one write per engine round; the host
  stages R rounds of event batches and the device scans them in ONE fused
  dispatch (``quorum_multistep``), amortizing host↔device latency.  This is
  the throughput-maximal mode (the analog of the reference's
  accept-while-in-flight pipelining, ``execengine.go:954-966``).
* **latency-bounded** — continuous small-R dispatches (R from
  BENCH_LAT_ROUNDS, default 1) measuring per-dispatch wall time; the p99 of
  that is the device-side commit-latency floor (BASELINE.md's "P99 commit
  latency" axis).

Robustness contract with the driver: this script ALWAYS prints exactly one
JSON line {"metric", "value", "unit", "vs_baseline", "detail"} on stdout.
The tunneled TPU backend ("axon") can be flaky, so backend init is retried
and falls back to CPU with the platform recorded in detail.platform
(round 1 died in backend init and emitted nothing — BENCH_r01.json rc=1).
"""
from __future__ import annotations

import functools
import json
import os
import sys
import time
import traceback

import numpy as np

BASELINE_WRITES_PER_SEC = 9_000_000.0


def _note(msg: str) -> None:
    """Diagnostics go to stderr — stdout carries exactly one JSON line."""
    print(f"# {msg}", file=sys.stderr)


#: diagnostics from every TPU probe attempt (surfaced in the artifact so a
#: cpu fallback is attributable — VERDICT r3 missing #1: the r03 record had
#: no TPU number and nothing explaining why)
PROBE_LOG: list = []


def _probe_tpu(timeout: float = 90.0, tries: int = 2):
    """Probe the default (TPU) backend in a SUBPROCESS with a timeout.

    The tunneled axon backend can hang (not just fail) during init —
    MULTICHIP_r01.json rc=124 — so the probe must be killable.  Only if a
    subprocess sees a live non-cpu device does the main process touch the
    default backend at all.
    """
    import subprocess
    import sys

    code = "import jax; print(jax.devices()[0].platform)"
    for attempt in range(tries):
        rec = {"attempt": len(PROBE_LOG) + 1}
        try:
            r = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True,
                text=True,
                timeout=timeout,
            )
            rec["rc"] = r.returncode
            if r.stderr:
                rec["stderr"] = r.stderr[-400:]
            if r.returncode == 0 and r.stdout.strip():
                platform = r.stdout.strip().splitlines()[-1].strip()
                if platform:
                    rec["platform"] = platform
                    PROBE_LOG.append(rec)
                    return platform
        except subprocess.TimeoutExpired:
            rec["timeout_s"] = timeout
        PROBE_LOG.append(rec)
        _note(f"tpu probe attempt failed: {rec}")
        if attempt + 1 < tries:
            time.sleep(2.0 * (attempt + 1))
    return None


def _resolve_platform(probed=None) -> str:
    from dragonboat_tpu import hostplatform

    forced = os.environ.get("BENCH_PLATFORM")
    if forced == "cpu":
        hostplatform.force_cpu()
    else:
        if forced is not None:
            _note(f"ignoring BENCH_PLATFORM={forced!r} (only 'cpu' supported)")
        if probed is None:
            probed = _probe_tpu()
        if probed is None or probed == "cpu":
            _note("TPU backend probe failed; falling back to cpu")
            hostplatform.force_cpu()
    import jax

    try:
        return jax.devices()[0].platform
    except Exception as e:  # probe said live but init still died
        _note(f"backend init failed after successful probe: {e!r}")
        hostplatform.force_cpu()
        hostplatform.clear_backends()
        return jax.devices()[0].platform


def build_state(n_groups: int, event_cap: int, n_peers: int = 3,
                device_ticks: bool = True):
    from dragonboat_tpu.ops.engine import BatchedQuorumEngine

    eng = BatchedQuorumEngine(
        n_groups, n_peers, event_cap=event_cap, device_ticks=device_ticks
    )
    peers = list(range(1, n_peers + 1))
    for cid in range(1, n_groups + 1):
        eng.add_group(cid, node_ids=peers, self_id=1)
        eng.set_leader(cid, term=1, term_start=1, last_index=1)
    eng._upload_dirty()
    return eng


def _staged_multistep_fn(n_groups: int, rounds: int):
    """Jitted R-round staged dispatch; event tensors derived on device.

    Uses the DENSE ingestion kernel (kernels.quorum_step_dense_impl): a
    round's acks collapse into a per-(group, peer) max matrix — exact,
    because scatter-max aggregation is order-independent — and ingestion
    becomes pure elementwise max/or, which measured 7× faster than the
    scatter form at this shape (14.0 → 2.0 ms/round at 131k groups).
    Each round every group's leader self-acks and one follower acks the
    next index, the same per-round traffic the sparse staging produced
    (committed advances exactly one index per group per round; _run_mode
    asserts it).
    """
    import jax
    import jax.numpy as jnp

    from dragonboat_tpu.ops.kernels import quorum_step_dense_impl

    n_peers = 3

    @functools.partial(jax.jit, donate_argnums=(0,))
    def staged_multistep(st, base_index):
        touched = jnp.broadcast_to(
            jnp.arange(n_peers, dtype=jnp.int32)[None, :] < 2,
            (n_groups, n_peers),
        )

        def body(carry, r):
            vals = jnp.where(
                jnp.arange(n_peers, dtype=jnp.int32)[None, :] < 2,
                base_index + 1 + r,
                0,
            )
            ack_max = jnp.broadcast_to(vals, (n_groups, n_peers))
            out = quorum_step_dense_impl(
                carry,
                ack_max,
                touched,
                jnp.zeros((1, 1), jnp.int8),
                do_tick=True,
                # every benched row is a LEADER (build_state set_leader),
                # and the contact reset writes only non-leader rows —
                # provably a no-op here, so it compiles out; ticks
                # themselves stay on (heartbeat/check-quorum clocks run)
                track_contact=False,
                has_votes=False,
            )
            return out.state, None

        st, _ = jax.lax.scan(
            body, st, jnp.arange(rounds, dtype=jnp.int32)
        )
        from dragonboat_tpu.ops.kernels import StepOutputs, TickFlags

        zeros = jnp.zeros((n_groups,), bool)
        return StepOutputs(
            st, st.committed, zeros, zeros, TickFlags(zeros, zeros, zeros)
        )

    return staged_multistep


def _run_mode(n_groups: int, rounds: int, dispatches: int, warmup: int = 3):
    """Run one operating point; returns (writes/s, per-dispatch times)."""
    import jax
    import jax.numpy as jnp

    # event_cap only matters for the engine's own sparse staging (unused
    # by the dense staged dispatch); keep it minimal
    eng = build_state(n_groups, 64)
    st = eng.dev
    staged = _staged_multistep_fn(n_groups, rounds)

    def dispatch(st, base_index):
        t0 = time.perf_counter()
        out = staged(st, jnp.int32(base_index))
        committed = np.asarray(out.committed)  # egress readback (blocks)
        return out.state, committed, time.perf_counter() - t0

    base = 1
    committed = None
    for _ in range(warmup):
        st, committed, _ = dispatch(st, base)
        base += rounds
    assert committed[0] == base, (committed[:4], base)

    times = []
    t0 = time.perf_counter()
    for _ in range(dispatches):
        st, committed, dt = dispatch(st, base)
        times.append(dt)
        base += rounds
    elapsed = time.perf_counter() - t0
    assert committed[0] == base

    writes = n_groups * rounds * dispatches
    return writes / elapsed, times


def _run_e2e(on_tpu: bool, engine: str, extra_env=None, timeout_key: str = "BENCH_E2E_TIMEOUT") -> dict:
    """Run bench_e2e in a killable subprocess tree.

    Called BEFORE this process initializes jax: in multiprocess mode the
    rank-0 child attaches to the (single) TPU chip, which must not be held
    by the parent at that point.
    """
    import subprocess

    env = dict(os.environ)
    env["E2E_TPU"] = "1" if on_tpu else "0"
    env["E2E_ENGINE"] = engine
    env.update(extra_env or {})
    timeout_s = float(os.environ.get(timeout_key, "600"))
    env.setdefault("E2E_DEADLINE", str(max(60.0, timeout_s - 60.0)))
    try:
        r = subprocess.run(
            [sys.executable, os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "bench_e2e.py")],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            env=env,
        )
        if r.returncode == 0 and r.stdout.strip():
            return json.loads(r.stdout.strip().splitlines()[-1])
        return {
            "error": f"rc={r.returncode}",
            "tail": (r.stderr or r.stdout)[-500:],
        }
    except Exception as e:
        return {"error": repr(e)}


def _check_cancel(cancel) -> None:
    """Cooperative watchdog flag for device-rung workers: a wedged
    tunneled backend degrades to an error entry, and the daemon worker
    stops DISPATCHING the moment the watchdog gives up — it must not keep
    feeding the device while the cpu fallback measures (ISSUE 1
    satellite; previously the abandoned thread ran to completion)."""
    if cancel is not None and cancel.is_set():
        raise RuntimeError("rung cancelled by watchdog")


def _run_host_loop(n_groups: int, rounds: int, k: int = 16,
                   cancel=None) -> dict:
    """Engine throughput through the real host staging path — now the
    K-round FUSED shape every ladder section runs (ISSUE 1 tentpole):
    per scanned round every group's leader self-ack and one follower ack
    are staged via the vectorized bulk-ingest API, ``begin_round`` closes
    the round, and ONE ``step_rounds`` dispatch scans all ``k`` rounds on
    device.  Host staging of block i+1 overlaps the in-flight dispatch of
    block i (``pipelined=True`` double-buffering), and egress is the
    vectorized watermark view — no per-row Python anywhere.  ``rounds``
    counts DISPATCHES; total engine rounds = rounds × k."""
    if rounds < 1 or n_groups < 1 or k < 1:
        return {"error": f"invalid parameters: groups={n_groups} rounds={rounds} k={k}"}
    # host-driven clocks: this mode never ticks on device, so the
    # contact-reset scatter compiles out (see kernels.quorum_step_impl)
    eng = build_state(n_groups, 2 * n_groups, device_ticks=False)
    rows = np.tile(np.arange(n_groups, dtype=np.int32), 2)
    slots = np.concatenate(
        [np.zeros(n_groups, np.int32), np.ones(n_groups, np.int32)]
    )

    def stage_block(base):
        # K rounds in one validated staging call: same (row, slot)
        # geometry every round, advancing rel indexes (ack_block_rounds)
        rels = (
            base + 1 + np.arange(k, dtype=np.int32)[:, None]
            + np.zeros((1, rows.size), np.int32)
        )
        eng.ack_block_rounds(rows, slots, rels)

    # warmup (jit compile of the fused K-round program)
    base = 1
    stage_block(base)
    eng.step_rounds(do_tick=False)
    base += k
    t0 = time.perf_counter()
    for _ in range(rounds):
        _check_cancel(cancel)
        stage_block(base)
        # returns the PREVIOUS block's egress; this block stays in flight
        # while the next one stages (ingress double-buffering)
        eng.step_rounds(do_tick=False, pipelined=True)
        base += k
    eng.harvest()
    view = eng.committed_view()
    elapsed = time.perf_counter() - t0
    assert view[0] == base, (view[:4], base)
    return {
        "groups": n_groups,
        "rounds": rounds,
        "rounds_per_dispatch": k,
        "writes_per_sec": round(n_groups * rounds * k / elapsed, 1),
    }


def _slim_e2e(e2e: dict) -> dict:
    """Headline-safe summary of an e2e result dict.

    The driver records only the last ~2000 chars of output: round 3's
    per-rank fast-lane stats bloated the JSON line past that and truncated
    the metric away (`BENCH_r03.json parsed: null`).  The full dict goes to
    BENCH_DETAIL.json; the stdout line carries only scalars.
    """
    if not isinstance(e2e, dict):
        return e2e
    out = {}
    for k in ("error", "groups", "hosts", "engine", "sm", "leader_mode",
              "writes_per_sec", "setup_s"):
        if k in e2e:
            out[k] = e2e[k]
    lat = e2e.get("commit_latency_ms")
    if isinstance(lat, dict):
        out["commit_latency_ms"] = {
            k: lat[k] for k in ("p50", "p99") if k in lat
        }
    mixed = e2e.get("mixed_phase")
    if isinstance(mixed, dict) and "ops_per_sec" in mixed:
        out["mixed_ops_per_sec"] = mixed["ops_per_sec"]
    fl = e2e.get("fastlane")
    if isinstance(fl, list):
        ranks = [r for r in fl if isinstance(r, dict)]
        if ranks:
            # scalars only: three e2e sections ride one stdout line and
            # the per-rank lists overflowed the driver's 2000-char tail
            # (full per-rank stats live in BENCH_DETAIL.json)
            duties = [
                r.get("enroll_duty") for r in ranks
                if isinstance(r.get("enroll_duty"), (int, float))
            ]
            out["fastlane"] = {
                "enroll_duty_min": min(duties) if duties else None,
                "ejects": sum(
                    sum((r.get("eject_reasons") or {}).values())
                    for r in ranks
                ),
                "dropped_spans": sum(
                    r.get("dropped_spans") or 0 for r in ranks
                ),
            }
    if e2e.get("rank_errors"):
        out["rank_errors"] = len(e2e["rank_errors"])
    if "tail" in e2e:
        out["tail"] = e2e["tail"][-200:]
    return out


def _run_rung4(n_groups: int = 65_536, rounds: int = 8, k: int = 16,
               cancel=None) -> dict:
    """Rung-4 batched-engine numbers (BASELINE.md ladder): 64k groups ×
    5 peer slots — every group commits once per scanned round via the
    vectorized ack_block ingest (quorum of 5 = self + 2 acks), K rounds
    fused per dispatch with double-buffered staging (ISSUE 1 tentpole),
    and sampled commit-watermark queries as the read-side probe.  The
    correctness twin (differential vs scalar oracles + membership/leader
    churn, and the genuinely mixed-load variant) is tests/test_rung4.py
    plus the fused-block differential in tests/test_multiround.py.
    ``rounds`` counts DISPATCHES; total engine rounds = rounds × k.

    A mixed 9:1 PHASE follows the pure-write window (ISSUE 3 tentpole):
    every group stages a batch of 9 ReadIndex requests per scanned round
    alongside its write, two followers echo the batch in the same round,
    and the fused ``read_confirm`` plane releases it in the dispatch that
    advances the commits.  ``reads_per_sec`` is the CONFIRMED ReadIndex
    rate through that plane (the honest read-path number VERDICT r5 weak
    #5 asked for); the old host-side watermark-query rate is kept as
    ``probe_reads_per_sec``."""
    from dragonboat_tpu.ops.engine import BatchedQuorumEngine

    eng = BatchedQuorumEngine(
        n_groups, 5, event_cap=4 * n_groups, device_ticks=False
    )
    peers = [1, 2, 3, 4, 5]
    for cid in range(1, n_groups + 1):
        eng.add_group(cid, node_ids=peers, self_id=1)
        eng.set_leader(cid, term=1, term_start=1, last_index=1)
    eng._upload_dirty()
    rows = np.arange(n_groups, dtype=np.int32)
    rows3 = np.concatenate([rows, rows, rows])
    slots = np.concatenate([
        np.zeros(n_groups, np.int32), np.ones(n_groups, np.int32),
        np.full(n_groups, 2, np.int32),
    ])

    def stage_block(start_rel):
        # one validated staging call for the whole K-round block
        rels = (
            start_rel + np.arange(k, dtype=np.int32)[:, None]
            + np.zeros((1, rows3.size), np.int32)
        )
        eng.ack_block_rounds(rows3, slots, rels)

    # warmup (compile the fused K-round program)
    stage_block(2)
    eng.step_rounds(do_tick=False)
    reads = writes = 0
    # read probe rows (~576 sampled watermarks per dispatch): validated
    # against the vectorized egress view the dispatch already paid for —
    # per-cid committed_index readbacks are ~67ms each on a tunneled
    # backend (the reason this rung used to be CPU-only).  reads_per_sec
    # measures the host-side watermark-query rate over fresh egress data.
    probe = np.arange(0, n_groups, max(1, n_groups // 576), dtype=np.int64)
    rel = k + 1  # committed after warmup
    expect_prev = None  # watermark the in-flight block will land on
    t0 = time.perf_counter()
    for _ in range(rounds):
        _check_cancel(cancel)
        stage_block(rel + 1)
        res = eng.step_rounds(do_tick=False, pipelined=True)
        if res is not None:
            # probe the PREVIOUS block's egress vector directly — it is
            # already host-side; touching committed_view here would
            # harvest (and so serialize) the in-flight dispatch
            assert (res.committed_rel[probe] == expect_prev).all(), (
                res.committed_rel[probe][:4], expect_prev
            )
            reads += probe.size
        expect_prev = rel + k
        rel += k
        writes += n_groups * k
    final = eng.harvest()
    elapsed = time.perf_counter() - t0
    assert (final.committed_rel[probe] == rel).all(), (
        final.committed_rel[probe][:4], rel
    )
    reads += probe.size
    assert eng.committed_index(1) == rel

    # ---- mixed 9:1 phase: ReadIndex through the device read plane ----
    # (per scanned round: 1 write commit + a 9-read ctx batch per group;
    # echoes from followers 2 and 3 land the same round, so read_confirm
    # releases the batch inside the same fused dispatch)
    rows2 = np.concatenate([rows, rows])
    peers2 = np.concatenate(
        [np.ones(n_groups, np.int32), np.full(n_groups, 2, np.int32)]
    )
    counts9 = np.full(n_groups, 9, np.int32)
    reads_confirmed = 0
    mwrites = 0
    mtimes = []

    def mixed_dispatch():
        nonlocal rel
        for _ in range(k):
            rel += 1
            eng.ack_block(rows3, slots, np.full(rows3.size, rel, np.int32))
            sl = eng.stage_read_block(
                rows, np.full(n_groups, rel, np.int32), counts9
            )
            eng.read_ack_block(rows2, np.concatenate([sl, sl]), peers2)
            eng.begin_round()
        return eng.step_rounds(do_tick=False, pipelined=True)

    mixed_dispatch()  # warmup: compile the read-plane fused program
    eng.harvest()
    reads_confirmed = 0
    t0 = time.perf_counter()
    for _ in range(rounds):
        _check_cancel(cancel)
        td0 = time.perf_counter()
        res = mixed_dispatch()
        if res is not None and res.read_counts is not None:
            reads_confirmed += int(res.read_counts.sum())
        mtimes.append(time.perf_counter() - td0)
        mwrites += n_groups * k
    final = eng.harvest()
    melapsed = time.perf_counter() - t0
    if final is not None and final.read_counts is not None:
        reads_confirmed += int(final.read_counts.sum())
    expected = n_groups * 9 * rounds * k
    assert reads_confirmed == expected, (reads_confirmed, expected)
    assert eng.committed_index(1) == rel
    mixed = {
        "read_ratio": 9,
        "reads_per_sec": round(reads_confirmed / melapsed, 1),
        "writes_per_sec": round(mwrites / melapsed, 1),
        "ops_per_sec": round((reads_confirmed + mwrites) / melapsed, 1),
        "read_dispatch_p99_ms": round(
            float(np.percentile(np.array(mtimes) * 1e3, 99)), 3
        ),
    }
    return {
        "groups": n_groups,
        "peer_slots": 5,
        "rounds": rounds,
        "rounds_per_dispatch": k,
        "writes_per_sec": round(writes / elapsed, 1),
        # the ReadIndex-confirmation rate (device read plane); the
        # watermark-probe rate this field used to carry moved to
        # probe_reads_per_sec
        "reads_per_sec": mixed["reads_per_sec"],
        "probe_reads_per_sec": round(reads / elapsed, 1),
        "mixed": mixed,
    }


def _run_cpu_section(fn_name: str, spec: list, timeout: float = 420.0) -> dict:
    """Run a bench section on the LOCAL cpu backend in a subprocess.

    The parent process may have initialized jax against the tunneled TPU;
    host-path sections (rung 4/5 coordinator ingest) must not ride it.
    ``spec`` is [env_name, default, env_name, default, ...]; parsing
    happens HERE so a malformed env var degrades one section to an error
    entry instead of zeroing the whole record.
    """
    import subprocess

    try:
        args = [
            int(os.environ.get(spec[i], str(spec[i + 1])))
            for i in range(0, len(spec), 2)
        ]
    except (ValueError, TypeError) as e:
        return {"error": f"bad env for {fn_name}: {e!r}"}

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("BENCH_PLATFORM", None)
    # JAX_PLATFORMS=cpu alone is NOT enough: jax still initializes every
    # registered plugin backend, and the tunneled axon client hangs (not
    # fails) when the tunnel is down — force_cpu() drops the factory
    # os._exit after the JSON lands: the result is already on stdout, and
    # interpreter teardown with large donated device buffers + a cleared
    # jit cache (the live-coord axis's restart simulation) can segfault
    # in the XLA CPU client's destructor order — a teardown-only crash
    # that must not discard a completed measurement
    code = (
        "from dragonboat_tpu import hostplatform; hostplatform.force_cpu(); "
        "import json, os, sys, bench; "
        f"print(json.dumps(bench.{fn_name}(*{args!r}))); "
        "sys.stdout.flush(); os._exit(0)"
    )
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=timeout,
            cwd=os.path.dirname(os.path.abspath(__file__)), env=env,
        )
        if r.returncode != 0:
            return {"error": f"rc={r.returncode}", "tail": r.stderr[-400:]}
        out = json.loads(r.stdout.strip().splitlines()[-1])
        out["platform"] = "cpu"
        return out
    except Exception as e:
        return {"error": repr(e)[:300]}


def _run_rung5(n_groups: int = 100_000, rounds: int = 6, k: int = 8,
               churn_block: int = 2_048, cancel=None) -> dict:
    """Rung-5 batched-engine numbers (BASELINE.md ladder, final rung):
    100k groups × 5 peer slots with membership churn ROLLING THROUGH the
    load — every scanned round recycles ``churn_block`` rows while every
    surviving group commits once.  The churn now travels INSIDE the
    dispatched program (``stage_recycle`` → masked row resets in
    ``kernels.quorum_multiround``, the VERDICT §7 design pivot) instead
    of as per-recycle host re-uploads, so K churn+commit rounds fuse into
    ONE dispatch with double-buffered staging.  The correctness twin
    (differential vs scalar oracles, leader transfers, bit-identity every
    round) is tests/test_rung5.py plus the recycle-mid-block differential
    in tests/test_multiround.py.  ``rounds`` counts DISPATCHES."""
    from dragonboat_tpu.ops.engine import BatchedQuorumEngine

    eng = BatchedQuorumEngine(
        n_groups, 5, event_cap=4 * n_groups, device_ticks=False
    )
    peers = [1, 2, 3, 4, 5]
    for cid in range(1, n_groups + 1):
        eng.add_group(cid, node_ids=peers, self_id=1)
        eng.set_leader(cid, term=1, term_start=1, last_index=1)
    eng._upload_dirty()
    rows = np.arange(n_groups, dtype=np.int32)
    rows3 = np.concatenate([rows, rows, rows])
    slots = np.concatenate([
        np.zeros(n_groups, np.int32), np.ones(n_groups, np.int32),
        np.full(n_groups, 2, np.int32),
    ])
    rel = np.full(n_groups, 1, np.int64)  # per-row committed rel watermark
    live = np.arange(1, n_groups + 1, dtype=np.int64)  # cid per row
    next_cid = n_groups + 1
    state = {"rel": rel, "next_cid": next_cid, "churn_at": 0}

    def stage_block():
        """K scanned rounds: recycle a rotating row block IN-PROGRAM,
        then every row commits one more entry."""
        rel = state["rel"]
        for _ in range(k):
            lo = state["churn_at"] % n_groups
            block = range(lo, min(lo + churn_block, n_groups))
            for i in block:
                cid = state["next_cid"]
                state["next_cid"] += 1
                eng.stage_recycle(
                    int(live[i]), cid, term=1, term_start=1, last_index=1
                )
                live[i] = cid
                rel[i] = 1
            state["churn_at"] += churn_block
            rel += 1
            rels3 = np.concatenate([rel, rel, rel]).astype(np.int32)
            eng.ack_block(rows3, slots, rels3)
            eng.begin_round()
            state["recycled"] = state.get("recycled", 0) + len(block)

    # warmup (compile the fused churn+commit program)
    stage_block()
    eng.step_rounds(do_tick=False)
    state["recycled"] = 0  # report only the measured window's churn
    probe = np.arange(0, n_groups, max(1, n_groups // 576), dtype=np.int64)
    reads = writes = 0
    prev_rel = None  # expected watermarks of the in-flight block
    t0 = time.perf_counter()
    for _ in range(rounds):
        _check_cancel(cancel)
        stage_block()
        res = eng.step_rounds(do_tick=False, pipelined=True)
        if res is not None:
            # vectorized probe of the PREVIOUS block's egress (rung-4
            # comment: committed_view here would serialize the pipeline)
            assert (res.committed_rel[probe] == prev_rel[probe]).all()
            reads += probe.size
        prev_rel = rel.copy()
        writes += n_groups * k
    final = eng.harvest()
    elapsed = time.perf_counter() - t0
    assert (final.committed_rel[probe] == rel[probe]).all(), (
        final.committed_rel[probe][:4], rel[probe][:4]
    )
    reads += probe.size
    return {
        "groups": n_groups,
        "peer_slots": 5,
        "rounds": rounds,
        "rounds_per_dispatch": k,
        "recycled_groups": state.get("recycled", 0),
        "writes_per_sec": round(writes / elapsed, 1),
        # host-side watermark-query rate (naming aligned with rung 4:
        # reads_per_sec is reserved for the ReadIndex confirm plane)
        "probe_reads_per_sec": round(reads / elapsed, 1),
    }


def _run_idle_axis(active: int = 1024, idle: int = 15_360, rounds: int = 6,
                   k: int = 8, cancel=None) -> dict:
    """Idle-groups-are-free axis (VERDICT r5 item 6; reference claim
    ``quiesce.go:84-86`` / README "thousands of idle Raft groups").

    Two engines of the SAME provisioned capacity (``active + idle``
    rows) run the identical fused write loop over the ``active`` set
    with device ticks firing every scanned round; variant A additionally
    registers ``idle`` live, device-clocked follower groups (clocks
    advance on every tick round; election timeouts large enough that no
    flag fires).  The measured delta is the steady-state cost of idle
    OCCUPANCY: per-tick host work is zero by construction (one fused
    tick kernel covers every row), staging cost keys off ACTIVE traffic,
    and the tensor cost keys off provisioned capacity — a deploy-time
    choice both variants share, exactly like the reference provisioning
    its worker pools.  The variants run INTERLEAVED windows and compare
    best-of (measured here: single A/B pairs on this box swing ±30%
    either direction from scheduler weather alone — best-of-interleaved
    is the same discipline PERF.md applies to the e2e A/Bs).  Asserts
    the delta < 10% and records it in the artifact."""
    from dragonboat_tpu.ops.engine import BatchedQuorumEngine

    total = active + idle
    peers = [1, 2, 3]
    rows = np.arange(active, dtype=np.int32)
    rows2 = np.tile(rows, 2)
    slots = np.concatenate(
        [np.zeros(active, np.int32), np.ones(active, np.int32)]
    )

    def build(register_idle: bool):
        eng = BatchedQuorumEngine(
            total, 3, event_cap=4 * total, device_ticks=True
        )
        for cid in range(1, active + 1):
            eng.add_group(cid, node_ids=peers, self_id=1)
            eng.set_leader(cid, term=1, term_start=1, last_index=1)
        if register_idle:
            for cid in range(active + 1, total + 1):
                # device-clocked idle followers: election clocks advance
                # every tick round; the (huge) timeout never fires inside
                # the bench window, mirroring a quiesced group whose
                # clock ownership moved off the host
                eng.add_group(
                    cid, node_ids=peers, self_id=1,
                    election_timeout=1 << 20,
                )
        eng._upload_dirty()
        return eng

    engs = {"idle": build(True), "alone": build(False)}
    bases = {"idle": 1, "alone": 1}

    def window(name: str) -> float:
        eng = engs[name]
        base = bases[name]
        t0 = time.perf_counter()
        for _ in range(rounds):
            _check_cancel(cancel)
            rels = (
                base + 1 + np.arange(k, dtype=np.int32)[:, None]
                + np.zeros((1, rows2.size), np.int32)
            )
            eng.ack_block_rounds(rows2, slots, rels)
            eng.step_rounds(do_tick=True, pipelined=True)
            base += k
        eng.harvest()
        elapsed = time.perf_counter() - t0
        view = eng.committed_view()
        assert view[0] == base, (view[:4], base)
        bases[name] = base
        return active * rounds * k / elapsed

    for name in ("idle", "alone"):  # warmup: compile + first dispatch
        window(name)
    wps_idle = wps_alone = 0.0
    for pair in range(6):  # interleaved pairs, best-of
        wps_idle = max(wps_idle, window("idle"))
        wps_alone = max(wps_alone, window("alone"))
        if pair >= 2 and (wps_alone - wps_idle) / wps_alone < 0.05:
            break  # verdict already clear; spare the box
    delta_pct = round((wps_alone - wps_idle) / wps_alone * 100.0, 2)
    # the assert IS the axis: idle occupancy must cost < 10%
    assert delta_pct < 10.0, (
        f"idle groups not free: {delta_pct}% "
        f"({wps_idle:.0f} vs {wps_alone:.0f} w/s)"
    )
    return {
        "active_groups": active,
        "idle_groups": idle,
        "rounds": rounds,
        "rounds_per_dispatch": k,
        "writes_per_sec_with_idle": round(wps_idle, 1),
        "writes_per_sec_alone": round(wps_alone, 1),
        "idle_delta_pct": delta_pct,
        "idle_free_ok": True,
    }


def _run_obs_axis(active: int = 16_384, rounds: int = 6, k: int = 16,
                  cancel=None) -> dict:
    """Obs-overhead axis (ISSUE 5 satellite): the rung-5-shaped host loop
    with the flight recorder + metric instruments ON vs OFF.

    Two engines of identical capacity run the same fused K-round write
    loop; variant "obs" carries a FlightRecorder (stall watchdog off —
    this axis measures steady state, not stalls) and a private
    MetricsRegistry.  Interleaved windows, best-of (the same scheduler-
    weather discipline as the idle axis).  The assert IS the axis:
    obs-on throughput must stay within 5% of obs-off — the enable-latch
    contract that keeps the obs-off host path bit-identical has a twin
    obligation that obs-ON stays cheap enough to leave on in production.
    The recorder's JSON dump ships in the artifact so the perf ledger
    derives its dispatch-latency / multidev-wait columns from the record
    itself (tools/perf_ledger.py)."""
    from dragonboat_tpu.events import MetricsRegistry
    from dragonboat_tpu.obs import FlightRecorder
    from dragonboat_tpu.ops.engine import BatchedQuorumEngine

    peers = [1, 2, 3]
    rows = np.arange(active, dtype=np.int32)
    rows2 = np.tile(rows, 2)
    slots = np.concatenate(
        [np.zeros(active, np.int32), np.ones(active, np.int32)]
    )

    def build():
        eng = BatchedQuorumEngine(
            active, 3, event_cap=4 * active, device_ticks=False
        )
        for cid in range(1, active + 1):
            eng.add_group(cid, node_ids=peers, self_id=1)
            eng.set_leader(cid, term=1, term_start=1, last_index=1)
        eng._upload_dirty()
        return eng

    engs = {"off": build(), "obs": build()}
    rec = FlightRecorder(capacity=64, stall_ms=0)
    reg = MetricsRegistry()
    engs["obs"].enable_obs(recorder=rec, registry=reg)
    bases = {"off": 1, "obs": 1}

    def window(name: str) -> float:
        eng = engs[name]
        base = bases[name]
        t0 = time.perf_counter()
        for _ in range(rounds):
            _check_cancel(cancel)
            rels = (
                base + 1 + np.arange(k, dtype=np.int32)[:, None]
                + np.zeros((1, rows2.size), np.int32)
            )
            eng.ack_block_rounds(rows2, slots, rels)
            eng.step_rounds(do_tick=False, pipelined=True)
            base += k
        eng.harvest()
        elapsed = time.perf_counter() - t0
        view = eng.committed_view()
        assert view[0] == base, (view[:4], base)
        bases[name] = base
        return active * rounds * k / elapsed

    for name in ("off", "obs"):  # warmup: compile + first dispatch
        window(name)
    wps_off = wps_obs = 0.0
    for pair in range(6):  # interleaved pairs, best-of
        wps_obs = max(wps_obs, window("obs"))
        wps_off = max(wps_off, window("off"))
        if pair >= 2 and (wps_off - wps_obs) / wps_off < 0.025:
            break  # verdict already clear; spare the box
    delta_pct = round((wps_off - wps_obs) / wps_off * 100.0, 2)
    assert delta_pct < 5.0, (
        f"obs overhead too high: {delta_pct}% "
        f"({wps_obs:.0f} vs {wps_off:.0f} w/s)"
    )
    return {
        "active_groups": active,
        "rounds": rounds,
        "rounds_per_dispatch": k,
        "writes_per_sec_obs_off": round(wps_off, 1),
        "writes_per_sec_obs_on": round(wps_obs, 1),
        "obs_overhead_pct": delta_pct,
        "obs_overhead_ok": True,
        "device_metric_families": len([
            f for f in reg.families() if f.startswith("dragonboat_device_")
        ]),
        # the recorder dump of record: the perf ledger sources its
        # dispatch-latency and multidev-wait columns from these spans
        "recorder": rec.to_json(limit=64),
    }


class _LiveNode:
    """Node shim for the live-coordinator axis: commit effects re-applied
    under raftMu with the scalar guards intact — the offload path the
    real NodeHost runs, minus transport."""

    __slots__ = ("cluster_id", "raft_mu", "peer", "commits", "obs_registry")

    def __init__(self, cid, raft):
        import threading

        self.cluster_id = cid
        self.raft_mu = threading.RLock()

        class _P:
            pass

        self.peer = _P()
        self.peer.raft = raft
        self.commits = 0
        self.obs_registry = None

    def offload_commit(self, q):
        r = self.peer.raft
        with self.raft_mu:
            if r.is_leader() and r.log.try_commit(q, r.term):
                self.commits += 1

    def offload_election(self, won, term):
        pass

    def offload_tick_elect(self):
        pass

    def offload_tick_heartbeat(self):
        pass

    def offload_tick_demote(self):
        pass


def _run_live_coord_axis(groups: int = 512, iters: int = 20) -> dict:
    """Live-coordinator adaptive-K axis (ISSUE 7 tentpole).

    The SAME live round — one append + two follower acks per group, a
    K-tick backlog, one coordinator round through the scalar-guarded
    offload path — driven through (a) a WARMED coordinator, whose round
    fuses the backlog into one multi-round dispatch, and (b) an UNWARMED
    one, whose round replays the backlog per-step (the pre-ISSUE-7
    behavior).  K sweeps the adaptive range; K=1 is the quiet-round
    case, where both modes run the identical single-round program.

    Also captured, because the perf ledger's live columns are
    ledger-backed, not prose: warm-enable wall seconds (cold and
    cache-hot after ``jax.clear_caches()`` — the in-process twin of a
    restart), persistent-cache hit/miss counts, the fused dispatch
    count, and the flight-recorder dump proving fused k_rounds>1
    dispatches on the live path with zero stalled spans."""
    import tempfile

    from dragonboat_tpu.config import Config
    from dragonboat_tpu.obs import FlightRecorder
    from dragonboat_tpu.ops.engine import enable_persistent_compilation_cache
    from dragonboat_tpu.raft import InMemLogDB, Raft
    from dragonboat_tpu.raft.remote import Remote
    from dragonboat_tpu.tpuquorum import TpuQuorumCoordinator
    from dragonboat_tpu.wire import Entry

    cache_base = tempfile.mkdtemp(prefix="dbtpu-bench-cc-")
    enable_persistent_compilation_cache(cache_base)

    def mk_coord(warm: bool):
        coord = TpuQuorumCoordinator(
            capacity=groups, n_peers=4, drive_ticks=True, interval_s=60.0,
        )
        # deterministic drive: rounds run through flush() only (the
        # round thread would consume the staged tick backlog mid-stage)
        coord._stopped.set()
        coord._pending.set()
        coord._thread.join(timeout=10)
        if warm:
            coord.eng.warmup_fused(background=False)
        nodes = {}
        for g in range(groups):
            cid = 1 + g
            r = Raft(
                Config(node_id=1, cluster_id=cid, election_rtt=10,
                       heartbeat_rtt=1),
                InMemLogDB(), seed=g,
            )
            for p in (1, 2, 3):
                if p not in r.remotes:
                    r.remotes[p] = Remote(next=1)
            r.reset_match_value_array()
            r.has_not_applied_config_change = lambda: False
            r.become_candidate()
            r.become_leader()
            n = _LiveNode(cid, r)
            r.offload = coord
            nodes[cid] = n
            coord._nodes[cid] = n
            with coord._mu:
                coord._sync_row_locked(n)
        coord.flush()
        return coord, nodes

    t0 = time.perf_counter()
    warm_coord, warm_nodes = mk_coord(warm=True)
    warm_enable_s = round(warm_coord.warmup_stats["seconds"], 3)
    cold_stats = dict(warm_coord.warmup_stats)
    rec = FlightRecorder(capacity=256, stall_ms=1000.0)
    warm_coord.enable_obs(recorder=rec)
    single_coord, single_nodes = mk_coord(warm=False)
    setup_s = round(time.perf_counter() - t0, 2)

    def window(coord, nodes, k) -> float:
        t0 = time.perf_counter()
        for _ in range(iters):
            for cid, n in nodes.items():
                r = n.peer.raft
                with n.raft_mu:
                    r.append_entries([Entry(cmd=b"w")])
                    idx = r.log.last_index()
                coord.ack(cid, 2, idx)
                coord.ack(cid, 3, idx)
            for _ in range(k):
                coord.request_tick()
            coord.flush()
        return groups * iters / (time.perf_counter() - t0)

    k_axis = {}
    for k in (1, 4, 8, 16):
        # first window per mode warms any residual first-use ops, then
        # interleaved best-of (the obs axis's scheduler-weather rule)
        window(warm_coord, warm_nodes, k)
        window(single_coord, single_nodes, k)
        wps_fused = wps_single = 0.0
        for _ in range(3):
            wps_fused = max(wps_fused, window(warm_coord, warm_nodes, k))
            wps_single = max(
                wps_single, window(single_coord, single_nodes, k)
            )
        k_axis[str(k)] = {
            "writes_per_sec_fused": round(wps_fused, 1),
            "writes_per_sec_single": round(wps_single, 1),
            "speedup": round(wps_fused / wps_single, 3),
        }

    spans = rec.spans()
    fused_spans = [s for s in spans if s["kind"] == "fused"]
    stalled = [
        s for s in spans
        if s.get("stalled") and s["kind"] in ("fused", "dispatch")
    ]
    warm_coord.stop()
    single_coord.stop()
    # cache-hot second enable: a REAL restart — a fresh process pointed
    # at the same cache directory warms the identical engine shape and
    # must deserialize every program from disk.  (An in-process
    # jax.clear_caches() twin segfaults jaxlib at this scale — double
    # free inside clear_all_caches with live donated executables.)
    import subprocess

    hot = {"hits": None, "misses": None, "enable_seconds": None}
    code = (
        "from dragonboat_tpu import hostplatform; hostplatform.force_cpu()\n"
        "import json, os, sys, time\n"
        "from dragonboat_tpu.ops.engine import (\n"
        "    BatchedQuorumEngine, enable_persistent_compilation_cache)\n"
        f"enable_persistent_compilation_cache({cache_base!r})\n"
        f"eng = BatchedQuorumEngine({groups}, 4, "
        f"event_cap={max(4 * groups, 4096)}, device_ticks=True)\n"
        "t0 = time.perf_counter()\n"
        "st = eng.warmup_fused(background=False)\n"
        "print(json.dumps({'enable_seconds': "
        "round(time.perf_counter() - t0, 3), 'hits': st['cache_hits'], "
        "'misses': st['cache_misses']}))\n"
        "sys.stdout.flush(); os._exit(0)\n"
    )
    try:
        r = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=300.0, env=dict(os.environ, JAX_PLATFORMS="cpu"),
        )
        if r.returncode == 0 and r.stdout.strip():
            hot = json.loads(r.stdout.strip().splitlines()[-1])
        else:
            hot["error"] = f"rc={r.returncode}"
    except Exception as e:
        hot["error"] = repr(e)[:200]
    return {
        "groups": groups,
        "iters": iters,
        "setup_s": setup_s,
        "k_axis": k_axis,
        "live_writes_per_sec": max(
            v["writes_per_sec_fused"] for v in k_axis.values()
        ),
        "live_writes_per_sec_single": max(
            v["writes_per_sec_single"] for v in k_axis.values()
        ),
        "fused_dispatches": warm_coord.fused_dispatches,
        "warm_enable_seconds": warm_enable_s,
        "warm_programs": cold_stats["programs"],
        "cache_cold": {
            "hits": cold_stats["cache_hits"],
            "misses": cold_stats["cache_misses"],
        },
        "cache_hot": hot,
        "stalled_spans": len(stalled),
        "fused_span_k_rounds": sorted(
            {int(s.get("k_rounds", 0)) for s in fused_spans}
        ),
        "recorder": rec.to_json(limit=96),
    }


def _run_mesh_axis(groups: int = 512, rounds: int = 4, k: int = 8,
                   cancel=None) -> dict:
    """Mesh-dispatch shard-count axis (ISSUE 16): the SAME fused K-round
    write loop at shards ∈ {1, 2, 4, 8} — one single-device engine at
    shards=1, the ``MeshQuorumEngine`` facade above that — reporting
    aggregate and implied per-shard writes/s per mesh size.

    Graduated from the driver's ``dryrun_multichip`` hook: the dry-run
    proved bit-identity on the 8-device virtual cpu mesh; this rung puts
    a THROUGHPUT number on the same topology, plus the live-migration
    wall time and the peak dispatch-stream concurrency read off the
    shared flight recorder's shard-tagged spans (>1 is the
    no-global-mutex evidence).

    On the cpu backend the 8 virtual devices share the host's real
    cores, so shard streams contend for the compute they are supposed to
    parallelize — the artifact carries an explicit ``noise`` label and
    the ≥0.6x-per-doubling scaling gate applies only off-cpu (where
    each shard owns real silicon).  The ledger prints the label next to
    every cpu row."""
    from dragonboat_tpu import hostplatform

    n_devices = 8
    hostplatform.set_host_device_count(n_devices)
    hostplatform.force_cpu()

    import jax

    from dragonboat_tpu.events import MetricsRegistry
    from dragonboat_tpu.obs import FlightRecorder
    from dragonboat_tpu.ops.engine import BatchedQuorumEngine
    from dragonboat_tpu.ops.mesh import MeshQuorumEngine

    devices = jax.local_devices(backend="cpu")
    if len(devices) < n_devices:
        hostplatform.clear_backends()
        devices = jax.local_devices(backend="cpu")
    devices = devices[:n_devices]
    on_cpu = devices[0].platform == "cpu"
    peers = [1, 2, 3]

    def build(n_shards: int):
        # one spare row per shard: migration needs a free row on the
        # target, and the exactly-sized mesh would refuse every move
        cap = groups + n_shards
        if n_shards == 1:
            eng = BatchedQuorumEngine(cap, 3, event_cap=4 * groups)
        else:
            eng = MeshQuorumEngine(
                cap, 3, event_cap=4 * groups,
                devices=devices[:n_shards],
            )
        for cid in range(1, groups + 1):
            eng.add_group(cid, node_ids=peers, self_id=1)
            eng.set_leader(cid, term=1, term_start=1, last_index=1)
        eng._upload_dirty()
        return eng

    def window(eng, base: int) -> float:
        """One measured fused window: K staged rounds on every shard,
        one mesh fan-out, blocking harvest.  Returns elapsed seconds."""
        shards = getattr(eng, "shards", None) or [eng]
        t0 = time.perf_counter()
        for _ in range(rounds):
            _check_cancel(cancel)
            for s in shards:
                n = len(s.groups)
                rows = np.array(
                    sorted(gi.row for gi in s.groups.values()), np.int32
                )
                rows2 = np.tile(rows, 2)
                slots = np.concatenate(
                    [np.zeros(n, np.int32), np.ones(n, np.int32)]
                )
                rels = (
                    base + 1 + np.arange(k, dtype=np.int32)[:, None]
                    + np.zeros((1, rows2.size), np.int32)
                )
                s.ack_block_rounds(rows2, slots, rels)
            eng.step_rounds(do_tick=True, pipelined=True)
            base += k
        eng.harvest()
        elapsed = time.perf_counter() - t0
        # the highest cid never migrates in this rung: a stable probe of
        # the commit watermark on both engine shapes
        got = eng.committed_index(groups)
        assert got == base, (got, base)
        return elapsed

    axis = {}
    mesh8 = None
    for n_shards in (1, 2, 4, 8):
        eng = build(n_shards)
        window(eng, 1)  # warmup: compile + first dispatch
        base = 1 + rounds * k
        best = min(window(eng, base + p * rounds * k) for p in range(3))
        axis[str(n_shards)] = {
            "writes_per_sec": round(groups * rounds * k / best, 1),
        }
        if n_shards == 8:
            mesh8 = eng  # keep the widest mesh for migration/obs probes
        else:
            if hasattr(eng, "stop"):
                eng.stop()

    # live migration + concurrency evidence on the widest mesh
    reg = MetricsRegistry()
    rec = FlightRecorder(stall_ms=0)
    mesh8.enable_obs(rec, registry=reg)
    mig_walls = []
    base = 1 + 4 * rounds * k
    for m in range(4):
        cid = 1 + m
        src = mesh8.shard_index(cid)
        t0 = time.perf_counter()
        ok = mesh8.migrate_group(cid, (src + 1) % mesh8.n_shards)
        if ok:
            mig_walls.append((time.perf_counter() - t0) * 1e3)
    window(mesh8, base)  # instrumented window: shard-tagged spans
    spans = []
    for s in rec.spans():
        if s.get("shard") is None or "egress_ms" not in s:
            continue
        start = s["ts"]
        end = start + (
            (s.get("dispatch_ms") or 0.0) + (s["egress_ms"] or 0.0)
        ) / 1e3
        spans.append((start, end, s["shard"]))
    peak = 0
    for start, end, shard in spans:
        live = {
            sh for (a, b, sh) in spans if a < end and start < b
        }
        peak = max(peak, len(live))
    mesh8.stop()

    ws1 = axis["1"]["writes_per_sec"]
    out = {
        "groups": groups,
        "rounds": rounds,
        "rounds_per_dispatch": k,
        "shards_axis": axis,
        "migration": {
            "count": len(mig_walls),
            "wall_ms_p50": round(
                sorted(mig_walls)[len(mig_walls) // 2], 3
            ) if mig_walls else None,
        },
        "concurrency_peak": peak,
        "scaling_vs_1shard": {
            n: round(v["writes_per_sec"] / ws1, 3) for n, v in axis.items()
        },
    }
    if on_cpu:
        out["noise"] = (
            "cpu: 8 virtual devices share the host cores — shard "
            "streams contend, scaling gate waived"
        )
    else:
        # off-cpu every shard owns real silicon: gate the per-doubling
        # scaling factor (ISSUE 16 acceptance: >= 0.6x ideal)
        prev = None
        for n in ("1", "2", "4", "8"):
            ws = axis[n]["writes_per_sec"]
            if prev is not None:
                assert ws >= 0.6 * 2 * prev, (
                    f"mesh scaling below 0.6x ideal at shards={n}: "
                    f"{ws:.0f} vs {prev:.0f} w/s"
                )
            prev = ws
    return out


def dryrun_multichip(n_devices: int) -> None:
    """Device-ticks differential under an ``n_devices`` group-sharded mesh.

    Group-axis sharding is this framework's whole parallelism story (the
    analog of the reference's clusterID%workers partitioning — SURVEY.md
    §2.7): state tensors split on the group axis, event batches replicated,
    zero collectives in steady state.

    Not a single hand-built step: 64 groups run a full seeded scenario —
    elections fired by DEVICE tick processing (elect_due asserted against
    the exact tick each scalar oracle campaigns), seeded vote outcomes
    including lost elections that re-campaign, 100+ commit rounds with the
    FULL commit vector asserted bit-identical to the scalar oracles every
    round, and check-quorum: the device raises the window flag for every
    leader row while the scalar oracles (the demotion authority)
    verifiably step down.

    Graduated here from ``__graft_entry__.py`` (ISSUE 16) so the
    correctness dry-run and the ``_run_mesh_axis`` throughput rung live
    side by side; the driver's hook delegates to this function.
    """
    # Force the CPU platform BEFORE any jax backend is touched.  The virtual
    # n-device CPU mesh never needs the TPU; round-1 this called
    # ``jax.devices()`` first, which dialled the tunneled axon backend and
    # hung until the driver's timeout (MULTICHIP_r01.json rc=124).
    import random

    from dragonboat_tpu import hostplatform

    hostplatform.set_host_device_count(n_devices)
    hostplatform.force_cpu()

    import jax

    from jax.sharding import NamedSharding, PartitionSpec as P

    from dragonboat_tpu.ops.engine import BatchedQuorumEngine
    from dragonboat_tpu.ops.sharding import GROUP_AXIS, make_mesh
    from dragonboat_tpu.raft import InMemLogDB, Raft
    from dragonboat_tpu.config import Config
    from dragonboat_tpu.wire import Entry, Message, MessageType as MT

    devices = jax.local_devices(backend="cpu")
    if len(devices) < n_devices:
        # jax was already imported with a smaller CPU device count: reset the
        # backend cache so the new XLA_FLAGS take effect
        hostplatform.clear_backends()
        devices = jax.local_devices(backend="cpu")
    devices = devices[:n_devices]
    assert len(devices) == n_devices, (
        f"need {n_devices} devices, have {len(devices)}"
    )
    mesh = make_mesh(np.array(devices))

    n_groups = 64
    assert n_groups % n_devices == 0
    rng = random.Random(42)
    # one prefix-spec sharding for every state field: group axis (dim 0)
    # split over the mesh, peer columns local to their group's chip
    eng = BatchedQuorumEngine(
        n_groups, n_peers=5, event_cap=4 * n_groups,
        sharding=NamedSharding(mesh, P(GROUP_AXIS)),
    )

    # scalar oracles: node 1's replica of each group, varied membership
    oracles = {}
    for g in range(n_groups):
        cid = 1 + g
        peers = [1, 2, 3] if cid % 2 else [1, 2, 3, 4, 5]
        cfg = Config(
            cluster_id=cid, node_id=1, election_rtt=10, heartbeat_rtt=1,
            check_quorum=True,
        )
        r = Raft(cfg, InMemLogDB(), seed=cid)
        for p in peers:
            r.add_node(p)
        oracles[cid] = (r, peers)
        eng.add_group(
            cid, node_ids=peers, self_id=1, election_timeout=10,
            rand_timeout=r.randomized_election_timeout,
            check_quorum=True,
        )
    eng._upload_dirty()

    # ---- phase A: elections fire from DEVICE ticks, outcomes seeded ----
    last_term = {cid: 0 for cid in oracles}
    leaders: set = set()
    ticks = 0
    while len(leaders) < n_groups and ticks < 400:
        ticks += 1
        campaigned = []
        for cid, (r, peers) in oracles.items():
            if cid in leaders:
                continue
            r.tick()
            if r.is_candidate() and r.term != last_term[cid]:
                last_term[cid] = r.term
                campaigned.append(cid)
        out = eng.step(do_tick=True)
        fired = set(out.elect)
        # the device must fire elect_due on EXACTLY the tick the scalar
        # oracle campaigns (first campaign; re-campaign backoff drifts by
        # design — the row clock resets at set_candidate time)
        for cid in campaigned:
            if last_term[cid] == 1:
                assert cid in fired, (ticks, cid, sorted(fired)[:8])
        for cid in campaigned:
            r, peers = oracles[cid]
            eng.set_candidate(cid, term=r.term)
            eng.vote(cid, 1, granted=True)  # campaign self-vote
            grant = rng.random() < 0.8  # ~20% of campaigns fail first
            for p in peers:
                if p == 1:
                    continue
                r.handle(Message(
                    from_=p, to=1, term=r.term,
                    type=MT.REQUEST_VOTE_RESP, reject=not grant,
                ))
                eng.vote(cid, p, granted=grant)
        if campaigned:
            out = eng.step(do_tick=False)
            for cid in campaigned:
                r, peers = oracles[cid]
                if r.is_leader():
                    assert cid in out.won, (cid, out.won[:8])
                    eng.set_leader(
                        cid, term=r.term,
                        term_start=r.log.last_index(),
                        last_index=r.log.last_index(),
                    )
                    leaders.add(cid)
                else:
                    assert cid in out.lost, (cid, out.lost[:8])
                    # lost: oracle stays candidate and re-campaigns on its
                    # next randomized timeout; resync the row's clock
                    eng.set_candidate(cid, term=r.term)
                    eng.set_randomized_timeout(
                        cid, r.randomized_election_timeout
                    )
    assert len(leaders) == n_groups, (
        f"only {len(leaders)}/{n_groups} elected in {ticks} ticks"
    )

    # ---- phase B: 100+ commit rounds, full-vector bit-identity ----
    rounds = 120
    for rnd in range(rounds):
        for cid, (r, peers) in oracles.items():
            if rng.random() < 0.7:  # sparse activity, like live traffic
                r.handle(Message(
                    from_=1, to=1, type=MT.PROPOSE, entries=[Entry(cmd=b"x")]
                ))
                idx = r.log.last_index()
                eng.ack(cid, 1, idx)  # self append
                followers = [p for p in peers if p != 1]
                rng.shuffle(followers)
                k = rng.randrange(0, len(followers) + 1)
                for p in followers[:k]:
                    r.handle(Message(
                        from_=p, to=1, term=r.term,
                        type=MT.REPLICATE_RESP, log_index=idx,
                    ))
                    eng.ack(cid, p, idx)
        eng.step(do_tick=False)
        # FULL commit vector, every round, bit-identical
        for cid, (r, _) in oracles.items():
            got, want = eng.committed_index(cid), r.log.committed
            assert got == want, (rnd, cid, got, want)

    # ---- phase C: check-quorum demotion, device window + scalar authority --
    # Leaders see no peer contact from here on.  The device fires the
    # check-quorum window flag every election_timeout ticks BY DESIGN
    # (kernels.py: the scalar handler is the authority and must consume
    # its activity bits each window), so the real assertion is two-sided:
    # the device raises the window for every leader row AND the scalar
    # oracles, ticked in lockstep with zero peer contact, actually step
    # down within two windows.
    demoted: set = set()
    for _ in range(2 * 10 + 5):
        for cid, (r, _) in oracles.items():
            r.tick()
        out = eng.step(do_tick=True)
        demoted.update(out.demote)
    assert len(demoted) == n_groups, (
        f"device raised check-quorum window for only {len(demoted)}/{n_groups}"
    )
    still_leading = [cid for cid, (r, _) in oracles.items() if r.is_leader()]
    assert not still_leading, (
        f"{len(still_leading)} stale leaders survived check-quorum: "
        f"{still_leading[:8]}"
    )

    total_committed = sum(r.log.committed for r, _ in oracles.values())

    # ---- phase D: the FULL stack on the sharded engine ----
    # 3 in-process NodeHosts whose TpuQuorumCoordinators are built with
    # ExpertConfig.engine_mesh_devices=n_devices: real registration/
    # staging/rounds through the coordinator, device-tick elections,
    # propose end to end — not the bare engine.  (Shared harness with
    # tests/test_sharding.py so the two cannot drift; the harness caps
    # dispatch streams at the host's core count.)
    from dragonboat_tpu.testing import run_sharded_stack_check

    n_stack_groups = 2 * n_devices
    stack_writes = run_sharded_stack_check(
        n_devices, groups=n_stack_groups, writes_per_group=5
    )

    print(
        f"dryrun_multichip ok: {n_devices} devices, {n_groups} groups, "
        f"{ticks} election ticks, {rounds} commit rounds bit-identical, "
        f"{total_committed} entries committed, "
        f"check-quorum demoted {len(demoted)}/{n_groups}; full stack: "
        f"{n_stack_groups} groups on 3 NodeHosts over the sharded "
        f"coordinator, {stack_writes} writes committed"
    )


def main() -> None:
    # ---- e2e NodeHost numbers first (ladder rung 3; VERDICT r2 item 1).
    # The TPU chip is free at this point — the probe subprocess exits and
    # the parent has not initialized jax yet, so the e2e rank-0 child can
    # own the device for the live-plugin run.
    probed = None
    if os.environ.get("BENCH_PLATFORM") != "cpu":
        probed = _probe_tpu()
    on_tpu = probed is not None and probed != "cpu"
    detail = {}
    if os.environ.get("BENCH_SKIP_E2E") != "1":
        # flagship: the winning configuration (auto's choice) — scalar
        # engine + fast lane + native C-ABI SM (apply path GIL-free)
        _note("running e2e (native SM, scalar engine, fast lane)...")
        detail["e2e"] = _run_e2e(False, "scalar", {"E2E_SM": "native"})
        _note(f"e2e: {json.dumps(detail['e2e'])[:300]}")
        # round-3-comparable: same but the Python dict SM
        _note("running e2e (python SM, scalar engine, fast lane)...")
        detail["e2e_python_sm"] = _run_e2e(
            False, "scalar", timeout_key="BENCH_E2E_SCALAR_TIMEOUT"
        )
        _note(f"e2e_python_sm: {json.dumps(detail['e2e_python_sm'])[:300]}")
        # engine comparison under IDENTICAL placement (VERDICT r3 weak #3).
        # Runs the device engine on the LOCAL (cpu) backend even when the
        # TPU probe succeeded: the comparison isolates the engine, and over
        # the tunneled chip the rank-0 kernel compiles alone blow the
        # startup deadline (measured: STARTED timeout at 500+s; tunnel
        # dispatch p50 ~67ms is the recorded reason auto picks scalar on
        # this topology — see PERF.md "tpu-engine vs scalar").
        _note("running e2e (tpu engine, same placement)...")
        detail["e2e_tpu"] = _run_e2e(
            False, "tpu", timeout_key="BENCH_E2E_SCALAR_TIMEOUT"
        )
        _note(f"e2e_tpu: {json.dumps(detail['e2e_tpu'])[:300]}")
        # scale rung (VERDICT r4 next #1): engine A/B at IDENTICAL
        # placement, 2,048 groups, leaders SPREAD (the production
        # shape).  Round-5 full dataset on a 1-vCPU box: tpu ~8.8k
        # ± 1.9k w/s over six runs vs scalar ~9.9k ± 1.0k over four —
        # parity within noise (r4 measured a 4x deficit), with the tpu
        # spread wide because every dispatch competes with the box's
        # single host core (PERF.md round-5 §3).  The rung keeps the
        # comparison honest run over run; single pairs on a small box
        # are weather.  2,048 keeps setup inside the section budget;
        # override with BENCH_SCALE_GROUPS.
        if os.environ.get("BENCH_SKIP_SCALE") != "1":
            scale_groups = os.environ.get("BENCH_SCALE_GROUPS", "2048")
            scale_env = {
                "E2E_SM": "native", "E2E_GROUPS": scale_groups,
                "E2E_DURATION": "20", "E2E_LEADER_TIMEOUT": "360",
            }
            for eng_name in ("tpu", "scalar"):
                key = f"e2e_scale_{eng_name}"
                _note(
                    f"running e2e scale rung ({scale_groups} groups, "
                    f"spread, {eng_name})..."
                )
                detail[key] = _run_e2e(
                    False, eng_name, dict(scale_env),
                    timeout_key="BENCH_E2E_SCALE_TIMEOUT",
                )
                _note(f"{key}: {json.dumps(_slim_e2e(detail[key]))[:300]}")
    if "e2e" in detail:
        e2e_ok = bool(
            detail["e2e"].get("writes_per_sec")
            and "error" not in detail["e2e"]
            and not detail["e2e"].get("rank_errors")
        )
    else:
        e2e_ok = None  # deliberately skipped ≠ failed

    # ---- kernel benches (parent now takes the device; reuse the probe —
    # unless the e2e run errored, in which case its rank-0 child may have
    # wedged the tunnel and a fresh probe is the cheap safety check)
    if detail.get("e2e", {}).get("error"):
        probed = None
    platform = _resolve_platform(probed)
    on_tpu = platform not in ("cpu",)
    detail["platform"] = platform

    n_groups = int(os.environ.get("BENCH_GROUPS", "131072" if on_tpu else "16384"))
    # pipelined R: 256 on the tunneled chip — the deeper scan amortizes
    # the dispatch round trip (+2.4% measured even on a slow-tunnel day)
    rounds = int(os.environ.get("BENCH_ROUNDS", "256" if on_tpu else "128"))
    dispatches = int(os.environ.get("BENCH_DISPATCHES", "5"))
    lat_rounds = int(os.environ.get("BENCH_LAT_ROUNDS", "1"))
    lat_groups = int(os.environ.get("BENCH_LAT_GROUPS", "1024"))
    lat_dispatches = int(os.environ.get("BENCH_LAT_DISPATCHES", "50"))

    # throughput-maximal pipelined mode
    writes_per_sec, times = _run_mode(n_groups, rounds, dispatches)
    detail.update(
        groups=n_groups,
        rounds_per_dispatch=rounds,
        dispatches=dispatches,
        # duplicated into the detail artifact so the PERF.md ledger
        # generator (tools/perf_ledger.py) has every figure in one file
        headline_writes_per_sec=round(writes_per_sec, 1),
        dispatch_p99_ms=round(
            float(np.percentile(np.array(times) * 1e3, 99)), 3
        ),
    )

    # latency-bounded mode: continuous small-R dispatches at rung-3 scale
    try:
        lat_wps, lat_times = _run_mode(
            lat_groups, lat_rounds, lat_dispatches, warmup=5
        )
        lat_ms = np.array(lat_times) * 1e3
        detail["latency_mode"] = {
            "groups": lat_groups,
            "rounds_per_dispatch": lat_rounds,
            "writes_per_sec": round(lat_wps, 1),
            "dispatch_p50_ms": round(float(np.percentile(lat_ms, 50)), 3),
            "dispatch_p99_ms": round(float(np.percentile(lat_ms, 99)), 3),
        }
    except Exception as e:
        detail["latency_mode"] = {"error": repr(e)}

    # host-loop mode: the engine's REAL ingest path — events staged
    # host-side through eng.ack()/BatchedQuorumEngine.step() exactly as
    # the live tpuquorum coordinator drives it (persistent device state,
    # per-round event deltas).  Honest midpoint between the kernel-only
    # pipelined number (events derived on device) and the full e2e stack.
    try:
        detail["host_loop"] = _run_host_loop(
            int(os.environ.get("BENCH_HOST_GROUPS", "65536" if on_tpu else "16384")),
            int(os.environ.get("BENCH_HOST_ROUNDS", "8")),
            int(os.environ.get("BENCH_HOST_K", "16")),
        )
        detail["host_loop"].setdefault("platform", platform)
    except Exception as e:
        detail["host_loop"] = {"error": repr(e)}

    # rungs 4 and 5 of the config ladder (BASELINE.md): 64k / 100k groups
    # through the coordinator ingest path.  With the bulk-readback probe
    # (committed_snapshot: one transfer per round instead of ~576 eager
    # per-cid reads) the rungs fit the tunnel budget, so they run ON THE
    # DEVICE when the parent holds one (VERDICT r4 #10) and fall back to
    # the cpu-subprocess shape otherwise.
    def _rung_on_device(fn, env_groups, dflt_groups, env_rounds, dflt_rounds,
                        env_k, dflt_k, timeout=420.0):
        """Run a rung inline on the parent's device, bounded by a watchdog
        thread: a wedged tunneled backend must degrade to an error entry
        (like the cpu-subprocess path's timeout), not hang the bench.
        The worker gets a CANCELLATION flag checked before every dispatch
        (_check_cancel): when the watchdog gives up, the abandoned daemon
        thread stops feeding the device instead of dispatching on in the
        background while the cpu fallback measures (ISSUE 1 satellite)."""
        import threading as _th

        box = {}
        cancel = _th.Event()

        def _work():
            try:
                g = int(os.environ.get(env_groups, str(dflt_groups)))
                rds = int(os.environ.get(env_rounds, str(dflt_rounds)))
                # same K override the cpu-subprocess spec honors — the
                # device and cpu capture must stay A/B-comparable
                kv = int(os.environ.get(env_k, str(dflt_k)))
                out = fn(g, rds, kv, cancel=cancel)
                out["platform"] = platform
                box["out"] = out
            except Exception as e:
                box["out"] = {"error": repr(e)[:300]}

        t = _th.Thread(target=_work, daemon=True)
        t.start()
        t.join(timeout)
        if t.is_alive():
            cancel.set()  # the worker aborts at its next dispatch boundary
            return {"error": f"device rung timed out after {timeout}s"}
        # BaseException (SystemExit etc.) ends the thread without a result
        return box.get("out", {"error": "device rung worker died"})

    if on_tpu:
        detail["rung4"] = _rung_on_device(
            _run_rung4, "BENCH_RUNG4_GROUPS", 65536, "BENCH_RUNG4_ROUNDS", 8,
            "BENCH_RUNG4_K", 16,
        )
        detail["rung5"] = _rung_on_device(
            _run_rung5, "BENCH_RUNG5_GROUPS", 100000, "BENCH_RUNG5_ROUNDS", 6,
            "BENCH_RUNG5_K", 8,
        )
    for rung in ("rung4", "rung5"):
        err = detail.get(rung, {}).get("error")
        if not on_tpu or err:
            if err:
                # a device-path failure (correctness assert, tunnel wedge)
                # must stay visible even after the cpu fallback succeeds
                detail[f"{rung}_device_error"] = err
            spec = (
                ["BENCH_RUNG4_GROUPS", 65536, "BENCH_RUNG4_ROUNDS", 8,
                 "BENCH_RUNG4_K", 16]
                if rung == "rung4"
                else ["BENCH_RUNG5_GROUPS", 100000, "BENCH_RUNG5_ROUNDS", 6,
                      "BENCH_RUNG5_K", 8]
            )
            detail[rung] = _run_cpu_section(f"_run_{rung}", spec)

    # idle-groups-are-free axis (VERDICT r5 item 6): always measured on
    # the local cpu backend — the axis isolates host-side occupancy cost
    # at fixed provisioned capacity, which is backend-agnostic by
    # construction, and the cpu subprocess keeps it off a flaky tunnel
    if os.environ.get("BENCH_SKIP_IDLE_AXIS") != "1":
        detail["idle_axis"] = _run_cpu_section(
            "_run_idle_axis",
            ["BENCH_IDLE_ACTIVE", 1024, "BENCH_IDLE_IDLE", 15360,
             "BENCH_IDLE_ROUNDS", 6, "BENCH_IDLE_K", 8],
        )

    # obs-overhead axis (ISSUE 5): flight recorder + metrics ON vs OFF on
    # the fused host loop — asserts < 5% and ships the recorder dump the
    # perf ledger's observability columns derive from.  Always on the
    # local cpu backend: the axis isolates HOST-side instrument cost,
    # which is backend-agnostic by construction.
    if os.environ.get("BENCH_SKIP_OBS_AXIS") != "1":
        detail["obs_axis"] = _run_cpu_section(
            "_run_obs_axis",
            ["BENCH_OBS_ACTIVE", 16384, "BENCH_OBS_ROUNDS", 6,
             "BENCH_OBS_K", 16],
        )

    # live-coordinator adaptive-K axis (ISSUE 7): the warmed fused round
    # vs the single-round replay through the scalar-guarded offload path,
    # plus warm-enable seconds and compile-cache hit/miss counts — the
    # perf ledger's live columns derive from this section.  Always on the
    # local cpu backend (it measures host round cost, and the subprocess
    # keeps the compile-cache churn off the parent's jax state).
    if os.environ.get("BENCH_SKIP_LIVE_COORD_AXIS") != "1":
        detail["live_coord"] = _run_cpu_section(
            "_run_live_coord_axis",
            ["BENCH_LIVE_GROUPS", 512, "BENCH_LIVE_ITERS", 20],
            timeout=900.0,
        )

    # mesh-dispatch shard-count axis (ISSUE 16): the fused write loop at
    # shards 1/2/4/8 on the 8-virtual-device cpu mesh, plus live
    # migration wall time and the shard-tagged span concurrency peak —
    # the perf ledger's "Mesh dispatch" table derives from this section.
    # Always a subprocess: the axis needs XLA's host platform forced to
    # 8 devices BEFORE any jax init, which must not leak into the parent.
    if os.environ.get("BENCH_SKIP_MESH_AXIS") != "1":
        detail["mesh_axis"] = _run_cpu_section(
            "_run_mesh_axis",
            ["BENCH_MESH_GROUPS", 512, "BENCH_MESH_ROUNDS", 4,
             "BENCH_MESH_K", 8],
            timeout=600.0,
        )
        _note(f"mesh_axis: {json.dumps(detail['mesh_axis'])[:300]}")

    def _run_e2e_axis(flag: str, timeout_env: str, default_timeout: str):
        """Run a bench_e2e.py axis in a killable subprocess (cpu backend)
        and return its last-stdout-line JSON, or an error entry — the
        shared shape of the trace and crossdomain sections."""
        import subprocess as _sp

        try:
            r = _sp.run(
                [sys.executable, os.path.join(os.path.dirname(
                    os.path.abspath(__file__)), "bench_e2e.py"), flag],
                capture_output=True, text=True,
                timeout=float(os.environ.get(timeout_env, default_timeout)),
                env={**os.environ, "E2E_TPU": "0"},
            )
            if r.returncode == 0 and r.stdout.strip():
                return json.loads(r.stdout.strip().splitlines()[-1])
            return {
                "error": f"rc={r.returncode}",
                "tail": (r.stderr or r.stdout)[-500:],
            }
        except Exception as e:
            return {"error": repr(e)}

    # request-tracing axis (ISSUE 9): trace-on vs trace-off interleaved
    # best-of on one live cluster per engine (<5% asserted) plus the
    # per-stage latency attribution — the perf ledger's "Latency
    # attribution" table derives from this section.  Runs bench_e2e in a
    # killable subprocess like the other e2e sections (cpu backend; the
    # axis measures host-side stage cost, backend-agnostic).
    if os.environ.get("BENCH_SKIP_TRACE_AXIS") != "1":
        detail["trace_axis"] = _run_e2e_axis(
            "--trace-axis", "BENCH_TRACE_TIMEOUT", "900"
        )
        _note(f"trace_axis: {json.dumps(detail['trace_axis'])[:300]}")

    # cross-domain lease axis (ISSUE 10): leader-lease local reads vs the
    # ReadIndex fallback on a live 3-host group whose follower quorum sits
    # one injected far link (40ms RTT) from the leader — the perf ledger's
    # "Read plane" table derives from this section.  Always on the cpu
    # backend (it measures the scalar read path; no device involved).
    if os.environ.get("BENCH_SKIP_CROSSDOMAIN") != "1":
        # outer timeout dominates the rung's own worst case (2 variants x
        # 120s placement deadlines + load + 6-host setup/teardown)
        detail["crossdomain"] = _run_e2e_axis(
            "--crossdomain", "BENCH_XDOM_TIMEOUT", "600"
        )
        _note(f"crossdomain: {json.dumps(detail['crossdomain'])[:300]}")

    # device state machine rung (ISSUE 11): 9:1 mixed KV load, device_kv
    # on vs off on identical 3-host topology — the perf ledger's "Device
    # SM" table derives from this section.  The outer timeout dominates
    # the has_kv program warm (minutes on a cold 1-vCPU box) plus two
    # variants of placement + load.
    if os.environ.get("BENCH_SKIP_DEVSM") != "1":
        detail["devsm"] = _run_e2e_axis(
            "--devsm", "BENCH_DEVSM_TIMEOUT", "900"
        )
        _note(f"devsm: {json.dumps(detail['devsm'])[:300]}")

    # multi-process host plane axis (ISSUE 12): host_workers=0 vs N on
    # the many-session durable cluster — the perf ledger's "Host
    # workers" table derives from this section.  The assertion is
    # cpu-topology gated inside the axis (single-core boxes run the
    # parity variant and label themselves; the ≥5x target gates on
    # os.cpu_count()).
    if os.environ.get("BENCH_SKIP_HOST_WORKERS") != "1":
        detail["host_workers"] = _run_e2e_axis(
            "--host-workers", "BENCH_HOST_WORKERS_TIMEOUT", "600"
        )
        _note(
            "host_workers: "
            f"{json.dumps(detail['host_workers'])[:300]}"
        )

    # cluster health axis (ISSUE 13): health-on/off interleaved best-of
    # on one live cluster (<5% asserted) plus a leadership-churn phase
    # whose detector open/close events carry measured recovery durations
    # — the perf ledger's "Cluster health" table derives from this
    # section's ring dump.
    if os.environ.get("BENCH_SKIP_HEALTH_AXIS") != "1":
        detail["health_axis"] = _run_e2e_axis(
            "--health-axis", "BENCH_HEALTH_TIMEOUT", "600"
        )
        _note(f"health_axis: {json.dumps(detail['health_axis'])[:300]}")

    # device capacity & profiling axis (ISSUE 15): profile-on/off paired
    # windows on a live tpu-engine cluster (<5% + 2·SEM asserted), the
    # capacity model diffed against measured resident bytes (<10%
    # asserted) and the warm-set program registry with per-program XLA
    # cost/memory analysis — the perf ledger's "Device programs" and
    # "Device capacity" tables derive from this section.
    if os.environ.get("BENCH_SKIP_DEVPROF_AXIS") != "1":
        detail["devprof_axis"] = _run_e2e_axis(
            "--devprof-axis", "BENCH_DEVPROF_TIMEOUT", "900"
        )
        _note(f"devprof_axis: {json.dumps(detail['devprof_axis'])[:300]}")

    # BlackWater churn soak A/B (ISSUE 17): same-seed recovery OFF/ON
    # runs of soak.py --churn, scored by per-detector MTTR p99 with a
    # zero-linearizability-violation gate — the perf ledger's "Recovery"
    # table derives from this section.  Two full soak arms are minutes
    # of wall time, so the axis honors its own skip gate.
    if os.environ.get("BENCH_SKIP_CHURN") != "1":
        detail["churn_soak"] = _run_e2e_axis(
            "--churn-soak", "BENCH_CHURN_TIMEOUT", "3600"
        )
        _note(f"churn_soak: {json.dumps(detail['churn_soak'])[:300]}")

    # full detail (per-rank stats and all) goes to a FILE; the stdout line
    # stays small enough that the driver's 2000-char tail capture can never
    # truncate the headline (VERDICT r3 missing #1)
    detail["tpu_probe"] = PROBE_LOG
    detail_file_ok = False
    try:
        with open(
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "BENCH_DETAIL.json"), "w"
        ) as f:
            json.dump(detail, f, indent=1)
        detail_file_ok = True
    except OSError as e:
        _note(f"could not write BENCH_DETAIL.json: {e!r}")
    slim = dict(detail)
    for k in ("e2e", "e2e_python_sm", "e2e_tpu"):
        if k in slim:
            slim[k] = _slim_e2e(slim[k])
    if isinstance(slim.get("obs_axis"), dict):
        # the recorder span dump stays in BENCH_DETAIL.json only — it
        # would blow the driver's 2000-char stdout tail capture
        slim["obs_axis"] = {
            k: v for k, v in slim["obs_axis"].items() if k != "recorder"
        }
    if isinstance(slim.get("live_coord"), dict):
        # scalars only on stdout; the k_axis table + recorder dump live
        # in BENCH_DETAIL.json
        slim["live_coord"] = {
            k: v for k, v in slim["live_coord"].items()
            if k in ("groups", "live_writes_per_sec",
                     "live_writes_per_sec_single", "warm_enable_seconds",
                     "fused_dispatches", "stalled_spans", "error", "tail")
        }
    if isinstance(slim.get("trace_axis"), dict):
        # verdict fields only on stdout; the per-stage attribution tables
        # and pair deltas (~KBs) live in BENCH_DETAIL.json — the adjacent
        # sections' 2000-char tail-capture discipline applies here too
        ta = slim["trace_axis"]
        slim["trace_axis"] = {
            k: v for k, v in ta.items()
            if k in ("trace_overhead_ok", "error", "tail")
        }
        for eng, e in (ta.get("engines") or {}).items():
            if isinstance(e, dict):
                slim["trace_axis"][eng] = {
                    k: v for k, v in e.items()
                    if k in ("trace_overhead_pct", "trace_overhead_sem_pct",
                             "trace_overhead_ok", "fused_dispatches")
                }
    if isinstance(slim.get("crossdomain"), dict):
        # headline fields only on stdout; full variant stats live in
        # BENCH_DETAIL.json
        slim["crossdomain"] = {
            k: v for k, v in slim["crossdomain"].items()
            if k in ("read_p99_ms_lease", "read_p99_ms_fallback",
                     "read_p99_speedup", "ops_ratio_on_off", "assert_ok",
                     "error", "tail")
        }
    if isinstance(slim.get("devsm"), dict):
        # headline fields only; per-stage attribution in BENCH_DETAIL.json
        slim["devsm"] = {
            k: v for k, v in slim["devsm"].items()
            if k in ("apply_share_pct_devsm", "apply_share_pct_host",
                     "read_p50_ms_devsm", "read_p50_ms_host", "assert_ok",
                     "error", "tail")
        }
    if isinstance(slim.get("health_axis"), dict):
        # verdict fields only on stdout; the ring dump + per-detector
        # recovery tables live in BENCH_DETAIL.json
        slim["health_axis"] = {
            k: v for k, v in slim["health_axis"].items()
            if k in ("health_overhead_pct", "health_overhead_ok",
                     "churn_events_ok", "samples_total", "error", "tail")
        }
    if isinstance(slim.get("devprof_axis"), dict):
        # verdict fields only on stdout; the program table + per-plane
        # ledger live in BENCH_DETAIL.json
        slim["devprof_axis"] = {
            k: v for k, v in slim["devprof_axis"].items()
            if k in ("devprof_overhead_pct", "devprof_overhead_ok",
                     "programs_ok", "error", "tail")
        }
        cap = (detail["devprof_axis"] or {}).get("capacity") or {}
        slim["devprof_axis"]["model_error_pct"] = cap.get("model_error_pct")
    if isinstance(slim.get("churn_soak"), dict):
        # verdict + per-detector p99 A/B only on stdout; the full arm
        # summaries (counts, actions, censored opens) live in
        # BENCH_DETAIL.json
        slim["churn_soak"] = {
            k: v for k, v in slim["churn_soak"].items()
            if k in ("churn_ok", "linearizable", "groups", "seed",
                     "mttr_p99", "error", "tail")
        }
    if isinstance(slim.get("host_workers"), dict):
        # headline fields only; the full A/B records live in
        # BENCH_DETAIL.json's host_workers.axis section
        hw = slim["host_workers"]
        slim["host_workers"] = {
            k: v for k, v in hw.items()
            if k in ("cores", "single_core", "workers", "restarts",
                     "assertion", "assert_ok", "error", "tail")
        }
        ax = (hw.get("axis") or [{}])[0]
        slim["host_workers"]["speedup"] = ax.get("speedup")
    for k in ("e2e_scale_tpu", "e2e_scale_scalar"):
        # ultra-slim: the A/B verdict fields only (full data in
        # BENCH_DETAIL.json); the driver's tail capture budget is 2000B
        if k in slim and isinstance(slim[k], dict):
            s = _slim_e2e(slim[k])
            slim[k] = {
                f: s[f]
                for f in ("writes_per_sec", "commit_latency_ms",
                          "mixed_ops_per_sec", "setup_s", "error", "tail")
                if f in s
            }
            if detail[k].get("led_groups") is not None:
                slim[k]["led"] = detail[k]["led_groups"]
    slim.pop("tpu_probe", None)
    if not on_tpu and PROBE_LOG:
        last = dict(PROBE_LOG[-1])
        if "stderr" in last:  # full stderr stays in BENCH_DETAIL.json
            last["stderr"] = last["stderr"][-160:]
        slim["tpu_probe_last"] = last
    tpu_required = os.environ.get("BENCH_PLATFORM") != "cpu"
    record = {
        "metric": "quorum_engine_writes_per_sec",
        "value": round(writes_per_sec, 1),
        "unit": "writes/s",
        "vs_baseline": round(writes_per_sec / BASELINE_WRITES_PER_SEC, 4),
        "platform": platform,
        # loud, machine-readable TPU status: false means the bench ran
        # but NOT on the hardware the record is about
        "tpu_ok": on_tpu,
        # machine-readable e2e status (ADVICE r2): a consumer
        # checking rc/parsed must not read a partial failure as an
        # unqualified pass
        "e2e_ok": e2e_ok,
        "detail": slim,
    }
    line = json.dumps(record)
    if len(line) > 1900:  # last-resort guard for the tail capture
        _note("slim detail still too large; dropping it from the line")
        record["detail"] = (
            {"see": "BENCH_DETAIL.json"}
            if detail_file_ok
            else {"error": "detail too large and BENCH_DETAIL.json unwritable"}
        )
        line = json.dumps(record)
    print(line)
    if tpu_required and not on_tpu:
        # the TPU was expected (driver runs on real hardware) but could not
        # be reached: exit nonzero so the record flags it even if nobody
        # reads the JSON fields
        sys.exit(3)


if __name__ == "__main__":
    try:
        main()
    except SystemExit:
        raise
    except Exception as e:  # ALWAYS emit a parseable line for the driver
        traceback.print_exc()
        print(
            json.dumps(
                {
                    "metric": "quorum_engine_writes_per_sec",
                    "value": 0.0,
                    "unit": "writes/s",
                    "vs_baseline": 0.0,
                    "platform": None,
                    "tpu_ok": False,
                    "e2e_ok": False,
                    "detail": {"error": repr(e)[:600]},
                }
            )
        )
        sys.exit(4)
