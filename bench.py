"""Benchmark: batched quorum engine write throughput.

Headline metric (BASELINE.json): writes/sec through the quorum path at 16B
payload vs active group count.  The reference's published peak is 9M
writes/sec over 48 groups on a 3-node cluster (README Performance,
SURVEY.md §6).

Here G concurrent groups each commit one write per engine round
(leader self-ack + follower ack, quorum 2-of-3).  The host stages R rounds
of ingested event batches and the device scans them in ONE fused dispatch
(``quorum_multistep``) — the pipelined operating mode that amortizes
host↔device latency, mirroring the reference's accept-while-in-flight
pipelining (``execengine.go:954-966``).  Each dispatch pays the full
upload → R×step → commit-watermark readback cycle.

Prints one JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
from __future__ import annotations

import functools
import json
import os
import time

import numpy as np

import jax
import jax.numpy as jnp

BASELINE_WRITES_PER_SEC = 9_000_000.0


def build_state(n_groups: int, event_cap: int, n_peers: int = 3):
    from dragonboat_tpu.ops.engine import BatchedQuorumEngine

    eng = BatchedQuorumEngine(n_groups, n_peers, event_cap=event_cap)
    peers = list(range(1, n_peers + 1))
    for cid in range(1, n_groups + 1):
        eng.add_group(cid, node_ids=peers, self_id=1)
        eng.set_leader(cid, term=1, term_start=1, last_index=1)
    eng._upload_dirty()
    return eng


def main() -> None:
    from dragonboat_tpu.ops.kernels import quorum_multistep

    n_groups = int(os.environ.get("BENCH_GROUPS", "131072"))
    rounds = int(os.environ.get("BENCH_ROUNDS", "128"))      # R per dispatch
    dispatches = int(os.environ.get("BENCH_DISPATCHES", "5"))
    warmup = 3

    cap = 2 * n_groups  # self-ack + follower ack per group per round
    eng = build_state(n_groups, cap)
    st = eng.dev

    # host ingest cost model: the real engine uploads compact event batches;
    # here the staged batches are regular (every group commits one entry per
    # round: self-ack + follower ack), so ALL event tensors are derived on
    # device from the scalar `base` — the persistent-state + delta-upload
    # design SURVEY.md §7 calls for, and nothing big crosses the host
    # boundary or lands in the program as a constant
    @functools.partial(jax.jit, donate_argnums=(0,))
    def staged_multistep(st, base_index):
        rows = jnp.arange(n_groups, dtype=jnp.int32)
        ack_g = jnp.broadcast_to(
            jnp.concatenate([rows, rows]), (rounds, cap)
        )
        ack_p = jnp.broadcast_to(
            jnp.concatenate(
                [
                    jnp.zeros((n_groups,), jnp.int32),
                    jnp.ones((n_groups,), jnp.int32),
                ]
            ),
            (rounds, cap),
        )
        vals = base_index + 1 + jnp.arange(rounds, dtype=jnp.int32)
        ack_val = jnp.broadcast_to(vals[:, None], (rounds, cap))
        ack_valid = jnp.ones((rounds, cap), bool)
        zeros_i32 = jnp.zeros((rounds, cap), jnp.int32)
        return quorum_multistep(
            st,
            ack_g,
            ack_p,
            ack_val,
            ack_valid,
            zeros_i32,
            zeros_i32,
            jnp.zeros((rounds, cap), jnp.int8),
            jnp.zeros((rounds, cap), bool),
            do_tick=True,
        )

    def dispatch(st, base_index):
        t0 = time.perf_counter()
        out = staged_multistep(st, jnp.int32(base_index))
        committed = np.asarray(out.committed)  # egress readback (blocks)
        return out.state, committed, time.perf_counter() - t0

    base = 1  # groups start with noop at index 1 committed? (committed=0, last=1)
    for _ in range(warmup):
        st, committed, _ = dispatch(st, base)
        base += rounds
    assert committed[0] == base, (committed[:4], base)

    times = []
    t0 = time.perf_counter()
    for _ in range(dispatches):
        st, committed, dt = dispatch(st, base)
        times.append(dt)
        base += rounds
    elapsed = time.perf_counter() - t0
    assert committed[0] == base

    writes = n_groups * rounds * dispatches
    writes_per_sec = writes / elapsed
    p99_dispatch_ms = float(np.percentile(np.array(times) * 1e3, 99))
    print(
        json.dumps(
            {
                "metric": "quorum_engine_writes_per_sec",
                "value": round(writes_per_sec, 1),
                "unit": "writes/s",
                "vs_baseline": round(writes_per_sec / BASELINE_WRITES_PER_SEC, 4),
                "detail": {
                    "groups": n_groups,
                    "rounds_per_dispatch": rounds,
                    "dispatches": dispatches,
                    "dispatch_p99_ms": round(p99_dispatch_ms, 3),
                    "platform": jax.devices()[0].platform,
                },
            }
        )
    )


if __name__ == "__main__":
    main()
