"""End-to-end NodeHost benchmark: ladder rung 3 (BASELINE.md).

Drives the REAL runtime — NodeHost facade, step/apply engine, LogDB
persistence (C++ native segmented-WAL engine with fsync when durable),
chan transport between three in-process NodeHosts, and the TPU batched
quorum plugin (``ExpertConfig.quorum_engine="tpu"``) — with G Raft groups
× 3 replicas, measuring:

* **writes/sec**: completed proposals (propose → user SM applied → future
  notified) per second at 16B payload
* **commit latency**: per-request propose→applied wall time, p50/p99

This is the honest companion to bench.py's kernel-only number: it includes
proposal ingest, host scheduling, log persistence, transport, apply and
request completion, exactly like the reference's published 9M writes/s
(which is measured through its full stack — ``tools/checkdisk/main.go:98``).
The Python host path is the bottleneck here, not the device engine; the
number is reported as its own metric, never conflated with the kernel one.

Run standalone:  python bench_e2e.py            (env: E2E_GROUPS, E2E_DURATION,
                 E2E_WINDOW, E2E_RTT_MS, E2E_ENGINE, E2E_DURABLE, E2E_THREADS)
From bench.py:   bench_e2e.run_quick() → dict for the JSON detail field.
"""
from __future__ import annotations

import collections
import json
import os
import shutil
import sys
import tempfile
import threading
import time

import numpy as np


def _force_cpu_for_engine() -> None:
    # the e2e bench runs the device engine on whatever platform jax gives
    # us; when the tunnel is dead this would hang, so standalone runs force
    # CPU unless E2E_TPU=1 (bench.py has already resolved the platform by
    # the time run_quick is called)
    if os.environ.get("E2E_TPU") != "1":
        from dragonboat_tpu import hostplatform

        hostplatform.force_cpu()


class CounterSM:
    """Minimal in-memory SM (reference checkdisk uses a noop-ish SM)."""

    def __init__(self, cluster_id, node_id):
        self.count = 0

    def update(self, cmd):
        from dragonboat_tpu import Result

        self.count += 1
        return Result(value=self.count)

    def lookup(self, query):
        return self.count

    def save_snapshot(self, w, files, done):
        w.write(self.count.to_bytes(8, "little"))

    def recover_from_snapshot(self, r, files, done):
        self.count = int.from_bytes(r.read(8), "little")

    def close(self):
        pass


def _mk_nodehosts(n_hosts, groups, rtt_ms, engine, dirs):
    from dragonboat_tpu import NodeHostConfig
    from dragonboat_tpu.config import ExpertConfig
    from dragonboat_tpu.nodehost import NodeHost
    from dragonboat_tpu.transport import ChanRouter, ChanTransport

    router = ChanRouter()
    nhs = []
    for i in range(1, n_hosts + 1):
        nhs.append(
            NodeHost(
                NodeHostConfig(
                    node_host_dir=dirs[i - 1] if dirs else ":memory:",
                    rtt_millisecond=rtt_ms,
                    raft_address=f"e2e{i}:1",
                    raft_rpc_factory=lambda src, rh, ch: ChanTransport(
                        src, rh, ch, router=router
                    ),
                    expert=ExpertConfig(
                        quorum_engine=engine,
                        engine_block_groups=max(groups, 64),
                    ),
                )
            )
        )
    return nhs


def _start_groups(nhs, groups, base_cid=1000):
    from dragonboat_tpu import Config

    addrs = {i: f"e2e{i}:1" for i in range(1, len(nhs) + 1)}
    for g in range(groups):
        cid = base_cid + g
        for i, nh in enumerate(nhs, start=1):
            nh.start_cluster(
                addrs,
                False,
                CounterSM,
                Config(
                    cluster_id=cid,
                    node_id=i,
                    election_rtt=10,
                    heartbeat_rtt=1,
                    snapshot_entries=0,
                ),
            )
    return [base_cid + g for g in range(groups)]


def _wait_leaders(nhs, cids, timeout):
    """Wait until every group has an elected leader; return cid→NodeHost."""
    deadline = time.time() + timeout
    leaders = {}
    remaining = set(cids)
    while remaining and time.time() < deadline:
        for cid in list(remaining):
            for nh in nhs:
                lid, ok = nh.get_leader_id(cid)
                if ok and 1 <= lid <= len(nhs):
                    leaders[cid] = nhs[lid - 1]
                    remaining.discard(cid)
                    break
        if remaining:
            time.sleep(0.05)
    if remaining:
        raise TimeoutError(f"{len(remaining)}/{len(cids)} groups leaderless")
    return leaders


def _load_worker(nh_by_cid, cids, payload, window, stop_at, out):
    """Drive a slice of groups: keep `window` proposals in flight per group,
    FIFO-wait completions (apply order is FIFO per group, so the oldest
    future completes first)."""
    inflight = collections.deque()  # (t0, rs)
    lat = []
    done = 0
    errors = 0
    try:
        sessions = {cid: nh_by_cid[cid].get_noop_session(cid) for cid in cids}
        cap = window * len(cids)
        cid_cycle = list(cids)
        i = 0
        while time.time() < stop_at:
            while len(inflight) < cap and time.time() < stop_at:
                cid = cid_cycle[i % len(cid_cycle)]
                i += 1
                t0 = time.perf_counter()
                try:
                    rs = nh_by_cid[cid].propose(
                        sessions[cid], payload, timeout=10.0
                    )
                except Exception:
                    errors += 1
                    time.sleep(0.01)  # don't busy-spin on a dead group
                    continue
                inflight.append((t0, rs))
            if not inflight:
                continue
            t0, rs = inflight.popleft()
            r = rs.wait(10.0)
            t1 = time.perf_counter()
            if r.completed:
                lat.append(t1 - t0)
                done += 1
            else:
                errors += 1
        # drain what's left so the tally is exact
        while inflight:
            t0, rs = inflight.popleft()
            r = rs.wait(10.0)
            t1 = time.perf_counter()
            if r.completed:
                lat.append(t1 - t0)
                done += 1
            else:
                errors += 1
    except Exception:
        errors += 1 + len(inflight)
    out.append((done, errors, lat))


def _measure(leaders, cids, payload, window, duration, threads) -> dict:
    nthreads = min(threads, len(cids))
    slices = [cids[i::nthreads] for i in range(nthreads)]
    out = []
    stop_at = time.time() + duration
    ts = [
        threading.Thread(
            target=_load_worker,
            args=(leaders, s, payload, window, stop_at, out),
        )
        for s in slices
    ]
    t0 = time.perf_counter()
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    elapsed = time.perf_counter() - t0
    done = sum(d for d, _, _ in out)
    errors = sum(e for _, e, _ in out)
    if any(l for _, _, l in out):
        lats = np.concatenate([np.asarray(l) for _, _, l in out if l])
        latency = {
            "p50": round(float(np.percentile(lats, 50)) * 1e3, 2),
            "p99": round(float(np.percentile(lats, 99)) * 1e3, 2),
            "mean": round(float(lats.mean()) * 1e3, 2),
        }
    else:  # no completions: keep the JSON strict (no NaN tokens)
        latency = None
    return {
        "writes_per_sec": round(done / elapsed, 1),
        "completed": done,
        "errors": errors,
        "elapsed_s": round(elapsed, 2),
        "proposing_groups": len(cids),
        "window": window,
        "latency_ms": latency,
    }


def run(
    groups: int = 1024,
    duration: float = 10.0,
    window: int = 16,
    rtt_ms: int = 500,
    engine: str = "tpu",
    durable: bool = True,
    threads: int = 16,
    n_hosts: int = 3,
    leader_timeout: float = 300.0,
    latency_groups: int = 64,
) -> dict:
    """Two measurement phases over one live 1024-group cluster:

    1. *throughput*: every group proposes with `window` in flight — the
       sustained writes/s number.  Per-request latency in this phase is
       queueing (Little's law: window/per-group-rate), reported but not the
       latency claim.
    2. *latency*: `latency_groups` groups propose with window=1 while the
       rest stay idle — the propose→applied commit-latency distribution
       (BASELINE.md's P99 commit latency axis).
    """
    payload = b"0123456789abcdef"  # 16B (BASELINE.md ladder payload)
    tmp = None
    dirs = None
    if durable:
        tmp = tempfile.mkdtemp(prefix="dbtpu-e2e-")
        dirs = [os.path.join(tmp, f"nh{i}") for i in range(n_hosts)]
    t_setup = time.perf_counter()
    nhs = _mk_nodehosts(n_hosts, groups, rtt_ms, engine, dirs)
    try:
        cids = _start_groups(nhs, groups)
        leaders = _wait_leaders(nhs, cids, leader_timeout)
        setup_s = time.perf_counter() - t_setup

        tput = _measure(leaders, cids, payload, window, duration, threads)
        lat = _measure(
            leaders,
            cids[: min(latency_groups, groups)],
            payload,
            1,
            min(duration, 5.0),
            threads,
        )
        return {
            "groups": groups,
            "hosts": n_hosts,
            "engine": engine,
            "durable": durable,
            "payload_bytes": len(payload),
            "setup_s": round(setup_s, 1),
            "writes_per_sec": tput["writes_per_sec"],
            "commit_latency_ms": lat["latency_ms"],
            "throughput_phase": tput,
            "latency_phase": lat,
        }
    finally:
        for nh in nhs:
            try:
                nh.stop()
            except Exception:
                pass
        if tmp:
            shutil.rmtree(tmp, ignore_errors=True)


def run_quick() -> dict:
    """Bounded run for bench.py's detail field (driver time budget)."""
    return run(
        groups=int(os.environ.get("E2E_GROUPS", "1024")),
        duration=float(os.environ.get("E2E_DURATION", "10")),
        window=int(os.environ.get("E2E_WINDOW", "16")),
        rtt_ms=int(os.environ.get("E2E_RTT_MS", "500")),
        engine=os.environ.get("E2E_ENGINE", "tpu"),
        durable=os.environ.get("E2E_DURABLE", "1") == "1",
        threads=int(os.environ.get("E2E_THREADS", "16")),
    )


if __name__ == "__main__":
    _force_cpu_for_engine()
    print(json.dumps(run_quick()), file=sys.stdout)
