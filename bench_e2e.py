"""End-to-end NodeHost benchmark: ladder rung 3 (BASELINE.md).

Drives the REAL runtime — NodeHost facade, step/apply engine, LogDB
persistence (C++ native segmented-WAL engine with fsync when durable),
transport between three NodeHosts, and the TPU batched quorum plugin
(``ExpertConfig.quorum_engine="tpu"``) — with G Raft groups × 3 replicas,
measuring:

* **writes/sec**: completed proposals (propose → user SM applied → future
  notified) per second at 16B payload
* **commit latency**: per-request propose→applied wall time, p50/p99

Two deployment modes:

* **multiprocess (default, E2E_PROCS=3)**: one OS process per NodeHost,
  framed-TCP transport on localhost — the same 3-server shape as the
  reference's published benchmark (``docs/test.md:40-53``) and, for a
  GIL-bound host runtime, the honest one: a single process hosting all
  three replicas serializes leader, follower and client work on one
  interpreter lock.  Leaders are placed deterministically via explicit
  campaigns (etcd ``raft.Campaign``) so setup converges in seconds.
* **single process (E2E_PROCS=1)**: all three NodeHosts in-process over
  the chan transport (the reference's memfs test build shape) — used by
  tests and as a fallback.

This is the honest companion to bench.py's kernel-only number: it includes
proposal ingest, host scheduling, log persistence, transport, apply and
request completion, exactly like the reference's published 9M writes/s
(which is measured through its full stack — ``tools/checkdisk/main.go:98``).

Run standalone:  python bench_e2e.py     (env: E2E_GROUPS, E2E_DURATION,
                 E2E_WINDOW, E2E_RTT_MS, E2E_ENGINE, E2E_DURABLE,
                 E2E_THREADS, E2E_PROCS, E2E_LEADER_MODE, E2E_DEADLINE,
                 E2E_MESH_DEVICES — tpu engine over the mesh dispatch plane)
From bench.py:   bench_e2e.run_quick() → dict for the JSON detail field.
"""
from __future__ import annotations

import collections
import json
import os
import random
import shutil
import socket
import subprocess
import sys
import tempfile
import threading
import time


def _force_cpu_for_engine() -> None:
    # the e2e bench runs the device engine on whatever platform jax gives
    # us; when the tunnel is dead this would hang, so standalone runs force
    # CPU unless E2E_TPU=1 (bench.py has already resolved the platform by
    # the time run_quick is called)
    if os.environ.get("E2E_TPU") != "1":
        from dragonboat_tpu import hostplatform

        hostplatform.force_cpu()


# Minimal in-memory SM (reference checkdisk uses a noop-ish SM).
# Imported — not defined here — because this file runs as __main__ for
# the bench axes: a __main__-scoped class has no ``module:qualname``
# spec a hostproc apply worker could import, so the worker tier would
# silently skip it (ISSUE 12); dragonboat_tpu.testing.CounterSM is the
# same machine in an importable home, marked process-spawnable.
from dragonboat_tpu.testing import CounterSM  # noqa: E402


def _payload() -> bytes:
    """E2E_PAYLOAD bytes (default 16; 1024 for the reference latency
    table's large-payload axis), rounded down to a 16B multiple."""
    return b"0123456789abcdef" * max(
        1, int(os.environ.get("E2E_PAYLOAD", "16")) // 16
    )


BASE_CID = 1000


def _percentiles(lats):
    if not lats:
        return None
    import numpy as np

    a = np.asarray(lats)
    return {
        "p50": round(float(np.percentile(a, 50)) * 1e3, 2),
        "p99": round(float(np.percentile(a, 99)) * 1e3, 2),
        "mean": round(float(a.mean()) * 1e3, 2),
    }


# ======================================================================
# load generation (shared by both modes)
# ======================================================================


def _load_worker(nh_by_cid, cids, payload, window, stop_at, drain_deadline, out):
    """Drive a slice of groups: keep `window` proposals in flight per group.

    Completions are consumed by POLLING finished futures in batches (apply
    order is FIFO per group, so each deque drains from the front) with a
    single blocking wait only when nothing has completed anywhere.  A
    per-op blocking ``Event.wait`` here throttles the whole benchmark: the
    GIL hands the client thread one wakeup per scheduling quantum, and the
    runtime ends up idle waiting for the client to refill windows (the
    native pipeline commits a full window in ~10ms; a blocking client took
    ~50ms to notice).  The throughput claim counts only completions inside
    [start, stop_at]; the drain afterwards is bounded and excluded."""
    lat = []
    in_window = 0
    done = 0
    errors = 0
    abandoned = 0
    inflight = {cid: collections.deque() for cid in cids}
    try:
        sessions = {cid: nh_by_cid[cid].get_noop_session(cid) for cid in cids}

        def refill(cid, dq):
            nonlocal errors
            want = window - len(dq)
            if want <= 0 or time.time() >= stop_at:
                return True
            t0 = time.perf_counter()
            try:
                # burst refill: one tracked future per command, one pass
                # through the propose path (NodeHost.propose_batch)
                states = nh_by_cid[cid].propose_batch(
                    sessions[cid], [payload] * want, timeout=30.0
                )
            except Exception:
                errors += 1
                time.sleep(0.005)  # don't busy-spin on a dead group
                return False
            for rs in states:
                dq.append((t0, rs))
            return True

        while time.time() < stop_at:
            progress = 0
            for cid, dq in inflight.items():
                while dq and dq[0][1].done():
                    t0, rs = dq.popleft()
                    r = rs.result  # property; set before the event
                    t1 = time.perf_counter()
                    if r is not None and r.completed:
                        lat.append(t1 - t0)
                        done += 1
                        progress += 1
                        if time.time() <= stop_at:
                            in_window += 1
                    else:
                        errors += 1
                refill(cid, dq)
            if not progress:
                oldest = None
                for dq in inflight.values():
                    if dq and (oldest is None or dq[0][0] < oldest[0]):
                        oldest = dq[0]
                if oldest is None:
                    time.sleep(0.002)
                else:
                    oldest[1].wait(0.05)
        # bounded drain (not counted toward the rate)
        for cid, dq in inflight.items():
            while dq and time.time() < drain_deadline:
                t0, rs = dq.popleft()
                r = rs.wait(max(0.1, min(10.0, drain_deadline - time.time())))
                t1 = time.perf_counter()
                if r.completed:
                    lat.append(t1 - t0)
                    done += 1
                else:
                    errors += 1
        abandoned = sum(len(dq) for dq in inflight.values())
    except Exception:
        errors += 1 + sum(len(dq) for dq in inflight.values())
    out.append((in_window, done, errors, abandoned, lat))


def _measure(
    leaders, cids, payload, window, stop_at, threads, drain_budget=30.0
) -> dict:
    nthreads = max(1, min(threads, len(cids)))
    slices = [cids[i::nthreads] for i in range(nthreads)]
    out = []
    t_begin = time.time()
    duration = max(stop_at - t_begin, 0.001)
    drain_deadline = stop_at + drain_budget
    ts = [
        threading.Thread(
            target=_load_worker,
            args=(leaders, s, payload, window, stop_at, drain_deadline, out),
        )
        for s in slices
        if s
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    in_window = sum(w for w, _, _, _, _ in out)
    done = sum(d for _, d, _, _, _ in out)
    errors = sum(e for _, _, e, _, _ in out)
    abandoned = sum(a for _, _, _, a, _ in out)
    lats = [l for _, _, _, _, ls in out for l in ls]
    return {
        "writes_per_sec": round(in_window / duration, 1),
        "completed_in_window": in_window,
        "completed": done,
        "errors": errors,
        "abandoned": abandoned,
        "duration_s": round(duration, 2),
        "proposing_groups": len(cids),
        "window": window,
        "latency_ms": _percentiles(lats),
        "_lats": lats,
    }


def _mixed_worker(nh_by_cid, cids, payload, read_ratio, stop_at, out,
                  window=None):
    """9:1-style mixed load (BASELINE.md's Mixed IO row): weighted
    round-robin of linearizable ReadIndex reads and writes, PIPELINED per
    thread — a window of ops is submitted, then completions are drained.

    Per-op latency stays an honest submit→complete round trip; the window
    only removes the client's own serialization (the reference's mixed
    number likewise comes from many concurrent in-flight clients).  The
    server collapses concurrent reads on a group into one ReadIndex
    context (``PendingReadIndex`` take-time batching), so the pipelined
    client measures server capacity instead of client turnaround."""
    if window is None:
        window = int(os.environ.get("E2E_MIXED_WINDOW", "8"))
    reads = writes = errors = 0
    lat_r = []
    lat_w = []
    try:
        sessions = {cid: nh_by_cid[cid].get_noop_session(cid) for cid in cids}
        i = 0
        while time.time() < stop_at:
            batch = []
            for _ in range(window):
                cid = cids[i % len(cids)]
                i += 1
                is_read = (i % (read_ratio + 1)) != 0
                t0 = time.perf_counter()
                try:
                    if is_read:
                        rs = nh_by_cid[cid].read_index(cid, 10.0)
                    else:
                        rs = nh_by_cid[cid].propose(
                            sessions[cid], payload, timeout=10.0
                        )
                    batch.append((is_read, cid, t0, rs))
                except Exception:
                    errors += 1
            for is_read, cid, t0, rs in batch:
                try:
                    r = rs.wait(10.0)
                    if is_read and not r.completed:
                        # dropped/timed-out reads are normal during leader
                        # movement and fast-lane ejects; sync_read retries
                        # them (_sync_retry), so the pipelined client must
                        # too or transient drops read as hard errors
                        rs = nh_by_cid[cid].read_index(cid, 10.0)
                        r = rs.wait(10.0)
                    if r.completed:
                        # completed_at (stamped at notify) keeps per-op
                        # latency honest: a slow op at the head of the
                        # drain loop must not inflate the ops behind it
                        done_t = rs.completed_at or time.perf_counter()
                        if is_read:
                            # the read value itself (sync_read tail)
                            nh_by_cid[cid].get_node(cid).sm.lookup(None)
                            lat_r.append(done_t - t0)
                            reads += 1
                        else:
                            lat_w.append(done_t - t0)
                            writes += 1
                    else:
                        errors += 1
                except Exception:
                    errors += 1
            if errors and not batch:
                time.sleep(0.01)
    except Exception:
        errors += 1
    out.append((reads, writes, errors, lat_r, lat_w))


def _measure_mixed(leaders, cids, payload, read_ratio, stop_at, threads) -> dict:
    nthreads = max(1, min(threads, len(cids)))
    slices = [cids[i::nthreads] for i in range(nthreads)]
    out = []
    t_begin = time.time()
    duration = max(stop_at - t_begin, 0.001)
    ts = [
        threading.Thread(
            target=_mixed_worker,
            args=(leaders, s, payload, read_ratio, stop_at, out),
        )
        for s in slices
        if s
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    reads = sum(r for r, _, _, _, _ in out)
    writes = sum(w for _, w, _, _, _ in out)
    errors = sum(e for _, _, e, _, _ in out)
    lat_r = [l for _, _, _, ls, _ in out for l in ls]
    lat_w = [l for _, _, _, _, ls in out for l in ls]
    return {
        "ops_per_sec": round((reads + writes) / duration, 1),
        "reads": reads,
        "writes": writes,
        "errors": errors,
        "read_ratio": read_ratio,
        "read_latency_ms": _percentiles(lat_r),
        "write_latency_ms": _percentiles(lat_w),
    }


# ======================================================================
# many-client/many-session axis (ISSUE 8: the commit-latency-bound
# scenario — a session serializes its series ids, so per-session
# throughput is one write per commit latency and aggregate throughput is
# sessions/latency; the compartmentalized host plane attacks exactly the
# per-write host overheads this shape exposes)
# ======================================================================


def _session_worker(nh, cid, stop_at, out):
    """One exactly-once session: register, serialized sync proposes until
    the deadline, close.  Latency is the full propose→applied→notified
    round trip (the session semantics forbid pipelining)."""
    done = 0
    errors = 0
    lats = []
    payload = _payload()
    try:
        s = nh.sync_get_session(cid, timeout=30.0)
    except Exception:
        out.append((0, 1, []))
        return
    try:
        while time.time() < stop_at:
            t0 = time.perf_counter()
            try:
                nh.sync_propose(s, payload, timeout=30.0)
                lats.append(time.perf_counter() - t0)
                done += 1
            except Exception:
                errors += 1
                time.sleep(0.01)
    finally:
        try:
            nh.sync_close_session(s, timeout=10.0)
        except Exception:
            pass
    out.append((done, errors, lats))


class _SlowDisk:
    """Simulated contended durability device: every fsync costs
    ``delay_ms`` of device time and the device serializes barrier
    flushes (one platter / one virtio queue — physically what an HDD or
    throttled cloud block device does).  CLEARLY A SIMULATION: the
    slow-disk axis labels its rows with the injected cost; the fast-disk
    axis next to it is the real device."""

    def __init__(self, delay_ms: float):
        self.delay_s = delay_ms / 1e3
        self.mu = threading.Lock()
        self.fsyncs = 0

    def wait(self):
        with self.mu:
            self.fsyncs += 1
            time.sleep(self.delay_s)


def _slow_fs(disk):
    from dragonboat_tpu import vfs

    class SlowFS(vfs.OSFS):
        def fsync(self, f):
            super().fsync(f)
            disk.wait()

        def fsync_dir(self, path):
            super().fsync_dir(path)
            disk.wait()

    return SlowFS()


def run_sessions(
    sessions: int = 32,
    groups: int = 32,
    duration: float = 10.0,
    rtt_ms: int = 50,
    compartments: bool = False,
    n_hosts: int = 3,
    engine: str = "scalar",
    fsync_ms: float = 0.0,
    host_workers: int = 0,
    wal_journal: str = "auto",
) -> dict:
    """Durable single-process 3-host cluster, S exactly-once sessions
    round-robined over G groups.  Returns w/s, commit p50/p99, fsyncs/s
    and (compartments on) the host-plane stats including the measured
    fsync amortization factor.

    ``fsync_ms > 0`` switches the LogDB to the pure-Python WAL backend on
    a SIMULATED serialized slow disk (see :class:`_SlowDisk`) — the
    contended-durability axis where every persisting group riding its own
    fsync is the bottleneck the cross-shard group commit removes."""
    from dragonboat_tpu import Config, NodeHostConfig
    from dragonboat_tpu.config import ExpertConfig, LogDBConfig
    from dragonboat_tpu.logdb import open_logdb
    from dragonboat_tpu.nodehost import NodeHost
    from dragonboat_tpu.transport import ChanRouter, ChanTransport

    tmp = tempfile.mkdtemp(prefix="dbtpu-sess-")
    router = ChanRouter()
    nhs = []
    disk = _SlowDisk(fsync_ms) if fsync_ms > 0 else None
    slow_fs = _slow_fs(disk) if disk is not None else None
    shards = int(os.environ.get("E2E_SHARDS", "4"))
    try:
        for i in range(1, n_hosts + 1):
            logdb_factory = None
            if slow_fs is not None:
                from dragonboat_tpu.logdb.kv import WalKV

                ldb_dir = os.path.join(tmp, f"ldb{i}")
                logdb_factory = (
                    lambda nhc, d=ldb_dir: open_logdb(
                        d, shards=shards,
                        kv_factory=lambda sd: WalKV(
                            sd, fsync=True, fs=slow_fs
                        ),
                    )
                )
            nhs.append(
                NodeHost(
                    NodeHostConfig(
                        node_host_dir=os.path.join(tmp, f"nh{i}"),
                        rtt_millisecond=rtt_ms,
                        raft_address=f"e2e{i}:1",  # _start_groups wires these names
                        raft_rpc_factory=lambda src, rh, ch: ChanTransport(
                            src, rh, ch, router=router
                        ),
                        logdb_config=LogDBConfig(fsync=True),
                        logdb_factory=logdb_factory,
                        expert=ExpertConfig(
                            quorum_engine=engine,
                            engine_block_groups=max(groups, 64),
                            logdb_shards=shards,
                            host_compartments=compartments,
                            # multi-process host plane (ISSUE 12): 0 =
                            # in-process tiers; N spawns N workers per
                            # host behind shared-memory rings
                            host_workers=host_workers,
                            host_wal_journal=wal_journal,
                            # the journal rides the same simulated device
                            fs=slow_fs,
                        ),
                    )
                )
            )
        cids = _start_groups(nhs, groups, election_rtt=20)
        leaders = _campaign_and_wait(nhs, cids, 120.0)
        fsync0 = sum(nh.logdb.fsync_count() for nh in nhs)
        t0 = time.time()
        stop_at = t0 + duration
        out = []
        ts = [
            threading.Thread(
                target=_session_worker,
                args=(leaders[cids[i % groups]], cids[i % groups], stop_at,
                      out),
            )
            for i in range(sessions)
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        elapsed = max(time.time() - t0, 1e-6)
        fsyncs = sum(nh.logdb.fsync_count() for nh in nhs) - fsync0
        done = sum(d for d, _, _ in out)
        errors = sum(e for _, e, _ in out)
        lats = [l for _, _, ls in out for l in ls]
        res = {
            "sessions": sessions,
            "groups": groups,
            "hosts": n_hosts,
            "engine": engine,
            "compartments": compartments,
            "host_workers": host_workers,
            # >0 = the SIMULATED serialized-device axis (fsync costs this
            # many ms and flushes queue at one device); 0 = the real disk
            "fsync_ms": fsync_ms,
            "duration_s": round(elapsed, 2),
            "writes_per_sec": round(done / elapsed, 1),
            "completed": done,
            "errors": errors,
            "commit_latency_ms": _percentiles(lats),
            "fsyncs": fsyncs,
            "fsyncs_per_sec": round(fsyncs / elapsed, 1),
        }
        if compartments or host_workers:
            hp = [nh.hostplane.stats() for nh in nhs]
            res["hostplane"] = hp
            if host_workers:
                res["hostproc"] = [
                    nh.hostproc.stats() for nh in nhs
                    if nh.hostproc is not None
                ]
            # cross-committer fsync amortization, load-weighted across
            # hosts: committer submissions per flusher cycle
            subs = sum(h["wal"]["submissions"] for h in hp)
            flushes = sum(h["wal"]["flushes"] for h in hp)
            res["amortization"] = round(subs / flushes, 2) if flushes else 0.0
        return res
    finally:
        for nh in nhs:
            try:
                nh.stop()
            except Exception:
                pass
        shutil.rmtree(tmp, ignore_errors=True)


def run_sessions_ab(
    sessions: int = 32, groups: int = 32, duration: float = 10.0,
    fsync_ms: float = 0.0,
) -> dict:
    """Compartments on/off A/B on the many-session axis (ISSUE 8
    acceptance: >= 2.5x at 32 sessions, amortization factor > 1)."""
    off = run_sessions(
        sessions=sessions, groups=groups, duration=duration,
        compartments=False, fsync_ms=fsync_ms,
    )
    on = run_sessions(
        sessions=sessions, groups=groups, duration=duration,
        compartments=True, fsync_ms=fsync_ms,
    )
    speed = (
        round(on["writes_per_sec"] / off["writes_per_sec"], 2)
        if off["writes_per_sec"]
        else None
    )
    return {"off": off, "on": on, "speedup": speed}


def run_host_workers_axis(
    sessions: int = 32, groups: int = 8, duration: float = 8.0,
    workers: int = 0,
) -> dict:
    """Multi-process host plane A/B (ISSUE 12 acceptance): the same
    many-session durable cluster with ``host_workers=0`` (in-process
    compartmentalized plane) vs N worker processes per host.

    The assertion is CPU-topology gated, by design: on a multi-core box
    the worker tier must deliver the scaling target (≥5x e2e w/s at 32+
    sessions with ≥8 cores, pro-rated below that — override with env
    ``E2E_HW_TARGET``); on a single-core box there is no parallelism to
    win — every process time-slices one core and each ring handoff is a
    scheduling quantum — so the axis asserts parity-within-noise
    (workers ≥ ``E2E_HW_PARITY_FLOOR``, default 0.5x, of in-process;
    single-window weather on the 1-vCPU box is ±15%) and LABELS itself
    ``single_core`` so the ledger records the limitation instead of a
    fake win."""
    cores = os.cpu_count() or 1
    n = workers or max(1, min(cores, 4))
    single_core = cores < 2
    # journal mode FORCED symmetrically: a fast-disk auto probe keeps
    # the classic per-shard saves and the WAL worker would idle — the
    # axis wants the redo-journal cycle on both sides so "on" routes the
    # same durability work through the worker that "off" runs in-process
    off = run_sessions(
        sessions=sessions, groups=groups, duration=duration,
        compartments=True, host_workers=0, wal_journal="force",
    )
    on = run_sessions(
        sessions=sessions, groups=groups, duration=duration,
        compartments=True, host_workers=n, wal_journal="force",
    )
    speedup = (
        round(on["writes_per_sec"] / off["writes_per_sec"], 2)
        if off["writes_per_sec"] else None
    )
    if single_core:
        target = float(os.environ.get("E2E_HW_PARITY_FLOOR", "0.5"))
        assert_ok = speedup is not None and speedup >= target
        assertion = (
            f"single-core parity-within-noise: {speedup}x >= {target}x"
        )
    else:
        target = float(
            os.environ.get(
                "E2E_HW_TARGET",
                "5.0" if cores >= 8 else str(round(0.6 * cores, 2)),
            )
        )
        assert_ok = speedup is not None and speedup >= target
        assertion = f"multi-core scaling: {speedup}x >= {target}x"
    hp = on.get("hostproc") or []
    return {
        "cores": cores,
        "single_core": single_core,
        "workers": n,
        "axis": [{"off": off, "on": on, "speedup": speedup}],
        "restarts": sum(h.get("restarts", 0) for h in hp),
        "fallbacks": {
            k: sum(h.get("fallbacks", {}).get(k, 0) for h in hp)
            for k in ("encode", "wal", "apply")
        },
        "assertion": assertion,
        "assert_ok": assert_ok,
    }


# ======================================================================
# single-process mode (chan transport; tests + fallback)
# ======================================================================


def _mk_nodehosts(n_hosts, groups, rtt_ms, engine, dirs, trace=0):
    from dragonboat_tpu import NodeHostConfig
    from dragonboat_tpu.config import ExpertConfig
    from dragonboat_tpu.nodehost import NodeHost
    from dragonboat_tpu.transport import ChanRouter, ChanTransport

    router = ChanRouter()
    nhs = []
    for i in range(1, n_hosts + 1):
        nhs.append(
            NodeHost(
                NodeHostConfig(
                    node_host_dir=dirs[i - 1] if dirs else ":memory:",
                    rtt_millisecond=rtt_ms,
                    raft_address=f"e2e{i}:1",
                    raft_rpc_factory=lambda src, rh, ch: ChanTransport(
                        src, rh, ch, router=router
                    ),
                    trace_sample_every=trace,
                    expert=ExpertConfig(
                        quorum_engine=engine,
                        engine_block_groups=max(groups, 64),
                        logdb_shards=4,
                        # mesh-sharded dispatch plane (ISSUE 16): N > 1
                        # builds each tpu-engine coordinator over the
                        # MeshQuorumEngine facade — one dispatch stream
                        # per shard instead of one GSPMD program
                        engine_mesh_devices=int(
                            os.environ.get("E2E_MESH_DEVICES", "0")
                        ),
                    ),
                )
            )
        )
    return nhs


def _start_groups(nhs, groups, base_cid=BASE_CID, election_rtt=20):
    from dragonboat_tpu import Config

    addrs = {i: f"e2e{i}:1" for i in range(1, len(nhs) + 1)}
    for g in range(groups):
        cid = base_cid + g
        for i, nh in enumerate(nhs, start=1):
            nh.start_cluster(
                addrs,
                False,
                CounterSM,
                Config(
                    cluster_id=cid,
                    node_id=i,
                    election_rtt=election_rtt,
                    heartbeat_rtt=1,
                    snapshot_entries=0,
                ),
            )
    return [base_cid + g for g in range(groups)]


def _campaign_and_wait(nhs, cids, timeout):
    """Deterministic leader placement: replica ``cid % n_hosts`` campaigns
    explicitly (etcd raft.Campaign), spreading leaders evenly without
    waiting out randomized election timeouts."""
    n = len(nhs)
    for cid in cids:
        nhs[cid % n].get_node(cid).request_campaign()
    deadline = time.time() + timeout
    leaders = {}
    remaining = set(cids)
    while remaining and time.time() < deadline:
        for cid in list(remaining):
            for nh in nhs:
                lid, ok = nh.get_leader_id(cid)
                if ok and 1 <= lid <= len(nhs):
                    leaders[cid] = nhs[lid - 1]
                    remaining.discard(cid)
                    break
        if remaining:
            time.sleep(0.05)
    if remaining:
        raise TimeoutError(f"{len(remaining)}/{len(cids)} groups leaderless")
    return leaders


def run(
    groups: int = 1024,
    duration: float = 10.0,
    window: int = 16,
    rtt_ms: int = 500,
    engine: str = "tpu",
    durable: bool = True,
    threads: int = 16,
    n_hosts: int = 3,
    leader_timeout: float = 180.0,
    latency_groups: int = 64,
) -> dict:
    """Single-process run; two measurement phases over one live cluster:

    1. *throughput*: every group proposes with `window` in flight — the
       sustained writes/s number.  Per-request latency in this phase is
       queueing (Little's law: window/per-group-rate), reported but not the
       latency claim.
    2. *latency*: `latency_groups` groups propose with window=1 while the
       rest stay idle — the propose→applied commit-latency distribution
       (BASELINE.md's P99 commit latency axis).
    """
    payload = _payload()  # 16B default (BASELINE.md ladder payload)
    tmp = None
    dirs = None
    if durable:
        tmp = tempfile.mkdtemp(prefix="dbtpu-e2e-")
        dirs = [os.path.join(tmp, f"nh{i}") for i in range(n_hosts)]
    t_setup = time.perf_counter()
    nhs = _mk_nodehosts(n_hosts, groups, rtt_ms, engine, dirs)
    try:
        cids = _start_groups(nhs, groups)
        leaders = _campaign_and_wait(nhs, cids, leader_timeout)
        setup_s = time.perf_counter() - t_setup
        print(f"e2e setup_s={setup_s:.1f}", file=sys.stderr)

        tput = _measure(
            leaders, cids, payload, window, time.time() + duration, threads
        )
        lat = _measure(
            leaders,
            cids[: min(latency_groups, groups)],
            payload,
            1,
            time.time() + min(duration, 5.0),
            threads,
        )
        tput.pop("_lats", None)
        lat.pop("_lats", None)
        return {
            "groups": groups,
            "hosts": n_hosts,
            "procs": 1,
            "engine": engine,
            "durable": durable,
            "payload_bytes": len(payload),
            "setup_s": round(setup_s, 1),
            "writes_per_sec": tput["writes_per_sec"],
            "commit_latency_ms": lat["latency_ms"],
            "throughput_phase": tput,
            "latency_phase": lat,
        }
    finally:
        for nh in nhs:
            try:
                nh.stop()
            except Exception:
                pass
        if tmp:
            shutil.rmtree(tmp, ignore_errors=True)


# ======================================================================
# trace axis (ISSUE 9): overhead A/B + per-stage latency attribution
# ======================================================================


def _set_tracing(nhs, on: bool) -> None:
    """Attach/detach the request tracer across a LIVE cluster.  Every
    hook gates on a plain ``is not None`` check, so the detached half of
    the A/B runs the trace-off path on the very same cluster — no
    cluster-to-cluster weather in the comparison.  The replication
    attribution plane (obs/replattr.py, ISSUE 14) lives and dies with
    the tracer: the same toggle detaches it everywhere down to the raft
    ack/commit hooks, so the off half also prices the replattr latch."""
    for nh in nhs:
        t = nh._trace_axis_tracer if on else None
        ra = (getattr(nh, "_trace_axis_replattr", None) or None) if on else None
        nh.tracer = t
        nh.replattr = ra
        nh.engine.tracer = t
        if nh.quorum_coordinator is not None:
            nh.quorum_coordinator.tracer = t
            nh.quorum_coordinator.replattr = ra
        with nh._mu:
            nodes = [n for n in nh._clusters.values() if n is not None]
        for n in nodes:
            n.tracer = t
            n.pending_reads._tracer = t
            n.replattr = ra
            n.peer.raft.replattr = ra


def _merged_stage_stats(nhs) -> dict:
    """Per-stage p50/p99 + share-of-e2e over every host's completed
    trace ring (leaders are spread, so each host traced its share) —
    the library's own ``compute_stage_stats`` does the math, so this
    table and ``nh.tracer.stage_stats()`` can never disagree."""
    from dragonboat_tpu.obs.trace import compute_stage_stats

    return compute_stage_stats(
        t for nh in nhs for t in nh._trace_axis_tracer.traces()
    )


def run_trace_axis() -> dict:
    """Request-tracing axis (ISSUE 9): trace-on vs trace-off throughput
    on the live host loop (interleaved windows on ONE cluster, best-of —
    the obs axis's scheduler-weather discipline; <5% asserted) plus the
    per-stage latency attribution tables, for BOTH the scalar and the
    tpu-engine (warmed fused) paths.  The perf ledger's "Latency
    attribution" table derives from this section.

    Env knobs: TRACE_AXIS_GROUPS (64), TRACE_AXIS_DURATION (5s/window),
    TRACE_AXIS_WINDOW (8 in flight/group), TRACE_AXIS_SAMPLE (1-in-8).
    """
    groups = int(os.environ.get("TRACE_AXIS_GROUPS", "64"))
    duration = float(os.environ.get("TRACE_AXIS_DURATION", "5"))
    window = int(os.environ.get("TRACE_AXIS_WINDOW", "8"))
    sample = int(os.environ.get("TRACE_AXIS_SAMPLE", "8"))
    threads = int(os.environ.get("TRACE_AXIS_THREADS", "4"))
    # rtt low enough that the loaded box's round thread (niced +5) sees
    # tick deficits > 1 — the tpu rows then measure the FUSED host loop
    # (fused_dispatches in the output evidences it), not just a warmed
    # one
    rtt_ms = int(os.environ.get("TRACE_AXIS_RTT_MS", "30"))
    payload = _payload()
    out = {
        "groups": groups,
        "window": window,
        "sample_every": sample,
        "window_duration_s": duration,
        "rtt_ms": rtt_ms,
        "engines": {},
    }
    for engine in ("scalar", "tpu"):
        tmp = tempfile.mkdtemp(prefix=f"dbtpu-trace-{engine}-")
        dirs = [os.path.join(tmp, f"nh{i}") for i in range(3)]
        nhs = _mk_nodehosts(3, groups, rtt_ms, engine, dirs, trace=sample)
        try:
            for nh in nhs:
                # keep a handle: the A/B detaches/reattaches mid-run
                nh._trace_axis_tracer = nh.tracer
                nh._trace_axis_replattr = nh.replattr
            cids = _start_groups(nhs, groups)
            leaders = _campaign_and_wait(nhs, cids, 180.0)
            fused_before = 0
            if engine == "tpu":
                # the fused host loop: wait for the background AOT warm
                # so measured rounds can replay tick backlogs fused
                deadline = time.time() + 180
                while time.time() < deadline and not all(
                    nh.quorum_coordinator.eng.fused_ready for nh in nhs
                ):
                    time.sleep(0.25)
                fused_before = sum(
                    nh.quorum_coordinator.fused_dispatches for nh in nhs
                )

            def measure(on):
                _set_tracing(nhs, on)
                m = _measure(
                    leaders, cids, payload, window,
                    time.time() + duration, threads, drain_budget=15.0,
                )
                return m["writes_per_sec"]

            measure(False)  # warmup window (compile, cache, enrollment)
            # paired A/B, MEAN of pair-wise deltas over an EVEN number
            # of alternating-order pairs: this axis has ±15%
            # window-to-window weather on a 1-vCPU box (BENCH_r09 note),
            # so single windows or best-of measure the weather, not the
            # tracer.  Adjacent windows pair off (drift cancels within
            # a pair); the order alternates per pair and the count is
            # even, so a systematic second-window penalty contributes
            # +p,-p,... and cancels EXACTLY in the mean.  The assert is
            # one-sided with a 2-SEM noise allowance — the residual
            # pair noise is published (pair_deltas/sem) so the artifact
            # shows the measurement's power, not just its verdict.
            pairs = max(2, int(os.environ.get("TRACE_AXIS_PAIRS", "6")) // 2 * 2)
            deltas = []
            wps_on = wps_off = 0.0
            for pair in range(pairs):
                if pair % 2 == 0:
                    on = measure(True)
                    off = measure(False)
                else:
                    off = measure(False)
                    on = measure(True)
                wps_on = max(wps_on, on)
                wps_off = max(wps_off, off)
                deltas.append((off - on) / off * 100.0)
            mean = sum(deltas) / len(deltas)
            var = sum((d - mean) ** 2 for d in deltas) / max(1, len(deltas) - 1)
            sem = (var / len(deltas)) ** 0.5
            overhead = round(mean, 2)
            # attribution phase: a DEDICATED traced window — the rings
            # are cleared (and widened past the steady-state cap) first,
            # so the published percentiles cover exactly this window's
            # population instead of the newest keep=256 tail of the A/B
            for nh in nhs:
                nh._trace_axis_tracer.reset_completed(keep=8192)
            _set_tracing(nhs, True)
            _measure(
                leaders, cids, payload, window, time.time() + duration,
                threads, drain_budget=15.0,
            )
            attribution = _merged_stage_stats(nhs)
            eng_out = {
                "writes_per_sec_trace_off": round(wps_off, 1),
                "writes_per_sec_trace_on": round(wps_on, 1),
                "trace_overhead_pct": overhead,  # mean pair-wise
                "trace_overhead_sem_pct": round(sem, 2),
                "pair_deltas_pct": [round(d, 2) for d in deltas],
                "trace_overhead_ok": overhead < 5.0 + 2 * sem,
                "attribution": attribution,
            }
            if engine == "tpu":
                eng_out["fused_dispatches"] = sum(
                    nh.quorum_coordinator.fused_dispatches for nh in nhs
                ) - fused_before
                eng_out["fused_ready"] = all(
                    nh.quorum_coordinator.eng.fused_ready for nh in nhs
                )
            assert overhead < 5.0 + 2 * sem, (
                f"trace overhead too high on {engine}: {overhead}% "
                f"(± {sem:.1f} SEM; {wps_on:.0f} vs {wps_off:.0f} w/s)"
            )
            out["engines"][engine] = eng_out
        finally:
            for nh in nhs:
                try:
                    nh.stop()
                except Exception:
                    pass
            shutil.rmtree(tmp, ignore_errors=True)
    out["trace_overhead_ok"] = all(
        e.get("trace_overhead_ok") for e in out["engines"].values()
    )
    return out


# ======================================================================
# cluster health axis (ISSUE 13): health-on/off overhead + a churn
# phase producing detector events with recovery durations
# ======================================================================


def _set_health(nhs, on: bool) -> None:
    """Attach/detach the health sampler across a LIVE cluster (the
    ``_set_tracing`` discipline): the tick-worker hook gates on a plain
    ``is not None`` check, so the detached half of the A/B runs the
    health-off path on the very same cluster."""
    for nh in nhs:
        nh.health = nh._health_axis_sampler if on else None


def run_health_axis() -> dict:
    """Cluster-health axis (ISSUE 13): health-on vs health-off
    throughput on a live 3-host cluster — interleaved windows on ONE
    cluster, but scored as the MEAN pair-wise delta ± SEM over
    alternating-order pairs (the trace-axis discipline, not raw
    best-of: this is the live e2e stack, whose window-to-window weather
    on a 1-vCPU box is ±15% — a best-of-3 measured the scheduler, and
    the first capture failed its own gate at 6.85% with the sampler
    costing ~1ms per 50ms cadence) — <5% + 2·SEM asserted; then a
    leadership-churn phase with health ON so the leader-flap detector
    opens and closes with real recovery durations.  The perf ledger's
    "Cluster health" table (detector counts, recovery p50/p99) derives
    from this section's health ring dump.

    Env knobs: HEALTH_AXIS_GROUPS (32), HEALTH_AXIS_DURATION (4s/window),
    HEALTH_AXIS_PAIRS (4), HEALTH_AXIS_SAMPLE_MS (50).
    """
    from dragonboat_tpu.obs.health import HealthSampler

    groups = int(os.environ.get("HEALTH_AXIS_GROUPS", "32"))
    duration = float(os.environ.get("HEALTH_AXIS_DURATION", "4"))
    pairs = max(2, int(os.environ.get("HEALTH_AXIS_PAIRS", "4")) // 2 * 2)
    sample_ms = int(os.environ.get("HEALTH_AXIS_SAMPLE_MS", "50"))
    window = int(os.environ.get("HEALTH_AXIS_WINDOW", "8"))
    threads = int(os.environ.get("HEALTH_AXIS_THREADS", "4"))
    payload = _payload()
    tmp = tempfile.mkdtemp(prefix="dbtpu-health-")
    dirs = [os.path.join(tmp, f"nh{i}") for i in range(3)]
    nhs = _mk_nodehosts(3, groups, 30, "scalar", dirs)
    out = {
        "groups": groups,
        "window_duration_s": duration,
        "pairs": pairs,
        "sample_ms": sample_ms,
    }
    try:
        cids = _start_groups(nhs, groups)
        leaders = _campaign_and_wait(nhs, cids, 180.0)
        for nh in nhs:
            # one sampler per host, constructed once and A/B-toggled;
            # tight flap knobs so the churn phase's transfers open the
            # leader-flap detector and a short quiet window closes it
            nh._health_axis_sampler = HealthSampler(
                nh, sample_ms=sample_ms,
                registry=nh.metrics_registry,
                leader_flap_changes=2,
                flap_window_s=3.0,
            )

        def measure(on):
            _set_health(nhs, on)
            m = _measure(
                leaders, cids, payload, window,
                time.time() + duration, threads, drain_budget=15.0,
            )
            return m["writes_per_sec"]

        measure(False)  # warmup window
        # paired A/B, mean of pair-wise deltas over an even number of
        # alternating-order pairs (drift cancels within a pair, a
        # systematic second-window penalty cancels across the
        # alternation) — the residual pair noise is published so the
        # artifact shows the measurement's power, not just its verdict
        deltas = []
        wps_on = wps_off = 0.0
        for pair in range(pairs):
            if pair % 2 == 0:
                on = measure(True)
                off = measure(False)
            else:
                off = measure(False)
                on = measure(True)
            wps_on = max(wps_on, on)
            wps_off = max(wps_off, off)
            deltas.append((off - on) / off * 100.0)
        mean = sum(deltas) / len(deltas)
        var = sum((d - mean) ** 2 for d in deltas) / max(1, len(deltas) - 1)
        sem = (var / len(deltas)) ** 0.5
        overhead = round(mean, 2)
        out["writes_per_sec_health_on"] = round(wps_on, 1)
        out["writes_per_sec_health_off"] = round(wps_off, 1)
        out["health_overhead_pct"] = overhead
        out["health_overhead_sem_pct"] = round(sem, 2)
        out["pair_deltas_pct"] = [round(d, 2) for d in deltas]
        out["health_overhead_ok"] = overhead < 5.0 + 2 * sem
        assert overhead < 5.0 + 2 * sem, (
            f"health overhead too high: {overhead}% (± {sem:.1f} SEM; "
            f"{wps_on:.0f} vs {wps_off:.0f} w/s)"
        )

        # churn phase: transfer one group's leadership around the ring
        # under sampling — each double-transfer is ≥2 leader changes
        # inside the flap window on some host, opening leader_flap;
        # the quiet tail closes it and records the recovery duration
        _set_health(nhs, True)
        churn_cid = cids[0]
        for i in range(4):
            for nh in nhs:
                lid, ok = nh.get_leader_id(churn_cid)
                if ok and 1 <= lid <= 3:
                    target = (lid % 3) + 1
                    try:
                        nhs[lid - 1].request_leader_transfer(
                            churn_cid, target
                        )
                    except Exception:
                        pass
                    break
            time.sleep(0.8)
        # quiet window: let the flap deque age out and the event close
        deadline = time.time() + 30.0
        while time.time() < deadline:
            if any(
                nh._health_axis_sampler.recovery_stats().get("leader_flap")
                for nh in nhs
            ) and not any(
                nh._health_axis_sampler.open_events() for nh in nhs
            ):
                break
            time.sleep(0.5)

        # aggregate detector counts + merged recovery durations
        detectors: dict = {}
        merged: dict = {}
        samples_total = 0
        for nh in nhs:
            hs = nh._health_axis_sampler
            samples_total += hs._n
            for det, c in hs.opened.items():
                d = detectors.setdefault(det, {"opened": 0, "closed": 0})
                d["opened"] += c
                d["closed"] += len(hs._recoveries[det])
                merged.setdefault(det, []).extend(hs._recoveries[det])
        out["samples_total"] = samples_total
        out["detectors"] = {
            d: v for d, v in detectors.items() if v["opened"]
        }
        from dragonboat_tpu.obs.health import _pctile

        out["recovery"] = {
            det: {
                "n": len(durs),
                "p50_s": round(_pctile(durs, 50), 4),
                "p99_s": round(_pctile(durs, 99), 4),
                "max_s": round(max(durs), 4),
            }
            for det, durs in merged.items() if durs
        }
        out["churn_events_ok"] = bool(out["recovery"].get("leader_flap"))
        # the ring dump of the host that recorded the churn (artifact
        # evidence for the ledger; trimmed)
        dump_nh = max(
            nhs, key=lambda nh: len(
                nh._health_axis_sampler._recoveries["leader_flap"]
            ),
        )
        out["ring"] = dump_nh._health_axis_sampler.to_json(limit=24)
        return out
    finally:
        for nh in nhs:
            try:
                nh.stop()
            except Exception:
                pass
        shutil.rmtree(tmp, ignore_errors=True)


# ======================================================================
# device telemetry axis (ISSUE 20): aggregate sampler wall flat in G +
# telem-fold dispatch overhead + on-device top-K hit rate
# ======================================================================


class _TelemNodeShim:
    """Per-group stand-in for the sampler walk: a bounded-cost
    ``health_snapshot`` like ``Node``'s, so sampler wall measures the
    walk discipline, not raft bookkeeping."""

    def health_snapshot(self, lock_timeout=0.0):
        return {"committed": 1, "applied": 1, "leader_id": 1}


class _TelemQcShim:
    """Engine-facade stand-in exposing exactly the coordinator surface
    ``HealthSampler.sample`` touches in aggregate mode."""

    def __init__(self, eng):
        self.eng = eng

    def telem_snapshot(self):
        return self.eng.telem_snapshot()

    def registered_cids(self):
        return set(self.eng.groups)

    def health_snapshot(self):
        return None


class _TelemNhShim:
    def __init__(self, eng, cids):
        self.quorum_coordinator = _TelemQcShim(eng)
        self._nodes = {c: _TelemNodeShim() for c in cids}
        self.tick_count = 0
        self.hostplane = None
        self.hostproc = None

    def _get_nodes(self):
        return None, self._nodes


def _telem_engine(groups, last_index=16, telem=True, topk=None):
    from dragonboat_tpu.ops.engine import BatchedQuorumEngine

    eng = BatchedQuorumEngine(groups, 3, event_cap=4 * groups)
    if telem:
        eng.enable_telem(topk=topk)
    for cid in range(1, groups + 1):
        eng.add_group(cid, node_ids=[1, 2, 3], self_id=1)
        eng.set_leader(cid, term=1, term_start=1, last_index=last_index)
    eng._upload_dirty()
    return eng


def run_telem_axis() -> dict:
    """Device telemetry axis (ISSUE 20): three pillars, engine-level so
    the rung-5-scale group counts fit the driver budget on cpu.

    1. **Sampler wall flat in G**: aggregate-mode sampler passes over a
       small and a 64×-larger device-backed engine — the walk set is
       top-K + open events, not the group axis, so the per-pass wall
       must grow ≤2× across the 64× group growth (the O(1)-in-G
       acceptance gate).  Full-walk wall at both sizes is captured for
       contrast (that one DOES scale with G).
    2. **Fold dispatch overhead**: telem-on vs telem-off dispatch wall
       on twin engines fed the same ack schedule, interleaved windows
       scored as mean pair-wise delta ± SEM (the trace-axis
       discipline) — <5% + 2·SEM asserted.
    3. **Top-K hit rate**: planted worst-lag groups must surface in the
       on-device top-K with their exact lags, fresh engine per trial.

    Env knobs: TELEM_AXIS_GROUPS (1024), TELEM_AXIS_SCALE (64),
    TELEM_AXIS_PASSES (50), TELEM_AXIS_PAIRS (4),
    TELEM_AXIS_DISPATCHES (30/window), TELEM_AXIS_TRIALS (4).
    """
    from dragonboat_tpu.events import MetricsRegistry
    from dragonboat_tpu.obs.health import HealthSampler

    g_small = int(os.environ.get("TELEM_AXIS_GROUPS", "1024"))
    scale = int(os.environ.get("TELEM_AXIS_SCALE", "64"))
    passes = int(os.environ.get("TELEM_AXIS_PASSES", "50"))
    pairs = max(2, int(os.environ.get("TELEM_AXIS_PAIRS", "4")) // 2 * 2)
    disp_per_win = int(os.environ.get("TELEM_AXIS_DISPATCHES", "30"))
    trials = int(os.environ.get("TELEM_AXIS_TRIALS", "4"))
    g_big = g_small * scale
    out: dict = {"groups_small": g_small, "groups_big": g_big,
                 "scale": scale}

    # -- pillar 1: sampler wall per pass, aggregate vs full walk -------
    def sampler_wall(groups, aggregate, n_passes):
        eng = _telem_engine(groups)
        # one real fold so the aggregate path has a snapshot to ride
        for cid in range(1, min(groups, 64) + 1):
            eng.ack(cid, 2, 1 + cid % 8)
        eng.step(do_tick=False)
        cids = list(range(1, groups + 1))
        hs = HealthSampler(
            _TelemNhShim(eng, cids), registry=MetricsRegistry(),
            aggregate=aggregate,
        )
        s = hs.sample()  # warm pass (drill-set cache, allocation)
        walls = []
        for _ in range(n_passes):
            s = hs.sample()
            walls.append(s["wall_ms"])
        walls.sort()
        return walls[len(walls) // 2], len(s.get("groups") or {})

    agg_small, walk_small = sampler_wall(g_small, True, passes)
    agg_big, walk_big = sampler_wall(g_big, True, passes)
    # the full-walk contrast pays O(G) per pass — a handful suffices
    full_small, _ = sampler_wall(g_small, False, max(3, passes // 10))
    full_big, _ = sampler_wall(g_big, False, max(3, passes // 10))
    # floor the denominator: a sub-10µs pass is measurement noise and
    # would flunk the ratio on jitter alone
    ratio = agg_big / max(agg_small, 0.01)
    out["sampler_wall_ms"] = {
        "aggregate_small": round(agg_small, 4),
        "aggregate_big": round(agg_big, 4),
        "full_small": round(full_small, 4),
        "full_big": round(full_big, 4),
        "aggregate_walk_small": walk_small,
        "aggregate_walk_big": walk_big,
        "aggregate_big_over_small": round(ratio, 2),
        "full_big_over_small": round(full_big / max(full_small, 0.01), 2),
    }
    out["sampler_flat_ok"] = ratio <= 2.0
    assert ratio <= 2.0, (
        f"aggregate sampler wall not flat in G: {agg_small:.3f}ms @ "
        f"{g_small} vs {agg_big:.3f}ms @ {g_big} ({ratio:.1f}x)"
    )

    # -- pillar 2: telem-fold dispatch overhead, paired A/B ------------
    # Gated on the FUSED MULTI-ROUND shape — the coordinator's deployed
    # dispatch (stage K rounds, one step_rounds scan) where the fold
    # runs ONCE on the block's final state, amortizing over the scanned
    # rounds exactly as it does in production.  The single-round shape
    # (fold per dispatch, the worst case) is measured too but recorded
    # informationally: on the cpu backend its ~2.7ms wall is host-
    # staging-dominated and the window weather (±15%, occasional 10×
    # outliers) swamps the fold's ~0.07ms program delta.
    rounds_per_block = int(os.environ.get("TELEM_AXIS_ROUNDS", "8"))
    eng_on = _telem_engine(g_small)
    eng_off = _telem_engine(g_small, telem=False)

    def window_multi(eng, seed):
        rng = random.Random(seed)
        t0 = time.perf_counter()
        for _ in range(disp_per_win):
            for _ in range(rounds_per_block):
                for _ in range(32):
                    eng.ack(rng.randrange(1, g_small + 1), 2,
                            rng.randrange(1, 17))
                eng.begin_round()
            eng.step_rounds(do_tick=False)
        return (disp_per_win * rounds_per_block) / (
            time.perf_counter() - t0
        )

    def window_single(eng, seed):
        rng = random.Random(seed)
        t0 = time.perf_counter()
        for _ in range(disp_per_win):
            for _ in range(32):
                eng.ack(rng.randrange(1, g_small + 1), 2,
                        rng.randrange(1, 17))
            eng.step(do_tick=False)
        return disp_per_win / (time.perf_counter() - t0)

    def paired_delta(win_fn, n_pairs, seed0):
        deltas = []
        for pair in range(n_pairs):
            seed = seed0 + pair
            if pair % 2 == 0:  # ABBA cancels slow box drift
                on = win_fn(eng_on, seed)
                off = win_fn(eng_off, seed)
            else:
                off = win_fn(eng_off, seed)
                on = win_fn(eng_on, seed)
            deltas.append((off - on) / off * 100.0)
        mean = sum(deltas) / len(deltas)
        var = sum((d - mean) ** 2 for d in deltas) / max(
            1, len(deltas) - 1
        )
        sem = (var / len(deltas)) ** 0.5
        return mean, sem, deltas

    window_multi(eng_on, 0)   # compile all variants before scoring
    window_multi(eng_off, 0)
    window_single(eng_on, 0)
    window_single(eng_off, 0)
    mean, sem, deltas = paired_delta(window_multi, pairs, 100)
    s_mean, s_sem, _ = paired_delta(window_single, max(2, pairs // 2), 500)
    out["rounds_per_block"] = rounds_per_block
    out["dispatch_overhead_pct"] = round(mean, 2)
    out["dispatch_overhead_sem_pct"] = round(sem, 2)
    out["pair_deltas_pct"] = [round(d, 2) for d in deltas]
    out["single_round_overhead_pct"] = round(s_mean, 2)
    out["single_round_overhead_sem_pct"] = round(s_sem, 2)
    out["dispatch_overhead_ok"] = mean < 5.0 + 2 * sem
    assert mean < 5.0 + 2 * sem, (
        f"telem fold dispatch overhead too high: {mean:.2f}% "
        f"(± {sem:.2f} SEM)"
    )

    # -- pillar 3: top-K hit rate on planted worst lags ----------------
    k = 8
    hits = total = 0
    for trial in range(trials):
        rng = random.Random(7000 + trial)
        g = 512
        eng = _telem_engine(g, last_index=8, topk=k)
        planted = rng.sample(range(1, g + 1), k)
        for cid in range(1, g + 1):
            if cid not in planted:
                eng.ack(cid, 2, 8)  # lag 0
        for i, cid in enumerate(planted):
            eng.ack(cid, 2, i % 4)  # lag 8 - i%4: the worst in the shard
        eng.step(do_tick=False)
        top = {c for c, _lag in eng.telem_snapshot()["topk"]}
        hits += len(top & set(planted))
        total += k
    hit_rate = hits / total
    out["topk_trials"] = trials
    out["topk_hit_rate"] = round(hit_rate, 4)
    out["topk_ok"] = hit_rate == 1.0
    assert hit_rate == 1.0, f"planted worst groups missed top-K: {hit_rate}"
    return out


# ======================================================================
# device capacity & profiling axis (ISSUE 15): profile-on/off overhead
# + capacity-model-vs-measured error + the warm-set program registry
# ======================================================================


def _set_devprof(nhs, on: bool) -> None:
    """Attach/detach the device profiling plane across a LIVE tpu-engine
    cluster (the ``_set_health``/``_set_tracing`` discipline): every
    engine dispatch site gates on a plain ``_devprof is not None``
    check, so the detached half of the A/B runs the profile-off path on
    the very same cluster."""
    for nh in nhs:
        if on:
            # the coordinator helper is THE wiring point (binds the
            # engine, records coordinator.devprof, hands the plane the
            # coordinator for devsm snapshots) — hand-rolled binds here
            # would silently fork from it
            nh.quorum_coordinator.enable_devprof(nh._devprof_axis)
        else:
            nh.quorum_coordinator.eng.disable_devprof()


def run_devprof_axis() -> dict:
    """Device capacity & profiling axis (ISSUE 15): profile-on vs
    profile-off throughput on a live 3-host TPU-ENGINE cluster —
    interleaved windows on one cluster, scored as the MEAN pair-wise
    delta ± SEM over alternating-order pairs (the r13 health-axis
    discipline: single-window weather on a 1-vCPU box is ±15%, pairing
    + alternation cancels it) — <5% + 2·SEM asserted.  Then the
    capacity phase: every host's HBM ledger is diffed against the
    capacity model (|error| < 10% asserted — the model is the sizing
    input for ROADMAP items 2/3), and the warm-set program registry is
    collected on one host with non-zero cost/memory analysis asserted
    per program (the perf ledger's "Device programs" table).

    Env knobs: DEVPROF_AXIS_GROUPS (8), DEVPROF_AXIS_DURATION
    (4s/window), DEVPROF_AXIS_PAIRS (4), DEVPROF_AXIS_SAMPLE (8),
    DEVPROF_AXIS_THREADS (4).
    """
    from dragonboat_tpu.obs.devprof import DevProf

    groups = int(os.environ.get("DEVPROF_AXIS_GROUPS", "8"))
    duration = float(os.environ.get("DEVPROF_AXIS_DURATION", "4"))
    pairs = max(2, int(os.environ.get("DEVPROF_AXIS_PAIRS", "4")) // 2 * 2)
    sample_every = int(os.environ.get("DEVPROF_AXIS_SAMPLE", "8"))
    window = int(os.environ.get("DEVPROF_AXIS_WINDOW", "8"))
    threads = int(os.environ.get("DEVPROF_AXIS_THREADS", "4"))
    payload = _payload()
    tmp = tempfile.mkdtemp(prefix="dbtpu-devprof-")
    dirs = [os.path.join(tmp, f"nh{i}") for i in range(3)]
    nhs = _mk_nodehosts(3, groups, 30, "tpu", dirs)
    out = {
        "groups": groups,
        "window_duration_s": duration,
        "pairs": pairs,
        "sample_every": sample_every,
    }
    try:
        cids = _start_groups(nhs, groups)
        leaders = _campaign_and_wait(nhs, cids, 180.0)
        for nh in nhs:
            # one DevProf per host, constructed once and A/B-toggled;
            # the registry is the host's own so the exposition carries
            # the families during the on-windows
            nh._devprof_axis = DevProf(
                registry=nh.metrics_registry,
                recorder=nh.flight_recorder,
                sample_every=sample_every,
            )

        def measure(on):
            _set_devprof(nhs, on)
            m = _measure(
                leaders, cids, payload, window,
                time.time() + duration, threads, drain_budget=15.0,
            )
            return m["writes_per_sec"]

        measure(False)  # warmup window
        deltas = []
        wps_on = wps_off = 0.0
        for pair in range(pairs):
            if pair % 2 == 0:
                on = measure(True)
                off = measure(False)
            else:
                off = measure(False)
                on = measure(True)
            wps_on = max(wps_on, on)
            wps_off = max(wps_off, off)
            deltas.append((off - on) / off * 100.0)
        mean = sum(deltas) / len(deltas)
        var = sum((d - mean) ** 2 for d in deltas) / max(1, len(deltas) - 1)
        sem = (var / len(deltas)) ** 0.5
        overhead = round(mean, 2)
        out["writes_per_sec_devprof_on"] = round(wps_on, 1)
        out["writes_per_sec_devprof_off"] = round(wps_off, 1)
        out["devprof_overhead_pct"] = overhead
        out["devprof_overhead_sem_pct"] = round(sem, 2)
        out["pair_deltas_pct"] = [round(d, 2) for d in deltas]
        out["devprof_overhead_ok"] = overhead < 5.0 + 2 * sem
        assert overhead < 5.0 + 2 * sem, (
            f"devprof overhead too high: {overhead}% (± {sem:.1f} SEM; "
            f"{wps_on:.0f} vs {wps_off:.0f} w/s)"
        )

        # capacity phase (profile back ON so the ledger gauges are live)
        _set_devprof(nhs, True)
        errors = []
        for nh in nhs:
            led = nh._devprof_axis.hbm_ledger()
            cap = led["capacity"]
            errors.append(abs(cap["model_error_pct"]))
            assert abs(cap["model_error_pct"]) < 10.0, cap
        dp0 = nhs[0]._devprof_axis
        led0 = dp0.hbm_ledger()
        cap0 = led0["capacity"]
        # reference sizing at a 16 GiB HBM budget (no chip attached on
        # the capture box — the per-group figure is backend-exact, the
        # budget is the documented reference input)
        ref = dp0.capacity_model(budget_bytes=16 << 30)
        out["capacity"] = {
            "planes": led0["planes"],
            "state_bytes": led0["state_bytes"],
            "measured_state_bytes": cap0.get("measured_state_bytes"),
            "bytes_per_group": round(cap0["bytes_per_group"], 1),
            "bytes_per_group_with_dispatch": round(
                cap0["bytes_per_group_with_dispatch"], 1
            ),
            "dispatch_bytes": cap0["dispatch_bytes"],
            "model_error_pct": cap0["model_error_pct"],
            "model_error_max_abs_pct": round(max(errors), 4),
            "max_groups_at_16gib": ref["max_groups"],
            "capacity_model_ok": max(errors) < 10.0,
        }

        # program registry on host 0's engine: the whole warm set with
        # non-zero cost/memory analysis per program (compiles ride the
        # jit/persistent caches where warm)
        rows = dp0.collect_programs(include_kv=False)
        assert rows and all(
            r.get("flops", 0) > 0 and r.get("bytes_accessed", 0) > 0
            for r in rows
        ), rows
        out["programs"] = rows
        out["programs_ok"] = True

        # estimator evidence from the on-windows (plus this phase) —
        # counters summed AND the device-ms sample windows MERGED before
        # the percentiles, so the ledger row's percentiles describe the
        # same population as its sample counts (host-0-only percentiles
        # against cluster-wide counts would misattribute)
        est = dp0.estimator_stats()
        merged_ms = list(dp0._device_ms)
        for nh in nhs[1:]:
            e2 = nh._devprof_axis.estimator_stats()
            est["dispatches"] += e2["dispatches"]
            est["sampled"] += e2["sampled"]
            est["padded_rounds"] += e2["padded_rounds"]
            est["wasted_rounds"] += e2["wasted_rounds"]
            merged_ms.extend(nh._devprof_axis._device_ms)
        est["padding_waste_ratio"] = (
            round(est["wasted_rounds"] / est["padded_rounds"], 4)
            if est["padded_rounds"] else 0.0
        )
        if merged_ms:
            from dragonboat_tpu.obs.health import _pctile

            est["device_ms"] = {
                "n": len(merged_ms),
                "p50": round(_pctile(merged_ms, 50), 4),
                "p99": round(_pctile(merged_ms, 99), 4),
                "max": round(max(merged_ms), 4),
            }
        out["estimator"] = est
        out["fused_ready"] = all(
            nh.quorum_coordinator.eng.fused_ready for nh in nhs
        )
        return out
    finally:
        for nh in nhs:
            try:
                nh.stop()
            except Exception:
                pass
        shutil.rmtree(tmp, ignore_errors=True)


# ======================================================================
# cross-domain lease axis (ISSUE 10): leader-lease local reads vs the
# ReadIndex fallback across injected high-RTT domains
# ======================================================================


def _mk_xdom_hosts(rtt_ms, far_one_way_s, trace=0):
    from dragonboat_tpu import NodeHostConfig
    from dragonboat_tpu.config import ExpertConfig
    from dragonboat_tpu.monkey import set_latency
    from dragonboat_tpu.nodehost import NodeHost
    from dragonboat_tpu.transport import ChanRouter, ChanTransport
    from dragonboat_tpu.transport.latency import crossdomain

    router = ChanRouter()
    nhs = []
    for i in (1, 2, 3):
        nhs.append(
            NodeHost(
                NodeHostConfig(
                    node_host_dir=":memory:",
                    rtt_millisecond=rtt_ms,
                    raft_address=f"xd{i}:1",
                    raft_rpc_factory=lambda src, rh, ch: ChanTransport(
                        src, rh, ch, router=router
                    ),
                    trace_sample_every=trace,
                    expert=ExpertConfig(
                        quorum_engine="scalar", logdb_shards=2
                    ),
                )
            )
        )
    # host 1 is the near/leader domain; the QUORUM (hosts 2+3) sits one
    # far link away — every ReadIndex confirmation and every commit pays
    # the cross-domain RTT, while lease reads stay in the near domain
    set_latency(
        nhs, crossdomain(["xd1:1"], ["xd2:1", "xd3:1"], far_one_way_s)
    )
    return nhs


def _xdom_place_leaders(nhs, cids):
    """Deterministic placement: the NEAR host (rank 1) leads every
    group.  The first campaign can race the bootstrap config-change
    apply (campaign_skipped) or lose to a randomized timeout on a far
    host — retry, transferring back when a far host won."""
    deadline = time.time() + 120
    led = set()
    while len(led) < len(cids) and time.time() < deadline:
        for cid in cids:
            if cid in led:
                continue
            n1 = nhs[0].get_node(cid)
            if n1.is_leader():
                led.add(cid)
                continue
            lid, ok = n1.get_leader_id()
            if ok and lid != 1 and 1 <= lid <= 3:
                try:
                    nhs[lid - 1].request_leader_transfer(cid, 1)
                except Exception:
                    pass
            else:
                n1.request_campaign()
        time.sleep(0.2)
    assert len(led) == len(cids), (
        f"near-domain leaders: {len(led)}/{len(cids)}"
    )


def run_crossdomain() -> dict:
    """Cross-domain lease rung (ISSUE 10; ROADMAP item 4 seed): a 3-host
    group whose follower quorum lives one injected far link (default
    40ms RTT) from the leader, under a 9:1 mixed read/write load.

    Two variants on identical topology: ``read_lease=True`` (clock-bound
    leader lease, reads served locally — dragonboat_tpu/lease.py) vs
    ``read_lease=False`` (every read pays the heartbeat-echo round across
    the far link).  Asserted: the lease variant's read p99 is single-digit
    milliseconds (vs the r07 device mixed-phase read-dispatch p99 of
    1.08s, and vs this rung's own ReadIndex fallback at ≥ the domain
    RTT), with a ≥90% lease hit ratio and write throughput unchanged
    within the box's noise band.

    Env knobs: E2E_XDOM_GROUPS (8), E2E_XDOM_DURATION (8s),
    E2E_XDOM_RTT_MS (20 tick), E2E_XDOM_FAR_MS (20 one-way),
    E2E_XDOM_THREADS (4), E2E_XDOM_ASSERT_MS (10).
    """
    groups = int(os.environ.get("E2E_XDOM_GROUPS", "8"))
    duration = float(os.environ.get("E2E_XDOM_DURATION", "8"))
    rtt_ms = int(os.environ.get("E2E_XDOM_RTT_MS", "20"))
    far_ms = float(os.environ.get("E2E_XDOM_FAR_MS", "20"))
    threads = int(os.environ.get("E2E_XDOM_THREADS", "4"))
    assert_ms = float(os.environ.get("E2E_XDOM_ASSERT_MS", "10"))
    payload = _payload()
    from dragonboat_tpu import Config

    out = {
        "groups": groups,
        "rtt_ms": rtt_ms,
        "far_one_way_ms": far_ms,
        "duration_s": duration,
        "topology": "leader near; 2-follower quorum one far link away",
        "variants": {},
    }
    for lease in (True, False):
        nhs = _mk_xdom_hosts(rtt_ms, far_ms / 1e3)
        try:
            addrs = {i: f"xd{i}:1" for i in (1, 2, 3)}
            cids = [BASE_CID + g for g in range(groups)]
            for cid in cids:
                for i, nh in enumerate(nhs, start=1):
                    nh.start_cluster(
                        addrs, False, CounterSM,
                        Config(
                            cluster_id=cid, node_id=i, election_rtt=10,
                            heartbeat_rtt=1, check_quorum=True,
                            read_lease=lease,
                        ),
                    )
            _xdom_place_leaders(nhs, cids)
            leaders = {cid: nhs[0] for cid in cids}
            # warm: one committed write per group (thesis §6.4 step 1 —
            # the lease serves only past a current-term commit) and a few
            # heartbeat round trips so quorum acks arm the lease
            for cid in cids:
                nhs[0].sync_propose(
                    nhs[0].get_noop_session(cid), payload, timeout=30.0
                )
            time.sleep(1.0)
            mixed = _measure_mixed(
                leaders, cids, payload, 9, time.time() + duration, threads
            )
            stats = None
            if lease:
                agg = {"reads_local": 0, "reads_fallback": 0, "grants": 0,
                       "expiries": 0}
                for cid in cids:
                    s = nhs[0].lease_status(cid) or {}
                    for k in agg:
                        agg[k] += s.get(k, 0)
                total = agg["reads_local"] + agg["reads_fallback"]
                agg["hit_ratio"] = (
                    round(agg["reads_local"] / total, 4) if total else None
                )
                stats = agg
            out["variants"]["lease_on" if lease else "lease_off"] = {
                **{k: v for k, v in mixed.items()},
                "lease": stats,
            }
        finally:
            for nh in nhs:
                try:
                    nh.stop()
                except Exception:
                    pass
    on = out["variants"]["lease_on"]
    off = out["variants"]["lease_off"]
    p99_on = (on.get("read_latency_ms") or {}).get("p99")
    p99_off = (off.get("read_latency_ms") or {}).get("p99")
    out["read_p99_ms_lease"] = p99_on
    out["read_p99_ms_fallback"] = p99_off
    out["read_p99_speedup"] = (
        round(p99_off / p99_on, 1) if p99_on and p99_off else None
    )
    wps_ratio = (
        on["ops_per_sec"] / off["ops_per_sec"] if off["ops_per_sec"] else None
    )
    out["ops_ratio_on_off"] = round(wps_ratio, 3) if wps_ratio else None
    # acceptance: lease reads are single-digit ms; the fallback pays at
    # least the far-domain RTT; throughput within the box's noise band
    hit = (on.get("lease") or {}).get("hit_ratio") or 0.0
    assert p99_on is not None and p99_on < assert_ms, (
        f"lease read p99 {p99_on}ms not single-digit (limit {assert_ms}ms)"
    )
    assert p99_off is not None and p99_off >= 2 * far_ms, (
        f"fallback read p99 {p99_off}ms below the {2 * far_ms}ms domain RTT "
        "— the injected topology is not being exercised"
    )
    assert hit >= 0.9, f"lease hit ratio {hit} < 0.9"
    assert wps_ratio is None or 0.5 <= wps_ratio <= 2.0, (
        f"mixed throughput moved {wps_ratio}x between lease on/off"
    )
    # commit attribution (ISSUE 14): READS got their cross-domain story
    # above; this phase prices what COMMITS still pay — per-peer quorum
    # attribution on the identical topology, trace on/off paired
    out["commit_attribution"] = _xdom_commit_attribution(
        groups, rtt_ms, far_ms, duration, threads, payload
    )
    out["assert_ok"] = True
    return out


def _xdom_commit_attribution(groups, rtt_ms, far_ms, duration, threads,
                             payload) -> dict:
    """Commit-attribution phase of the cross-domain rung (ISSUE 14
    tentpole): same 3-host topology (near leader, 2-follower quorum one
    far link away), pure-write load, the replication attribution plane
    (obs/replattr.py) decomposing every sampled commit's quorum close
    per peer.  Asserted: the far-domain peers are the ONLY laggards and
    closers (by latency class, not bare node id), the quorum close pays
    the far round trip, the closing path's stage share is wire-dominated
    (the number ROADMAP item 4's domain-local sub-quorum attacks), and
    the paired trace-on/off overhead stays under 5% + 2·SEM (the r10
    trace-axis pairing discipline) with the off half structurally
    detached down to the raft hooks.

    Env knobs: E2E_XDOM_TRACE_SAMPLE (1-in-4), E2E_XDOM_TRACE_PAIRS (4
    windows), E2E_XDOM_TRACE_WINDOW (duration/2 s).
    """
    from dragonboat_tpu import Config

    sample = int(os.environ.get("E2E_XDOM_TRACE_SAMPLE", "4"))
    pairs = max(2, int(os.environ.get("E2E_XDOM_TRACE_PAIRS", "4")) // 2 * 2)
    win = (
        float(os.environ.get("E2E_XDOM_TRACE_WINDOW", "0"))
        or max(2.0, duration / 2)
    )
    nhs = _mk_xdom_hosts(rtt_ms, far_ms / 1e3, trace=sample)
    try:
        for nh in nhs:
            # handles for the A/B detach/reattach (_set_tracing)
            nh._trace_axis_tracer = nh.tracer
            nh._trace_axis_replattr = nh.replattr
        addrs = {i: f"xd{i}:1" for i in (1, 2, 3)}
        cids = [BASE_CID + g for g in range(groups)]
        for cid in cids:
            for i, nh in enumerate(nhs, start=1):
                nh.start_cluster(
                    addrs, False, CounterSM,
                    Config(cluster_id=cid, node_id=i, election_rtt=10,
                           heartbeat_rtt=1, check_quorum=True),
                )
        _xdom_place_leaders(nhs, cids)
        leaders = {cid: nhs[0] for cid in cids}
        for cid in cids:
            nhs[0].sync_propose(
                nhs[0].get_noop_session(cid), payload, timeout=30.0
            )

        def measure(on):
            _set_tracing(nhs, on)
            if not on:
                # trace-off structural identity on the live cluster:
                # nothing below the latch may survive the detach
                n = nhs[0].get_node(cids[0])
                assert n.replattr is None
                assert n.peer.raft.replattr is None
            m = _measure_mixed(
                leaders, cids, payload, 0, time.time() + win, threads
            )
            return m["ops_per_sec"]

        measure(False)  # warmup window
        deltas = []
        wps_on = wps_off = 0.0
        for pair in range(pairs):
            if pair % 2 == 0:
                on = measure(True)
                off = measure(False)
            else:
                off = measure(False)
                on = measure(True)
            wps_on = max(wps_on, on)
            wps_off = max(wps_off, off)
            deltas.append((off - on) / off * 100.0)
        mean = sum(deltas) / len(deltas)
        var = sum((d - mean) ** 2 for d in deltas) / max(1, len(deltas) - 1)
        sem = (var / len(deltas)) ** 0.5
        overhead = round(mean, 2)
        # dedicated attribution window, then let straggler (laggard)
        # acks land so their RTTs make the table
        _set_tracing(nhs, True)
        _measure_mixed(leaders, cids, payload, 0, time.time() + win, threads)
        time.sleep(max(1.0, 4 * far_ms / 1e3))
        summ = nhs[0].replattr.summary()
        inj = nhs[0].transport.latency
        out = {
            "sample_every": sample,
            "window_s": win,
            "writes_per_sec_trace_on": round(wps_on, 1),
            "writes_per_sec_trace_off": round(wps_off, 1),
            "trace_overhead_pct": overhead,
            "trace_overhead_sem_pct": round(sem, 2),
            "pair_deltas_pct": [round(d, 2) for d in deltas],
            "trace_overhead_ok": overhead < 5.0 + 2 * sem,
            "summary": summ,
            "latency_domains": (
                inj.health_snapshot() if inj is not None else None
            ),
        }
        # every quorum member besides the leader is far-class: each
        # sampled commit must close on a far ack AND laggard the other
        # far peer — per-peer attribution by latency class
        peers = summ["peers"]
        assert peers and all(d["cls"] == "B" for d in peers.values()), (
            f"far quorum not labeled by latency class: {peers}"
        )
        laggard_total = sum(d["laggard"] for d in peers.values())
        closer_total = sum(d["closer"] for d in peers.values())
        assert closer_total > 0 and laggard_total > 0, (
            f"attribution empty: closers {closer_total}, "
            f"laggards {laggard_total} "
            f"({summ['commits_attributed']} commits)"
        )
        # the quorum close pays the far round trip (lower bounds NOT
        # load-scaled; pipelined sends coalesce onto shared far round
        # trips, so p50 can undershoot the full RTT a little — p99 sees
        # the uncoalesced close)
        assert summ["close_ms"]["p99"] >= 2 * far_ms * 0.9, (
            f"close p99 {summ['close_ms']} below the {2 * far_ms}ms "
            "domain RTT — attribution is not seeing the far quorum"
        )
        assert summ["close_ms"]["p50"] >= far_ms, (
            f"close p50 {summ['close_ms']} below the {far_ms}ms far "
            "one-way leg"
        )
        shares = summ["close_stage_share_pct"]
        wire = shares.get("wire_out", 0.0) + shares.get("wire_back", 0.0)
        out["wire_share_pct"] = round(wire, 1)
        assert wire >= 50.0, (
            f"closing path not wire-dominated: {shares}"
        )
        assert overhead < 5.0 + 2 * sem, (
            f"repl-trace overhead too high: {overhead}% "
            f"(± {sem:.1f} SEM; {wps_on:.0f} vs {wps_off:.0f} w/s)"
        )
        out["attribution_ok"] = True
        return out
    finally:
        for nh in nhs:
            try:
                nh.stop()
            except Exception:
                pass


# ======================================================================
# hierarchical commit rung (hier, ISSUE 18)
# ======================================================================


def _mk_hier_hosts(rtt_ms, far_one_way_s, trace=0):
    """Four hosts in a 2+2 domain split: hd1+hd2 near (domain A), hd3+hd4
    one far link away (domain B).  With n=4 voters the classic quorum is
    3, so every classic commit must wait on a far ack — the topology the
    domain-local sub-quorum (raft/hier.py) is built to beat."""
    from dragonboat_tpu import NodeHostConfig
    from dragonboat_tpu.config import ExpertConfig
    from dragonboat_tpu.monkey import set_latency
    from dragonboat_tpu.nodehost import NodeHost
    from dragonboat_tpu.transport import ChanRouter, ChanTransport
    from dragonboat_tpu.transport.latency import crossdomain

    router = ChanRouter()
    nhs = []
    for i in (1, 2, 3, 4):
        nhs.append(
            NodeHost(
                NodeHostConfig(
                    node_host_dir=":memory:",
                    rtt_millisecond=rtt_ms,
                    raft_address=f"hd{i}:1",
                    raft_rpc_factory=lambda src, rh, ch: ChanTransport(
                        src, rh, ch, router=router
                    ),
                    trace_sample_every=trace,
                    expert=ExpertConfig(
                        quorum_engine="scalar", logdb_shards=2
                    ),
                )
            )
        )
    set_latency(
        nhs,
        crossdomain(
            ["hd1:1", "hd2:1"], ["hd3:1", "hd4:1"], far_one_way_s
        ),
    )
    return nhs


def _hier_place_leaders(nhs, cids):
    """_xdom_place_leaders for the 4-host topology: host 1 (near domain)
    leads every group."""
    deadline = time.time() + 120
    led = set()
    while len(led) < len(cids) and time.time() < deadline:
        for cid in cids:
            if cid in led:
                continue
            n1 = nhs[0].get_node(cid)
            if n1.is_leader():
                led.add(cid)
                continue
            lid, ok = n1.get_leader_id()
            if ok and lid != 1 and 1 <= lid <= len(nhs):
                try:
                    nhs[lid - 1].request_leader_transfer(cid, 1)
                except Exception:
                    pass
            else:
                n1.request_campaign()
        time.sleep(0.2)
    assert len(led) == len(cids), (
        f"near-domain leaders: {len(led)}/{len(cids)}"
    )


def _closer_by_class(summ) -> dict:
    """Collapse the per-peer attribution table to closer counts per
    latency class — the number the hier rung's flip assertion reads."""
    agg: dict = {}
    for d in summ["peers"].values():
        agg[d["cls"]] = agg.get(d["cls"], 0) + d["closer"]
    return agg


def _hier_far_read_phase(nhs, cids, threads=4, reads_per_thread=25) -> dict:
    """Far-domain read path (ISSUE 18 tentpole, part 4): concurrent
    linearizable reads issued FROM a far-domain host (hd3) while the
    leader sits in the near domain.  Without batching each read pays its
    own cross-domain leader round trip; the FarReadBatcher coalesces
    mid-flight arrivals onto the in-flight confirmation."""
    far = nhs[2]  # hd3, domain B
    cid = cids[0]
    errors = [0]

    def worker():
        for _ in range(reads_per_thread):
            try:
                far.sync_read(cid, None, timeout=30.0)
            except Exception:
                errors[0] += 1

    ts = [threading.Thread(target=worker) for _ in range(threads)]
    t0 = time.perf_counter()
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    elapsed = time.perf_counter() - t0
    fr = far.get_node(cid).peer.raft.far_reads
    total = threads * reads_per_thread
    return {
        "reads": total,
        "errors": errors[0],
        "elapsed_s": round(elapsed, 3),
        "reads_per_sec": round(total / elapsed, 1) if elapsed else None,
        "leader_round_trips": fr.batches,
        "reads_coalesced": fr.coalesced,
        "coalesce_ratio": (
            round(fr.coalesced / total, 3) if total else None
        ),
    }


def run_hier() -> dict:
    """Hierarchical commit rung (ISSUE 18 tentpole): a 4-host group in a
    2+2 domain split (near leader + one near follower; two followers one
    far link away).  n=4 voters makes the classic quorum 3, so WITHOUT
    hier every commit close pays the far round trip; WITH
    ``hier_commit=True`` the near-domain sub-quorum (2 of {hd1,hd2})
    closes at the near RTT and the far acks catch up asynchronously.

    Two variants on identical topology and identical pure-write load,
    both with replication attribution sampling on (the trace overhead
    cancels in the A/B).  Asserted: the closer table flips far→near (off:
    every sampled close is a far-class ack; on: near-class closers
    dominate), commit close p99 drops from ≥ the far RTT to ≤ 0.5× the
    far RTT, write throughput does not regress beyond noise, the
    sub-quorum counters confirm the near rule (not a lucky topology) did
    the closing, and the far-domain read phase coalesces concurrent
    follower reads onto shared leader round trips.

    Env knobs: E2E_HIER_GROUPS (8), E2E_HIER_DURATION (8s),
    E2E_HIER_RTT_MS (20 tick), E2E_HIER_FAR_MS (20 one-way),
    E2E_HIER_THREADS (4), E2E_HIER_TRACE_SAMPLE (1-in-4).
    """
    groups = int(os.environ.get("E2E_HIER_GROUPS", "8"))
    duration = float(os.environ.get("E2E_HIER_DURATION", "8"))
    rtt_ms = int(os.environ.get("E2E_HIER_RTT_MS", "20"))
    far_ms = float(os.environ.get("E2E_HIER_FAR_MS", "20"))
    threads = int(os.environ.get("E2E_HIER_THREADS", "4"))
    sample = int(os.environ.get("E2E_HIER_TRACE_SAMPLE", "4"))
    payload = _payload()
    from dragonboat_tpu import Config

    doms = {1: "A", 2: "A", 3: "B", 4: "B"}
    far_rtt_ms = 2 * far_ms
    out = {
        "groups": groups,
        "rtt_ms": rtt_ms,
        "far_one_way_ms": far_ms,
        "duration_s": duration,
        "sample_every": sample,
        "domains": {str(k): v for k, v in doms.items()},
        "topology": (
            "2+2 split: leader + 1 near follower; 2-follower far "
            "domain; classic quorum (3/4) must cross the far link"
        ),
        "variants": {},
    }
    for hier in (False, True):
        nhs = _mk_hier_hosts(rtt_ms, far_ms / 1e3, trace=sample)
        try:
            addrs = {i: f"hd{i}:1" for i in (1, 2, 3, 4)}
            cids = [BASE_CID + g for g in range(groups)]
            for cid in cids:
                for i, nh in enumerate(nhs, start=1):
                    nh.start_cluster(
                        addrs, False, CounterSM,
                        Config(
                            cluster_id=cid, node_id=i, election_rtt=10,
                            heartbeat_rtt=1, check_quorum=True,
                            hier_commit=hier,
                            hier_domains=dict(doms) if hier else {},
                        ),
                    )
            _hier_place_leaders(nhs, cids)
            leaders = {cid: nhs[0] for cid in cids}
            for cid in cids:
                nhs[0].sync_propose(
                    nhs[0].get_noop_session(cid), payload, timeout=30.0
                )
            time.sleep(0.5)
            mixed = _measure_mixed(
                leaders, cids, payload, 0, time.time() + duration, threads
            )
            # let straggler far acks land so their RTTs make the table
            time.sleep(max(1.0, 4 * far_ms / 1e3))
            summ = nhs[0].replattr.summary()
            hsnap = None
            far_read = None
            if hier:
                hsnap = {
                    "subquorum_closes": 0, "fallback_closes": 0,
                    "election_holds": 0,
                }
                for cid in cids:
                    s = nhs[0].get_node(cid).peer.raft.hier.snapshot()
                    for k in hsnap:
                        hsnap[k] += s[k]
                far_read = _hier_far_read_phase(nhs, cids)
            out["variants"]["hier_on" if hier else "hier_off"] = {
                **{k: v for k, v in mixed.items()},
                "close_ms": summ["close_ms"],
                "closer_by_class": _closer_by_class(summ),
                "peers": summ["peers"],
                "commits_attributed": summ["commits_attributed"],
                "hier": hsnap,
                "far_read": far_read,
            }
        finally:
            for nh in nhs:
                try:
                    nh.stop()
                except Exception:
                    pass
    on = out["variants"]["hier_on"]
    off = out["variants"]["hier_off"]
    p99_on = on["close_ms"]["p99"]
    p99_off = off["close_ms"]["p99"]
    out["close_p99_ms_hier"] = p99_on
    out["close_p99_ms_classic"] = p99_off
    out["close_p99_speedup"] = (
        round(p99_off / p99_on, 1) if p99_on and p99_off else None
    )
    wps_ratio = (
        on["ops_per_sec"] / off["ops_per_sec"] if off["ops_per_sec"] else None
    )
    out["ops_ratio_on_off"] = round(wps_ratio, 3) if wps_ratio else None
    # acceptance (ISSUE 18): the closer table flips far→near ...
    cls_off = off["closer_by_class"]
    cls_on = on["closer_by_class"]
    assert cls_off.get("B", 0) > 0 and cls_off.get("A", 0) == 0, (
        f"classic closers not all far-class: {cls_off} — the 2+2 "
        "topology is not forcing the far ack"
    )
    assert cls_on.get("A", 0) > cls_on.get("B", 0), (
        f"hier closers did not flip to the near class: {cls_on}"
    )
    # ... commit close p99 drops below half the far RTT (vs >= it off) ...
    assert p99_off is not None and p99_off >= far_rtt_ms * 0.9, (
        f"classic close p99 {p99_off}ms below the {far_rtt_ms}ms far "
        "RTT — the injected topology is not being exercised"
    )
    assert p99_on is not None and p99_on <= 0.5 * far_rtt_ms, (
        f"hier close p99 {p99_on}ms not under half the {far_rtt_ms}ms "
        "far RTT"
    )
    # ... the sub-quorum did the closing ...
    assert on["hier"]["subquorum_closes"] > 0, (
        f"no sub-quorum closes recorded: {on['hier']}"
    )
    # ... throughput within noise (the sub-quorum path should only help:
    # sync_propose unblocks at the near close) ...
    assert wps_ratio is None or wps_ratio >= 0.8, (
        f"hier-on write throughput regressed {wps_ratio}x"
    )
    # ... and far-domain reads coalesce onto shared leader round trips
    fr = on["far_read"]
    assert fr["errors"] == 0, f"far-domain reads failed: {fr}"
    assert fr["reads_coalesced"] > 0, (
        f"far reads never coalesced: {fr}"
    )
    assert fr["leader_round_trips"] < fr["reads"], (
        f"every far read paid its own leader round trip: {fr}"
    )
    out["assert_ok"] = True
    return out


# ======================================================================
# device state machine rung (devsm, ISSUE 11)
# ======================================================================


def _devsm_mixed_worker(nh, cids, read_ratio, stop_at, out):
    """9:1 mixed KV load through the sync APIs: writes are fixed-width
    devsm SET ops, reads are linearizable key lookups with the value
    CHECKED against the last committed write per key (a stale device
    read fails the rung, not just slows it)."""
    from dragonboat_tpu.devsm import encode_op

    reads = writes = errors = 0
    lat_r, lat_w = [], []
    stale = None
    last = {}  # (cid, key) -> last written value
    sessions = {cid: nh.get_noop_session(cid) for cid in cids}
    i = 0
    while time.time() < stop_at and stale is None:
        cid = cids[i % len(cids)]
        key = (i // len(cids)) % 8
        i += 1
        is_read = (i % (read_ratio + 1)) != 0
        t0 = time.perf_counter()
        try:
            if is_read:
                v = nh.sync_read(cid, key, timeout=10.0)
                lat_r.append(time.perf_counter() - t0)
                reads += 1
                expect = last.get((cid, key))
                if expect is not None and v != expect:
                    # recorded, not raised: an exception on this bare
                    # thread would die silently and the rung would
                    # report assert_ok over a linearizability violation
                    stale = f"stale devsm read {cid}/{key}: {v} != {expect}"
            else:
                val = i & 0x7FFFFFFF
                nh.sync_propose(
                    sessions[cid], encode_op(key, val), timeout=10.0
                )
                lat_w.append(time.perf_counter() - t0)
                writes += 1
                last[(cid, key)] = val
        except Exception:
            errors += 1
    out.append((reads, writes, errors, lat_r, lat_w, stale))


def run_devsm() -> dict:
    """Device SM rung (ISSUE 11): a 3-host tpu-engine cluster under a
    9:1 mixed KV load, ``Config.device_kv`` on vs off on identical
    topology (same DeviceKVStateMachine class both ways — the off
    variant IS the host-apply oracle).  Leaders concentrate on host 1 so
    every client read hits the leader host, where the devsm variant
    serves straight from device state (zero host apply on the read
    path).  Reported per variant: mixed ops/s, read/write latency
    percentiles, and the sampled per-stage trace attribution — the
    acceptance signal is the READ path's ``apply`` share collapsing on
    the devsm variant (reads release at the device commit watermark, the
    fold having run inside that very dispatch).

    Env knobs: E2E_DEVSM_GROUPS (4), E2E_DEVSM_DURATION (8s),
    E2E_DEVSM_RTT_MS (20), E2E_DEVSM_THREADS (2),
    E2E_DEVSM_WARM_TIMEOUT (240s).
    """
    from dragonboat_tpu import Config, NodeHostConfig
    from dragonboat_tpu.config import ExpertConfig
    from dragonboat_tpu.devsm import DeviceKVStateMachine
    from dragonboat_tpu.nodehost import NodeHost
    from dragonboat_tpu.obs.trace import compute_stage_stats
    from dragonboat_tpu.transport import ChanRouter, ChanTransport

    groups = int(os.environ.get("E2E_DEVSM_GROUPS", "4"))
    duration = float(os.environ.get("E2E_DEVSM_DURATION", "8"))
    rtt_ms = int(os.environ.get("E2E_DEVSM_RTT_MS", "20"))
    threads = int(os.environ.get("E2E_DEVSM_THREADS", "2"))
    warm_timeout = float(os.environ.get("E2E_DEVSM_WARM_TIMEOUT", "240"))
    out = {
        "groups": groups,
        "duration_s": duration,
        "rtt_ms": rtt_ms,
        "read_ratio": 9,
        "variants": {},
    }
    for devsm in (True, False):
        router = ChanRouter()
        addrs = {i: f"dsm{i}:1" for i in (1, 2, 3)}
        nhs = [
            NodeHost(
                NodeHostConfig(
                    node_host_dir=":memory:",
                    rtt_millisecond=rtt_ms,
                    raft_address=addrs[i],
                    raft_rpc_factory=lambda src, rh, ch: ChanTransport(
                        src, rh, ch, router=router
                    ),
                    trace_sample_every=2,
                    expert=ExpertConfig(
                        quorum_engine="tpu",
                        engine_block_groups=max(groups, 64),
                    ),
                )
            )
            for i in (1, 2, 3)
        ]
        try:
            cids = [BASE_CID + g for g in range(groups)]
            for cid in cids:
                for i, nh in enumerate(nhs, start=1):
                    nh.start_cluster(
                        addrs, False, DeviceKVStateMachine,
                        Config(
                            cluster_id=cid, node_id=i, election_rtt=10,
                            heartbeat_rtt=1, device_kv=devsm,
                        ),
                    )
            if devsm:
                # first-use XLA compiles of the has_kv programs must not
                # stall the round thread mid-measurement (warmup_devsm is
                # kicked at registration; wait it out)
                deadline = time.time() + warm_timeout
                while time.time() < deadline:
                    if all(
                        nh.quorum_coordinator.eng.kv_fused_ready
                        for nh in nhs
                    ):
                        break
                    time.sleep(0.25)
            # concentrate leaders on host 1 (the crossdomain placement
            # dance): device-served reads require the client to read on
            # the leader host
            deadline = time.time() + 120
            led = set()
            while len(led) < len(cids) and time.time() < deadline:
                for cid in cids:
                    if cid in led:
                        continue
                    n1 = nhs[0].get_node(cid)
                    if n1.is_leader():
                        led.add(cid)
                        continue
                    lid, ok = n1.get_leader_id()
                    if ok and lid != 1 and 1 <= lid <= 3:
                        try:
                            nhs[lid - 1].request_leader_transfer(cid, 1)
                        except Exception:
                            pass
                    else:
                        n1.request_campaign()
                time.sleep(0.2)
            assert len(led) == len(cids), (
                f"host-1 leaders: {len(led)}/{len(cids)}"
            )
            if devsm:
                plane = nhs[0].quorum_coordinator.devsm
                deadline = time.time() + 60
                while time.time() < deadline and not all(
                    plane.bound(cid) for cid in cids
                ):
                    time.sleep(0.1)
            time.sleep(0.5)  # settle startup config-change resyncs
            stop_at = time.time() + duration
            outs = []
            slices = [cids[i::threads] for i in range(threads)]
            ts = [
                threading.Thread(
                    target=_devsm_mixed_worker,
                    args=(nhs[0], s, 9, stop_at, outs),
                )
                for s in slices
                if s
            ]
            t_begin = time.time()
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            wall = max(time.time() - t_begin, 1e-3)
            # every worker must have reported, and none may have seen a
            # stale read (the worker records instead of raising — a
            # thread death would silently shrink the stats)
            assert len(outs) == len([s for s in slices if s]), (
                f"devsm worker died: {len(outs)} reports"
            )
            stales = [s for *_rest, s in outs if s]
            assert not stales, stales[0]
            reads = sum(r for r, _, _, _, _, _ in outs)
            writes = sum(w for _, w, _, _, _, _ in outs)
            errors = sum(e for _, _, e, _, _, _ in outs)
            lat_r = [l for _, _, _, ls, _, _ in outs for l in ls]
            lat_w = [l for _, _, _, _, ls, _ in outs for l in ls]
            attribution = compute_stage_stats(
                t for nh in nhs if nh.tracer is not None
                for t in nh.tracer.traces()
            )
            variant = {
                "ops_per_sec": round((reads + writes) / wall, 1),
                "reads": reads,
                "writes": writes,
                "errors": errors,
                "read_latency_ms": _percentiles(lat_r),
                "write_latency_ms": _percentiles(lat_w),
                "attribution": attribution,
            }
            if devsm:
                plane = nhs[0].quorum_coordinator.devsm
                served = plane.reads_served
                fb = plane.read_fallbacks
                variant["devsm"] = {
                    "reads_served": served,
                    "read_fallbacks": fb,
                    "ops_staged": plane.ops_staged,
                    "binds": plane.binds,
                    "served_ratio": (
                        round(served / (served + fb), 4)
                        if served + fb else None
                    ),
                }
            out["variants"]["devsm_on" if devsm else "devsm_off"] = variant
        finally:
            for nh in nhs:
                try:
                    nh.stop()
                except Exception:
                    pass
    on = out["variants"]["devsm_on"]
    off = out["variants"]["devsm_off"]

    def _apply_share(v):
        st = (v.get("attribution") or {}).get("stages") or {}
        return (st.get("apply") or {}).get("share_pct")

    out["apply_share_pct_devsm"] = _apply_share(on)
    out["apply_share_pct_host"] = _apply_share(off)
    out["read_p50_ms_devsm"] = (on.get("read_latency_ms") or {}).get("p50")
    out["read_p50_ms_host"] = (off.get("read_latency_ms") or {}).get("p50")
    # acceptance: the device plane (not the shadow fallback) served the
    # read load, correctness held (the worker asserts read-your-writes
    # inline), and the apply share collapsed on the devsm path
    served_ratio = (on.get("devsm") or {}).get("served_ratio") or 0.0
    assert served_ratio >= 0.5, (
        f"device served only {served_ratio} of leader-host reads"
    )
    assert on["errors"] == 0 or on["errors"] < on["reads"] // 10
    a_on, a_off = out["apply_share_pct_devsm"], out["apply_share_pct_host"]
    if a_on is not None and a_off is not None and a_off > 1.0:
        assert a_on <= max(5.0, 0.5 * a_off), (
            f"devsm apply share {a_on}% did not collapse vs host {a_off}%"
        )
    out["assert_ok"] = True
    return out


# ======================================================================
# multiprocess mode: one process per NodeHost over framed TCP
# ======================================================================


def _rank_env_int(name, default):
    return int(os.environ.get(name, str(default)))


def rank_main() -> int:
    """Child body: one NodeHost + this rank's share of the load threads.

    Line protocol on stdio (parent drives):
      child → parent:  READY {...}   then   RESULT {...}
      parent → child:  RUN {"t0":…, "duration":…, "lat_t0":…,
                            "lat_duration":…, "lat_cids":[…]}
    """
    rank = _rank_env_int("E2E_RANK", 0)
    # GIL switch interval is tunable for experiments; the default (5ms)
    # measured best — shorter intervals add context-switch overhead
    # without improving the pipeline's wakeup latency
    si = os.environ.get("E2E_SWITCH_INTERVAL")
    if si:
        sys.setswitchinterval(float(si))
    if os.environ.get("DBTPU_CPROFILE_STEP_DIR"):
        os.environ["DBTPU_CPROFILE_STEP"] = os.path.join(
            os.environ["DBTPU_CPROFILE_STEP_DIR"], f"step_rank{rank}.prof"
        )
    procs = _rank_env_int("E2E_PROCS", 3)
    groups = _rank_env_int("E2E_GROUPS", 1024)
    rtt_ms = _rank_env_int("E2E_RTT_MS", 500)
    window = _rank_env_int("E2E_WINDOW", 16)
    threads = _rank_env_int("E2E_THREADS", 8)
    durable = os.environ.get("E2E_DURABLE", "1") == "1"
    engine = os.environ.get("E2E_ENGINE", "tpu")
    leader_mode = os.environ.get("E2E_LEADER_MODE", "spread")
    leader_timeout = float(os.environ.get("E2E_LEADER_TIMEOUT", "120"))
    ports = [int(p) for p in os.environ["E2E_PORTS"].split(",")]
    base_dir = os.environ.get("E2E_DIR", "")

    # engine per rank: the device engine lives where the leaders it serves
    # live; with one TPU chip only rank 0 attaches to it (leader_mode
    # "rank0" puts every leader there so ALL commit tallying runs through
    # the device).  Other ranks never import jax.  (An all-ranks-engined
    # spread variant was tried and thrashes elections: three device-ticked
    # replicas per group contend through three round pipelines.)
    my_engine = engine if (engine != "tpu" or rank == 0) else "scalar"
    if my_engine == "tpu":
        _force_cpu_for_engine()

    from dragonboat_tpu import Config, NodeHostConfig
    from dragonboat_tpu.config import ExpertConfig
    from dragonboat_tpu.nodehost import NodeHost

    t_setup = time.perf_counter()
    addr = f"127.0.0.1:{ports[rank]}"
    from dragonboat_tpu.config import LogDBConfig

    ldb = LogDBConfig()
    ldb.fsync = os.environ.get("E2E_FSYNC", "1") == "1"
    # native replication fast lane (fastlane.py): the steady-state data
    # plane of enrolled groups runs in C++ — the host-path answer to the
    # ~75us-of-Python-per-write bound documented in PERF.md.  On by
    # default in this benchmark's deployment shape (TCP + durable native
    # LogDB); E2E_FAST_LANE=0 measures the pure-Python path.
    fast_lane = durable and os.environ.get("E2E_FAST_LANE", "1") == "1"
    nh = NodeHost(
        NodeHostConfig(
            node_host_dir=(
                os.path.join(base_dir, f"nh{rank}") if durable else ":memory:"
            ),
            rtt_millisecond=rtt_ms,
            raft_address=addr,
            logdb_config=ldb,
            expert=ExpertConfig(
                quorum_engine=my_engine,
                engine_block_groups=max(groups, 64),
                logdb_shards=int(os.environ.get("E2E_SHARDS", "4")),
                fast_lane=fast_lane,
                # 4ms: the round-4 sweep (0.5/2/4/6/8ms at rung 3, native
                # SM) found the best throughput/latency balance here —
                # w=4 gave 17.3k w/s at p50 10ms / p99 60ms vs 15k at
                # p99 90-120ms for the old 2ms (PERF.md)
                fast_lane_commit_window_ms=float(
                    os.environ.get("E2E_COMMIT_WINDOW_MS", "4.0")
                ),
                # compartmentalized host plane A/B axis (ISSUE 8);
                # default off — the scalar path is the baseline
                host_compartments=os.environ.get("E2E_COMPARTMENTS", "0")
                == "1",
            ),
        )
    )
    addrs = {i + 1: f"127.0.0.1:{ports[i]}" for i in range(procs)}
    # E2E_SM=native: the C-ABI KV state machine (natsm.py) — enrolled
    # groups then apply committed entries natively with only batched
    # completion records crossing the GIL (PERF.md ~40us/write apply rim)
    sm_factory = CounterSM
    if os.environ.get("E2E_SM", "python") == "native":
        from dragonboat_tpu.native.natsm import NativeKVStateMachine

        sm_factory = NativeKVStateMachine
    cids = [BASE_CID + g for g in range(groups)]

    election_rtt = int(os.environ.get("E2E_ELECTION_RTT", "20"))

    def _start_one(cid):
        nh.start_cluster(
            addrs,
            False,
            sm_factory,
            Config(
                cluster_id=cid,
                node_id=rank + 1,
                election_rtt=election_rtt,
                heartbeat_rtt=1,
                snapshot_entries=0,
            ),
        )

    # start_cluster is thread-safe (the id is reserved under the NodeHost
    # lock); at 4k+ groups the serial loop is the setup bottleneck (round
    # 4: 223s for 12,288 replicas) — the cost is IO/lock waits, which a
    # small pool overlaps
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(
        max_workers=int(os.environ.get("E2E_START_THREADS", "4"))
    ) as ex:
        for _ in ex.map(_start_one, cids):
            pass

    def preferred(cid):
        return 0 if leader_mode == "rank0" else cid % procs

    mine = [cid for cid in cids if preferred(cid) == rank]
    started_s = time.perf_counter() - t_setup

    platform = ""
    if my_engine == "tpu":
        try:
            import jax

            platform = jax.devices()[0].platform
        except Exception:
            platform = "unknown"

    def emit(tag, obj):
        sys.stdout.write(tag + " " + json.dumps(obj) + "\n")
        sys.stdout.flush()

    def expect(tag):
        line = sys.stdin.readline()
        if not line.startswith(tag + " ") and line.strip() != tag:
            raise RuntimeError(f"expected {tag}, got {line!r}")
        rest = line[len(tag) :].strip()
        return json.loads(rest) if rest else None

    # barrier 1: every rank has started all replicas before anyone
    # campaigns — campaigning into a peer that hasn't started the group
    # yet just drops the vote request and burns a retry cycle
    emit("STARTED", {"rank": rank, "started_s": round(started_s, 1)})
    expect("CAMPAIGN")

    t_campaign = time.perf_counter()
    deadline = time.time() + leader_timeout
    # staggered initial campaigns (round-4 election storm: 3,049/4,096
    # elected in 300s when every group campaigned at once — simultaneous
    # campaigns collide on the wire and their vote responses starve behind
    # each other's Replicate/noop traffic).  Keep at most `wave` unresolved
    # campaigns in flight; each resolved election frees a slot.
    #
    # A campaign is RESOLVED when the group has any leader — not
    # necessarily this rank's replica: under storm pressure another
    # replica's own randomized timeout can win the election first, and
    # re-campaigning against that healthy leader just deposes it (a term
    # war that stalled the round-4 tail indefinitely).  Whoever leads,
    # drives: the final scan below picks up every locally-led group,
    # preferred or adopted.
    wave = int(os.environ.get("E2E_CAMPAIGN_WAVE", "384"))
    # Explicit campaigns are only a bootstrap accelerant; the tail is
    # raft's own job.  Two measured anti-patterns shaped this: (1)
    # aggressive restarts bump terms and invalidate in-flight votes
    # (475/1,365 resolved at 171s); (2) a log-behind replica can NEVER
    # win (vote rejections, raft §5.4.1) and each of its campaigns resets
    # its peers' election clocks (term bump → become_follower → etick=0),
    # so retrying it forever starves the replica that could win (32
    # groups/rank wedged at term 40).  So: up to `attempts_max` spaced
    # campaigns per preferred group, then hands off to the replicas'
    # randomized election timeouts, with the resolution scan accepting a
    # leader wherever it emerges.
    attempts_max = int(os.environ.get("E2E_CAMPAIGN_ATTEMPTS", "3"))
    to_campaign = list(reversed(mine))
    inflight: dict = {}  # cid -> [last campaign wall time, attempts]
    resolved = 0
    next_retry = time.time() + 2.0
    next_report = time.time() + 5.0
    # wait until every LOCAL replica sees LIVE leadership — self-led, or
    # follower with leader known and a fresh election clock (a stale
    # leader_id with a growing clock means the leader died post-election;
    # its replicas will re-elect naturally and the scan keeps waiting)
    def _resolved(cid):
        r = nh.get_node(cid).peer.raft
        return r.leader_id != 0 and (
            r.is_leader() or r.election_tick < r.election_timeout
        )

    leaderless = set(cids)
    all_live = False
    next_scan = 0.0
    while not all_live and time.time() < deadline:
        now = time.time()
        for cid in list(leaderless):
            # raw raft read (GIL-atomic): Node.leader_id is the scalar
            # tick path's change cache and goes quiet once the group
            # enrolls in the fast lane
            if nh.get_node(cid).peer.raft.leader_id != 0:
                leaderless.discard(cid)
                inflight.pop(cid, None)
                if preferred(cid) == rank:
                    resolved += 1
        if not leaderless and now >= next_scan:
            all_live = all(_resolved(cid) for cid in cids)
            next_scan = now + 2.0
        while to_campaign and len(inflight) < wave:
            cid = to_campaign.pop()
            if cid not in leaderless:
                continue
            nh.get_node(cid).request_campaign()
            inflight[cid] = [now, 1]
        if now >= next_retry:
            for cid, slot in list(inflight.items()):
                t0, attempts = slot
                node = nh.get_node(cid)
                if attempts >= attempts_max or node.peer.raft.is_candidate():
                    continue
                if now - t0 >= 2.0:
                    node.request_campaign()
                    slot[0], slot[1] = now, attempts + 1
            next_retry = now + 2.0
        if time.time() >= next_report:
            # election progress to stderr so a slow tunneled-TPU run
            # is diagnosable from the driver capture
            print(
                f"rank{rank}: resolved {resolved}/{len(mine)} at "
                f"{time.perf_counter() - t_campaign:.1f}s",
                file=sys.stderr, flush=True,
            )
            next_report = time.time() + 5.0
        time.sleep(0.05)
    # unresolved-tail diagnostics: every replica of every leaderless
    # group, so the three rank logs together give the full picture
    for cid in cids:
        node = nh.get_node(cid)
        r = node.peer.raft
        if r.leader_id != 0:
            continue
        print(
            f"rank{rank}: STUCK cid={cid} state={r.state} term={r.term} "
            f"voted_for={r.vote} votes={dict(r.votes)} "
            f"etick={r.election_tick}/{r.randomized_election_timeout} "
            f"fastlane={node.fast_lane} "
            f"mq={len(node.mq._left) + len(node.mq._right)} "
            f"trace={list(r.vote_trace)}",
            file=sys.stderr, flush=True,
        )
    # drive every group THIS rank leads, preferred or adopted
    led = {cid for cid in cids if nh.get_node(cid).is_leader()}
    leaders = {cid: nh for cid in led}
    setup_s = time.perf_counter() - t_setup

    emit(
        "READY",
        {
            "rank": rank,
            "led": len(led),
            "mine": len(mine),
            "setup_s": round(setup_s, 1),
            "engine": my_engine,
            "platform": platform,
        },
    )

    sampler = None
    prof_dir = os.environ.get("E2E_PROFILE_DIR", "")
    if prof_dir:
        from profile_e2e import Sampler

        sampler = Sampler()
        sampler.start()

    rc = 0
    stage = "TPUT"  # tag the parent is blocked on; errors must carry it
    try:
        payload = _payload()
        # phase 1: throughput — every led group, window in flight.  The
        # per-group window is capped so AGGREGATE in-flight per rank stays
        # bounded: at 4k+ groups a fixed per-group window floods the
        # pipeline with 100k+ queued proposals and the measurement window
        # only sees the queue ramp (Little's law: latency = inflight/rate),
        # not steady-state throughput.
        target_inflight = int(os.environ.get("E2E_TARGET_INFLIGHT", "16384"))
        window = max(1, min(window, target_inflight // max(1, len(led))))
        plan = expect("RUN")
        while time.time() < plan["t0"]:
            time.sleep(0.005)
        # enrollment duty cycle, bracketed around the MEASUREMENT windows
        # only (drain budgets and cross-rank barriers between phases would
        # otherwise dilute the denominator)
        _fl_on = nh.fastlane is not None and nh.fastlane.enabled
        _dgs = nh.fastlane.duty_group_seconds if _fl_on else (lambda: 0.0)
        duty_gs = duty_el = 0.0
        _w_t0, _w_g0 = time.monotonic(), _dgs()
        tput = _measure(
            leaders, sorted(led), payload, window,
            plan["t0"] + plan["duration"], threads,
            drain_budget=plan.get("drain_budget", 30.0),
        )
        duty_gs += _dgs() - _w_g0
        duty_el += time.monotonic() - _w_t0
        tput_lats = tput.pop("_lats")
        tput["window"] = window  # effective (aggregate-inflight-capped)
        emit(
            "TPUT",
            {
                "rank": rank,
                "tput": tput,
                "tput_lats": tput_lats[:: max(1, len(tput_lats) // 20000)],
            },
        )
        # phase 2 (own barrier — starts only after every rank drained):
        # latency — window=1 on the designated subset
        stage = "RESULT"
        plan = expect("LAT")
        lat_cids = [c for c in plan["lat_cids"] if c in led]
        while time.time() < plan["t0"]:
            time.sleep(0.005)
        _w_t0, _w_g0 = time.monotonic(), _dgs()
        lat = _measure(
            leaders, lat_cids, payload, 1,
            plan["t0"] + plan["duration"], threads,
        )
        duty_gs += _dgs() - _w_g0
        duty_el += time.monotonic() - _w_t0
        lat_lats = lat.pop("_lats")
        fl_stats = (
            nh.fastlane.stats() if nh.fastlane is not None else {"enabled": False}
        )
        # round-3-comparable key: groups this rank LEADS that are enrolled
        # (stats() separately reports enrolled_replicas = all local
        # replicas in the lane, followers included)
        fl_stats["enrolled_now"] = sum(
            1 for cid in led if nh.get_node(cid).fast_lane
        )
        fl_stats["led"] = len(led)
        if _fl_on:
            # duty cycle over the measurement windows: fraction of
            # group-seconds this rank's REPLICAS (not just leaders — every
            # local replica can enroll) spent in the lane
            fl_stats["enroll_duty"] = round(
                duty_gs / (max(1, groups) * max(1e-9, duty_el)), 4
            )
        emit(
            "RESULT",
            {
                "rank": rank,
                "lat": lat,
                "engine_stats": nh.engine.stats(),
                "fastlane": fl_stats,
                "lat_lats": lat_lats[:: max(1, len(lat_lats) // 20000)],
            },
        )
        # phase 3: mixed 9:1 read:write (BASELINE.md Mixed IO axis)
        stage = "MIXED"
        plan = expect("MIX")
        mix_cids = [c for c in plan["cids"] if c in led]
        while time.time() < plan["t0"]:
            time.sleep(0.005)
        mixed = _measure_mixed(
            leaders, mix_cids, payload, plan.get("read_ratio", 9),
            plan["t0"] + plan["duration"], threads,
        )
        emit("MIXED", {"rank": rank, "mixed": mixed})
        # final barrier: a rank with no leaders finishes its phases
        # instantly — it must NOT stop its NodeHost (killing quorum for
        # the others) until every rank is done measuring
        expect("EXIT")
    except Exception as e:  # noqa: BLE001 — report, don't die silently
        # emit the error under the tag the parent is currently waiting for,
        # plus every later tag, so the parent never hangs or drops it
        err = {"rank": rank, "error": str(e)}
        emit(stage, err)
        for later in {"TPUT": ("RESULT", "MIXED"), "RESULT": ("MIXED",)}.get(
            stage, ()
        ):
            emit(later, err)
        rc = 1
    finally:
        if sampler is not None:
            sampler.stop()
            with open(os.path.join(prof_dir, f"rank{rank}.txt"), "w") as f:
                f.write(sampler.report() + "\n")
        try:
            nh.stop()
        except Exception:
            pass
    return rc


def _aggregate_mixed(mixed_results):
    oks = [r["mixed"] for r in mixed_results if "mixed" in r]
    if not oks:
        return {"error": "no rank completed the mixed phase"}
    return {
        "ops_per_sec": round(sum(m["ops_per_sec"] for m in oks), 1),
        "reads": sum(m["reads"] for m in oks),
        "writes": sum(m["writes"] for m in oks),
        "errors": sum(m["errors"] for m in oks),
        "read_ratio": oks[0]["read_ratio"],
        "read_latency_ms": oks[0]["read_latency_ms"],
        "write_latency_ms": oks[0]["write_latency_ms"],
    }


def _free_ports(n):
    socks = []
    ports = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        socks.append(s)
    for s in socks:
        s.close()
    return ports


def run_mp(
    groups: int = 1024,
    duration: float = 10.0,
    window: int = 16,
    rtt_ms: int = 500,
    engine: str = "tpu",
    durable: bool = True,
    threads: int = 8,
    procs: int = 3,
    leader_mode: str = "",
    leader_timeout: float = 180.0,
    latency_groups: int = 64,
    deadline_s: float = 420.0,
) -> dict:
    """Parent orchestration: spawn one rank per NodeHost, coordinate the
    two measurement phases by wall clock, aggregate."""
    if not leader_mode:
        # With the native fast lane carrying steady-state replication,
        # leaders spread evenly in BOTH modes: concentrating all 1,024
        # leaders on the device rank (round 2's shape, when the device
        # engine was the only commit-tally offload) overloads one process
        # and wedges the mixed phase.  The device engine still runs on
        # rank 0 serving election tallies, device ticks and any
        # non-enrolled group's commit math; enrolled steady-state commits
        # are native (see PERF.md).
        leader_mode = "spread"
        if engine == "tpu" and os.environ.get("E2E_FAST_LANE", "1") != "1":
            leader_mode = "rank0"  # round-2 shape: device tallies it all
    t_start = time.time()
    hard_deadline = t_start + deadline_s
    ports = _free_ports(procs)
    tmp = tempfile.mkdtemp(prefix="dbtpu-e2e-") if durable else ""
    env = dict(os.environ)
    env.update(
        {
            "E2E_PROCS": str(procs),
            "E2E_GROUPS": str(groups),
            "E2E_RTT_MS": str(rtt_ms),
            "E2E_WINDOW": str(window),
            "E2E_THREADS": str(threads),
            "E2E_DURABLE": "1" if durable else "0",
            "E2E_ENGINE": engine,
            "E2E_LEADER_MODE": leader_mode,
            "E2E_LEADER_TIMEOUT": str(leader_timeout),
            "E2E_PORTS": ",".join(str(p) for p in ports),
            "E2E_DIR": tmp,
        }
    )
    children = []
    hogs = []
    try:
        rank_log_dir = os.environ.get("E2E_RANK_LOG_DIR", "")
        if rank_log_dir:
            os.makedirs(rank_log_dir, exist_ok=True)
        for rank in range(procs):
            cenv = dict(env)
            cenv["E2E_RANK"] = str(rank)
            stderr_to = subprocess.DEVNULL
            if rank_log_dir:
                stderr_to = open(
                    os.path.join(rank_log_dir, f"rank{rank}.err"), "w"
                )
            children.append(
                subprocess.Popen(
                    [sys.executable, os.path.abspath(__file__), "--rank"],
                    stdin=subprocess.PIPE,
                    stdout=subprocess.PIPE,
                    stderr=stderr_to,
                    env=cenv,
                    text=True,
                    cwd=os.path.dirname(os.path.abspath(__file__)),
                )
            )
            if stderr_to is not subprocess.DEVNULL:
                stderr_to.close()  # the child holds its own duplicated fd

        import queue as _queue

        # one reader thread per child: readline() can't be timed out
        # directly, so a hung rank must not wedge the parent past deadline_s
        rank_lines = [_queue.Queue() for _ in children]

        def _reader(proc, q):
            for line in proc.stdout:
                q.put(line)
            q.put(None)

        for c, q in zip(children, rank_lines):
            threading.Thread(target=_reader, args=(c, q), daemon=True).start()

        def read_tagged(idx, tag, deadline):
            """Read lines until one starts with tag; enforce deadline."""
            q = rank_lines[idx]
            while True:
                timeout = deadline - time.time()
                if timeout <= 0:
                    raise TimeoutError(f"deadline waiting for {tag}")
                try:
                    line = q.get(timeout=min(timeout, 1.0))
                except _queue.Empty:
                    continue
                if line is None:
                    raise RuntimeError(f"rank died before {tag}")
                if line.startswith(tag + " "):
                    return json.loads(line[len(tag) + 1 :])

        def broadcast(tag, obj=None):
            line = tag + (" " + json.dumps(obj) if obj is not None else "") + "\n"
            for c in children:
                try:
                    c.stdin.write(line)
                    c.stdin.flush()
                except (BrokenPipeError, OSError):
                    pass  # an errored rank may already have exited

        # barrier 1: all ranks started → campaign
        started = [
            read_tagged(i, "STARTED", hard_deadline - 30)
            for i in range(len(children))
        ]
        print(f"e2e mp started={started}", file=sys.stderr)
        broadcast("CAMPAIGN", {})
        readies = [
            read_tagged(i, "READY", hard_deadline - 20)
            for i in range(len(children))
        ]
        setup_s = time.time() - t_start
        print(f"e2e mp setup_s={setup_s:.1f} readies={readies}", file=sys.stderr)
        led_total = sum(r["led"] for r in readies)

        # E2E_HOG=N: spawn N busy-loop processes for the MEASUREMENT
        # phases only (setup/elections stay clean) — the contended-box
        # robustness axis (VERDICT r4 #2).  The assertion of interest is
        # the fastlane duty staying ~1.0 (no contact-loss/quorum-loss
        # eject cascade) while throughput degrades gracefully; killed in
        # the finally block below.
        n_hog = int(os.environ.get("E2E_HOG", "0"))
        for _ in range(n_hog):
            hogs.append(subprocess.Popen(
                [sys.executable, "-c", "while True:\n pass"],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            ))

        # phase 1: throughput
        broadcast("RUN", {"t0": time.time() + 0.5, "duration": duration,
                          "drain_budget": 30.0})
        tputs = [
            read_tagged(i, "TPUT", hard_deadline) for i in range(len(children))
        ]
        # phase 2: latency (after every rank drained).  If any rank
        # abandoned in-flight proposals at the drain deadline the runtime is
        # still chewing on them — give it a bounded quiesce window so the
        # window=1 latency probes don't queue behind leftover backlog
        abandoned_now = sum(
            r["tput"]["abandoned"] for r in tputs if "tput" in r
        )
        quiesce = 0.5 if not abandoned_now else min(20.0, 2.0 + abandoned_now / 1000.0)
        lat_cids = [BASE_CID + g for g in range(min(latency_groups, groups))]
        broadcast("LAT", {"t0": time.time() + quiesce,
                          "duration": min(duration, 5.0),
                          "lat_cids": lat_cids})
        results = [
            read_tagged(i, "RESULT", hard_deadline)
            for i in range(len(children))
        ]
        # phase 3: mixed 9:1 read:write on a bounded group subset
        mix_cids = [BASE_CID + g for g in range(min(256, groups))]
        broadcast("MIX", {"t0": time.time() + 0.5,
                          "duration": min(duration, 5.0),
                          "read_ratio": 9, "cids": mix_cids})
        mixed_results = []
        for i in range(len(children)):
            try:
                mixed_results.append(read_tagged(i, "MIXED", hard_deadline))
            except Exception as e:  # a rank that died earlier
                mixed_results.append({"rank": i, "error": str(e)})
        broadcast("EXIT", {})
        # one entry per failed rank (a TPUT-stage error is re-emitted under
        # RESULT so the parent never hangs — don't double-count it)
        errors = list(
            {r["rank"]: r for r in tputs + results if "error" in r}.values()
        )
        tput_oks = [r for r in tputs if "tput" in r]
        lat_oks = [r for r in results if "lat" in r]
        tput_done = sum(r["tput"]["completed_in_window"] for r in tput_oks)
        tput_errs = sum(r["tput"]["errors"] for r in tput_oks)
        abandoned = sum(r["tput"]["abandoned"] for r in tput_oks)
        lat_done = sum(r["lat"]["completed"] for r in lat_oks)
        tput_lats = [l for r in tput_oks for l in r["tput_lats"]]
        lat_lats = [l for r in lat_oks for l in r["lat_lats"]]
        writes_per_sec = round(tput_done / duration, 1)
        out = {
            "groups": groups,
            "hosts": procs,
            "procs": procs,
            "engine": engine,
            "sm": os.environ.get("E2E_SM", "python"),
            "leader_mode": leader_mode,
            "durable": durable,
            "payload_bytes": len(_payload()),
            "setup_s": round(setup_s, 1),
            "led_groups": led_total,
            "writes_per_sec": writes_per_sec,
            "commit_latency_ms": _percentiles(lat_lats),
            "throughput_phase": {
                "writes_per_sec": writes_per_sec,
                "completed_in_window": tput_done,
                "errors": tput_errs,
                "abandoned": abandoned,
                "latency_ms": _percentiles(tput_lats),
                # effective per-rank windows (the aggregate-inflight cap
                # depends on each rank's led count)
                "window": sorted(
                    r["tput"].get("window", window) for r in tput_oks
                ) or [window],
            },
            "latency_phase": {
                "completed": lat_done,
                "proposing_groups": len(lat_cids),
                "latency_ms": _percentiles(lat_lats),
            },
            "mixed_phase": _aggregate_mixed(mixed_results),
            "ranks": [
                {k: r[k] for k in ("rank", "engine", "platform", "led", "setup_s")}
                for r in readies
            ],
        }
        if os.environ.get("E2E_KEEP_STATS") == "1":
            out["rank_engine_stats"] = [r.get("engine_stats") for r in lat_oks]
        out["fastlane"] = [r.get("fastlane") for r in lat_oks]
        if errors:
            out["rank_errors"] = errors
        return out
    finally:
        for h in hogs:
            try:
                h.kill()
                h.wait(timeout=5)  # reap: a kill without wait leaves a zombie
            except Exception:
                pass
        for c in children:
            # let ranks finish their own cleanup (NodeHost.stop, profile
            # dumps) before the hard kill
            try:
                c.stdin.close()
            except Exception:
                pass
        deadline = time.time() + 8
        for c in children:
            try:
                c.wait(timeout=max(0.1, deadline - time.time()))
            except Exception:
                pass
        for c in children:
            try:
                c.kill()
            except Exception:
                pass
        if tmp:
            shutil.rmtree(tmp, ignore_errors=True)


def run_churn_soak() -> dict:
    """BlackWater churn soak A/B (ISSUE 17), scored by automated MTTR.

    Shells ``soak.py --churn`` twice with the SAME seed and schedule —
    recovery plane OFF, then ON — and merges the two summaries into one
    record.  The scored quantity is per-detector MTTR (health-event open
    to close, p50/p99 across the fleet, censored opens included); the
    gate is zero linearizability violations in BOTH arms.  Env knobs:
    CHURN_GROUPS (default 100), CHURN_MINUTES, CHURN_SEED,
    CHURN_ARM_TIMEOUT (seconds, per arm).
    """
    groups = int(os.environ.get("CHURN_GROUPS", "100"))
    minutes = float(os.environ.get("CHURN_MINUTES", "0.1"))
    seed = int(os.environ.get("CHURN_SEED", "7"))
    arm_timeout = float(os.environ.get("CHURN_ARM_TIMEOUT", "1800"))
    soak = os.path.join(os.path.dirname(os.path.abspath(__file__)), "soak.py")

    def _arm(recover: bool) -> dict:
        cmd = [
            sys.executable, soak, "--churn",
            "--minutes", str(minutes),
            "--groups", str(groups),
            "--seed", str(seed),
        ]
        if recover:
            cmd.append("--recover")
        try:
            p = subprocess.run(
                cmd, capture_output=True, text=True, timeout=arm_timeout,
            )
        except subprocess.TimeoutExpired:
            return {"churn_ok": False, "linearizable": False,
                    "error": f"arm timed out after {arm_timeout}s"}
        # the summary is the last stdout line; stderr carries progress
        lines = [ln for ln in p.stdout.strip().splitlines() if ln.strip()]
        try:
            s = json.loads(lines[-1])
        except Exception:
            s = {"churn_ok": False, "linearizable": False,
                 "error": f"unparseable summary (exit {p.returncode}): "
                          f"{(lines or ['<empty>'])[-1][:200]}"}
        s["exit_code"] = p.returncode
        return s

    off = _arm(False)
    on = _arm(True)
    improvement = {}
    for det, o in (off.get("mttr") or {}).items():
        n = (on.get("mttr") or {}).get(det)
        if not n or o.get("p99_s") is None or n.get("p99_s") is None:
            continue
        improvement[det] = {
            "off_p99_s": o["p99_s"],
            "on_p99_s": n["p99_s"],
            "off_p50_s": o.get("p50_s"),
            "on_p50_s": n.get("p50_s"),
            "speedup_x": (
                round(o["p99_s"] / n["p99_s"], 3) if n["p99_s"] else None
            ),
        }
    return {
        "groups": groups,
        "minutes": minutes,
        "seed": seed,
        "churn_ok": bool(off.get("churn_ok")) and bool(on.get("churn_ok")),
        "linearizable": (
            bool(off.get("linearizable")) and bool(on.get("linearizable"))
        ),
        "mttr_p99": improvement,
        "recovery_actions": on.get("recovery_actions"),
        "off": off,
        "on": on,
    }


def run_quick() -> dict:
    """Bounded run for bench.py's detail field (driver time budget)."""
    groups = int(os.environ.get("E2E_GROUPS", "1024"))
    # 15s measurement window: at 1,024 groups the 10s window showed ±30%
    # run-to-run spread from election/enrollment timing riding the edges
    duration = float(os.environ.get("E2E_DURATION", "15"))
    window = int(os.environ.get("E2E_WINDOW", "32"))
    rtt_ms = int(os.environ.get("E2E_RTT_MS", "1000"))
    engine = os.environ.get("E2E_ENGINE", "tpu")
    durable = os.environ.get("E2E_DURABLE", "1") == "1"
    threads = int(os.environ.get("E2E_THREADS", "8"))
    procs = int(os.environ.get("E2E_PROCS", "3"))
    deadline = float(os.environ.get("E2E_DEADLINE", "420"))
    if procs > 1:
        return run_mp(
            groups=groups,
            duration=duration,
            window=window,
            rtt_ms=rtt_ms,
            engine=engine,
            durable=durable,
            threads=threads,
            procs=procs,
            # honor an explicit placement request (E2E_LEADER_MODE=rank0
            # for the concentrated topology); "" keeps run_mp's policy
            # default — without this passthrough the orchestrator
            # silently overwrote the caller's env with "spread"
            leader_mode=os.environ.get("E2E_LEADER_MODE", ""),
            leader_timeout=float(os.environ.get("E2E_LEADER_TIMEOUT", "180")),
            deadline_s=deadline,
        )
    return run(
        groups=groups,
        duration=duration,
        window=window,
        rtt_ms=rtt_ms,
        engine=engine,
        durable=durable,
        threads=threads,
        leader_timeout=float(os.environ.get("E2E_LEADER_TIMEOUT", "180")),
    )


if __name__ == "__main__":
    if "--rank" in sys.argv:
        sys.exit(rank_main())
    _force_cpu_for_engine()
    if "--trace-axis" in sys.argv:
        print(json.dumps(run_trace_axis()), file=sys.stdout)
        sys.exit(0)
    if "--crossdomain" in sys.argv:
        print(json.dumps(run_crossdomain()), file=sys.stdout)
        sys.exit(0)
    if "--devsm" in sys.argv:
        print(json.dumps(run_devsm()), file=sys.stdout)
        sys.exit(0)
    if "--host-workers" in sys.argv:
        print(json.dumps(run_host_workers_axis()), file=sys.stdout)
        sys.exit(0)
    if "--health-axis" in sys.argv:
        print(json.dumps(run_health_axis()), file=sys.stdout)
        sys.exit(0)
    if "--telem-axis" in sys.argv:
        print(json.dumps(run_telem_axis()), file=sys.stdout)
        sys.exit(0)
    if "--devprof-axis" in sys.argv:
        print(json.dumps(run_devprof_axis()), file=sys.stdout)
        sys.exit(0)
    if "--churn-soak" in sys.argv:
        print(json.dumps(run_churn_soak()), file=sys.stdout)
        sys.exit(0)
    if "--hier-axis" in sys.argv:
        print(json.dumps(run_hier()), file=sys.stdout)
        sys.exit(0)
    print(json.dumps(run_quick()), file=sys.stdout)
