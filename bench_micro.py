"""Micro-benchmark suite — the reference ``benchmark_test.go`` analog.

Per-subsystem throughput probes for the hot host-path pieces, mirroring
the reference's families (``/root/reference/benchmark_test.go:54-641``):
payload encoding (plain + snappy), entry queue, pending-proposal key
allocation, entry marshal/unmarshal (Python and the C accelerator),
LogDB SaveRaftState at 16/128/1024B, fsync latency, transport framing,
SM step through the RSM manager, and the native-KV update path.

Run:  python bench_micro.py            (all sections, one JSON line each)
      python bench_micro.py entry_q    (substring-filter sections)

Numbers are ops/s on the current box; they exist for regression
comparison run-over-run, not cross-machine comparison (the e2e story
lives in bench.py / PERF.md).
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
import time


def _rate(fn, n, *, min_s=0.4):
    """ops/s for fn(i) called n times (repeats until min_s elapsed)."""
    reps = 0
    t0 = time.perf_counter()
    while True:
        for i in range(n):
            fn(i)
        reps += n
        dt = time.perf_counter() - t0
        if dt >= min_s:
            return round(reps / dt, 1)


def bench_encoded_payload():
    """BenchmarkNoCompression/SnappyEncodedPayload{16,512,4096}Bytes."""
    from dragonboat_tpu.rsm.encoded import get_encoded_payload

    out = {}
    for size in (16, 512, 4096):
        data = os.urandom(size)
        out[f"plain_{size}B"] = _rate(
            lambda i, d=data: get_encoded_payload(0, d), 20_000
        )
        out[f"snappy_{size}B"] = _rate(
            lambda i, d=data: get_encoded_payload(1, d), 2_000
        )
    return out


def bench_entry_queue():
    """BenchmarkAddToEntryQueue: the propose-side staging queue."""
    from dragonboat_tpu.queue import EntryQueue
    from dragonboat_tpu.wire import Entry

    q = EntryQueue(1 << 16)
    e = Entry(term=1, index=1, cmd=b"x" * 16)

    def add(i):
        if not q.add(e):
            q.get()  # drain when full (amortized)

    return {"add": _rate(add, 50_000)}


def bench_pending_proposal_key():
    """BenchmarkPendingProposalNextKey + Propose{16,128,1024} through the
    sharded pending-proposal store (no raft underneath — the tracking
    cost itself)."""
    from dragonboat_tpu.requests import PendingProposal

    pp = PendingProposal()
    out = {"next_key": _rate(lambda i: pp._next_key(), 100_000)}
    for size in (16, 128, 1024):
        cmd = b"x" * size

        def prop(i, c=cmd):
            rs, e = pp.propose(0, 0, c, 100)
            pp.dropped(e.key)

        out[f"propose_{size}B"] = _rate(prop, 20_000)
    return out


def bench_marshal_entry():
    """BenchmarkMarshalEntry{16,128,1024}: wire codec, Python and the C
    accelerator (dbtpu_wirecodec)."""
    from dragonboat_tpu.wire import Entry
    from dragonboat_tpu.wire import codec

    out = {}
    for size in (16, 128, 1024):
        e = Entry(term=5, index=42, key=7, client_id=1, series_id=2,
                  cmd=b"x" * size)
        buf = bytearray()
        codec.encode_entry_into(buf, e)
        blob = bytes(buf)

        def enc(i, ent=e):
            ent._enc = None  # defeat the wire cache: measure marshaling
            b = bytearray()
            codec.encode_entry_into(b, ent)

        out[f"encode_{size}B"] = _rate(enc, 20_000)
        out[f"decode_{size}B"] = _rate(
            lambda i, bl=blob: codec.decode_entry(bl), 20_000
        )
    return out


def bench_logdb_save(durable: bool):
    """BenchmarkSaveRaftState{16,128,1024}: one Update with 128 entries
    per call through the real LogDB (in-mem KV, or the durable WAL with
    fsync when durable=True — the fsync variant is the
    BenchmarkFSyncLatency analog)."""
    from dragonboat_tpu.logdb import open_logdb
    from dragonboat_tpu.wire import Entry, State, Update

    tmp = None
    if durable:
        tmp = tempfile.mkdtemp(prefix="dbtpu-microbench-")
        db = open_logdb(tmp, shards=1, fsync=True)
    else:
        db = open_logdb(shards=1)
    out = {}
    try:
        for size in (16, 128, 1024):
            seq = [0]

            def save(i, s=size, q=seq):
                lo = q[0] * 128 + 1
                q[0] += 1
                ents = [
                    Entry(term=1, index=lo + j, cmd=b"x" * s)
                    for j in range(128)
                ]
                db.save_raft_state([
                    Update(cluster_id=1, node_id=1, entries_to_save=ents,
                           state=State(term=1, vote=1, commit=lo))
                ])

            key = f"save128x{size}B"
            # entries/s, not calls/s: each call persists 128 entries
            out[key] = round(_rate(save, 8 if durable else 64) * 128, 1)
    finally:
        db.close()
        if tmp:
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)
    return out


def bench_fsync():
    """BenchmarkFSyncLatency: the raw device floor under this repo's WAL
    (one small durable append per call)."""
    from dragonboat_tpu.logdb import open_logdb
    from dragonboat_tpu.wire import Entry, Update

    tmp = tempfile.mkdtemp(prefix="dbtpu-fsync-")
    db = open_logdb(tmp, shards=1, fsync=True)
    try:
        lat = []

        def one(i):
            t0 = time.perf_counter()
            db.save_raft_state([
                Update(cluster_id=1, node_id=1,
                       entries_to_save=[Entry(term=1, index=i + 1, cmd=b"x")])
            ])
            lat.append(time.perf_counter() - t0)

        _rate(one, 8, min_s=1.0)
        lat.sort()
        return {
            "p50_ms": round(lat[len(lat) // 2] * 1e3, 3),
            "p99_ms": round(lat[int(len(lat) * 0.99)] * 1e3, 3),
            "ops_s": round(len(lat) / sum(lat), 1),
        }
    finally:
        db.close()
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)


def bench_transport_framing():
    """BenchmarkTransport{16,128,1024} stand-in at the framing layer: the
    message-batch encode/decode that every wire byte passes through (the
    socket itself is measured by the e2e bench)."""
    from dragonboat_tpu.wire import Entry, Message, MessageBatch, MessageType
    from dragonboat_tpu.wire.codec import (
        decode_message_batch as decode_batch,
        encode_message_batch as encode_batch,
    )

    out = {}
    for size in (16, 128, 1024):
        batch = MessageBatch(
            source_address="127.0.0.1:1", deployment_id=1,
            requests=[
                Message(
                    type=MessageType.REPLICATE, cluster_id=1, from_=1, to=2,
                    term=3, log_index=7, log_term=3,
                    entries=[Entry(term=3, index=8 + j, cmd=b"x" * size)
                             for j in range(8)],
                )
            ],
        )
        blob = encode_batch(batch)

        def enc(i, b=batch):
            for m in b.requests:  # defeat the per-entry wire cache
                for e in m.entries:
                    e._enc = None
            encode_batch(b)

        out[f"encode_8x{size}B"] = _rate(enc, 5_000)
        out[f"decode_8x{size}B"] = _rate(
            lambda i, bl=blob: decode_batch(bl), 5_000
        )
    return out


def bench_sm_step():
    """BenchmarkStateMachineStepNoOPSession16 analog: committed entries
    through the RSM manager's batch apply (noop session, 16B cmds) —
    the per-entry apply rim PERF.md itemizes."""
    from dragonboat_tpu.rsm.statemachine import StateMachine, Task
    from dragonboat_tpu.rsm.adapters import RegularSM
    from dragonboat_tpu.statemachine import Result
    from dragonboat_tpu.wire import Entry

    class _NoopSM:
        def update(self, cmd):
            return Result(value=len(cmd))

        def lookup(self, q):
            return None

        def save_snapshot(self, *a):
            pass

        def recover_from_snapshot(self, *a):
            pass

        def close(self):
            pass

    applied = []

    class _Node:
        def apply_update(self, e, result, rejected, ignored, notify_read):
            applied.append(e.index)

        def apply_config_change(self, *a):
            pass

        def restore_remotes(self, *a):
            pass

    sm = StateMachine(RegularSM(_NoopSM()), None, _Node(), 1, 1)
    seq = [0]

    def step(i, q=seq):
        lo = q[0] * 64 + 1
        q[0] += 1
        sm.handle([Task(cluster_id=1, node_id=1, entries=[
            Entry(term=1, index=lo + j, cmd=b"y" * 16) for j in range(64)
        ])])

    return {"apply64x16B": round(_rate(step, 64) * 64, 1)}


def bench_natsm_update():
    """The C-ABI KV update path (scalar-plane ctypes hop included) — the
    per-op floor the native fast lane's zero-GIL apply avoids."""
    from dragonboat_tpu.native import natsm

    if not natsm.available():
        return {"skipped": "libnatsm unavailable"}
    sm = natsm.NativeKVStateMachine(1, 1)
    try:
        return {
            "update_16B": _rate(
                lambda i: sm.update(b"k%d=v" % (i % 512)), 20_000
            ),
            "lookup": _rate(lambda i: sm.lookup("k1"), 20_000),
        }
    finally:
        sm.close()


SECTIONS = [
    ("encoded_payload", bench_encoded_payload),
    ("entry_queue", bench_entry_queue),
    ("pending_proposal", bench_pending_proposal_key),
    ("marshal_entry", bench_marshal_entry),
    ("logdb_save_inmem", lambda: bench_logdb_save(False)),
    ("logdb_save_fsync", lambda: bench_logdb_save(True)),
    ("fsync_latency", bench_fsync),
    ("transport_framing", bench_transport_framing),
    ("sm_step", bench_sm_step),
    ("natsm_update", bench_natsm_update),
]


def main() -> int:
    pat = sys.argv[1] if len(sys.argv) > 1 else ""
    for name, fn in SECTIONS:
        if pat and pat not in name:
            continue
        try:
            res = fn()
        except Exception as e:  # a broken section must not hide the rest
            res = {"error": repr(e)[:200]}
        print(json.dumps({"section": name, **res}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
