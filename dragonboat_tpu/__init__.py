"""dragonboat_tpu — a TPU-native multi-group Raft consensus framework.

A ground-up re-design of the capabilities of Dragonboat (a multi-group Raft
library, reference at /root/reference) for TPU hosts: per-group protocol
bookkeeping (vote tallies, match-index/commit advancement, tick and election
timers) is batched into ``(nGroups, nPeers)`` JAX device tensors stepped by
fused XLA/Pallas kernels once per tick, while I/O (log persistence, network,
user state machines) remains on the host, with a C++ native log engine.

Public surface mirrors the reference's L0 facade: ``NodeHost``, per-group
``Config`` / per-host ``NodeHostConfig``, the three user state machine
interfaces, client sessions, and the pluggable LogDB/transport factories.
"""

__version__ = "0.1.0"

from .config import (  # noqa: F401
    Config,
    ConfigError,
    ExpertConfig,
    LogDBConfig,
    NodeHostConfig,
)
