"""dragonboat_tpu: a TPU-native multi-group Raft consensus framework.

Brand-new implementation with the capabilities of the reference dragonboat
library (multi-group Raft in Go): a NodeHost facade hosting thousands of
raft groups, pluggable state machines, sharded log storage, chunked snapshot
transfer — plus a batched (nGroups × nPeers) quorum engine that steps group
protocol state on TPU via JAX (see ``dragonboat_tpu.ops``).
"""
from .client import Session  # noqa: F401
from .config import Config, ExpertConfig, LogDBConfig, NodeHostConfig  # noqa: F401
from .nodehost import ClusterInfo, NodeHost, NodeHostInfo  # noqa: F401
from .requests import (  # noqa: F401
    ClusterAlreadyExistError,
    ClusterNotFoundError,
    InvalidOperationError,
    PayloadTooBigError,
    RejectedError,
    RequestError,
    RequestResult,
    RequestState,
    SystemBusyError,
    TimeoutError_,
)
from .statemachine import (  # noqa: F401
    IConcurrentStateMachine,
    IOnDiskStateMachine,
    IStateMachine,
    Result,
    SMEntry,
)

__version__ = "0.1.0"


def __getattr__(name):
    # lazy: the device-resident KV state machine (devsm, ISSUE 11) pulls
    # in numpy/ops machinery that plain host-SM users never need
    if name == "DeviceKVStateMachine":
        from .devsm.machine import DeviceKVStateMachine

        return DeviceKVStateMachine
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
