"""Client-side session state machine for exactly-once proposals.

Reference: ``client/session.go:23-167`` — a session carries
``(ClientID, SeriesID, RespondedTo)``; the RSM's session store dedups retried
proposals by ``SeriesID`` and evicts cached responses up to ``RespondedTo``.
NoOP sessions opt out of exactly-once semantics.
"""
from __future__ import annotations

import secrets
from dataclasses import dataclass

from .wire import (
    NOOP_CLIENT_ID,
    NOOP_SERIES_ID,
    SERIES_ID_FIRST_PROPOSAL,
    SERIES_ID_FOR_REGISTER,
    SERIES_ID_FOR_UNREGISTER,
)


@dataclass
class Session:
    """Reference ``client/session.go:45`` ``Session``."""

    cluster_id: int = 0
    client_id: int = NOOP_CLIENT_ID
    series_id: int = NOOP_SERIES_ID
    responded_to: int = 0

    # ---- constructors ----

    @staticmethod
    def new_session(cluster_id: int, rng=None) -> "Session":
        cid = (rng() if rng is not None else secrets.randbits(64)) or 1
        return Session(
            cluster_id=cluster_id,
            client_id=cid,
            series_id=SERIES_ID_FOR_REGISTER,
        )

    @staticmethod
    def noop_session(cluster_id: int) -> "Session":
        return Session(
            cluster_id=cluster_id,
            client_id=NOOP_CLIENT_ID,
            series_id=NOOP_SERIES_ID,
        )

    # ---- lifecycle (reference session.go:87-167) ----

    def prepare_for_register(self) -> None:
        self.series_id = SERIES_ID_FOR_REGISTER

    def prepare_for_unregister(self) -> None:
        self.series_id = SERIES_ID_FOR_UNREGISTER

    def prepare_for_propose(self) -> None:
        if self.series_id in (SERIES_ID_FOR_REGISTER, SERIES_ID_FOR_UNREGISTER):
            self.series_id = SERIES_ID_FIRST_PROPOSAL

    def proposal_completed(self) -> None:
        """Must be called once a proposal's result is accepted; advances the
        series and marks everything up to it as responded."""
        if self.is_noop_session():
            return
        if self.series_id in (SERIES_ID_FOR_REGISTER, SERIES_ID_FOR_UNREGISTER):
            raise RuntimeError(
                "proposal_completed called on a register/unregister session"
            )
        self.responded_to = self.series_id
        self.series_id += 1

    # ---- predicates ----

    def is_noop_session(self) -> bool:
        return self.client_id == NOOP_CLIENT_ID

    def validate_for_proposal(self, cluster_id: int) -> bool:
        if self.cluster_id != cluster_id:
            return False
        if self.is_noop_session():
            return self.series_id == NOOP_SERIES_ID
        return self.series_id not in (
            SERIES_ID_FOR_REGISTER,
            SERIES_ID_FOR_UNREGISTER,
        ) or self.series_id == SERIES_ID_FOR_REGISTER  # registration proposals
        # travel through the same path

    def validate_for_session_op(self, cluster_id: int) -> bool:
        if self.cluster_id != cluster_id or self.is_noop_session():
            return False
        return self.series_id in (
            SERIES_ID_FOR_REGISTER,
            SERIES_ID_FOR_UNREGISTER,
        )
