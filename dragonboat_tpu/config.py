"""Public configuration types.

Reference: ``config/config.go`` — per-group ``Config`` (:68-223), per-host
``NodeHostConfig`` (:226-576) and ``LogDBConfig``.  This build adds the
``ExpertConfig`` plugin boundary called for by the north star (the reference
v3.3.0-dev has no ``Expert`` field; its pluggability precedent is
``LogDBFactory``/``RaftRPCFactory``, ``config/config.go:298-305``): the
batched TPU quorum engine is selected through ``ExpertConfig.quorum_engine``
so the pure-host scalar path stays available for differential testing.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, Optional


class ConfigError(ValueError):
    pass


@dataclass
class Config:
    """Per-raft-group configuration (reference ``config/config.go:68-223``)."""

    node_id: int = 0
    cluster_id: int = 0
    check_quorum: bool = False
    election_rtt: int = 10
    heartbeat_rtt: int = 1
    snapshot_entries: int = 0
    compaction_overhead: int = 5000
    ordered_config_change: bool = False
    max_in_mem_log_size: int = 0
    snapshot_compression: int = 0  # CompressionType
    entry_compression: int = 0  # CompressionType
    disable_auto_compactions: bool = False
    is_observer: bool = False
    is_witness: bool = False
    quiesce: bool = False
    # leader-lease read plane (ISSUE 10, dragonboat_tpu/lease.py): a
    # CheckQuorum-backed, clock-bound lease lets a leader serve
    # linearizable reads locally with ZERO confirmation rounds — valid
    # for election_rtt − drift_epsilon ticks after the last quorum of
    # heartbeat acks; expiry/leadership-transfer/membership-change/term
    # change all fall back to the ReadIndex path.  OFF (default) keeps
    # the request paths structurally bit-identical (raft.lease is None,
    # the _read_plane_used precedent).  Requires check_quorum (the §6
    # vote lease is what makes the clock bound hold against forced
    # campaigns) and is rejected with quiesce (a quiesced leader's tick
    # clock freezes while follower election clocks keep running).
    read_lease: bool = False
    # device-resident state machine (devsm, ISSUE 11,
    # dragonboat_tpu/devsm): with the tpu quorum engine and a
    # DeviceKVStateMachine factory, committed fixed-width (key_slot,
    # value) ops apply as (G, slots) tensor updates INSIDE the fused
    # quorum dispatch — apply == commit on the device — and
    # lease/ReadIndex reads serve straight from device state with zero
    # host apply on the read path.  OFF (default) keeps the SM a plain
    # host IStateMachine and every request path structurally
    # bit-identical (the _devsm_used latch precedent).  On the scalar
    # engine the flag is inert — the same SM just runs host-side.
    device_kv: bool = False
    # hierarchical commit plane (ISSUE 18, dragonboat_tpu/raft/hier.py):
    # partition the voter set into latency domains (``hier_domains``:
    # node_id -> domain label) and let a leader whose own domain holds a
    # durable sub-quorum (majority of that domain, CD-Raft / Fast
    # Hierarchical Raft rule) close commits at the near RTT — far-domain
    # voters catch up asynchronously through the ordinary
    # replicate/resend machinery.  Safety comes from the paired vote
    # rule: winning an election additionally requires enough grants
    # inside every eligible (>= 2 voters) domain to guarantee
    # intersection with any sub-quorum that may have committed there,
    # so a new leader always carries every sub-quorum-committed entry.
    # Classic-quorum commits remain valid throughout (the rule is
    # max(classic, sub-quorum)).  Liveness tradeoff (documented in
    # docs/overview.md): while an eligible domain is entirely
    # unreachable, elections stall until it heals or membership drops
    # it.  OFF (default) keeps every request path structurally
    # bit-identical (raft.hier is None, the lease/_obs latch precedent).
    # Peers absent from ``hier_domains`` classify as domain "" and never
    # form sub-quorums.
    hier_commit: bool = False
    hier_domains: Dict[int, str] = field(default_factory=dict)

    def validate(self) -> None:
        # mirrors reference config.Config.Validate (config/config.go:168-223)
        if self.node_id == 0:
            raise ConfigError("invalid NodeID, it must be >= 1")
        if self.heartbeat_rtt == 0:
            raise ConfigError("HeartbeatRTT must be > 0")
        if self.election_rtt == 0:
            raise ConfigError("ElectionRTT must be > 0")
        if self.election_rtt <= 2 * self.heartbeat_rtt:
            raise ConfigError("invalid ElectionRTT, must be > 2 * HeartbeatRTT")
        if self.election_rtt < 10 * self.heartbeat_rtt:
            import warnings

            warnings.warn(
                "ElectionRTT is not a magnitude larger than HeartbeatRTT",
                stacklevel=2,
            )
        if self.max_in_mem_log_size < 0:
            raise ConfigError("MaxInMemLogSize must be >= 0")
        if 0 < self.max_in_mem_log_size < 64 * 1024:
            raise ConfigError("MaxInMemLogSize must be >= 64KB when set")
        if self.snapshot_compression not in (0, 1):
            raise ConfigError("unknown compression type")
        if self.entry_compression not in (0, 1):
            raise ConfigError("unknown compression type")
        if self.is_witness and self.snapshot_entries > 0:
            raise ConfigError("witness node cannot take snapshot")
        if self.is_witness and self.is_observer:
            raise ConfigError("witness node can not be an observer")
        if self.read_lease and not self.check_quorum:
            raise ConfigError("read_lease requires check_quorum")
        if self.read_lease and self.quiesce:
            raise ConfigError("read_lease can not be used with quiesce")
        if self.hier_domains and not isinstance(self.hier_domains, dict):
            raise ConfigError("hier_domains must map node_id -> domain label")
        if self.hier_commit:
            for nid, dom in self.hier_domains.items():
                if not isinstance(nid, int) or nid < 1:
                    raise ConfigError(
                        f"hier_domains key {nid!r} is not a node id"
                    )
                if not isinstance(dom, str):
                    raise ConfigError(
                        f"hier_domains[{nid}] must be a str domain label"
                    )


@dataclass
class ExpertConfig:
    """Expert-only knobs; the plugin boundary for the batched quorum engine.

    ``quorum_engine``:
      - ``"scalar"``: per-group host stepping only (the reference's model).
      - ``"tpu"``: route hot-path group stepping through the batched
        ``(nGroups, nPeers)`` device engine (:mod:`dragonboat_tpu.ops`).
      - ``"auto"``: resolved at NodeHost construction: ``scalar`` when the
        native fast lane is active (measured r4: at ~1.0 enrollment duty
        the device engine's per-tick dispatches only compete for CPU —
        6.3k vs 8.8k w/s at rung 3), else ``tpu`` iff a probe dispatch
        fits the commit-latency budget (a tunneled backend's ~70ms round
        trip does not; a local device's ~0.2ms does).

        Scale note (measured r5, spread placement, native SM, 1-vCPU
        box): the round-4 4x deficit at identical placement closed to
        parity with a slight tpu edge at 2,048 groups (tpu ~10.7k ±
        1.5k w/s vs scalar ~10.2k ± 1.1k; scalar still wins ~10% at
        1,024).  Getting there required running the coordinator's round
        thread at niceness +5 (default; ``DBTPU_ENGINE_NICE``
        overrides): un-niced, the scheduler sometimes favored the
        dispatch thread over raft/transport on the shared core and a
        run lost a third of its throughput for its lifetime.  A
        decisive ``tpu`` e2e win still wants spare host cores for the
        dispatch thread, a co-located (non-tunneled) device, or group
        counts far past the per-group-Python crossover — measure with
        bench.py's scale rung on the target topology before switching
        (PERF.md round-5 §3).
    """

    quorum_engine: str = "scalar"
    engine_block_groups: int = 0  # 0 = use Soft.quorum_engine_block_groups
    # AOT warm-compile the engine's fused (K,G,P) program set on a
    # background thread at NodeHost construction (ISSUE 7): until the
    # readiness latch flips, the coordinator's round thread stays on the
    # already-compiled single-round programs, so proposals never block
    # behind a first-use XLA compile; once ready, tick backlogs replay as
    # ONE adaptive-K fused dispatch.  Off = the live path stays
    # single-round forever (the pre-warmup behavior).
    engine_warm_fused: bool = True
    # shard the quorum engine's group axis over a jax.sharding.Mesh of
    # this many devices (ops/sharding.py): state tensors split on the
    # group axis, event batches replicated, zero collectives in steady
    # state — the multi-chip twin of the reference's clusterID%workers
    # partitioning (execengine.go:654-706).  0 = single device; capped at
    # the available device count; capacity rounds up to a multiple.
    engine_mesh_devices: int = 0
    step_worker_count: int = 0  # 0 = use Hard.step_engine_worker_count
    logdb_shards: int = 0  # 0 = use Hard.logdb_pool_size
    # native replication fast lane (fastlane.py + native/natraft.cpp): the
    # steady-state data plane of enrolled groups runs in C++.  Requires the
    # TCP transport and the native LogDB backend; silently unavailable
    # otherwise.
    fast_lane: bool = False
    # group-commit accumulation window per WAL shard (ms): pacing fsyncs
    # multiplies batch depth when the flush device is the bottleneck, at
    # the cost of up to this much added commit latency per durability hop
    fast_lane_commit_window_ms: float = 0.0
    # ---- compartmentalized host plane (hostplane.py, ISSUE 8) ----
    # master switch: build the proposal ingress batcher, the cross-shard
    # group-commit WAL flusher and the decoupled apply/egress executor
    # pools.  OFF (default) constructs none of it — the scalar host path
    # stays bit-identical to the pre-compartment build.
    host_compartments: bool = False
    # striped ingress staging shards (0 = 2).  One group always maps to
    # one shard, so a client's back-to-back proposals stay ordered.
    host_ingress_shards: int = 0
    # per-shard staging-ring capacity (0 = 4x incoming_proposal_queue_length);
    # a full ring raises SystemBusyError like a full entry_q
    host_ingress_ring: int = 0
    # shared-flusher accumulation window (ms): 0 flushes whatever is
    # queued when the flusher wakes (concurrency alone provides the
    # cross-committer merge); >0 trades up to that much commit latency
    # for deeper fsync amortization
    host_wal_window_ms: float = 0.0
    # dedicated apply / client-completion egress executors (0 = 2 / 1)
    host_apply_workers: int = 0
    host_egress_workers: int = 0
    # ---- multi-process host plane (hostproc/, ISSUE 12) ----
    # promote the host-plane stages to WORKER PROCESSES connected by
    # shared-memory staging rings: ingress payload encode, the
    # group-commit redo-journal append+fsync, and an apply tier for
    # state machines with process-spawnable factories (see
    # dragonboat_tpu.hostproc.spawnable).  0 (default) = today's
    # in-process path, structurally bit-identical; N > 0 spawns N
    # workers and implies the compartmentalized host plane (the worker
    # tiers are its stages' execution resources).  Worker crash/exit
    # falls back in-process mid-flight with nothing acked-before-fsync
    # violated; cap N at os.cpu_count() — extra workers only add
    # handoffs.
    host_workers: int = 0
    # group-commit journal strategy for the host plane's WAL tier:
    #   "auto"  — a startup fsync probe picks journaled vs classic
    #             per-shard saves (min-of-samples, robust to a
    #             GIL-polluted probe);
    #   "force" — always journal; the probe still runs (re-probed with
    #             extra samples) but only paces the accumulation window;
    #   "off"   — never journal (classic merged per-shard saves).
    # The chosen strategy is introspectable via NodeHost.wal_status().
    host_wal_journal: str = "auto"
    # filesystem the snapshot paths go through; None = the real OS fs.
    # Setting a vfs.MemFS runs the whole stack diskless (reference memfs
    # builds); a vfs.ErrorFS enables fault-injection testing and is
    # auto-detected like the reference's nodehost.go:321-327
    fs: object = None

    def validate(self) -> None:
        if self.quorum_engine not in ("scalar", "tpu", "auto"):
            raise ConfigError(f"unknown quorum engine {self.quorum_engine!r}")
        if self.host_workers < 0:
            raise ConfigError("host_workers must be >= 0")
        if self.host_wal_journal not in ("auto", "force", "off"):
            raise ConfigError(
                f"unknown host_wal_journal {self.host_wal_journal!r}"
            )


@dataclass
class LogDBConfig:
    """LogDB tuning (reference ``config/config.go`` LogDBConfig).

    The reference exposes RocksDB-style block/cache/WAL knobs; the native
    engine here is a segmented WAL+index (see ``dragonboat_tpu/native``), so
    the surface is the subset that translates.
    """

    kv_write_buffer_size: int = 128 * 1024 * 1024
    kv_max_write_buffer_number: int = 4
    kv_block_size: int = 32 * 1024
    kv_max_background_compactions: int = 2
    segment_file_size: int = 1024 * 1024 * 1024
    shards: int = 16
    # fsync every committed write batch (the reference always does; turning
    # this off trades durability of the last instants for throughput and is
    # only for benchmarks/tests — results must report it)
    fsync: bool = True

    @staticmethod
    def default() -> "LogDBConfig":
        return LogDBConfig()

    @staticmethod
    def tiny() -> "LogDBConfig":
        # reference GetTinyMemLogDBConfig: fit small-memory hosts
        return LogDBConfig(kv_write_buffer_size=4 * 1024 * 1024)


@dataclass
class NodeHostConfig:
    """Per-host configuration (reference ``config/config.go:226-576``)."""

    deployment_id: int = 0
    wal_dir: str = ""
    node_host_dir: str = ""
    rtt_millisecond: int = 200
    raft_address: str = ""
    listen_address: str = ""
    mutual_tls: bool = False
    ca_file: str = ""
    cert_file: str = ""
    key_file: str = ""
    max_send_queue_size: int = 0
    max_receive_queue_size: int = 0
    enable_metrics: bool = False
    max_snapshot_send_bytes_per_second: int = 0
    max_snapshot_recv_bytes_per_second: int = 0
    notify_commit: bool = False
    # persistent XLA compilation cache directory for the batched quorum
    # engine (ISSUE 7): restarts deserialize the warmed device programs
    # instead of recompiling (the directory is versioned internally by a
    # kernel-source hash, so kernel changes never mix stale executables;
    # point several hosts at one shared directory to amortize the first
    # compile across the fleet).  Empty = env DBTPU_COMPILATION_CACHE,
    # else no persistent cache.
    compilation_cache_dir: str = ""
    # cross-plane request tracing (obs/trace.py, ISSUE 9): sample 1 in N
    # requests into a full per-stage trace context (ingress → raft step →
    # WAL → device round → apply → egress), publish
    # dragonboat_trace_stage_seconds{stage} / dragonboat_trace_e2e_seconds
    # into this host's registry, and enable NodeHost.dump_trace (Chrome
    # trace / Perfetto export).  0 (default) = tracing off, request paths
    # bit-identical; env DBTPU_TRACE_SAMPLE is the no-config fallback.
    trace_sample_every: int = 0
    # opt-in SIGUSR2 live-debug dump: on signal, write the flight
    # recorder ring + any in-flight/completed sampled traces to a
    # timestamped JSON file next to the node host dir (soak/chaos
    # debugging without attaching a debugger)
    dump_signal: bool = False
    # cluster health plane (obs/health.py, ISSUE 13): sample every
    # group's raft/host-plane health on this cadence (driven off the
    # tick worker) into a rolling ring, run the anomaly detectors
    # (commit-stall, apply-lag, quorum-at-risk, leader-flap,
    # worker-flap, lease-thrash, devsm-rebind) and publish the
    # dragonboat_health_* families + NodeHost.health_report().  0
    # (default) = health plane off, nothing constructed, request paths
    # bit-identical; env DBTPU_HEALTH_SAMPLE_MS is the no-config
    # fallback.
    health_sample_ms: int = 0
    # aggregate health sampling (ISSUE 20, kernels.telem_fold): flip the
    # quorum engine's device telemetry fold and teach the health sampler
    # to cover device-backed groups from the fixed-size per-dispatch
    # aggregate (commit-lag histogram, per-state counts, stalled count,
    # slot occupancy, on-device top-K worst groups) at O(shards) host
    # cost — only the top-K flagged groups plus non-device groups take
    # the per-group raft_mu walk.  Requires the health plane
    # (health_sample_ms > 0) and the device quorum engine; without
    # either it logs a warning and changes nothing.  False (default) =
    # fold off, engine programs byte-identical, sampler walks every
    # group; env DBTPU_HEALTH_AGGREGATE is the no-config fallback.
    health_aggregate: bool = False
    # live scrape endpoint (obs/health.py MetricsServer): "host:port"
    # serves /metrics (Prometheus text exposition), /healthz
    # (aggregated detector verdict, 503 while degraded) and
    # /debug/health + /debug/trace dumps.  Empty (default) = no
    # listener; bind loopback ("127.0.0.1:9090") unless you front it
    # with auth — the exposition names clusters and addresses.  Port 0
    # binds ephemeral (NodeHost.metrics_server.port).  Env
    # DBTPU_METRICS_ADDR is the no-config fallback.
    metrics_addr: str = ""
    # closed-loop recovery plane (obs/recovery.py, ISSUE 17): let the
    # health detectors ACTUATE — quorum_at_risk evicts the unreachable
    # voter and promotes a standing observer (or adds a standby
    # witness, the BlackWater move), leader_flap transfers leadership
    # away from the flapping hosts, devsm_rebind force-releases the
    # device binding, commit_stall re-drives the fast-lane
    # eject/re-enroll path; worker_flap stays observe-only (the
    # hostproc monitor owns respawn).  Every action is rate-limited per
    # group, cooldown-gated and flap-damped (RecoveryController
    # guardrails).  Requires the health plane (health_sample_ms > 0) —
    # auto_recover without it logs a warning and constructs nothing.
    # False (default) = recovery off, nothing constructed, no sampler
    # subscription, request paths bit-identical; env DBTPU_AUTO_RECOVER
    # is the no-config fallback.
    auto_recover: bool = False
    # dry-run for the recovery plane: decisions run end to end and are
    # logged/counted (dragonboat_recovery_dryrun_total) but no
    # remediation executes.  Env DBTPU_RECOVER_DRY_RUN is the
    # no-config fallback.
    auto_recover_dry_run: bool = False
    # guardrail/behavior overrides for the RecoveryController
    # (rate_limit_s, cooldown_s, max_reopens, reopen_window_s,
    # action_timeout_s, workers, max_attempts, retry_delay_s,
    # standby_witness_addrs) — merged over the controller defaults;
    # unknown keys raise at construction.
    auto_recover_knobs: Dict[str, object] = field(default_factory=dict)
    # wall-clock lease guard (lease.py, ISSUE 17 churn-soak caught): the
    # leader lease's validity clock is the event loop's tick counter — a
    # CPU-starved or descheduled leader ticks slower than wall time, so
    # its tick-valid lease can outlive the majority's wall-time election
    # and serve a stale read.  True additionally bounds validity by
    # monotonic wall time (quorum-th newest ack within
    # duration * rtt_millisecond wall seconds) — strictly conservative:
    # starvation can only expire the lease early, never extend it.
    # Default off: tick-driven virtual-clock tests stay deterministic.
    lease_wall_guard: bool = False
    # device capacity & profiling plane (obs/devprof.py, ISSUE 15):
    # N > 0 attaches a DevProf to the batched quorum engine — the HBM
    # memory ledger + capacity model (dragonboat_devprof_hbm_bytes /
    # max-groups extrapolation), fused padding-waste accounting, and a
    # device-time estimator that samples every N-th dispatch with a
    # blocking block_until_ready delta (N is this value; 16 is the
    # measured-overhead default).  Enables NodeHost.profile_device
    # (on-demand jax.profiler capture windows) and the read-only
    # /debug/devprof endpoint on the MetricsServer.  0 (default) =
    # nothing constructed, the engine keeps its bit-identical
    # _devprof=None path; env DBTPU_DEVICE_PROFILE is the no-config
    # fallback.  Inert without the tpu quorum engine (the plane profiles
    # the device engine).
    device_profile: int = 0
    logdb_config: LogDBConfig = field(default_factory=LogDBConfig.default)
    expert: ExpertConfig = field(default_factory=ExpertConfig)
    # factories (reference config/config.go:298-305)
    logdb_factory: Optional[Callable] = None
    raft_rpc_factory: Optional[Callable] = None
    # user event listeners (reference raftio/listener.go:33,59)
    raft_event_listener: Optional[object] = None
    system_event_listener: Optional[object] = None
    fs: Optional[object] = None  # vfs override for tests

    def validate(self) -> None:
        if self.rtt_millisecond == 0:
            raise ConfigError("invalid RTTMillisecond")
        if not self.node_host_dir:
            raise ConfigError("NodeHostDir not specified")
        if not self.raft_address:
            raise ConfigError("RaftAddress not specified")
        if not _valid_address(self.raft_address):
            raise ConfigError(f"invalid RaftAddress {self.raft_address!r}")
        if self.listen_address and not _valid_address(self.listen_address):
            raise ConfigError(f"invalid ListenAddress {self.listen_address!r}")
        if self.mutual_tls and (
            not self.ca_file or not self.cert_file or not self.key_file
        ):
            raise ConfigError("CAFile/CertFile/KeyFile must be set for mutual TLS")
        self.expert.validate()

    def prepare(self) -> None:
        if not self.listen_address:
            self.listen_address = self.raft_address
        if self.deployment_id == 0:
            self.deployment_id = 1

    def get_deployment_id(self) -> int:
        return self.deployment_id if self.deployment_id else 1

    def get_listen_address(self) -> str:
        return self.listen_address or self.raft_address


def _valid_address(addr: str) -> bool:
    # host:port validation (reference utils/stringutil IsValidAddress)
    if ":" not in addr:
        return False
    host, _, port = addr.rpartition(":")
    if not host:
        return False
    try:
        p = int(port)
    except ValueError:
        return False
    return 0 < p < 65536
