"""Device-resident replicated KV state machine (devsm, ISSUE 11).

BENCH_r10's latency-attribution ledger localizes 48-65% of e2e p50 in
the APPLY stage — Python threads contending on one GIL while the device
plane absorbs hundreds of millions of writes per second.  This package
attacks it from the device side: for the fixed-width KV workload, the
state machine itself moves into the fused program.  Committed entries
carry ``(key_slot, value)`` SET ops, staged into per-group entry buffers
at append time; a batched apply fold inside ``quorum_multiround``'s scan
writes them into HBM-resident ``(G, slots)`` value tensors the moment
the commit watermark passes their index (``ops/kernels._kv_plane``).
Apply == commit by construction, so lease and ReadIndex reads serve
straight from device state with ZERO host apply on the read path —
"Compartmentalization"'s stage separation taken one step further, the
way CD-Raft co-locates the latency-critical stages with the data they
touch (PAPERS.md).

Pieces:

- :mod:`codec` — the fixed-width op wire format (8 bytes: int32 key
  slot + int32 value, little-endian);
- :mod:`machine` — :class:`DeviceKVStateMachine`, the user-facing SM:
  a normal ``IStateMachine`` everywhere (the host shadow stays warm on
  every replica — snapshots, failover and the devsm-off oracle all read
  it), whose ``lookup`` routes through the device plane when its group
  is device-bound;
- :mod:`plane` — :class:`DevKVPlane`, the coordinator-side manager:
  leadership-scoped binding (shadow upload at promotion once host apply
  catches the bind watermark), entry-op staging from
  ``raft.append_entries``, and the KV read service that resolves
  lookups from the fused dispatch's capture egress.

Default OFF: ``Config.device_kv`` gates registration; without it (or on
the scalar engine) nothing here is imported on the hot path and the
request paths stay structurally bit-identical — the engine-side
``_devsm_used`` latch is the same contract the read plane ships under.
"""
from .codec import OP_WIDTH, decode_op, encode_op  # noqa: F401
from .machine import DeviceKVStateMachine  # noqa: F401
from .plane import DevKVPlane  # noqa: F401
