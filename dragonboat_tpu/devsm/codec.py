"""Fixed-width devsm op codec.

One op = 8 bytes, little-endian: ``int32 key_slot`` + ``int32 value``.
The width is the contract that lets committed entries ride the fused
program as dense ``(G, E)`` int32 tensors — anything that doesn't parse
is a no-op on BOTH the device plane and the host shadow, so the two can
never diverge over a malformed command.
"""
from __future__ import annotations

import struct
from typing import Optional, Tuple

OP_WIDTH = 8
_OP = struct.Struct("<ii")


def encode_op(key_slot: int, value: int) -> bytes:
    """The proposal payload for ``SET key_slot := value``."""
    return _OP.pack(key_slot, value)


def decode_op(cmd: bytes) -> Optional[Tuple[int, int]]:
    """``(key_slot, value)``, or None when ``cmd`` is not a devsm op
    (wrong width).  Key-slot range is validated by the consumer against
    its configured width — the codec only owns the wire shape."""
    if len(cmd) != OP_WIDTH:
        return None
    return _OP.unpack(cmd)
