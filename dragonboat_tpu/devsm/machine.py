"""The user-facing device KV state machine.

:class:`DeviceKVStateMachine` is a complete ``IStateMachine``: without
the device plane (``Config.device_kv`` off, scalar engine, follower
replicas) it is just a small fixed-width KV store over a numpy shadow —
that shadow is also the differential ORACLE ``tests/test_devsm.py`` pins
the device fold against.  With the plane bound (tpu engine + device_kv +
this host leading the group), ``lookup`` serves from the HBM-resident
device state via the fused dispatch's capture egress, and the host
shadow stays warm in the background (single numpy cell writes) so
snapshots, failover and rebinding never need a device pull.
"""
from __future__ import annotations

from typing import BinaryIO, List

import numpy as np

from ..ops.state import KV_SLOTS
from ..statemachine import IStateMachine, Result, SnapshotFile
from .codec import decode_op

_MAGIC = b"DKV1"


class DeviceKVStateMachine(IStateMachine):
    """Fixed-width replicated KV: ``kv_slots`` int32 value cells,
    commands are :func:`devsm.codec.encode_op` SETs, lookups take an int
    key slot and return the int value.

    Registration: pass the class (or a factory returning instances) to
    ``NodeHost.start_cluster`` with ``Config.device_kv=True`` on the tpu
    engine — the NodeHost registers the group with the coordinator's
    :class:`~dragonboat_tpu.devsm.plane.DevKVPlane` and the apply stage
    moves into the fused program.  Without the flag the same class runs
    as an ordinary host SM (the default-OFF contract).
    """

    #: registration marker the NodeHost checks (duck-typed so wrappers
    #: and factories can carry it without subclassing)
    device_kv = True
    #: the numpy-shadow half is process-spawnable (ISSUE 12): when the
    #: group runs WITHOUT ``Config.device_kv`` (plain host SM) and
    #: ``host_workers > 0``, the hostproc apply tier may host it — the
    #: NodeHost never proxies a device-BOUND machine (the devsm plane IS
    #: its apply offload)
    __hostproc_spawnable__ = True
    #: value slots; must fit the engine's ``n_kv_slots`` width
    kv_slots = KV_SLOTS

    def __init__(self, cluster_id: int, node_id: int):
        self.cluster_id = cluster_id
        self.node_id = node_id
        self.values = np.zeros(self.kv_slots, dtype=np.int64)
        # wired by DevKVPlane.register (NodeHost start_cluster); None =
        # pure host SM, every path below short-circuits on it
        self._plane = None

    # ------------------------------------------------------------------
    # IStateMachine
    # ------------------------------------------------------------------

    def update(self, cmd: bytes) -> Result:
        """Apply one SET to the host shadow.  Runs on EVERY replica —
        including a device-bound leader, where it is a single numpy cell
        write off the read path: the shadow is what makes leadership
        transitions, snapshots and the devsm-off oracle trivially
        correct.  Commands that don't parse (or point outside the slot
        range) are no-ops on both planes, so shadow and device state can
        never diverge over one."""
        op = decode_op(cmd)
        if op is None:
            return Result(value=0)
        key, value = op
        if not (0 <= key < self.kv_slots):
            return Result(value=0)
        self.values[key] = value
        return Result(value=value & 0xFFFFFFFF)

    def lookup(self, query: object) -> object:
        """Value of key slot ``query``.  Device-bound groups serve from
        device state (a staged KV read captured by the next fused
        dispatch — zero host apply on the path); otherwise the host
        shadow answers, gated by the plane so a device-released read
        never outruns the shadow."""
        key = int(query)
        if not (0 <= key < self.kv_slots):
            raise KeyError(f"kv key slot {key} out of range")
        plane = self._plane
        if plane is not None:
            return plane.lookup(self.cluster_id, key, self)
        return int(self.values[key])

    def save_snapshot(self, w: BinaryIO, files, done) -> None:
        w.write(_MAGIC)
        w.write(np.int64(self.kv_slots).tobytes())
        w.write(self.values.astype("<i8").tobytes())

    def recover_from_snapshot(
        self, r: BinaryIO, files: List[SnapshotFile], done
    ) -> None:
        magic = r.read(4)
        if magic != _MAGIC:
            raise ValueError(f"bad devsm snapshot magic {magic!r}")
        hdr = r.read(8)
        if len(hdr) != 8:
            raise ValueError("truncated devsm snapshot header")
        n = int(np.frombuffer(hdr, dtype="<i8")[0])
        # validate the width BEFORE the body read (a corrupt header must
        # not drive a giant allocation) and the body length BEFORE any
        # mutation (a truncated body must not leave a half-wiped SM)
        if not (0 <= n <= self.kv_slots):
            raise ValueError(
                f"devsm snapshot width {n} outside [0, {self.kv_slots}]"
            )
        body = r.read(8 * n)
        if len(body) != 8 * n:
            raise ValueError("truncated devsm snapshot body")
        vals = np.frombuffer(body, dtype="<i8").astype(np.int64)
        self.values[:] = 0
        self.values[:n] = vals
        plane = self._plane
        if plane is not None:
            plane.on_restore(self.cluster_id)

    def close(self) -> None:
        plane, self._plane = self._plane, None
        if plane is not None:
            plane.unregister(self.cluster_id)
