"""Coordinator-side manager of device-bound KV groups.

One :class:`DevKVPlane` per :class:`~dragonboat_tpu.tpuquorum.TpuQuorumCoordinator`
(created lazily by the first registration).  It owns three protocols:

**Leadership-scoped binding.**  Device KV state is leader-row state: only
the leader stages entry ops (at ``append_entries``), so only a leading
host's row holds live values.  At promotion the plane records the bind
watermark B = the leader's ``last_index`` (every entry <= B predates op
staging; every entry > B WILL be staged).  Once host apply catches B,
the shadow — which then covers exactly the unstaged prefix — uploads as
the row's KV image and buffered ops flush.  Ops in (B, applied] may both
ride the shadow and restage: re-applying a contiguous suffix of SETs in
log order is idempotent, so the overlap is harmless (the torn-snapshot
argument lives in ``try_bind``).  Any transition away from leadership
unbinds; the shadow (warm on every replica) makes rebinding cheap and
device pulls unnecessary.

**Entry-op staging.**  ``raft.append_entries`` offloads application
entries under raftMu; the coordinator drain hands them here, where the
fixed-width codec filters real ops (session/config/noop entries fall
out) and the engine buffers them for the fused apply fold.

**The KV read service.**  A lookup on a bound group stages a device KV
read and parks on an event; the round that captures it resolves the
waiter from the harvest egress (``StepResult.kv_reads``).  Fallbacks
(unbound, slot backpressure, timeout) serve the host shadow — gated on
host apply reaching the group's device-release floor, so a read released
at the device watermark never reads a stale shadow.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..logger import get_logger
from .codec import decode_op

dlog = get_logger("devsm")

#: device-capture wait before the shadow fallback takes over (a capture
#: normally lands within one coordinator round, ~ms)
READ_TIMEOUT_S = 5.0


class DevKVPlane:
    """Per-coordinator devsm manager.  Engine access happens under the
    coordinator's ``_mu`` (drain context or explicitly taken); waiter
    bookkeeping under the plane's own lock."""

    def __init__(self, coord):
        self.coord = coord
        self._mu = threading.Lock()
        self._sms: Dict[int, object] = {}          # cid -> machine
        self._bound: set = set()
        self._pending_bind: Dict[int, int] = {}    # cid -> bind watermark B
        self._prebind_ops: Dict[int, List[Tuple[int, int, int]]] = {}
        # (cid, slot) -> [event, value, index]
        self._waiters: Dict[Tuple[int, int], list] = {}
        # observability (read by tests/bench; devsm metric families are
        # published by the ENGINE's apply_kernel/devsm_egress spans)
        self.ops_staged = 0
        self.reads_served = 0
        self.read_fallbacks = 0
        self.binds = 0
        # per-group bind counts (cluster health plane, ISSUE 13): the
        # devsm-rebind detector needs per-group increments, not the
        # plane-wide total
        self._bind_counts: Dict[int, int] = {}

    # ------------------------------------------------------------------
    # registration (NodeHost.start_cluster wiring)
    # ------------------------------------------------------------------

    def register(self, cluster_id: int, sm) -> None:
        """Bind a :class:`DeviceKVStateMachine` instance to its group.
        Kicks the devsm program warmup so the first kv-carrying fused
        dispatch never stalls behind XLA (the warmup_fused contract)."""
        if sm.kv_slots > self.coord.eng.n_kv_slots:
            raise ValueError(
                f"kv_slots {sm.kv_slots} exceeds engine width "
                f"{self.coord.eng.n_kv_slots}"
            )
        with self._mu:
            self._sms[cluster_id] = sm
            sm._plane = self
        if self.coord.drive_ticks and self.coord.mesh_devices <= 1:
            self.coord.eng.warmup_devsm()

    def unregister(self, cluster_id: int) -> None:
        with self._mu:
            sm = self._sms.pop(cluster_id, None)
            if sm is not None:
                sm._plane = None
            self._bound.discard(cluster_id)
            self._pending_bind.pop(cluster_id, None)
            self._prebind_ops.pop(cluster_id, None)
            self._bind_counts.pop(cluster_id, None)
            self._flush_waiters_locked(cluster_id)

    def tracks(self, cluster_id: int) -> bool:
        return cluster_id in self._sms

    def bound(self, cluster_id: int) -> bool:
        """True while the group's reads/applies are device-served (the
        node's read-release gate checks this per commit offload)."""
        return cluster_id in self._bound

    def health_snapshot(self, cluster_id: int) -> Optional[dict]:
        """One group's devsm status for the cluster health sampler
        (ISSUE 13): binding state, pending bind watermark and the
        per-group bind count the rebind-loop detector differentiates."""
        with self._mu:
            if cluster_id not in self._sms:
                return None
            return {
                "bound": cluster_id in self._bound,
                "pending_bind": self._pending_bind.get(cluster_id),
                "binds": self._bind_counts.get(cluster_id, 0),
            }

    def devprof_snapshot(self) -> dict:
        """Plane-level residency for the device profiling plane (ISSUE
        15): the device-side kv slabs are already priced by the engine's
        HBM ledger — what only this plane knows is the HOST-side shadow
        residency (one warm numpy image per registered SM on every
        replica) and how many groups are actually device-serving."""
        with self._mu:
            shadow = 0
            for sm in self._sms.values():
                vals = getattr(sm, "values", None)
                if vals is not None and hasattr(vals, "nbytes"):
                    shadow += int(vals.nbytes)
            return {
                "groups": len(self._sms),
                "bound": len(self._bound),
                "pending_binds": len(self._pending_bind),
                "shadow_bytes": shadow,
                "binds": self.binds,
                "reads_served": self.reads_served,
                "read_fallbacks": self.read_fallbacks,
            }

    # ------------------------------------------------------------------
    # leadership transitions (coordinator drain, under coord._mu)
    # ------------------------------------------------------------------

    def on_leader(self, cluster_id: int, last_index: int) -> None:
        """This host took the lease on the group's apply plane: arm the
        bind at watermark B = the promotion ``last_index`` (includes the
        term-start noop; every later append stages its ops)."""
        if cluster_id not in self._sms:
            return
        with self._mu:
            self._bound.discard(cluster_id)
            self._prebind_ops[cluster_id] = []
            self._pending_bind[cluster_id] = last_index
            self._flush_waiters_locked(cluster_id)
        self._try_bind(cluster_id)

    def on_unbind(self, cluster_id: int) -> None:
        """Leadership moved (follower/candidate/resync): device serving
        stops, parked readers fall back to the gated shadow."""
        if cluster_id not in self._sms:
            return
        with self._mu:
            self._bound.discard(cluster_id)
            self._pending_bind.pop(cluster_id, None)
            self._prebind_ops.pop(cluster_id, None)
            self._flush_waiters_locked(cluster_id)

    def on_restore(self, cluster_id: int) -> None:
        """Snapshot recover on a bound group (rare: a leader restoring):
        the shadow is the new truth — re-upload it."""
        coord = self.coord
        with coord._mu:
            if cluster_id in self._bound and cluster_id in coord.eng.groups:
                sm = self._sms.get(cluster_id)
                if sm is not None:
                    self._upload_shadow(cluster_id, sm)

    def _upload_shadow(self, cluster_id: int, sm) -> None:
        eng = self.coord.eng
        vals = np.zeros(eng.n_kv_slots, dtype=np.int64)
        vals[: sm.kv_slots] = sm.values
        eng.kv_restore(cluster_id, vals)

    def poll(self) -> None:
        """Advance pending binds (called per coordinator round, under
        coord._mu)."""
        if not self._pending_bind:
            return
        for cid in list(self._pending_bind):
            self._try_bind(cid)

    def _try_bind(self, cluster_id: int) -> None:
        """Complete a pending bind once host apply reaches the bind
        watermark.  The shadow copy may tear against the concurrent
        apply executor, but any op it could miss has index > B — and
        every such op is staged to the device, so the re-apply (a
        contiguous suffix of SETs in log order over a superset image)
        reconverges exactly.  Caller holds coord._mu."""
        b = self._pending_bind.get(cluster_id)
        if b is None:
            return
        node = self.coord._nodes.get(cluster_id)
        sm = self._sms.get(cluster_id)
        if node is None or sm is None:
            return
        try:
            applied = node.sm.get_last_applied()
        except Exception:
            return
        if applied < b:
            return
        eng = self.coord.eng
        if cluster_id not in eng.groups:
            return
        with self._mu:
            if self._pending_bind.pop(cluster_id, None) is None:
                return
            # ops at or below the watermark are already inside the shadow
            # image (and may be OLDER than later shadow writes for the
            # same key) — only the suffix above B restages
            buffered = [
                op for op in self._prebind_ops.pop(cluster_id, [])
                if op[0] > b
            ]
            try:
                self._upload_shadow(cluster_id, sm)
                staged_all = True
                if buffered:
                    idx, keys, vals = zip(*buffered)
                    staged_all = eng.stage_kv_ops(
                        cluster_id, list(idx), list(keys), list(vals)
                    )
                    self.ops_staged += len(buffered)
            except (ValueError, KeyError) as e:
                # out-of-window index / vanished group: stay unbound, the
                # shadow keeps serving; a later promotion re-arms cleanly
                # (raising here would abort the coordinator round)
                dlog.warning(
                    "devsm bind flush failed for %d: %r", cluster_id, e
                )
                return
            if not staged_all:
                # the flush itself overflowed the entry buffers: binding
                # now would reopen the stale-read window handle_ops
                # unbinds over (a queued op can commit before it applies)
                # — re-arm past the batch and keep host-serving instead
                self._prebind_ops[cluster_id] = []
                self._pending_bind[cluster_id] = buffered[-1][0]
                dlog.info(
                    "devsm bind flush overflowed on group %d: re-armed "
                    "at %d", cluster_id, buffered[-1][0],
                )
                return
            self._bound.add(cluster_id)
            self.binds += 1
            self._bind_counts[cluster_id] = (
                self._bind_counts.get(cluster_id, 0) + 1
            )
        dlog.info(
            "devsm bound group %d at watermark %d (%d buffered ops)",
            cluster_id, b, len(buffered),
        )

    # ------------------------------------------------------------------
    # entry-op staging (coordinator drain, under coord._mu)
    # ------------------------------------------------------------------

    def handle_ops(self, cluster_id: int, ops) -> None:
        """Application entries offloaded from ``append_entries``:
        ``ops`` is ``[(index, payload), ...]`` in log order.  Non-op
        payloads fall out here exactly as they no-op in the shadow's
        ``update`` — the two planes stay in lockstep by construction."""
        if cluster_id not in self._sms:
            return
        decoded = []
        for index, payload in ops:
            op = decode_op(payload)
            if op is None:
                continue
            key, value = op
            sm = self._sms.get(cluster_id)
            if sm is None or not (0 <= key < sm.kv_slots):
                continue
            decoded.append((index, key, value))
        if not decoded:
            return
        with self._mu:
            pre = self._prebind_ops.get(cluster_id)
            if pre is not None:
                pre.extend(decoded)
                return
            if cluster_id not in self._bound:
                return  # not leading here; followers never stage
        try:
            idx, keys, vals = zip(*decoded)
            staged_all = self.coord.eng.stage_kv_ops(
                cluster_id, list(idx), list(keys), list(vals)
            )
            self.ops_staged += len(decoded)
        except (ValueError, KeyError) as e:
            # out-of-window index (rebase race) or a vanished group:
            # unbind — the shadow keeps applying, a later promotion
            # rebinds cleanly
            dlog.warning("devsm staging failed for %d: %r", cluster_id, e)
            self.on_unbind(cluster_id)
            return
        if not staged_all:
            # entry-buffer overflow: a queued op may COMMIT before it
            # applies, so the device value plane would momentarily trail
            # the watermark the read-release gate uses — a stale-read
            # window.  Serve from the (always-current) host shadow until
            # host apply passes this batch, then rebind: same protocol
            # as a promotion, with the batch tail as the watermark.
            dlog.info(
                "devsm overflow on group %d: host-serving until apply "
                "reaches %d, then rebinding", cluster_id, decoded[-1][0],
            )
            with self._mu:
                self._bound.discard(cluster_id)
                self._prebind_ops[cluster_id] = []
                self._pending_bind[cluster_id] = decoded[-1][0]
                self._flush_waiters_locked(cluster_id)

    # ------------------------------------------------------------------
    # the KV read service
    # ------------------------------------------------------------------

    def lookup(self, cluster_id: int, key: int, sm) -> int:
        """Serve one read.  Bound groups stage a device KV read and park
        until the capturing round resolves it; everything else (and
        every fallback) reads the host shadow behind the release-floor
        gate."""
        coord = self.coord
        if cluster_id in self._bound:
            waiter = [threading.Event(), None, None]
            slot = None
            with coord._mu:
                if cluster_id in self._bound and (
                    cluster_id in coord.eng.groups
                ):
                    try:
                        slot = coord.eng.stage_kv_read(cluster_id, key)
                    except RuntimeError:
                        slot = None  # backpressure: all capture slots busy
                    if slot is not None:
                        with self._mu:
                            self._waiters[(cluster_id, slot)] = waiter
            if slot is not None:
                coord._pending.set()
                if waiter[0].wait(READ_TIMEOUT_S) and waiter[1] is not None:
                    self.reads_served += 1
                    return int(waiter[1])
                with self._mu:
                    self._waiters.pop((cluster_id, slot), None)
        # shadow fallback, gated: a read released at the DEVICE commit
        # watermark must not read a shadow that host apply hasn't caught
        # up to yet (the unbind-between-release-and-lookup race)
        self.read_fallbacks += 1
        node = coord._nodes.get(cluster_id)
        floor = getattr(node, "devsm_release_floor", 0) if node else 0
        if floor:
            deadline = time.monotonic() + READ_TIMEOUT_S
            while time.monotonic() < deadline:
                try:
                    if node.sm.get_last_applied() >= floor:
                        break
                except Exception:
                    break
                time.sleep(0.001)
        return int(sm.values[key])

    def deliver(self, res) -> None:
        """Resolve parked readers from a harvest's capture egress
        (round thread, outside coord._mu)."""
        if res is None or res.kv_cids is None:
            return
        for cid, slot, value, index in res.kv_reads:
            with self._mu:
                waiter = self._waiters.pop((cid, slot), None)
            if waiter is not None:
                waiter[1] = value
                waiter[2] = index
                waiter[0].set()

    def _flush_waiters_locked(self, cluster_id: int) -> None:
        """Wake a group's parked readers empty-handed (they take the
        gated shadow fallback).  Caller holds self._mu."""
        for key in [k for k in self._waiters if k[0] == cluster_id]:
            waiter = self._waiters.pop(key)
            waiter[0].set()
