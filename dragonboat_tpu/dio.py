"""Compression stream utilities.

Reference: ``internal/utils/dio/io.go`` — ``CompressionType``,
``CountedWriter`` and the Compressor/Decompressor WriteCloser pair used by
the snapshot file writer and the streaming chunk path.  The codec here is
the pure-Python snappy block format (:mod:`dragonboat_tpu.snappy`); streams
are framed as repeated ``[u32 compressed_len][compressed block]`` with 1MB
uncompressed blocks (the reference uses the snappy streaming format — the
framing differs, the block payloads are standard snappy; documented in the
snapshot header's compression_type field so files are self-describing).
"""
from __future__ import annotations

import enum
import struct
from typing import BinaryIO

from . import snappy

_U32 = struct.Struct("<I")
BLOCK_SIZE = 1024 * 1024


class CompressionType(enum.IntEnum):
    """Twin of the reference dio.CompressionType / config.CompressionType."""

    NO_COMPRESSION = 0
    SNAPPY = 1


def max_block_len(ct: CompressionType) -> int:
    if ct == CompressionType.SNAPPY:
        return snappy.MAX_BLOCK_LEN
    return (1 << 63) - 1


def max_encoded_len(ct: CompressionType, n: int) -> int:
    if ct == CompressionType.SNAPPY:
        return snappy.max_encoded_len(n)
    return n


def compress_snappy_block(data) -> bytes:
    return snappy.compress(data)


def decompress_snappy_block(data) -> bytes:
    return snappy.decompress(data)


class CountedWriter:
    """Byte-counting WriteCloser wrapper (reference ``io.go:38-70``)."""

    def __init__(self, w):
        self._w = w
        self._total = 0
        self._closed = False

    def write(self, data) -> int:
        self._total += len(data)
        self._w.write(data)
        return len(data)

    def close(self) -> None:
        self._closed = True
        if hasattr(self._w, "close"):
            self._w.close()

    def bytes_written(self) -> int:
        if not self._closed:
            raise RuntimeError("BytesWritten called before close")
        return self._total


class Compressor:
    """Write-side compression stream (reference ``io.go`` Compressor).

    Buffers writes into BLOCK_SIZE uncompressed blocks; each block is
    snappy-compressed and framed with its compressed length.
    """

    def __init__(self, ct: CompressionType, w):
        self.ct = CompressionType(ct)
        self._w = w
        self._buf = bytearray()
        self._closed = False

    def write(self, data) -> int:
        if self._closed:
            raise ValueError("write on closed Compressor")
        if self.ct == CompressionType.NO_COMPRESSION:
            self._w.write(data)
            return len(data)
        self._buf += data
        while len(self._buf) >= BLOCK_SIZE:
            self._flush_block(self._buf[:BLOCK_SIZE])
            del self._buf[:BLOCK_SIZE]
        return len(data)

    def _flush_block(self, block) -> None:
        comp = snappy.compress(block)
        self._w.write(_U32.pack(len(comp)))
        self._w.write(comp)

    def close(self) -> None:
        if self._closed:
            return
        if self.ct == CompressionType.SNAPPY and self._buf:
            self._flush_block(bytes(self._buf))
            self._buf.clear()
        self._closed = True


class Decompressor:
    """Read-side decompression stream (reference ``io.go`` Decompressor)."""

    def __init__(self, ct: CompressionType, r: BinaryIO):
        self.ct = CompressionType(ct)
        self._r = r
        self._buf = bytearray()

    def _fill(self) -> bool:
        hdr = self._r.read(_U32.size)
        if not hdr:
            return False
        if len(hdr) != _U32.size:
            raise snappy.SnappyError("truncated block header")
        (clen,) = _U32.unpack(hdr)
        comp = self._r.read(clen)
        if len(comp) != clen:
            raise snappy.SnappyError("truncated block")
        self._buf += snappy.decompress(comp)
        return True

    def read(self, n: int = -1) -> bytes:
        if self.ct == CompressionType.NO_COMPRESSION:
            return self._r.read(n)
        if n is None or n < 0:
            while self._fill():
                pass
            out = bytes(self._buf)
            self._buf.clear()
            return out
        while len(self._buf) < n:
            if not self._fill():
                break
        out = bytes(self._buf[:n])
        del self._buf[:n]
        return out

    def close(self) -> None:
        pass
