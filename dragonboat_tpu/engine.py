"""Execution engine: the scheduler driving step/apply work across groups.

Reference: ``execengine.go`` — step/apply/snapshot worker pools with groups
partitioned to workers by ``clusterID % workerCount`` and per-worker
``workReady`` wakeups.  The Python build keeps the same structure with
smaller default pools (GIL), and this is exactly the seam the batched TPU
quorum engine replaces: ``process_steps``'s per-group loop becomes one
device dispatch per tick (SURVEY.md §7).
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple, TYPE_CHECKING

from .logger import get_logger
from .queue import ReadyCluster
from .server.partition import FixedPartitioner

if TYPE_CHECKING:
    from .node import Node

plog = get_logger("engine")


class _WorkReady:
    """Per-worker ready-set + wakeup (reference ``execengine.go:90-132``)."""

    def __init__(self, count: int):
        self.count = count
        self.partitioner = FixedPartitioner(count)
        self.ready = [ReadyCluster() for _ in range(count)]
        self.cv = [threading.Condition() for _ in range(count)]
        self.flag = [False] * count

    def notify(self, idx: int) -> None:
        with self.cv[idx]:
            self.flag[idx] = True
            self.cv[idx].notify()

    def cluster_ready(self, cluster_id: int) -> None:
        idx = self.partitioner.get_partition_id(cluster_id)
        self.ready[idx].set_ready(cluster_id)
        self.notify(idx)

    def all_ready(self, idx: int) -> None:
        self.notify(idx)

    def wait(self, idx: int, timeout: float = 1.0) -> None:
        with self.cv[idx]:
            if not self.flag[idx]:
                self.cv[idx].wait(timeout)
            self.flag[idx] = False

    def get_ready(self, idx: int):
        return self.ready[idx].get_ready()


class _Committer:
    """Per-step-worker LogDB commit pipeline (one per shard when shards are
    worker-aligned).

    The reference's step worker blocks in ``SaveRaftState``
    (``execengine.go:966``) — affordable with Go's goroutine count and an
    Optane fsync; here a synchronous fsync in the step loop serializes every
    group on the worker behind every commit.  Instead the worker hands
    ``(pairs, updates)`` off and keeps stepping other groups; this thread
    **coalesces everything queued into one fsynced write batch** (classic
    group commit — same effect as the reference's one-WriteBatch-per-round
    geometry, ``rdb.go:187-210``) and then runs the post-fsync half of the
    round (non-Replicate messages out, committed entries to apply,
    ``Peer.Commit``) in submission order.  Per-group ordering is preserved
    by the node's ``commit_inflight`` flag: a group is never stepped again
    until its previous update has been committed.
    """

    def __init__(self, engine: "Engine", idx: int):
        self.engine = engine
        self.idx = idx
        self._q: List = []
        self._cv = threading.Condition()
        # diagnostics (read by Engine.stats)
        self.cycles = 0
        self.merged = 0
        self.commit_s = 0.0
        self.post_s = 0.0
        self._thread = threading.Thread(
            target=self._main, name=f"committer-{idx}", daemon=True
        )
        self._thread.start()

    def submit(self, pairs, updates) -> None:
        with self._cv:
            self._q.append((pairs, updates))
            self._cv.notify()

    def _main(self) -> None:
        stopped = self.engine._stopped
        while True:
            with self._cv:
                while not self._q and not stopped.is_set():
                    self._cv.wait(0.2)
                if stopped.is_set() and not self._q:
                    return
                batch, self._q = self._q, []
            try:
                self._commit(batch)
            except Exception:
                plog.exception("committer %d failed", self.idx)
                # clear flags AND re-arm the groups (their ready bits were
                # consumed before the submit) so they retry immediately
                # instead of stalling until the next tick
                for pairs, _ in batch:
                    for n, _ in pairs:
                        n.commit_inflight = False
                        self.engine.set_step_ready(n.cluster_id)

    def _commit(self, batch) -> None:
        import time as _time

        t0 = _time.perf_counter()
        merged = [ud for _, updates in batch for ud in updates]
        if merged:
            hp = self.engine.hostplane
            if hp is not None:
                # cross-shard group-commit tier: the shared flusher merges
                # this committer's batch with every other committer's into
                # one fsync cycle; returns only once durable, then the
                # post-fsync half below runs here, concurrently with the
                # other committers' halves (per-group ordering untouched —
                # a group only ever rides its owning committer)
                hp.wal.flush(merged)
            else:
                self.engine.logdb.save_raft_state(merged)
        t1 = _time.perf_counter()
        tr = self.engine.tracer
        if tr is not None and merged:
            # the merged batch is durable here — whichever tier fsynced
            # it (group-commit WAL or the classic per-committer save)
            tr.mark_updates(merged, "wal")
        for pairs, _ in batch:
            for n, ud in pairs:
                n.process_raft_update(ud)
                n.commit_raft_update(ud)
                n.commit_inflight = False
                # re-check inputs that arrived while the commit was in
                # flight (the step worker skipped this group meanwhile)
                self.engine.set_step_ready(n.cluster_id)
        self.cycles += 1
        self.merged += len(merged)
        self.commit_s += t1 - t0
        self.post_s += _time.perf_counter() - t1

    def join(self, timeout: float = 2.0) -> None:
        with self._cv:
            self._cv.notify()
        self._thread.join(timeout=timeout)


class Engine:
    """Reference ``execengine.go:637`` ``execEngine``."""

    def __init__(
        self,
        get_nodes,  # Callable[[], Tuple[int, Dict[int, Node]]] → (csi, map)
        logdb,
        step_workers: int = 4,
        apply_workers: int = 4,
        get_csi=None,  # cheap cluster-set-index read; avoids the locked
        # dict copy in get_nodes on every worker wakeup when nothing changed
        hostplane=None,  # compartmentalized host plane (hostplane.py):
        # committers persist through its shared group-commit flusher and
        # apply readiness routes to its dedicated pool; None keeps the
        # classic per-committer fsync + in-engine apply workers
    ):
        self.get_nodes = get_nodes
        self.get_csi = get_csi
        self.logdb = logdb
        self.hostplane = hostplane
        # cross-plane request tracer (obs/trace.py, ISSUE 9; set by
        # NodeHost): committers stamp the "wal" stage on sampled entries
        # after their fsync.  None keeps the commit path bit-identical.
        self.tracer = None
        self._stopped = threading.Event()
        self.step_ready = _WorkReady(step_workers)
        self.apply_ready = _WorkReady(apply_workers)
        self._threads: List[threading.Thread] = []
        # per-worker node-map cache, reloaded when the cluster-set index
        # changes (reference loadBucketNodes execengine.go:889)
        self._step_cache: List = [(-1, {}) for _ in range(step_workers)]
        self._apply_cache: List = [(-1, {}) for _ in range(apply_workers)]
        # diagnostics per step worker: [rounds, groups_stepped, skipped,
        # step_s]
        self._step_stats = [[0, 0, 0, 0.0] for _ in range(step_workers)]
        self._committers = [_Committer(self, i) for i in range(step_workers)]
        # dedicated snapshot worker pool (reference execengine.go:240-635,
        # 64 workers): multi-second SM save/recover/stream work must never
        # block the apply workers — a slow user snapshot on one group would
        # stall every group sharing that apply worker
        import queue as _queue

        self._ss_q: "_queue.Queue" = _queue.Queue()
        snapshot_workers = max(2, min(8, step_workers * 2))
        for i in range(snapshot_workers):
            t = threading.Thread(
                target=self._snapshot_worker_main,
                name=f"snapshot-worker-{i}", daemon=True,
            )
            t.start()
            self._threads.append(t)
        for i in range(step_workers):
            t = threading.Thread(
                target=self._step_worker_main, args=(i,),
                name=f"step-worker-{i}", daemon=True,
            )
            t.start()
            self._threads.append(t)
        # with the host plane attached, apply readiness routes to its
        # dedicated pool — the in-engine apply workers would never be
        # signalled, so don't spawn them (thread budget matters on the
        # 1-vCPU box)
        for i in range(0 if hostplane is not None else apply_workers):
            t = threading.Thread(
                target=self._apply_worker_main, args=(i,),
                name=f"apply-worker-{i}", daemon=True,
            )
            t.start()
            self._threads.append(t)

    # ---- wakeups (reference setStepReady / setApplyReady) ----

    def set_step_ready(self, cluster_id: int) -> None:
        self.step_ready.cluster_ready(cluster_id)

    def set_apply_ready(self, cluster_id: int) -> None:
        hp = self.hostplane
        if hp is not None:
            # decoupled apply executor (sharded by group, order preserved)
            hp.apply_pool.submit(cluster_id)
            return
        self.apply_ready.cluster_ready(cluster_id)

    def notify_all(self) -> None:
        for i in range(self.step_ready.count):
            self.step_ready.notify(i)
        for i in range(self.apply_ready.count):
            self.apply_ready.notify(i)

    def _worker_nodes(
        self, cache: List, idx: int, partitioner: FixedPartitioner
    ) -> Dict[int, "Node"]:
        cached_csi, cached = cache[idx]
        if self.get_csi is not None and self.get_csi() == cached_csi:
            return cached
        csi, nodes = self.get_nodes()
        if cached_csi == csi:
            return cached
        mine = {
            cid: n
            for cid, n in nodes.items()
            if partitioner.get_partition_id(cid) == idx
        }
        cache[idx] = (csi, mine)
        return mine

    def _rearm_unknown(self, ready, nodes, work_ready) -> None:
        """Defense in depth against lost wakeups: a ready bit consumed for
        a cid the worker's map does not know is RE-ARMED when the
        authoritative map knows it (a signal racing cluster registration
        would otherwise be dropped — consumed bit, no retry — and a
        one-shot wakeup like the initial-recovery task is lost forever).
        A cid unknown to the authoritative map (stopped cluster) stays
        dropped."""
        missing = [cid for cid in ready if cid not in nodes]
        if not missing:
            return
        _, all_nodes = self.get_nodes()
        for cid in missing:
            if cid in all_nodes:
                work_ready.cluster_ready(cid)

    # ---- step path (reference stepWorkerMain/processSteps :860-1010) ----

    def _step_worker_main(self, idx: int) -> None:
        import os

        if idx == 0 and os.environ.get("DBTPU_CPROFILE_STEP"):
            # diagnostics: profile one step worker, dump on engine stop
            import cProfile

            self._prof = cProfile.Profile()
            self._prof.enable()
        while not self._stopped.is_set():
            self.step_ready.wait(idx)
            if self._stopped.is_set():
                return
            nodes = self._worker_nodes(
                self._step_cache, idx, self.step_ready.partitioner
            )
            ready = self.step_ready.get_ready(idx)
            self._rearm_unknown(ready, nodes, self.step_ready)
            active = [nodes[cid] for cid in ready if cid in nodes]
            if active:
                try:
                    import time as _time

                    st = self._step_stats[idx]
                    t0 = _time.perf_counter()
                    stepped, skipped = self.process_steps(
                        active, self._committers[idx]
                    )
                    st[0] += 1
                    st[1] += stepped
                    st[2] += skipped
                    st[3] += _time.perf_counter() - t0
                except Exception:
                    plog.exception("step worker %d failed", idx)

    def process_steps(
        self, active: List["Node"], committer: Optional[_Committer] = None
    ) -> Tuple[int, int]:
        """The hot loop (reference ``processSteps`` ``execengine.go:923``):
        step → send replicates → one batched fsync → execute → commit.

        The fsync + post-fsync half is pipelined through the worker's
        committer (see :class:`_Committer`); groups whose previous update is
        still being committed are skipped and re-scheduled by the committer,
        so per-group round ordering is untouched.  Message-only updates
        (heartbeats) bypass the committer entirely — nothing to persist, no
        reason to ride behind an fsync.
        """
        pairs = []
        skipped = 0
        for n in active:
            if n.commit_inflight:
                skipped += 1
                continue
            ud = n.step_node()
            if ud is not None:
                pairs.append((n, ud))
        if not pairs:
            return len(pairs), skipped
        for n, ud in pairs:
            n.process_dropped(ud)
            n.send_replicate_messages(ud)  # before fsync (thesis §10.2.1)
        # only updates that can put a record on disk need the committer;
        # the rest complete inline
        persist = []
        updates = []
        inline = []
        for n, ud in pairs:
            if (
                ud.entries_to_save
                or not ud.state.is_empty()
                or (ud.snapshot is not None and not ud.snapshot.is_empty())
            ):
                persist.append((n, ud))
                updates.append(ud)
            else:
                inline.append((n, ud))
        for n, ud in inline:
            n.process_raft_update(ud)
            n.commit_raft_update(ud)
        if persist:
            if committer is not None:
                for n, _ in persist:
                    n.commit_inflight = True
                committer.submit(persist, updates)
            else:
                self.logdb.save_raft_state(updates)
                tr = self.tracer
                if tr is not None:
                    tr.mark_updates(updates, "wal")
                for n, ud in persist:
                    n.process_raft_update(ud)
                    n.commit_raft_update(ud)
        return len(pairs), skipped

    def stats(self) -> dict:
        """Diagnostic counters (benchmarks; not part of the public API)."""
        return {
            "step_workers": [
                {
                    "rounds": s[0],
                    "groups_stepped": s[1],
                    "skipped_inflight": s[2],
                    "step_s": round(s[3], 3),
                }
                for s in self._step_stats
            ],
            "committers": [
                {
                    "cycles": c.cycles,
                    "merged_updates": c.merged,
                    "commit_s": round(c.commit_s, 3),
                    "post_s": round(c.post_s, 3),
                }
                for c in self._committers
            ],
        }

    # ---- apply path (reference applyWorkerMain/processApplies :794-858) ----

    def _apply_worker_main(self, idx: int) -> None:
        while not self._stopped.is_set():
            self.apply_ready.wait(idx)
            if self._stopped.is_set():
                return
            nodes = self._worker_nodes(
                self._apply_cache, idx, self.apply_ready.partitioner
            )
            ready = self.apply_ready.get_ready(idx)
            self._rearm_unknown(ready, nodes, self.apply_ready)
            for cid in ready:
                n = nodes.get(cid)
                if n is None:
                    continue
                try:
                    n.handle_apply_tasks()
                except Exception:
                    plog.exception("apply worker %d failed on %d", idx, cid)

    def submit_snapshot(self, fn) -> None:
        """Queue snapshot save/stream work onto the dedicated pool."""
        self._ss_q.put(fn)

    def _snapshot_worker_main(self) -> None:
        while True:
            fn = self._ss_q.get()
            if fn is None or self._stopped.is_set():
                return
            try:
                fn()
            except Exception:
                plog.exception("snapshot worker task failed")

    def stop(self) -> None:
        import os

        if getattr(self, "_prof", None) is not None:
            self._prof.disable()
            path = os.environ.get("DBTPU_CPROFILE_STEP")
            try:
                self._prof.dump_stats(path)
            except Exception:
                pass
            self._prof = None
        self._stopped.set()
        self.notify_all()
        for _ in range(32):  # wake every snapshot worker
            self._ss_q.put(None)
        for c in self._committers:
            c.join()
        for t in self._threads:
            t.join(timeout=2)
