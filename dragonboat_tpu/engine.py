"""Execution engine: the scheduler driving step/apply work across groups.

Reference: ``execengine.go`` — step/apply/snapshot worker pools with groups
partitioned to workers by ``clusterID % workerCount`` and per-worker
``workReady`` wakeups.  The Python build keeps the same structure with
smaller default pools (GIL), and this is exactly the seam the batched TPU
quorum engine replaces: ``process_steps``'s per-group loop becomes one
device dispatch per tick (SURVEY.md §7).
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, TYPE_CHECKING

from .logger import get_logger
from .queue import ReadyCluster
from .server.partition import FixedPartitioner

if TYPE_CHECKING:
    from .node import Node

plog = get_logger("engine")


class _WorkReady:
    """Per-worker ready-set + wakeup (reference ``execengine.go:90-132``)."""

    def __init__(self, count: int):
        self.count = count
        self.partitioner = FixedPartitioner(count)
        self.ready = [ReadyCluster() for _ in range(count)]
        self.cv = [threading.Condition() for _ in range(count)]
        self.flag = [False] * count

    def notify(self, idx: int) -> None:
        with self.cv[idx]:
            self.flag[idx] = True
            self.cv[idx].notify()

    def cluster_ready(self, cluster_id: int) -> None:
        idx = self.partitioner.get_partition_id(cluster_id)
        self.ready[idx].set_ready(cluster_id)
        self.notify(idx)

    def all_ready(self, idx: int) -> None:
        self.notify(idx)

    def wait(self, idx: int, timeout: float = 1.0) -> None:
        with self.cv[idx]:
            if not self.flag[idx]:
                self.cv[idx].wait(timeout)
            self.flag[idx] = False

    def get_ready(self, idx: int):
        return self.ready[idx].get_ready()


class Engine:
    """Reference ``execengine.go:637`` ``execEngine``."""

    def __init__(
        self,
        get_nodes,  # Callable[[], Tuple[int, Dict[int, Node]]] → (csi, map)
        logdb,
        step_workers: int = 4,
        apply_workers: int = 4,
    ):
        self.get_nodes = get_nodes
        self.logdb = logdb
        self._stopped = threading.Event()
        self.step_ready = _WorkReady(step_workers)
        self.apply_ready = _WorkReady(apply_workers)
        self._threads: List[threading.Thread] = []
        # per-worker node-map cache, reloaded when the cluster-set index
        # changes (reference loadBucketNodes execengine.go:889)
        self._step_cache: List = [(-1, {}) for _ in range(step_workers)]
        self._apply_cache: List = [(-1, {}) for _ in range(apply_workers)]
        for i in range(step_workers):
            t = threading.Thread(
                target=self._step_worker_main, args=(i,),
                name=f"step-worker-{i}", daemon=True,
            )
            t.start()
            self._threads.append(t)
        for i in range(apply_workers):
            t = threading.Thread(
                target=self._apply_worker_main, args=(i,),
                name=f"apply-worker-{i}", daemon=True,
            )
            t.start()
            self._threads.append(t)

    # ---- wakeups (reference setStepReady / setApplyReady) ----

    def set_step_ready(self, cluster_id: int) -> None:
        self.step_ready.cluster_ready(cluster_id)

    def set_apply_ready(self, cluster_id: int) -> None:
        self.apply_ready.cluster_ready(cluster_id)

    def notify_all(self) -> None:
        for i in range(self.step_ready.count):
            self.step_ready.notify(i)
        for i in range(self.apply_ready.count):
            self.apply_ready.notify(i)

    def _worker_nodes(
        self, cache: List, idx: int, partitioner: FixedPartitioner
    ) -> Dict[int, "Node"]:
        csi, nodes = self.get_nodes()
        cached_csi, cached = cache[idx]
        if cached_csi == csi:
            return cached
        mine = {
            cid: n
            for cid, n in nodes.items()
            if partitioner.get_partition_id(cid) == idx
        }
        cache[idx] = (csi, mine)
        return mine

    # ---- step path (reference stepWorkerMain/processSteps :860-1010) ----

    def _step_worker_main(self, idx: int) -> None:
        while not self._stopped.is_set():
            self.step_ready.wait(idx)
            if self._stopped.is_set():
                return
            nodes = self._worker_nodes(
                self._step_cache, idx, self.step_ready.partitioner
            )
            ready = self.step_ready.get_ready(idx)
            active = [nodes[cid] for cid in ready if cid in nodes]
            if active:
                try:
                    self.process_steps(active)
                except Exception:
                    plog.exception("step worker %d failed", idx)

    def process_steps(self, active: List["Node"]) -> None:
        """The hot loop (reference ``processSteps`` ``execengine.go:923``):
        step → send replicates → one batched fsync → execute → commit."""
        pairs = []
        for n in active:
            ud = n.step_node()
            if ud is not None:
                pairs.append((n, ud))
        if not pairs:
            return
        for n, ud in pairs:
            n.process_dropped(ud)
            n.send_replicate_messages(ud)  # before fsync (thesis §10.2.1)
        updates = [ud for _, ud in pairs if ud.has_update()]
        if updates:
            self.logdb.save_raft_state(updates)
        for n, ud in pairs:
            n.process_raft_update(ud)
        for n, ud in pairs:
            n.commit_raft_update(ud)

    # ---- apply path (reference applyWorkerMain/processApplies :794-858) ----

    def _apply_worker_main(self, idx: int) -> None:
        while not self._stopped.is_set():
            self.apply_ready.wait(idx)
            if self._stopped.is_set():
                return
            nodes = self._worker_nodes(
                self._apply_cache, idx, self.apply_ready.partitioner
            )
            ready = self.apply_ready.get_ready(idx)
            for cid in ready:
                n = nodes.get(cid)
                if n is None:
                    continue
                try:
                    n.handle_apply_tasks()
                except Exception:
                    plog.exception("apply worker %d failed on %d", idx, cid)

    def stop(self) -> None:
        self._stopped.set()
        self.notify_all()
        for t in self._threads:
            t.join(timeout=2)
