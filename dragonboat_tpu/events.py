"""Event/metrics plumbing.

Reference: ``event.go`` — ``raftEventListener`` feeding Prometheus
counters/gauges (metric names ``event.go:65-88``) and forwarding
``LeaderUpdated`` to the user's ``IRaftEventListener``
(``raftio/listener.go:33``); ``sysEventListener`` serializing the 15
system event types (``internal/server/event.go:86-123``) to the user's
``ISystemEventListener`` (``raftio/listener.go:59-75``) on a dedicated
delivery thread (``nodehost.go:1748-1769``); ``WriteHealthMetrics``
(``event.go:31``) exposing Prometheus text.

The reference leans on VictoriaMetrics; here a tiny dependency-free
registry provides the same counter/gauge + text-exposition surface.
"""
from __future__ import annotations

import bisect
import enum
import queue
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional, Tuple

from .logger import get_logger

plog = get_logger("events")


# ---------------------------------------------------------------------------
# metrics registry (Prometheus text exposition)
# ---------------------------------------------------------------------------


def escape_label_value(v: str) -> str:
    """Prometheus text-format label escaping: ``\\`` → ``\\\\``, ``"`` →
    ``\\"``, newline → ``\\n`` (exposition spec).  Backslash first — the
    replacements must not re-escape each other's output."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def escape_help_text(v: str) -> str:
    """``# HELP`` text escaping: only ``\\`` and newline (the exposition
    spec does NOT escape quotes in help text, unlike label values)."""
    return v.replace("\\", "\\\\").replace("\n", "\\n")


#: default histogram bucket upper bounds (ms-scale latencies); callers
#: pass their own geometry at first observe
DEFAULT_BUCKETS = (
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
    100.0, 250.0, 500.0, 1000.0, 2500.0, 10000.0,
)


class MetricsRegistry:
    """Counters, gauges, and bucketed histograms keyed by name + label
    set, with valid Prometheus text exposition.

    Exposition invariants (ISSUE 5 satellite audit — the original
    formatter re-emitted ``# TYPE`` per LABEL SET, invalid for repeated
    metric names, and wrote label values unescaped, so a ``"``, ``\\``
    or newline in a value corrupted the whole scrape): exactly one
    ``# TYPE`` line per metric name, label values escaped, and stable
    (name, labels)-sorted ordering so successive scrapes diff cleanly.
    ISSUE 9 satellite: every family also gets exactly one ``# HELP``
    line (immediately before its ``# TYPE``) — instruments register
    their description via :meth:`describe`; undescribed families fall
    back to a deterministic placeholder so the exposition is uniformly
    self-documenting.
    """

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._counters: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
        self._gauges: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
        # histograms: per-NAME bucket geometry (first declare/observe
        # wins — mergeable series require one geometry per family) and
        # per-series [counts (len(buckets)+1, +Inf last), sum, count]
        self._hist_buckets: Dict[str, Tuple[float, ...]] = {}
        self._hists: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], list] = {}
        # per-family ``# HELP`` text (first describe wins, like bucket
        # geometry — a family must read the same across scrapes)
        self._help: Dict[str, str] = {}
        # cardinality guard (ISSUE 20 satellite): families already
        # warned about, so an unbounded label set logs once, not once
        # per scrape
        self._cardinality_warned: set = set()

    def describe(self, name: str, help_text: str) -> None:
        """Register a family's ``# HELP`` text (first call wins)."""
        with self._mu:
            self._help.setdefault(name, help_text)

    def help_text(self, name: str) -> str:
        with self._mu:
            return self._help.get(name, f"dragonboat_tpu metric {name}")

    @staticmethod
    def _key(name: str, labels: Optional[Dict[str, str]]):
        return (name, tuple(sorted((labels or {}).items())))

    def counter_add(
        self, name: str, value: float = 1, labels: Optional[Dict[str, str]] = None
    ) -> None:
        k = self._key(name, labels)
        with self._mu:
            self._counters[k] = self._counters.get(k, 0) + value

    def gauge_set(
        self, name: str, value: float, labels: Optional[Dict[str, str]] = None
    ) -> None:
        with self._mu:
            self._gauges[self._key(name, labels)] = value

    def counter_value(
        self, name: str, labels: Optional[Dict[str, str]] = None
    ) -> float:
        with self._mu:
            return self._counters.get(self._key(name, labels), 0)

    def gauge_value(
        self, name: str, labels: Optional[Dict[str, str]] = None
    ) -> float:
        with self._mu:
            return self._gauges.get(self._key(name, labels), 0)

    # -- histograms (device-plane latency families; obs/instruments.py) --

    def _hist_series(self, name, labels, buckets) -> list:
        """Get-or-create one histogram series; caller holds ``_mu``."""
        bk = self._hist_buckets.get(name)
        if bk is None:
            bk = tuple(float(b) for b in (buckets or DEFAULT_BUCKETS))
            if list(bk) != sorted(bk):
                raise ValueError("histogram buckets must be sorted")
            self._hist_buckets[name] = bk
        k = self._key(name, labels)
        series = self._hists.get(k)
        if series is None:
            series = [[0] * (len(bk) + 1), 0.0, 0]
            self._hists[k] = series
        return series

    def histogram_declare(
        self, name: str, buckets=None, labels: Optional[Dict[str, str]] = None
    ) -> None:
        """Materialize an empty histogram series so the family is visible
        in the exposition before the first observation."""
        with self._mu:
            self._hist_series(name, labels, buckets)

    def histogram_observe(
        self,
        name: str,
        value: float,
        labels: Optional[Dict[str, str]] = None,
        buckets=None,
    ) -> None:
        with self._mu:
            series = self._hist_series(name, labels, buckets)
            bk = self._hist_buckets[name]
            i = bisect.bisect_left(bk, value)
            series[0][i] += 1
            series[1] += value
            series[2] += 1

    def histogram_merge(
        self,
        name: str,
        counts,
        total: float,
        count: int,
        labels: Optional[Dict[str, str]] = None,
        buckets=None,
    ) -> None:
        """Bulk-merge pre-bucketed observations into a series (the
        request tracer accumulates per-stage observations locally off
        the hot path and flushes them here on the tick cadence — one
        registry lock per flush instead of one per observation).
        ``counts`` must match the family geometry: len(buckets)+1, +Inf
        last."""
        with self._mu:
            series = self._hist_series(name, labels, buckets)
            bk = self._hist_buckets[name]
            if len(counts) != len(bk) + 1:
                raise ValueError(
                    f"histogram_merge: {len(counts)} counts for "
                    f"{len(bk)} buckets"
                )
            for i, c in enumerate(counts):
                series[0][i] += c
            series[1] += total
            series[2] += count

    def histogram_value(
        self, name: str, labels: Optional[Dict[str, str]] = None
    ):
        """``(buckets, counts, sum, count)`` for one series (counts are
        per-bucket, +Inf last), or None when the series doesn't exist."""
        with self._mu:
            series = self._hists.get(self._key(name, labels))
            if series is None:
                return None
            return (
                self._hist_buckets[name], tuple(series[0]),
                series[1], series[2],
            )

    def families(self):
        """Sorted metric family names across all instrument kinds."""
        with self._mu:
            names = {n for n, _ in self._counters}
            names.update(n for n, _ in self._gauges)
            names.update(n for n, _ in self._hists)
        return sorted(names)

    @staticmethod
    def _fmt(name: str, label_items, value: float) -> str:
        if label_items:
            body = ",".join(
                f'{k}="{escape_label_value(str(v))}"' for k, v in label_items
            )
            return f"{name}{{{body}}} {value:g}"
        return f"{name} {value:g}"

    #: series-per-family count past which the exposition warns (once
    #: per family) about a probably-unbounded label set; instrument
    #: label vocabularies are all small and fixed, so anything past this
    #: is a per-request/per-group label leak.  Override per registry.
    cardinality_warn = 1000

    def _check_cardinality(self, name: str, series: int) -> None:
        """Warn ONCE per family whose series count crossed the guard
        (ISSUE 20 satellite): an unbounded label set grows the scrape
        linearly and silently — make it loud before it hurts."""
        if series > self.cardinality_warn and (
            name not in self._cardinality_warned
        ):
            self._cardinality_warned.add(name)
            plog.warning(
                "metric family %s has %d label sets (> %d): unbounded "
                "label values? scrape size grows with every new series",
                name, series, self.cardinality_warn,
            )

    def iter_health_metrics(self):
        """Generator form of the Prometheus text exposition: the
        instrument state is snapshotted under the registry lock ONCE,
        then yielded one chunk per metric family — the MetricsServer
        streams these as chunked transfer instead of materializing the
        whole exposition in one string (ISSUE 20 satellite: at
        high group/shard cardinality the single join is a latency spike
        on the serving thread).  Same invariants as the historical
        monolithic writer: exactly one ``# HELP`` + ``# TYPE`` per
        family, escaped values, stable (name, labels)-sorted ordering
        (counters, then gauges, then histograms)."""
        with self._mu:
            counters = sorted(self._counters.items())
            gauges = sorted(self._gauges.items())
            hists = sorted(
                (k, self._hist_buckets[k[0]], list(v[0]), v[1], v[2])
                for k, v in self._hists.items()
            )
            help_texts = dict(self._help)

        def _head(name: str, kind: str) -> str:
            text = help_texts.get(name, f"dragonboat_tpu metric {name}")
            return (
                f"# HELP {name} {escape_help_text(text)}\n"
                f"# TYPE {name} {kind}\n"
            )

        for kind, items in (("counter", counters), ("gauge", gauges)):
            i, n = 0, len(items)
            while i < n:
                name = items[i][0][0]
                parts = [_head(name, kind)]
                j = i
                while j < n and items[j][0][0] == name:
                    (_, labels), v = items[j]
                    parts.append(f"{self._fmt(name, labels, v)}\n")
                    j += 1
                self._check_cardinality(name, j - i)
                yield "".join(parts)
                i = j
        i, n = 0, len(hists)
        while i < n:
            name = hists[i][0][0]
            parts = [_head(name, "histogram")]
            j = i
            while j < n and hists[j][0][0] == name:
                (_, labels), bk, counts, total, count = hists[j]
                cum = 0
                for le, c in zip(bk, counts):
                    cum += c
                    parts.append(
                        f"{self._fmt(name + '_bucket', labels + (('le', f'{le:g}'),), cum)}\n"
                    )
                parts.append(
                    f"{self._fmt(name + '_bucket', labels + (('le', '+Inf'),), count)}\n"
                )
                parts.append(f"{self._fmt(name + '_sum', labels, total)}\n")
                parts.append(f"{self._fmt(name + '_count', labels, count)}\n")
                j += 1
            self._check_cardinality(name, j - i)
            yield "".join(parts)
            i = j

    def write_health_metrics(self, out) -> None:
        """Prometheus text format (reference ``WriteHealthMetrics``
        ``event.go:31``): one ``# HELP`` + one ``# TYPE`` per metric
        name, escaped label values and help text, stable ordering
        (counters, then gauges, then histograms; (name, labels)-sorted
        within each).  Delegates to :meth:`iter_health_metrics` so the
        monolithic and streaming paths can never drift."""
        for chunk in self.iter_health_metrics():
            out.write(chunk)

    def reset(self) -> None:
        with self._mu:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()
            self._hist_buckets.clear()
            self._cardinality_warned.clear()


DEFAULT_REGISTRY = MetricsRegistry()


# ---------------------------------------------------------------------------
# raft event listener (per-node metrics + LeaderUpdated forwarding)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class LeaderInfo:
    """Reference ``raftio.LeaderInfo``."""

    cluster_id: int
    node_id: int
    term: int
    leader_id: int


class RaftEventListener:
    """Implements the raft core's ``events`` hook surface
    (``raft.py`` emission sites; reference ``event.go:37-91``): updates the
    metric family the reference exports and forwards leader changes to the
    user listener's ``leader_updated``."""

    def __init__(
        self,
        user_listener=None,
        registry: Optional[MetricsRegistry] = None,
        enabled: bool = True,
    ):
        self.user_listener = user_listener
        self.registry = registry or DEFAULT_REGISTRY
        self.enabled = enabled

    def _labels(self, cluster_id: int, node_id: int) -> Dict[str, str]:
        return {"cluster_id": str(cluster_id), "node_id": str(node_id)}

    # -- hook surface consumed by raft.py --

    def leader_updated(
        self, cluster_id: int, node_id: int, leader_id: int, term: int
    ) -> None:
        if self.enabled:
            labels = self._labels(cluster_id, node_id)
            self.registry.gauge_set(
                "dragonboat_raftnode_has_leader", 1 if leader_id else 0, labels
            )
            self.registry.gauge_set("dragonboat_raftnode_term", term, labels)
        if self.user_listener is not None:
            try:
                self.user_listener.leader_updated(
                    LeaderInfo(cluster_id, node_id, term, leader_id)
                )
            except Exception:  # user callback must never hurt the node
                plog.exception("user leader_updated callback failed")

    def campaign_launched(self, cluster_id: int, node_id: int, term: int) -> None:
        if self.enabled:
            self.registry.counter_add(
                "dragonboat_raftnode_campaign_launched_total",
                labels=self._labels(cluster_id, node_id),
            )

    def campaign_skipped(self, cluster_id: int, node_id: int, term: int) -> None:
        if self.enabled:
            self.registry.counter_add(
                "dragonboat_raftnode_campaign_skipped_total",
                labels=self._labels(cluster_id, node_id),
            )

    def snapshot_rejected(
        self, cluster_id: int, node_id: int, ss_index: int, ss_term: int,
        from_node: int,
    ) -> None:
        if self.enabled:
            self.registry.counter_add(
                "dragonboat_raftnode_snapshot_rejected_total",
                labels=self._labels(cluster_id, node_id),
            )

    def replication_rejected(
        self, cluster_id: int, node_id: int, log_index: int, log_term: int,
        from_node: int,
    ) -> None:
        if self.enabled:
            self.registry.counter_add(
                "dragonboat_raftnode_replication_rejected_total",
                labels=self._labels(cluster_id, node_id),
            )

    def proposal_dropped(self, cluster_id: int, node_id: int, entries) -> None:
        if self.enabled:
            self.registry.counter_add(
                "dragonboat_raftnode_proposal_dropped_total",
                value=max(1, len(entries)),
                labels=self._labels(cluster_id, node_id),
            )

    def read_index_dropped(self, cluster_id: int, node_id: int) -> None:
        if self.enabled:
            self.registry.counter_add(
                "dragonboat_raftnode_read_index_dropped_total",
                labels=self._labels(cluster_id, node_id),
            )


# ---------------------------------------------------------------------------
# system events
# ---------------------------------------------------------------------------


class SystemEventType(enum.Enum):
    """Reference ``internal/server/event.go:86-123`` (15 types)."""

    NODE_HOST_SHUTTING_DOWN = "node_host_shutting_down"
    NODE_UNLOADED = "node_unloaded"
    NODE_READY = "node_ready"
    MEMBERSHIP_CHANGED = "membership_changed"
    CONNECTION_ESTABLISHED = "connection_established"
    CONNECTION_FAILED = "connection_failed"
    SEND_SNAPSHOT_STARTED = "send_snapshot_started"
    SEND_SNAPSHOT_COMPLETED = "send_snapshot_completed"
    SEND_SNAPSHOT_ABORTED = "send_snapshot_aborted"
    SNAPSHOT_RECEIVED = "snapshot_received"
    SNAPSHOT_RECOVERED = "snapshot_recovered"
    SNAPSHOT_CREATED = "snapshot_created"
    SNAPSHOT_COMPACTED = "snapshot_compacted"
    LOG_COMPACTED = "log_compacted"
    LOGDB_COMPACTED = "logdb_compacted"


@dataclass(frozen=True)
class SystemEvent:
    """Reference ``server.SystemEvent``."""

    type: SystemEventType
    cluster_id: int = 0
    node_id: int = 0
    from_: int = 0
    index: int = 0
    address: str = ""


class SysEventListener:
    """Serializes system events to the user's ``ISystemEventListener`` on a
    dedicated thread (reference ``event.go:146-207`` + delivery goroutine
    ``nodehost.go:1748-1769``): raft worker threads only enqueue; a slow or
    crashing user callback can never stall the engine."""

    _STOP = object()

    def __init__(self, user_listener=None, registry=None):
        self.user_listener = user_listener
        self.registry = registry or DEFAULT_REGISTRY
        self._q: "queue.Queue" = queue.Queue(maxsize=4096)
        self._thread: Optional[threading.Thread] = None
        if user_listener is not None:
            self._thread = threading.Thread(
                target=self._main, name="sys-events", daemon=True
            )
            self._thread.start()

    def publish(self, ev: SystemEvent) -> None:
        self.registry.counter_add(
            "dragonboat_system_event_total", labels={"type": ev.type.value}
        )
        if self._thread is None:
            return
        try:
            self._q.put_nowait(ev)
        except queue.Full:
            plog.warning("system event queue full, dropping %s", ev.type)

    def stop(self) -> None:
        if self._thread is not None:
            self._q.put(self._STOP)
            self._thread.join(timeout=5)
            self._thread = None

    def _main(self) -> None:
        # method names follow raftio/listener.go:59-75, snake_cased
        dispatch: Dict[SystemEventType, str] = {
            SystemEventType.NODE_HOST_SHUTTING_DOWN: "node_host_shutting_down",
            SystemEventType.NODE_UNLOADED: "node_unloaded",
            SystemEventType.NODE_READY: "node_ready",
            SystemEventType.MEMBERSHIP_CHANGED: "membership_changed",
            SystemEventType.CONNECTION_ESTABLISHED: "connection_established",
            SystemEventType.CONNECTION_FAILED: "connection_failed",
            SystemEventType.SEND_SNAPSHOT_STARTED: "send_snapshot_started",
            SystemEventType.SEND_SNAPSHOT_COMPLETED: "send_snapshot_completed",
            SystemEventType.SEND_SNAPSHOT_ABORTED: "send_snapshot_aborted",
            SystemEventType.SNAPSHOT_RECEIVED: "snapshot_received",
            SystemEventType.SNAPSHOT_RECOVERED: "snapshot_recovered",
            SystemEventType.SNAPSHOT_CREATED: "snapshot_created",
            SystemEventType.SNAPSHOT_COMPACTED: "snapshot_compacted",
            SystemEventType.LOG_COMPACTED: "log_compacted",
            SystemEventType.LOGDB_COMPACTED: "logdb_compacted",
        }
        while True:
            ev = self._q.get()
            if ev is self._STOP:
                return
            fn = getattr(self.user_listener, dispatch[ev.type], None)
            if fn is None:
                continue
            try:
                fn(ev)
            except Exception:
                plog.exception("user system event callback failed for %s", ev.type)
