"""Fast-lane manager: Python side of the native replication core.

Pairs one :class:`~dragonboat_tpu.native.natraft.NatRaft` engine with a
NodeHost.  The native core (``native/natraft.cpp``) owns the steady-state
replication data plane for *enrolled* groups; this module owns:

- **eligibility + enrollment** — :meth:`try_enroll` is called from the
  node's step path at a quiescent instant under raftMu (see
  ``Node._maybe_enroll``);
- **the eject protocol** — :meth:`eject_locked` finalizes the native group
  and hands its state snapshot back to the scalar raft object (the caller,
  ``Node.fast_eject``, rebuilds log watermarks / remote progress);
- **pumps** — sender threads draining native frames onto per-remote TCP
  connections, the apply pump converting native commit spans into normal
  apply Tasks, and the event pump servicing native-initiated ejects
  (contact loss, check-quorum failure, protocol punts);
- **ingest** — the raw-payload hook installed into the TCP transport: the
  native core consumes fast-path messages and returns a leftover
  MessageBatch for the normal Python router.

The fast lane is enabled by ``ExpertConfig.fast_lane`` and additionally
requires the real TCP transport and the native (NativeKV) LogDB backend in
plain-entry format, because the native core writes WAL records directly.
Everything degrades gracefully: when unavailable, nothing below runs and
the pure-Python path is untouched (the same contract as the TPU quorum
plugin, ``tpuquorum.py``).
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from .logger import get_logger

plog = get_logger("fastlane")

# native eject event codes (natraft.cpp EventCode)
EV_NAMES = {
    1: "contact-lost", 2: "quorum-lost", 3: "protocol", 4: "wal-error",
    5: "term-mismatch", 6: "wrong-role", 7: "gap", 8: "prev-term",
    9: "reject-resp", 10: "unknown-peer", 11: "resend-preenroll", 12: "parse",
    13: "commit-stall", 14: "sm-punt",
}


class FastLaneManager:
    """One per NodeHost; see module docstring."""

    def __init__(self, nh) -> None:
        self.nh = nh
        self.enabled = False
        self.nat = None
        self._nodes: Dict[int, object] = {}  # cid -> Node, while enrolled
        self._nodes_mu = threading.Lock()
        self._slots: Dict[str, int] = {}
        self._slots_mu = threading.Lock()
        # injected netsplits (set_partition); mirrored into the native
        # engine and consulted by the transport's partition_filter
        self._blocked_addrs: set = set()
        # ordering gate between the apply pump and eject hand-off: spans are
        # popped from the native queue only under this lock, so an eject can
        # atomically drain the remainder and keep per-group apply order
        self.apply_gate = threading.Lock()
        # nodes whose task queues received spans this drain (gate-guarded);
        # the pump applies them inline after releasing the gate
        self._touched = []
        self._stopped = threading.Event()
        self._threads = []
        # diagnostics: why groups leave the lane (native event codes plus
        # Python-initiated reasons), exposed via stats()
        self.eject_reasons: Dict[str, int] = {}
        self.drop_reasons: Dict[str, int] = {}
        # serializes completion-batch draining: the pump and the eject-path
        # drain share the native call's reusable buffers
        self._compl_mu = threading.Lock()
        # nodes whose applied delta crossed snapshot_entries during native
        # applies (see _process_completions); ejected by the pump OUTSIDE
        # _compl_mu so the periodic snapshot machinery can run scalar-side
        self._snapshot_due: list = []
        self._duty_mu = threading.Lock()
        self._enroll_t0: Dict[int, float] = {}
        self._enrolled_gs = 0.0
        self.enroll_events = 0
        # invariant counter: apply spans that arrived for an unregistered
        # group (MUST stay 0 — a dropped span loses committed entries from
        # the apply stream and wedges linearizable reads; chaos tests
        # assert on it)
        self.dropped_spans = 0

        handles = self._native_shard_handles()
        if handles is None:
            return
        rpc = getattr(nh.transport, "rpc", None)
        if rpc is None or not hasattr(rpc, "raw_handler"):
            plog.info("fast lane off: transport has no raw ingest hook")
            return
        from .native import natraft

        if not natraft.available():
            plog.info("fast lane off: libnatraft unavailable")
            return
        self.nat = natraft.NatRaft(
            nh.raft_address(), nh.nhconfig.get_deployment_id()
        )
        self.nat.set_shards(handles)
        window_ms = nh.nhconfig.expert.fast_lane_commit_window_ms
        if window_ms > 0:
            self.nat.set_commit_window(int(window_ms * 1000))
        self.n_shards = len(handles)
        rpc.raw_handler = self._ingest
        rpc.raw_stream = self  # stream_open/stream_feed/stream_close below
        if not getattr(nh.nhconfig, "mutual_tls", False) and hasattr(
            rpc, "takeover_fd"
        ):
            # plain TCP: native reader threads own inbound connections
            rpc.takeover_fd = self._takeover_fd
        self.nat.start()
        for fn, name in (
            (self._apply_pump, "fastlane-apply"),
            (self._event_pump, "fastlane-events"),
            (self._leftover_pump, "fastlane-leftover"),
            (self._read_pump, "fastlane-reads"),
            (self._completion_pump, "fastlane-compl"),
        ):
            t = threading.Thread(target=fn, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        self.enabled = True

    def _native_shard_handles(self):
        from . import native

        logdb = self.nh.logdb
        shards = getattr(logdb, "_shards", None)
        if shards is None or getattr(logdb, "_batched", False):
            plog.info("fast lane off: logdb is not plain-format sharded")
            return None
        handles = []
        for s in shards:
            kv = s.kv
            if not isinstance(kv, native.NativeKV):
                plog.info("fast lane off: shard backend %s", kv.name())
                return None
            handles.append(kv._h)
        return handles

    # ------------------------------------------------------------ ingest

    def _ingest(self, payload: bytes) -> Optional[bytes]:
        """TCP raw hook: returns the leftover batch payload (or None when
        fully consumed).  Runs on transport recv threads."""
        nat = self.nat
        if nat is None or self._stopped.is_set():  # late frame past stop
            return payload
        n, leftover = nat.ingest(payload)
        if n < 0:
            return payload
        return leftover

    # stream-ingest hooks (tcp.py _serve_conn_stream): large recv chunks
    # go straight to the native frame reassembler; only leftovers return

    def stream_open(self) -> int:
        return self.nat.conn_new()

    def stream_feed(self, h: int, data: bytes):
        nat = self.nat
        if nat is None or self._stopped.is_set():
            return [(0xFFFF, b"")]  # shutting down: close the connection
        return nat.ingest_stream(h, data)

    def stream_close(self, h: int) -> None:
        nat = self.nat
        if nat is not None and h:
            nat.conn_free(h)

    def send_message(self, m) -> bool:
        """Send a scalar-path raft message over the remote's native
        stream (one ordered stream per remote; see natr_send_msg).  False
        when the fast plane cannot serve it (caller uses the transport)."""
        nat = self.nat
        if nat is None or self._stopped.is_set():
            return False
        addr = self.nh.node_registry.resolve(m.cluster_id, m.to)
        if addr is None:
            return False
        slot = self.slot_for(addr)
        if slot < 0:
            return False
        from .wire.codec import encode_message

        return nat.send_msg(slot, encode_message(m))

    def _takeover_fd(self, fd: int) -> bool:
        nat = self.nat
        if nat is None or self._stopped.is_set():
            return False
        return nat.serve_fd(fd)

    def _leftover_pump(self) -> None:
        """Route frames the native readers could not consume through the
        normal transport handlers (decode + router; the router completes
        any needed eject before delivery)."""
        from .wire.codec import decode_chunk, decode_message_batch

        transport = self.nh.transport
        while not self._stopped.is_set():
            try:
                got = self.nat.next_leftover(200)
            except ConnectionError:
                return
            if got is None:
                continue
            method, payload, conn_id = got
            try:
                if method == 100:
                    transport.handle_request(decode_message_batch(payload))
                elif method == 200:
                    # _add_chunk_filtered, NOT chunks.add_chunk: chunks
                    # arriving on native-served connections must respect
                    # an injected partition too
                    if not transport._add_chunk_filtered(decode_chunk(payload)):
                        # a rejected chunk must fail the stream visibly:
                        # close the connection so the sender reports a
                        # failed snapshot instead of believing it landed
                        self.nat.close_conn(conn_id)
                # poison (999) / framing errors (0xFFFF): the native
                # reader already closed the connection
            except Exception:
                plog.exception("leftover route failed (method %d)", method)

    def ingest_message(self, m) -> bool:
        """Offer one decoded in-flight message to the native core (used for
        fast-path messages that were already queued on the Python side when
        the group enrolled).  True = consumed natively."""
        from .wire import MessageBatch
        from .wire.codec import encode_message_batch

        nat = self.nat
        if nat is None:
            return False
        payload = encode_message_batch(
            MessageBatch(
                requests=[m],
                deployment_id=self.nh.nhconfig.get_deployment_id(),
                source_address=self.nh.raft_address(),
            )
        )
        n, leftover = nat.ingest(payload)
        return n == 1 and leftover is None

    # --------------------------------------------------------- enrollment

    def slot_for(self, addr: str) -> int:
        with self._slots_mu:
            slot = self._slots.get(addr)
            if slot is not None:
                return slot
            slot = self.nat.add_remote()
            if slot < 0:
                return -1
            self._slots[addr] = slot
            # outbound: a native sender thread when the address is a plain
            # IPv4 literal AND the wire is plaintext; under mutual TLS the
            # Python sender owns the connection (transport._dial wraps it,
            # so fast-plane frames ride the same encrypted channel as the
            # scalar path — never a silent plaintext downgrade).  Inbound
            # under TLS likewise stays encrypted: tcp.py decrypts on its
            # recv thread and feeds plaintext to the native reassembler
            # via the stream hooks (no fd takeover of TLS sockets).
            host, _, port = addr.rpartition(":")
            native_ok = False
            tls = bool(getattr(self.nh.nhconfig, "mutual_tls", False))
            try:
                socket_ok = all(
                    p.isdigit() and 0 <= int(p) <= 255
                    for p in host.split(".")
                ) and len(host.split(".")) == 4
                if socket_ok and not tls:
                    native_ok = self.nat.remote_connect(slot, host, int(port))
            except (ValueError, OSError):
                native_ok = False
            if not native_ok:
                t = threading.Thread(
                    target=self._sender, args=(slot, addr),
                    name=f"fastlane-send-{addr}", daemon=True,
                )
                t.start()
                self._threads.append(t)
            return slot

    def set_partition(self, addr: str, on: bool) -> None:
        """Symmetric partition from the remote NodeHost at ``addr``
        (monkey.go:184-213 parity at the REAL wire): inbound raft batches
        from it are dropped at the native ingest choke point, outbound
        passes to it at flush, and the paths that do NOT ride the native
        streams — Python-socket sends, snapshot jobs, inbound chunks —
        are blocked by the transport's partition_filter (wired to
        :meth:`is_partitioned` at NodeHost construction).  ``on=False``
        heals; recovery is the protocol's own machinery (progress-timeout
        resends, contact-loss/check-quorum ejects, re-enrollment)."""
        # allocate the slot on demand: a never-yet-contacted remote must
        # still be blocked SYMMETRICALLY, not inbound-only
        slot = self.slot_for(addr)
        with self._slots_mu:
            if on:
                self._blocked_addrs.add(addr)
            else:
                self._blocked_addrs.discard(addr)
        self.nat.set_partition(addr, slot, on)

    def is_partitioned(self, addr: str) -> bool:
        with self._slots_mu:
            return addr in self._blocked_addrs

    def register_node(self, node) -> None:
        with self._nodes_mu:
            self._nodes[node.cluster_id] = node

    # enrollment duty cycle (VERDICT r3 weak #2): fraction of group-seconds
    # spent enrolled.  note_enrolled/note_ejected bracket each enrollment;
    # duty_group_seconds() is monotonic so callers diff two samples

    def note_enrolled(self, cid: int) -> None:
        with self._duty_mu:
            self._enroll_t0[cid] = time.monotonic()
            self.enroll_events += 1

    def note_ejected(self, cid: int) -> None:
        with self._duty_mu:
            t0 = self._enroll_t0.pop(cid, None)
            if t0 is not None:
                self._enrolled_gs += time.monotonic() - t0

    def duty_group_seconds(self) -> float:
        with self._duty_mu:
            now = time.monotonic()
            live = sum(now - t0 for t0 in self._enroll_t0.values())
            return self._enrolled_gs + live

    def unregister_node(self, node) -> None:
        with self._nodes_mu:
            if self._nodes.get(node.cluster_id) is node:
                self._nodes.pop(node.cluster_id)

    def eject_locked(self, node):
        """Finalize the native group (caller holds the node's raftMu) and
        return the EjectState; remaining apply spans are enqueued onto the
        node's apply queue, in order, before returning."""
        from .rsm import Task
        from .wire.codec import decode_entry_batch

        touched = []
        try:
            with self.apply_gate:
                # drain spans the pump has not yet taken (ours and others' —
                # delivering other groups' spans here is harmless and keeps
                # the gate hold short).  ConnectionError = the engine
                # stopped (NodeHost shutdown): proceed best-effort — the
                # process is exiting and restart replays from disk; an
                # escaped exception here would instead kill the event
                # pump and strand the node half-ejected
                try:
                    self._drain_applies_locked()
                except ConnectionError:
                    pass
                # claim whatever the drain touched: the pump only swaps
                # _touched after wait_apply reports a NEW span, so without
                # this, a quiescent system would leave those groups'
                # committed entries enqueued but never applied
                touched, self._touched = self._touched, []
                st = self.nat.eject(node.cluster_id)
                # native-SM completions must land before scalar applies
                # resume (the eject blob starts at the NATIVE applied
                # watermark, so the manager watermark must catch up first)
                # — and only AFTER nat.eject, which finalizes the group:
                # draining a still-ACTIVE group would race further native
                # applies queued behind the drain
                try:
                    self._drain_completions()
                except ConnectionError:
                    pass  # engine stopped mid-eject (see drain above)
                with self._nodes_mu:
                    self._nodes.pop(node.cluster_id, None)
                if st is not None:
                    entries = decode_entry_batch(st.apply_blob)
                    if entries:
                        node.to_apply.enqueue(
                            Task(
                                cluster_id=node.cluster_id,
                                node_id=node.node_id,
                                entries=entries,
                            )
                        )
                        self.nh.engine.set_apply_ready(node.cluster_id)
        finally:
            # even if nat.eject raised (the WAL-failure path fast_eject
            # handles), the drained groups must get their apply signal.
            # The caller holds this node's raftMu, so inline apply would
            # deadlock; hand them to the engine's apply workers (safe:
            # Node._apply_serial serializes with any concurrent apply)
            for n in touched:
                self.nh.engine.set_apply_ready(n.cluster_id)
        return st

    # ------------------------------------------------------------- pumps

    def _deliver_span(self, cid: int, blob: bytes) -> None:
        from .rsm import Task
        from .wire.codec import decode_entry_batch

        with self._nodes_mu:
            node = self._nodes.get(cid)
        if node is None:
            # unreachable by construction (registration precedes enroll,
            # ejects drain under the gate); log loudly rather than
            # silently dropping committed entries
            self.dropped_spans += 1
            plog.error("apply span for unenrolled group %d dropped", cid)
            return
        entries = decode_entry_batch(blob)
        node.to_apply.enqueue(
            Task(cluster_id=cid, node_id=node.node_id, entries=entries)
        )
        self._touched.append(node)

    # applies for fast-lane spans run INLINE on the pump thread (same FIFO
    # task queue, so ordering with slow-path tasks is preserved) — routing
    # through the engine's apply workers adds a cross-thread wakeup whose
    # GIL handoff latency dominates the end-to-end commit path
    _APPLY_INLINE = True

    def _drain_applies_locked(self) -> None:
        while True:
            got = self.nat.next_apply(0)
            if got is None:
                return
            cid, _first, _last, blob = got
            self._deliver_span(cid, blob)

    def _apply_pump(self) -> None:
        while not self._stopped.is_set():
            try:
                if not self.nat.wait_apply(200):
                    continue
            except ConnectionError:
                return
            with self.apply_gate:
                try:
                    self._drain_applies_locked()
                except ConnectionError:
                    return  # engine stopped between wait_apply and drain
                touched, self._touched = self._touched, []
            # applies run OUTSIDE the gate: handle_apply_tasks takes
            # raftMu, and fast_eject holds raftMu while taking the gate —
            # running inside would deadlock (lock-order inversion)
            for node in touched:
                if self._APPLY_INLINE:
                    try:
                        node.handle_apply_tasks()
                    except Exception:
                        plog.exception("inline apply failed")
                else:
                    self.nh.engine.set_apply_ready(node.cluster_id)

    def _process_completions(self, got) -> None:
        """Apply one batch of native-SM completion records: advance the
        manager watermark (the native plane already applied the entries to
        the shared SM) and complete leader proposal futures.  None of this
        takes raftMu, so the eject path can drain synchronously while
        holding it."""
        from .statemachine import Result

        cids, indexes, terms, keys, results, client_ids, series_ids, \
            payload_ids, leaders, statuses = got
        per: Dict[int, list] = {}
        for i in range(len(cids)):
            per.setdefault(int(cids[i]), []).append(i)
        for cid, idxs in per.items():
            # dict lookup, NOT nh.get_node (which RAISES for a removed
            # cluster — an exception here would drop the whole popped
            # batch, or abort an eject between nat.eject and the blob
            # enqueue; NodeHost.stop clears _clusters before node stops,
            # making that deterministic at shutdown)
            node = self.nh._clusters.get(cid)
            if node is None:
                # consume (and discard) any parked payloads: nothing else
                # will ever fetch them for a removed cluster, and the C++
                # side keeps a parked copy until it is taken
                for i in idxs:
                    if payload_ids[i]:
                        self.nat.take_payload(int(payload_ids[i]))
                continue
            last = idxs[-1]
            node.sm.advance_applied_native(
                int(indexes[last]), int(terms[last])
            )
            for i in idxs:
                # status 2 = ignored (client already responded): the
                # future is deliberately NOT completed — Node.apply_update
                # semantics for has_responded duplicates
                if leaders[i] and keys[i] and statuses[i] != 2:
                    # cached session responses with data bytes ride the
                    # payload side-channel (the u64 record can't carry
                    # them; round 4 ejected instead)
                    data = (
                        self.nat.take_payload(int(payload_ids[i]))
                        if payload_ids[i] else b""
                    )
                    node.pending_proposals.applied(
                        int(keys[i]), int(client_ids[i]), int(series_ids[i]),
                        Result(value=int(results[i]), data=data),
                        statuses[i] == 1,
                    )
            node.pending_reads.applied(node.sm.get_last_applied())
            # periodic snapshot trigger (reference saveSnapshotRequired):
            # the scalar trigger rides process_raft_update, which is IDLE
            # while the group applies natively — without this check an
            # enrolled group under sustained load never auto-snapshots
            # and its LogDB grows without bound until some other eject.
            # Queue the node; the pump ejects OUTSIDE _compl_mu (the
            # eject path holds raftMu while draining completions, so
            # ejecting in here would invert that lock order) and the
            # scalar window runs the normal save + compaction machinery,
            # after which the group re-enrolls mid-load.
            if node.snapshot_due():
                self._snapshot_due.append(node)

    def _completion_pump(self) -> None:
        # Processing happens WHILE HOLDING _compl_mu: the eject-path drain
        # must never observe an empty native queue while a popped batch is
        # still mid-flight on this thread (the watermark would be stale
        # when the eject blob applies).  The 20ms idle timeout bounds how
        # long a drain (which runs under raftMu) can wait for the lock.
        while not self._stopped.is_set():
            try:
                with self._compl_mu:
                    got = self.nat.next_completions(20)
                    if got is not None:
                        self._process_completions(got)
                    # swap under _compl_mu: the eject-path drain (another
                    # thread) appends under this lock — swapping outside
                    # could discard its freshly queued node forever
                    due, self._snapshot_due = self._snapshot_due, []
                for node in due:  # ejects OUTSIDE the lock (order: raftMu
                    if node._natsm_attached:
                        # native-SM groups snapshot in place via the
                        # consistent capture path (natr_capture_sm) — no
                        # eject.  _snapshotting's non-blocking acquire
                        # dedups re-triggers while a save is in flight.
                        node._save_snapshot_required()
                        continue
                    if node.fast_lane:  # -> _compl_mu, never the reverse)
                        self.count_eject("snapshot-due")
                        node.fast_eject()
            except ConnectionError:
                return
            except Exception:
                plog.exception("completion batch failed")

    def _drain_completions(self) -> None:
        """Synchronously drain pending native-SM completions (eject path:
        the manager watermark must be current before scalar applies resume
        past it)."""
        while True:
            try:
                with self._compl_mu:
                    got = self.nat.next_completions(0)
                    if got is not None:
                        self._process_completions(got)
            except ConnectionError:
                return
            if got is None:
                return

    def _event_pump(self) -> None:
        while not self._stopped.is_set():
            try:
                ev = self.nat.next_event(500)
            except ConnectionError:
                return
            if ev is None:
                continue
            cid, code = ev
            with self._nodes_mu:
                node = self._nodes.get(cid)
            if node is not None:
                plog.info(
                    "group %d native eject: %s", cid, EV_NAMES.get(code, code)
                )
                self.count_eject(EV_NAMES.get(code, str(code)))
                node.fast_eject(
                    contact_lost=code in (1, 2), reenroll_backoff=code == 13
                )
                continue

    def _read_pump(self) -> None:
        """Deliver quorum-confirmed native ReadIndex contexts to the
        pending-read trackers (the scalar path's ReadyToRead flow)."""
        from .wire import ReadyToRead, SystemCtx

        while not self._stopped.is_set():
            try:
                got = self.nat.next_read(200)
            except ConnectionError:
                return
            if got is None:
                continue
            cid, low, high, index = got
            with self._nodes_mu:
                node = self._nodes.get(cid)
            if node is None:
                continue
            node.pending_reads.add_ready(
                [ReadyToRead(index=index, system_ctx=SystemCtx(low=low, high=high))]
            )
            node.pending_reads.applied(node.sm.get_last_applied())

    def _sender(self, slot: int, addr: str) -> None:
        """Drain native frames for one remote onto a dedicated TCP
        connection (the fast plane's analog of the transport's per-remote
        sender, ``transport.go:436``)."""
        conn = None
        backoff = 0.05
        buf = None
        retries = 0
        while not self._stopped.is_set():
            if buf is None:
                try:
                    buf = self.nat.take_send(slot, 200)
                except ConnectionError:
                    break
                if buf is None:
                    continue
            try:
                if conn is None:
                    conn = self.nh.transport.rpc.get_connection(addr)
                    backoff = 0.05
                conn.sock.sendall(buf)
                buf = None
                retries = 0
            except Exception:
                if conn is not None:
                    try:
                        conn.close()
                    except Exception:
                        pass
                    conn = None
                retries += 1
                if retries > 20:
                    buf = None  # drop; raft-level retry recovers
                    retries = 0
                time.sleep(backoff)
                backoff = min(1.0, backoff * 2)
        if conn is not None:
            try:
                conn.close()
            except Exception:
                pass

    # ------------------------------------------------------------- misc

    def count_eject(self, reason: str) -> None:
        self.eject_reasons[reason] = self.eject_reasons.get(reason, 0) + 1

    def count_drop(self, reason: str) -> None:
        """Messages consumed-without-effect for an enrolled group (stale
        stragglers that scalar raft would no-op); distinct from ejects."""
        self.drop_reasons[reason] = self.drop_reasons.get(reason, 0) + 1

    def stats(self) -> dict:
        if not self.enabled:
            return {"enabled": False}
        out = self.nat.stats()
        out["enabled"] = True
        out["eject_reasons"] = dict(self.eject_reasons)
        out["drop_reasons"] = dict(self.drop_reasons)
        out["dropped_spans"] = self.dropped_spans
        with self._duty_mu:
            # explicit population: ALL local enrolled replicas (followers
            # enroll too) — distinct from the e2e's led-only count
            out["enrolled_replicas"] = len(self._enroll_t0)
        out["enroll_events"] = self.enroll_events
        out["enrolled_group_seconds"] = round(self.duty_group_seconds(), 2)
        return out

    def stop(self) -> None:
        self._stopped.set()
        if self.nat is not None:
            self.nat.stop()
        for t in self._threads:
            t.join(timeout=2)
        if self.nat is not None:
            self.nat.close()
            self.nat = None
