"""Delayed snapshot-status feedback to raft.

Reference: ``feedback.go:23-129`` ``snapshotFeedback``.  When the transport
finishes (or fails) sending a snapshot to a follower, the status must not
reach raft immediately: the follower still needs time to install the image,
and reporting success too early moves its progress tracker out of the
Snapshot state before it can accept appends.  Instead the status is parked
with a long release delay; when the follower's SNAPSHOT_RECEIVED ack
arrives, the release is rescheduled much sooner.  If pushing the status
into the node's queue fails, it is retried shortly after — a dropped
status message therefore cannot strand a follower in Snapshot state.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Tuple

# delays in milliseconds (reference ticks are 1ms: feedback.go:24-27)
PUSH_DELAY_MS = 20000
CONFIRMED_DELAY_MS = 1500
RETRY_DELAY_MS = 200


class _Status:
    __slots__ = ("cluster_id", "node_id", "release_ms", "failed")

    def __init__(self, cluster_id, node_id, release_ms, failed):
        self.cluster_id = cluster_id
        self.node_id = node_id
        self.release_ms = release_ms
        self.failed = failed


class SnapshotFeedback:
    """push_fn(cluster_id, node_id, failed) -> bool (True = delivered)."""

    def __init__(
        self,
        push_fn: Callable[[int, int, bool], bool],
        push_delay_ms: int = PUSH_DELAY_MS,
        confirmed_delay_ms: int = CONFIRMED_DELAY_MS,
        retry_delay_ms: int = RETRY_DELAY_MS,
    ):
        self._pf = push_fn
        self._mu = threading.Lock()
        self._pendings: Dict[Tuple[int, int], _Status] = {}
        self.push_delay_ms = push_delay_ms
        self.confirmed_delay_ms = confirmed_delay_ms
        self.retry_delay_ms = retry_delay_ms

    def add_status(self, cluster_id: int, node_id: int, failed: bool, now_ms: int) -> None:
        """Transport finished a snapshot send (reference addStatus)."""
        with self._mu:
            self._pendings[(cluster_id, node_id)] = _Status(
                cluster_id, node_id, now_ms + self.push_delay_ms, failed
            )

    def confirm(self, cluster_id: int, node_id: int, now_ms: int) -> None:
        """The follower acked with SNAPSHOT_RECEIVED (reference confirm):
        release a success status soon."""
        with self._mu:
            self._pendings[(cluster_id, node_id)] = _Status(
                cluster_id, node_id, now_ms + self.confirmed_delay_ms, False
            )

    def _get_ready(self, now_ms: int) -> List[_Status]:
        with self._mu:
            ready = [s for s in self._pendings.values() if s.release_ms < now_ms]
            for s in ready:
                del self._pendings[(s.cluster_id, s.node_id)]
            return ready

    def push_ready(self, now_ms: int) -> None:
        """Called from the tick loop (reference pushReady)."""
        ready = self._get_ready(now_ms)
        if not ready:
            return
        retry = [s for s in ready if not self._pf(s.cluster_id, s.node_id, s.failed)]
        if retry:
            with self._mu:
                for s in retry:
                    self._pendings[(s.cluster_id, s.node_id)] = _Status(
                        s.cluster_id,
                        s.node_id,
                        now_ms + self.retry_delay_ms,
                        s.failed,
                    )

    def pending_count(self) -> int:
        with self._mu:
            return len(self._pendings)
