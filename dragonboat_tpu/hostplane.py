"""Compartmentalized host plane: ingress batcher, group-commit WAL,
decoupled apply/egress executors.

The e2e leaf profile (PROFILE_e2e.txt) is lock waits plus the
``commit_write_batch`` durability hop: every client proposal takes the
per-group ``entry_q`` lock and a step-ready condition-variable notify,
every persisting step-worker cycle rides its own fsync, and the apply
workers run the client-completion ``Event.set`` storm inline.  Following
"Scaling Replicated State Machines with Compartmentalization" (PAPERS.md),
this module splits the monolithic host path into independently-sharded
stages so host throughput scales with cores instead of being one raftMu
wide:

1. :class:`ProposalIngress` — the paper's proxy/batcher tier.  ``propose``
   / ``propose_batch`` append raw commands to a striped per-shard staging
   ring (one micro-lock, no per-group locks, no engine wakeup) and return
   their futures immediately; per-shard batcher threads drain whole rings
   and stage each group's burst under ONE ``entry_q`` lock acquisition and
   ONE step-ready signal per group per drain.

2. :class:`GroupCommitWAL` — the cross-shard group-commit tier.  Step
   workers' committers submit their write batches to ONE shared flusher
   that merges everything queued — across committers, groups and LogDB
   shards — into a single ``save_raft_state`` call per cycle (one fsync
   per touched shard per cycle instead of one per committer cycle), then
   releases each submitter to run its own post-fsync half concurrently.
   Nothing is acked before its fsync: a submitter only unblocks after the
   merged batch it rode is durable, and a flush failure re-raises in every
   rider (the committer's retry path re-arms the groups).

3. :class:`ApplyPool` / :class:`EgressPool` — decoupled executors.  Apply
   readiness routes to a dedicated pool (sharded by group, so per-group
   task order is untouched) and client-completion ``RequestState.notify``
   calls move off the apply workers onto egress workers, so
   step→replicate→persist never waits behind user SM code or the client
   wakeup storm.

Everything here is OFF by default (``ExpertConfig.host_compartments``);
with the switch off no object in this module is constructed and the
scalar host path is bit-identical to the pre-compartment build.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, TYPE_CHECKING

from .logger import get_logger
from .requests import SystemBusyError
from .settings import Soft
from .wire import Entry, EntryType

if TYPE_CHECKING:
    from .node import Node

plog = get_logger("hostplane")


class _IngressShard:
    """One staging ring + its batcher thread."""

    __slots__ = ("idx", "mu", "cv", "ring", "ncmds", "cap", "thread",
                 "mu_wait_s", "draining")

    def __init__(self, cap: int, idx: int = 0):
        self.idx = idx
        self.mu = threading.Lock()
        self.cv = threading.Condition(self.mu)
        self.ring: list = []
        self.ncmds = 0  # commands staged (the cap's unit — a ring item
        # is a whole burst, so len(ring) alone under-counts backpressure)
        self.cap = cap
        self.thread: Optional[threading.Thread] = None
        self.mu_wait_s = 0.0
        # True from ring swap until the swapped burst is fully staged —
        # singles arriving meanwhile must ring (ordering), not go inline
        self.draining = False


class ProposalIngress:
    """Striped MPSC proposal staging in front of the node runtime.

    ``submit``/``submit_one`` run on client threads: create the futures
    (key + deadline only — registration with the tracker is deferred to
    the batcher, which always runs before the entry can reach the apply
    path, so no completion can miss it), append to the owning shard's
    ring, wake the batcher.  A full ring raises :class:`SystemBusyError`
    exactly like a full ``entry_q`` on the direct path.

    The batcher drains the whole ring in one swap, groups by node, does
    the payload encoding (amortized off the client threads), bulk-registers
    the futures, and stages each group's burst with ONE lock acquisition —
    the native fast lane's ``propose_batch`` when enrolled, else one
    ``entry_q.add_batch`` — and ONE step-ready signal per group.
    """

    def __init__(
        self,
        shards: int = 2,
        ring_cap: int = 0,
        obs=None,
        hostproc=None,
    ):
        self.nshards = max(1, shards)
        cap = ring_cap or Soft.incoming_proposal_queue_length * 4
        self._shards = [
            _IngressShard(cap, idx=i) for i in range(self.nshards)
        ]
        # multi-process encode tier (hostproc, ISSUE 12): one
        # shared-memory encode lane per staging shard — the batcher
        # ships the whole drained burst's payload encode to a worker
        # process and stamps the ``ipc`` trace stage on return.  None
        # (host_workers=0, or a topology where the handoff cannot pay —
        # see HostProcPlane.offload_default) keeps the inline encode
        # bit-identical.
        self._encoders = (
            [hostproc.encode_lane(i) for i in range(self.nshards)]
            if hostproc is not None and hostproc.offload_default
            else None
        )
        self._stopped = False
        self._paused = False  # test hook: hold drains to observe ring caps
        self._obs = obs
        self.submitted = 0  # commands accepted into rings (GIL-counted)
        self.drains = 0
        self.drained = 0  # commands drained (batch size = drained/drains)
        for i, sh in enumerate(self._shards):
            t = threading.Thread(
                target=self._batcher_main, args=(sh,),
                name=f"ingress-batcher-{i}", daemon=True,
            )
            sh.thread = t
            t.start()

    # ---- client side ----

    def submit_one(self, node: "Node", session, cmd: bytes, timeout_s: float):
        return self.submit(node, session, (cmd,), timeout_s)[0]

    def submit_single_if_active(
        self, node: "Node", session, cmd: bytes, timeout_s: float
    ):
        """Adaptive single-proposal routing: ring the command only when
        the owning shard already has staged or draining work (the burst
        keeps it active, and ring order puts this proposal behind it);
        return None on a quiet shard so the caller stages inline with no
        thread handoff.  Caveat (documented in the differential suite):
        a thread that interleaves an UN-awaited ``propose_batch`` with a
        bare ``propose`` on the same group may see the two stage in
        either order — the same guarantee two independent clients get."""
        sh = self._shards[node.cluster_id % self.nshards]
        if not sh.ring and not sh.draining:
            return None
        return self.submit(node, session, (cmd,), timeout_s)[0]

    def submit(
        self, node: "Node", session, cmds, timeout_s: float
    ) -> list:
        """Stage a burst for ``node`` and return one future per command.

        The witness/payload precheck happened in the caller (``Node``
        keeps it synchronous so ``PayloadTooBigError`` /
        ``InvalidOperationError`` semantics match the direct path)."""
        pp = node.pending_proposals
        deadline = pp._clock.tick + node._timeout_ticks(timeout_s)
        from .requests import RequestState

        tr = node.tracer
        t0 = time.perf_counter() if tr is not None else 0.0
        states = []
        client_id, series_id = session.client_id, session.series_id
        responded_to = session.responded_to
        bits = pp._rng.getrandbits
        for _ in cmds:
            rs = RequestState(key=bits(64) or 1, deadline=deadline)
            rs.client_id = client_id
            rs.series_id = series_id
            states.append(rs)
        if tr is not None:
            # contexts attach BEFORE the ring append, so the ingress
            # stage measures the ring wait + batcher drain
            tr.attach_all(states, node.cluster_id, t0)
        sh = self._shards[node.cluster_id % self.nshards]
        with sh.mu:
            # cap is in COMMANDS; an oversized burst on an otherwise
            # empty ring is accepted (the direct path would accept it
            # too and let entry_q truncate the tail to DROPPED futures)
            if self._stopped or (
                sh.ncmds and sh.ncmds + len(cmds) > sh.cap
            ):
                if tr is not None:
                    # the rejected futures never reach a tracker, so no
                    # notify will ever finish their contexts — drop them
                    # from the in-flight index or they leak to the
                    # stall watchdog
                    tr.discard(states)
                raise SystemBusyError()
            sh.ring.append(
                (node, states, cmds, client_id, series_id, responded_to)
            )
            sh.ncmds += len(cmds)
            sh.cv.notify()
        self.submitted += len(cmds)
        obs = self._obs
        if obs is not None:
            obs.ingress_submit(len(cmds))
        return states

    # ---- batcher side ----

    def _batcher_main(self, sh: _IngressShard) -> None:
        while True:
            with sh.mu:
                while (not sh.ring or self._paused) and not self._stopped:
                    sh.cv.wait(0.2)
                if self._stopped and not sh.ring:
                    return
                if self._paused and not self._stopped:
                    continue
                burst, sh.ring = sh.ring, []
                sh.ncmds = 0
                sh.draining = True
            try:
                self._drain(burst, sh.idx)
            except Exception:
                plog.exception("ingress batcher drain failed")
                # resolve every future the failed drain may have
                # stranded: dropped() covers registered keys; a future
                # the failure preceded registration for is invisible to
                # the tracker (and its timeout GC) and must be notified
                # directly or the client blocks for its full timeout
                from .requests import RequestResult, RequestResultCode

                for node, states, *_ in burst:
                    for rs in states:
                        if not rs.done():
                            node.pending_proposals.dropped(rs.key)
                        if not rs.done():
                            rs.notify(
                                RequestResult(
                                    code=RequestResultCode.DROPPED
                                )
                            )
            finally:
                sh.draining = False

    def _drain(self, burst: list, shard_idx: int = 0) -> None:
        t0 = time.perf_counter() if self._obs is not None else 0.0
        by_node: Dict[int, list] = {}
        nodes: Dict[int, "Node"] = {}
        for item in burst:
            node = item[0]
            by_node.setdefault(node.cluster_id, []).append(item)
            nodes[node.cluster_id] = node
        n_cmds = 0
        for cid, items in by_node.items():
            n_cmds += self._stage_node(nodes[cid], items, shard_idx)
        self.drains += 1
        self.drained += n_cmds
        obs = self._obs
        if obs is not None:
            obs.ingress_drain(
                groups=len(by_node), cmds=n_cmds,
                wall_ms=(time.perf_counter() - t0) * 1e3,
                ring_depth=sum(len(s.ring) for s in self._shards),
            )

    def _stage_node(self, node: "Node", items: list,
                    shard_idx: int = 0) -> int:
        """Encode + register + stage one group's burst.  Returns the
        number of commands staged.  Ordering: ring order is preserved
        (one group always maps to one shard, so a client's back-to-back
        proposals stay ordered exactly like the direct path)."""
        from .rsm.encoded import get_encoded_payload

        pp = node.pending_proposals
        ct = node._entry_ct
        tr = node.tracer
        # hostproc encode tier: ship the burst's non-empty payloads to
        # the shard's worker lane in ONE round trip; a None return
        # (worker gone / ring busy) falls back to the inline encode —
        # same bytes, just on this thread.  ``ipc`` stamps the handoff
        # (ring enqueue -> worker dequeue -> encoded burst returned).
        enc_iter = None
        if self._encoders is not None:
            raw = [
                cmd
                for _n, _s, cmds, *_ in items
                for cmd in cmds
                if cmd
            ]
            if raw:
                encs = self._encoders[shard_idx].encode(int(ct), raw)
                if encs is not None:
                    enc_iter = iter(encs)
                    if tr is not None:
                        # only states whose command actually rode the
                        # encode worker — empty commands stage inline
                        # and must not inherit a handoff interval in
                        # the attribution table
                        for _n, states, cmds, *_ in items:
                            for rs, cmd in zip(states, cmds):
                                if cmd:
                                    tr.mark(rs, "ipc")
        entries: List[Entry] = []
        all_states: list = []
        runs: list = []  # (client_id, series_id, responded_to, start, end)
        for _node, states, cmds, client_id, series_id, responded_to in items:
            start = len(entries)
            for rs, cmd in zip(states, cmds):
                if cmd:
                    enc = (
                        next(enc_iter) if enc_iter is not None
                        else get_encoded_payload(ct, cmd)
                    )
                    etype = EntryType.ENCODED
                else:
                    enc = cmd
                    etype = EntryType.APPLICATION
                e = Entry(
                    key=rs.key, client_id=client_id, series_id=series_id,
                    cmd=enc,
                )
                e.type = etype
                e.responded_to = responded_to
                entries.append(e)
            all_states.extend(states)
            runs.append(
                (client_id, series_id, responded_to, start, len(entries))
            )
        if not entries:
            return 0
        # register BEFORE staging: completion (apply path) can only run
        # after the entry is staged, so registration is always visible
        # by the time ``applied`` looks the key up
        pp.register_batch(all_states)
        if node._stopped.is_set():
            for rs in all_states:
                pp.dropped(rs.key)
            return len(entries)
        staged_native = 0
        fl = node.fastlane
        if node.fast_lane and fl is not None:
            # per-session contiguous runs ride the native batch append
            # (indices assigned under one C++ lock); the first run the
            # native core refuses falls the remainder back to the scalar
            # queue so cross-run ordering is preserved
            for client_id, series_id, responded_to, start, end in runs:
                chunk = entries[start:end]
                etypes = {e.type for e in chunk}
                if len(etypes) == 1 and fl.nat.propose_batch(
                    node.cluster_id,
                    [e.key for e in chunk],
                    client_id, series_id, responded_to,
                    int(chunk[0].type),
                    _pack_blob(chunk),
                ):
                    staged_native = end
                    continue
                break
        rest = entries[staged_native:]
        if rest:
            accepted = node.entry_q.add_batch(rest)
            for e in rest[accepted:]:
                # queue full mid-burst: resolve like the direct
                # ``propose_batch`` (DROPPED futures, clients retry)
                pp.dropped(e.key)
        node.nh.engine.set_step_ready(node.cluster_id)
        if tr is not None:
            for rs in all_states:
                tr.mark(rs, "ingress")
        return len(entries)

    # ---- lifecycle / test hooks ----

    def pause(self) -> None:
        """Hold all batchers (tests: observe ring backpressure)."""
        self._paused = True

    def resume(self) -> None:
        self._paused = False
        for sh in self._shards:
            with sh.mu:
                sh.cv.notify()

    def stop(self) -> None:
        self._stopped = True
        for sh in self._shards:
            with sh.mu:
                sh.cv.notify()
        for sh in self._shards:
            if sh.thread is not None:
                sh.thread.join(timeout=2)

    def stats(self) -> dict:
        return {
            "shards": self.nshards,
            "submitted": self.submitted,
            "drains": self.drains,
            "drained": self.drained,
            "batch_avg": round(self.drained / self.drains, 2)
            if self.drains else 0.0,
        }


def _pack_blob(entries: List[Entry]) -> bytes:
    """Length-prefixed payload blob for the native batch append.  The
    header packer is cached per length — a pipelined burst is usually one
    payload size repeated, and ``struct.pack`` per entry was a measured
    term in the propose profile (ISSUE 8 satellite)."""
    from .node import _pack_len

    return b"".join(_pack_len(len(e.cmd)) + e.cmd for e in entries)


class GroupCommitWAL:
    """Cross-shard group commit: one fsync amortized across every step
    worker's write batches per flush cycle.

    Leader-based protocol (no dedicated flusher thread — a thread handoff
    on a saturated single-core box costs a scheduling quantum per hop,
    which is exactly the tax this tier exists to remove): the first
    committer to arrive while no flush is running becomes the LEADER,
    takes everything queued (its own submission plus every concurrent
    committer's), and persists the merged batch on its own thread; later
    arrivals become RIDERS and sleep until the leader completes them.
    Uncontended, a committer flushes inline with zero handoffs; under
    concurrency, one leader's single fsync covers all riders.

    The persist itself is ``save_raft_state_journaled`` when the LogDB
    supports the host journal (one journal fsync for ALL shards' batches
    — see ``logdb/journal.py``), else the classic per-shard fsynced save
    (still merged across committers).

    Per-group ordering is untouched: a group only ever rides its owning
    committer, which blocks here until the batch carrying it lands.
    Failure re-raises into EVERY participant of the failed cycle; the
    committer's exception path clears ``commit_inflight`` and re-arms the
    groups, so the updates are re-emitted and retried.  Nothing is acked
    before its fsync — leader and riders return strictly after the
    journal (or per-shard) fsync.
    """

    #: flush cycles between shard-store checkpoints (the journal's
    #: truncation cadence; each checkpoint costs one fsync per shard)
    CHECKPOINT_EVERY = 256

    def __init__(self, logdb, window_ms: float = 0.0, obs=None, fs=None,
                 journal_mode: str = "auto", hostproc=None):
        self.logdb = logdb
        self.window_s = max(0.0, window_ms) / 1e3
        self._cv = threading.Condition()
        self._q: list = []  # (updates, slot=[done, error])
        self._flushing = False
        self._stopped = False
        self._obs = obs
        self._fs = fs
        self.flushes = 0
        self.submissions = 0
        self.updates_flushed = 0
        # journal strategy (ExpertConfig.host_wal_journal): "auto" lets
        # the device probe below pick; "force" always journals (the
        # probe only paces the window); "off" never arms the journal
        self._mode = (
            journal_mode if journal_mode in ("auto", "force", "off")
            else "auto"
        )
        # cross-shard journal: when the LogDB supports it (durable
        # sharded backend), every flush cycle is ONE journal fsync for
        # ALL shards' batches; otherwise fall back to the per-shard
        # fsynced save (still merged across committers)
        self._journal = None
        enable = getattr(logdb, "enable_host_journal", None)
        if enable is not None and self._mode != "off":
            try:
                self._journal = enable(fs=fs)
            except OSError:
                plog.exception("host journal unavailable; per-shard fsync")
        self._since_checkpoint = 0
        self._single_streak = 0
        self._probes = 0
        # startup device probe (the box is quiet, so the measurement is
        # as GIL-clean as it gets — runtime persist walls are polluted
        # by GIL-reacquisition waits and cannot attribute device cost):
        # a slow durability device (ms-class barrier) engages the
        # cross-file journal and a short accumulation window, both of
        # which pay for themselves many times over there; a fast device
        # (sub-ms) keeps the classic per-shard fsynced save — merged
        # across committers by the leader protocol, but with zero extra
        # encode/write work.  The probe keeps the MIN over its samples:
        # GIL pollution only ever INFLATES a sample, so the min is the
        # robust device-cost estimator (a polluted mean could pin the
        # journal on a fast disk for the process lifetime).
        # ``journal.bytes > 0`` still forces the journaled path
        # regardless (replay-regression correctness rule, see
        # ShardedDB.save_raft_state_journaled).
        self._device_probe_s = self._probe_device(fs)
        if self._mode == "force" and self._journal is not None:
            # forced strategy (ISSUE 12 satellite): the probe no longer
            # picks the strategy, only the pacing window — RE-probe so
            # one polluted startup sample can't pin the window either
            self.reprobe()
            self._journal_engaged = True
        else:
            self._journal_engaged = (
                self._journal is not None
                and self._device_probe_s >= 0.0005
            )
        # WAL-worker sink (hostproc, ISSUE 12): the journal's
        # append+fsync cycle runs in a worker process; raw-OS path only
        # (a fault-injection vfs cannot cross the process boundary, and
        # must keep reaching the in-process durability point).  Gated
        # like the journal itself, by measurement: the cross-process
        # round trip costs ~1-2 scheduling quanta, so it pays only when
        # spare cores can hide it (hostproc.offload_default) or the
        # durability barrier dwarfs it — a sub-ms fsync on a single-core
        # box measured ~8x SLOWER through the worker.
        if (
            hostproc is not None and self._journal is not None
            and fs is None
            and (
                hostproc.offload_default
                or self._device_probe_s >= 0.0005
            )
        ):
            try:
                self._journal.sink = hostproc.wal_sink()
            except Exception:
                plog.exception("hostproc WAL sink unavailable")

    def _probe_device(self, fs, samples: int = 3) -> float:
        if self._journal is None:
            return 0.0
        import os as _os

        path = self._journal.path + ".probe"
        try:
            f = open(path, "ab") if fs is None else fs.open(path, "ab")
            try:
                cost = None
                for _ in range(samples):
                    t0 = time.perf_counter()
                    f.write(b"p")
                    f.flush()
                    if fs is None:
                        _os.fsync(f.fileno())
                    else:
                        fs.fsync(f)
                    dt = time.perf_counter() - t0
                    cost = dt if cost is None else min(cost, dt)
            finally:
                f.close()
                try:
                    (_os.unlink if fs is None else fs.remove)(path)
                except OSError:
                    pass
            self._probes += 1
            return cost or 0.0
        except OSError:
            return 0.0

    def reprobe(self) -> float:
        """Refresh the device probe (min-of-samples) and re-derive the
        strategy: mode "auto" re-decides engagement, mode "force" only
        re-paces the accumulation window.  Construction calls this for
        forced mode; tests/operators may call it whenever the device
        characteristics changed."""
        p = self._probe_device(self._fs, samples=5)
        self._device_probe_s = p
        if self._mode == "auto":
            self._journal_engaged = (
                self._journal is not None and p >= 0.0005
            )
        return p

    def status(self) -> dict:
        """Introspection (the ``lease_status`` pattern): which strategy
        the probe chose, what it measured, and where durability happens
        (worker sink vs in-process)."""
        j = self._journal
        snk = getattr(j, "sink", None) if j is not None else None
        return {
            "mode": self._mode,
            "engaged": self._journal_engaged,
            "probe_ms": round(self._device_probe_s * 1e3, 4),
            "probes": self._probes,
            "window_ms": round(self._adaptive_window_s() * 1e3, 4),
            "journal": j is not None,
            "journal_bytes": j.bytes if j is not None else 0,
            "journal_fsyncs": j.fsyncs if j is not None else 0,
            "worker_sink": bool(
                snk is not None and getattr(snk, "attached", False)
            ),
            "flushes": self.flushes,
            "amortization": round(self.amortization, 2),
        }

    def _adaptive_window_s(self) -> float:
        if self.window_s:
            return self.window_s
        if not self._journal_engaged:
            return 0.0
        # pace by half the device barrier cost, capped single-digit ms
        return min(self._device_probe_s / 2.0, 0.004)

    def flush(self, updates: list) -> None:
        """Persist ``updates`` (blocking until fsynced).  Raises whatever
        the merged persist raised."""
        if not self._journal_engaged and (
            self._journal is None or not self._journal.nonempty()
        ):
            # fast durability device: merging saves under one leader
            # measured as a net LOSS there (serializing sub-ms barriers
            # that would otherwise overlap across committers, while the
            # merge amortizes nothing) — take the classic concurrent
            # per-committer save, which is the uncompartmented path
            # exactly.  The leader protocol below engages only where the
            # device probe says barriers are worth amortizing.
            self.flushes += 1
            self.submissions += 1
            self.updates_flushed += len(updates)
            self.logdb.save_raft_state(updates)
            return
        slot = [False, None]
        with self._cv:
            if self._stopped:
                raise RuntimeError("group-commit WAL stopped")
            self._q.append((updates, slot))
            while True:
                if slot[0]:
                    # a leader completed us (rider path)
                    if slot[1] is not None:
                        raise slot[1]
                    return
                if not self._flushing:
                    self._flushing = True
                    break  # leadership: persist the queue ourselves
                self._cv.wait(0.2)
                if self._stopped and not slot[0]:
                    raise RuntimeError("group-commit WAL stopped")
            window = self._adaptive_window_s()
            if window:
                # accumulation window: trade up to this much commit
                # latency for deeper merge — worth it exactly when the
                # device barrier is the bottleneck (see
                # _adaptive_window_s; an explicit window_ms pins it)
                self._cv.wait(window)
            batch, self._q = self._q, []
        err = self._persist(batch)
        with self._cv:
            self._flushing = False
            for _, s in batch:
                s[0] = True
                s[1] = err
            self._cv.notify_all()
        if err is not None:
            raise err

    def _persist(self, batch: list) -> Optional[BaseException]:
        """Leader half, OUTSIDE the lock: one merged save (+fsync)."""
        merged = [ud for updates, _ in batch for ud in updates]
        t0 = time.perf_counter()
        err: Optional[BaseException] = None
        try:
            if merged:
                if self._journal is not None and (
                    self._journal_engaged or self._journal.nonempty()
                ):
                    if self.logdb.save_raft_state_journaled(merged):
                        self._since_checkpoint += 1
                        if len(batch) <= 1:
                            self._single_streak += 1
                        else:
                            self._single_streak = 0
                        # checkpoint on cadence, or when load has fallen
                        # back to single-rider cycles (drain the journal
                        # so quiet-period cycles return to the classic
                        # direct path — see save_raft_state_journaled's
                        # journal-empty rule)
                        if self._since_checkpoint >= self.CHECKPOINT_EVERY or (
                            self._single_streak >= 4
                        ):
                            self._since_checkpoint = 0
                            self._single_streak = 0
                            self.logdb.journal_checkpoint()
                else:
                    self.logdb.save_raft_state(merged)
        except Exception as e:  # noqa: BLE001 — re-raised in participants
            err = e
            plog.exception("group-commit flush cycle failed")
        self.flushes += 1
        self.submissions += len(batch)
        self.updates_flushed += len(merged)
        obs = self._obs
        if obs is not None:
            obs.wal_flush(
                riders=len(batch), updates=len(merged),
                wall_ms=(time.perf_counter() - t0) * 1e3,
                amortization=self.amortization,
            )
        return err

    @property
    def amortization(self) -> float:
        """Committer submissions per fsync cycle (>1 = amortizing)."""
        return self.submissions / self.flushes if self.flushes else 0.0

    def stop(self) -> None:
        # no thread to join — just refuse new work and wake any riders
        # whose leader died with them (their error marks the shutdown)
        with self._cv:
            self._stopped = True
            batch, self._q = self._q, []
            for _, slot in batch:
                if not slot[0]:
                    slot[0] = True
                    slot[1] = RuntimeError("group-commit WAL stopped")
            self._cv.notify_all()

    def stats(self) -> dict:
        return {
            "flushes": self.flushes,
            "submissions": self.submissions,
            "updates": self.updates_flushed,
            "amortization": round(self.amortization, 2),
        }


class ApplyPool:
    """Dedicated apply executors (sharded by group id so one group's task
    batches stay on one worker — ``Node.handle_apply_tasks`` additionally
    serializes against the fast lane's inline pump)."""

    def __init__(self, get_node: Callable[[int], Optional["Node"]],
                 workers: int = 2, obs=None):
        self.get_node = get_node
        self.count = max(1, workers)
        self._cvs = [threading.Condition() for _ in range(self.count)]
        self._ready: List[set] = [set() for _ in range(self.count)]
        self._stopped = False
        self._obs = obs
        self.batches = 0
        self._threads = []
        for i in range(self.count):
            t = threading.Thread(
                target=self._main, args=(i,),
                name=f"host-apply-{i}", daemon=True,
            )
            t.start()
            self._threads.append(t)

    def submit(self, cluster_id: int) -> None:
        idx = cluster_id % self.count
        cv = self._cvs[idx]
        with cv:
            self._ready[idx].add(cluster_id)
            cv.notify()

    def depth(self) -> int:
        """Groups queued for apply across all executors (health sample;
        GIL-atomic len reads — gauge-grade, no locks taken)."""
        return sum(len(s) for s in self._ready)

    def _main(self, idx: int) -> None:
        cv = self._cvs[idx]
        while True:
            with cv:
                while not self._ready[idx] and not self._stopped:
                    cv.wait(0.2)
                if self._stopped:
                    return
                ready, self._ready[idx] = self._ready[idx], set()
            for cid in ready:
                # get_node reads the AUTHORITATIVE live cluster dict (the
                # node is stored before any signal fires, nodehost's
                # start contract), so None here means stopped/removed —
                # unlike the engine's cached worker maps, there is no
                # stale-map window needing a _rearm_unknown defense
                node = self.get_node(cid)
                if node is None:
                    continue
                try:
                    node.handle_apply_tasks()
                except Exception:
                    plog.exception("host apply worker failed on %d", cid)
            self.batches += 1
            obs = self._obs
            if obs is not None:
                obs.apply_batch(groups=len(ready))

    def stop(self) -> None:
        self._stopped = True
        for cv in self._cvs:
            with cv:
                cv.notify()
        for t in self._threads:
            t.join(timeout=2)


class EgressPool:
    """Client-completion executors: ``RequestState.notify`` (the
    ``Event.set`` that wakes a client thread) moves off the apply workers
    onto these, batched per wakeup.  Sharded by request key so a single
    future is only ever notified from one worker; per-shard FIFO keeps
    completion order stable for one group's stream (group → committer →
    apply worker → same-key shard)."""

    #: two completions closer together than this are a storm — the
    #: second and later ones batch onto the worker (adaptive: an idle
    #: plane keeps the off-mode single-hop latency; a bursty one moves
    #: the client-wakeup storm off the apply worker)
    BURST_S = 0.0005

    def __init__(self, workers: int = 1, obs=None):
        self.count = max(1, workers)
        self._cvs = [threading.Condition() for _ in range(self.count)]
        self._qs: List[list] = [[] for _ in range(self.count)]
        self._busy = [False] * self.count
        self._stopped = False
        self._obs = obs
        self.notified = 0
        self.inline = 0
        self._last_notify = 0.0
        self._streak = 0
        self._threads = []
        for i in range(self.count):
            t = threading.Thread(
                target=self._main, args=(i,),
                name=f"host-egress-{i}", daemon=True,
            )
            t.start()
            self._threads.append(t)

    def __call__(self, rs, result) -> None:
        """The sink ``PendingProposal``/``PendingReadIndex`` call in place
        of ``rs.notify(result)``.  Adaptive: a quiet shard notifies
        inline (no handoff — the off-mode latency); once completions
        queue faster than the worker drains them, the storm batches onto
        the worker thread."""
        now = time.perf_counter()
        if now - self._last_notify < self.BURST_S:
            self._streak += 1
        else:
            self._streak = 0
        self._last_notify = now
        idx = rs.key % self.count
        cv = self._cvs[idx]
        with cv:
            # a SUSTAINED storm (3+ back-to-back completions) or an
            # already-engaged worker routes to the pool; occasional close
            # pairs stay inline — a lone handoff costs a scheduling
            # quantum and amortizes nothing
            if not self._stopped and (
                self._streak >= 2 or self._busy[idx] or self._qs[idx]
            ):
                self._qs[idx].append((rs, result))
                cv.notify()
                return
        self.inline += 1
        rs.notify(result)

    def depth(self) -> int:
        """Completions queued for delivery (health sample; gauge-grade
        GIL-atomic reads)."""
        return sum(len(q) for q in self._qs)

    def _main(self, idx: int) -> None:
        cv = self._cvs[idx]
        while True:
            with cv:
                self._busy[idx] = False
                while not self._qs[idx] and not self._stopped:
                    cv.wait(0.2)
                if self._stopped and not self._qs[idx]:
                    return
                batch, self._qs[idx] = self._qs[idx], []
                self._busy[idx] = True
            for rs, result in batch:
                try:
                    rs.notify(result)
                except Exception:
                    plog.exception("egress notify failed")
            self.notified += len(batch)
            obs = self._obs
            if obs is not None:
                obs.egress_batch(len(batch))

    def stop(self) -> None:
        self._stopped = True
        for cv in self._cvs:
            with cv:
                cv.notify()
        for t in self._threads:
            t.join(timeout=2)


class HostPlane:
    """The three tiers plus their wiring surface (built by NodeHost when
    ``ExpertConfig.host_compartments`` is on)."""

    def __init__(
        self,
        logdb,
        get_node: Callable[[int], Optional["Node"]],
        ingress_shards: int = 0,
        ingress_ring: int = 0,
        wal_window_ms: float = 0.0,
        apply_workers: int = 0,
        egress_workers: int = 0,
        fs=None,
        hostproc=None,
        wal_journal_mode: str = "auto",
    ):
        self._obs = None
        self.hostproc = hostproc
        self.ingress = ProposalIngress(
            shards=ingress_shards or 2, ring_cap=ingress_ring,
            hostproc=hostproc,
        )
        self.wal = GroupCommitWAL(
            logdb, window_ms=wal_window_ms, fs=fs,
            journal_mode=wal_journal_mode, hostproc=hostproc,
        )
        # default matches the engine's apply-worker count: fewer dedicated
        # executors than the engine pool they replace measured ~5% off on
        # the many-session axis (apply batches queued behind each other)
        self.apply_pool = ApplyPool(get_node, workers=apply_workers or 4)
        self.egress = EgressPool(workers=egress_workers or 1)
        self.logdb = logdb

    def enable_obs(self, registry=None, recorder=None):
        """Attach the ``dragonboat_host_*`` instruments (same
        ``is not None`` latch contract as the device plane: obs-off keeps
        every tier's hot path bit-identical)."""
        from .obs.instruments import HostObs

        if self._obs is None or registry is not None or recorder is not None:
            self._obs = HostObs(recorder=recorder, registry=registry)
            self.ingress._obs = self._obs
            self.wal._obs = self._obs
            self.apply_pool._obs = self._obs
            self.egress._obs = self._obs
        return self._obs

    def wake_nodes(self, nodes) -> None:
        """Coalesced step-ready fan-out for the device-plane coordinator:
        one signal per touched group per round instead of one per offload
        effect (the coordinator feeds the same ingress tier's wakeup
        discipline)."""
        for n in nodes:
            n.nh.engine.set_step_ready(n.cluster_id)

    def fsync_count(self) -> int:
        fn = getattr(self.logdb, "fsync_count", None)
        return fn() if fn is not None else 0

    def health_snapshot(self) -> dict:
        """Host-plane depths for the cluster health sampler (ISSUE 13):
        per-shard staging-ring occupancy, the WAL strategy/window, and
        the apply/egress queue depths — gauge-grade unlocked reads (the
        sampler must never queue behind a drain or a flush)."""
        ing = self.ingress
        shards = [
            {"ringed": sh.ncmds, "cap": sh.cap} for sh in ing._shards
        ]
        w = self.wal.status()
        return {
            "ingress": {
                "shards": shards,
                "ringed": sum(s["ringed"] for s in shards),
                "submitted": ing.submitted,
                "drains": ing.drains,
            },
            "wal": {
                "mode": w["mode"],
                "engaged": w["engaged"],
                "window_ms": w["window_ms"],
                "flushes": w["flushes"],
                "amortization": w["amortization"],
                "worker_sink": w["worker_sink"],
            },
            "apply_depth": self.apply_pool.depth(),
            "egress_depth": self.egress.depth(),
        }

    def stats(self) -> dict:
        out = {
            "ingress": self.ingress.stats(),
            "wal": self.wal.stats(),
            "wal_status": self.wal.status(),
            "apply_batches": self.apply_pool.batches,
            "egress_notified": self.egress.notified,
            "egress_inline": self.egress.inline,
            "fsyncs": self.fsync_count(),
        }
        if self.hostproc is not None:
            out["hostproc"] = self.hostproc.stats()
        return out

    def stop(self) -> None:
        self.ingress.stop()
        self.apply_pool.stop()
        self.egress.stop()
        self.wal.stop()
