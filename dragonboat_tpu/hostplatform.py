"""Host-side jax platform helpers shared by driver hooks and benches.

The environment registers a tunneled TPU backend ("axon") via sitecustomize;
its init can hang (not just fail) when the tunnel is down, so anything that
must run reliably (tests, the multichip dryrun, bench fallback paths) forces
the CPU platform *before* first backend use and drops the tunneled factory.
"""
from __future__ import annotations

import os
import re


def force_cpu() -> None:
    """Force the CPU platform and drop the tunneled backend factory.

    Safe to call before or after ``import jax`` but must run before the
    first backend init in this process.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["JAX_PLATFORM_NAME"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        from jax._src import xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
    except Exception:
        pass


def set_host_device_count(n: int) -> None:
    """Ensure XLA_FLAGS requests >= n virtual host (CPU) devices.

    Replaces any existing smaller ``--xla_force_host_platform_device_count``
    value instead of substring-checking, so a stale count from the caller's
    environment cannot survive.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if m:
        if int(m.group(1)) >= n:
            return
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+",
            f"--xla_force_host_platform_device_count={n}",
            flags,
        )
    else:
        flags = (flags + f" --xla_force_host_platform_device_count={n}").strip()
    os.environ["XLA_FLAGS"] = flags


def clear_backends() -> None:
    """Best-effort reset of jax's backend cache (e.g. after flag changes)."""
    try:
        import jax.extend.backend as _eb

        _eb.clear_backends()
    except Exception:
        pass
