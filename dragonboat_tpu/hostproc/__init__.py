"""Multi-process host plane (ISSUE 12): escape the GIL by promoting the
per-shard host-plane stages to worker processes connected by
shared-memory staging rings.

Layout:

- :mod:`rings`   — SPSC shared-memory byte rings (length-prefixed blobs,
  seqlock-style head/tail cursors, busy→event doorbell layered above);
- :mod:`workers` — the spawned worker process: ingress payload encode,
  the redo-journal append+fsync cycle, and the apply tier holding state
  machines built from process-spawnable factories;
- :mod:`control` — spawn/handshake/heartbeat/restart/drain-and-stop,
  plus the host-side lane clients and their in-process fallbacks;
- :mod:`sm`      — the ``ProcStateMachine`` proxy with snapshot+redo
  crash fallback.

Everything is gated by ``ExpertConfig.host_workers`` (default 0 = the
in-process compartmentalized plane, structurally bit-identical to the
pre-hostproc build).  This ``__init__`` stays import-light on purpose:
spawned workers execute it on their startup path.
"""
from __future__ import annotations

__all__ = [
    "HostProcPlane",
    "ProcStateMachine",
    "spawnable",
    "spawnable_spec",
]


def spawnable(factory):
    """Mark a module-level state-machine factory (class or callable
    taking ``(cluster_id, node_id)``) as safe to instantiate inside a
    hostproc apply worker.  Decorator-friendly."""
    factory.__hostproc_spawnable__ = True
    return factory


def spawnable_spec(factory) -> "str | None":
    """``module:qualname`` spec for a spawnable factory, or None when
    the factory did not opt in / cannot be imported from a worker
    (``__main__`` scripts, closures, instance-bound callables)."""
    if not getattr(factory, "__hostproc_spawnable__", False):
        return None
    mod = getattr(factory, "__module__", None)
    qual = getattr(factory, "__qualname__", None)
    if not mod or not qual or mod == "__main__" or "<locals>" in qual:
        return None
    return f"{mod}:{qual}"


def __getattr__(name):
    # lazy: workers importing this package must not pull the host-side
    # control plane (multiprocessing spawn machinery) or the proxy
    if name == "HostProcPlane":
        from .control import HostProcPlane

        return HostProcPlane
    if name == "ProcStateMachine":
        from .sm import ProcStateMachine

        return ProcStateMachine
    raise AttributeError(name)
