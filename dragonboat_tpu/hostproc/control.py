"""Control plane for the multi-process host tier: spawn, handshake,
heartbeat, crash detection/restart, drain-and-stop.

The :class:`HostProcPlane` owns every shared-memory ring and every
worker process.  Topology: ``encode`` lanes (one per ingress staging
shard) and one ``wal`` lane ride the first workers round-robin; every
worker additionally serves one ``apply`` lane (state-machine proxies
shard onto them by cluster id).  All lanes are request/response ring
PAIRS; the host side of a pair is a :class:`RingClient` whose per-call
lock makes it the ring's single logical producer.

Failure contract (the design's robustness half, not an afterthought):

- a worker that exits — crash, kill -9, OOM — is detected by the
  monitor thread (``Process`` liveness + a shared-memory heartbeat
  stamp); its lanes flip ``alive=False`` and every in-flight waiter is
  woken to raise :class:`WorkerGone`;
- callers FALL BACK IN-PROCESS on ``WorkerGone``: the ingress batcher
  encodes inline, the journal appends+fsyncs on the flush leader's
  thread, and SM proxies rebuild from their snapshot+redo buffer
  (``sm.ProcStateMachine``) — nothing acked-before-fsync is ever
  violated because the ack only happens after SOME fsync returned, and
  an ambiguous worker-side append is simply re-appended (journal replay
  is idempotent);
- the monitor respawns dead workers (bounded by ``MAX_RESTARTS``) after
  RESETTING their rings, so a fresh worker never replays a dead one's
  backlog; lanes re-arm with a bumped ``epoch`` — stateful users (SM
  proxies) observe the epoch change and stay fallen-back, stateless
  users (encode, WAL) simply resume;
- a ring that stays full past the producer's busy window raises
  :class:`dragonboat_tpu.requests.SystemBusyError` — the same
  backpressure surface as a full ingress staging ring;
- ``stop()`` drains deterministically: callers are quiesced first by
  the NodeHost (hostplane stops before hostproc), each worker gets an
  ``OP_STOP`` it answers after finishing its backlog, and only then is
  the process tree joined/terminated and the segments unlinked.
"""
from __future__ import annotations

import multiprocessing
import threading
import time
from typing import Dict, List, Optional

from ..logger import get_logger
from ..requests import SystemBusyError
from . import workers as wp
from .rings import RingClosed, ShmRing

plog = get_logger("hostproc")


class WorkerGone(RuntimeError):
    """The lane's worker is dead/unreachable — fall back in-process."""


class WorkerError(OSError):
    """The worker executed the op and reported a failure (e.g. a real —
    or injected — fsync error).  NOT a fallback signal: the op genuinely
    failed, exactly as it would have in-process."""


class RingClient:
    """Host-side endpoint of one lane (request ring + response ring +
    doorbells).  ``call`` is one synchronous round trip; the internal
    lock admits one outstanding request per lane, which keeps seq
    correlation FIFO and the shared-memory side strictly SPSC."""

    __slots__ = ("plane", "role", "req", "resp", "worker_id", "alive",
                 "epoch", "_mu", "_seq", "calls", "wall_us_total")

    def __init__(self, plane, role: str, req: ShmRing, resp: ShmRing,
                 worker_id: int):
        self.plane = plane
        self.role = role
        self.req = req
        self.resp = resp
        self.worker_id = worker_id
        self.alive = False
        self.epoch = 0
        self._mu = threading.Lock()
        self._seq = 0
        self.calls = 0
        self.wall_us_total = 0

    def call(self, op: int, body: bytes = b"", timeout: float = 10.0,
             busy_timeout: float = 0.05) -> bytes:
        """One round trip.  Raises :class:`SystemBusyError` when the
        request ring stays full past ``busy_timeout`` (sustained-full
        backpressure), :class:`WorkerGone` when the worker is dead or
        unresponsive past ``timeout``, :class:`WorkerError` when the
        worker reports the op failed."""
        try:
            return self._call_locked(op, body, timeout, busy_timeout)
        except RingClosed as e:
            # plane stopped underneath the caller: same fallback
            # surface as a dead worker
            raise WorkerGone(str(e)) from e

    def _call_locked(self, op: int, body: bytes, timeout: float,
                     busy_timeout: float) -> bytes:
        with self._mu:
            if not self.alive:
                raise WorkerGone(f"{self.role} worker {self.worker_id} down")
            self._seq = seq = (self._seq + 1) & 0xFFFFFFFF
            rec = wp.pack_req(op, seq, body)
            if 4 + len(rec) > self.req.cap:
                # an oversized payload can never fit this ring: surface
                # the in-process fallback path, not a crash (a journal
                # cycle or SM snapshot larger than the ring is legal)
                raise WorkerGone(
                    f"{self.role} request of {len(rec)}B exceeds ring "
                    f"capacity {self.req.cap}"
                )
            deadline = time.perf_counter() + busy_timeout
            spins = 0
            while not self.req.push(rec):
                if not self.alive:
                    # checked INSIDE the loop so the monitor can safely
                    # reset a dead worker's rings: it takes _mu first,
                    # and any in-flight producer drains out through
                    # this check instead of writing over the reset
                    raise WorkerGone(
                        f"{self.role} worker {self.worker_id} died mid-push"
                    )
                if time.perf_counter() > deadline:
                    self.plane._count_busy(self.role)
                    raise SystemBusyError()
                spins += 1
                time.sleep(0 if spins < 100 else 0.0005)
            deadline = time.perf_counter() + timeout
            spins = 0
            while True:
                blob = self.resp.pop()
                if blob is not None:
                    _op, rseq, status, wall_us, rbody = wp.unpack_resp(blob)
                    if rseq != seq:
                        # stale response from a timed-out earlier call on
                        # this lane — discard and keep draining (seqs are
                        # FIFO, ours is still ahead)
                        continue
                    break
                if not self.alive:
                    raise WorkerGone(
                        f"{self.role} worker {self.worker_id} died mid-call"
                    )
                if time.perf_counter() > deadline:
                    raise WorkerGone(
                        f"{self.role} worker {self.worker_id} unresponsive"
                    )
                spins += 1
                if spins < 200:
                    time.sleep(0)
                else:
                    # tiered sleep-poll, NOT a semaphore doorbell: a
                    # kill -9'd worker can die holding a posix-sem
                    # event's lock and deadlock every later set()/wait()
                    time.sleep(0.0002 if spins < 1000 else 0.001)
            self.calls += 1
            self.wall_us_total += wall_us
        obs = self.plane._obs
        if obs is not None:
            obs.call(self.role, wall_us / 1e3)
        if status != wp.ST_OK:
            raise WorkerError(rbody.decode("utf-8", "replace"))
        return rbody

    def depth(self) -> int:
        try:
            return self.req.depth() + self.resp.depth()
        except Exception:
            return 0


class EncodeLane:
    """Ingress-batcher facing wrapper: encode one command burst on the
    worker; ``None`` means fall back to the inline encode (worker gone
    or ring busy — the staging-ring cap stays the client-visible
    backpressure surface)."""

    __slots__ = ("_c",)

    def __init__(self, client: RingClient):
        self._c = client

    def encode(self, ct: int, cmds) -> Optional[list]:
        c = self._c
        if not c.alive:
            return None
        try:
            out = c.call(
                wp.OP_ENCODE, bytes([ct]) + wp.pack_cmds(cmds),
                timeout=5.0, busy_timeout=0.01,
            )
        except (WorkerGone, SystemBusyError):
            c.plane._count_fallback("encode")
            return None
        except WorkerError:
            c.plane._count_fallback("encode")
            return None
        encs, _ = wp.unpack_cmds(out)
        return encs


class WalSink:
    """Journal-facing wrapper (see ``logdb.journal.HostJournal.sink``):
    ``append``/``truncate`` return True when the worker performed the
    durable op, False when the worker tier is unavailable (the journal
    falls back to its own in-process write+fsync), and raise
    :class:`WorkerError` (an ``OSError``) when the worker REALLY failed
    the op — that failure propagates to the flush cycle exactly like an
    in-process fsync error, so nothing is acked."""

    __slots__ = ("_c", "_opened_epoch")

    def __init__(self, client: RingClient):
        self._c = client
        self._opened_epoch = -1

    def _ensure_open(self, path: str) -> bool:
        c = self._c
        if self._opened_epoch == c.epoch:
            return True
        c.call(wp.OP_WAL_OPEN, path.encode("utf-8"), timeout=10.0)
        self._opened_epoch = c.epoch
        return True

    def append(self, path: str, rec: bytes) -> bool:
        c = self._c
        if not c.alive:
            return False
        try:
            self._ensure_open(path)
            c.call(wp.OP_WAL_APPEND, rec, timeout=30.0, busy_timeout=0.25)
            return True
        except (WorkerGone, SystemBusyError):
            c.plane._count_fallback("wal")
            return False
        # WorkerError propagates: the op ran and failed (real or
        # injected fsync error) — the flush cycle must fail, not ack

    def truncate(self, path: str, expected_bytes: int = 0) -> bool:
        """Size-guarded: the worker refuses when the file is not exactly
        ``expected_bytes`` long (a stale abandoned truncate executing
        late would otherwise wipe acked records) — the refusal comes
        back as WorkerError and the journal falls back to its own
        in-process truncate."""
        c = self._c
        if not c.alive:
            return False
        try:
            self._ensure_open(path)
            c.call(
                wp.OP_WAL_TRUNC,
                wp._U64.pack(max(0, expected_bytes)),
                timeout=30.0, busy_timeout=0.25,
            )
            return True
        except (WorkerGone, SystemBusyError):
            c.plane._count_fallback("wal")
            return False
        except WorkerError:
            c.plane._count_fallback("wal")
            return False

    @property
    def attached(self) -> bool:
        return self._c.alive


class _WorkerRec:
    __slots__ = ("wid", "proc", "hb", "pairs", "restarts", "down")

    def __init__(self, wid):
        self.wid = wid
        self.proc = None
        self.hb = None
        self.pairs: List[RingClient] = []
        self.restarts = 0
        self.down = False


class HostProcPlane:
    """Spawn + own the worker tier.  Built by NodeHost when
    ``ExpertConfig.host_workers > 0``; everything here is absent at the
    default 0 (the in-process host plane is structurally untouched)."""

    #: bounded respawns per worker — a crash-looping worker devolves to
    #: the in-process path instead of burning cores on restarts
    MAX_RESTARTS = 3
    #: heartbeat staleness that earns a warning (NOT a kill: a worker
    #: blocked in a long fsync is slow, not dead — Process liveness is
    #: the authoritative death signal)
    HB_STALE_S = 15.0

    def __init__(self, workers: int = 1, encode_lanes: int = 2,
                 ring_bytes: int = 1 << 20, spawn_timeout: float = 60.0):
        import os as _os

        self.nworkers = max(1, int(workers))
        # topology-adaptive engagement: a cross-process round trip costs
        # 1-2 scheduling quanta, so stage offload pays only when spare
        # cores can hide it — on a single-core box every tier would
        # time-slice the serving process and LOSE throughput (measured
        # ~0.2x on the sessions axis), so the default there is
        # spawn-but-idle (crash-safe plumbing stays testable, the ledger
        # records the limitation).  DBTPU_HOSTPROC_OFFLOAD=1 forces full
        # engagement (differential tests, perf experiments); the WAL
        # sink additionally self-engages when the durability barrier
        # dwarfs the handoff (see GroupCommitWAL).
        self.offload_default = (
            (_os.cpu_count() or 1) > 1
            or _os.environ.get("DBTPU_HOSTPROC_OFFLOAD") == "1"
        )
        self._ctx = multiprocessing.get_context("spawn")
        self._obs = None
        self._stopping = False
        self._mu = threading.Lock()
        self._busy: Dict[str, int] = {}
        self._fallbacks: Dict[str, int] = {}
        self._monitor: Optional[threading.Thread] = None
        self.restarts_total = 0
        self._workers = [_WorkerRec(i) for i in range(self.nworkers)]
        self.encode_lanes: List[RingClient] = []
        self.wal_lane: Optional[RingClient] = None
        self.apply_lanes: List[RingClient] = []
        # ---- lanes ----
        def mk_lane(role, wid):
            c = RingClient(
                self, role,
                ShmRing(capacity=ring_bytes),
                ShmRing(capacity=ring_bytes),
                wid,
            )
            self._workers[wid].pairs.append(c)
            return c

        for i in range(max(1, encode_lanes)):
            self.encode_lanes.append(mk_lane("encode", i % self.nworkers))
        self.wal_lane = mk_lane("wal", 0)
        for i in range(self.nworkers):
            self.apply_lanes.append(mk_lane("apply", i))
        # ---- spawn + handshake ----
        for rec in self._workers:
            self._spawn(rec)
        deadline = time.monotonic() + spawn_timeout
        for rec in self._workers:
            while rec.hb.value == 0.0 and rec.proc.exitcode is None:
                if time.monotonic() > deadline:
                    break
                time.sleep(0.005)
            if rec.hb.value == 0.0:
                self.stop()
                raise RuntimeError(
                    f"hostproc worker {rec.wid} failed its spawn handshake"
                )
            for c in rec.pairs:
                c.alive = True
        self._monitor = threading.Thread(
            target=self._monitor_main, name="hostproc-monitor", daemon=True
        )
        self._monitor.start()
        plog.info(
            "hostproc plane up: %d workers, %d encode lanes, 1 wal lane, "
            "%d apply lanes", self.nworkers, len(self.encode_lanes),
            len(self.apply_lanes),
        )

    # ---- spawn / respawn ----

    def _spawn(self, rec: _WorkerRec) -> None:
        # the heartbeat is a LOCKLESS shared double (raw shared memory):
        # nothing here is semaphore-backed, so a kill -9'd worker cannot
        # strand a lock the host would later block on.  Its first stamp
        # doubles as the spawn handshake.
        rec.hb = self._ctx.Value("d", 0.0, lock=False)
        specs = [(c.req.name, c.resp.name) for c in rec.pairs]
        rec.proc = self._ctx.Process(
            target=wp.worker_main,
            args=(rec.wid, specs, rec.hb),
            name=f"hostproc-worker-{rec.wid}",
            daemon=True,
        )
        rec.proc.start()

    def _monitor_main(self) -> None:
        warned_stale = set()
        while not self._stopping:
            time.sleep(0.15)
            if self._stopping:
                return
            try:
                self._monitor_tick(warned_stale)
            except Exception:
                # the monitor IS the crash detector — it must survive
                # its own failures (spawn OSError under fd pressure, a
                # segment closed by a concurrent stop) or dead workers
                # stop being detected and every call eats its full
                # timeout instead of failing fast to the fallback
                plog.exception("hostproc monitor tick failed")

    def _monitor_tick(self, warned_stale) -> None:
            for rec in self._workers:
                p = rec.proc
                if p is None:
                    continue
                if p.exitcode is not None and not rec.down:
                    # death: poison the lanes FIRST (wake any in-flight
                    # waiter into WorkerGone), then decide on respawn
                    rec.down = True
                    for c in rec.pairs:
                        c.alive = False  # in-flight waiters poll this
                    plog.warning(
                        "hostproc worker %d exited (code %s); lanes fell "
                        "back in-process", rec.wid, p.exitcode,
                    )
                    obs = self._obs
                    if obs is not None:
                        # the dead lane's rings still hold its ghost
                        # backlog until the respawn resets them —
                        # ring_depth() excludes down lanes, so republish
                        # NOW or a scrape between death and respawn
                        # (forever, when MAX_RESTARTS is exhausted)
                        # keeps showing the dead epoch's bytes
                        obs.workers_alive(self.alive_count())
                        obs.ring_depth(self.ring_depth())
                    if self._stopping or rec.restarts >= self.MAX_RESTARTS:
                        continue
                    rec.restarts += 1
                    self.restarts_total += 1
                    if obs is not None:
                        obs.restart()
                    # a fresh worker must not replay the dead one's
                    # backlog: reset ring cursors while nothing is
                    # attached — under each client's call lock, so an
                    # in-flight producer (which re-checks ``alive``
                    # every push/pop iteration) has fully drained out
                    # before the cursors move
                    for c in rec.pairs:
                        with c._mu:
                            c.req.reset()
                            c.resp.reset()
                    self._spawn(rec)
                    hs = time.monotonic() + 30.0
                    while (rec.hb.value == 0.0
                           and rec.proc.exitcode is None
                           and time.monotonic() < hs):
                        time.sleep(0.01)
                    if rec.hb.value:
                        rec.down = False
                        for c in rec.pairs:
                            c.epoch += 1   # stateful users stay fallen-back
                            c.alive = True
                        plog.info("hostproc worker %d respawned", rec.wid)
                        if obs is not None:
                            # epoch bump: fresh rings, fresh epoch —
                            # republish both gauges so the scrape flips
                            # with the lane, not a monitor period later
                            obs.workers_alive(self.alive_count())
                            obs.ring_depth(self.ring_depth())
                    else:
                        plog.error(
                            "hostproc worker %d respawn handshake failed",
                            rec.wid,
                        )
                elif p.exitcode is None and rec.hb.value:
                    stale = time.monotonic() - rec.hb.value
                    if stale > self.HB_STALE_S and rec.wid not in warned_stale:
                        warned_stale.add(rec.wid)
                        plog.warning(
                            "hostproc worker %d heartbeat stale %.1fs "
                            "(blocked in a long op?)", rec.wid, stale,
                        )
                    elif stale < self.HB_STALE_S:
                        warned_stale.discard(rec.wid)
            obs = self._obs
            if obs is not None:
                obs.ring_depth(self.ring_depth())

    # ---- lane accessors ----

    def encode_lane(self, shard_idx: int) -> EncodeLane:
        return EncodeLane(self.encode_lanes[shard_idx % len(self.encode_lanes)])

    def wal_sink(self) -> WalSink:
        return WalSink(self.wal_lane)

    def apply_client(self, cluster_id: int) -> RingClient:
        return self.apply_lanes[cluster_id % len(self.apply_lanes)]

    # ---- counters / obs ----

    def _count_busy(self, role: str) -> None:
        with self._mu:
            self._busy[role] = self._busy.get(role, 0) + 1
        obs = self._obs
        if obs is not None:
            obs.ring_full(role)

    def _count_fallback(self, role: str) -> None:
        with self._mu:
            self._fallbacks[role] = self._fallbacks.get(role, 0) + 1
        obs = self._obs
        if obs is not None:
            obs.fallback(role)

    def enable_obs(self, registry=None):
        from ..obs.instruments import HostProcObs

        if self._obs is None or registry is not None:
            self._obs = HostProcObs(registry=registry)
            self._obs.workers_alive(self.alive_count())
        return self._obs

    def alive_count(self) -> int:
        return sum(
            1 for r in self._workers
            if r.proc is not None and r.proc.exitcode is None and not r.down
        )

    def ring_depth(self) -> int:
        """Bytes staged across LIVE lanes' shared-memory rings.  Dead
        lanes are excluded (ISSUE 13 satellite): their rings hold the
        dead epoch's ghost backlog until the respawn resets the
        cursors — or forever when the restart budget is exhausted —
        and a scrape must never read that as live depth."""
        total = 0
        for r in self._workers:
            p = r.proc
            if r.down or p is None or p.exitcode is not None:
                continue
            total += sum(c.depth() for c in r.pairs)
        return total

    def health_snapshot(self) -> dict:
        """Worker-tier health for the cluster health sampler (ISSUE 13):
        liveness, restart counts and per-worker heartbeat age (the
        lockless shared-double the monitor already watches)."""
        now = time.monotonic()
        per_worker = []
        for r in self._workers:
            p = r.proc
            alive = p is not None and p.exitcode is None and not r.down
            hb = r.hb.value
            per_worker.append({
                "wid": r.wid,
                "alive": alive,
                "restarts": r.restarts,
                "hb_age_s": round(now - hb, 3) if (alive and hb) else None,
            })
        return {
            "workers": self.nworkers,
            "alive": self.alive_count(),
            "restarts": self.restarts_total,
            "ring_depth": self.ring_depth(),
            "per_worker": per_worker,
        }

    def worker_pid(self, wid: int) -> Optional[int]:
        p = self._workers[wid].proc
        return p.pid if p is not None else None

    def inject(self, wid: int, faults: dict) -> None:
        """Test hook: ship an OP_INJECT fault dict to one worker (e.g.
        ``{"wal_fail_fsyncs": 2}`` or ``{"die": True}``)."""
        import json

        self._workers[wid].pairs[0].call(
            wp.OP_INJECT, json.dumps(faults).encode("utf-8"), timeout=10.0
        )

    def stats(self) -> dict:
        lanes = {}
        for role, cs in (
            ("encode", self.encode_lanes),
            ("wal", [self.wal_lane]),
            ("apply", self.apply_lanes),
        ):
            lanes[role] = {
                "calls": sum(c.calls for c in cs),
                "wall_ms": round(sum(c.wall_us_total for c in cs) / 1e3, 3),
            }
        with self._mu:
            busy = dict(self._busy)
            fallbacks = dict(self._fallbacks)
        return {
            "workers": self.nworkers,
            "alive": self.alive_count(),
            "restarts": self.restarts_total,
            "ring_depth": self.ring_depth(),
            "busy": busy,
            "fallbacks": fallbacks,
            "lanes": lanes,
        }

    # ---- lifecycle ----

    def stop(self) -> None:
        """Drain-and-stop: callers were quiesced by the NodeHost (the
        in-process host plane stops first), so each worker's backlog is
        at most what it is already draining; OP_STOP makes it finish
        that backlog, answer, and exit before we join/terminate."""
        if self._stopping:
            return
        self._stopping = True
        for rec in self._workers:
            p = rec.proc
            if p is None:
                continue
            if p.exitcode is None:
                try:
                    rec.pairs[0].call(
                        wp.OP_STOP, timeout=2.0, busy_timeout=0.1
                    )
                except Exception:
                    pass
                p.join(2.0)
            if p.exitcode is None:
                p.terminate()
                p.join(1.0)
            if p.exitcode is None:
                p.kill()
                p.join(1.0)
            for c in rec.pairs:
                c.alive = False
        if self._monitor is not None and self._monitor.is_alive():
            self._monitor.join(timeout=2.0)
        for rec in self._workers:
            for c in rec.pairs:
                c.req.close()
                c.resp.close()
