"""SPSC shared-memory staging rings for the multi-process host plane.

One :class:`ShmRing` is a byte ring over a ``multiprocessing``
``SharedMemory`` segment carrying the same length-prefixed blobs the
host plane already produces (``_pack_len``-style ``<u32 len><payload>``
framing — see :func:`dragonboat_tpu.hostplane._pack_blob`).  The cursor
discipline is seqlock-style single-writer-per-cursor:

- header byte 0:   ``tail`` (u64, total bytes ever pushed) — written
  only by the producer, AFTER the record bytes land;
- header byte 64:  ``head`` (u64, total bytes ever popped) — written
  only by the consumer, AFTER the record bytes were copied out.

The cursors live on separate cache lines and never wrap (u64 of total
bytes; ``cursor % capacity`` is the byte offset), so each side publishes
exactly one aligned 8-byte store and reads the other side's with one
aligned 8-byte load.  On x86-64 (TSO) that ordering is sufficient
without explicit fences: the producer's record stores cannot sink below
its tail store, and the consumer's loads cannot hoist above its tail
load; the CPython eval loop adds further (incidental) fencing around
every buffer op.  Records may split across the physical end of the
buffer — the ring is a byte ring, not a slot ring, so wraparound is two
memcpys instead of a padding marker.

Blocking/wakeup is layered ABOVE the ring (see ``control.RingClient``
and ``workers.worker_main``): a short busy-poll first, then a
futex-backed ``multiprocessing.Event`` doorbell — the ring itself never
sleeps.  A producer that cannot place a record after its busy window
surfaces :class:`dragonboat_tpu.requests.SystemBusyError` to the caller
(the same backpressure contract as a full ingress staging ring).
"""
from __future__ import annotations

import struct
from multiprocessing import shared_memory
from typing import Optional

_U64 = struct.Struct("<Q")
_U32 = struct.Struct("<I")

#: header bytes ahead of the data region: tail @0, head @64 — one cache
#: line apart so the two writers never false-share
HEADER = 128


class RingClosed(RuntimeError):
    """The ring's segment is gone (plane stopped underneath the caller)."""


class ShmRing:
    """One single-producer/single-consumer byte ring in shared memory.

    The CREATOR (host process) passes ``create=True`` and owns unlink;
    workers attach by name with ``create=False``.  Capacity is derived
    from the actual segment size on both sides (the kernel page-rounds
    the requested size), so producer and consumer always agree.
    """

    __slots__ = ("shm", "cap", "_owner", "closed")

    def __init__(self, capacity: int = 1 << 20, name: Optional[str] = None,
                 create: bool = True):
        if create:
            self.shm = shared_memory.SharedMemory(
                create=True, size=HEADER + max(4096, capacity)
            )
            # zero the header (fresh segments are zero-filled on Linux,
            # but be explicit — reset() reuses this path)
            self.shm.buf[:HEADER] = b"\x00" * HEADER
        else:
            self.shm = shared_memory.SharedMemory(name=name)
            # CPython's resource tracker registers ATTACHED segments too
            # (bpo-38119).  The tracker PROCESS is shared with the host
            # (spawn inherits its fd) and its cache is a set, so the
            # attach-register is a no-op — and an unregister here would
            # strip the HOST's entry and make its unlink-at-stop warn.
            # Leave the shared tracker alone: the creator owns the name.
        self.cap = self.shm.size - HEADER
        self._owner = create
        self.closed = False

    # ---- cursors ----

    def _load(self, off: int) -> int:
        return _U64.unpack_from(self.shm.buf, off)[0]

    def _store(self, off: int, v: int) -> None:
        _U64.pack_into(self.shm.buf, off, v)

    @property
    def tail(self) -> int:
        return self._load(0)

    @property
    def head(self) -> int:
        return self._load(64)

    def depth(self) -> int:
        """Bytes currently staged (producer-published, not yet popped)."""
        return self.tail - self.head

    # ---- byte ring IO (wraparound = two memcpys) ----

    def _write(self, pos: int, data: bytes) -> None:
        off = pos % self.cap
        first = min(len(data), self.cap - off)
        base = HEADER + off
        self.shm.buf[base : base + first] = data[:first]
        rest = len(data) - first
        if rest:
            self.shm.buf[HEADER : HEADER + rest] = data[first:]

    def _read(self, pos: int, n: int) -> bytes:
        off = pos % self.cap
        first = min(n, self.cap - off)
        base = HEADER + off
        out = bytes(self.shm.buf[base : base + first])
        rest = n - first
        if rest:
            out += bytes(self.shm.buf[HEADER : HEADER + rest])
        return out

    # ---- SPSC API ----

    def push(self, blob: bytes) -> bool:
        """Place one length-prefixed record; False when it doesn't fit
        (the caller busy-waits / escalates to SystemBusy — see module
        docstring).  Only ever called from ONE producer at a time (the
        host side serializes with a per-ring lock; logically still SPSC
        at the memory level)."""
        if self.closed:
            raise RingClosed()
        n = 4 + len(blob)
        if n > self.cap:
            raise ValueError(
                f"record of {len(blob)} bytes exceeds ring capacity {self.cap}"
            )
        tail = self._load(0)
        if self.cap - (tail - self._load(64)) < n:
            return False
        self._write(tail, _U32.pack(len(blob)))
        if blob:
            self._write(tail + 4, blob)
        # publish: the ONE producer-side store consumers order on
        self._store(0, tail + n)
        return True

    def pop(self) -> Optional[bytes]:
        """Take one record, or None when the ring is empty."""
        if self.closed:
            raise RingClosed()
        head = self._load(64)
        if self._load(0) == head:
            return None
        (ln,) = _U32.unpack(self._read(head, 4))
        blob = self._read(head + 4, ln) if ln else b""
        # release: the ONE consumer-side store producers order on
        self._store(64, head + 4 + ln)
        return blob

    def reset(self) -> None:
        """Zero both cursors (host side, with the worker KNOWN dead —
        a respawned worker must not replay the dead one's backlog)."""
        self.shm.buf[:HEADER] = b"\x00" * HEADER

    # ---- lifecycle ----

    @property
    def name(self) -> str:
        return self.shm.name

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        try:
            self.shm.close()
        except Exception:
            pass
        if self._owner:
            try:
                self.shm.unlink()
            except Exception:
                pass
