"""Apply-tier proxy: an ``IStateMachine`` whose live state lives in a
worker process.

A state machine whose factory is PROCESS-SPAWNABLE (a module-level
class/callable marked ``__hostproc_spawnable__`` — see
:func:`dragonboat_tpu.hostproc.spawnable_spec`) is wrapped in a
:class:`ProcStateMachine` at ``start_cluster``: the worker builds the
real machine from the ``module:qualname`` spec, and every ``update`` /
``lookup`` / snapshot call becomes one shared-memory ring round trip.
The rsm layer above is untouched — sessions, ordering and snapshot
framing all operate on the proxy exactly as on a plain host SM, and the
snapshot STREAM is byte-identical (the worker writes the user SM's own
format), so replicas with and without the worker tier interoperate.

Crash fallback (the part that makes kill -9 safe): the proxy keeps a
host-side REDO BUFFER — every command the worker acknowledged since the
last snapshot — plus the last snapshot bytes.  When the worker dies (or
its lane re-arms under a new epoch, or a call times out), the proxy
rebuilds in-process: fresh factory instance, recover from the cached
snapshot, replay the redo buffer in order, then apply the in-flight
command locally.  Every command is applied EXACTLY once in the surviving
state — a command the dying worker may or may not have applied only ever
mutated the now-discarded worker copy.  The proxy then LATCHES
in-process for its lifetime (worker restarts serve only newly started
groups).  The buffer is bounded by self-rebase: past
``REBASE_CMDS``/``REBASE_BYTES`` the proxy snapshots the worker state
and truncates — the same bounding discipline the raft log gets from
snapshotting.
"""
from __future__ import annotations

import io
import struct
import threading
from typing import Optional

from ..logger import get_logger
from ..requests import SystemBusyError
from ..statemachine import Result
from . import workers as wp
from .control import WorkerError, WorkerGone
from .workers import _NeverStop

plog = get_logger("hostproc")

_2U64 = struct.Struct("<QQ")
_I64 = struct.Struct("<q")


def _infra_error(e: BaseException) -> bool:
    """WorkerError raised by the TIER (machine missing after a respawn,
    result too large for the ring) rather than by the user SM — these
    warrant the in-process fallback; a user-SM exception propagates."""
    msg = str(e)
    return "no worker SM" in msg or "exceeds ring capacity" in msg


class ProcStateMachine:
    """IStateMachine facade over a worker-held machine (see module doc)."""

    #: self-rebase thresholds bounding the host-side redo buffer
    REBASE_CMDS = 2048
    REBASE_BYTES = 8 << 20

    def __init__(self, plane, spec: str, cluster_id: int, node_id: int,
                 factory):
        self._plane = plane
        self._spec = spec
        self._cid = cluster_id
        self._nid = node_id
        self._factory = factory
        self._hdr = _2U64.pack(cluster_id, node_id)
        self._mu = threading.RLock()
        self._local = None          # not None = fallen back in-process
        self._snap: Optional[bytes] = None
        self._redo: list = []
        self._redo_bytes = 0
        self._client = None
        self._epoch = -1
        c = plane.apply_client(cluster_id)
        try:
            c.call(
                wp.OP_SM_CREATE, self._hdr + spec.encode("utf-8"),
                timeout=30.0,
            )
            self._client = c
            self._epoch = c.epoch
        except Exception:
            # spec unimportable in the worker, worker down, ... — serve
            # in-process from birth; the group never notices
            plog.exception(
                "hostproc SM create failed for %d:%d (%s); in-process",
                cluster_id, node_id, spec,
            )
            plane._count_fallback("apply")
            self._local = factory(cluster_id, node_id)

    # ---- fallback machinery ----

    @property
    def device_bound(self) -> bool:
        """True while the machine still lives in the worker process."""
        with self._mu:
            return self._local is None

    def _remote_ok(self) -> bool:
        c = self._client
        return (
            self._local is None
            and c is not None
            and c.alive
            and c.epoch == self._epoch
        )

    def _fallback(self, pending: Optional[bytes] = None):
        """Rebuild in-process: snapshot + redo replay (exactly-once by
        construction — the worker copy is discarded wholesale), then the
        in-flight command.  Latches ``_local`` for the proxy lifetime."""
        sm = self._factory(self._cid, self._nid)
        if self._snap is not None:
            sm.recover_from_snapshot(io.BytesIO(self._snap), [], _NeverStop())
        for cmd in self._redo:
            sm.update(cmd)
        self._local = sm
        self._plane._count_fallback("apply")
        # best-effort release of the abandoned worker-side machine (a
        # transient timeout latches us local while the worker lives on
        # — without this its copy leaks for the worker's lifetime);
        # short timeouts: the lane may be the slow thing that got us
        # here, and apply must not stall behind courtesy cleanup
        c = self._client
        if c is not None and c.alive and c.epoch == self._epoch:
            try:
                c.call(
                    wp.OP_SM_CLOSE, self._hdr,
                    timeout=1.0, busy_timeout=0.05,
                )
            except Exception:
                pass
        plog.warning(
            "hostproc SM %d:%d fell back in-process (replayed %d cmds%s)",
            self._cid, self._nid, len(self._redo),
            " + snapshot" if self._snap is not None else "",
        )
        if pending is not None:
            return sm.update(pending)
        return None

    def _try_rebase(self) -> None:
        try:
            body = self._client.call(wp.OP_SM_SNAP, self._hdr, timeout=30.0)
        except (WorkerGone, WorkerError, SystemBusyError):
            return  # keep the buffer; the next threshold retries
        self._snap = body
        self._redo = []
        self._redo_bytes = 0

    # ---- IStateMachine ----

    def update(self, cmd) -> Result:
        with self._mu:
            if self._local is not None:
                return self._local.update(cmd)
            cmd_b = bytes(cmd)
            if not self._remote_ok():
                return self._fallback(pending=cmd_b)
            try:
                body = self._client.call(
                    wp.OP_SM_UPDATE, self._hdr + cmd_b,
                    timeout=30.0, busy_timeout=0.25,
                )
            except (WorkerGone, SystemBusyError):
                return self._fallback(pending=cmd_b)
            except WorkerError as e:
                if _infra_error(e):
                    # respawned worker without our machine (defensive —
                    # the epoch check above normally catches this) or a
                    # result the ring cannot carry
                    return self._fallback(pending=cmd_b)
                # the user SM raised: propagate like the in-process path
                # (worker state unchanged, command not buffered)
                raise RuntimeError(str(e)) from e
            self._redo.append(cmd_b)
            self._redo_bytes += len(cmd_b)
            if (
                len(self._redo) >= self.REBASE_CMDS
                or self._redo_bytes >= self.REBASE_BYTES
            ):
                self._try_rebase()
            (value,) = _I64.unpack_from(body, 0)
            return Result(value=value, data=bytes(body[_I64.size:]))

    def lookup(self, query):
        import pickle

        with self._mu:
            if self._local is not None:
                return self._local.lookup(query)
            if not self._remote_ok():
                self._fallback()
                return self._local.lookup(query)
            try:
                body = self._client.call(
                    wp.OP_SM_LOOKUP,
                    self._hdr + pickle.dumps(
                        query, protocol=pickle.HIGHEST_PROTOCOL
                    ),
                    timeout=30.0, busy_timeout=0.25,
                )
            except (WorkerGone, SystemBusyError):
                self._fallback()
                return self._local.lookup(query)
            except WorkerError as e:
                if _infra_error(e):
                    self._fallback()
                    return self._local.lookup(query)
                # the user SM's lookup raised: propagate like the
                # in-process path — the worker and its state are
                # healthy, one bad query must not abandon the tier
                raise RuntimeError(str(e)) from e
            return pickle.loads(body)

    def save_snapshot(self, w, files, done) -> None:
        with self._mu:
            if self._local is not None:
                return self._local.save_snapshot(w, files, done)
            try:
                body = self._client.call(
                    wp.OP_SM_SNAP, self._hdr, timeout=60.0
                )
            except (WorkerGone, SystemBusyError):
                self._fallback()
                return self._local.save_snapshot(w, files, done)
            except WorkerError as e:
                if _infra_error(e):
                    self._fallback()
                    return self._local.save_snapshot(w, files, done)
                raise RuntimeError(str(e)) from e
            w.write(body)
            # the snapshot doubles as the redo buffer's rebase point
            self._snap = body
            self._redo = []
            self._redo_bytes = 0
            return None

    def recover_from_snapshot(self, r, files, done) -> None:
        data = r.read()
        with self._mu:
            if self._local is not None:
                return self._local.recover_from_snapshot(
                    io.BytesIO(data), files, done
                )
            try:
                self._client.call(
                    wp.OP_SM_RECOVER, self._hdr + data, timeout=60.0
                )
            except (WorkerGone, SystemBusyError):
                sm = self._factory(self._cid, self._nid)
                sm.recover_from_snapshot(io.BytesIO(data), files, done)
                self._local = sm
                self._plane._count_fallback("apply")
                return None
            except WorkerError as e:
                if _infra_error(e):
                    sm = self._factory(self._cid, self._nid)
                    sm.recover_from_snapshot(io.BytesIO(data), files, done)
                    self._local = sm
                    self._plane._count_fallback("apply")
                    return None
                raise RuntimeError(str(e)) from e
            self._snap = data
            self._redo = []
            self._redo_bytes = 0
            return None

    def close(self) -> None:
        with self._mu:
            if self._local is not None:
                return self._local.close()
            if self._remote_ok():
                try:
                    self._client.call(
                        wp.OP_SM_CLOSE, self._hdr, timeout=5.0,
                        busy_timeout=0.1,
                    )
                except Exception:
                    pass
            return None
