"""Worker-process side of the multi-process host plane.

``worker_main`` is the spawn target (``multiprocessing`` ``spawn``
context — never fork: the serving process carries JAX and a dozen
threads).  A worker attaches the shared-memory staging rings the control
plane created, then loops: drain every request ring, execute, push the
response, ring the response doorbell.  Stage work it executes:

- ``OP_ENCODE``   — the ingress batcher's payload encode/pack
  (:func:`dragonboat_tpu.rsm.encoded.get_encoded_payload` per command);
- ``OP_WAL_*``    — the group-commit redo-journal cycle: append one
  pre-framed journal record and fsync it (the durability point nothing
  may be acked before), plus checkpoint truncation;
- ``OP_SM_*``     — the apply tier: hold live state machines built from
  process-spawnable factories (``module:qualname`` specs) and run their
  ``update``/``lookup``/snapshot calls off the serving process's GIL.

Module-level imports stay light on purpose: a spawned worker pays this
module's import on its critical startup path, and none of the heavy
host-side machinery (engine, transport, JAX) is ever pulled in.

Wire format (both directions ride the length-prefixed ring records):

- request payload:  ``<u8 op><u32 seq><body>``
- response payload: ``<u8 op><u32 seq><u8 status><u32 wall_us><body>``
  (status 0 = ok, body is the result; status 1 = error, body is the
  utf-8 message; ``wall_us`` is the worker-side execution wall time the
  host feeds the ``dragonboat_hostproc_worker_wall_ms`` histogram)
"""
from __future__ import annotations

import io
import os
import struct
import time

from .rings import ShmRing

_REQ = struct.Struct("<BI")      # op, seq
_RESP = struct.Struct("<BIBI")   # op, seq, status, wall_us
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")
_2U64 = struct.Struct("<QQ")

OP_PING = 1
OP_ENCODE = 2
OP_WAL_OPEN = 3
OP_WAL_APPEND = 4
OP_WAL_TRUNC = 5
OP_SM_CREATE = 6
OP_SM_UPDATE = 7
OP_SM_LOOKUP = 8
OP_SM_SNAP = 9
OP_SM_RECOVER = 10
OP_SM_CLOSE = 11
OP_INJECT = 12
OP_STOP = 13

ST_OK = 0
ST_ERR = 1


def pack_req(op: int, seq: int, body: bytes = b"") -> bytes:
    return _REQ.pack(op, seq) + body


def unpack_req(blob: bytes):
    op, seq = _REQ.unpack_from(blob, 0)
    return op, seq, blob[_REQ.size:]


def pack_resp(op: int, seq: int, status: int, wall_us: int,
              body: bytes = b"") -> bytes:
    return _RESP.pack(op, seq, status, min(wall_us, 0xFFFFFFFF)) + body


def unpack_resp(blob: bytes):
    op, seq, status, wall_us = _RESP.unpack_from(blob, 0)
    return op, seq, status, wall_us, blob[_RESP.size:]


def pack_cmds(cmds) -> bytes:
    """Length-prefixed command burst (the ``_pack_blob`` framing)."""
    return _U32.pack(len(cmds)) + b"".join(
        _U32.pack(len(c)) + bytes(c) for c in cmds
    )


def unpack_cmds(body: bytes, pos: int = 0):
    (n,) = _U32.unpack_from(body, pos)
    pos += 4
    out = []
    for _ in range(n):
        (ln,) = _U32.unpack_from(body, pos)
        pos += 4
        out.append(body[pos : pos + ln])
        pos += ln
    return out, pos


class _NullFiles:
    """Snapshot file collection for worker-held SMs: process-spawnable
    machines must keep their whole state in the snapshot stream (the
    external-file surface has no cross-process story)."""

    def add_file(self, file_id, path, metadata):
        raise RuntimeError(
            "process-spawnable state machines cannot attach external "
            "snapshot files"
        )


class _NeverStop:
    def __bool__(self):
        return False

    def check(self):
        return None


def _resolve(spec: str):
    """``module:qualname`` → the factory object (class or callable)."""
    import importlib

    mod_name, _, qual = spec.partition(":")
    obj = importlib.import_module(mod_name)
    for part in qual.split("."):
        obj = getattr(obj, part)
    return obj


class _WorkerState:
    __slots__ = ("journal_f", "sms", "inject", "running")

    def __init__(self):
        self.journal_f = None
        self.sms = {}       # (cluster_id, node_id) -> sm instance
        self.inject = {}    # test-only fault hooks (OP_INJECT)
        self.running = True


def _handle(st: _WorkerState, op: int, body: bytes) -> bytes:
    """Execute one opcode; returns the ok-body (errors raise)."""
    if op == OP_PING:
        return b""
    if op == OP_ENCODE:
        from ..rsm.encoded import get_encoded_payload

        ct = body[0]
        cmds, _ = unpack_cmds(body, 1)
        return pack_cmds([get_encoded_payload(ct, c) for c in cmds])
    if op == OP_WAL_OPEN:
        if st.journal_f is not None:
            st.journal_f.close()
        path = body.decode("utf-8")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        # "ab" = O_APPEND: every write lands at the true end of file even
        # while the serving process interleaves its own fallback appends
        st.journal_f = open(path, "ab")
        return b""
    if op == OP_WAL_APPEND:
        if st.journal_f is None:
            raise RuntimeError("journal not opened")
        n = st.inject.get("wal_fail_fsyncs", 0)
        st.journal_f.write(body)
        st.journal_f.flush()
        if n:
            st.inject["wal_fail_fsyncs"] = n - 1
            raise OSError("injected fsync failure (hostproc test hook)")
        os.fsync(st.journal_f.fileno())
        return b""
    if op == OP_WAL_TRUNC:
        if st.journal_f is None:
            raise RuntimeError("journal not opened")
        # size-guarded truncation: the host sends the journal length it
        # believes is current; a STALE truncate (a request abandoned on
        # a timeout, executed after the host appended more — possibly
        # via its in-process fallback) sees a larger file and must
        # refuse, or it would wipe acked records whose only durable
        # copy is this journal.  The host falls back to its own
        # truncate on refusal.
        (expected,) = _U64.unpack_from(body, 0)
        actual = os.fstat(st.journal_f.fileno()).st_size
        if actual != expected:
            raise RuntimeError(
                f"stale truncate refused: journal is {actual}B, "
                f"host expected {expected}B"
            )
        st.journal_f.truncate(0)
        st.journal_f.flush()
        os.fsync(st.journal_f.fileno())
        return b""
    if op == OP_SM_CREATE:
        cid, nid = _2U64.unpack_from(body, 0)
        spec = body[_2U64.size:].decode("utf-8")
        st.sms[(cid, nid)] = _resolve(spec)(cid, nid)
        return b""
    if op in (OP_SM_UPDATE, OP_SM_LOOKUP, OP_SM_SNAP, OP_SM_RECOVER,
              OP_SM_CLOSE):
        cid, nid = _2U64.unpack_from(body, 0)
        sm = st.sms.get((cid, nid))
        if sm is None:
            raise RuntimeError(f"no worker SM for ({cid},{nid})")
        arg = body[_2U64.size:]
        if op == OP_SM_UPDATE:
            r = sm.update(arg)
            data = getattr(r, "data", None) or b""
            return struct.pack("<q", int(getattr(r, "value", 0))) + bytes(data)
        if op == OP_SM_LOOKUP:
            import pickle

            return pickle.dumps(
                sm.lookup(pickle.loads(arg)), protocol=pickle.HIGHEST_PROTOCOL
            )
        if op == OP_SM_SNAP:
            w = io.BytesIO()
            sm.save_snapshot(w, _NullFiles(), _NeverStop())
            return w.getvalue()
        if op == OP_SM_RECOVER:
            sm.recover_from_snapshot(io.BytesIO(arg), [], _NeverStop())
            return b""
        # OP_SM_CLOSE
        st.sms.pop((cid, nid), None)
        try:
            sm.close()
        except Exception:
            pass
        return b""
    if op == OP_INJECT:
        import json

        st.inject.update(json.loads(body.decode("utf-8")))
        if st.inject.pop("die", False):
            os._exit(17)  # crash-test hook: hard exit, no cleanup
        return b""
    if op == OP_STOP:
        st.running = False
        return b""
    raise RuntimeError(f"unknown hostproc opcode {op}")


#: idle backoff ceilings: a RECENTLY-busy worker sleeps at most the
#: short nap between ring polls (sub-ms handoffs under load); one idle
#: past ``IDLE_DEEP_AFTER_S`` drops to the deep nap so parked workers
#: stop costing a contended box scheduler quanta (3 idle workers at
#: 1kHz polls measured ~25% off the single-core sessions axis).
#: Polling — NOT a semaphore-backed doorbell — is deliberate: POSIX
#: ``multiprocessing`` events share a lock a kill -9'd process can die
#: HOLDING, deadlocking every later set()/wait() on the host (observed;
#: the rings' cursor stores are the kill-safe wake signal instead).
IDLE_SLEEP_MAX_S = 0.001
IDLE_DEEP_SLEEP_S = 0.02
IDLE_DEEP_AFTER_S = 0.25


def worker_main(worker_id: int, pair_specs, hb) -> None:
    """Process entrypoint.  ``pair_specs`` is a list of
    ``(req_name, resp_name)`` — the rings this worker serves; ``hb`` (a
    LOCKLESS shared double — raw shared memory, nothing a dying process
    can strand) is stamped with ``time.monotonic()`` every loop: the
    first stamp is the spawn handshake, staleness is the control plane's
    health signal."""
    pairs = []
    try:
        for req_name, resp_name in pair_specs:
            pairs.append((
                ShmRing(name=req_name, create=False),
                ShmRing(name=resp_name, create=False),
            ))
    except Exception:
        os._exit(11)  # handshake failure: control plane times out + logs
    st = _WorkerState()
    hb.value = time.monotonic()  # first stamp = ready handshake
    idle_sleep = 0.0
    last_work = time.monotonic()
    while st.running:
        hb.value = time.monotonic()
        worked = False
        for req, resp in pairs:
            while True:
                try:
                    blob = req.pop()
                except Exception:
                    st.running = False
                    break
                if blob is None:
                    break
                worked = True
                try:
                    op, seq, body = unpack_req(blob)
                except Exception:
                    # torn/foreign record (defense in depth — the
                    # control plane never resets a ring under a live
                    # producer): drop it; no seq to answer
                    continue
                t0 = time.perf_counter()
                try:
                    out = _handle(st, op, body)
                    status = ST_OK
                except BaseException as e:  # noqa: BLE001 — shipped to host
                    out = f"{type(e).__name__}: {e}".encode()
                    status = ST_ERR
                wall_us = int((time.perf_counter() - t0) * 1e6)
                rec = pack_resp(op, seq, status, wall_us, out)
                if 4 + len(rec) > resp.cap:
                    # a result (e.g. a large SM snapshot) that can never
                    # fit the ring must degrade to a reported error, not
                    # kill the worker — the host side falls back
                    # in-process on it
                    rec = pack_resp(
                        op, seq, ST_ERR, wall_us,
                        b"response exceeds ring capacity",
                    )
                # the response ring is sized like the request ring; a
                # full one only means the host waiter hasn't drained yet
                while not resp.push(rec):
                    time.sleep(0.0005)
                if not st.running:
                    break
        if worked:
            idle_sleep = 0.0
            last_work = time.monotonic()
            continue
        # idle: short busy window first (sub-ms handoffs), then an
        # exponential nap capped at IDLE_SLEEP_MAX_S while recently
        # busy, dropping to the deep nap once the lanes look parked
        if idle_sleep == 0.0:
            idle_sleep = 0.00005
            for _ in range(50):
                time.sleep(0)
        else:
            time.sleep(idle_sleep)
            cap = (
                IDLE_DEEP_SLEEP_S
                if time.monotonic() - last_work > IDLE_DEEP_AFTER_S
                else IDLE_SLEEP_MAX_S
            )
            idle_sleep = min(idle_sleep * 2, cap)
    for sm in list(st.sms.values()):
        try:
            sm.close()
        except Exception:
            pass
    if st.journal_f is not None:
        try:
            st.journal_f.close()
        except Exception:
            pass
    for req, resp in pairs:
        req.close()
        resp.close()
