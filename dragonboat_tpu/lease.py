"""Leader-lease read plane: clock-bound local reads (ISSUE 10 tentpole).

The ReadIndex protocol (thesis §6.4, ``raft/readindex.py``) makes every
linearizable read pay one heartbeat-echo confirmation round; on the device
read plane that round additionally rides the write-round gate (the measured
1.08s mixed-phase read-dispatch p99, BENCH_r07).  A **leader lease** removes
the round entirely: a leader that heard heartbeat acks from a quorum within
the last ``election_timeout − drift_epsilon`` ticks knows no other leader
can exist yet — §6.4.1 of the raft thesis, plus the §6 CheckQuorum vote
lease that makes the bound hold even against forced campaigns — so it may
serve reads at its committed watermark locally, with the ReadIndex plane as
the always-correct fallback.

Validity rule (tick-based — ticks are the protocol's native clock, shared
with the election/heartbeat timers the bound is measured against):

- every heartbeat broadcast records its send tick per voting peer (a
  bounded FIFO; a send it cannot record is *counted*, and that many
  later acks attribute nothing — never a newer send's tick, see
  ``PENDING_CAP`` — so attribution can only go conservative);
- every heartbeat ack pops the oldest recorded send tick for that peer
  and makes it the peer's **ack basis** (acks confirm the peer's election
  clock was reset no earlier than the send instant, never later);
- the lease basis is the quorum-th newest ack basis over the voting
  members (self counts at the current tick) — the same ``kth_largest``
  reduction ``try_commit``/``commit_quorum`` run over match indexes;
- the lease is valid while ``now < basis + election_timeout − epsilon``,
  where ``epsilon`` (default ``election_timeout // 5``, min 1) absorbs
  tick-delivery jitter and cross-host tick-cadence drift.

Invalidation matrix (all enforced in ``raft/raft.py``):

==================  =====================================================
event               effect
==================  =====================================================
expiry              ``valid()`` turns False; reads fall back to ReadIndex
term change         ``Raft.reset`` → :meth:`LeaderLease.reset`
leadership xfer     :meth:`cede` the moment the transfer target is set —
                    the target campaigns WITHOUT waiting out the election
                    timeout (TIMEOUT_NOW), so the clock bound is void;
                    sticky until the next term (an aborted transfer may
                    already have delivered TIMEOUT_NOW)
membership change   add/remove node/witness/observer, snapshot-restored
                    membership → :meth:`reset` (quorum size moved; re-arm
                    from fresh acks against the new membership)
==================  =====================================================

Interaction with ``device_ticks`` (documented per ISSUE 10): on
device-ticked groups the scalar clock advances lazily at step time
(``node._catch_up_ticks``), but every read reaches
``handle_leader_read_index`` through a step that catches the clock up
first, so ``valid()`` always compares a current tick count.  The catch-up
cap (``max(4 * election_rtt, 16)`` ticks) is ≥ 4 lease durations, so a
stall long enough for the cap to swallow ticks has long since expired the
lease it could otherwise overextend.  ``Config.validate`` rejects
``read_lease`` with ``quiesce`` (a quiesced leader's tick counter freezes
while its followers' election clocks keep running).

The :class:`LeaseTable` is the batched device-plane variant: the tpu
coordinator tallies the heartbeat-ack ops it is already draining into the
engine and keeps an advisory per-group validity deadline — obs/bench
introspection over thousands of groups without touching any raftMu.  The
*serving* authority is always the scalar :class:`LeaderLease` (its
send-tick attribution is strictly conservative; the table's drain-tick
attribution is not).
"""
from __future__ import annotations

import collections
import time
from typing import Dict, Iterable, Optional

_L = "dragonboat_lease_"

#: remaining-validity histogram buckets (ticks): a healthy lease sits in
#: the top buckets; reads served just before expiry land at the bottom
VALIDITY_BUCKETS_TICKS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0)

_HELP = {
    _L + "grants_total": "lease transitions invalid-to-valid",
    _L + "expiries_total": "lease transitions valid-to-invalid",
    _L + "ceded_total": "leases ceded for leadership transfer",
    _L + "reads_local_total": "linearizable reads served under the lease",
    _L + "reads_fallback_total": "reads routed to the ReadIndex fallback",
    _L + "remaining_validity_ticks": "lease ticks left when a read was served",
    _L + "groups_held": "groups the coordinator lease table sees as held",
}


def describe_families(registry) -> None:
    """Register the ``# HELP`` texts for every ``dragonboat_lease_*``
    family (test_events round-trip contract: one HELP per TYPE)."""
    for name, text in _HELP.items():
        registry.describe(name, text)


class LeaseObs:
    """Registry-backed lease instruments, shared by every lease-enabled
    group on one NodeHost.  Attached only when ``enable_metrics`` is on;
    the raft hooks gate on ``obs is not None`` (the PR-5 latch precedent),
    so metrics-off hosts never touch the registry."""

    __slots__ = ("registry",)

    def __init__(self, registry):
        self.registry = registry
        describe_families(registry)
        for name in ("grants_total", "expiries_total", "ceded_total",
                     "reads_local_total", "reads_fallback_total"):
            registry.counter_add(_L + name, 0)
        registry.histogram_declare(
            _L + "remaining_validity_ticks", buckets=VALIDITY_BUCKETS_TICKS
        )

    def grant(self) -> None:
        self.registry.counter_add(_L + "grants_total")

    def expire(self) -> None:
        self.registry.counter_add(_L + "expiries_total")

    def cede(self) -> None:
        self.registry.counter_add(_L + "ceded_total")

    def read_local(self, remaining_ticks: int) -> None:
        self.registry.counter_add(_L + "reads_local_total")
        self.registry.histogram_observe(
            _L + "remaining_validity_ticks", float(remaining_ticks)
        )

    def read_fallback(self) -> None:
        self.registry.counter_add(_L + "reads_fallback_total")


class LeaderLease:
    """One raft group's lease state (leader side).

    All methods run under the owning node's raftMu (they are called from
    raft handlers only), so there is no internal locking.  Plain int
    counters (``reads_local`` etc.) are always maintained — tests and the
    bench read them without the metrics plumbing; :class:`LeaseObs`
    mirrors them into the registry when attached.
    """

    #: per-peer bound on DISTINCT TICKS of recorded-but-unacked
    #: heartbeat sends.  Attribution is tick-granular, so all sends a
    #: peer gets within one tick share one FIFO entry carrying a count
    #: (ReadIndex fallback load broadcasts a hint heartbeat per ctx —
    #: per-SEND capacity would overflow under exactly that load and
    #: freeze the bases, review-caught); the in-flight window in ticks
    #: is bounded by the link RTT, so 16 covers any RTT the lease is
    #: usable at (RTT ≥ the election timeout makes it moot).  A send
    #: that still cannot be recorded is COUNTED (``_unrecorded``) and
    #: that many later acks attribute NOTHING instead of popping an
    #: entry recorded after the refused send (which would inflate the
    #: basis — the optimistic direction the whole scheme exists to
    #: exclude).  Requires per-peer in-order delivery of heartbeats and
    #: acks, which the per-remote FIFO send queues of both wire modules
    #: provide; with message LOSS the FIFO only over-holds old entries,
    #: so attribution can only age.
    PENDING_CAP = 16

    __slots__ = (
        "election_timeout", "epsilon", "duration",
        "_pending", "_unrecorded", "bases", "ceded", "skew", "_held",
        "obs", "grants", "expiries", "reads_local", "reads_fallback",
        "tick_interval_s", "wall_clock", "_ack_walls",
    )

    def __init__(self, election_timeout: int,
                 drift_ticks: Optional[int] = None,
                 tick_interval_s: Optional[float] = None):
        self.election_timeout = election_timeout
        self.epsilon = (
            drift_ticks if drift_ticks is not None
            else max(1, election_timeout // 5)
        )
        self.duration = max(1, election_timeout - self.epsilon)
        # wall-clock guard (ISSUE 17, churn-soak caught): the tick clock
        # is the event loop's — a starved or descheduled leader ticks
        # SLOWER than wall time, so its tick-valid lease can outlive the
        # majority's wall-time election and serve a stale read.  With
        # ``tick_interval_s`` set (the host's tick period in seconds),
        # validity additionally requires the quorum-th newest ack to be
        # within ``duration * tick_interval_s`` WALL seconds — monotonic
        # time keeps running while the process is starved or SIGSTOPped,
        # so starvation can only expire the lease, never extend it.
        # Default off: purely tick-driven tests stay deterministic.
        self.tick_interval_s = tick_interval_s
        self.wall_clock = time.monotonic
        self._ack_walls: Dict[int, float] = {}
        self.obs: Optional[LeaseObs] = None
        self.grants = 0
        self.expiries = 0
        self.reads_local = 0
        self.reads_fallback = 0
        self._pending: Dict[int, collections.deque] = {}
        self._unrecorded: Dict[int, int] = {}
        self.bases: Dict[int, int] = {}
        self.ceded = False
        self.skew = 0
        self._held = False

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def reset(self) -> None:
        """Full invalidation: any ``become_*`` transition (term change,
        promotion, demotion).  Clearing the send FIFOs is safe ONLY here:
        acks still in flight from the old term carry the old term and
        are dropped by raft's term filter before ever reaching
        ``record_ack``, so the fresh FIFO stays aligned with the wire."""
        if self._held:
            self._note_expired()
        self._pending = {}
        self._unrecorded = {}
        self.bases = {}
        self._ack_walls = {}
        self.ceded = False
        self.skew = 0

    def membership_changed(self) -> None:
        """Invalidate for a SAME-TERM membership change: drop the bases
        (the quorum they were tallied against no longer exists; the
        lease re-arms from post-change acks) but KEEP the send FIFOs —
        same-term acks still in flight pass raft's term filter, and a
        cleared FIFO would let such a stale ack pop a post-change send
        and inflate its basis (review-caught: the misalignment then
        persists for the rest of the leadership, the unsafe direction).
        ``ceded`` also survives: a transfer in progress stays ceded."""
        if self._held:
            self._note_expired()
        self.bases = {}
        self._ack_walls = {}

    def cede(self) -> None:
        """Leadership transfer: the target may campaign immediately
        (TIMEOUT_NOW skips its election timeout), so the clock bound the
        lease rests on is void.  Sticky until the next ``reset`` — an
        aborted transfer may already have delivered TIMEOUT_NOW."""
        if not self.ceded:
            if self._held:
                self._note_expired()
            self.ceded = True
            if self.obs is not None:
                self.obs.cede()

    def inject_clock_jump(self, delta_ticks: int) -> None:
        """Fault injection (linearizability soak): shift this replica's
        view of *now* by ``delta_ticks``.  A negative delta simulates the
        local clock jumping backward — the lease then overestimates its
        validity, which is exactly the stale-lease fault the checker must
        catch."""
        self.skew += delta_ticks

    # ------------------------------------------------------------------
    # heartbeat plumbing (called from raft under raftMu)
    # ------------------------------------------------------------------

    def record_send(self, tick: int, peer_ids: Iterable[int]) -> None:
        """A heartbeat broadcast left for ``peer_ids`` at ``tick``.

        FIFO entries are ``[tick, count]`` — every send within one tick
        folds into the tail entry's count (attribution is tick-granular,
        so all of a tick's sends share one basis), keeping the capacity
        a bound on in-flight TICKS rather than sends.  A send that still
        cannot be recorded (cap'd distinct-tick window, or earlier
        refused sends still in flight) is COUNTED instead: its ack must
        consume an ``_unrecorded`` slot, never a send recorded after it
        — refusing silently would let that later ack pop a newer tick
        and inflate the basis (the unsafe direction).  Once a refusal
        happens, recording stays suspended for the peer until every
        outstanding refused send's ack has drained, preserving the
        FIFO ↔ wire-order correspondence the attribution relies on."""
        for nid in peer_ids:
            dq = self._pending.get(nid)
            if dq is None:
                dq = self._pending[nid] = collections.deque()
            if self._unrecorded.get(nid):
                self._unrecorded[nid] += 1
            elif dq and dq[-1][0] == tick:
                dq[-1][1] += 1
            elif len(dq) < self.PENDING_CAP:
                dq.append([tick, 1])
            else:
                self._unrecorded[nid] = 1

    def record_ack(self, node_id: int, _now: int) -> None:
        """A heartbeat ack arrived from voting member ``node_id``: its
        ack basis becomes the OLDEST recorded send tick (conservative —
        with message loss the ack may actually answer a newer send).
        Acks answering refused-to-record sends (FIFO overflow) drain the
        refusal count and attribute nothing."""
        dq = self._pending.get(node_id)
        if dq:
            head = dq[0]
            self.bases[node_id] = head[0]
            if self.tick_interval_s is not None:
                self._ack_walls[node_id] = self.wall_clock()
            head[1] -= 1
            if head[1] <= 0:
                dq.popleft()
        elif self._unrecorded.get(node_id):
            self._unrecorded[node_id] -= 1

    # ------------------------------------------------------------------
    # validity
    # ------------------------------------------------------------------

    def remaining(self, now: int, quorum: int,
                  voter_ids: Iterable[int], self_id: int) -> int:
        """Ticks of validity left (<= 0: not held).  ``voter_ids`` is the
        current voting membership (remotes + witnesses)."""
        if self.ceded:
            return 0
        voters = list(voter_ids)
        now = now + self.skew
        bases = sorted(
            (now if nid == self_id else self.bases.get(nid, -1))
            for nid in voters
        )
        n = len(bases)
        if n < quorum:
            return 0
        basis = bases[n - quorum]  # quorum-th newest (kth_largest)
        if basis < 0:
            return 0
        rem = basis + self.duration - now
        if rem > 0 and self.tick_interval_s is not None:
            # wall-clock guard: a starved tick loop must not overextend
            # the lease (see __init__) — the quorum-th newest ack must
            # also be fresh in WALL time
            now_w = self.wall_clock()
            walls = sorted(
                (now_w if nid == self_id else self._ack_walls.get(nid, -1.0))
                for nid in voters
            )
            wall_basis = walls[n - quorum]
            if (wall_basis < 0
                    or now_w - wall_basis
                    > self.duration * self.tick_interval_s):
                return 0
        return rem

    def check(self, now: int, quorum: int,
              voter_ids: Iterable[int], self_id: int) -> int:
        """One reduction per read: the remaining validity (<= 0 = not
        held), with the grant/expiry transition accounting folded in."""
        rem = self.remaining(now, quorum, voter_ids, self_id)
        if rem > 0 and not self._held:
            self._held = True
            self.grants += 1
            if self.obs is not None:
                self.obs.grant()
        elif rem <= 0 and self._held:
            self._note_expired()
        return rem

    def valid(self, now: int, quorum: int,
              voter_ids: Iterable[int], self_id: int) -> bool:
        return self.check(now, quorum, voter_ids, self_id) > 0

    def _note_expired(self) -> None:
        self._held = False
        self.expiries += 1
        if self.obs is not None:
            self.obs.expire()

    # ------------------------------------------------------------------
    # read accounting (raft's serve/fallback decision points)
    # ------------------------------------------------------------------

    def note_read_local(self, remaining_ticks: int) -> None:
        self.reads_local += 1
        if self.obs is not None:
            self.obs.read_local(remaining_ticks)

    def note_read_fallback(self) -> None:
        self.reads_fallback += 1
        if self.obs is not None:
            self.obs.read_fallback()

    def stats(self) -> dict:
        """Plain-int snapshot (bench/tests; no registry required)."""
        total = self.reads_local + self.reads_fallback
        return {
            "grants": self.grants,
            "expiries": self.expiries,
            "reads_local": self.reads_local,
            "reads_fallback": self.reads_fallback,
            "hit_ratio": round(self.reads_local / total, 4) if total else None,
        }


class LeaseTable:
    """Advisory per-group lease deadlines for the tpu coordinator (the
    batched device-plane variant).

    The coordinator's drain loop already walks every staged heartbeat-ack
    op (``hbresp``) on its way into the engine; for lease-configured
    groups it additionally folds the acker id into a per-round tally —
    one dict update per op, no extra host pass, no raftMu.  A round whose
    tally reaches a group's quorum extends that group's deadline to
    ``round_tick + duration``.

    Attribution here is drain-tick (optimistic by up to one round), so
    the table is **introspection-grade**: lease-coverage gauges and the
    cross-domain bench read it; the serving decision stays with the
    scalar :class:`LeaderLease` and its conservative send-tick bases.
    """

    __slots__ = ("_quorum", "_duration", "_deadline", "_self_id", "_voters")

    def __init__(self) -> None:
        self._quorum: Dict[int, int] = {}
        self._duration: Dict[int, int] = {}
        self._self_id: Dict[int, int] = {}
        self._voters: Dict[int, frozenset] = {}
        self._deadline: Dict[int, int] = {}

    def configure(self, cluster_id: int, quorum: int, duration: int,
                  self_id: int, voters: Iterable[int] = ()) -> None:
        """``voters`` is the voting membership (remotes + witnesses):
        hbresp ops are staged for EVERY heartbeat responder, observers
        included, so the tally must filter to voters or an observer-ack
        round would extend a deadline no voting quorum backs."""
        self._quorum[cluster_id] = quorum
        self._duration[cluster_id] = duration
        self._self_id[cluster_id] = self_id
        self._voters[cluster_id] = frozenset(voters)
        self._deadline.pop(cluster_id, None)

    def drop(self, cluster_id: int) -> None:
        """Row transition / resync / unregister: the deadline is stale."""
        self._deadline.pop(cluster_id, None)

    def remove(self, cluster_id: int) -> None:
        self._quorum.pop(cluster_id, None)
        self._duration.pop(cluster_id, None)
        self._self_id.pop(cluster_id, None)
        self._voters.pop(cluster_id, None)
        self._deadline.pop(cluster_id, None)

    def tracks(self, cluster_id: int) -> bool:
        return cluster_id in self._quorum

    def note_round(self, acks_by_cid: Dict[int, set], round_tick: int) -> None:
        """Fold one round's heartbeat-ack tally in: ``acks_by_cid`` maps
        cluster id → set of acker node ids seen this round."""
        for cid, ackers in acks_by_cid.items():
            q = self._quorum.get(cid)
            if q is None:
                continue
            voting = ackers & self._voters.get(cid, frozenset())
            voting.add(self._self_id.get(cid, 0))
            if len(voting) >= q:
                self._deadline[cid] = round_tick + self._duration[cid]

    def valid(self, cluster_id: int, now_tick: int) -> bool:
        d = self._deadline.get(cluster_id)
        return d is not None and now_tick < d

    def held_count(self, now_tick: int) -> int:
        return sum(1 for d in self._deadline.values() if now_tick < d)

    def publish(self, registry, now_tick: int) -> None:
        """Once-per-round gauge refresh (only called with obs enabled)."""
        registry.describe(_L + "groups_held", _HELP[_L + "groups_held"])
        registry.gauge_set(_L + "groups_held", self.held_count(now_tick))
