"""Linearizability checker for KV operation histories.

Reference context: the reference library is verified externally with
Jepsen Knossos and porcupine over histories produced by its monkey-test
harness (``docs/test.md:6,11-36``).  This module brings that capability
in-tree: a Wing & Gong style search with memoization (the algorithm
family porcupine implements) over a per-key register model, so the chaos
tests (``tests/test_chaos.py``) can assert histories collected under
partitions/crashes are linearizable.

Model: independent keys, each a last-writer-wins register.  ``put``
operations with unknown outcome (client timeout) are treated as
*possibly applied*: their response time is +inf, which lets the checker
linearize them after every observed read — equivalent to "never took
effect" for all observations — or anywhere after their invocation.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

INF = math.inf


@dataclass
class Op:
    """One client operation."""

    client: int
    kind: str  # "put" | "get"
    key: str
    value: Optional[str]  # put: value written; get: value observed
    invoke: float  # invocation timestamp
    ret: float  # response timestamp; INF when the outcome is unknown
    ok: bool = True  # False = unknown outcome (treated as maybe-applied)


def _check_register(ops: List[Op], initial: Optional[str] = None) -> bool:
    """Wing & Gong search over one key's history.

    Iterative DFS with an explicit stack: a soak history can run to
    thousands of ops per key, so the search depth (one level per op) must
    not ride the Python recursion limit.
    """
    n = len(ops)
    if n == 0:
        return True
    order = sorted(range(n), key=lambda i: ops[i].invoke)
    ops = [ops[i] for i in order]
    full = (1 << n) - 1
    seen: set = set()
    budget = 5_000_000  # visited-state cap: fail loudly, never hang

    def _successors(done_mask: int, state: Optional[str]):
        # an op may linearize next only if no other pending op RETURNED
        # before this op was INVOKED (returned-before implies
        # linearized-before)
        min_ret = INF
        for i in range(n):
            if not done_mask & (1 << i):
                r = ops[i].ret
                if r < min_ret:
                    min_ret = r
        out = []
        for i in range(n):
            bit = 1 << i
            if done_mask & bit:
                continue
            op = ops[i]
            if op.invoke > min_ret:
                continue
            if op.kind == "put":
                out.append((done_mask | bit, op.value))
            elif not op.ok or op.value == state:
                # a get with unknown outcome observed nothing: any state fits
                out.append((done_mask | bit, state))
        return out

    stack = [(0, initial)]
    while stack:
        done_mask, state = stack.pop()
        if done_mask == full:
            return True
        if (done_mask, state) in seen:
            continue
        seen.add((done_mask, state))
        budget -= 1
        if budget < 0:
            raise RuntimeError("linearizability search budget exhausted")
        stack.extend(_successors(done_mask, state))
    return False


def check_linearizable(
    history: List[Op], initial: Optional[Dict[str, str]] = None
) -> Tuple[bool, List[str]]:
    """Check a multi-key history; returns (ok, offending_keys).

    Keys are independent registers, so the history factors per key — the
    same decomposition porcupine's KV model uses.
    """
    by_key: Dict[str, List[Op]] = {}
    for op in history:
        by_key.setdefault(op.key, []).append(op)
    bad: List[str] = []
    for key, ops in by_key.items():
        init = (initial or {}).get(key)
        if not _check_register(ops, init):
            bad.append(key)
    return (not bad, bad)


class HistoryRecorder:
    """Thread-safe invoke/response recorder used by chaos test clients."""

    def __init__(self) -> None:
        import threading
        import time

        self._mu = threading.Lock()
        self._clock = time.monotonic
        self.ops: List[Op] = []

    def invoke(self, client: int, kind: str, key: str, value: Optional[str]):
        """Returns a completion callback: call with the observed value (get)
        or True (put success); call with ``unknown=True`` on timeout."""
        t0 = self._clock()

        def complete(value_seen=None, unknown: bool = False) -> None:
            t1 = self._clock()
            op = Op(
                client=client,
                kind=kind,
                key=key,
                value=value if kind == "put" else value_seen,
                invoke=t0,
                ret=INF if unknown else t1,
                ok=not unknown,
            )
            with self._mu:
                self.ops.append(op)

        return complete

    def history(self) -> List[Op]:
        with self._mu:
            return list(self.ops)
