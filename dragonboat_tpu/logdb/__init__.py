"""Log storage layer (reference ``internal/logdb/``).

Sharded, write-batched persistent storage for raft entries, state,
bootstrap records and snapshot metadata.  The storage contract is
``IKVStore``-shaped (reference ``internal/logdb/kv/kv.go:28``): write-batch
atomicity, range delete and manual compaction — satisfied by the pure-Python
backends in :mod:`dragonboat_tpu.logdb.kv` and by the C++ native engine in
``dragonboat_tpu/native`` once built.
"""
from .kv import IKVStore, InMemKV, KVWriteBatch, WalKV  # noqa: F401
from .logreader import LogReader  # noqa: F401
from .sharded import ShardedDB, open_logdb  # noqa: F401
