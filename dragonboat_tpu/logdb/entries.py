"""Entry record managers: plain (one entry per key) and batched.

Reference: ``internal/logdb/plain.go`` and ``internal/logdb/batch.go`` — the
plain manager stores each entry under its own ``(cluster, node, index)`` key;
the batched manager packs ``Hard.logdb_entry_batch_size`` (48) consecutive
entries into one record keyed by ``index // 48``.  The open path auto-detects
which format is on disk (reference ``logdb.go:44-56``).
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from ..settings import Hard
from ..wire import Entry
from ..wire.codec import (
    decode_entry,
    decode_entry_batch,
    encode_entry,
    encode_entry_batch,
)
from . import keys
from .kv import IKVStore, KVWriteBatch


class PlainEntries:
    """One entry per record (reference ``plain.go:31``)."""

    name = "plain"

    def __init__(self, kv: IKVStore):
        self.kv = kv

    def record_entries(
        self, wb: KVWriteBatch, cluster_id: int, node_id: int, entries: List[Entry]
    ) -> int:
        """Append entry records to the write batch; returns the max index."""
        if not entries:
            return 0
        for e in entries:
            wb.put(keys.entry_key(cluster_id, node_id, e.index), encode_entry(e))
        return entries[-1].index

    def iterate_entries(
        self,
        ents: List[Entry],
        size: int,
        cluster_id: int,
        node_id: int,
        low: int,
        high: int,
        max_size: int,
    ) -> Tuple[List[Entry], int]:
        """Collect entries in ``[low, high)`` up to ``max_size`` bytes.

        Mirrors the reference's contract: stops at the first hole, always
        returns at least one entry if one exists at ``low``.
        """
        fk = keys.entry_key(cluster_id, node_id, low)
        lk = keys.entry_key(cluster_id, node_id, high - 1)
        expected = low
        for _, v in self.kv.iterate(fk, lk, True):
            e = decode_entry(v)
            if e.index != expected:
                break
            size += e.size()
            if ents and size > max_size:
                return ents, size
            ents.append(e)
            expected += 1
        return ents, size

    def get_entry(self, cluster_id: int, node_id: int, index: int) -> Optional[Entry]:
        v = self.kv.get(keys.entry_key(cluster_id, node_id, index))
        return decode_entry(v) if v is not None else None

    def remove_entries_to(
        self, wb: KVWriteBatch, cluster_id: int, node_id: int, index: int
    ) -> None:
        wb.delete_range(
            keys.entry_key(cluster_id, node_id, 0),
            keys.entry_key(cluster_id, node_id, index + 1),
        )

    def remove_all(self, wb: KVWriteBatch, cluster_id: int, node_id: int) -> None:
        wb.delete_range(
            keys.entry_key(cluster_id, node_id, 0),
            keys.entry_key(cluster_id, node_id, keys.MAX_INDEX),
        )

    def compact_range(self, cluster_id: int, node_id: int, index: int) -> None:
        # end key is exclusive (index + 1); a full-range request at
        # MAX_INDEX (RequestCompaction for a removed node) must clamp
        # instead of overflowing the u64 key pack
        self.kv.compact_entries(
            keys.entry_key(cluster_id, node_id, 0),
            keys.entry_key(cluster_id, node_id, min(index + 1, keys.MAX_INDEX)),
        )


class BatchedEntries:
    """48-entry batch records (reference ``batch.go:142``).

    A batch record with id ``b`` holds entries with ``index // batch_size ==
    b`` that were live at write time; overwrites after a conflict rewrite the
    first affected batch (merging the surviving prefix) and then replace all
    later batches.
    """

    name = "batched"

    def __init__(self, kv: IKVStore):
        self.kv = kv
        self.batch_size = Hard.logdb_entry_batch_size

    def _bid(self, index: int) -> int:
        return index // self.batch_size

    def _read_batch(
        self, cluster_id: int, node_id: int, bid: int
    ) -> List[Entry]:
        v = self.kv.get(keys.entry_batch_key(cluster_id, node_id, bid))
        return decode_entry_batch(v) if v is not None else []

    def record_entries(
        self, wb: KVWriteBatch, cluster_id: int, node_id: int, entries: List[Entry]
    ) -> int:
        if not entries:
            return 0
        first = entries[0]
        fbid = self._bid(first.index)
        # merge surviving prefix of the first touched batch
        existing = self._read_batch(cluster_id, node_id, fbid)
        merged = [e for e in existing if e.index < first.index]
        batch: List[Entry] = merged
        bid = fbid
        for e in entries:
            ebid = self._bid(e.index)
            if ebid != bid:
                wb.put(
                    keys.entry_batch_key(cluster_id, node_id, bid),
                    encode_entry_batch(batch),
                )
                bid = ebid
                batch = []
            batch.append(e)
        wb.put(
            keys.entry_batch_key(cluster_id, node_id, bid),
            encode_entry_batch(batch),
        )
        return entries[-1].index

    def iterate_entries(
        self,
        ents: List[Entry],
        size: int,
        cluster_id: int,
        node_id: int,
        low: int,
        high: int,
        max_size: int,
    ) -> Tuple[List[Entry], int]:
        expected = low
        for bid in range(self._bid(low), self._bid(high - 1) + 1):
            batch = self._read_batch(cluster_id, node_id, bid)
            if not batch:
                return ents, size
            for e in batch:
                if e.index < expected or e.index >= high:
                    continue
                if e.index != expected:
                    return ents, size
                size += e.size()
                if ents and size > max_size:
                    return ents, size
                ents.append(e)
                expected += 1
        return ents, size

    def get_entry(self, cluster_id: int, node_id: int, index: int) -> Optional[Entry]:
        for e in self._read_batch(cluster_id, node_id, self._bid(index)):
            if e.index == index:
                return e
        return None

    def remove_entries_to(
        self, wb: KVWriteBatch, cluster_id: int, node_id: int, index: int
    ) -> None:
        # only whole batches strictly below the boundary can be removed
        wb.delete_range(
            keys.entry_batch_key(cluster_id, node_id, 0),
            keys.entry_batch_key(cluster_id, node_id, self._bid(index + 1)),
        )

    def remove_all(self, wb: KVWriteBatch, cluster_id: int, node_id: int) -> None:
        wb.delete_range(
            keys.entry_batch_key(cluster_id, node_id, 0),
            keys.entry_batch_key(cluster_id, node_id, keys.MAX_INDEX),
        )

    def compact_range(self, cluster_id: int, node_id: int, index: int) -> None:
        self.kv.compact_entries(
            keys.entry_batch_key(cluster_id, node_id, 0),
            keys.entry_batch_key(cluster_id, node_id, self._bid(index + 1)),
        )


def has_entry_records(kv: IKVStore, batched: bool) -> bool:
    """Format self-check helper (reference ``sharded_rdb.go`` selfCheckFailed)."""
    tag = keys.TAG_ENTRY_BATCH if batched else keys.TAG_ENTRY
    first = keys.make_key(tag, 0, 0, 0)
    last = keys.make_key(tag, 2**64 - 1, 2**64 - 1, keys.MAX_INDEX)
    for _ in kv.iterate(first, last, True):
        return True
    return False
