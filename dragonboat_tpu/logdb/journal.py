"""Host-plane group-commit journal: ONE fsync covering every LogDB
shard's write batches per flush cycle.

The sharded LogDB keeps one WAL file per shard, and the step-worker
committers are shard-aligned — so merging their submissions can never
reduce fsyncs below one per touched FILE per cycle.  This journal is the
cross-shard half of ISSUE 8's group-commit tier: the flush cycle appends
every shard's encoded write batch to a single redo-log file, fsyncs THAT
once, and then applies the batches to the shard stores without their own
fsync (``commit_write_batch_nosync``).  Durability argument:

- nothing is acked before the journal fsync returns;
- every journaled-mode shard write is journal-first, so shard state is
  always a prefix of journal history;
- recovery (``replay``, run by ``open_logdb`` whenever a journal file
  exists — including after a crash, a downgrade to compartments-off, or
  a kill between journal fsync and shard apply) re-applies the whole
  journal in append order.  Re-application is idempotent (keyed puts /
  deletes / range-deletes), and replaying from the checkpoint base ends
  at exactly the newest journaled state;
- checkpoints bound the journal: after ``checkpoint_every`` cycles the
  flusher fsyncs every shard store (``sync_all``) and truncates the
  journal — a crash between those two steps just replays an
  already-applied suffix.

Record framing (crc-checked, torn tails dropped like WalKV):
``<crc32 u32><len u32><nbatches u32>`` then ``nbatches`` ×
``<shard u32><nops u32><len u32><ops payload>`` where the ops payload is
:func:`dragonboat_tpu.logdb.kv.encode_ops`'s format.
"""
from __future__ import annotations

import os
import struct
import threading
import zlib
from typing import List, Optional, Tuple

from ..logger import get_logger
from .kv import KVWriteBatch, decode_ops, encode_ops

plog = get_logger("logdb")

_HDR = struct.Struct("<III")  # crc32(payload), payload len, batch count
_SUB = struct.Struct("<III")  # shard idx, op count, ops payload len

JOURNAL_NAME = "host-journal.wal"


class HostJournal:
    """The redo log the group-commit flusher appends to.

    ``fs`` (a :mod:`dragonboat_tpu.vfs` IFS) routes the journal's IO so
    vfs.ErrorFS fault injection reaches the ACTUAL durability point of
    journaled mode — the one fsync nothing may be acked before."""

    def __init__(self, path: str, fs=None):
        self.path = path
        self._fs = fs
        # optional WAL-worker sink (hostproc, ISSUE 12): when attached,
        # the append's write+fsync runs in a worker process — the host
        # blocks until the worker acks the fsync (nothing acked before
        # it).  Contract: ``sink.append(path, rec) -> bool`` — True =
        # durable in the worker; False = worker tier unavailable, fall
        # back to the in-process write+fsync below; raises OSError when
        # the worker REALLY failed the durable op (propagates to the
        # flush cycle like a local fsync error).  Both processes open
        # the file O_APPEND, so fallback interleaving always lands at
        # the true end of file, and an AMBIGUOUS worker append (worker
        # died post-fsync pre-ack) is simply re-appended — replay is
        # idempotent.  Only attached on the raw-OS path (fs is None):
        # a vfs (ErrorFS/MemFS) cannot cross the process boundary.
        self.sink = None
        # append vs checkpoint/close can come from different threads
        # (flush leader / ShardedDB journal barrier); serialize file IO
        self._mu = threading.Lock()
        if fs is None:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            self._f = open(path, "ab")
        else:
            fs.makedirs(os.path.dirname(path), exist_ok=True)
            self._f = fs.open(path, "ab")
        #: journal fsyncs issued (one per flush cycle + checkpoints) —
        #: the bench's amortization factor divides committer submissions
        #: by these
        self.fsyncs = 0
        self.appends = 0
        self.bytes = 0

    def append(self, batches: List[Tuple[int, KVWriteBatch]]) -> None:
        """One flush cycle: frame every shard's batch, write, fsync ONCE."""
        buf = bytearray()
        n = 0
        for shard_idx, wb in batches:
            if not wb.ops:
                continue
            ops = encode_ops(wb)
            buf += _SUB.pack(shard_idx, len(wb.ops), len(ops))
            buf += ops
            n += 1
        if not n:
            return
        payload = bytes(buf)
        rec = _HDR.pack(zlib.crc32(payload), len(payload), n) + payload
        with self._mu:
            snk = self.sink
            if snk is not None and self._fs is None:
                if snk.append(self.path, rec):  # OSError propagates: the
                    # worker ran the durable op and it FAILED — the
                    # flush cycle must fail, exactly like a local fsync
                    self.fsyncs += 1  # one durability barrier, worker-side
                    self.appends += 1
                    self.bytes += len(rec)
                    return
                # worker tier unavailable (dead/busy): in-process path
            self._f.write(rec)
            self._f.flush()
            self._fsync()
            self.fsyncs += 1
            self.appends += 1
            self.bytes += len(rec)

    def checkpoint(self, sync_all) -> None:
        """Bound the journal: make every shard store durable on its own,
        then truncate.  A crash between the two steps only leaves an
        already-applied suffix for replay."""
        sync_all()
        with self._mu:
            snk = self.sink
            if snk is not None and self._fs is None and snk.truncate(
                self.path, self.bytes
            ):
                self.fsyncs += 1
                self.bytes = 0
                return
            self._f.truncate(0)
            self._f.flush()
            self._fsync()
            self.fsyncs += 1
            self.bytes = 0

    def nonempty(self) -> bool:
        """Whether journal history exists that a crash replay would
        re-apply.  With a WAL-worker sink attached this consults the
        FILE, not just the host counter: a request abandoned on a host
        timeout can execute late in a slow-but-alive worker and land a
        record the counter never saw — a direct (journal-bypassing)
        write while such a record exists would be regressed by replay,
        so the direct-path guards must see it.  (FIFO rings make
        per-append staleness guards unsound — the stale append always
        precedes any resync marker — hence guarding the READ side.)"""
        if self.bytes:
            return True
        if self.sink is not None and self._fs is None:
            try:
                return os.fstat(self._f.fileno()).st_size > 0
            except (OSError, ValueError):
                return True  # conservative: assume history exists
        return False

    def _fsync(self) -> None:
        if self._fs is None:
            os.fsync(self._f.fileno())
        else:
            self._fs.fsync(self._f)

    def close(self) -> None:
        with self._mu:
            if not self._f.closed:
                self._f.flush()
                try:
                    self._fsync()
                except OSError:
                    plog.exception("host journal close fsync failed")
                self._f.close()


def replay(path: str, shards) -> int:
    """Re-apply a leftover journal into the shard stores (called by
    ``open_logdb`` before the DB is handed out).  Returns the number of
    cycles replayed; the journal is truncated afterwards (the replayed
    writes were committed durably through the stores' fsynced path)."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return 0
    pos, n = 0, len(data)
    cycles = 0
    while pos + _HDR.size <= n:
        crc, plen, nbatches = _HDR.unpack_from(data, pos)
        body = pos + _HDR.size
        if body + plen > n:
            break
        payload = data[body : body + plen]
        if zlib.crc32(payload) != crc:
            break  # torn tail: its writes were never acked
        p = 0
        ok = True
        for _ in range(nbatches):
            if p + _SUB.size > plen:
                ok = False
                break
            shard_idx, nops, olen = _SUB.unpack_from(payload, p)
            p += _SUB.size
            wb = decode_ops(payload[p : p + olen], nops)
            p += olen
            if wb is None or shard_idx >= len(shards):
                ok = False
                break
            # durable commit: replay re-lands the write through the
            # shard's own fsynced path, so a crash mid-replay just
            # replays again (idempotent)
            shards[shard_idx].kv.commit_write_batch(wb)
        if not ok:
            break
        cycles += 1
        pos = body + plen
    if cycles:
        plog.info("host journal %s: replayed %d cycles", path, cycles)
    try:
        with open(path, "r+b") as f:
            f.truncate(0)
            f.flush()
            os.fsync(f.fileno())
    except OSError:
        pass
    return cycles
