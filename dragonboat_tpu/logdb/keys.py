"""Key schema for the log storage layer.

Reference: ``internal/logdb/pooledkey.go:23-55`` — fixed-size binary keys
whose lexical order equals numeric ``(clusterID, nodeID, index)`` order, so
range scans and range deletes cover exactly one node's records.

Layout (25 bytes): ``<tag u8><cluster u64 BE><node u64 BE><index u64 BE>``.
Big-endian makes byte order == integer order.  Bootstrap/state/max-index
records use index 0; snapshot and entry records key on their raft index.
"""
from __future__ import annotations

import struct

_KEY = struct.Struct(">BQQQ")

TAG_BOOTSTRAP = 0x01
TAG_STATE = 0x02
TAG_MAX_INDEX = 0x03
TAG_SNAPSHOT = 0x04
TAG_ENTRY = 0x05
TAG_ENTRY_BATCH = 0x06

KEY_SIZE = _KEY.size

MAX_INDEX = 2**64 - 1


def make_key(tag: int, cluster_id: int, node_id: int, index: int = 0) -> bytes:
    return _KEY.pack(tag, cluster_id, node_id, index)


def parse_key(key: bytes):
    return _KEY.unpack(key)


def bootstrap_key(cluster_id: int, node_id: int) -> bytes:
    return make_key(TAG_BOOTSTRAP, cluster_id, node_id)


def state_key(cluster_id: int, node_id: int) -> bytes:
    return make_key(TAG_STATE, cluster_id, node_id)


def max_index_key(cluster_id: int, node_id: int) -> bytes:
    return make_key(TAG_MAX_INDEX, cluster_id, node_id)


def snapshot_key(cluster_id: int, node_id: int, index: int) -> bytes:
    return make_key(TAG_SNAPSHOT, cluster_id, node_id, index)


def entry_key(cluster_id: int, node_id: int, index: int) -> bytes:
    return make_key(TAG_ENTRY, cluster_id, node_id, index)


def entry_batch_key(cluster_id: int, node_id: int, batch_id: int) -> bytes:
    return make_key(TAG_ENTRY_BATCH, cluster_id, node_id, batch_id)


def node_first_key(cluster_id: int, node_id: int) -> bytes:
    """Smallest possible key for a node, across all tags."""
    return make_key(TAG_BOOTSTRAP, cluster_id, node_id, 0)


def node_last_key(cluster_id: int, node_id: int) -> bytes:
    """Largest possible key for a node, across all tags."""
    return make_key(TAG_ENTRY_BATCH, cluster_id, node_id, MAX_INDEX)
