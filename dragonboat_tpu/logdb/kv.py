"""Key-value storage abstraction and pure-Python backends.

Reference: ``internal/logdb/kv/kv.go:28-73`` (``IKVStore``: iterate / get /
put / delete, atomic WriteBatch, BulkRemoveEntries range-delete, manual
CompactEntries) and the Pebble backend (``kv/pebble/kv_pebble.go``).

Two host backends are provided here:

- :class:`InMemKV` — ordered in-memory map (plays the role of the memfs
  Pebble used by the reference test builds).
- :class:`WalKV` — :class:`InMemKV` plus an append-only write-ahead file so
  state survives process restart; every committed write batch is one framed,
  crc-checked WAL record.  This is the interim durable engine until the C++
  native log engine (``dragonboat_tpu/native``) takes over.
"""
from __future__ import annotations

import os
import struct
import threading
import zlib
from bisect import bisect_left, insort
from typing import Callable, Iterator, List, Optional, Protocol, Tuple

_PUT = 0
_DELETE = 1
_DELETE_RANGE = 2


class KVWriteBatch:
    """Atomic group of writes (reference ``kv.go`` ``IWriteBatch``)."""

    __slots__ = ("ops",)

    def __init__(self) -> None:
        self.ops: List[Tuple[int, bytes, bytes]] = []

    def put(self, key: bytes, value: bytes) -> None:
        self.ops.append((_PUT, bytes(key), bytes(value)))

    def delete(self, key: bytes) -> None:
        self.ops.append((_DELETE, bytes(key), b""))

    def delete_range(self, first: bytes, last: bytes) -> None:
        """Delete keys in ``[first, last)``."""
        self.ops.append((_DELETE_RANGE, bytes(first), bytes(last)))

    def clear(self) -> None:
        self.ops.clear()

    def __len__(self) -> int:
        return len(self.ops)


class IKVStore(Protocol):
    """Reference ``internal/logdb/kv/kv.go:28``."""

    def name(self) -> str: ...

    def get(self, key: bytes) -> Optional[bytes]: ...

    def put(self, key: bytes, value: bytes) -> None: ...

    def delete(self, key: bytes) -> None: ...

    def iterate(
        self, first: bytes, last: bytes, inc_last: bool
    ) -> Iterator[Tuple[bytes, bytes]]: ...

    def get_write_batch(self) -> KVWriteBatch: ...

    def commit_write_batch(self, wb: KVWriteBatch) -> None: ...

    def bulk_remove_entries(self, first: bytes, last: bytes) -> None: ...

    def compact_entries(self, first: bytes, last: bytes) -> None: ...

    def full_compaction(self) -> None: ...

    def close(self) -> None: ...


class InMemKV:
    """Ordered in-memory KV store with atomic write batches."""

    def __init__(self) -> None:
        self._data: dict = {}
        self._keys: List[bytes] = []  # sorted
        self._mu = threading.Lock()

    def name(self) -> str:
        return "inmem"

    def get(self, key: bytes) -> Optional[bytes]:
        with self._mu:
            return self._data.get(key)

    def put(self, key: bytes, value: bytes) -> None:
        wb = self.get_write_batch()
        wb.put(key, value)
        self.commit_write_batch(wb)

    def delete(self, key: bytes) -> None:
        wb = self.get_write_batch()
        wb.delete(key)
        self.commit_write_batch(wb)

    def iterate(
        self, first: bytes, last: bytes, inc_last: bool
    ) -> Iterator[Tuple[bytes, bytes]]:
        # yields in bounded chunks (re-bisecting per chunk) so early-exit
        # consumers don't pay a full-range copy
        chunk = 128
        cursor = first
        first_round = True
        while True:
            with self._mu:
                lo = bisect_left(self._keys, cursor)
                if not first_round:
                    # skip the cursor key itself, already yielded
                    if lo < len(self._keys) and self._keys[lo] == cursor:
                        lo += 1
                pairs = []
                for i in range(lo, min(lo + chunk, len(self._keys))):
                    k = self._keys[i]
                    if k > last or (k == last and not inc_last):
                        break
                    pairs.append((k, self._data[k]))
            if not pairs:
                return
            yield from pairs
            cursor = pairs[-1][0]
            first_round = False
            if len(pairs) < chunk:
                return

    def get_write_batch(self) -> KVWriteBatch:
        return KVWriteBatch()

    def commit_write_batch(self, wb: KVWriteBatch) -> None:
        with self._mu:
            self._apply_locked(wb)

    def commit_write_batch_nosync(self, wb: KVWriteBatch) -> None:
        """No durability to skip in memory — identical to commit."""
        self.commit_write_batch(wb)

    def sync(self) -> None:
        pass

    def _apply_locked(self, wb: KVWriteBatch) -> None:
        for op, k, v in wb.ops:
            if op == _PUT:
                if k not in self._data:
                    insort(self._keys, k)
                self._data[k] = v
            elif op == _DELETE:
                if k in self._data:
                    del self._data[k]
                    i = bisect_left(self._keys, k)
                    del self._keys[i]
            else:  # _DELETE_RANGE [k, v)
                lo = bisect_left(self._keys, k)
                hi = bisect_left(self._keys, v)
                for dk in self._keys[lo:hi]:
                    del self._data[dk]
                del self._keys[lo:hi]

    def bulk_remove_entries(self, first: bytes, last: bytes) -> None:
        wb = self.get_write_batch()
        wb.delete_range(first, last)
        self.commit_write_batch(wb)

    def compact_entries(self, first: bytes, last: bytes) -> None:
        pass  # no LSM levels to compact

    def full_compaction(self) -> None:
        pass

    def close(self) -> None:
        pass


_WAL_MAGIC = 0x57414C31  # "WAL1"
_HDR = struct.Struct("<IIi")  # crc32(payload), payload len, op count


def encode_ops(wb: KVWriteBatch) -> bytes:
    """The WAL record's op payload (shared with the host-plane group-commit
    journal): ``nops`` × ``<op u8><klen u32><key><vlen u32><value>``."""
    buf = bytearray()
    for op, k, v in wb.ops:
        buf.append(op)
        buf += struct.pack("<I", len(k))
        buf += k
        buf += struct.pack("<I", len(v))
        buf += v
    return bytes(buf)


def decode_ops(payload: bytes, nops: int) -> Optional[KVWriteBatch]:
    """Inverse of :func:`encode_ops`; None on a malformed payload."""
    wb = KVWriteBatch()
    p = 0
    for _ in range(nops):
        try:
            op = payload[p]
            klen = struct.unpack_from("<I", payload, p + 1)[0]
            p += 5
            k = payload[p : p + klen]
            p += klen
            vlen = struct.unpack_from("<I", payload, p)[0]
            p += 4
            v = payload[p : p + vlen]
            p += vlen
        except (IndexError, struct.error):
            return None
        wb.ops.append((op, bytes(k), bytes(v)))
    return wb


class WalKV(InMemKV):
    """Durable KV: in-memory index + append-only WAL, one record per batch.

    Record framing: ``<crc32><len><nops>`` header followed by
    ``nops`` × ``<op u8><klen u32><key><vlen u32><value>``.  Torn tails are
    detected by the crc and dropped on replay.  ``full_compaction`` rewrites
    the WAL as a single snapshot batch of live keys.
    """

    def __init__(self, dirname: str, fsync: bool = True, fs=None) -> None:
        """``fs`` (a :mod:`dragonboat_tpu.vfs` IFS) routes the WAL file IO
        through a virtual filesystem — vfs.ErrorFS turns this into the
        fault-injection backend the host-plane flusher durability test
        uses (nothing may ack before its fsync); None keeps the direct
        ``os`` path."""
        super().__init__()
        self._dir = dirname
        self._fsync = fsync
        self._fs = fs
        #: committed write batches fsynced through this store — the
        #: host-plane bench derives fsyncs/s and the group-commit
        #: amortization factor from the sum across shards
        self.fsyncs = 0
        if fs is None:
            os.makedirs(dirname, exist_ok=True)
        else:
            fs.makedirs(dirname, exist_ok=True)
        self._path = os.path.join(dirname, "kv.wal")
        self._replay()
        self._f = self._open_append()

    def _open_append(self):
        if self._fs is None:
            return open(self._path, "ab")
        return self._fs.open(self._path, "ab")

    def _do_fsync(self, f) -> None:
        if self._fs is None:
            os.fsync(f.fileno())
        else:
            self._fs.fsync(f)
        self.fsyncs += 1

    def name(self) -> str:
        return "walkv"

    @staticmethod
    def _encode_batch(wb: KVWriteBatch) -> bytes:
        payload = encode_ops(wb)
        return _HDR.pack(zlib.crc32(payload), len(payload), len(wb.ops)) + payload

    def _replay(self) -> None:
        if self._fs is None:
            if not os.path.exists(self._path):
                return
            with open(self._path, "rb") as f:
                data = f.read()
        else:
            if not self._fs.exists(self._path):
                return
            with self._fs.open(self._path, "rb") as f:
                data = f.read()
        pos, n = 0, len(data)
        valid_to = 0
        while pos + _HDR.size <= n:
            crc, plen, nops = _HDR.unpack_from(data, pos)
            body_start = pos + _HDR.size
            if body_start + plen > n:
                break
            payload = data[body_start : body_start + plen]
            if zlib.crc32(payload) != crc:
                break
            wb = decode_ops(payload, nops)
            if wb is None:
                break
            self._apply_locked(wb)
            pos = body_start + plen
            valid_to = pos
        if valid_to < n:  # truncate torn tail
            opener = open if self._fs is None else self._fs.open
            with opener(self._path, "r+b") as f:
                f.truncate(valid_to)

    def commit_write_batch(self, wb: KVWriteBatch) -> None:
        rec = self._encode_batch(wb)
        with self._mu:
            self._f.write(rec)
            self._f.flush()
            if self._fsync:
                self._do_fsync(self._f)
            # the in-memory view only moves AFTER the record is durable:
            # a failed write/fsync (vfs.ErrorFS injection) leaves state
            # unchanged, so nothing upstream can ack an unpersisted batch
            self._apply_locked(wb)

    def commit_write_batch_nosync(self, wb: KVWriteBatch) -> None:
        """Append + apply without the fsync — only valid under the
        host-plane group-commit journal (logdb/journal.py), whose own
        fsynced append covers this batch's durability."""
        rec = self._encode_batch(wb)
        with self._mu:
            self._f.write(rec)
            self._f.flush()
            self._apply_locked(wb)

    def sync(self) -> None:
        """Fsync the WAL tail (journal checkpoint half)."""
        with self._mu:
            if not self._f.closed:
                self._f.flush()
                self._do_fsync(self._f)

    def full_compaction(self) -> None:
        with self._mu:
            wb = KVWriteBatch()
            for k in self._keys:
                wb.put(k, self._data[k])
            rec = self._encode_batch(wb)
            tmp = self._path + ".tmp"
            opener = open if self._fs is None else self._fs.open
            with opener(tmp, "wb") as f:
                f.write(rec)
                f.flush()
                self._do_fsync(f)
            self._f.close()
            if self._fs is None:
                os.replace(tmp, self._path)
            else:
                self._fs.replace(tmp, self._path)
            self._f = self._open_append()

    def close(self) -> None:
        with self._mu:
            if not self._f.closed:
                self._f.flush()
                if self._fsync:
                    self._do_fsync(self._f)
                self._f.close()


KVFactory = Callable[[str], IKVStore]
