"""LogReader: the raft core's read view over the sharded LogDB.

Reference: ``internal/logdb/logreader.go`` — keeps an in-memory
``[marker, marker+length)`` window describing which indexes are available in
stable storage; ``append``/``set_range`` advance it after each persisted
round, while reads go straight to the DB.  The marker entry mirrors etcd's
dummy entry carrying the snapshot boundary term.
"""
from __future__ import annotations

import threading
from typing import List, Tuple

from ..wire import Entry, Membership, Snapshot, State
from ..raft.log import CompactedError, SnapshotOutOfDateError, UnavailableError


class LogReader:
    """Reference ``logreader.go`` ``LogReader``."""

    def __init__(self, cluster_id: int, node_id: int, logdb):
        self.cluster_id = cluster_id
        self.node_id = node_id
        self.logdb = logdb
        self._mu = threading.Lock()
        self.marker = 0
        self.marker_term = 0
        self.length = 1  # includes the marker dummy entry
        self.state = State()
        self.snapshot_record = Snapshot()

    # ---- ILogDB protocol (raft read view) ----

    def get_range(self) -> Tuple[int, int]:
        with self._mu:
            return self._first_index(), self._last_index()

    def _first_index(self) -> int:
        return self.marker + 1

    def _last_index(self) -> int:
        return self.marker + self.length - 1

    def node_state(self) -> Tuple[State, Membership]:
        with self._mu:
            return self.state, self.snapshot_record.membership

    def set_state(self, ps: State) -> None:
        with self._mu:
            self.state = ps

    def term(self, index: int) -> int:
        with self._mu:
            return self._term_locked(index)

    def _term_locked(self, index: int) -> int:
        if index == self.marker:
            return self.marker_term
        if index < self.marker:
            raise CompactedError()
        if index > self._last_index():
            raise UnavailableError()
        ents, _ = self.logdb.iterate_entries(
            [], 0, self.cluster_id, self.node_id, index, index + 1, 1 << 62
        )
        if not ents:
            raise UnavailableError()
        return ents[0].term

    def entries(self, low: int, high: int, max_size: int) -> List[Entry]:
        # the lock is held across the DB read so a concurrent compact cannot
        # delete the head of a validated range (reference logreader.go holds
        # lr.Lock() for the whole read)
        with self._mu:
            if low > high:
                raise ValueError(f"invalid range {low} > {high}")
            if low <= self.marker:
                raise CompactedError()
            if high > self._last_index() + 1:
                raise UnavailableError()
            ents, _ = self.logdb.iterate_entries(
                [], 0, self.cluster_id, self.node_id, low, high, max_size
            )
            return ents

    def snapshot(self) -> Snapshot:
        with self._mu:
            return self.snapshot_record

    def create_snapshot(self, ss: Snapshot) -> None:
        """Record a newly taken snapshot (reference ``logreader.go``
        ``CreateSnapshot``)."""
        with self._mu:
            if ss.index <= self.snapshot_record.index:
                raise SnapshotOutOfDateError()
            self.snapshot_record = ss

    def apply_snapshot(self, ss: Snapshot) -> None:
        """Reset the window to an installed snapshot (reference
        ``ApplySnapshot``)."""
        with self._mu:
            if ss.index <= self.snapshot_record.index:
                raise SnapshotOutOfDateError()
            self.snapshot_record = ss
            self.marker = ss.index
            self.marker_term = ss.term
            self.length = 1

    def append(self, entries: List[Entry]) -> None:
        """Advance the stable window after a persisted round (reference
        ``logreader.go`` ``Append``); entries were already written via
        ``SaveRaftState``."""
        if not entries:
            return
        first, last = entries[0].index, entries[-1].index
        if first + len(entries) - 1 != last:
            raise RuntimeError("gap in appended entries")
        self.set_range(first, len(entries))

    def set_range(self, index: int, length: int) -> None:
        """Merge ``[index, index+length)`` into the stable window
        (reference ``logreader.go`` ``SetRange``)."""
        if length == 0:
            return
        with self._mu:
            first = index
            last = index + length - 1
            if last < self._first_index():
                return
            if self.marker > first:
                cut = self.marker + 1 - first
                first = self.marker + 1
                length -= cut
            offset = first - self.marker
            if self.length > offset:
                self.length = offset + length
            elif self.length == offset:
                self.length += length
            else:
                raise RuntimeError(
                    f"gap in log: marker {self.marker} len {self.length} "
                    f"first {first}"
                )

    def extend_to(self, last: int) -> None:
        """Monotonically grow the stable window to cover ``last``.

        Unlike a ``get_range``+``set_range`` pair this is atomic, and it
        can only GROW the window — the no-eject snapshot path extends the
        window from outside raftMu, so it must never shrink a range a
        concurrent ``fast_eject`` (which holds raftMu) just set."""
        with self._mu:
            cur_last = self._last_index()
            if last > cur_last:
                self.length += last - cur_last

    def compact(self, index: int) -> None:
        """Move the marker forward (reference ``logreader.go:273``
        ``Compact``; strict ``<`` — compacting AT the marker is a no-op
        success, matching the real LogReader rather than the etcd test
        double, whose table treats it as already-compacted)."""
        with self._mu:
            if index < self.marker:
                raise CompactedError()
            if index > self._last_index():
                raise UnavailableError()
            term = self._term_locked(index)
            i = index - self.marker
            self.length -= i
            self.marker = index
            self.marker_term = term

    # ---- recovery ----

    def set_compact_to(self, index: int, term: int) -> None:
        with self._mu:
            self.marker = index
            self.marker_term = term
            self.length = 1

    @staticmethod
    def load(cluster_id: int, node_id: int, logdb) -> "LogReader":
        """Rebuild the reader from storage on restart: newest snapshot sets
        the marker, ``read_raft_state`` sets state + entry window
        (reference ``node.go`` ``replayLog`` first half)."""
        lr = LogReader(cluster_id, node_id, logdb)
        snapshots = logdb.list_snapshots(cluster_id, node_id)
        ss = snapshots[-1] if snapshots else None
        if ss is not None and not ss.is_empty():
            lr.snapshot_record = ss
            lr.marker = ss.index
            lr.marker_term = ss.term
            lr.length = 1
        rs = logdb.read_raft_state(cluster_id, node_id, lr.marker)
        if rs is not None:
            if not rs.state.is_empty():
                lr.state = rs.state
            if rs.entry_count > 0:
                lr.set_range(rs.first_index, rs.entry_count)
        return lr
