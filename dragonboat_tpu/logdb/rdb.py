"""Per-shard storage engine: key-schema CRUD over one KV store.

Reference: ``internal/logdb/rdb.go`` — State / MaxIndex / Bootstrap /
Snapshot-list / Entries records, one atomic WriteBatch per ``SaveRaftState``
round (``rdb.go:187-210``), plus the per-node write-suppression cache
(``internal/logdb/rdbcache.go``).
"""
from __future__ import annotations

import struct
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..wire import Bootstrap, Entry, Snapshot, State, Update
from ..wire.codec import (
    decode_bootstrap,
    decode_snapshot,
    decode_state,
    encode_bootstrap,
    encode_snapshot,
    encode_state,
)
from . import keys
from .entries import BatchedEntries, PlainEntries
from .kv import IKVStore, KVWriteBatch


@dataclass(frozen=True)
class NodeInfo:
    """Reference ``raftio/logdb.go`` ``NodeInfo``."""

    cluster_id: int
    node_id: int


@dataclass
class RaftState:
    """Reference ``raftio/logdb.go`` ``RaftState``."""

    state: State = field(default_factory=State)
    first_index: int = 0
    entry_count: int = 0


class RDBCache:
    """Suppresses redundant State / maxIndex writes (reference
    ``rdbcache.go:28-116``)."""

    def __init__(self) -> None:
        self._ps: Dict[Tuple[int, int], State] = {}
        self._max_index: Dict[Tuple[int, int], int] = {}
        self._mu = threading.Lock()

    def set_state(self, cluster_id: int, node_id: int, st: State) -> bool:
        """Returns True when the state changed and must be written."""
        key = (cluster_id, node_id)
        with self._mu:
            cur = self._ps.get(key)
            if (
                cur is not None
                and cur.term == st.term
                and cur.vote == st.vote
                and cur.commit == st.commit
            ):
                return False
            self._ps[key] = State(term=st.term, vote=st.vote, commit=st.commit)
            return True

    def set_max_index(self, cluster_id: int, node_id: int, max_index: int) -> bool:
        key = (cluster_id, node_id)
        with self._mu:
            if self._max_index.get(key) == max_index:
                return False
            self._max_index[key] = max_index
            return True

    def get_max_index(self, cluster_id: int, node_id: int) -> Optional[int]:
        with self._mu:
            return self._max_index.get((cluster_id, node_id))

    def invalidate(self, pairs) -> None:
        """Drop the cached State/maxIndex for ``(cluster_id, node_id)``
        pairs whose write batch FAILED to commit (ISSUE 12 fix): the
        cache was advanced at build time, so without this the retry's
        rebuild suppresses the very records the failed batch lost and
        the state silently never lands.  A dropped entry only costs the
        next round one unsuppressed write."""
        with self._mu:
            for key in pairs:
                self._ps.pop(key, None)
                self._max_index.pop(key, None)


_U64 = struct.Struct(">Q")


class RDB:
    """One storage shard (reference ``rdb.go:50``)."""

    def __init__(self, kv: IKVStore, batched: bool = False):
        self.kv = kv
        self.cache = RDBCache()
        self.entries = BatchedEntries(kv) if batched else PlainEntries(kv)

    # ---- bootstrap ----

    def save_bootstrap(self, cluster_id: int, node_id: int, bs: Bootstrap) -> None:
        self.kv.put(keys.bootstrap_key(cluster_id, node_id), encode_bootstrap(bs))

    def get_bootstrap(self, cluster_id: int, node_id: int) -> Optional[Bootstrap]:
        v = self.kv.get(keys.bootstrap_key(cluster_id, node_id))
        return decode_bootstrap(v) if v is not None else None

    def list_node_info(self) -> List[NodeInfo]:
        first = keys.make_key(keys.TAG_BOOTSTRAP, 0, 0, 0)
        last = keys.make_key(keys.TAG_BOOTSTRAP, 2**64 - 1, 2**64 - 1, 0)
        out = []
        for k, _ in self.kv.iterate(first, last, True):
            _, cid, nid, _ = keys.parse_key(k)
            out.append(NodeInfo(cluster_id=cid, node_id=nid))
        return out

    # ---- raft state round (the hot write path) ----

    def save_raft_state(self, updates: List[Update], wb: KVWriteBatch) -> None:
        """One atomic, fsynced write batch for a worker round
        (reference ``rdb.go:187-210``)."""
        self.build_raft_state(updates, wb)
        # rounds where every record was suppressed (heartbeat traffic with
        # unchanged State) must not pay a WAL append + fsync for an empty
        # batch — the rdbcache exists precisely to elide these writes
        if wb.ops:
            try:
                self.kv.commit_write_batch(wb)
            except BaseException:
                # the build advanced the rdbcache for records this batch
                # was carrying; a failed commit must drop those entries
                # or the retry's rebuild suppresses them forever
                self.cache.invalidate(
                    {(u.cluster_id, u.node_id) for u in updates}
                )
                raise

    def build_raft_state(self, updates: List[Update], wb: KVWriteBatch) -> None:
        """Fill ``wb`` with the round's records WITHOUT committing — the
        host-plane group-commit journal path commits the batch itself
        (journal fsync first, then ``commit_write_batch_nosync``)."""
        for ud in updates:
            self._record_state(ud, wb)
            if ud.snapshot is not None and not ud.snapshot.is_empty():
                self._record_snapshot(wb, ud.cluster_id, ud.node_id, ud.snapshot)
            if ud.entries_to_save:
                mi = self.entries.record_entries(
                    wb, ud.cluster_id, ud.node_id, ud.entries_to_save
                )
                if mi > 0:
                    self._record_max_index(wb, ud.cluster_id, ud.node_id, mi)
            elif ud.snapshot is not None and not ud.snapshot.is_empty():
                self._record_max_index(
                    wb, ud.cluster_id, ud.node_id, ud.snapshot.index
                )

    def _record_state(self, ud: Update, wb: KVWriteBatch) -> None:
        if ud.state.is_empty():
            return
        if not self.cache.set_state(ud.cluster_id, ud.node_id, ud.state):
            return
        wb.put(keys.state_key(ud.cluster_id, ud.node_id), encode_state(ud.state))

    def _record_max_index(
        self, wb: KVWriteBatch, cluster_id: int, node_id: int, max_index: int
    ) -> None:
        if not self.cache.set_max_index(cluster_id, node_id, max_index):
            return
        wb.put(keys.max_index_key(cluster_id, node_id), _U64.pack(max_index))

    def read_max_index(self, cluster_id: int, node_id: int) -> int:
        v = self.kv.get(keys.max_index_key(cluster_id, node_id))
        return _U64.unpack(v)[0] if v is not None else 0

    def read_state(self, cluster_id: int, node_id: int) -> Optional[State]:
        v = self.kv.get(keys.state_key(cluster_id, node_id))
        return decode_state(v) if v is not None else None

    def read_raft_state(
        self, cluster_id: int, node_id: int, last_index: int
    ) -> Optional[RaftState]:
        """Reference ``rdb.go`` ``readRaftState``: state + entry range."""
        st = self.read_state(cluster_id, node_id)
        if st is None:
            return None
        max_index = self.read_max_index(cluster_id, node_id)
        first, length = self._entry_range(cluster_id, node_id, last_index, max_index)
        return RaftState(state=st, first_index=first, entry_count=length)

    def _entry_range(
        self, cluster_id: int, node_id: int, snapshot_index: int, max_index: int
    ) -> Tuple[int, int]:
        if max_index == 0 or max_index < snapshot_index:
            return 0, 0
        # find the first stored entry at or after the snapshot boundary
        ents, _ = self.entries.iterate_entries(
            [], 0, cluster_id, node_id, snapshot_index, snapshot_index + 1, 1 << 62
        )
        start = snapshot_index
        if not ents:
            start = snapshot_index + 1
            e = self.entries.get_entry(cluster_id, node_id, start)
            if e is None:
                return 0, 0
        return start, max_index - start + 1

    def iterate_entries(
        self,
        ents: List[Entry],
        size: int,
        cluster_id: int,
        node_id: int,
        low: int,
        high: int,
        max_size: int,
    ) -> Tuple[List[Entry], int]:
        max_index = self.read_max_index(cluster_id, node_id)
        if high > max_index + 1:
            high = max_index + 1
        if low >= high:
            return ents, size
        return self.entries.iterate_entries(
            ents, size, cluster_id, node_id, low, high, max_size
        )

    # ---- snapshots ----

    def _record_snapshot(
        self, wb: KVWriteBatch, cluster_id: int, node_id: int, ss: Snapshot
    ) -> None:
        wb.put(
            keys.snapshot_key(cluster_id, node_id, ss.index), encode_snapshot(ss)
        )

    def save_snapshot(self, cluster_id: int, node_id: int, ss: Snapshot) -> None:
        wb = self.kv.get_write_batch()
        self._record_snapshot(wb, cluster_id, node_id, ss)
        self.kv.commit_write_batch(wb)

    def delete_snapshot(self, cluster_id: int, node_id: int, index: int) -> None:
        self.kv.delete(keys.snapshot_key(cluster_id, node_id, index))

    def list_snapshots(
        self, cluster_id: int, node_id: int, index: int = keys.MAX_INDEX
    ) -> List[Snapshot]:
        """Ascending snapshot records up to ``index`` inclusive."""
        fk = keys.snapshot_key(cluster_id, node_id, 0)
        lk = keys.snapshot_key(cluster_id, node_id, index)
        return [decode_snapshot(v) for _, v in self.kv.iterate(fk, lk, True)]

    # ---- removal / compaction ----

    def remove_entries_to(self, cluster_id: int, node_id: int, index: int) -> None:
        wb = self.kv.get_write_batch()
        self.entries.remove_entries_to(wb, cluster_id, node_id, index)
        self.kv.commit_write_batch(wb)

    def compact_entries_to(self, cluster_id: int, node_id: int, index: int) -> None:
        self.entries.compact_range(cluster_id, node_id, index)

    def remove_node_data(self, cluster_id: int, node_id: int) -> None:
        """Reference ``rdb.go`` ``removeNodeData``: wipe everything.

        Keys are tag-major, so each tag's ``(cluster, node)`` range must be
        deleted separately — one cross-tag range would span other nodes'
        records.
        """
        wb = self.kv.get_write_batch()
        wb.delete(keys.bootstrap_key(cluster_id, node_id))
        wb.delete(keys.state_key(cluster_id, node_id))
        wb.delete(keys.max_index_key(cluster_id, node_id))
        for tag in (keys.TAG_SNAPSHOT, keys.TAG_ENTRY, keys.TAG_ENTRY_BATCH):
            wb.delete_range(
                keys.make_key(tag, cluster_id, node_id, 0),
                keys.make_key(tag, cluster_id, node_id, keys.MAX_INDEX),
            )
            wb.delete(keys.make_key(tag, cluster_id, node_id, keys.MAX_INDEX))
        self.kv.commit_write_batch(wb)
        self.cache.set_max_index(cluster_id, node_id, 0)

    def import_snapshot(self, ss: Snapshot, node_id: int) -> None:
        """Reference ``rdb.go:212-237``: reset a node's records from an
        imported snapshot (quorum-loss repair)."""
        if ss.type == 0 and not ss.membership.addresses:
            raise ValueError("invalid snapshot for import")
        selected = [
            rec
            for rec in self.list_snapshots(ss.cluster_id, node_id)
            if rec.index >= ss.index
        ]
        bs = Bootstrap(join=True, type=ss.type)
        wb = self.kv.get_write_batch()
        wb.put(keys.bootstrap_key(ss.cluster_id, node_id), encode_bootstrap(bs))
        for rec in selected:
            wb.delete(keys.snapshot_key(ss.cluster_id, node_id, rec.index))
        wb.put(
            keys.state_key(ss.cluster_id, node_id),
            encode_state(State(term=ss.term, commit=ss.index)),
        )
        self._record_snapshot(wb, ss.cluster_id, node_id, ss)
        wb.put(keys.max_index_key(ss.cluster_id, node_id), _U64.pack(ss.index))
        self.kv.commit_write_batch(wb)
        self.cache.set_max_index(ss.cluster_id, node_id, ss.index)

    def close(self) -> None:
        self.kv.close()
