"""Sharded LogDB: N independent shards + async compaction worker.

Reference: ``internal/logdb/sharded_rdb.go`` — 16 shards
(``settings/hard.go:37``), ``clusterID % shards`` placement via the
partitioner (``server/partition.go:59``), background compaction queue
(``sharded_rdb.go:292``), and the plain/batched format self-check.
"""
from __future__ import annotations

import os
import queue
import threading
from typing import Callable, List, Optional, Tuple

from ..logger import get_logger
from ..settings import Hard
from ..wire import Bootstrap, Entry, Snapshot, Update
from .entries import has_entry_records
from .kv import IKVStore, InMemKV, WalKV
from .rdb import RDB, NodeInfo, RaftState

plog = get_logger("logdb")

_STOP = object()


class ShardedDB:
    """Reference ``sharded_rdb.go:44`` ``ShardedRDB``."""

    def __init__(
        self, shards: List[RDB], batched: bool = False, dirname: str = ""
    ):
        self._shards = shards
        self._batched = batched
        self._dir = dirname
        # host-plane group-commit journal (logdb/journal.py): armed by
        # enable_host_journal(); save_raft_state_journaled then rides ONE
        # journal fsync per flush cycle for every shard's batches.
        # _journal_mu serializes a whole journaled cycle (append + the
        # nosync shard applies) against checkpoints: a checkpoint
        # truncating between the two would discard the only durable copy
        # of the in-flight cycle while the shard stores still lag.
        self.journal = None
        self._journal_mu = threading.Lock()
        # invoked after each async compaction round (cluster_id, node_id);
        # nodehost publishes LOGDB_COMPACTED through it
        self.on_compaction = None
        self._compaction_q: "queue.Queue" = queue.Queue()
        self._compaction_worker = threading.Thread(
            target=self._compaction_main, name="logdb-compaction", daemon=True
        )
        self._compaction_worker.start()

    # ---- identity / format ----

    def name(self) -> str:
        fmt = "batched" if self._batched else "plain"
        return f"sharded-{self._shards[0].kv.name()}-{fmt}"

    def binary_format(self) -> int:
        return 1

    def selfcheck_failed(self) -> bool:
        """True when on-disk entry format disagrees with the configured one
        (reference ``logdb.go:44-56``)."""
        other = not self._batched
        return any(has_entry_records(s.kv, other) for s in self._shards)

    def _shard(self, cluster_id: int) -> RDB:
        return self._shards[cluster_id % len(self._shards)]

    # ---- bootstrap ----

    def save_bootstrap_info(
        self, cluster_id: int, node_id: int, bs: Bootstrap
    ) -> None:
        self._shard(cluster_id).save_bootstrap(cluster_id, node_id, bs)

    def get_bootstrap_info(
        self, cluster_id: int, node_id: int
    ) -> Optional[Bootstrap]:
        return self._shard(cluster_id).get_bootstrap(cluster_id, node_id)

    def list_node_info(self) -> List[NodeInfo]:
        out: List[NodeInfo] = []
        for s in self._shards:
            out.extend(s.list_node_info())
        return out

    # ---- raft state ----

    def save_raft_state(self, updates: List[Update]) -> None:
        """Group updates by shard; one atomic write batch per shard.

        The reference passes a per-worker IContext whose write batch covers
        exactly one shard because workers and shards are co-partitioned
        (``server/partition.go:59``); here updates are bucketed explicitly so
        any caller threading model works.
        """
        buckets = {}
        for ud in updates:
            buckets.setdefault(ud.cluster_id % len(self._shards), []).append(ud)
        for idx, uds in buckets.items():
            shard = self._shards[idx]
            wb = shard.kv.get_write_batch()
            shard.save_raft_state(uds, wb)

    # ---- host-plane group-commit journal (ISSUE 8) ----

    def enable_host_journal(self, fs=None):
        """Arm the cross-shard group-commit journal (durable dirs only).
        Returns the journal, or None when this DB has no directory (the
        in-memory backend has nothing to amortize).  ``fs`` routes the
        journal IO through a vfs (ErrorFS fault injection)."""
        if self.journal is not None:
            return self.journal
        if not self._dir:
            return None
        import os as _os

        from .journal import JOURNAL_NAME, HostJournal

        self.journal = HostJournal(
            _os.path.join(self._dir, JOURNAL_NAME), fs=fs
        )
        return self.journal

    def save_raft_state_journaled(self, updates: List[Update]) -> bool:
        """The group-commit flush cycle: build every shard's write batch,
        append them all to the journal under ONE fsync, then apply to the
        shard stores without their own fsync.  Requires
        ``enable_host_journal``; per-group ordering is the caller's
        (single flush leader at a time) and per-shard batches stay atomic.

        Adaptive: a cycle carrying exactly ONE shard batch while the
        journal is EMPTY has nothing to amortize — it commits through the
        shard's classic fsynced path (bit-identical cost to the
        uncompartmented committer) and returns False.  The journal-empty
        guard is a correctness rule, not a heuristic: a direct write
        landing AFTER journaled-but-unsynced writes would be regressed by
        a crash replay re-applying the older journal history over it.
        Returns True when the cycle rode the journal."""
        buckets = {}
        for ud in updates:
            buckets.setdefault(ud.cluster_id % len(self._shards), []).append(ud)
        prepared = []
        for idx, uds in buckets.items():
            shard = self._shards[idx]
            wb = shard.kv.get_write_batch()
            shard.build_raft_state(uds, wb)
            if wb.ops:
                prepared.append((idx, wb))
        if not prepared:
            return False
        with self._journal_mu:
            try:
                if len(prepared) == 1 and not self.journal.nonempty():
                    idx, wb = prepared[0]
                    self._shards[idx].kv.commit_write_batch(wb)
                    return False
                # the ONE fsync (in-process or via the hostproc WAL
                # worker sink); raises on failure
                self.journal.append(prepared)
                for idx, wb in prepared:
                    self._shards[idx].kv.commit_write_batch_nosync(wb)
                return True
            except BaseException:
                # build_raft_state advanced each shard's rdbcache for
                # the records these batches carry; a failed append /
                # commit must drop those entries or the committer's
                # RETRY rebuild suppresses them and the state silently
                # never lands (ISSUE 12 fix, caught by the WAL-worker
                # fault-injection suite)
                for idx, uds in buckets.items():
                    self._shards[idx].cache.invalidate(
                        {(u.cluster_id, u.node_id) for u in uds}
                    )
                raise

    def journal_checkpoint(self) -> None:
        """Fsync every shard store, then truncate the journal — under the
        journal mutex so an in-flight journaled cycle is never stranded
        half-applied (see ``_journal_mu``)."""
        with self._journal_mu:
            j = self.journal
            if j is not None and j.nonempty():
                j.checkpoint(self.sync_all)

    def sync_all(self) -> None:
        """Fsync every shard store (journal checkpoint half)."""
        for s in self._shards:
            sync = getattr(s.kv, "sync", None)
            if sync is not None:
                sync()

    def _journal_barrier(self) -> None:
        """Checkpoint before a DIRECT destructive mutation (snapshot
        delete, node-data removal, snapshot import): journal history
        replayed over such a mutation after a crash would resurrect the
        deleted records.  Rare operations, so the nshards-fsync cost is
        irrelevant; with the journal empty nothing happens.  A failed
        checkpoint PROPAGATES — proceeding with the mutation would
        re-create the exact replay-resurrection hazard the barrier
        exists to prevent."""
        if self.journal is not None and self.journal.nonempty():
            self.journal_checkpoint()

    def fsync_count(self) -> int:
        """Committed-write-batch fsyncs across all shards plus the host
        journal's (backends that don't count — in-memory — contribute 0).
        The host-plane bench reads this for its fsyncs/s and amortization
        columns."""
        n = sum(getattr(s.kv, "fsyncs", 0) for s in self._shards)
        if self.journal is not None:
            n += self.journal.fsyncs
        return n

    def read_raft_state(
        self, cluster_id: int, node_id: int, last_index: int
    ) -> Optional[RaftState]:
        return self._shard(cluster_id).read_raft_state(
            cluster_id, node_id, last_index
        )

    def refresh_cached_state(
        self, cluster_id: int, node_id: int, term: int, vote: int,
        commit: int, max_index: int,
    ) -> None:
        """Re-seed the write-suppression caches after an external writer
        (the native fast lane) updated the State/MaxIndex records directly —
        else a later save round would either suppress a needed write or
        re-issue a redundant one against stale assumptions."""
        from ..wire import State

        shard = self._shard(cluster_id)
        shard.cache.set_state(
            cluster_id, node_id, State(term=term, vote=vote, commit=commit)
        )
        shard.cache.set_max_index(cluster_id, node_id, max_index)

    def iterate_entries(
        self,
        ents: List[Entry],
        size: int,
        cluster_id: int,
        node_id: int,
        low: int,
        high: int,
        max_size: int,
    ) -> Tuple[List[Entry], int]:
        return self._shard(cluster_id).iterate_entries(
            ents, size, cluster_id, node_id, low, high, max_size
        )

    # ---- snapshots ----

    def save_snapshots(self, updates: List[Update]) -> None:
        for ud in updates:
            if ud.snapshot is not None and not ud.snapshot.is_empty():
                self._shard(ud.cluster_id).save_snapshot(
                    ud.cluster_id, ud.node_id, ud.snapshot
                )

    def save_snapshot(self, cluster_id: int, node_id: int, ss: Snapshot) -> None:
        self._shard(cluster_id).save_snapshot(cluster_id, node_id, ss)

    def delete_snapshot(self, cluster_id: int, node_id: int, index: int) -> None:
        self._journal_barrier()
        self._shard(cluster_id).delete_snapshot(cluster_id, node_id, index)

    def list_snapshots(
        self, cluster_id: int, node_id: int, index: int = 2**64 - 1
    ) -> List[Snapshot]:
        return self._shard(cluster_id).list_snapshots(cluster_id, node_id, index)

    # ---- removal / compaction ----

    def remove_entries_to(self, cluster_id: int, node_id: int, index: int) -> None:
        """Synchronously range-delete, then queue async compaction
        (reference ``sharded_rdb.go:270-298``)."""
        self._journal_barrier()
        self._shard(cluster_id).remove_entries_to(cluster_id, node_id, index)
        self._compaction_q.put((cluster_id, node_id, index))

    def compact_entries_to(self, cluster_id: int, node_id: int, index: int):
        done = threading.Event()
        self._compaction_q.put((cluster_id, node_id, index, done))
        return done

    def remove_node_data(self, cluster_id: int, node_id: int) -> None:
        self._journal_barrier()
        self._shard(cluster_id).remove_node_data(cluster_id, node_id)

    def import_snapshot(self, ss: Snapshot, node_id: int) -> None:
        self._journal_barrier()
        self._shard(ss.cluster_id).import_snapshot(ss, node_id)

    def _compaction_main(self) -> None:
        while True:
            item = self._compaction_q.get()
            if item is _STOP:
                return
            cluster_id, node_id, index = item[0], item[1], item[2]
            try:
                self._shard(cluster_id).compact_entries_to(
                    cluster_id, node_id, index
                )
                if self.on_compaction is not None:
                    self.on_compaction(cluster_id, node_id)
            except Exception:
                # the worker must survive a failed compaction: letting the
                # exception kill this thread would silently disable ALL
                # future compaction (the queue drains nowhere) — found by
                # the RequestCompaction full-range overflow test
                plog.exception(
                    "compaction %d:%d to %d failed", cluster_id, node_id, index
                )
            finally:
                if len(item) > 3:
                    item[3].set()

    def close(self) -> None:
        self._compaction_q.put(_STOP)
        self._compaction_worker.join(timeout=5)
        if self.journal is not None:
            # shard stores may hold journal-covered, un-fsynced tails:
            # make them durable, then retire the journal cleanly
            try:
                self.journal_checkpoint()
            except OSError:
                plog.exception("host journal final checkpoint failed")
            self.journal.close()
        for s in self._shards:
            s.close()


def open_logdb(
    dirname: str = "",
    shards: int = 0,
    batched: bool = False,
    kv_factory: Optional[Callable[[str], IKVStore]] = None,
    fsync: bool = True,
) -> ShardedDB:
    """Open (or create) a sharded LogDB.

    ``dirname == ""`` selects the in-memory backend (test/bench builds,
    analogous to the reference's memfs Pebble).  Otherwise each shard gets
    ``dirname/shard-NN`` backed by the C++ native segmented-WAL engine
    (``dragonboat_tpu/native``, the analog of the reference's default
    Pebble / optional RocksDB cgo backend) — falling back to the Python
    :class:`WalKV` only where the native library cannot be built.
    """
    n = shards or Hard.logdb_pool_size
    durable_factory: Optional[Callable[[str], IKVStore]] = None
    if kv_factory is None and dirname:
        from .. import native

        if native.available():
            durable_factory = lambda d: native.NativeKV(d, fsync=fsync)
        else:
            durable_factory = lambda d: WalKV(d, fsync=fsync)
    rdbs: List[RDB] = []
    for i in range(n):
        if kv_factory is not None:
            kv = kv_factory(os.path.join(dirname, f"shard-{i:02d}") if dirname else "")
        elif dirname:
            kv = durable_factory(os.path.join(dirname, f"shard-{i:02d}"))
        else:
            kv = InMemKV()
        rdbs.append(RDB(kv, batched=batched))
    if dirname:
        # leftover host-plane group-commit journal (crash, or a restart
        # with compartments off): its writes were acked but the shard
        # stores may lag — replay before the DB is handed out
        from .journal import JOURNAL_NAME, replay

        jpath = os.path.join(dirname, JOURNAL_NAME)
        if os.path.exists(jpath):
            replay(jpath, rdbs)
    db = ShardedDB(rdbs, batched=batched, dirname=dirname)
    if db.selfcheck_failed():
        db.close()
        raise RuntimeError(
            "on-disk entry format does not match the configured format"
        )
    return db
