"""Pluggable per-subsystem logging (reference ``logger/logger.go:25-60``).

The reference registers named loggers per package with adjustable levels and a
pluggable factory; this maps directly onto the stdlib ``logging`` module with a
thin shim preserving the reference's API shape (``GetLogger``,
``SetLoggerFactory``, per-logger levels).
"""
from __future__ import annotations

import logging
from typing import Callable, Dict, Optional

CRITICAL = logging.CRITICAL
ERROR = logging.ERROR
WARNING = logging.WARNING
INFO = logging.INFO
DEBUG = logging.DEBUG

_factory: Optional[Callable[[str], logging.Logger]] = None
_loggers: Dict[str, logging.Logger] = {}


def set_logger_factory(factory: Callable[[str], logging.Logger]) -> None:
    global _factory
    _factory = factory
    _loggers.clear()


def get_logger(pkg_name: str) -> logging.Logger:
    if pkg_name not in _loggers:
        if _factory is not None:
            _loggers[pkg_name] = _factory(pkg_name)
        else:
            _loggers[pkg_name] = logging.getLogger(f"dragonboat_tpu.{pkg_name}")
    return _loggers[pkg_name]


def set_package_log_level(pkg_name: str, level: int) -> None:
    get_logger(pkg_name).setLevel(level)
