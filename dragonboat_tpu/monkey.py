"""Monkey/chaos-test hooks.

Reference: ``monkey.go`` (build-tag-gated introspection: partition
injection :184-213, transport drop hooks :82, SM/session/membership
hashes :110-144) — the instrumentation surface the external Drummer
harness drives.  Here the hooks are a plain module (no build tags needed:
nothing below mutates production behavior unless invoked) used by
``tests/test_chaos.py``.
"""
from __future__ import annotations

import random
from typing import Dict, Iterable, List, Optional

from .nodehost import NodeHost

# ---------------------------------------------------------------------------
# cross-replica consistency hashes (reference monkey.go:110-144)
# ---------------------------------------------------------------------------


def get_state_hash(nh: NodeHost, cluster_id: int) -> int:
    """Combined sessions+applied+membership hash (reference rsm.GetHash)."""
    return nh.get_node(cluster_id).sm.get_hash()


def get_session_hash(nh: NodeHost, cluster_id: int) -> int:
    return nh.get_node(cluster_id).sm.get_session_hash()


def get_membership_hash(nh: NodeHost, cluster_id: int) -> int:
    return nh.get_node(cluster_id).sm.get_membership_hash()


def get_applied_index(nh: NodeHost, cluster_id: int) -> int:
    return nh.get_node(cluster_id).sm.get_last_applied()


def assert_replicas_converged(
    nhs: Iterable[NodeHost], cluster_id: int
) -> Dict[str, int]:
    """Raises AssertionError unless every replica reports the same state
    hash at the same applied index; returns {address: hash}."""
    snap = {}
    applied = set()
    for nh in nhs:
        snap[nh.raft_address()] = get_state_hash(nh, cluster_id)
        applied.add(get_applied_index(nh, cluster_id))
    if len(applied) != 1 or len(set(snap.values())) != 1:
        raise AssertionError(
            f"replicas diverged: applied={applied} hashes={snap}"
        )
    return snap


# ---------------------------------------------------------------------------
# partition / drop injection over the chan transport
# ---------------------------------------------------------------------------


class PartitionInjector:
    """Drives ChanRouter partitions the way the reference's monkey harness
    partitions NodeHosts (``monkey.go:184-213``): pick a random minority,
    cut it off, heal later."""

    def __init__(self, router, addresses: List[str], seed: int = 0):
        self.router = router
        self.addresses = list(addresses)
        self.rng = random.Random(seed)
        self.active: List[tuple] = []

    def partition_random_minority(self) -> List[str]:
        n = len(self.addresses)
        k = self.rng.randrange(1, max(2, (n + 1) // 2))
        minority = self.rng.sample(self.addresses, k)
        majority = [a for a in self.addresses if a not in minority]
        for a in minority:
            for b in majority:
                self.router.partition(a, b)
                self.active.append((a, b))
        return minority

    def isolate(self, addr: str) -> None:
        for b in self.addresses:
            if b != addr:
                self.router.partition(addr, b)
                self.active.append((addr, b))

    def heal_all(self) -> None:
        self.router.heal()
        self.active.clear()


def set_drop_rate(router, rate: float, seed: int = 0) -> None:
    """Probabilistically drop message batches (reference
    SetTransportDropBatchHook ``monkey.go:82``).  ``rate=0`` clears."""
    if rate <= 0:
        router.set_drop_hook(None)
        return
    rng = random.Random(seed)
    router.set_drop_hook(lambda batch: rng.random() < rate)


# ---------------------------------------------------------------------------
# cross-domain latency injection (ISSUE 10; transport/latency.py)
# ---------------------------------------------------------------------------


def set_latency(nhs: Iterable[NodeHost], injector) -> None:
    """Install a :class:`~dragonboat_tpu.transport.latency.LatencyInjector`
    on every host's transport send plane (``injector=None`` clears).  The
    per-remote sender threads then sleep each link's one-way delay before
    sending — the cross-domain harness the `run_crossdomain` bench rung
    and the lease tests drive."""
    for nh in nhs:
        nh.transport.latency = injector
