"""Native (C++) log engine binding.

The reference's only native component is its RocksDB cgo backend
(``internal/logdb/kv/rocksdb/gorocksdb/gorocksdb.c``, SURVEY.md §2.4).
This package is the TPU build's equivalent: ``nativekv.cpp`` is a
segmented-WAL key-value log engine with the ``IKVStore`` contract —
atomic write batches, range-delete, manual compaction, crash recovery —
compiled to ``libnativekv.so`` and fronted here over ``ctypes``
(pybind11 is not available in this image).

The library is compiled on demand via the bundled Makefile the first time
:func:`available` / :class:`NativeKV` is used and then cached.
"""
from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import threading
from typing import Iterator, Optional, Tuple

from ..logdb.kv import KVWriteBatch

_DIR = os.path.dirname(os.path.abspath(__file__))
# DBTPU_NATIVE_LIB_DIR: load the .so set from an alternate build dir —
# the TSAN race-detection gate (make test-tsan) points it at
# -fsanitize=thread builds, the analog of the reference's RACE=1 make
# test (docs Makefile:122-127)
_LIB_DIR = os.environ.get("DBTPU_NATIVE_LIB_DIR") or _DIR
_SO = os.path.join(_LIB_DIR, "libnativekv.so")
_SRC = os.path.join(_DIR, "nativekv.cpp")

_lib = None
_lib_mu = threading.Lock()
_build_error: Optional[str] = None


def _load():
    global _lib, _build_error
    with _lib_mu:
        if _lib is not None:
            return _lib
        if _build_error is not None:
            raise RuntimeError(_build_error)
        # build-on-demand applies only to the default lib dir: an explicit
        # DBTPU_NATIVE_LIB_DIR override is load-only (make would rebuild
        # the DEFAULT .so and this would then silently load a stale
        # override build — the TSAN gate rebuilds its own dir explicitly)
        if _LIB_DIR == _DIR and (
            not os.path.exists(_SO)
            or os.path.getmtime(_SO) < os.path.getmtime(_SRC)
        ):
            proc = subprocess.run(
                ["make", "-C", _DIR, "libnativekv.so"],
                capture_output=True,
                text=True,
            )
            if proc.returncode != 0:
                _build_error = f"nativekv build failed:\n{proc.stderr}"
                raise RuntimeError(_build_error)
        lib = ctypes.CDLL(_SO)
        lib.nkv_open.restype = ctypes.c_void_p
        lib.nkv_open.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int,
            ctypes.c_char_p,
            ctypes.c_size_t,
        ]
        lib.nkv_close.argtypes = [ctypes.c_void_p]
        lib.nkv_errmsg.restype = ctypes.c_char_p
        lib.nkv_errmsg.argtypes = [ctypes.c_void_p]
        lib.nkv_get.restype = ctypes.c_int
        lib.nkv_get.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_size_t),
        ]
        lib.nkv_buf_free.argtypes = [ctypes.c_void_p]
        lib.nkv_commit.restype = ctypes.c_int
        lib.nkv_commit.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_size_t,
        ]
        # nkv_commit2/nkv_sync: host-plane group-commit support (a
        # stale .so — e.g. an old DBTPU_NATIVE_LIB_DIR build — simply
        # lacks them; NativeKV degrades to always-fsync commits)
        if hasattr(lib, "nkv_commit2"):
            lib.nkv_commit2.restype = ctypes.c_int
            lib.nkv_commit2.argtypes = [
                ctypes.c_void_p,
                ctypes.c_char_p,
                ctypes.c_size_t,
                ctypes.c_int,
            ]
            lib.nkv_sync.restype = ctypes.c_int
            lib.nkv_sync.argtypes = [ctypes.c_void_p]
        lib.nkv_bulk_remove.restype = ctypes.c_int
        lib.nkv_bulk_remove.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.c_char_p,
            ctypes.c_size_t,
        ]
        lib.nkv_compact_range.restype = ctypes.c_int
        lib.nkv_compact_range.argtypes = [ctypes.c_void_p]
        lib.nkv_full_compaction.restype = ctypes.c_int
        lib.nkv_full_compaction.argtypes = [ctypes.c_void_p]
        lib.nkv_segment_count.restype = ctypes.c_uint64
        lib.nkv_segment_count.argtypes = [ctypes.c_void_p]
        lib.nkv_iter_new.restype = ctypes.c_void_p
        lib.nkv_iter_new.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.c_int,
        ]
        lib.nkv_iter_next.restype = ctypes.c_int
        lib.nkv_iter_next.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_size_t),
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_size_t),
        ]
        lib.nkv_iter_free.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib


def available() -> bool:
    """True when the native engine can be built/loaded on this machine."""
    try:
        _load()
        return True
    except (RuntimeError, OSError):
        return False


def _encode_batch(wb: KVWriteBatch) -> bytes:
    buf = bytearray()
    for op, k, v in wb.ops:
        buf.append(op)
        buf += struct.pack("<I", len(k))
        buf += k
        buf += struct.pack("<I", len(v))
        buf += v
    return bytes(buf)


class NativeKV:
    """``IKVStore`` over the C++ segmented-WAL engine."""

    def __init__(self, dirname: str, fsync: bool = True) -> None:
        lib = _load()
        os.makedirs(dirname, exist_ok=True)
        errbuf = ctypes.create_string_buffer(512)
        self._h = lib.nkv_open(
            dirname.encode(), 1 if fsync else 0, errbuf, len(errbuf)
        )
        if not self._h:
            raise IOError(f"nativekv open {dirname!r}: {errbuf.value.decode()}")
        self._lib = lib
        self._mu = threading.Lock()
        self._closed = False
        self._fsync = fsync
        #: committed-batch fsyncs issued through this store (commit with
        #: fsync enabled, plus explicit sync()) — the host-plane bench
        #: derives fsyncs/s from the per-shard sum
        self.fsyncs = 0

    # -- IKVStore --

    def name(self) -> str:
        return "nativekv"

    def _check(self, rc: int) -> None:
        if rc < 0:
            msg = self._lib.nkv_errmsg(self._h)
            raise IOError(msg.decode() if msg else "nativekv error")

    def get(self, key: bytes) -> Optional[bytes]:
        val = ctypes.c_void_p()
        vlen = ctypes.c_size_t()
        rc = self._lib.nkv_get(
            self._h, key, len(key), ctypes.byref(val), ctypes.byref(vlen)
        )
        self._check(rc)
        if rc == 0:
            return None
        try:
            return ctypes.string_at(val.value, vlen.value)
        finally:
            self._lib.nkv_buf_free(val)

    def put(self, key: bytes, value: bytes) -> None:
        wb = self.get_write_batch()
        wb.put(key, value)
        self.commit_write_batch(wb)

    def delete(self, key: bytes) -> None:
        wb = self.get_write_batch()
        wb.delete(key)
        self.commit_write_batch(wb)

    def iterate(
        self, first: bytes, last: bytes, inc_last: bool
    ) -> Iterator[Tuple[bytes, bytes]]:
        it = self._lib.nkv_iter_new(
            self._h, first, len(first), last, len(last), 1 if inc_last else 0
        )
        if not it:
            self._check(-1)
        k = ctypes.c_void_p()
        klen = ctypes.c_size_t()
        v = ctypes.c_void_p()
        vlen = ctypes.c_size_t()
        try:
            while self._lib.nkv_iter_next(
                it,
                ctypes.byref(k),
                ctypes.byref(klen),
                ctypes.byref(v),
                ctypes.byref(vlen),
            ):
                yield (
                    ctypes.string_at(k.value, klen.value),
                    ctypes.string_at(v.value, vlen.value),
                )
        finally:
            self._lib.nkv_iter_free(it)

    def get_write_batch(self) -> KVWriteBatch:
        return KVWriteBatch()

    def commit_write_batch(self, wb: KVWriteBatch) -> None:
        payload = _encode_batch(wb)
        self._check(self._lib.nkv_commit(self._h, payload, len(payload)))
        if self._fsync:
            self.fsyncs += 1

    def commit_write_batch_nosync(self, wb: KVWriteBatch) -> None:
        """Append + apply WITHOUT the fdatasync — only valid under the
        host-plane group-commit journal, whose own fsynced append covers
        this batch's durability (logdb/journal.py).  Falls back to the
        durable commit on a stale native library."""
        if not hasattr(self._lib, "nkv_commit2"):
            self.commit_write_batch(wb)
            return
        payload = _encode_batch(wb)
        self._check(self._lib.nkv_commit2(self._h, payload, len(payload), 0))

    def sync(self) -> None:
        """Flush the active segment (journal checkpoint half)."""
        if hasattr(self._lib, "nkv_sync"):
            self._check(self._lib.nkv_sync(self._h))
            self.fsyncs += 1

    def bulk_remove_entries(self, first: bytes, last: bytes) -> None:
        self._check(
            self._lib.nkv_bulk_remove(self._h, first, len(first), last, len(last))
        )

    def compact_entries(self, first: bytes, last: bytes) -> None:
        self._check(self._lib.nkv_compact_range(self._h))

    def full_compaction(self) -> None:
        self._check(self._lib.nkv_full_compaction(self._h))

    def segment_count(self) -> int:
        return int(self._lib.nkv_segment_count(self._h))

    def close(self) -> None:
        with self._mu:
            if not self._closed:
                self._closed = True
                self._lib.nkv_close(self._h)

    def __del__(self) -> None:  # best effort
        try:
            self.close()
        except Exception:
            pass
