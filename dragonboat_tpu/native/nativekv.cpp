// nativekv: segmented-WAL key-value log engine for the TPU dragonboat port.
//
// Plays the role the reference fills with Pebble/RocksDB behind
// internal/logdb/kv/kv.go:28 (IKVStore): atomic WriteBatch commits,
// range-delete (BulkRemoveEntries), manual compaction, crash recovery.
// The workload is a Raft LogDB: small fixed-size keys, write-mostly,
// sequential appends, periodic range-deletes of compacted log entries —
// so the design is a log-structured store (Bitcask-with-ordered-index):
//
//   * all writes append framed, crc32-guarded batch records to the active
//     segment file (seg-%08u.nkv); one optional fdatasync per commit
//   * an in-memory ordered index (std::map) maps key -> value location
//     (segment id, offset, length); reads pread() from the segment
//   * delete/delete-range are logged as tombstone ops in the same records
//   * per-segment dead-byte accounting drives GC: segments whose live
//     fraction drops below a threshold are rewritten into the active
//     segment and unlinked (CompactEntries/FullCompaction)
//   * recovery replays segments in id order; a torn tail in the newest
//     segment is truncated, torn records elsewhere abort the open
//
// Exposed as a flat C ABI (extern "C") consumed from Python over ctypes
// (dragonboat_tpu/native/__init__.py).

#include <algorithm>
#include <cerrno>
#include <cstdarg>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <set>
#include <memory>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include <dirent.h>
#include <fcntl.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <sys/uio.h>
#include <unistd.h>

namespace {

// ---------------------------------------------------------------- crc32
// Standard IEEE 802.3 crc32 (same polynomial as zlib.crc32).
uint32_t crc32_table[256];
struct Crc32Init {
  Crc32Init() {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      crc32_table[i] = c;
    }
  }
} crc32_init_;

uint32_t crc32(const uint8_t* data, size_t n, uint32_t crc = 0) {
  crc = ~crc;
  for (size_t i = 0; i < n; i++)
    crc = crc32_table[(crc ^ data[i]) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

// ------------------------------------------------------------- framing
// Record: u32 crc32(payload) | u32 payload_len | i32 nops | payload.
// Payload per op: u8 op | u32 klen | key | u32 vlen | value.
// Identical shape to the Python WalKV framing so the formats stay
// mutually intelligible for debugging (not interchanged in practice).
constexpr size_t kHdrSize = 12;
constexpr uint8_t kOpPut = 0;
constexpr uint8_t kOpDelete = 1;
constexpr uint8_t kOpDeleteRange = 2;

constexpr uint64_t kSegmentLimit = 64ull << 20;  // rotate at 64 MiB
constexpr double kGcLiveThreshold = 0.40;        // rewrite below 40% live

void put_u32(std::string& out, uint32_t v) {
  char b[4] = {char(v), char(v >> 8), char(v >> 16), char(v >> 24)};
  out.append(b, 4);
}
uint32_t get_u32(const uint8_t* p) {
  return uint32_t(p[0]) | uint32_t(p[1]) << 8 | uint32_t(p[2]) << 16 |
         uint32_t(p[3]) << 24;
}

struct Loc {
  uint32_t seg;
  uint32_t len;
  uint64_t off;
};

struct SegInfo {
  int fd = -1;
  uint64_t size = 0;       // bytes written (valid length)
  uint64_t live = 0;       // bytes of values still referenced
  uint64_t total = 0;      // bytes of values ever written
};

class NativeKV;

struct IterOut {
  std::vector<std::pair<std::string, std::string>> pairs;
  size_t pos = 0;
};

class NativeKV {
 public:
  std::string err;

  int Open(const std::string& dir, bool fsync) {
    dir_ = dir;
    fsync_ = fsync;
    if (::mkdir(dir.c_str(), 0755) != 0 && errno != EEXIST)
      return Fail("mkdir %s: %s", dir.c_str(), strerror(errno));
    std::vector<uint32_t> ids;
    DIR* d = ::opendir(dir.c_str());
    if (!d) return Fail("opendir %s: %s", dir.c_str(), strerror(errno));
    while (dirent* e = ::readdir(d)) {
      unsigned id;
      if (sscanf(e->d_name, "seg-%08u.nkv", &id) == 1) ids.push_back(id);
    }
    ::closedir(d);
    std::sort(ids.begin(), ids.end());
    for (size_t i = 0; i < ids.size(); i++) {
      if (Replay(ids[i], i + 1 == ids.size()) != 0) return -1;
    }
    active_ = ids.empty() ? 1 : ids.back();
    if (ids.empty() || segs_[active_].size >= kSegmentLimit) {
      if (!ids.empty()) active_++;
      if (OpenSegment(active_, /*create=*/true) != 0) return -1;
    }
    return 0;
  }

  ~NativeKV() {
    for (auto& [id, s] : segs_)
      if (s.fd >= 0) ::close(s.fd);
  }

  int Get(const uint8_t* k, size_t klen, std::string* out, bool* found) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = index_.find(std::string((const char*)k, klen));
    if (it == index_.end()) {
      *found = false;
      return 0;
    }
    *found = true;
    return ReadValue(it->second, out);
  }

  // batch: sequence of ops in the payload format described above.
  int Commit(const uint8_t* batch, size_t blen) {
    std::lock_guard<std::mutex> g(mu_);
    return CommitLocked(batch, blen);
  }

  // Commit with an explicit durability override: do_fsync=0 appends +
  // applies without the fdatasync — the host-plane group-commit journal
  // (logdb/journal.py) provides the durability, one fsync amortized
  // across every shard's batches per flush cycle.  Sync() is the
  // checkpoint half: flush the active segment so the journal can be
  // truncated.
  int Commit2(const uint8_t* batch, size_t blen, bool do_fsync) {
    std::lock_guard<std::mutex> g(mu_);
    bool saved = fsync_;
    fsync_ = do_fsync;
    int rc = CommitLocked(batch, blen);
    fsync_ = saved;
    return rc;
  }

  int Sync() {
    std::lock_guard<std::mutex> g(mu_);
    // every segment an unsynced commit touched since the last Sync —
    // a Commit2(do_fsync=0) burst can rotate segments, and syncing only
    // the active one would let the journal checkpoint truncate the sole
    // durable copy of the rotated-out tail
    for (uint32_t id : dirty_) {
      auto it = segs_.find(id);
      if (it == segs_.end()) continue;
      if (::fdatasync(it->second.fd) != 0)
        return Fail("fdatasync seg %u: %s", id, strerror(errno));
    }
    dirty_.clear();
    return 0;
  }

  int BulkRemove(const uint8_t* f, size_t fl, const uint8_t* l, size_t ll) {
    std::string payload;
    payload.push_back((char)kOpDeleteRange);
    put_u32(payload, fl);
    payload.append((const char*)f, fl);
    put_u32(payload, ll);
    payload.append((const char*)l, ll);
    return Commit((const uint8_t*)payload.data(), payload.size());
  }

  // GC segments whose live fraction fell below threshold.  first/last kept
  // for interface parity (the dead bytes already tell us what to do).
  int CompactRange() {
    std::lock_guard<std::mutex> g(mu_);
    std::vector<uint32_t> victims;
    for (auto& [id, s] : segs_) {
      if (id == active_) continue;
      double live = s.total ? double(s.live) / double(s.total) : 0.0;
      if (live < kGcLiveThreshold) victims.push_back(id);
    }
    for (uint32_t id : victims)
      if (Rewrite(id) != 0) return -1;
    return 0;
  }

  int FullCompaction() {
    std::lock_guard<std::mutex> g(mu_);
    std::vector<uint32_t> victims;
    for (auto& [id, s] : segs_)
      if (id != active_) victims.push_back(id);
    for (uint32_t id : victims)
      if (Rewrite(id) != 0) return -1;
    // roll the active segment too so its garbage is collectable next round
    if (segs_[active_].live < segs_[active_].total) {
      uint32_t old = active_;
      if (OpenSegment(++active_, true) != 0) return -1;
      if (Rewrite(old) != 0) return -1;
    }
    return 0;
  }

  IterOut* NewIter(const uint8_t* f, size_t fl, const uint8_t* l, size_t ll,
                   bool inc_last) {
    std::lock_guard<std::mutex> g(mu_);
    auto out = std::make_unique<IterOut>();
    std::string first((const char*)f, fl), last((const char*)l, ll);
    auto it = index_.lower_bound(first);
    for (; it != index_.end(); ++it) {
      if (it->first > last || (it->first == last && !inc_last)) break;
      std::string v;
      if (ReadValue(it->second, &v) != 0) return nullptr;
      out->pairs.emplace_back(it->first, std::move(v));
    }
    return out.release();
  }

  uint64_t SegmentCount() {
    std::lock_guard<std::mutex> g(mu_);
    return segs_.size();
  }

 private:
  int Fail(const char* fmt, ...) {
    char buf[512];
    va_list ap;
    va_start(ap, fmt);
    vsnprintf(buf, sizeof buf, fmt, ap);
    va_end(ap);
    err = buf;
    return -1;
  }

  std::string SegPath(uint32_t id) {
    char name[64];
    snprintf(name, sizeof name, "seg-%08u.nkv", id);
    return dir_ + "/" + name;
  }

  int OpenSegment(uint32_t id, bool create) {
    int flags = O_RDWR | O_APPEND | (create ? O_CREAT : 0);
    int fd = ::open(SegPath(id).c_str(), flags, 0644);
    if (fd < 0) return Fail("open seg %u: %s", id, strerror(errno));
    segs_[id].fd = fd;
    if (create && fsync_) SyncDir();
    return 0;
  }

  void SyncDir() {
    int dfd = ::open(dir_.c_str(), O_RDONLY | O_DIRECTORY);
    if (dfd >= 0) {
      ::fsync(dfd);
      ::close(dfd);
    }
  }

  int ReadValue(const Loc& loc, std::string* out) {
    out->resize(loc.len);
    if (loc.len == 0) return 0;
    int fd = segs_[loc.seg].fd;
    ssize_t n = ::pread(fd, &(*out)[0], loc.len, (off_t)loc.off);
    if (n != (ssize_t)loc.len)
      return Fail("pread seg %u off %llu: %s", loc.seg,
                  (unsigned long long)loc.off, strerror(errno));
    return 0;
  }

  int CommitLocked(const uint8_t* payload, size_t plen) {
    SegInfo& si = segs_[active_];
    if (si.size >= kSegmentLimit) {
      if (OpenSegment(++active_, true) != 0) return -1;
    }
    SegInfo& seg = segs_[active_];
    int nops = CountOps(payload, plen);
    if (nops < 0) return Fail("malformed batch payload");
    std::string hdr;
    put_u32(hdr, crc32(payload, plen));
    put_u32(hdr, (uint32_t)plen);
    put_u32(hdr, (uint32_t)nops);
    iovec iov[2] = {{(void*)hdr.data(), hdr.size()},
                    {(void*)payload, plen}};
    ssize_t want = (ssize_t)(hdr.size() + plen);
    if (::writev(seg.fd, iov, 2) != want)
      return Fail("writev: %s", strerror(errno));
    if (fsync_) {
      if (::fdatasync(seg.fd) != 0)
        return Fail("fdatasync: %s", strerror(errno));
    } else {
      dirty_.insert(active_);  // made durable by the next Sync()
    }
    uint64_t base = seg.size + kHdrSize;
    seg.size += (uint64_t)want;
    return ApplyPayloadWithOverwriteAccounting(payload, plen, active_, base);
  }

  // Like ApplyPayload but discounts overwritten values' live bytes.
  int ApplyPayloadWithOverwriteAccounting(const uint8_t* p, size_t n,
                                          uint32_t seg, uint64_t base) {
    size_t pos = 0;
    while (pos < n) {
      uint8_t op = p[pos];
      uint32_t klen = get_u32(p + pos + 1);
      pos += 5;
      std::string key((const char*)p + pos, klen);
      pos += klen;
      uint32_t vlen = get_u32(p + pos);
      pos += 4;
      if (op == kOpPut) {
        auto it = index_.find(key);
        if (it != index_.end()) segs_[it->second.seg].live -= it->second.len;
        index_[key] = Loc{seg, vlen, base + pos};
        segs_[seg].total += vlen;
        segs_[seg].live += vlen;
      } else if (op == kOpDelete) {
        auto it = index_.find(key);
        if (it != index_.end()) {
          segs_[it->second.seg].live -= it->second.len;
          index_.erase(it);
        }
      } else {  // kOpDeleteRange
        std::string last((const char*)p + pos, vlen);
        auto lo = index_.lower_bound(key);
        auto hi = index_.lower_bound(last);
        for (auto it = lo; it != hi; ++it)
          segs_[it->second.seg].live -= it->second.len;
        index_.erase(lo, hi);
      }
      pos += vlen;
    }
    return 0;
  }

  static int CountOps(const uint8_t* p, size_t n) {
    size_t pos = 0;
    int count = 0;
    while (pos < n) {
      if (pos + 5 > n) return -1;
      uint32_t klen = get_u32(p + pos + 1);
      pos += 5 + klen;
      if (pos + 4 > n) return -1;
      uint32_t vlen = get_u32(p + pos);
      pos += 4 + vlen;
      count++;
    }
    return pos == n ? count : -1;
  }

  int Replay(uint32_t id, bool is_last) {
    if (OpenSegment(id, /*create=*/false) != 0) return -1;
    SegInfo& seg = segs_[id];
    struct stat st;
    if (::fstat(seg.fd, &st) != 0) return Fail("fstat: %s", strerror(errno));
    uint64_t n = (uint64_t)st.st_size;
    std::vector<uint8_t> buf(n);
    if (n && ::pread(seg.fd, buf.data(), n, 0) != (ssize_t)n)
      return Fail("replay pread: %s", strerror(errno));
    uint64_t pos = 0, valid_to = 0;
    while (pos + kHdrSize <= n) {
      uint32_t crc = get_u32(&buf[pos]);
      uint32_t plen = get_u32(&buf[pos + 4]);
      uint64_t body = pos + kHdrSize;
      if (body + plen > n) break;
      if (crc32(&buf[body], plen) != crc) break;
      if (ApplyPayloadWithOverwriteAccounting(&buf[body], plen, id, body) != 0)
        return -1;
      pos = body + plen;
      valid_to = pos;
    }
    if (valid_to < n) {
      if (!is_last)
        return Fail("corrupt record in segment %u at %llu", id,
                    (unsigned long long)valid_to);
      if (::ftruncate(seg.fd, (off_t)valid_to) != 0)
        return Fail("ftruncate: %s", strerror(errno));
    }
    seg.size = valid_to;
    return 0;
  }

  // Move segment `id`'s live values into the active segment, then drop it.
  // Re-putting an existing key never inserts or erases map nodes, so the
  // range-for stays valid across the embedded CommitLocked calls.
  int Rewrite(uint32_t id) {
    std::string payload;
    for (auto& [k, loc] : index_) {
      if (loc.seg != id) continue;
      std::string v;
      if (ReadValue(loc, &v) != 0) return -1;
      payload.push_back((char)kOpPut);
      put_u32(payload, k.size());
      payload += k;
      put_u32(payload, v.size());
      payload += v;
      if (payload.size() >= (8u << 20)) {  // bounded batches
        if (CommitLocked((const uint8_t*)payload.data(), payload.size()) != 0)
          return -1;
        payload.clear();
      }
    }
    if (!payload.empty() &&
        CommitLocked((const uint8_t*)payload.data(), payload.size()) != 0)
      return -1;
    SegInfo& s = segs_[id];
    if (s.fd >= 0) ::close(s.fd);
    ::unlink(SegPath(id).c_str());
    segs_.erase(id);
    if (fsync_) SyncDir();
    return 0;
  }

  std::string dir_;
  bool fsync_ = true;
  std::mutex mu_;
  std::set<uint32_t> dirty_;  // segments with unsynced commits (Sync())
  std::map<std::string, Loc> index_;
  std::unordered_map<uint32_t, SegInfo> segs_;
  uint32_t active_ = 1;
};

}  // namespace

// ------------------------------------------------------------- C ABI
extern "C" {

NativeKV* nkv_open(const char* dir, int do_fsync, char* errbuf,
                   size_t errlen) {
  auto kv = std::make_unique<NativeKV>();
  if (kv->Open(dir, do_fsync != 0) != 0) {
    if (errbuf && errlen) snprintf(errbuf, errlen, "%s", kv->err.c_str());
    return nullptr;
  }
  return kv.release();
}

void nkv_close(NativeKV* kv) { delete kv; }

const char* nkv_errmsg(NativeKV* kv) { return kv->err.c_str(); }

// returns 1 found, 0 not found, -1 error; *val is malloc'd, free with
// nkv_buf_free
int nkv_get(NativeKV* kv, const uint8_t* k, size_t klen, uint8_t** val,
            size_t* vlen) {
  std::string out;
  bool found = false;
  if (kv->Get(k, klen, &out, &found) != 0) return -1;
  if (!found) return 0;
  *vlen = out.size();
  *val = (uint8_t*)malloc(out.size() ? out.size() : 1);
  memcpy(*val, out.data(), out.size());
  return 1;
}

void nkv_buf_free(uint8_t* p) { free(p); }

int nkv_commit(NativeKV* kv, const uint8_t* batch, size_t blen) {
  return kv->Commit(batch, blen);
}

int nkv_commit2(NativeKV* kv, const uint8_t* batch, size_t blen,
                int do_fsync) {
  return kv->Commit2(batch, blen, do_fsync != 0);
}

int nkv_sync(NativeKV* kv) { return kv->Sync(); }

int nkv_bulk_remove(NativeKV* kv, const uint8_t* f, size_t fl,
                    const uint8_t* l, size_t ll) {
  return kv->BulkRemove(f, fl, l, ll);
}

int nkv_compact_range(NativeKV* kv) { return kv->CompactRange(); }

int nkv_full_compaction(NativeKV* kv) { return kv->FullCompaction(); }

uint64_t nkv_segment_count(NativeKV* kv) { return kv->SegmentCount(); }

IterOut* nkv_iter_new(NativeKV* kv, const uint8_t* f, size_t fl,
                      const uint8_t* l, size_t ll, int inc_last) {
  return kv->NewIter(f, fl, l, ll, inc_last != 0);
}

// returns 1 and fills pointers while pairs remain; 0 at end.  Pointers are
// valid until the next nkv_iter_next / nkv_iter_free call.
int nkv_iter_next(IterOut* it, const uint8_t** k, size_t* klen,
                  const uint8_t** v, size_t* vlen) {
  if (!it || it->pos >= it->pairs.size()) return 0;
  auto& p = it->pairs[it->pos++];
  *k = (const uint8_t*)p.first.data();
  *klen = p.first.size();
  *v = (const uint8_t*)p.second.data();
  *vlen = p.second.size();
  return 1;
}

void nkv_iter_free(IterOut* it) { delete it; }

}  // extern "C"
