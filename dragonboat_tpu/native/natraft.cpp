// natraft: native steady-state replication core (the "fast lane").
//
// WHAT THIS IS.  The host-path profile (PERF.md) shows ~75us of serialized
// Python per write spread across propose -> step -> replicate -> WAL ->
// ack -> commit -> apply; with three NodeHost ranks on one machine that
// bounds the end-to-end rate around 10k writes/s, ~3 orders of magnitude
// off the reference's 9M/s (README Performance; SURVEY.md section 6).  The
// reference reaches its number with a compiled per-group step loop
// (internal/raft/raft.go driven by execengine.go worker goroutines); this
// file is that loop's native equivalent for the tpu build: the steady-state
// replication data plane (leader propose -> Replicate fan-out, follower
// append -> ack, ack -> quorum commit, heartbeats, WAL persistence) for
// *enrolled* groups runs entirely in C++, while Python remains the control
// plane (elections, membership, snapshots, ReadIndex, recovery) and the
// apply/notify surface.
//
// ENROLLMENT CONTRACT.  A group is enrolled from Python at a quiescent
// point (under the node's raftMu, with no pending raft Update, log fully
// persisted, commit == processed == last_index, every remote caught up).
// While enrolled, the Python raft object for the group is *frozen*: every
// fast-path message (REPLICATE / REPLICATE_RESP / HEARTBEAT /
// HEARTBEAT_RESP in the group's current term) is consumed here and MUST NOT
// reach the stale Python state machine.  Anything else -- a different
// term, a vote request, a snapshot, a rejection, flow-control trouble,
// contact loss -- flips the group to EJECTING: subsequent messages pass
// through to Python as leftovers, and the Python router completes the
// handoff (natr_eject) under raftMu before delivering them, rebuilding
// scalar raft state (log watermarks, remote progress, persisted-state
// cache) from the snapshot this core returns.  Correctness therefore never
// depends on the fast path handling every case -- only on the eject
// protocol being airtight (tests/test_fastlane*.py).
//
// PERSISTENCE.  Entries/State/MaxIndex records are written to the SAME
// native segmented-WAL KV engine (nativekv.cpp, via dlopen) with byte-
// identical key schema (logdb/keys.py: >BQQQ big-endian, tag 5 plain
// entries) and value encodings (wire/codec.py varint entries; 3x u64-LE
// State; u64-BE MaxIndex), so restart/replay and all Python-side readers
// (logreader, conformance tests, import tools) see one coherent store.
// The round thread groups every staged append across all groups of a
// shard into ONE fsynced nkv batch -- the reference's
// one-WriteBatch-per-worker-round geometry (rdb.go:187-210).
//
// ORDERING RULES (mirroring the reference's execengine pipeline):
//   - Replicate fan-out of freshly proposed entries is sent BEFORE the
//     local fsync (thesis 10.2.1; execengine.go:954-961).
//   - Follower REPLICATE_RESP and all apply hand-offs are emitted only
//     AFTER the local fsync covers them (rdb save -> processRaftUpdate).
//   - The leader's own match advances only at fsync; commit q is the
//     quorum-th largest of {self fsynced} U {peer match}, and entries are
//     handed to apply only up to min(commit, fsynced).
//   - Entries committed by counting are always in the leader's current
//     term: enrollment starts at commit == last_index, so every index a
//     tally can newly commit was appended under the enrolled term (raft
//     paper p8's guard holds structurally).
//
// Reference map: leader tally tryCommit raft.go:861-909, follower append
// handleReplicateMessage raft.go:1426-1450, resp handling raft.go:1671-1700,
// heartbeat raft.go:826+1702, transport framing tcp.go:57-114.
#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <dlfcn.h>
#include <functional>
#include <sys/prctl.h>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <sys/types.h>
#include <thread>
#include <unistd.h>
#include <unordered_map>
#include <vector>

namespace {

// ---------------------------------------------------------------- utils

static inline int64_t mono_ms() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (int64_t)ts.tv_sec * 1000 + ts.tv_nsec / 1000000;
}

// zlib-compatible CRC32 (IEEE), table-based.
struct Crc32Table {
  uint32_t t[256];
  Crc32Table() {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      t[i] = c;
    }
  }
};
static const Crc32Table kCrc;
static uint32_t crc32ieee(const uint8_t* p, size_t n, uint32_t crc = 0) {
  crc = ~crc;
  for (size_t i = 0; i < n; i++) crc = kCrc.t[(crc ^ p[i]) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

static void put_uvarint(std::string& b, uint64_t v) {
  while (v >= 0x80) {
    b.push_back((char)(v | 0x80));
    v >>= 7;
  }
  b.push_back((char)v);
}

// Matches wire/codec.py `_read_uvarint` limits (max 10 bytes, uint64).
static bool get_uvarint(const uint8_t* d, size_t len, size_t& pos, uint64_t& out) {
  uint64_t r = 0;
  int shift = 0;
  while (true) {
    if (pos >= len) return false;
    uint8_t b = d[pos++];
    if (shift == 63 && (b & 0x7F) > 1) return false;
    r |= (uint64_t)(b & 0x7F) << shift;
    if (!(b & 0x80)) {
      out = r;
      return true;
    }
    shift += 7;
    if (shift > 63) return false;
  }
}

static void put_u64be(std::string& b, uint64_t v) {
  for (int i = 7; i >= 0; i--) b.push_back((char)((v >> (8 * i)) & 0xFF));
}
static void put_u64le(std::string& b, uint64_t v) {
  for (int i = 0; i < 8; i++) b.push_back((char)((v >> (8 * i)) & 0xFF));
}
static void put_u32le(std::string& b, uint32_t v) {
  for (int i = 0; i < 4; i++) b.push_back((char)((v >> (8 * i)) & 0xFF));
}

// message types (wire/types.py, values == raftpb/raft.proto:26-53)
enum MsgType : uint64_t {
  MT_REPLICATE = 12,
  MT_REPLICATE_RESP = 13,
  MT_HEARTBEAT = 17,
  MT_HEARTBEAT_RESP = 18,
  MT_READ_INDEX = 19,       // follower-forwarded ReadIndex (raft.go:1258)
  MT_READ_INDEX_RESP = 20,  // leader's confirmation back to the origin
};
constexpr uint8_t kFlagSnapshot = 1;
constexpr uint8_t kFlagReject = 2;
// replication-trace trailer (wire/codec.py _MSG_HAS_TRACE, ISSUE 14):
// the C readers never stamp or consume it, but a python peer without a
// fast lane may attach it to a sampled REPLICATE — the parser must skip
// the trailer (and keep it inside the forwarded span) or the next
// message header in the batch desyncs.
constexpr uint8_t kFlagReplTrace = 4;

// logdb key schema (logdb/keys.py)
enum KeyTag : uint8_t { TAG_STATE = 0x02, TAG_MAX_INDEX = 0x03, TAG_ENTRY = 0x05 };
static std::string make_key(uint8_t tag, uint64_t cid, uint64_t nid, uint64_t idx) {
  std::string k;
  k.reserve(25);
  k.push_back((char)tag);
  put_u64be(k, cid);
  put_u64be(k, nid);
  put_u64be(k, idx);
  return k;
}

// nativekv write-batch op encoding (native/__init__.py _encode_batch)
static void batch_put(std::string& b, const std::string& k, const std::string& v) {
  b.push_back((char)0);  // _PUT
  put_u32le(b, (uint32_t)k.size());
  b += k;
  put_u32le(b, (uint32_t)v.size());
  b += v;
}

// ------------------------------------------------------------ wire model

struct NEntry {
  uint64_t term = 0, index = 0;
  int64_t born_us = 0;  // propose/append time (latency diagnostics)
  std::string enc;  // canonical wire encoding (codec.encode_entry)
};

// Witness replication twin (make_metadata_entries raft.py:104, reference
// raft.go:744-758): every entry becomes a METADATA-only encoding (same
// term/index, no payload) EXCEPT CONFIG_CHANGE, which passes verbatim —
// the enrollment tail can hold already-committed config entries.
static void append_witness_entry(std::string& b, const NEntry& en) {
  const uint8_t* d = (const uint8_t*)en.enc.data();
  size_t len = en.enc.size(), pos = 0;
  uint64_t term, index, etype;
  if (get_uvarint(d, len, pos, term) && get_uvarint(d, len, pos, index) &&
      get_uvarint(d, len, pos, etype) && etype == 1 /*CONFIG_CHANGE*/) {
    b += en.enc;
    return;
  }
  put_uvarint(b, en.term);
  put_uvarint(b, en.index);
  put_uvarint(b, 3);  // EntryType.METADATA
  for (int i = 0; i < 5; i++) put_uvarint(b, 0);  // key/cid/sid/resp/len
}

static inline int64_t mono_us() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (int64_t)ts.tv_sec * 1000000 + ts.tv_nsec / 1000;
}

// Build the canonical Entry encoding (wire/codec.py encode_entry_into).
static std::string encode_entry(uint64_t term, uint64_t index, uint64_t etype,
                                uint64_t key, uint64_t client_id,
                                uint64_t series_id, uint64_t responded_to,
                                const uint8_t* cmd, size_t cmdlen) {
  std::string b;
  b.reserve(cmdlen + 24);
  put_uvarint(b, term);
  put_uvarint(b, index);
  put_uvarint(b, etype);
  put_uvarint(b, key);
  put_uvarint(b, client_id);
  put_uvarint(b, series_id);
  put_uvarint(b, responded_to);
  put_uvarint(b, cmdlen);
  b.append((const char*)cmd, cmdlen);
  return b;
}

// Parse an Entry, returning its term/index and raw span.
static bool parse_entry(const uint8_t* d, size_t len, size_t& pos,
                        uint64_t& term, uint64_t& index) {
  uint64_t etype, key, cid, sid, resp, clen;
  if (!get_uvarint(d, len, pos, term)) return false;
  if (!get_uvarint(d, len, pos, index)) return false;
  if (!get_uvarint(d, len, pos, etype)) return false;
  if (!get_uvarint(d, len, pos, key)) return false;
  if (!get_uvarint(d, len, pos, cid)) return false;
  if (!get_uvarint(d, len, pos, sid)) return false;
  if (!get_uvarint(d, len, pos, resp)) return false;
  if (!get_uvarint(d, len, pos, clen)) return false;
  // Overflow-safe: a crafted ~2^64 clen would wrap `pos + clen` backwards
  // past a naive `pos + clen > len` check (pos <= len holds after
  // get_uvarint, so len - pos cannot underflow).
  if (clen > len - pos) return false;
  pos += clen;
  return true;
}

static bool skip_str(const uint8_t* d, size_t len, size_t& pos) {
  uint64_t n;
  if (!get_uvarint(d, len, pos, n)) return false;
  if (n > len - pos) return false;  // overflow-safe (see parse_entry)
  pos += n;
  return true;
}
static bool skip_addr_map(const uint8_t* d, size_t len, size_t& pos) {
  uint64_t n;
  if (!get_uvarint(d, len, pos, n)) return false;
  for (uint64_t i = 0; i < n; i++) {
    uint64_t k;
    if (!get_uvarint(d, len, pos, k)) return false;
    if (!skip_str(d, len, pos)) return false;
  }
  return true;
}
static bool skip_membership(const uint8_t* d, size_t len, size_t& pos) {
  uint64_t ccid, nrem;
  if (!get_uvarint(d, len, pos, ccid)) return false;
  if (!skip_addr_map(d, len, pos)) return false;
  if (!get_uvarint(d, len, pos, nrem)) return false;
  for (uint64_t i = 0; i < nrem; i++) {
    uint64_t k;
    if (!get_uvarint(d, len, pos, k)) return false;
  }
  if (!skip_addr_map(d, len, pos)) return false;
  if (!skip_addr_map(d, len, pos)) return false;
  return true;
}
static bool skip_snapshot_file(const uint8_t* d, size_t len, size_t& pos) {
  uint64_t v;
  if (!skip_str(d, len, pos)) return false;           // filepath
  if (!get_uvarint(d, len, pos, v)) return false;     // file_size
  if (!get_uvarint(d, len, pos, v)) return false;     // file_id
  if (!skip_str(d, len, pos)) return false;           // metadata (bytes)
  return true;
}
// Skip a Snapshot (wire/codec.py decode_snapshot_from) -- needed only to
// find message span boundaries; snapshot messages always go to Python.
static bool skip_snapshot(const uint8_t* d, size_t len, size_t& pos) {
  uint64_t v, nfiles;
  if (!skip_str(d, len, pos)) return false;        // filepath
  if (!get_uvarint(d, len, pos, v)) return false;  // file_size
  if (!get_uvarint(d, len, pos, v)) return false;  // index
  if (!get_uvarint(d, len, pos, v)) return false;  // term
  if (!skip_membership(d, len, pos)) return false;
  if (!get_uvarint(d, len, pos, nfiles)) return false;
  for (uint64_t i = 0; i < nfiles; i++)
    if (!skip_snapshot_file(d, len, pos)) return false;
  if (!skip_str(d, len, pos)) return false;  // checksum
  if (pos >= len) return false;
  pos += 1;  // flags
  if (!get_uvarint(d, len, pos, v)) return false;  // cluster_id
  if (!get_uvarint(d, len, pos, v)) return false;  // type
  if (!get_uvarint(d, len, pos, v)) return false;  // on_disk_index
  return true;
}

struct ParsedMsg {
  uint64_t type, to, from, cluster_id, term, log_term, log_index, commit, hint,
      hint_high, nentries;
  uint8_t flags;
  size_t span_start, span_end;      // raw bytes of the whole message
  size_t entries_start;             // offset of first entry
};

static bool parse_message(const uint8_t* d, size_t len, size_t& pos, ParsedMsg& m) {
  m.span_start = pos;
  if (!get_uvarint(d, len, pos, m.type)) return false;
  if (pos >= len) return false;
  m.flags = d[pos++];
  if (!get_uvarint(d, len, pos, m.to)) return false;
  if (!get_uvarint(d, len, pos, m.from)) return false;
  if (!get_uvarint(d, len, pos, m.cluster_id)) return false;
  if (!get_uvarint(d, len, pos, m.term)) return false;
  if (!get_uvarint(d, len, pos, m.log_term)) return false;
  if (!get_uvarint(d, len, pos, m.log_index)) return false;
  if (!get_uvarint(d, len, pos, m.commit)) return false;
  if (!get_uvarint(d, len, pos, m.hint)) return false;
  if (!get_uvarint(d, len, pos, m.hint_high)) return false;
  if (!get_uvarint(d, len, pos, m.nentries)) return false;
  m.entries_start = pos;
  for (uint64_t i = 0; i < m.nentries; i++) {
    uint64_t t, ix;
    if (!parse_entry(d, len, pos, t, ix)) return false;
  }
  if (m.flags & kFlagSnapshot) {
    if (!skip_snapshot(d, len, pos)) return false;
  }
  if (m.flags & kFlagReplTrace) {
    uint64_t v;
    if (!get_uvarint(d, len, pos, v)) return false;  // tid
    if (!skip_str(d, len, pos)) return false;        // origin
    if (!get_uvarint(d, len, pos, v)) return false;  // index
    if (pos + 48 > len) return false;  // 6 x f64 wall-clock stamps
    pos += 48;
  }
  m.span_end = pos;
  return true;
}

// Encode a fast-path message header (wire/codec.py encode_message_into).
static void put_msg_header(std::string& b, uint64_t type, uint8_t flags,
                           uint64_t to, uint64_t from, uint64_t cid,
                           uint64_t term, uint64_t log_term, uint64_t log_index,
                           uint64_t commit, uint64_t hint, uint64_t hint_high,
                           uint64_t nentries) {
  put_uvarint(b, type);
  b.push_back((char)flags);
  put_uvarint(b, to);
  put_uvarint(b, from);
  put_uvarint(b, cid);
  put_uvarint(b, term);
  put_uvarint(b, log_term);
  put_uvarint(b, log_index);
  put_uvarint(b, commit);
  put_uvarint(b, hint);
  put_uvarint(b, hint_high);
  put_uvarint(b, nentries);
}

// ---------------------------------------------------------------- engine

typedef int (*nkv_commit_fn)(void*, const uint8_t*, size_t);

struct Group;

// One WAL shard with its own committer thread: the staging pass appends
// records and queues per-group post-fsync work; the committer swaps the
// whole accumulation out, issues ONE fsynced nkv batch covering it
// (classic group commit — the deeper the pipeline backs up, the bigger
// the batch), then runs the deferred effects.  Staging never blocks on a
// disk flush, and shards flush in parallel — the reference's
// one-WriteBatch-per-worker-round geometry (sharded_rdb.go:156-163).
struct Shard {
  void* handle = nullptr;
  std::mutex mu;
  std::condition_variable cv;
  std::string batch;
  // (group, staged_until): entries <= staged_until are covered by the
  // next commit of `batch`
  std::vector<std::pair<std::shared_ptr<Group>, uint64_t>> post;
  std::thread thread;
  int64_t last_fsync_end_us = 0;
};

// Outbound plane: one buffer of ready-to-send transport frames per remote
// address slot, drained by a native sender thread over its own TCP
// connection (connect/reconnect handled here; the GIL never touches the
// outbound fast plane).  A Python pump via natr_take_send remains as the
// fallback when no native connection is attached (tests).
struct Remote {
  std::mutex mu;
  // serializes whole flush passes (swap -> frame-build -> buf append):
  // flush_remotes runs concurrently on the round thread and the shard
  // committers, and the swap and append are separate mu sections — without
  // this, a later-queued REPLICATE could be appended before an earlier one
  // on the single ordered stream, forcing gap punts + step-path ejects on
  // followers
  std::mutex flush_mu;
  std::condition_variable cv;
  std::string buf;          // complete frames
  std::string msgs;         // current pass's message spans (under r->mu)
  uint64_t msg_count = 0;   // messages in `msgs`
  bool closed = false;
  uint64_t dropped = 0;
  // native sender (natr_remote_connect)
  std::string host;
  int port = 0;
  std::thread sender;
  int fd = -1;
};

struct ApplySpan {
  uint64_t cid = 0, first = 0, last = 0;
  std::string blob;  // varint(count) + entry encodings (decode_entry_batch)
};

enum GroupState { G_ACTIVE = 0, G_EJECTING = 1, G_GONE = 2 };
enum EventCode {
  EV_CONTACT_LOST = 1,   // follower: no leader contact within timeout
  EV_QUORUM_LOST = 2,    // leader: check-quorum window expired
  EV_PROTOCOL = 3,       // conflicting/unsupported message needs Python
  EV_WAL_ERROR = 4,
  // protocol sub-causes (diagnostics; all handled as EV_PROTOCOL)
  EV_TERM_MISMATCH = 5,
  EV_WRONG_ROLE = 6,
  EV_GAP = 7,   // historical (gaps now punt to the router re-ingest path)
  EV_PREV_TERM = 8,
  EV_REJECT_RESP = 9,
  EV_UNKNOWN_PEER = 10,
  EV_RESEND_PREENROLL = 11,
  EV_PARSE = 12,
  EV_COMMIT_STALL = 13,  // liveness watchdog: pending entries, no progress
  EV_SM = 14,  // native SM cannot apply (session-managed / non-app entry)
};

struct PeerP {
  uint64_t id = 0;
  int slot = -1;
  uint64_t match = 0, next = 0;
  int64_t contact_ms = 0;
  int64_t progress_ms = 0;  // last match advance / resend reset
  int64_t hb_sent_us = 0;   // outstanding heartbeat send time (RTT diag)
  // observers (reference nonVoting members) replicate and heartbeat like
  // voters but count toward NO quorum: commit tally, check-quorum and
  // ReadIndex confirmation all skip them
  bool voting = true;
  // witnesses vote and ack like voters but replicate METADATA-ONLY
  // entries (reference raft.go:744-758): same term/index, no payload
  bool witness = false;
};

struct PendResp {
  int slot;
  uint64_t to, type, log_index, hint, hint_high;
  uint8_t flags;
};

struct Group {
  std::mutex mu;
  std::weak_ptr<Group> self;  // set at enroll; lets mark_dirty avoid gmu
  uint64_t cid = 0, nid = 0, term = 0, vote = 0, leader_id = 0;
  bool leader = false;
  uint32_t shard = 0;
  int state = G_ACTIVE;
  // log
  uint64_t log_first = 0;            // index of log.front()
  uint64_t enroll_last = 0, enroll_last_term = 0;
  uint64_t last_index = 0;
  uint64_t staged_to = 0;            // appended into the shard batch
  uint64_t fsynced = 0;              // durable locally
  uint64_t commit = 0;
  uint64_t applied_handed = 0;       // handed to the apply pump / applied natively
  uint64_t commit_sent = 0;          // commit watermark last broadcast
  // native C-ABI state machine (natsm.cpp): when attached, committed
  // noop-session application entries are applied HERE (no Python apply
  // hop) and only batched completion records cross the GIL boundary
  void* sm = nullptr;
  uint64_t (*sm_update)(void*, const uint8_t*, size_t) = nullptr;
  // native exactly-once session store (natsm.cpp SessStore): when
  // attached, session-managed entries apply natively too — register/
  // unregister, dedup against the per-series response history, and the
  // responded_to watermark all mirror StateMachineManager's handling
  // (_handle_session_entry); without it they eject (EV_SM)
  void* sess = nullptr;
  int (*sess_apply)(void*, void*, uint64_t, uint64_t, uint64_t,
                    const uint8_t*, size_t, uint64_t*, uint8_t**,
                    size_t*) = nullptr;
  // consistent-image serializers (natsm_save / natsm_sess_save): let
  // natr_capture_sm snapshot the attached SM at an exact applied index,
  // so periodic snapshots no longer eject the group
  long long (*sm_save)(void*, uint8_t**) = nullptr;
  long long (*sess_save)(void*, uint8_t**) = nullptr;
  // capture in progress: applies DEFER (emit_apply no-ops) while the
  // image serializes OFF g->mu — replication/heartbeats/acks continue,
  // mirroring the reference's regular-SM semantics where a save blocks
  // only the update lock, never the raft plane.  natr_eject waits on
  // capture_cv so a racing eject cannot hand pending applies to the
  // Python plane mid-serialization (which would tear the image).
  bool capturing = false;
  std::condition_variable capture_cv;
  // order barrier vs the scalar plane: entries <= apply_barrier were
  // handed to the PYTHON apply queue before enrollment; native applies
  // hold off until Python reports them applied (py_applied)
  uint64_t apply_barrier = 0;
  uint64_t py_applied = 0;
  std::deque<NEntry> log;
  std::vector<PeerP> peers;
  std::vector<PendResp> resps;       // post-fsync responses (follower)
  // leader-side ReadIndex (thesis 6.4): pending contexts awaiting a
  // heartbeat-echo quorum; the follower side is a pure hint echo
  struct PendRead {
    uint64_t low, high, index;
    uint32_t acks;      // self counts as one
    uint32_t peer_mask; // peers already counted
    uint64_t origin = 0;  // requesting node for forwarded reads (0 = local)
  };
  std::vector<PendRead> reads;
  // raft.go:1079: a leader may serve ReadIndex only once an entry of its
  // own term is committed; enrollment seeds this from the scalar state
  // and any native commit advance (always current-term) sets it
  bool term_commit_ok = false;
  // persisted-record suppression (plays rdbcache's role for this group)
  uint64_t st_written_term = 0, st_written_vote = 0, st_written_commit = 0;
  uint64_t maxindex_written = 0;
  bool dirty = false;
  // clocks
  int64_t hb_period_ms = 100, elect_timeout_ms = 1000;
  int64_t last_commit_adv_ms = 0;    // liveness watchdog clock
  int64_t last_hb_ms = 0;            // leader: last heartbeat broadcast
  int64_t leader_contact_ms = 0;     // follower: last leader contact
  int64_t quorum_ok_ms = 0;          // leader: last time a quorum was in contact
  uint32_t nvoting = 0;              // voting PEERS (excludes self)

  uint64_t term_of(uint64_t index) const {
    // only called for index >= enroll_last (enrollment guarantees older
    // indexes are committed and consistent)
    if (index == enroll_last) return enroll_last_term;
    if (index >= log_first && index < log_first + log.size())
      return log[index - log_first].term;
    return 0;  // unknown
  }
};

constexpr int kMaxRemotes = 64;

struct Engine {
  std::string source_address;
  uint64_t deployment_id = 0, bin_ver = 1;
  nkv_commit_fn nkv_commit = nullptr;
  void* nkv_dl = nullptr;
  std::vector<std::unique_ptr<Shard>> shards;
  // preallocated so ingest/round threads can index without locking the
  // container while natr_add_remote runs
  std::vector<std::unique_ptr<Remote>> remotes;
  std::atomic<int> nremotes{0};

  std::mutex gmu;  // group registry
  // shared_ptr: the round thread and eject may hold a group concurrently;
  // erasing the map entry must not free state under another thread
  std::unordered_map<uint64_t, std::shared_ptr<Group>> groups;

  // work signalling
  std::mutex wmu;
  std::condition_variable wcv;
  std::vector<std::shared_ptr<Group>> dirtyq;

  // apply plane
  std::mutex amu;
  std::condition_variable acv;
  std::deque<ApplySpan> applyq;

  // eject events
  std::mutex emu;
  std::condition_variable ecv;
  std::deque<std::pair<uint64_t, int>> eventq;

  // native-SM apply completions: one record per natively applied LEADER
  // entry (key!=0 completes the proposal future) plus per-span follower
  // watermark records (key==0); drained in batches by the Python pump
  struct Completion {
    uint64_t cid, index, term, key, result;
    // session identity for pending-proposal matching (requests.py
    // applied() validates client_id/series_id); 0/0 for noop entries
    uint64_t client_id, series_id;
    // payload side-channel id (0 = none): cached session responses that
    // carry data bytes park them in paymap; the Python pump fetches by
    // id (natr_take_payload) and completes the future with Result.data
    uint64_t payload_id = 0;
    uint8_t leader;
    // 0 completed, 1 rejected (no session / unregister miss), 2 ignored
    // (client already responded — the future is NOT completed)
    uint8_t status;
  };
  std::mutex cmu;
  std::condition_variable ccv;
  std::deque<Completion> complq;
  std::unordered_map<uint64_t, std::string> paymap;  // under cmu
  uint64_t next_payload_id = 1;

  // confirmed ReadIndex contexts: (cid, low, high, commit_index)
  std::mutex rmu;
  std::condition_variable rcv;
  struct ReadReady {
    uint64_t cid, low, high, index;
  };
  std::deque<ReadReady> readyq;

  // native connection readers (natr_serve_fd) + leftover frames for the
  // Python pump
  struct Reader {
    int fd = -1;
    bool closed = false;
    std::thread th;
  };
  std::mutex readers_mu;
  bool readers_stopping = false;
  std::vector<std::shared_ptr<Reader>> readers;
  std::mutex lmu;
  std::condition_variable lcv;
  struct Leftover {
    uint16_t method;
    uint64_t conn_id;  // Reader identity, for natr_close_conn
    std::string payload;
  };
  std::deque<Leftover> leftq;

  std::atomic<bool> stopped{false};
  std::thread round_thread;
  std::thread clock_thread;
  int64_t round_interval_ms = 1;
  std::atomic<int64_t> commit_window_us{0};

  // stats
  std::atomic<uint64_t> proposed{0}, ingested_fast{0}, ingested_slow{0},
      commits_advanced{0}, rounds{0}, fsyncs{0};
  std::atomic<uint64_t> fsync_ns{0}, round_ns{0}, entries_staged{0};
  // latency diagnostics (us sums + counts): born->staged, born->fsynced,
  // born->apply-emitted
  std::atomic<uint64_t> lat_stage_us{0}, lat_fsync_us{0}, lat_emit_us{0},
      lat_count{0};
  std::atomic<uint64_t> lat_emitf_us{0}, lat_countf{0}, buf_hiwater{0};
  std::atomic<uint64_t> lat_ack_us{0}, lat_ackn{0};  // leader: born->ack covering entry
  std::atomic<uint64_t> lat_resp_us{0}, lat_respn{0};  // follower: born->resp flushed
  std::atomic<uint64_t> rtt_us{0}, rttn{0}, rtt_max_us{0};  // hb echo round trip
  std::atomic<uint64_t> stale_dropped{0};  // stale-term fast frames consumed
  // scheduling-stall compensation diagnostics (clock_pass).
  // RESIDUAL LIMITATION of the stall compensation: the pass-gap check
  // only detects the CLOCK thread's own starvation.  The complementary
  // failure — reader threads starved while the clock thread ran on
  // schedule — is covered by last_ingest_ms below, but only engine-wide:
  // ingest progress on ANY connection re-arms contact-loss ejects, so
  // one starved reader among otherwise-busy connections can still
  // mis-eject its groups; and in a fully idle deployment (no inbound
  // bytes at all) the stamp stays old and a genuine dead-leader eject is
  // deferred to the 2x cap in clock_pass.  Per-connection stamps would
  // close both gaps at the cost of a remote->groups reverse map on the
  // hot ingest path; not paid until observed in practice.
  std::atomic<uint64_t> clock_stalls{0}, clock_stall_ms{0};
  // last wall time any ingest path (native connection readers,
  // stream/batch ingest from the transport recv thread) finished
  // processing inbound bytes; 0 until the first ingest
  std::atomic<int64_t> last_ingest_ms{0};
  // contact-loss ejects deferred because the ingest plane itself showed
  // no progress over the silence window (see clock_pass)
  std::atomic<uint64_t> contact_ejects_deferred{0};
  // partition injection (natr_set_partition): blocked inbound source
  // addresses + outbound remote-slot bitmask, with drop counters
  std::mutex block_mu;
  std::vector<std::string> blocked_in;
  std::atomic<uint64_t> blocked_in_n{0};  // lock-free emptiness guard
  std::atomic<uint64_t> blocked_out{0};   // bit per remote slot
  std::atomic<uint64_t> part_in_dropped{0}, part_out_dropped{0};
  // single-group debug timeline (natr_debug)
  std::atomic<uint64_t> debug_cid{0};
  std::mutex dbg_mu;
  std::string dbg;
  void dbg_ev(Group* g, const char* ev, uint64_t a, uint64_t b) {
    if (g->cid != debug_cid.load()) return;
    std::lock_guard<std::mutex> lk(dbg_mu);
    if (dbg.size() > (1u << 20)) return;
    char line[160];
    snprintf(line, sizeof(line), "%lld %s a=%llu b=%llu last=%llu fs=%llu c=%llu ah=%llu\n",
             (long long)mono_us(), ev, (unsigned long long)a,
             (unsigned long long)b, (unsigned long long)g->last_index,
             (unsigned long long)g->fsynced, (unsigned long long)g->commit,
             (unsigned long long)g->applied_handed);
    dbg += line;
  }

  Engine() {
    remotes.reserve(kMaxRemotes);
    for (int i = 0; i < kMaxRemotes; i++) remotes.emplace_back(new Remote());
  }

  ~Engine() { stop(); }

  void stop() {
    bool was = stopped.exchange(true);
    if (was) return;
    wcv.notify_all();
    acv.notify_all();
    ecv.notify_all();
    ccv.notify_all();
    for (auto& r : remotes) {
      {
        std::lock_guard<std::mutex> g(r->mu);
        r->closed = true;
        if (r->fd >= 0) shutdown(r->fd, SHUT_RDWR);
        r->cv.notify_all();
      }
      if (r->sender.joinable()) r->sender.join();
    }
    for (auto& sh : shards) {
      sh->cv.notify_all();
      if (sh->thread.joinable()) sh->thread.join();
    }
    if (round_thread.joinable()) round_thread.join();
    if (clock_thread.joinable()) clock_thread.join();
    // wake the readers (shutdown their sockets), then join them outside
    // the mutex (their exit path takes readers_mu briefly)
    std::vector<std::shared_ptr<Reader>> rds;
    {
      std::lock_guard<std::mutex> lk(readers_mu);
      readers_stopping = true;
      for (auto& rd : readers) {
        if (!rd->closed) shutdown(rd->fd, SHUT_RDWR);
      }
      rds = readers;
    }
    for (auto& rd : rds)
      if (rd->th.joinable()) rd->th.join();
    lcv.notify_all();
    rcv.notify_all();
  }

  std::shared_ptr<Group> find(uint64_t cid) {
    std::lock_guard<std::mutex> g(gmu);
    auto it = groups.find(cid);
    return it == groups.end() ? nullptr : it->second;
  }

  void mark_dirty(Group* g) {  // callers hold g->mu; must NOT take gmu
    if (g->dirty) return;
    g->dirty = true;
    std::shared_ptr<Group> sp = g->self.lock();
    if (!sp) return;
    std::lock_guard<std::mutex> lk(wmu);
    dirtyq.push_back(std::move(sp));
    wcv.notify_one();
  }

  void push_event(uint64_t cid, int code) {
    std::lock_guard<std::mutex> lk(emu);
    eventq.emplace_back(cid, code);
    ecv.notify_one();
  }

  // callers hold g->mu
  void begin_eject(Group* g, int code) {
    if (g->state != G_ACTIVE) return;
    g->state = G_EJECTING;
    push_event(g->cid, code);
  }

  // Append a message span to a remote's current-pass buffer.  Callers:
  // round thread (replication), clock thread (heartbeats/timeouts) and
  // ingest threads (direct responses) — safe because r->mu guards msgs.
  void queue_msg(int slot, const std::string& span) {
    if (slot < 0 || slot >= nremotes.load()) return;
    Remote* r = remotes[slot].get();
    std::lock_guard<std::mutex> lk(r->mu);
    r->msgs += span;
    r->msg_count++;
  }

  // Wrap each remote's accumulated messages into one transport frame and
  // publish it to the pump (tcp.py frame layout: >HHQII + payload).
  void flush_remotes() {
    int n = nremotes.load();
    uint64_t blocked = blocked_out.load(std::memory_order_relaxed);
    for (int ri = 0; ri < n; ri++) {
      Remote* r = remotes[ri].get();
      std::lock_guard<std::mutex> flk(r->flush_mu);
      std::string msgs;
      uint64_t count;
      {
        std::lock_guard<std::mutex> lk(r->mu);
        if (!r->msg_count) continue;
        msgs.swap(r->msgs);
        count = r->msg_count;
        r->msg_count = 0;
      }
      if (ri < 64 && (blocked >> ri) & 1) {
        // partitioned remote: the pass's messages vanish on the floor
        part_out_dropped += count;
        continue;
      }
      std::string payload;
      payload.reserve(msgs.size() + source_address.size() + 24);
      put_uvarint(payload, deployment_id);
      put_uvarint(payload, source_address.size());
      payload += source_address;
      put_uvarint(payload, bin_ver);
      put_uvarint(payload, count);
      payload += msgs;
      std::string frame;
      frame.reserve(payload.size() + 20);
      // >HHQI magic method size payload_crc, then header crc, big-endian
      frame.push_back((char)0xAE);
      frame.push_back((char)0x7D);
      frame.push_back((char)0x00);
      frame.push_back((char)0x64);  // RAFT_METHOD 100
      for (int i = 7; i >= 0; i--)
        frame.push_back((char)((payload.size() >> (8 * i)) & 0xFF));
      uint32_t pcrc = crc32ieee((const uint8_t*)payload.data(), payload.size());
      for (int i = 3; i >= 0; i--) frame.push_back((char)((pcrc >> (8 * i)) & 0xFF));
      uint32_t hcrc = crc32ieee((const uint8_t*)frame.data(), frame.size());
      for (int i = 3; i >= 0; i--) frame.push_back((char)((hcrc >> (8 * i)) & 0xFF));
      frame += payload;
      {
        std::lock_guard<std::mutex> lk(r->mu);
        if (r->buf.size() > (64u << 20)) {
          // pump stalled / peer dead: drop like the reference's full
          // sendQueue (transport.go Send -> false); raft retries cover it
          r->dropped++;
        } else {
          r->buf += frame;
          uint64_t sz = r->buf.size();
          uint64_t hw = buf_hiwater.load();
          while (sz > hw && !buf_hiwater.compare_exchange_weak(hw, sz)) {}
          r->cv.notify_one();
        }
      }
    }
  }

  // quorum-th largest of {self fsynced} U {peer match} (tryCommit,
  // raft.go:888-909; same reduction ops/kernels.py commit_quorum runs
  // on-device for the batched engine)
  uint64_t tally(Group* g) {
    uint64_t m[17];
    size_t n = 0;
    m[n++] = g->fsynced;
    for (auto& p : g->peers)
      if (p.voting) m[n++] = p.match;  // observers carry no quorum weight
    std::sort(m, m + n);
    size_t quorum = n / 2 + 1;
    return m[n - quorum];
  }

  void emit_apply(Group* g) {  // g->mu held
    if (g->capturing) return;  // applies defer until the capture clears
    uint64_t upto = std::min(g->commit, g->fsynced);
    if (upto <= g->applied_handed) return;
    if (g->sm != nullptr && g->state == G_ACTIVE) {
      // entries handed to the PYTHON apply queue before enrollment must
      // land in the shared SM first (natr_note_applied lifts the barrier)
      if (g->py_applied >= g->apply_barrier) apply_native(g, upto);
      return;
    }
    uint64_t first = g->applied_handed + 1;
    if (first < g->log_first) return;  // should not happen
    ApplySpan span;
    span.cid = g->cid;
    span.first = first;
    span.last = upto;
    put_uvarint(span.blob, upto - first + 1);
    int64_t now = mono_us();
    for (uint64_t i = first; i <= upto; i++) {
      NEntry& e2 = g->log[i - g->log_first];
      span.blob += e2.enc;
      if (g->leader) {
        lat_emit_us += now - e2.born_us;
        lat_count++;
      } else {
        lat_emitf_us += now - e2.born_us;
        lat_countf++;
      }
    }
    g->applied_handed = upto;
    {
      std::lock_guard<std::mutex> lk(amu);
      applyq.push_back(std::move(span));
      acv.notify_one();
    }
  }

  // Apply committed entries straight into the attached native SM (the
  // whole point: no GIL on the apply path).  Session-managed or non-
  // application entries punt to the scalar plane via eject — exactly-once
  // dedup and config semantics live in the Python RSM.  g->mu held.
  void apply_native(Group* g, uint64_t upto) {
    uint64_t first = g->applied_handed + 1;
    if (first < g->log_first) return;  // should not happen
    int64_t now = mono_us();
    std::vector<Completion> batch;
    batch.reserve(upto - first + 1);
    for (uint64_t i = first; i <= upto; i++) {
      NEntry& e2 = g->log[i - g->log_first];
      const uint8_t* d = (const uint8_t*)e2.enc.data();
      size_t len = e2.enc.size(), pos = 0;
      uint64_t term, index, etype, key, cid_, sid, resp, clen;
      bool ok = get_uvarint(d, len, pos, term) &&
                get_uvarint(d, len, pos, index) &&
                get_uvarint(d, len, pos, etype) &&
                get_uvarint(d, len, pos, key) &&
                get_uvarint(d, len, pos, cid_) &&
                get_uvarint(d, len, pos, sid) &&
                get_uvarint(d, len, pos, resp) &&
                get_uvarint(d, len, pos, clen) && clen <= len - pos;
      // applicable natively: APPLICATION (0) raw cmd, or ENCODED (2) with
      // the v0 uncompressed header (rsm/encoded.py: |ver4|compress3|ses1|
      // then raw payload) — snappy-compressed payloads and everything
      // session-managed punt to the Python RSM
      const uint8_t* payload = d + pos;
      size_t plen = clen;
      if (ok && etype == 2 && clen >= 1 && payload[0] == 0) {
        payload += 1;  // strip the v0 no-compression no-session header
        plen -= 1;
      } else if (!ok || etype != 0) {
        begin_eject(g, EV_SM);
        break;
      }
      uint64_t result = 0;
      uint64_t payload_id = 0;
      uint8_t status = 0;
      if (cid_ != 0) {
        // session-managed: exactly-once dedup through the shared native
        // session store (twin: _handle_session_entry) — register (sid 0),
        // unregister (sid ~0), duplicate suppression, responded_to GC
        if (g->sess == nullptr || g->sess_apply == nullptr) {
          begin_eject(g, EV_SM);
          break;
        }
        uint8_t* pay = nullptr;
        size_t pay_len = 0;
        int stc = g->sess_apply(g->sess, g->sm, cid_, sid, resp, payload,
                                plen, &result, &pay, &pay_len);
        if (pay != nullptr) {
          // cached response with data bytes: park it in the completion
          // side-channel (the u64 record can't carry it); the Python
          // pump fetches by id and completes with Result.data.  ONLY a
          // leader completion with a future to notify consumes it —
          // parking on followers (or keyless entries) would leak the
          // copy for the engine's lifetime.
          if (g->leader && key != 0 && stc == 0) {
            std::lock_guard<std::mutex> lk(cmu);
            payload_id = next_payload_id++;
            paymap.emplace(payload_id,
                           std::string((const char*)pay, pay_len));
          }
          free(pay);
        }
        status = (uint8_t)stc;
      } else {
        result = g->sm_update(g->sm, payload, plen);
      }
      g->applied_handed = i;
      if (g->leader) {
        batch.push_back(
            {g->cid, i, term, key, result, cid_, sid, payload_id, 1, status});
        lat_emit_us += now - e2.born_us;
        lat_count++;
      } else {
        lat_emitf_us += now - e2.born_us;
        lat_countf++;
      }
    }
    if (g->applied_handed >= first && !g->leader) {
      // follower watermark record: Python needs last_applied to advance
      // (ReadIndex completion, snapshot triggers) but no futures complete
      uint64_t hi = g->applied_handed;
      batch.push_back(
          {g->cid, hi, g->term_of(hi), 0, 0, 0, 0, 0, 0, 0});
    }
    if (!batch.empty()) {
      std::lock_guard<std::mutex> lk(cmu);
      for (auto& c : batch) complq.push_back(c);
      ccv.notify_one();
    }
  }

  void trim_log(Group* g) {  // g->mu held
    uint64_t keep_from = g->applied_handed + 1;
    for (auto& p : g->peers) keep_from = std::min(keep_from, p.match + 1);
    while (g->log_first < keep_from && !g->log.empty() &&
           g->log.size() > 64) {  // keep a small resend cushion
      g->log.pop_front();
      g->log_first++;
    }
  }

  // Build and queue a REPLICATE to peer p with entries (p.next..last],
  // capped; advances p.next (pipeline mode).  g->mu held.
  void send_entries(Group* g, PeerP& p) {
    static constexpr uint64_t kMaxBatch = 4096;
    static constexpr uint64_t kMaxInflight = 1u << 14;
    if (p.next <= g->enroll_last) {
      // the follower needs entries from before this enrollment's window;
      // only the scalar path can serve them (snapshot/catch-up logic)
      begin_eject(g, EV_RESEND_PREENROLL);
      return;
    }
    while (p.next <= g->last_index && p.next - 1 - p.match < kMaxInflight) {
      uint64_t first = p.next;
      uint64_t last = std::min(g->last_index, first + kMaxBatch - 1);
      uint64_t prev = first - 1;
      uint64_t prev_term = g->term_of(prev);
      if (prev_term == 0 && prev != 0) {
        begin_eject(g, EV_PROTOCOL);
        return;
      }
      std::string b;
      put_msg_header(b, MT_REPLICATE, 0, p.id, g->nid, g->cid, g->term,
                     prev_term, prev, g->commit, 0, 0, last - first + 1);
      for (uint64_t i = first; i <= last; i++) {
        NEntry& en = g->log[i - g->log_first];
        if (p.witness) {
          append_witness_entry(b, en);
        } else {
          b += en.enc;
        }
      }
      queue_msg(p.slot, b);
      dbg_ev(g, "send", first, last);
      p.next = last + 1;
    }
    if (g->commit > g->commit_sent && p.next > g->last_index) {
      // commit-update broadcast: empty REPLICATE carrying the watermark
      std::string b;
      put_msg_header(b, MT_REPLICATE, 0, p.id, g->nid, g->cid, g->term,
                     g->term_of(g->last_index), g->last_index, g->commit, 0, 0,
                     0);
      queue_msg(p.slot, b);
    }
  }

  // Stage a State record when term/vote/commit changed since the last
  // written one (rdbcache-style suppression).  g->mu held.
  void stage_state(Group* g) {
    if (g->term == g->st_written_term && g->vote == g->st_written_vote &&
        g->commit == g->st_written_commit)
      return;
    std::string v;
    put_u64le(v, g->term);
    put_u64le(v, g->vote);
    put_u64le(v, g->commit);
    Shard* sh = shards[g->shard].get();
    {
      std::lock_guard<std::mutex> lk(sh->mu);
      batch_put(sh->batch, make_key(TAG_STATE, g->cid, g->nid, 0), v);
    }
    sh->cv.notify_one();
    g->st_written_term = g->term;
    g->st_written_vote = g->vote;
    g->st_written_commit = g->commit;
  }

  // Effects that are legal at the group's CURRENT durability point:
  // follower acks covered by the local fsync, leader quorum tally +
  // commit, apply hand-off (<= min(commit, fsynced)), entry fan-out
  // (pre-fsync sending is the thesis-10.2.1 pipelining), commit-update
  // broadcast, log trim.  g->mu held; called from both the round thread
  // (stage/ack work) and the shard committers (post-fsync).
  void run_effects(Group* g) {
    size_t kept = 0;
    for (auto& r : g->resps) {
      // never ack an entry the local fsync does not cover yet: the
      // leader would count a non-durable replica toward commit
      if (r.log_index > g->fsynced) {
        g->resps[kept++] = r;
        continue;
      }
      std::string b;
      put_msg_header(b, r.type, r.flags, r.to, g->nid, g->cid, g->term, 0,
                     r.log_index, 0, r.hint, r.hint_high, 0);
      queue_msg(r.slot, b);
      if (r.type == MT_REPLICATE_RESP && r.log_index >= g->log_first &&
          r.log_index < g->log_first + g->log.size()) {
        lat_resp_us += mono_us() - g->log[r.log_index - g->log_first].born_us;
        lat_respn++;
      }
    }
    g->resps.resize(kept);
    if (g->leader) {
      uint64_t q = tally(g);
      if (q > g->commit) {
        g->commit = q;
        g->last_commit_adv_ms = mono_ms();
        g->term_commit_ok = true;  // counting commits are current-term
        commits_advanced++;
        dbg_ev(g, "commit", q, 0);
        stage_state(g);
      }
      emit_apply(g);
      for (auto& p : g->peers) send_entries(g, p);
      if (g->commit > g->commit_sent) g->commit_sent = g->commit;
    } else {
      emit_apply(g);
    }
    trim_log(g);
  }

  // One pass of the round loop: stage WAL bytes to the shard committers,
  // run fsync-independent effects, heartbeats/clocks.  The round thread
  // NEVER blocks on a disk flush.
  void round_pass() {
    std::vector<std::shared_ptr<Group>> work;
    {
      std::unique_lock<std::mutex> lk(wmu);
      if (dirtyq.empty())
        wcv.wait_for(lk, std::chrono::milliseconds(round_interval_ms));
      work.swap(dirtyq);
    }
    rounds++;
    struct timespec t0;
    clock_gettime(CLOCK_MONOTONIC, &t0);
    for (auto& gsp : work) {
      Group* g = gsp.get();
      std::lock_guard<std::mutex> lk(g->mu);
      g->dirty = false;
      if (g->state != G_ACTIVE) continue;
      if (g->last_index > g->staged_to) {
        Shard* sh = shards[g->shard].get();
        {
          std::lock_guard<std::mutex> slk(sh->mu);
          std::string& b = sh->batch;
          int64_t now = mono_us();
          for (uint64_t i = g->staged_to + 1; i <= g->last_index; i++) {
            NEntry& e2 = g->log[i - g->log_first];
            lat_stage_us += now - e2.born_us;
            batch_put(b, make_key(TAG_ENTRY, g->cid, g->nid, i), e2.enc);
          }
          if (g->last_index != g->maxindex_written) {
            std::string v;
            put_u64be(v, g->last_index);
            batch_put(b, make_key(TAG_MAX_INDEX, g->cid, g->nid, 0), v);
            g->maxindex_written = g->last_index;
          }
          sh->post.emplace_back(gsp, g->last_index);
        }
        sh->cv.notify_one();
        dbg_ev(g, "stage", g->last_index, 0);
        entries_staged += g->last_index - g->staged_to;
        g->staged_to = g->last_index;
      }
      stage_state(g);
      run_effects(g);
    }
    flush_remotes();
    struct timespec t3;
    clock_gettime(CLOCK_MONOTONIC, &t3);
    round_ns += (uint64_t)(t3.tv_sec - t0.tv_sec) * 1000000000ull +
                (t3.tv_nsec - t0.tv_nsec);
  }

  // Per-shard committer: swap out everything staged since the last flush,
  // commit it as ONE fsynced batch, then run the deferred post-fsync
  // effects.  Group commit: a flush in progress lets the next batch grow.
  void committer_main(Shard* sh) {
    prctl(PR_SET_NAME, "natr-committer", 0, 0, 0);
    while (!stopped.load()) {
      std::string batch;
      std::vector<std::pair<std::shared_ptr<Group>, uint64_t>> post;
      {
        std::unique_lock<std::mutex> lk(sh->mu);
        if (sh->batch.empty() && sh->post.empty())
          sh->cv.wait_for(lk, std::chrono::milliseconds(50));
        if (sh->batch.empty() && sh->post.empty()) continue;
      }
      // group-commit accumulation window: pace fsyncs so each one covers
      // more staged work (the fsync device is the shared bottleneck; at
      // ~1ms per flush a handful of extra milliseconds multiplies batch
      // depth and divides flush load).  Bounded added latency <= window.
      int64_t w = commit_window_us.load();
      if (w > 0) {
        struct timespec ts;
        clock_gettime(CLOCK_MONOTONIC, &ts);
        int64_t now_us = (int64_t)ts.tv_sec * 1000000 + ts.tv_nsec / 1000;
        int64_t wait_us = sh->last_fsync_end_us + w - now_us;
        if (wait_us > 0) {
          struct timespec d = {wait_us / 1000000,
                               (wait_us % 1000000) * 1000};
          nanosleep(&d, nullptr);
        }
      }
      {
        std::lock_guard<std::mutex> lk(sh->mu);
        batch.swap(sh->batch);
        post.swap(sh->post);
      }
      if (batch.empty() && post.empty()) continue;
      bool ok = true;
      if (!batch.empty()) {
        fsyncs++;
        struct timespec t1, t2;
        clock_gettime(CLOCK_MONOTONIC, &t1);
        ok = nkv_commit(sh->handle, (const uint8_t*)batch.data(),
                        batch.size()) >= 0;
        clock_gettime(CLOCK_MONOTONIC, &t2);
        fsync_ns += (uint64_t)(t2.tv_sec - t1.tv_sec) * 1000000000ull +
                    (t2.tv_nsec - t1.tv_nsec);
        sh->last_fsync_end_us =
            (int64_t)t2.tv_sec * 1000000 + t2.tv_nsec / 1000;
      }
      for (auto& [gsp, until] : post) {
        Group* g = gsp.get();
        std::lock_guard<std::mutex> lk(g->mu);
        if (g->state != G_ACTIVE) continue;
        if (!ok) {
          begin_eject(g, EV_WAL_ERROR);
          continue;
        }
        dbg_ev(g, "fsync-post", until, 0);
        if (until > g->fsynced) {
          int64_t now2 = mono_us();
          for (uint64_t i = std::max(g->fsynced + 1, g->log_first);
               i <= until && i < g->log_first + g->log.size(); i++)
            lat_fsync_us += now2 - g->log[i - g->log_first].born_us;
          g->fsynced = until;
        }
        run_effects(g);
      }
      flush_remotes();
    }
  }

  int64_t last_clock_ms = 0;
  void clock_pass() {
    int64_t now = mono_ms();
    if (now - last_clock_ms < 10) return;
    // Scheduling-stall compensation: when this thread was off-CPU for a
    // long gap (box contention, SIGSTOP, VM pause), the liveness stamps
    // aged without the process observing its peers — remote heartbeats
    // sat unread in socket buffers while the local clocks "expired".
    // Firing contact-loss/quorum-loss ejects on resume would punish the
    // remotes for a LOCAL stall, and every spurious eject exiles the
    // group to the scalar path for 2+ election windows (the duty-0.706
    // collapse under a contended box, BENCH_r04).  Shift the eject
    // stamps forward by the unobserved time so each timeout is measured
    // in OBSERVED time; a genuinely dead peer still ejects, one fresh
    // window after the stall.  Send-side stamps (last_hb_ms) stay put —
    // after a stall, heartbeats should fire immediately, not later.
    int64_t stall = 0;
    if (last_clock_ms != 0) {
      int64_t gap = now - last_clock_ms;
      if (gap > 100) {
        stall = gap;
        clock_stalls++;
        clock_stall_ms += (uint64_t)gap;
      }
    }
    last_clock_ms = now;
    // snapshot the registry first: holding gmu while locking a group
    // would invert the g->mu -> (no gmu) order the hot paths rely on
    std::vector<std::shared_ptr<Group>> all;
    {
      std::lock_guard<std::mutex> reg(gmu);
      all.reserve(groups.size());
      for (auto& kv : groups) all.push_back(kv.second);
    }
    for (auto& sp : all) {
      Group* g = sp.get();
      std::lock_guard<std::mutex> lk(g->mu);
      if (g->state != G_ACTIVE) continue;
      if (stall > 0) {
        // clamp to now: ingest/reader threads kept running during the
        // clock thread's gap and may have stamped fresh contact — an
        // unclamped shift would push those stamps into the future and
        // delay GENUINE failure detection by up to the stall
        auto bump = [&](int64_t& t) { t = std::min(t + stall, now); };
        bump(g->leader_contact_ms);
        bump(g->quorum_ok_ms);
        bump(g->last_commit_adv_ms);
        for (auto& p : g->peers) {
          bump(p.contact_ms);
          if (p.progress_ms != 0) bump(p.progress_ms);
        }
      }
      if (g->leader) {
        if (now - g->last_hb_ms >= g->hb_period_ms) {
          g->last_hb_ms = now;
          uint64_t hl = 0, hh = 0;
          if (!g->reads.empty()) {  // broadcast the newest pending ctx
            hl = g->reads.back().low;
            hh = g->reads.back().high;
          }
          for (auto& p : g->peers) {
            std::string b;
            put_msg_header(b, MT_HEARTBEAT, 0, p.id, g->nid, g->cid, g->term,
                           0, 0, std::min(p.match, g->commit), hl, hh, 0);
            queue_msg(p.slot, b);
            // re-stamp every send: a lost echo would otherwise freeze the
            // stamp and inflate the next sample by N heartbeat periods
            p.hb_sent_us = mono_us();
          }
        }
        // check-quorum (leaderHasQuorum raft.go:380-390): count VOTING
        // peers heard from inside the election window
        size_t active = 1;
        for (auto& p : g->peers)
          if (p.voting && now - p.contact_ms < g->elect_timeout_ms) active++;
        size_t quorum = (g->nvoting + 1) / 2 + 1;
        if (active >= quorum) g->quorum_ok_ms = now;
        if (now - g->quorum_ok_ms > 2 * g->elect_timeout_ms)
          begin_eject(g, EV_QUORUM_LOST);
        // stall resend: a frame lost on a broken sender connection is
        // never retransmitted by the pipeline itself (p.next is already
        // past it) — the reference recovers via retry-state resends
        // (remote.go becomeRetry); mirror that on a progress timeout
        for (auto& p : g->peers) {
          if (p.match >= g->last_index) continue;
          if (p.progress_ms == 0) p.progress_ms = now;
          if (now - p.progress_ms >
              std::max((int64_t)50, 2 * g->hb_period_ms)) {
            p.next = p.match + 1;
            p.progress_ms = now;
            mark_dirty(g);
          }
        }
      } else {
        // 2x window (matching the check-quorum and commit-stall margins):
        // the eject is a FALLBACK, not an election — scalar raft runs its
        // own election clock after the handoff, so the extra margin costs
        // little failover latency but absorbs heartbeat jitter from a
        // starved LEADER box (the remote-side half of the duty collapse;
        // the local half is the stall compensation above)
        if (now - g->leader_contact_ms > 2 * g->elect_timeout_ms) {
          // Reader-plane cross-check (last_ingest_ms): the stall
          // compensation above keys only off THIS thread's pass gap, so
          // a starvation that hit the reader threads alone leaves the
          // leader's heartbeats unread in kernel socket buffers while
          // the local stamps age normally — ejecting then punishes the
          // remote for a local stall.  Only eject when the ingest plane
          // demonstrably ran inside the silence window; otherwise defer
          // so resumed readers get a pass to drain the backlog (which
          // refreshes leader_contact_ms before the stamp is written).
          // Cap at 2x the window: a genuinely dead link feeds no bytes
          // anywhere, and the eject must still fire, one window late.
          int64_t ingest = last_ingest_ms.load(std::memory_order_relaxed);
          bool readers_live =
              ingest != 0 && now - ingest < 2 * g->elect_timeout_ms;
          bool capped = now - g->leader_contact_ms > 4 * g->elect_timeout_ms;
          if (readers_live || capped)
            begin_eject(g, EV_CONTACT_LOST);
          else
            contact_ejects_deferred++;
        }
      }
      // liveness watchdog: entries are pending yet commit has not moved
      // for two election windows — some corner case has wedged the fast
      // path; hand the group to the scalar machinery, which has the full
      // recovery toolbox (snapshots, flow-control retries, elections).
      // The clock REARMS while nothing is pending, so the window starts
      // when pendingness begins — else the first burst after an idle gap
      // would eject instantly on a stale clock.
      if (g->commit >= g->last_index)
        g->last_commit_adv_ms = now;
      else if (g->state == G_ACTIVE &&
               now - g->last_commit_adv_ms > 2 * g->elect_timeout_ms)
        begin_eject(g, EV_COMMIT_STALL);
    }
    flush_remotes();
  }

  void round_main() {
    prctl(PR_SET_NAME, "natr-round", 0, 0, 0);
    while (!stopped.load()) round_pass();
  }

  // Heartbeats and liveness timeouts run on their OWN lean thread: under
  // box contention the round thread can spend an entire election window
  // inside one heavy pass (batch staging for thousands of groups), and
  // heartbeats riding behind that work are exactly what made remote
  // followers fire contact-loss ejects (BENCH_r04 duty 0.706).  A thread
  // whose whole loop is O(groups) stamp checks gets scheduled far more
  // reliably than one carrying the data plane.
  void clock_main() {
    prctl(PR_SET_NAME, "natr-clock", 0, 0, 0);
    while (!stopped.load()) {
      clock_pass();
      struct timespec d = {0, 10 * 1000000};
      nanosleep(&d, nullptr);
    }
  }

  // ------------------------------------------------------------ ingest

  // Handle one fast-path message for an ACTIVE group.  Returns false when
  // the message must go to Python.  Most refusals flip the group to
  // EJECTING first, but a REPLICATE past the local tail PUNTS while the
  // group stays ACTIVE: the missing frames are usually queued in order
  // behind the Python router (they took the leftover path during a
  // (re)enrollment window), so the enrolled step re-ingests the sequence
  // with no eject — a false return does NOT imply an eject is underway.
  bool handle_fast(Group* g, const ParsedMsg& m, const uint8_t* d) {
    std::lock_guard<std::mutex> lk(g->mu);
    if (g->state != G_ACTIVE) return false;
    if (m.term > g->term || m.to != g->nid) {
      // a HIGHER term must go to scalar raft (step down / new election)
      begin_eject(g, EV_TERM_MISMATCH);
      return false;
    }
    if (m.term < g->term &&
        !(m.type == MT_READ_INDEX && m.term == 0)) {
      // stale-term fast-path message: a deposed leader's tail or a late
      // response from the pre-enrollment term.  Scalar raft ignores stale
      // responses and answers stale leaders only to depose them — and the
      // deposed peer independently recovers via the NEW leader's
      // higher-term traffic plus its own quorum/commit-stall watchdogs.
      // Consuming (dropping) instead of ejecting removes a post-churn
      // eject storm (round 3: term-mismatch ejects on every late RESP).
      // Exception: READ_INDEX is a termless REQUEST in this protocol
      // (is_request_message raft.py:73 — finalize_message_term leaves it
      // at 0), so a scalar peer's forwarded read must fall through to
      // the handler, not be swallowed as stale.
      stale_dropped++;
      return true;
    }
    int64_t now = mono_ms();
    switch (m.type) {
      case MT_REPLICATE: {
        if (g->leader || m.from != g->leader_id) {
          begin_eject(g, EV_WRONG_ROLE);
          return false;
        }
        g->leader_contact_ms = now;
        int slot = peer_slot(g, m.from);
        if (slot < 0) {
          begin_eject(g, EV_UNKNOWN_PEER);
          return false;
        }
        if (m.log_index < g->commit) {
          // ack at the commit watermark, capped to what is durable here
          // (commit may run ahead of the local fsync on a follower)
          uint64_t ack = std::min(g->commit, g->fsynced);
          g->resps.push_back({slot, m.from, MT_REPLICATE_RESP, ack, 0, 0, 0});
          mark_dirty(g);
          return true;
        }
        if (m.log_index > g->last_index) {
          // gap: the missing frames usually took the leftover path while
          // this group was (re)enrolling and are queued IN ORDER behind
          // the router/mq — punting this frame onto the same path lets
          // the enrolled step re-ingest everything in sequence with no
          // eject.  A genuine loss still converges: the re-ingest refuses
          // again and the step path ejects (step-msgs), and the leader's
          // progress-timeout resend covers the rest.
          return false;
        }
        // prev-term check where verifiable (enrollment guarantees
        // consistency at or below enroll_last == commit-at-enroll)
        uint64_t pt = g->term_of(m.log_index);
        if (pt != 0 && pt != m.log_term) {
          begin_eject(g, EV_PREV_TERM);
          return false;
        }
        // append entries with index > last_index (same-term overlap is
        // identical by the raft log-matching property)
        size_t pos = m.entries_start;
        uint64_t appended_last = m.log_index;
        for (uint64_t i = 0; i < m.nentries; i++) {
          size_t espan = pos;
          uint64_t term, index;
          if (!parse_entry(d, m.span_end, pos, term, index)) {
            begin_eject(g, EV_PROTOCOL);
            return false;
          }
          appended_last = index;
          if (index <= g->last_index) continue;  // duplicate resend
          if (index != g->last_index + 1 || term != g->term) {
            begin_eject(g, EV_PROTOCOL);
            return false;
          }
          NEntry e;
          e.term = term;
          e.index = index;
          e.born_us = mono_us();
          e.enc.assign((const char*)d + espan, pos - espan);
          g->log.push_back(std::move(e));
          g->last_index = index;
        }
        uint64_t c = std::min(appended_last, m.commit);
        c = std::min(c, g->last_index);
        if (c > g->commit) {
          g->commit = c;
          g->last_commit_adv_ms = now;
        }
        g->resps.push_back(
            {slot, m.from, MT_REPLICATE_RESP, appended_last, 0, 0, 0});
        mark_dirty(g);
        return true;
      }
      case MT_REPLICATE_RESP: {
        if (!g->leader) {
          begin_eject(g, EV_PROTOCOL);
          return false;
        }
        if (m.flags & kFlagReject) {
          begin_eject(g, EV_REJECT_RESP);  // conflict/lag: Python flow control
          return false;
        }
        for (auto& p : g->peers) {
          if (p.id != m.from) continue;
          p.contact_ms = now;
          if (m.log_index > p.match) {
            uint64_t old_match = p.match;
            p.match = m.log_index;
            p.progress_ms = now;
            dbg_ev(g, "ack", m.from, m.log_index);
            if (p.next < p.match + 1) p.next = p.match + 1;
            // diagnostics: how stale is the newly acked range?
            int64_t nowu = mono_us();
            for (uint64_t i = std::max(old_match + 1, g->log_first);
                 i <= m.log_index && i < g->log_first + g->log.size(); i++) {
              lat_ack_us += nowu - g->log[i - g->log_first].born_us;
              lat_ackn++;
            }
            mark_dirty(g);  // tally/apply happen on the round thread
          }
          return true;
        }
        begin_eject(g, EV_PROTOCOL);
        return false;
      }
      case MT_HEARTBEAT: {
        if (g->leader || m.from != g->leader_id) {
          begin_eject(g, EV_PROTOCOL);
          return false;
        }
        g->leader_contact_ms = now;
        int slot = peer_slot(g, m.from);
        if (slot < 0) {
          begin_eject(g, EV_PROTOCOL);
          return false;
        }
        uint64_t c = std::min(m.commit, g->fsynced);
        if (c > g->commit) {
          g->commit = c;
          g->last_commit_adv_ms = now;
          mark_dirty(g);
        }
        // ReadIndex confirmation hints are a pure echo on the follower
        // (raft.go:883-892), so an enrolled follower keeps serving a
        // Python leader's ReadIndex protocol
        g->resps.push_back(
            {slot, m.from, MT_HEARTBEAT_RESP, 0, m.hint, m.hint_high, 0});
        mark_dirty(g);
        return true;
      }
      case MT_HEARTBEAT_RESP: {
        if (!g->leader) {
          begin_eject(g, EV_PROTOCOL);
          return false;
        }
        // validate the sender FIRST: an echo from a non-member must not
        // touch g->reads — with pi == peers.size() the phantom bit
        // 1<<pi could count toward ReadIndex quorums
        size_t pi = g->peers.size();
        for (size_t i = 0; i < g->peers.size(); i++)
          if (g->peers[i].id == m.from) { pi = i; break; }
        if (pi == g->peers.size()) {
          begin_eject(g, EV_PROTOCOL);
          return false;
        }
        PeerP& pr0 = g->peers[pi];
        pr0.contact_ms = now;
        if (pr0.hb_sent_us) {  // heartbeat echo round trip (diagnostics)
          uint64_t rtt = (uint64_t)(mono_us() - pr0.hb_sent_us);
          pr0.hb_sent_us = 0;
          rtt_us += rtt;
          rttn++;
          uint64_t mx = rtt_max_us.load();
          while (rtt > mx && !rtt_max_us.compare_exchange_weak(mx, rtt)) {}
        }
        if (m.hint != 0 || m.hint_high != 0) {
          // ReadIndex confirmation echo (readindex.go confirm): count the
          // peer toward every pending context at or before this one
          uint32_t bit = 1u << pi;
          // the echo proves leadership only for contexts registered at or
          // before the one the heartbeat carried (readindex.go:77 confirm
          // semantics): find the match FIRST, then count
          size_t pos = g->reads.size();
          for (size_t i = 0; i < g->reads.size(); i++) {
            if (g->reads[i].low == m.hint && g->reads[i].high == m.hint_high) {
              pos = i;
              break;
            }
          }
          if (pos < g->reads.size() && pr0.voting) {
            // only voting echoes prove leadership (observers confirm
            // nothing — readindex.go confirm semantics)
            uint32_t quorum = (g->nvoting + 1) / 2 + 1;
            size_t done = 0;
            for (size_t i = 0; i <= pos; i++) {
              auto& pr = g->reads[i];
              if (!(pr.peer_mask & bit)) {
                pr.peer_mask |= bit;
                pr.acks++;
              }
              if (i == done && pr.acks >= quorum) done++;
            }
            if (done) {
              bool fwd = false;
              {
                std::lock_guard<std::mutex> rlk(rmu);
                bool local = false;
                for (size_t i = 0; i < done; i++) {
                  auto& pr = g->reads[i];
                  if (pr.origin == 0 || pr.origin == g->nid) {
                    readyq.push_back({g->cid, pr.low, pr.high, pr.index});
                    local = true;
                  }
                }
                if (local) rcv.notify_one();
              }
              // forwarded contexts answer their origin (scalar twin:
              // handle_read_index_leader_confirmation raft.py:1185).
              // Sent DIRECTLY, not via g->resps: the resps queue gates on
              // the local fsync (run_effects), but a quorum of echoes has
              // already confirmed leadership — a read confirmation must
              // not wait on the leader's disk.
              for (size_t i = 0; i < done; i++) {
                auto& pr = g->reads[i];
                if (pr.origin != 0 && pr.origin != g->nid) {
                  int oslot = peer_slot(g, pr.origin);
                  if (oslot >= 0) {
                    std::string b;
                    put_msg_header(b, MT_READ_INDEX_RESP, 0, pr.origin,
                                   g->nid, g->cid, g->term, 0, pr.index, 0,
                                   pr.low, pr.high, 0);
                    queue_msg(oslot, b);
                    fwd = true;
                  }
                }
              }
              g->reads.erase(g->reads.begin(), g->reads.begin() + done);
              if (fwd) mark_dirty(g);  // flush the confirmations promptly
            }
          }
        }
        if (pr0.match < g->last_index) mark_dirty(g);
        return true;
      }
      case MT_READ_INDEX: {
        // linearizable read forwarded by an enrolled follower (scalar
        // twins: handle_leader_read_index raft.py:1095 on the leader,
        // handle_follower_read_index raft.py:1258 re-forward elsewhere).
        // Unservable requests are DROPPED — the origin's client retries
        // (report_dropped_read_index semantics) — never ejected.
        if (g->leader) {
          reg_read(g, m.hint, m.hint_high, m.from);
        } else {
          fwd_read(g, m.from, m.hint, m.hint_high);
        }
        return true;
      }
      case MT_READ_INDEX_RESP: {
        // confirmation for a read this node forwarded (scalar twin:
        // handle_follower_read_index_resp raft.py:1271) — may come from
        // a native leader or a Python-scalar leader over the same stream
        if (m.from == g->leader_id) g->leader_contact_ms = now;
        std::lock_guard<std::mutex> rlk(rmu);
        readyq.push_back({g->cid, m.hint, m.hint_high, m.log_index});
        rcv.notify_one();
        return true;
      }
      default:
        begin_eject(g, EV_PROTOCOL);
        return false;
    }
  }

  static int peer_slot(Group* g, uint64_t id) {
    for (auto& p : g->peers)
      if (p.id == id) return p.slot;
    return -1;
  }

  // Forward a READ_INDEX toward this follower's leader on behalf of
  // `origin` (g->mu held).  Shared by natr_read_fwd (origin == self) and
  // the handle_fast re-forward (origin == the requesting peer) so the
  // frame layout lives in one place.
  bool fwd_read(Group* g, uint64_t origin, uint64_t low, uint64_t high) {
    if (g->leader || g->leader_id == 0 || g->leader_id == origin)
      return false;
    int slot = peer_slot(g, g->leader_id);
    if (slot < 0) return false;
    std::string b;
    put_msg_header(b, MT_READ_INDEX, 0, g->leader_id, origin, g->cid,
                   g->term, 0, 0, 0, low, high, 0);
    queue_msg(slot, b);
    mark_dirty(g);  // flush promptly
    return true;
  }

  // Register a leader-side ReadIndex context (thesis 6.4) and broadcast
  // the hinted heartbeats whose echoes confirm it.  g->mu held.
  // origin != 0 marks a follower-forwarded request (the scalar twin is
  // handle_leader_read_index, raft.py:1095); the confirmation fan-out
  // answers those with MT_READ_INDEX_RESP instead of the local readyq.
  bool reg_read(Group* g, uint64_t low, uint64_t high, uint64_t origin) {
    if (!g->leader || !g->term_commit_ok) return false;
    if (g->reads.size() >= 1024) return false;
    g->reads.push_back({low, high, g->commit, 1, 0, origin});
    for (auto& p : g->peers) {
      if (!p.voting) continue;  // observer echoes confirm nothing —
                                // don't spend a hint per read on them
      std::string b;
      put_msg_header(b, MT_HEARTBEAT, 0, p.id, g->nid, g->cid, g->term, 0, 0,
                     std::min(p.match, g->commit), low, high, 0);
      queue_msg(p.slot, b);
    }
    mark_dirty(g);  // flush the hinted heartbeats promptly
    return true;
  }
};

}  // namespace

// ------------------------------------------------------------------ C ABI

extern "C" {

void* natr_create(const char* source_address, uint64_t deployment_id,
                  uint64_t bin_ver, const char* nativekv_so_path, char* errbuf,
                  size_t errlen) {
  auto e = std::make_unique<Engine>();
  e->source_address = source_address ? source_address : "";
  e->deployment_id = deployment_id;
  e->bin_ver = bin_ver;
  e->nkv_dl = dlopen(nativekv_so_path, RTLD_NOW | RTLD_GLOBAL);
  if (!e->nkv_dl) {
    if (errbuf && errlen) snprintf(errbuf, errlen, "dlopen: %s", dlerror());
    return nullptr;
  }
  e->nkv_commit = (nkv_commit_fn)dlsym(e->nkv_dl, "nkv_commit");
  if (!e->nkv_commit) {
    if (errbuf && errlen) snprintf(errbuf, errlen, "dlsym nkv_commit failed");
    return nullptr;
  }
  return e.release();
}

void natr_start(void* h) {
  Engine* e = (Engine*)h;
  e->round_thread = std::thread([e] { e->round_main(); });
  e->clock_thread = std::thread([e] { e->clock_main(); });
}

void natr_destroy(void* h) {
  Engine* e = (Engine*)h;
  delete e;
}

void natr_free(void* p) { free(p); }

int natr_set_shards(void* h, void** handles, int n) {
  Engine* e = (Engine*)h;
  for (int i = 0; i < n; i++) {
    auto sh = std::make_unique<Shard>();
    sh->handle = handles[i];
    Shard* p = sh.get();
    sh->thread = std::thread([e, p] { e->committer_main(p); });
    e->shards.push_back(std::move(sh));
  }
  return 0;
}

// Register a remote address slot; returns the slot index (-1 when full).
int natr_add_remote(void* h) {
  Engine* e = (Engine*)h;
  int slot = e->nremotes.fetch_add(1);
  if (slot >= kMaxRemotes) {
    e->nremotes.fetch_sub(1);
    return -1;
  }
  return slot;
}

// Enroll a group, possibly mid-flight.  The caller (Node._maybe_enroll,
// under raftMu, at a step instant with no pending raft Update) passes:
// - the unapplied/unacked log tail `tail` = entries (log_first..last_index]
//   as concatenated canonical encodings (everything a peer resend or an
//   apply hand-off can still need: log_first = min(processed+1,
//   min(peer next)));
// - prev_term = term(log_first-1) for REPLICATE prev-entry checks;
// - per-peer match/next as the scalar progress tracker holds them;
// - processed = entries already handed to apply by the scalar path.
// The caller guarantees every entry in (commit..last_index] carries the
// current term (so counting-based commits never violate raft p8) and that
// the log is fully persisted (no pending entries_to_save).
int natr_enroll(void* h, uint64_t cid, uint64_t nid, uint64_t term,
                uint64_t vote, uint64_t leader_id, int is_leader,
                uint64_t last_index, uint64_t commit, uint64_t processed,
                uint64_t log_first, uint64_t prev_term, uint32_t shard,
                int64_t hb_period_ms, int64_t elect_timeout_ms,
                int term_commit_ok,
                const uint64_t* peer_ids, const int32_t* peer_slots,
                const uint64_t* peer_match, const uint64_t* peer_next,
                const int32_t* peer_voting,
                int npeers, const uint8_t* tail, size_t tail_len) {
  Engine* e = (Engine*)h;
  if (shard >= e->shards.size() || npeers > 16) return -1;
  if (log_first > last_index + 1 || processed < log_first - 1 ||
      commit > last_index || processed > commit)
    return -1;
  auto g = std::make_shared<Group>();
  g->self = g;
  g->cid = cid;
  g->nid = nid;
  g->term = term;
  g->vote = vote;
  g->leader_id = leader_id;
  g->leader = is_leader != 0;
  g->shard = shard;
  g->log_first = log_first;
  g->enroll_last = log_first - 1;
  g->enroll_last_term = prev_term;
  g->last_index = last_index;
  g->staged_to = last_index;
  g->fsynced = last_index;
  g->commit = commit;
  g->applied_handed = processed;
  g->commit_sent = commit;
  // parse the tail entries; spans are the canonical encodings
  size_t pos = 0;
  for (uint64_t i = log_first; i <= last_index; i++) {
    size_t start = pos;
    uint64_t et, ei;
    if (!parse_entry(tail, tail_len, pos, et, ei) || ei != i) return -3;
    NEntry en;
    en.term = et;
    en.index = ei;
    en.born_us = mono_us();
    en.enc.assign((const char*)tail + start, pos - start);
    g->log.push_back(std::move(en));
  }
  if (pos != tail_len) return -3;
  // seed the suppression caches with current on-disk values so the first
  // round only writes records that actually change
  g->st_written_term = term;
  g->st_written_vote = vote;
  g->st_written_commit = commit;
  g->maxindex_written = last_index;
  g->hb_period_ms = hb_period_ms;
  g->elect_timeout_ms = elect_timeout_ms;
  g->term_commit_ok = term_commit_ok != 0;
  int64_t now = mono_ms();
  g->last_hb_ms = now;
  g->leader_contact_ms = now;
  g->quorum_ok_ms = now;
  g->last_commit_adv_ms = now;
  for (int i = 0; i < npeers; i++) {
    PeerP p;
    p.id = peer_ids[i];
    p.slot = peer_slots[i];
    p.match = peer_match[i];
    p.next = peer_next[i];
    // role values: 0 = observer (non-voting), 1 = voter, 2 = witness
    // (voting, metadata-only replication)
    int role = peer_voting == nullptr ? 1 : peer_voting[i];
    p.voting = role != 0;
    p.witness = role == 2;
    if (p.next < log_first || p.match > last_index) return -4;
    p.contact_ms = now;
    g->peers.push_back(p);
    if (p.voting) g->nvoting++;
  }
  // self must be a voter (observers/witnesses never enroll), so the
  // quorum base is nvoting peers + 1
  if (g->nvoting + 1 < 2) return -4;
  {
    std::lock_guard<std::mutex> lk(e->gmu);
    auto& slot = e->groups[cid];
    if (slot && slot->state != G_GONE) return -2;  // still enrolled
    slot = g;
  }
  // kick the first round so unacked tail entries resend / commit promptly
  {
    std::lock_guard<std::mutex> lk(g->mu);
    e->mark_dirty(g.get());
  }
  return 0;
}

// Attach a native C-ABI state machine (natsm.cpp) to an enrolled group.
// Entries already handed to the Python apply plane form the order barrier:
// native applies start only once Python reports them applied
// (natr_note_applied).  py_applied0 = the Python RSM manager's current
// last_applied.  Returns 1 on success, 0 when the group is not enrolled.
int natr_attach_sm(void* h, uint64_t cid, void* sm, void* update_fn,
                   uint64_t py_applied0, void* sess, void* sess_apply_fn,
                   void* sm_save_fn, void* sess_save_fn) {
  Engine* e = (Engine*)h;
  std::shared_ptr<Group> sp = e->find(cid);
  Group* g = sp.get();
  if (!g || sm == nullptr || update_fn == nullptr) return 0;
  std::lock_guard<std::mutex> lk(g->mu);
  if (g->state != G_ACTIVE) return 0;
  g->sm = sm;
  g->sm_update = (uint64_t (*)(void*, const uint8_t*, size_t))update_fn;
  g->sm_save = (long long (*)(void*, uint8_t**))sm_save_fn;
  if (sess != nullptr && sess_apply_fn != nullptr) {
    g->sess = sess;
    g->sess_apply =
        (int (*)(void*, void*, uint64_t, uint64_t, uint64_t, const uint8_t*,
                 size_t, uint64_t*, uint8_t**, size_t*))sess_apply_fn;
    g->sess_save = (long long (*)(void*, uint8_t**))sess_save_fn;
  }
  g->apply_barrier = g->applied_handed;
  // max: a racing natr_note_applied may already have reported fresher
  // Python progress than the caller's snapshot — never clobber a lift
  if (py_applied0 > g->py_applied) g->py_applied = py_applied0;
  e->mark_dirty(g);  // an applicable backlog applies on the next pass
  return 1;
}

// Consistent native-SM snapshot capture: returns a malloc'd blob
// [uvarint index][uvarint term][uvarint kv_len][kv bytes]
// [uvarint sess_len][sess bytes] at exactly applied_handed.
// Consistency protocol: the capturing flag is set under g->mu, then the
// image serializes OFF the lock while emit_apply defers (applies are the
// only SM/session writers) and natr_eject waits on capture_cv — so no
// write can land mid-image, yet replication/heartbeats/commit tallying
// keep running (the reference's regular-SM saves block only the update
// lock, never the raft plane; internal/rsm/statemachine.go:552-814).
// Any new SM writer MUST either run through emit_apply or check
// g->capturing.  Returns the blob length, or -1 when the group is not
// enrolled / attached / capturable — the caller falls back to the eject
// path.
long long natr_capture_sm(void* h, uint64_t cid, uint8_t** out) {
  Engine* e = (Engine*)h;
  std::shared_ptr<Group> sp = e->find(cid);
  Group* g = sp.get();
  if (!g) return -1;
  uint64_t index, term;
  void *sm, *sess;
  long long (*sm_save)(void*, uint8_t**);
  long long (*sess_save)(void*, uint8_t**);
  {
    std::lock_guard<std::mutex> lk(g->mu);
    if (g->state != G_ACTIVE || g->sm == nullptr || g->sm_save == nullptr ||
        g->capturing)
      return -1;
    // a sessions-bearing group without a session serializer must fall
    // back (eject path): capturing with an empty session image would
    // persist a snapshot that silently drops all exactly-once dedup state
    if (g->sess != nullptr && g->sess_save == nullptr) return -1;
    // pre-enrollment entries may still be in flight on the PYTHON apply
    // plane (the attach barrier); an image taken now could miss them
    if (g->py_applied < g->apply_barrier) return -1;
    index = g->applied_handed;
    term = g->term_of(index);  // 0 below the enrollment window
    if (index == 0 || term == 0) return -1;
    // freeze APPLIES only (emit_apply defers while capturing), then
    // serialize off g->mu: replication, heartbeats, acks and commit
    // tallying keep running — an O(state) image must never stall the
    // raft plane for this group (that would drop leadership on every
    // periodic snapshot of a large SM)
    g->capturing = true;
    sm = g->sm;
    sess = g->sess;
    sm_save = g->sm_save;
    sess_save = g->sess_save;
  }
  uint8_t* kv = nullptr;
  long long kvn = sm_save(sm, &kv);
  uint8_t* ss = nullptr;
  long long ssn = 0;
  if (kvn >= 0 && sess != nullptr) {
    ssn = sess_save(sess, &ss);
  }
  {
    std::lock_guard<std::mutex> lk(g->mu);
    g->capturing = false;
    g->capture_cv.notify_all();
    e->mark_dirty(g);  // resume any deferred applies promptly
  }
  if (kvn < 0 || ssn < 0) {
    free(kv);
    free(ss);
    return -1;
  }
  std::string b;
  put_uvarint(b, index);
  put_uvarint(b, term);
  put_uvarint(b, (uint64_t)kvn);
  b.append((const char*)kv, (size_t)kvn);
  put_uvarint(b, (uint64_t)ssn);
  if (ssn > 0) b.append((const char*)ss, (size_t)ssn);
  free(kv);
  free(ss);
  *out = (uint8_t*)malloc(b.size() ? b.size() : 1);
  memcpy(*out, b.data(), b.size());
  return (long long)b.size();
}

// Python reports its apply progress (lifts the attach-time barrier).
void natr_note_applied(void* h, uint64_t cid, uint64_t applied) {
  Engine* e = (Engine*)h;
  std::shared_ptr<Group> sp = e->find(cid);
  Group* g = sp.get();
  if (!g) return;
  std::lock_guard<std::mutex> lk(g->mu);
  if (applied > g->py_applied) g->py_applied = applied;
  if (g->sm != nullptr && g->py_applied >= g->apply_barrier)
    e->mark_dirty(g);
}

// Drain up to `cap` native-SM apply completions into the caller's arrays.
// Returns the count, 0 on timeout, -1 when stopped.
long long natr_next_completions(void* h, int timeout_ms, uint64_t* cids,
                                uint64_t* indexes, uint64_t* terms,
                                uint64_t* keys, uint64_t* results,
                                uint64_t* client_ids, uint64_t* series_ids,
                                uint64_t* payload_ids, uint8_t* leaders,
                                uint8_t* statuses, long long cap) {
  Engine* e = (Engine*)h;
  std::unique_lock<std::mutex> lk(e->cmu);
  if (e->complq.empty() && !e->stopped.load())
    e->ccv.wait_for(lk, std::chrono::milliseconds(timeout_ms));
  if (e->complq.empty()) return e->stopped.load() ? -1 : 0;
  long long n = 0;
  while (n < cap && !e->complq.empty()) {
    const Engine::Completion& c = e->complq.front();
    cids[n] = c.cid;
    indexes[n] = c.index;
    terms[n] = c.term;
    keys[n] = c.key;
    results[n] = c.result;
    client_ids[n] = c.client_id;
    series_ids[n] = c.series_id;
    payload_ids[n] = c.payload_id;
    leaders[n] = c.leader;
    statuses[n] = c.status;
    e->complq.pop_front();
    n++;
  }
  return n;
}

// Fetch (and consume) a completion payload parked by the apply loop.
// Copies min(len, cap) bytes and returns the payload's full length; the
// entry is erased only when the caller's buffer held all of it, so an
// undersized read can retry with a bigger buffer.  Unknown id: -1.
long long natr_take_payload(void* h, uint64_t pid, uint8_t* buf,
                            long long cap) {
  Engine* e = (Engine*)h;
  std::lock_guard<std::mutex> lk(e->cmu);
  auto it = e->paymap.find(pid);
  if (it == e->paymap.end()) return -1;
  long long len = (long long)it->second.size();
  memcpy(buf, it->second.data(), (size_t)std::min(len, cap));
  if (cap >= len) e->paymap.erase(it);
  return len;
}

// Propose on an enrolled leader group.  Returns the assigned index (>0) or
// 0 when the group is not accepting (caller falls back to the scalar path).
uint64_t natr_propose(void* h, uint64_t cid, uint64_t key, uint64_t client_id,
                      uint64_t series_id, uint64_t responded_to, uint8_t etype,
                      const uint8_t* cmd, size_t cmdlen) {
  Engine* e = (Engine*)h;
  std::shared_ptr<Group> sp = e->find(cid);
  Group* g = sp.get();
  if (!g) return 0;
  std::lock_guard<std::mutex> lk(g->mu);
  if (g->state != G_ACTIVE || !g->leader) return 0;
  // backpressure: the scalar path bounds in-flight work via its entry
  // queue; the native lane bounds the retained log (which trim_log cannot
  // shrink past the slowest peer's match).  Falling back (return 0) routes
  // the proposal through the scalar queue, whose next step ejects the
  // group and applies the normal flow-control/snapshot machinery.
  if (g->log.size() >= 32768) return 0;
  uint64_t index = g->last_index + 1;
  NEntry en;
  en.term = g->term;
  en.index = index;
  en.born_us = mono_us();
  en.enc = encode_entry(g->term, index, etype, key, client_id, series_id,
                        responded_to, cmd, cmdlen);
  g->log.push_back(std::move(en));
  g->last_index = index;
  e->dbg_ev(g, "propose", index, 0);
  e->proposed++;
  e->mark_dirty(g);
  return index;
}

// Batch propose: append `count` entries in one lock hold.  cmds is
// [u32le len][bytes] per command; keys are per-entry tracker keys; all
// entries share client/series/responded/etype (one client burst).
// Returns the FIRST assigned index (>0), or 0 when not accepting (the
// caller falls back to the scalar queue for the whole batch).
uint64_t natr_propose_batch(void* h, uint64_t cid, int count,
                            const uint64_t* keys, uint64_t client_id,
                            uint64_t series_id, uint64_t responded_to,
                            uint8_t etype, const uint8_t* cmds,
                            size_t cmds_len) {
  Engine* e = (Engine*)h;
  std::shared_ptr<Group> sp = e->find(cid);
  Group* g = sp.get();
  if (!g || count <= 0) return 0;
  std::lock_guard<std::mutex> lk(g->mu);
  if (g->state != G_ACTIVE || !g->leader) return 0;
  if (g->log.size() + (size_t)count > 32768) return 0;  // backpressure
  uint64_t first = g->last_index + 1;
  // validate the whole blob BEFORE appending anything: a mid-batch
  // failure after partial appends would make the caller's full-batch
  // fallback double-propose the prefix
  {
    size_t vpos = 0;
    for (int i = 0; i < count; i++) {
      if (vpos + 4 > cmds_len) return 0;
      uint32_t clen = 0;
      memcpy(&clen, cmds + vpos, 4);
      vpos += 4 + clen;
      if (vpos > cmds_len) return 0;
    }
  }
  size_t pos = 0;
  int64_t now = mono_us();
  for (int i = 0; i < count; i++) {
    uint32_t clen = 0;
    memcpy(&clen, cmds + pos, 4);
    pos += 4;
    NEntry en;
    en.term = g->term;
    en.index = first + i;
    en.born_us = now;
    en.enc = encode_entry(g->term, first + i, etype, keys[i], client_id,
                          series_id, responded_to, cmds + pos, clen);
    pos += clen;
    g->log.push_back(std::move(en));
  }
  g->last_index = first + count - 1;
  e->proposed += count;
  e->mark_dirty(g);
  return first;
}

// Core batch ingest: consume fast-path messages for ACTIVE enrolled
// groups.  Returns consumed count; -1 on parse error / foreign deployment
// (caller must route the whole payload to Python).  When some messages
// remain, *leftover_out receives a rebuilt MessageBatch payload.
static long long ingest_batch(Engine* e, const uint8_t* d, size_t len,
                              std::string* leftover_out, bool* has_leftover) {
  *has_leftover = false;
  size_t pos = 0;
  uint64_t dep_id, bin_ver, count;
  if (!get_uvarint(d, len, pos, dep_id)) return -1;
  // deployment filtering stays in Python (transport.handle_request):
  // foreign batches pass through untouched
  if (dep_id != e->deployment_id) return -1;
  size_t src_start = pos;
  if (!skip_str(d, len, pos)) return -1;
  size_t src_end = pos;
  if (!get_uvarint(d, len, pos, bin_ver)) return -1;
  if (!get_uvarint(d, len, pos, count)) return -1;
  if (e->blocked_in_n.load(std::memory_order_relaxed)) {
    // src span = uvarint(len) + bytes; re-parse the length for the compare
    size_t sp = src_start;
    uint64_t slen = 0;
    if (get_uvarint(d, len, sp, slen) && sp + slen <= len) {
      std::lock_guard<std::mutex> lk(e->block_mu);
      for (const std::string& a : e->blocked_in)
        if (a.size() == slen && memcmp(a.data(), d + sp, slen) == 0) {
          // partitioned: the whole batch vanishes, leftovers included
          e->part_in_dropped += count;
          return (long long)count;
        }
    }
  }
  long long consumed = 0;
  std::string left;
  uint64_t left_count = 0;
  for (uint64_t i = 0; i < count; i++) {
    ParsedMsg m;
    if (!parse_message(d, len, pos, m)) return -1;
    bool fast = false;
    if (m.type == MT_REPLICATE || m.type == MT_REPLICATE_RESP ||
        m.type == MT_HEARTBEAT || m.type == MT_HEARTBEAT_RESP ||
        m.type == MT_READ_INDEX || m.type == MT_READ_INDEX_RESP) {
      std::shared_ptr<Group> g = e->find(m.cluster_id);
      if (g) fast = e->handle_fast(g.get(), m, d);
    }
    if (fast) {
      consumed++;
      e->ingested_fast++;
    } else {
      e->ingested_slow++;
      left.append((const char*)d + m.span_start, m.span_end - m.span_start);
      left_count++;
    }
  }
  if (left_count) {
    std::string& out = *leftover_out;
    out.clear();
    out.reserve(left.size() + 32);
    put_uvarint(out, dep_id);
    out.append((const char*)d + src_start, src_end - src_start);
    put_uvarint(out, bin_ver);
    put_uvarint(out, left_count);
    out += left;
    *has_leftover = true;
  }
  return consumed;
}

// Partition injection (monkey.go:184-213 at the real transport).  `addr`
// blocks INBOUND raft batches whose source address matches (NULL = skip);
// `slot` >= 0 blocks OUTBOUND passes to that remote.  on=0 heals.  The
// protocol recovers by itself afterwards (resends, ejects, re-enrolls).
void natr_set_partition(void* h, const char* addr, int slot, int on) {
  Engine* e = (Engine*)h;
  if (addr != nullptr && addr[0]) {
    std::lock_guard<std::mutex> lk(e->block_mu);
    std::string a(addr);
    auto& v = e->blocked_in;
    auto it = std::find(v.begin(), v.end(), a);
    if (on && it == v.end()) v.push_back(a);
    if (!on && it != v.end()) v.erase(it);
    e->blocked_in_n.store(v.size(), std::memory_order_relaxed);
  }
  if (slot >= 0 && slot < 64) {
    uint64_t bit = 1ULL << slot;
    if (on)
      e->blocked_out.fetch_or(bit);
    else
      e->blocked_out.fetch_and(~bit);
  }
}

long long natr_ingest(void* h, const uint8_t* d, size_t len, uint8_t** leftover,
                      size_t* leftover_len) {
  Engine* e = (Engine*)h;
  *leftover = nullptr;
  *leftover_len = 0;
  std::string out;
  bool has = false;
  long long consumed = ingest_batch(e, d, len, &out, &has);
  e->last_ingest_ms.store(mono_ms(), std::memory_order_relaxed);
  if (consumed < 0) return -1;
  if (has) {
    *leftover = (uint8_t*)malloc(out.size());
    memcpy(*leftover, out.data(), out.size());
    *leftover_len = out.size();
  }
  return consumed;
}

// ---- stream ingest: the transport recv thread reads large chunks and
// hands the raw byte stream here; frames are reassembled, CRC-checked and
// fast-path batches consumed entirely without the GIL.  Leftovers (partial
// batches, non-raft methods, corrupt frames) are returned packed as
// [u16 method][u32 len][payload]... for the Python side to route.  A
// method of 0xFFFF signals a framing/CRC error: the caller must close the
// connection (matching tcp.py's TransportError behavior).
struct ConnState {
  std::string pending;
};

// ---- partition injection (monkey.go:184-213 parity, but at the REAL
// transport: in fast-lane deployments every raft message for a remote —
// both planes — rides the single ordered native stream).  Inbound raft
// batches from a blocked source address are consumed and dropped at the
// single ingest choke point (leftovers included — nothing leaks to the
// Python router); outbound passes for a blocked remote slot are dropped
// at flush.  Traffic that does NOT ride these streams — snapshot jobs,
// inbound chunks, Python-socket sends — is blocked by the Python
// transport's partition_filter (transport.py), wired to the same
// fastlane.set_partition call.  Healing is the protocol's own job:
// progress-timeout resends, check-quorum/contact-loss ejects,
// re-enrollment.

void* natr_conn_new(void* h) { return new ConnState(); }

void natr_conn_free(void* h, void* c) { delete (ConnState*)c; }

// Core stream processor: reassemble frames from raw bytes, consume raft
// batches, emit leftovers via `emit(method, data, len)`.  Returns false on
// a framing/CRC error (connection must be closed); an 0xFFFF record is
// emitted in that case too.
typedef std::function<void(uint16_t, const uint8_t*, size_t)> EmitFn;
static bool process_stream(Engine* e, ConnState* cs, const uint8_t* d,
                           size_t len, const EmitFn& emit) {
  const uint8_t* buf = d;
  size_t blen = len;
  if (!cs->pending.empty()) {
    cs->pending.append((const char*)d, len);
    buf = (const uint8_t*)cs->pending.data();
    blen = cs->pending.size();
  }
  std::string batch_left;
  size_t pos = 0;
  bool fatal = false;
  while (true) {
    if (blen - pos < 20) break;  // header: >HHQII
    const uint8_t* hp = buf + pos;
    uint32_t magic = ((uint32_t)hp[0] << 8) | hp[1];
    uint32_t method = ((uint32_t)hp[2] << 8) | hp[3];
    uint64_t size = 0;
    for (int i = 0; i < 8; i++) size = (size << 8) | hp[4 + i];
    uint32_t pcrc = 0, hcrc = 0;
    for (int i = 0; i < 4; i++) pcrc = (pcrc << 8) | hp[12 + i];
    for (int i = 0; i < 4; i++) hcrc = (hcrc << 8) | hp[16 + i];
    if (magic != 0xAE7D || size > (1ull << 30) ||
        crc32ieee(hp, 16) != hcrc) {
      fatal = true;
      break;
    }
    if (blen - pos - 20 < size) break;  // wait for the rest
    const uint8_t* payload = hp + 20;
    if (crc32ieee(payload, size) != pcrc) {
      fatal = true;
      break;
    }
    pos += 20 + size;
    if (method == 100) {
      bool has = false;
      long long n = ingest_batch(e, payload, size, &batch_left, &has);
      if (n < 0) {
        emit(100, payload, size);  // foreign/unparseable: all to Python
      } else if (has) {
        emit(100, (const uint8_t*)batch_left.data(), batch_left.size());
      }
    } else {
      // snapshot chunks, poison, unknown: Python routes them
      emit(method, payload, size);
    }
  }
  if (fatal) emit(0xFFFF, nullptr, 0);
  // keep the unconsumed remainder for the next read
  std::string rest((const char*)buf + pos, blen - pos);
  cs->pending.swap(rest);
  // ingest-progress stamp for clock_pass's contact-loss cross-check —
  // written AFTER the frames were consumed, so a "live" reading implies
  // any heartbeat in this chunk already refreshed its group's contact
  e->last_ingest_ms.store(mono_ms(), std::memory_order_relaxed);
  return !fatal;
}

long long natr_ingest_stream(void* h, void* cstate, const uint8_t* d,
                             size_t len, uint8_t** leftover,
                             size_t* leftover_len) {
  Engine* e = (Engine*)h;
  ConnState* cs = (ConnState*)cstate;
  *leftover = nullptr;
  *leftover_len = 0;
  std::string out;
  bool ok = process_stream(e, cs, d, len,
                           [&](uint16_t method, const uint8_t* p, size_t n) {
                             out.push_back((char)(method >> 8));
                             out.push_back((char)(method & 0xFF));
                             put_u32le(out, (uint32_t)n);
                             if (n) out.append((const char*)p, n);
                           });
  if (!out.empty()) {
    *leftover = (uint8_t*)malloc(out.size());
    memcpy(*leftover, out.data(), out.size());
    *leftover_len = out.size();
  }
  return ok ? 0 : -1;
}

// ---- native connection readers: the whole inbound fast plane runs
// without the GIL.  tcp.py hands over plain (non-TLS) accepted sockets;
// a reader thread per connection recvs, reassembles and consumes frames;
// leftovers are queued for the Python leftover pump (fastlane.py), which
// routes them through the normal transport handlers.  This removes the
// Python recv glue from the hot path: with the GIL's scheduling quantum
// in the loop, inbound service was capped near the switch rate and the
// backlog sat invisibly in the kernel socket buffers (~hundreds of ms).
int natr_serve_fd(void* h, int fd) {
  Engine* e = (Engine*)h;
  struct timeval tv = {60, 0};
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  auto rd = std::make_shared<Engine::Reader>();
  rd->fd = fd;
  // registration + thread start are atomic against stop(): either stop
  // sees this reader (shuts it down and joins it) or we see stopped
  std::lock_guard<std::mutex> reg(e->readers_mu);
  if (e->stopped.load()) return -1;
  e->readers.push_back(rd);
  rd->th = std::thread([e, rd] {
    prctl(PR_SET_NAME, "natr-reader", 0, 0, 0);
    ConnState cs;
    std::vector<uint8_t> buf(256 << 10);
    uint64_t conn_id = (uint64_t)(uintptr_t)rd.get();
    auto emit = [e, conn_id](uint16_t method, const uint8_t* p, size_t n) {
      std::lock_guard<std::mutex> lk(e->lmu);
      e->leftq.push_back({method, conn_id, std::string((const char*)p, n)});
      e->lcv.notify_one();
    };
    while (!e->stopped.load()) {
      ssize_t n = recv(rd->fd, buf.data(), buf.size(), 0);
      if (n <= 0) break;
      if (!process_stream(e, &cs, buf.data(), (size_t)n, emit)) break;
    }
    std::lock_guard<std::mutex> lk(e->readers_mu);
    if (!rd->closed) {
      rd->closed = true;
      close(rd->fd);
    }
    // self-reap: without this, connection churn accumulates dead Reader
    // entries (and unjoined thread handles) until engine stop
    if (!e->readers_stopping) {
      rd->th.detach();
      auto& v = e->readers;
      for (auto it = v.begin(); it != v.end(); ++it) {
        if (it->get() == rd.get()) {
          v.erase(it);
          break;
        }
      }
    }
  });
  return 0;
}

// Attach a native sender to a remote slot: its thread owns a TCP
// connection to host:port, drains the slot's frame buffer with plain
// send(2), and reconnects with backoff on failure.
int natr_remote_connect(void* h, int slot, const char* host, int port) {
  Engine* e = (Engine*)h;
  if (slot < 0 || slot >= e->nremotes.load()) return -1;
  Remote* r = e->remotes[slot].get();
  std::lock_guard<std::mutex> reg(r->mu);
  if (r->sender.joinable() || r->closed) return -1;  // attached / stopping
  r->host = host;
  r->port = port;
  r->sender = std::thread([e, r] {
    prctl(PR_SET_NAME, "natr-sender", 0, 0, 0);
    int backoff_ms = 50;
    while (!e->stopped.load()) {
      // connect
      int fd = socket(AF_INET, SOCK_STREAM, 0);
      if (fd < 0) return;
      struct sockaddr_in sa;
      memset(&sa, 0, sizeof(sa));
      sa.sin_family = AF_INET;
      sa.sin_port = htons((uint16_t)r->port);
      if (inet_pton(AF_INET, r->host.c_str(), &sa.sin_addr) != 1 ||
          connect(fd, (struct sockaddr*)&sa, sizeof(sa)) != 0) {
        close(fd);
        struct timespec d = {backoff_ms / 1000,
                             (backoff_ms % 1000) * 1000000};
        nanosleep(&d, nullptr);
        backoff_ms = std::min(backoff_ms * 2, 1000);
        continue;
      }
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      {
        std::lock_guard<std::mutex> lk(r->mu);
        if (r->closed) {
          close(fd);
          return;
        }
        r->fd = fd;
      }
      backoff_ms = 50;
      bool broken = false;
      while (!e->stopped.load() && !broken) {
        std::string out;
        {
          std::unique_lock<std::mutex> lk(r->mu);
          if (r->buf.empty() && !r->closed)
            r->cv.wait_for(lk, std::chrono::milliseconds(200));
          if (r->closed) break;
          out.swap(r->buf);
        }
        size_t off = 0;
        while (off < out.size()) {
          ssize_t n = send(fd, out.data() + off, out.size() - off,
                           MSG_NOSIGNAL);
          if (n <= 0) {
            broken = true;
            break;
          }
          off += (size_t)n;
        }
      }
      bool was_closed;
      {
        std::lock_guard<std::mutex> lk(r->mu);
        r->fd = -1;
        // read under r->mu: stop() writes it there, and the naked read
        // here was the one data race a TSAN sweep found in the engine
        was_closed = r->closed;
      }
      close(fd);
      if (was_closed) return;
    }
  });
  return 0;
}

// Queue an already-encoded Message span onto a remote slot's outbound
// stream.  Used by the Python runtime for its scalar-path messages so a
// group's traffic rides ONE ordered stream per remote regardless of
// enrollment state (mixing two sockets across eject/re-enroll cycles
// reorders entries and forces gap ejects on the receiver).
int natr_send_msg(void* h, int slot, const uint8_t* payload, size_t len) {
  Engine* e = (Engine*)h;
  if (slot < 0 || slot >= e->nremotes.load()) return -1;
  e->queue_msg(slot, std::string((const char*)payload, len));
  // flushed by the next round pass (<= round_interval_ms away); nudge it
  std::lock_guard<std::mutex> lk(e->wmu);
  e->wcv.notify_one();
  return 0;
}

// Next leftover frame from the native readers; 1 filled, 0 timeout,
// -1 stopped.
int natr_next_leftover(void* h, int timeout_ms, int* method, uint8_t** data,
                       size_t* dlen, uint64_t* conn_id) {
  Engine* e = (Engine*)h;
  std::unique_lock<std::mutex> lk(e->lmu);
  if (e->leftq.empty() && !e->stopped.load())
    e->lcv.wait_for(lk, std::chrono::milliseconds(timeout_ms));
  if (e->leftq.empty()) return e->stopped.load() ? -1 : 0;
  auto fr = std::move(e->leftq.front());
  e->leftq.pop_front();
  *method = fr.method;
  *conn_id = fr.conn_id;
  *data = (uint8_t*)malloc(fr.payload.size() ? fr.payload.size() : 1);
  memcpy(*data, fr.payload.data(), fr.payload.size());
  *dlen = fr.payload.size();
  return 1;
}

// Shut down a native-owned inbound connection (e.g. a failed snapshot
// stream must close so the sender observes the failure).  conn_id comes
// from natr_next_leftover; a stale id is a harmless no-op.
void natr_close_conn(void* h, uint64_t conn_id) {
  Engine* e = (Engine*)h;
  std::lock_guard<std::mutex> lk(e->readers_mu);
  for (auto& rd : e->readers) {
    if ((uint64_t)(uintptr_t)rd.get() == conn_id && !rd->closed) {
      shutdown(rd->fd, SHUT_RDWR);
      return;
    }
  }
}

// Take ready-to-send frames for a remote slot; blocks up to timeout_ms.
// Returns byte length (0 = timeout, -1 = stopped); *data is malloc'd.
long long natr_take_send(void* h, int slot, int timeout_ms, uint8_t** data) {
  Engine* e = (Engine*)h;
  *data = nullptr;
  if (slot < 0 || slot >= (int)e->remotes.size()) return -1;
  Remote* r = e->remotes[slot].get();
  std::unique_lock<std::mutex> lk(r->mu);
  if (r->buf.empty() && !r->closed)
    r->cv.wait_for(lk, std::chrono::milliseconds(timeout_ms));
  if (r->buf.empty()) return r->closed ? -1 : 0;
  *data = (uint8_t*)malloc(r->buf.size());
  memcpy(*data, r->buf.data(), r->buf.size());
  long long n = (long long)r->buf.size();
  r->buf.clear();
  return n;
}

// Take the next apply span; blocks up to timeout_ms.  Blob is an
// encode_entry_batch payload (decode with wire.codec.decode_entry_batch).
// Returns 1 with outputs set, 0 on timeout, -1 when stopped.
int natr_next_apply(void* h, int timeout_ms, uint64_t* cid, uint64_t* first,
                    uint64_t* last, uint8_t** data, size_t* dlen) {
  Engine* e = (Engine*)h;
  std::unique_lock<std::mutex> lk(e->amu);
  if (e->applyq.empty() && !e->stopped.load())
    e->acv.wait_for(lk, std::chrono::milliseconds(timeout_ms));
  if (e->applyq.empty()) return e->stopped.load() ? -1 : 0;
  ApplySpan s = std::move(e->applyq.front());
  e->applyq.pop_front();
  *cid = s.cid;
  *first = s.first;
  *last = s.last;
  *data = (uint8_t*)malloc(s.blob.size());
  memcpy(*data, s.blob.data(), s.blob.size());
  *dlen = s.blob.size();
  return 1;
}

// Next native-initiated eject event.  Returns 1/0/-1 like natr_next_apply.
int natr_next_event(void* h, int timeout_ms, uint64_t* cid, int* code) {
  Engine* e = (Engine*)h;
  std::unique_lock<std::mutex> lk(e->emu);
  if (e->eventq.empty() && !e->stopped.load())
    e->ecv.wait_for(lk, std::chrono::milliseconds(timeout_ms));
  if (e->eventq.empty()) return e->stopped.load() ? -1 : 0;
  auto ev = e->eventq.front();
  e->eventq.pop_front();
  *cid = ev.first;
  *code = ev.second;
  return 1;
}

// Eject: finalize the group and return the state snapshot Python needs to
// rebuild the scalar raft object.  Any locally appended but not yet
// fsynced entries are synchronously persisted here (nkv_commit is
// thread-safe; double-writing a key the round thread also staged is
// idempotent).  Remaining committed-but-unhanded entries are returned in
// *apply_blob -- including any spans still sitting in the apply queue --
// so the caller can enqueue them under raftMu in order.
// Returns 0 on success, -1 unknown group.
int natr_eject(void* h, uint64_t cid, uint64_t* term, uint64_t* vote,
               uint64_t* leader_id, uint64_t* commit, uint64_t* last_index,
               uint64_t* applied_handed, uint64_t* peer_match,
               uint64_t* peer_next, int* npeers, uint8_t** apply_blob,
               size_t* apply_len, uint64_t* apply_first) {
  Engine* e = (Engine*)h;
  std::shared_ptr<Group> sp = e->find(cid);
  Group* g = sp.get();
  if (!g) return -1;
  std::string pending_blob;
  uint64_t pending_first = 0, pending_count = 0;
  {
    std::unique_lock<std::mutex> lk(g->mu);
    // an in-flight consistent capture serializes the SM off g->mu with
    // applies frozen; handing pending applies to the Python plane now
    // would let them mutate the SM mid-serialization and tear the image
    while (g->capturing) g->capture_cv.wait(lk);
    if (g->state == G_GONE) return -1;
    g->state = G_EJECTING;
    // flush un-persisted tail synchronously
    if (g->last_index > g->fsynced) {
      std::string b;
      for (uint64_t i = g->fsynced + 1; i <= g->last_index; i++)
        batch_put(b, make_key(TAG_ENTRY, g->cid, g->nid, i),
                  g->log[i - g->log_first].enc);
      std::string v;
      put_u64be(v, g->last_index);
      batch_put(b, make_key(TAG_MAX_INDEX, g->cid, g->nid, 0), v);
      int rc = e->nkv_commit(e->shards[g->shard]->handle,
                             (const uint8_t*)b.data(), b.size());
      if (rc < 0) return -2;
      g->staged_to = g->fsynced = g->last_index;
      g->maxindex_written = g->last_index;
    }
    // final tally so committed-by-quorum entries are not lost (leader)
    if (g->leader) {
      uint64_t q = e->tally(g);
      if (q > g->commit) g->commit = q;
    }
    // drain spans already queued for the pump (keep order) + the rest
    {
      std::lock_guard<std::mutex> alk(e->amu);
      for (auto it = e->applyq.begin(); it != e->applyq.end();) {
        if (it->cid != cid) {
          ++it;
          continue;
        }
        if (!pending_count) pending_first = it->first;
        // strip the per-span count varint; re-counted below
        size_t p = 0;
        uint64_t c;
        get_uvarint((const uint8_t*)it->blob.data(), it->blob.size(), p, c);
        pending_blob.append(it->blob, p, std::string::npos);
        pending_count += c;
        it = e->applyq.erase(it);
      }
    }
    uint64_t upto = std::min(g->commit, g->fsynced);
    if (upto > g->applied_handed) {
      if (!pending_count) pending_first = g->applied_handed + 1;
      for (uint64_t i = g->applied_handed + 1; i <= upto; i++) {
        pending_blob += g->log[i - g->log_first].enc;
        pending_count++;
      }
      g->applied_handed = upto;
    }
    *term = g->term;
    *vote = g->vote;
    *leader_id = g->leader_id;
    *commit = g->commit;
    *last_index = g->last_index;
    *applied_handed = g->applied_handed;
    int n = 0;
    for (auto& p : g->peers) {
      peer_match[n] = p.match;
      peer_next[n] = p.next;
      n++;
    }
    *npeers = n;
    g->state = G_GONE;
  }
  std::string out;
  put_uvarint(out, pending_count);
  out += pending_blob;
  *apply_blob = (uint8_t*)malloc(out.size() ? out.size() : 1);
  memcpy(*apply_blob, out.data(), out.size());
  *apply_len = out.size();
  *apply_first = pending_first;
  {
    std::lock_guard<std::mutex> lk(e->gmu);
    e->groups.erase(cid);
  }
  return 0;
}

// Leader-side ReadIndex: record the context and broadcast an immediate
// hinted heartbeat (raft.go:1636 handleLeaderReadIndex + thesis 6.4).
// Returns the recorded commit index (>0) or 0 when not serving (caller
// falls back to the scalar protocol, which ejects the group).
uint64_t natr_read_index(void* h, uint64_t cid, uint64_t low, uint64_t high) {
  Engine* e = (Engine*)h;
  std::shared_ptr<Group> sp = e->find(cid);
  Group* g = sp.get();
  if (!g || low == 0) return 0;
  std::lock_guard<std::mutex> lk(g->mu);
  if (g->state != G_ACTIVE) return 0;
  if (!e->reg_read(g, low, high, 0)) return 0;
  return g->commit;
}

// Forward a linearizable read from an enrolled FOLLOWER to its leader
// (scalar twin: handle_follower_read_index raft.py:1258).  Returns 1 when
// the forward went out natively — the confirmation arrives as
// MT_READ_INDEX_RESP and completes through natr_next_read — else 0 and
// the caller falls back to the scalar path (eject).
int natr_read_fwd(void* h, uint64_t cid, uint64_t low, uint64_t high) {
  Engine* e = (Engine*)h;
  std::shared_ptr<Group> sp = e->find(cid);
  Group* g = sp.get();
  if (!g || low == 0) return 0;
  std::lock_guard<std::mutex> lk(g->mu);
  if (g->state != G_ACTIVE) return 0;
  return e->fwd_read(g, g->nid, low, high) ? 1 : 0;
}

// Next confirmed read context; 1 filled, 0 timeout, -1 stopped.
int natr_next_read(void* h, int timeout_ms, uint64_t* cid, uint64_t* low,
                   uint64_t* high, uint64_t* index) {
  Engine* e = (Engine*)h;
  std::unique_lock<std::mutex> lk(e->rmu);
  if (e->readyq.empty() && !e->stopped.load())
    e->rcv.wait_for(lk, std::chrono::milliseconds(timeout_ms));
  if (e->readyq.empty()) return e->stopped.load() ? -1 : 0;
  auto rr = e->readyq.front();
  e->readyq.pop_front();
  *cid = rr.cid;
  *low = rr.low;
  *high = rr.high;
  *index = rr.index;
  return 1;
}

// Lightweight status probe: 1 = enrolled-active, 0 = not.
int natr_active(void* h, uint64_t cid) {
  Engine* e = (Engine*)h;
  std::shared_ptr<Group> g = e->find(cid);
  if (!g) return 0;
  std::lock_guard<std::mutex> lk(g->mu);
  return g->state == G_ACTIVE ? 1 : 0;
}

// Wait for the apply queue to become non-empty WITHOUT popping — the
// Python apply pump blocks here, then drains with non-blocking
// natr_next_apply calls under its ordering gate so an eject can atomically
// take over the remaining spans.  Returns 1 ready, 0 timeout, -1 stopped.
int natr_wait_apply(void* h, int timeout_ms) {
  Engine* e = (Engine*)h;
  std::unique_lock<std::mutex> lk(e->amu);
  if (e->applyq.empty() && !e->stopped.load())
    e->acv.wait_for(lk, std::chrono::milliseconds(timeout_ms));
  if (e->stopped.load()) return -1;
  return e->applyq.empty() ? 0 : 1;
}

void natr_stats(void* h, uint64_t* out12) {  // array of 25 u64
  Engine* e = (Engine*)h;
  out12[0] = e->proposed.load();
  out12[1] = e->ingested_fast.load();
  out12[2] = e->ingested_slow.load();
  out12[3] = e->commits_advanced.load();
  out12[4] = e->rounds.load();
  out12[5] = e->fsyncs.load();
  uint64_t dropped = 0;
  for (auto& r : e->remotes) dropped += r->dropped;
  out12[6] = dropped;
  {
    std::lock_guard<std::mutex> lk(e->gmu);
    out12[7] = e->groups.size();
  }
  out12[8] = e->fsync_ns.load();
  out12[9] = e->round_ns.load();
  out12[10] = e->entries_staged.load();
  uint64_t n = e->lat_count.load();
  uint64_t nf = e->lat_countf.load();
  uint64_t ns = std::max(1ul, (unsigned long)e->entries_staged.load());
  uint64_t ntot = std::max(1ul, (unsigned long)(n + nf));
  out12[11] = n ? (e->lat_emit_us.load() / n) : 0;
  out12[12] = e->lat_stage_us.load() / ns;
  out12[13] = e->lat_fsync_us.load() / ntot;
  out12[14] = nf ? (e->lat_emitf_us.load() / nf) : 0;
  out12[15] = e->buf_hiwater.load();
  uint64_t na = e->lat_ackn.load();
  out12[16] = na ? (e->lat_ack_us.load() / na) : 0;
  uint64_t nr = e->lat_respn.load();
  out12[17] = nr ? (e->lat_resp_us.load() / nr) : 0;
  uint64_t nrt = e->rttn.load();
  out12[18] = nrt ? (e->rtt_us.load() / nrt) : 0;
  out12[19] = e->rtt_max_us.load();
  out12[20] = e->stale_dropped.load();
  out12[21] = e->part_in_dropped.load();   // partition-dropped inbound msgs
  out12[22] = e->part_out_dropped.load();  // partition-dropped outbound msgs
  out12[23] = (e->clock_stalls.load() << 32) | (e->clock_stall_ms.load() & 0xffffffffu);
  out12[24] = e->contact_ejects_deferred.load();
}

void natr_set_debug_cid(void* h, uint64_t cid) {
  ((Engine*)h)->debug_cid.store(cid);
}

long long natr_debug_dump(void* h, uint8_t** data) {
  Engine* e = (Engine*)h;
  std::lock_guard<std::mutex> lk(e->dbg_mu);
  *data = (uint8_t*)malloc(e->dbg.size() ? e->dbg.size() : 1);
  memcpy(*data, e->dbg.data(), e->dbg.size());
  long long n = (long long)e->dbg.size();
  e->dbg.clear();
  return n;
}

void natr_set_commit_window(void* h, int64_t us) {
  Engine* e = (Engine*)h;
  e->commit_window_us.store(us);
}

void natr_stop(void* h) {
  Engine* e = (Engine*)h;
  e->stop();
}

}  // extern "C"
