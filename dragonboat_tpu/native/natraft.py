"""ctypes binding for the native replication fast-lane core (natraft.cpp).

One :class:`NatRaft` per NodeHost.  See the C++ header comment for the
architecture; the Python-facing surface here is deliberately thin — raw
buffers in/out, with all object mapping done by the fast-lane manager
(:mod:`dragonboat_tpu.fastlane`).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Tuple

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_DIR = os.environ.get("DBTPU_NATIVE_LIB_DIR") or _DIR  # see native/__init__.py
_SO = os.path.join(_LIB_DIR, "libnatraft.so")
_SRC = os.path.join(_DIR, "natraft.cpp")
_NKV_SO = os.path.join(_LIB_DIR, "libnativekv.so")

_lib = None
_lib_mu = threading.Lock()
_build_error: Optional[str] = None


def _load():
    global _lib, _build_error
    with _lib_mu:
        if _lib is not None:
            return _lib
        if _build_error is not None:
            raise RuntimeError(_build_error)
        # override dirs are load-only (see native/__init__.py)
        if _LIB_DIR == _DIR and (
            not os.path.exists(_SO)
            or os.path.getmtime(_SO) < os.path.getmtime(_SRC)
        ):
            proc = subprocess.run(
                ["make", "-C", _DIR, "libnatraft.so"],
                capture_output=True,
                text=True,
            )
            if proc.returncode != 0:
                _build_error = f"natraft build failed:\n{proc.stderr}"
                raise RuntimeError(_build_error)
        lib = ctypes.CDLL(_SO)
        c = ctypes
        lib.natr_create.restype = c.c_void_p
        lib.natr_create.argtypes = [
            c.c_char_p, c.c_uint64, c.c_uint64, c.c_char_p, c.c_char_p,
            c.c_size_t,
        ]
        lib.natr_start.argtypes = [c.c_void_p]
        lib.natr_destroy.argtypes = [c.c_void_p]
        lib.natr_stop.argtypes = [c.c_void_p]
        lib.natr_free.argtypes = [c.c_void_p]
        lib.natr_set_shards.argtypes = [
            c.c_void_p, c.POINTER(c.c_void_p), c.c_int,
        ]
        lib.natr_add_remote.restype = c.c_int
        lib.natr_add_remote.argtypes = [c.c_void_p]
        lib.natr_enroll.restype = c.c_int
        lib.natr_enroll.argtypes = [
            c.c_void_p, c.c_uint64, c.c_uint64, c.c_uint64, c.c_uint64,
            c.c_uint64, c.c_int, c.c_uint64, c.c_uint64, c.c_uint64,
            c.c_uint64, c.c_uint64, c.c_uint32, c.c_int64, c.c_int64,
            c.c_int,
            c.POINTER(c.c_uint64), c.POINTER(c.c_int32),
            c.POINTER(c.c_uint64), c.POINTER(c.c_uint64),
            c.POINTER(c.c_int32), c.c_int,
            c.c_char_p, c.c_size_t,
        ]
        lib.natr_propose.restype = c.c_uint64
        lib.natr_propose.argtypes = [
            c.c_void_p, c.c_uint64, c.c_uint64, c.c_uint64, c.c_uint64,
            c.c_uint64, c.c_uint8, c.c_char_p, c.c_size_t,
        ]
        lib.natr_propose_batch.restype = c.c_uint64
        lib.natr_propose_batch.argtypes = [
            c.c_void_p, c.c_uint64, c.c_int, c.POINTER(c.c_uint64),
            c.c_uint64, c.c_uint64, c.c_uint64, c.c_uint8, c.c_char_p,
            c.c_size_t,
        ]
        lib.natr_ingest.restype = c.c_longlong
        lib.natr_ingest.argtypes = [
            c.c_void_p, c.c_char_p, c.c_size_t, c.POINTER(c.c_void_p),
            c.POINTER(c.c_size_t),
        ]
        lib.natr_take_send.restype = c.c_longlong
        lib.natr_take_send.argtypes = [
            c.c_void_p, c.c_int, c.c_int, c.POINTER(c.c_void_p),
        ]
        lib.natr_next_apply.restype = c.c_int
        lib.natr_next_apply.argtypes = [
            c.c_void_p, c.c_int, c.POINTER(c.c_uint64), c.POINTER(c.c_uint64),
            c.POINTER(c.c_uint64), c.POINTER(c.c_void_p), c.POINTER(c.c_size_t),
        ]
        lib.natr_wait_apply.restype = c.c_int
        lib.natr_wait_apply.argtypes = [c.c_void_p, c.c_int]
        lib.natr_next_event.restype = c.c_int
        lib.natr_next_event.argtypes = [
            c.c_void_p, c.c_int, c.POINTER(c.c_uint64), c.POINTER(c.c_int),
        ]
        lib.natr_eject.restype = c.c_int
        lib.natr_eject.argtypes = [
            c.c_void_p, c.c_uint64,
            c.POINTER(c.c_uint64), c.POINTER(c.c_uint64),  # term, vote
            c.POINTER(c.c_uint64), c.POINTER(c.c_uint64),  # leader, commit
            c.POINTER(c.c_uint64), c.POINTER(c.c_uint64),  # last, handed
            c.POINTER(c.c_uint64), c.POINTER(c.c_uint64),  # match[], next[]
            c.POINTER(c.c_int),                            # npeers
            c.POINTER(c.c_void_p), c.POINTER(c.c_size_t),  # blob
            c.POINTER(c.c_uint64),                         # apply_first
        ]
        lib.natr_read_index.restype = c.c_uint64
        lib.natr_read_index.argtypes = [
            c.c_void_p, c.c_uint64, c.c_uint64, c.c_uint64,
        ]
        lib.natr_read_fwd.restype = c.c_int
        lib.natr_read_fwd.argtypes = [
            c.c_void_p, c.c_uint64, c.c_uint64, c.c_uint64,
        ]
        lib.natr_next_read.restype = c.c_int
        lib.natr_next_read.argtypes = [
            c.c_void_p, c.c_int, c.POINTER(c.c_uint64), c.POINTER(c.c_uint64),
            c.POINTER(c.c_uint64), c.POINTER(c.c_uint64),
        ]
        lib.natr_active.restype = c.c_int
        lib.natr_active.argtypes = [c.c_void_p, c.c_uint64]
        lib.natr_set_commit_window.argtypes = [c.c_void_p, c.c_int64]
        lib.natr_set_partition.argtypes = [
            c.c_void_p, c.c_char_p, c.c_int, c.c_int
        ]
        lib.natr_conn_new.restype = c.c_void_p
        lib.natr_conn_new.argtypes = [c.c_void_p]
        lib.natr_conn_free.argtypes = [c.c_void_p, c.c_void_p]
        lib.natr_ingest_stream.restype = c.c_longlong
        lib.natr_ingest_stream.argtypes = [
            c.c_void_p, c.c_void_p, c.c_char_p, c.c_size_t,
            c.POINTER(c.c_void_p), c.POINTER(c.c_size_t),
        ]
        lib.natr_serve_fd.restype = c.c_int
        lib.natr_serve_fd.argtypes = [c.c_void_p, c.c_int]
        lib.natr_remote_connect.restype = c.c_int
        lib.natr_remote_connect.argtypes = [
            c.c_void_p, c.c_int, c.c_char_p, c.c_int,
        ]
        lib.natr_next_leftover.restype = c.c_int
        lib.natr_next_leftover.argtypes = [
            c.c_void_p, c.c_int, c.POINTER(c.c_int), c.POINTER(c.c_void_p),
            c.POINTER(c.c_size_t), c.POINTER(c.c_uint64),
        ]
        lib.natr_close_conn.argtypes = [c.c_void_p, c.c_uint64]
        lib.natr_send_msg.restype = c.c_int
        lib.natr_send_msg.argtypes = [
            c.c_void_p, c.c_int, c.c_char_p, c.c_size_t,
        ]
        lib.natr_stats.argtypes = [c.c_void_p, c.POINTER(c.c_uint64)]
        lib.natr_attach_sm.restype = c.c_int
        lib.natr_attach_sm.argtypes = [
            c.c_void_p, c.c_uint64, c.c_void_p, c.c_void_p, c.c_uint64,
            c.c_void_p, c.c_void_p, c.c_void_p, c.c_void_p,
        ]
        lib.natr_capture_sm.restype = c.c_longlong
        lib.natr_capture_sm.argtypes = [
            c.c_void_p, c.c_uint64, c.POINTER(c.POINTER(c.c_uint8)),
        ]
        lib.natr_note_applied.argtypes = [c.c_void_p, c.c_uint64, c.c_uint64]
        lib.natr_next_completions.restype = c.c_longlong
        lib.natr_next_completions.argtypes = [
            c.c_void_p, c.c_int,
            c.POINTER(c.c_uint64), c.POINTER(c.c_uint64),
            c.POINTER(c.c_uint64), c.POINTER(c.c_uint64),
            c.POINTER(c.c_uint64), c.POINTER(c.c_uint64),
            c.POINTER(c.c_uint64), c.POINTER(c.c_uint64),
            c.POINTER(c.c_uint8), c.POINTER(c.c_uint8), c.c_longlong,
        ]
        lib.natr_take_payload.restype = c.c_longlong
        lib.natr_take_payload.argtypes = [
            c.c_void_p, c.c_uint64, c.POINTER(c.c_uint8), c.c_longlong,
        ]
        _lib = lib
        return lib


def available() -> bool:
    try:
        _load()
        return True
    except (RuntimeError, OSError):
        return False


class EjectState:
    __slots__ = (
        "term", "vote", "leader_id", "commit", "last_index",
        "applied_handed", "peers", "apply_blob", "apply_first",
    )

    def __init__(self, term, vote, leader_id, commit, last_index,
                 applied_handed, peers, apply_blob, apply_first):
        self.term = term
        self.vote = vote
        self.leader_id = leader_id
        self.commit = commit
        self.last_index = last_index
        self.applied_handed = applied_handed
        self.peers = peers  # list of (match, next) aligned with enroll order
        self.apply_blob = apply_blob  # encode_entry_batch payload
        self.apply_first = apply_first


class NatRaft:
    """One native replication core (per NodeHost)."""

    def __init__(self, source_address: str, deployment_id: int,
                 bin_ver: int = 1):
        lib = _load()
        errbuf = ctypes.create_string_buffer(512)
        self._h = lib.natr_create(
            source_address.encode(), deployment_id, bin_ver,
            _NKV_SO.encode(), errbuf, len(errbuf),
        )
        if not self._h:
            raise RuntimeError(f"natraft init: {errbuf.value.decode()}")
        self._lib = lib
        self._peer_order: dict = {}  # cid -> peer id order used at enroll
        self._stopped = False
        # guards the reused take_payload scratch buffer: the completion
        # pump is the designed single caller, but the discard path for
        # removed clusters (fastlane._completion_main) and any future
        # caller outside _compl_mu must not interleave reads of one
        # shared buffer (ISSUE 1 satellite)
        self._pay_mu = threading.Lock()

    def start(self) -> None:
        self._lib.natr_start(self._h)

    def set_shards(self, handles: List[int]) -> None:
        arr = (ctypes.c_void_p * len(handles))(*handles)
        self._lib.natr_set_shards(self._h, arr, len(handles))

    def add_remote(self) -> int:
        return int(self._lib.natr_add_remote(self._h))

    def enroll(
        self,
        cluster_id: int,
        node_id: int,
        term: int,
        vote: int,
        leader_id: int,
        is_leader: bool,
        last_index: int,
        commit: int,
        processed: int,
        log_first: int,
        prev_term: int,
        shard: int,
        hb_period_ms: int,
        elect_timeout_ms: int,
        term_commit_ok: bool,
        # (id, slot, match, next[, role]) — role defaults voter (1);
        # observers (nonVoting members) pass 0/False: replicate and
        # heartbeat, no quorum weight; witnesses pass 2: vote and ack,
        # receive metadata-only entries
        peers: List[Tuple],
        tail: bytes,  # concatenated encodings of (log_first..last_index]
    ) -> bool:
        ids = (ctypes.c_uint64 * len(peers))(*[p[0] for p in peers])
        slots = (ctypes.c_int32 * len(peers))(*[p[1] for p in peers])
        match = (ctypes.c_uint64 * len(peers))(*[p[2] for p in peers])
        nxt = (ctypes.c_uint64 * len(peers))(*[p[3] for p in peers])
        voting = (ctypes.c_int32 * len(peers))(
            *[1 if len(p) < 5 else int(p[4]) for p in peers]
        )
        rc = self._lib.natr_enroll(
            self._h, cluster_id, node_id, term, vote, leader_id,
            1 if is_leader else 0, last_index, commit, processed, log_first,
            prev_term, shard, hb_period_ms, elect_timeout_ms,
            1 if term_commit_ok else 0, ids, slots,
            match, nxt, voting, len(peers), tail, len(tail),
        )
        if rc == 0:
            self._peer_order[cluster_id] = [p[0] for p in peers]
        return rc == 0

    def propose(self, cluster_id: int, key: int, client_id: int,
                series_id: int, responded_to: int, etype: int,
                cmd: bytes) -> int:
        """Returns the assigned index, or 0 (not enrolled / ejecting)."""
        return int(
            self._lib.natr_propose(
                self._h, cluster_id, key, client_id, series_id, responded_to,
                etype, cmd, len(cmd),
            )
        )

    def propose_batch(self, cluster_id: int, keys: List[int], client_id: int,
                      series_id: int, responded_to: int, etype: int,
                      cmds_blob: bytes) -> int:
        """Append a burst of entries atomically (cmds_blob: u32le-length-
        prefixed commands, one per key).  Returns the first assigned index
        or 0 (caller falls back for the whole batch)."""
        arr = (ctypes.c_uint64 * len(keys))(*keys)
        return int(
            self._lib.natr_propose_batch(
                self._h, cluster_id, len(keys), arr, client_id, series_id,
                responded_to, etype, cmds_blob, len(cmds_blob),
            )
        )

    def ingest(self, payload: bytes) -> Tuple[int, Optional[bytes]]:
        """Returns (consumed_count, leftover_batch_payload_or_None).
        consumed < 0 means a parse error: treat the payload as leftover."""
        out = ctypes.c_void_p()
        outlen = ctypes.c_size_t()
        n = self._lib.natr_ingest(
            self._h, payload, len(payload), ctypes.byref(out),
            ctypes.byref(outlen),
        )
        if n < 0:
            return -1, payload
        leftover = None
        if out.value:
            leftover = ctypes.string_at(out.value, outlen.value)
            self._lib.natr_free(out)
        return int(n), leftover

    def take_send(self, slot: int, timeout_ms: int = 100) -> Optional[bytes]:
        """Blocks (GIL released) for ready frames; None on timeout,
        raises on shutdown."""
        data = ctypes.c_void_p()
        n = self._lib.natr_take_send(self._h, slot, timeout_ms,
                                     ctypes.byref(data))
        if n < 0:
            raise ConnectionError("natraft stopped")
        if n == 0:
            return None
        buf = ctypes.string_at(data.value, n)
        self._lib.natr_free(data)
        return buf

    def next_apply(self, timeout_ms: int = 100):
        """Returns (cluster_id, first, last, blob) or None; raises on stop."""
        cid = ctypes.c_uint64()
        first = ctypes.c_uint64()
        last = ctypes.c_uint64()
        data = ctypes.c_void_p()
        dlen = ctypes.c_size_t()
        rc = self._lib.natr_next_apply(
            self._h, timeout_ms, ctypes.byref(cid), ctypes.byref(first),
            ctypes.byref(last), ctypes.byref(data), ctypes.byref(dlen),
        )
        if rc < 0:
            raise ConnectionError("natraft stopped")
        if rc == 0:
            return None
        blob = ctypes.string_at(data.value, dlen.value)
        self._lib.natr_free(data)
        return int(cid.value), int(first.value), int(last.value), blob

    def wait_apply(self, timeout_ms: int = 100) -> bool:
        """Block until the apply queue is non-empty (no pop).  Raises on
        shutdown."""
        rc = self._lib.natr_wait_apply(self._h, timeout_ms)
        if rc < 0:
            raise ConnectionError("natraft stopped")
        return rc == 1

    def next_event(self, timeout_ms: int = 100):
        """Returns (cluster_id, code) or None; raises on stop."""
        cid = ctypes.c_uint64()
        code = ctypes.c_int()
        rc = self._lib.natr_next_event(
            self._h, timeout_ms, ctypes.byref(cid), ctypes.byref(code)
        )
        if rc < 0:
            raise ConnectionError("natraft stopped")
        if rc == 0:
            return None
        return int(cid.value), int(code.value)

    def eject(self, cluster_id: int) -> Optional[EjectState]:
        c = ctypes
        term = c.c_uint64()
        vote = c.c_uint64()
        leader = c.c_uint64()
        commit = c.c_uint64()
        last = c.c_uint64()
        handed = c.c_uint64()
        match = (c.c_uint64 * 16)()
        nxt = (c.c_uint64 * 16)()
        npeers = c.c_int()
        blob = c.c_void_p()
        blen = c.c_size_t()
        afirst = c.c_uint64()
        rc = self._lib.natr_eject(
            self._h, cluster_id, c.byref(term), c.byref(vote), c.byref(leader),
            c.byref(commit), c.byref(last), c.byref(handed), match, nxt,
            c.byref(npeers), c.byref(blob), c.byref(blen), c.byref(afirst),
        )
        if rc == -2:
            # the synchronous WAL tail flush failed: the native log holds
            # appended entries that never reached disk and the group is
            # stuck EJECTING — the caller must treat the replica as failed
            # (resuming scalar raft on pre-enroll state would reuse
            # already-persisted indices)
            raise IOError(f"fast-lane eject of group {cluster_id}: WAL flush failed")
        if rc != 0:
            return None
        apply_blob = ctypes.string_at(blob.value, blen.value)
        self._lib.natr_free(blob)
        order = self._peer_order.pop(cluster_id, [])
        peers = {
            order[i]: (int(match[i]), int(nxt[i]))
            for i in range(npeers.value)
            if i < len(order)
        }
        return EjectState(
            int(term.value), int(vote.value), int(leader.value),
            int(commit.value), int(last.value), int(handed.value), peers,
            apply_blob, int(afirst.value),
        )

    def read_index(self, cluster_id: int, low: int, high: int) -> int:
        """Stage a leader-side ReadIndex; returns the recorded commit
        index (>0) or 0 when the group is not natively serving."""
        return int(
            self._lib.natr_read_index(self._h, cluster_id, low, high)
        )

    def read_fwd(self, cluster_id: int, low: int, high: int) -> bool:
        """Forward a follower-side ReadIndex to the leader natively;
        False when the group cannot forward (caller falls back to the
        scalar path)."""
        return bool(
            self._lib.natr_read_fwd(self._h, cluster_id, low, high)
        )

    def next_read(self, timeout_ms: int = 200):
        """Next quorum-confirmed read ctx: (cid, low, high, index)."""
        cid = ctypes.c_uint64()
        low = ctypes.c_uint64()
        high = ctypes.c_uint64()
        index = ctypes.c_uint64()
        rc = self._lib.natr_next_read(
            self._h, timeout_ms, ctypes.byref(cid), ctypes.byref(low),
            ctypes.byref(high), ctypes.byref(index),
        )
        if rc < 0:
            raise ConnectionError("natraft stopped")
        if rc == 0:
            return None
        return int(cid.value), int(low.value), int(high.value), int(index.value)

    def active(self, cluster_id: int) -> bool:
        return bool(self._lib.natr_active(self._h, cluster_id))

    def conn_new(self) -> int:
        return self._lib.natr_conn_new(self._h)

    def conn_free(self, conn: int) -> None:
        self._lib.natr_conn_free(self._h, conn)

    def ingest_stream(self, conn: int, data: bytes):
        """Feed raw TCP bytes; returns a list of (method, payload) leftover
        frames for Python routing.  method 0xFFFF = framing/CRC error, the
        connection must be closed."""
        out = ctypes.c_void_p()
        outlen = ctypes.c_size_t()
        self._lib.natr_ingest_stream(
            self._h, conn, data, len(data), ctypes.byref(out),
            ctypes.byref(outlen),
        )
        frames = []
        if out.value:
            buf = ctypes.string_at(out.value, outlen.value)
            self._lib.natr_free(out)
            pos = 0
            import struct as _struct

            while pos < len(buf):
                method = (buf[pos] << 8) | buf[pos + 1]
                (n,) = _struct.unpack_from("<I", buf, pos + 2)
                pos += 6
                frames.append((method, buf[pos : pos + n]))
                pos += n
        return frames

    def remote_connect(self, slot: int, host: str, port: int) -> bool:
        """Attach a native sender thread (own TCP connection + reconnect)
        to a remote slot.  IPv4 literal hosts only."""
        return (
            self._lib.natr_remote_connect(self._h, slot, host.encode(), port)
            == 0
        )

    def serve_fd(self, fd: int) -> bool:
        """Hand a connected socket fd to a native reader thread (ownership
        transfers; native closes it).  False when stopped."""
        return self._lib.natr_serve_fd(self._h, fd) == 0

    def next_leftover(self, timeout_ms: int = 200):
        """Next leftover frame from native readers:
        (method, payload, conn_id); None on timeout; raises on stop."""
        method = ctypes.c_int()
        data = ctypes.c_void_p()
        dlen = ctypes.c_size_t()
        conn = ctypes.c_uint64()
        rc = self._lib.natr_next_leftover(
            self._h, timeout_ms, ctypes.byref(method), ctypes.byref(data),
            ctypes.byref(dlen), ctypes.byref(conn),
        )
        if rc < 0:
            raise ConnectionError("natraft stopped")
        if rc == 0:
            return None
        payload = ctypes.string_at(data.value, dlen.value)
        self._lib.natr_free(data)
        return int(method.value), payload, int(conn.value)

    def send_msg(self, slot: int, payload: bytes) -> bool:
        return self._lib.natr_send_msg(self._h, slot, payload, len(payload)) == 0

    # ---- native C-ABI state machine (natsm.cpp) ----

    def attach_sm(
        self, cid: int, sm_handle: int, update_fn: int, py_applied: int,
        sess_handle: int = 0, sess_apply_fn: int = 0,
        sm_save_fn: int = 0, sess_save_fn: int = 0,
    ) -> bool:
        """Attach a native SM to an enrolled group; committed application
        entries then apply in C++ with only batched completion records
        crossing the GIL.  With a session store handle (natsm.cpp
        SessStore + its ``natsm_sess_apply`` pointer), session-managed
        entries apply natively too — exactly-once dedup included.  The
        save pointers (``natsm_save`` / ``natsm_sess_save``) enable
        :meth:`capture_sm` — snapshots without ejecting the group."""
        return (
            self._lib.natr_attach_sm(
                self._h, cid, sm_handle, update_fn, py_applied,
                sess_handle, sess_apply_fn, sm_save_fn, sess_save_fn,
            )
            == 1
        )

    def capture_sm(self, cid: int):
        """Consistent snapshot of an enrolled group's attached native SM,
        taken under the group mutex at exactly the native applied index.
        Returns ``(index, term, kv_image, session_image)`` or ``None``
        when the group cannot be captured (not enrolled / not attached /
        apply barrier still in flight) — callers fall back to the
        eject-based snapshot path."""
        out = ctypes.POINTER(ctypes.c_uint8)()
        n = self._lib.natr_capture_sm(self._h, cid, ctypes.byref(out))
        if n < 0:
            return None
        try:
            blob = bytes(ctypes.string_at(out, n))
        finally:
            self._lib.natr_free(ctypes.cast(out, ctypes.c_void_p))
        from ..wire.codec import _read_uvarint

        pos = 0
        index, pos = _read_uvarint(blob, pos)
        term, pos = _read_uvarint(blob, pos)
        kvn, pos = _read_uvarint(blob, pos)
        kv = blob[pos:pos + kvn]
        pos += kvn
        ssn, pos = _read_uvarint(blob, pos)
        sess = blob[pos:pos + ssn]
        return int(index), int(term), kv, sess

    def note_applied(self, cid: int, applied: int) -> None:
        """Report Python-plane apply progress (lifts the attach barrier)."""
        self._lib.natr_note_applied(self._h, cid, applied)

    _COMPL_CAP = 4096

    def next_completions(self, timeout_ms: int = 200):
        """Batch of native-SM apply completions as parallel lists
        (cids, indexes, terms, keys, results, client_ids, series_ids,
        payload_ids, leader_flags, statuses); None on timeout; raises on
        stop.  Status: 0 completed, 1 rejected, 2 ignored (already
        responded — no future completion, mirroring Node.apply_update).
        A nonzero payload_id points at data bytes in the side-channel
        (``take_payload``)."""
        cap = self._COMPL_CAP
        if not hasattr(self, "_cbufs"):
            u64 = ctypes.c_uint64 * cap
            u8 = ctypes.c_uint8 * cap
            self._cbufs = (
                u64(), u64(), u64(), u64(), u64(), u64(), u64(), u64(),
                u8(), u8(),
            )
        b = self._cbufs
        n = self._lib.natr_next_completions(
            self._h, timeout_ms, b[0], b[1], b[2], b[3], b[4], b[5], b[6],
            b[7], b[8], b[9], cap
        )
        if n < 0:
            raise ConnectionError("natraft stopped")
        if n == 0:
            return None
        return tuple(buf[:n] for buf in b)

    def take_payload(self, payload_id: int) -> bytes:
        """Fetch (and consume) a completion payload from the side-channel
        (cached session responses whose Result carried data bytes).

        Thread-safe: ``_pay_mu`` serializes use of the shared scratch
        buffer, so callers outside the completion pump's ``_compl_mu``
        (e.g. the removed-cluster discard path) can't interleave with an
        in-flight read and hand one caller another payload's bytes."""
        with self._pay_mu:
            # reuse one 64KB buffer across calls (the _cbufs pattern):
            # the common payload is tiny and the discard path for removed
            # clusters shouldn't pay a fresh zeroed allocation per record
            buf = getattr(self, "_paybuf", None)
            cap = 1 << 16
            if buf is None:
                buf = self._paybuf = (ctypes.c_uint8 * cap)()
            else:
                cap = len(buf)
            while True:
                n = self._lib.natr_take_payload(self._h, payload_id, buf, cap)
                if n < 0:
                    return b""  # unknown id (already consumed)
                if n <= cap:
                    return bytes(buf[:n])
                cap = int(n)  # undersized: retry with the exact size
                buf = (ctypes.c_uint8 * cap)()  # oversize stays per-call

    def close_conn(self, conn_id: int) -> None:
        self._lib.natr_close_conn(self._h, conn_id)

    def set_commit_window(self, us: int) -> None:
        """Group-commit accumulation window per WAL shard, in microseconds
        (0 = flush as fast as the device allows)."""
        self._lib.natr_set_commit_window(self._h, us)

    def set_partition(self, addr: str, slot: int, on: bool) -> None:
        """Partition injection at the native transport (monkey.go parity):
        block inbound raft batches from ``addr`` and/or outbound passes to
        remote ``slot`` (-1 = inbound only).  ``on=False`` heals."""
        self._lib.natr_set_partition(
            self._h, addr.encode() if addr else b"", slot, 1 if on else 0
        )

    def stats(self) -> dict:
        out = (ctypes.c_uint64 * 25)()
        self._lib.natr_stats(self._h, out)
        return {
            "proposed": int(out[0]),
            "ingested_fast": int(out[1]),
            "ingested_slow": int(out[2]),
            "commits_advanced": int(out[3]),
            "rounds": int(out[4]),
            "fsyncs": int(out[5]),
            "send_dropped": int(out[6]),
            "groups": int(out[7]),
            "fsync_ms": round(int(out[8]) / 1e6, 1),
            "round_ms": round(int(out[9]) / 1e6, 1),
            "entries_staged": int(out[10]),
            "lat_emit_avg_us": int(out[11]),
            "lat_stage_avg_us": int(out[12]),
            "lat_fsync_avg_us": int(out[13]),
            "lat_emit_follower_avg_us": int(out[14]),
            "send_buf_hiwater": int(out[15]),
            "lat_ack_avg_us": int(out[16]),
            "lat_resp_avg_us": int(out[17]),
            "hb_rtt_avg_us": int(out[18]),
            "hb_rtt_max_us": int(out[19]),
            "stale_dropped": int(out[20]),
            "part_in_dropped": int(out[21]),
            "part_out_dropped": int(out[22]),
            # scheduling-stall compensation (clock_pass): passes whose gap
            # exceeded the stall threshold, and the summed unobserved time
            "clock_stalls": int(out[23]) >> 32,
            "clock_stall_ms": int(out[23]) & 0xFFFFFFFF,
            # contact-loss ejects held back because the READER plane made
            # no ingest progress over the silence window (clock_pass
            # cross-check; see the residual-limitation note in natraft.cpp)
            "contact_ejects_deferred": int(out[24]),
        }

    def stop(self) -> None:
        if not self._stopped:
            self._stopped = True
            self._lib.natr_stop(self._h)

    def close(self) -> None:
        self.stop()
        if self._h:
            self._lib.natr_destroy(self._h)
            self._h = None
