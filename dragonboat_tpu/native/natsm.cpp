// Native C-ABI state machine: the in-memory KV test/bench SM implemented
// in C++ so ENROLLED fast-lane groups can apply committed entries without
// the GIL (attacking the measured ~40us/write Python apply rim — PERF.md).
//
// Role model: the reference's KVTest SM (internal/tests/kvtest.go:85) — an
// in-memory map with deterministic snapshot serialization — but exposed
// through a minimal C ABI so BOTH planes share one instance:
//
//   - the native replication core calls `natsm_update` directly from its
//     apply path (function pointer handed over at enrollment);
//   - the Python adapter (native/natsm.py NativeKVStateMachine) fronts the
//     same handle for the scalar path: lookups, post-eject applies,
//     snapshot save/recover.
//
// Command format matches the Python test SMs: "key=value" sets, the result
// is the map size after the set (deterministic across replicas).  The
// internal mutex makes cross-plane access safe; per-group apply ORDER is
// the replication layer's contract (native applies only past the
// enrollment barrier; ejects drain before the scalar plane resumes).
//
// Build: make -C dragonboat_tpu/native  (libnatsm.so)

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <list>
#include <map>
#include <mutex>
#include <string>
#include <unordered_map>

namespace {

struct KV {
  std::mutex mu;
  // std::map: ordered iteration gives deterministic snapshots/hashes
  // without a sort pass at save time
  std::map<std::string, std::string> m;
};

// crc32 (IEEE, same table the WAL/wire paths use)
uint32_t crc_table[256];
struct CrcInit {
  CrcInit() {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      crc_table[i] = c;
    }
  }
} crc_init;

uint32_t crc32ieee(uint32_t crc, const uint8_t* p, size_t n) {
  crc = ~crc;
  for (size_t i = 0; i < n; i++) crc = crc_table[(crc ^ p[i]) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

void put_u32(std::string& b, uint32_t v) {
  for (int i = 0; i < 4; i++) b.push_back((char)((v >> (8 * i)) & 0xFF));
}
uint32_t get_u32(const uint8_t* p) {
  return (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
         ((uint32_t)p[3] << 24);
}

// uvarint twins of wire/codec.py _write_uvarint/_read_uvarint — the
// session image must be BYTE-IDENTICAL to rsm/session.py's so snapshots
// interop across planes and the cross-replica session hash matches.
void put_uvarint(std::string& b, uint64_t v) {
  while (true) {
    uint8_t x = v & 0x7F;
    v >>= 7;
    if (v) b.push_back((char)(x | 0x80));
    else { b.push_back((char)x); return; }
  }
}
bool get_uvarint(const uint8_t* d, size_t len, size_t& pos, uint64_t& out) {
  out = 0;
  for (int shift = 0; shift < 64; shift += 7) {
    if (pos >= len) return false;
    uint8_t x = d[pos++];
    out |= (uint64_t)(x & 0x7F) << shift;
    if (!(x & 0x80)) return true;
  }
  return false;
}

// ---------------------------------------------------------------- sessions
//
// Native twin of rsm/session.py (reference internal/rsm/session.go +
// lrusession.go + sessionmanager.go): exactly-once dedup state shared by
// BOTH planes so session-managed clients keep the native apply path.
// Semantics mirrored exactly — LRU order (register/lookup move-to-back,
// eviction pops the front at > maxn), responded_up_to watermark, per-
// series response history, and the deterministic serialization (LRU
// order, history sorted by series id).

constexpr uint64_t kSeriesRegister = 0;
constexpr uint64_t kSeriesUnregister = ~0ULL;

struct NatSession {
  uint64_t client_id = 0;
  uint64_t responded_up_to = 0;
  // value + data; native applies only ever store empty data (the C-ABI
  // SM result is a u64) but images loaded from the Python plane may
  // carry payloads — kept verbatim for round-trip fidelity
  std::map<uint64_t, std::pair<uint64_t, std::string>> history;
};

struct SessStore {
  std::mutex mu;
  size_t maxn;
  std::list<NatSession> order;  // front = least recently used
  std::unordered_map<uint64_t, std::list<NatSession>::iterator> idx;

  explicit SessStore(size_t m) : maxn(m) {}

  NatSession* touch(uint64_t cid) {  // mu held; moves to MRU
    auto it = idx.find(cid);
    if (it == idx.end()) return nullptr;
    order.splice(order.end(), order, it->second);
    return &*it->second;
  }
  NatSession* peek(uint64_t cid) {  // mu held; no LRU move
    auto it = idx.find(cid);
    return it == idx.end() ? nullptr : &*it->second;
  }
};

void sess_save_locked(SessStore* s, std::string& b) {
  put_uvarint(b, s->order.size());
  for (auto& sess : s->order) {
    put_uvarint(b, sess.client_id);
    put_uvarint(b, sess.responded_up_to);
    put_uvarint(b, sess.history.size());
    for (auto& [sid, r] : sess.history) {  // std::map: sorted by sid
      put_uvarint(b, sid);
      put_uvarint(b, r.first);
      put_uvarint(b, r.second.size());
      b += r.second;
    }
  }
}

uint64_t sess_register_locked(SessStore* s, uint64_t cid) {
  if (s->touch(cid) != nullptr) return cid;  // re-register: LRU refresh
  s->order.emplace_back();
  s->order.back().client_id = cid;
  s->idx[cid] = std::prev(s->order.end());
  if (s->order.size() > s->maxn) {  // evict LRU (OrderedDict popitem(0))
    s->idx.erase(s->order.front().client_id);
    s->order.pop_front();
  }
  return cid;
}

uint64_t sess_unregister_locked(SessStore* s, uint64_t cid) {
  auto it = s->idx.find(cid);
  if (it == s->idx.end()) return 0;
  s->order.erase(it->second);
  s->idx.erase(it);
  return cid;
}

void sess_clear_to_locked(NatSession* sess, uint64_t sid) {
  if (sid <= sess->responded_up_to) return;
  sess->history.erase(sess->history.begin(),
                      sess->history.upper_bound(sid));
  sess->responded_up_to = sid;
}

}  // namespace

extern "C" {

void* natsm_kv_create() { return new KV(); }

void natsm_close(void* h) { delete (KV*)h; }

// Apply one command; returns the SM result value (map size after the set,
// matching the Python KVSM/CountSM convention).  Unparseable commands are
// applied as a no-op returning the current size (never crash: a committed
// entry must not wedge the apply loop).
uint64_t natsm_update(void* h, const uint8_t* cmd, size_t len) {
  KV* kv = (KV*)h;
  const uint8_t* eq = (const uint8_t*)memchr(cmd, '=', len);
  std::lock_guard<std::mutex> lk(kv->mu);
  if (eq != nullptr) {
    kv->m[std::string((const char*)cmd, eq - cmd)] =
        std::string((const char*)eq + 1, len - (eq - cmd) - 1);
  }
  return (uint64_t)kv->m.size();
}

// Point lookup; returns value length and a malloc'd copy in *out (caller
// frees via natsm_buf_free), or -1 when the key is absent.
long long natsm_lookup(void* h, const uint8_t* q, size_t qlen, uint8_t** out) {
  KV* kv = (KV*)h;
  std::lock_guard<std::mutex> lk(kv->mu);
  auto it = kv->m.find(std::string((const char*)q, qlen));
  if (it == kv->m.end()) return -1;
  *out = (uint8_t*)malloc(it->second.size() ? it->second.size() : 1);
  memcpy(*out, it->second.data(), it->second.size());
  return (long long)it->second.size();
}

// Deterministic state hash (reference monkey.go GetHash role).
uint64_t natsm_hash(void* h) {
  KV* kv = (KV*)h;
  std::lock_guard<std::mutex> lk(kv->mu);
  uint32_t c = 0;
  for (auto& [k, v] : kv->m) {
    c = crc32ieee(c, (const uint8_t*)k.data(), k.size());
    c = crc32ieee(c, (const uint8_t*)"\x00", 1);
    c = crc32ieee(c, (const uint8_t*)v.data(), v.size());
    c = crc32ieee(c, (const uint8_t*)"\x01", 1);
  }
  return ((uint64_t)kv->m.size() << 32) | c;
}

// Serialize the full state (count, then length-prefixed k/v pairs, ordered)
// into a malloc'd buffer; returns its size.
long long natsm_save(void* h, uint8_t** out) {
  KV* kv = (KV*)h;
  std::string b;
  {
    std::lock_guard<std::mutex> lk(kv->mu);
    put_u32(b, (uint32_t)kv->m.size());
    for (auto& [k, v] : kv->m) {
      put_u32(b, (uint32_t)k.size());
      b += k;
      put_u32(b, (uint32_t)v.size());
      b += v;
    }
  }
  *out = (uint8_t*)malloc(b.size() ? b.size() : 1);
  memcpy(*out, b.data(), b.size());
  return (long long)b.size();
}

// Replace the state from a natsm_save image; 0 ok, -1 malformed.
int natsm_recover(void* h, const uint8_t* data, size_t len) {
  KV* kv = (KV*)h;
  std::map<std::string, std::string> m;
  size_t pos = 0;
  if (len < 4) return -1;
  uint32_t n = get_u32(data);
  pos = 4;
  for (uint32_t i = 0; i < n; i++) {
    if (pos + 4 > len) return -1;
    uint32_t kl = get_u32(data + pos);
    pos += 4;
    if (kl > len - pos) return -1;
    std::string k((const char*)data + pos, kl);
    pos += kl;
    if (pos + 4 > len) return -1;
    uint32_t vl = get_u32(data + pos);
    pos += 4;
    if (vl > len - pos) return -1;
    m[std::move(k)] = std::string((const char*)data + pos, vl);
    pos += vl;
  }
  std::lock_guard<std::mutex> lk(kv->mu);
  kv->m = std::move(m);
  return 0;
}

void natsm_buf_free(uint8_t* p) { free(p); }

// The update entry point as a raw pointer, for handing to the replication
// core (natr_enroll's sm_update parameter) through Python without the two
// libraries linking against each other.
void* natsm_update_ptr() { return (void*)&natsm_update; }

// Image serializers as raw pointers, for natraft's consistent snapshot
// capture (natr_capture_sm) — same no-link handoff as natsm_update_ptr.
void* natsm_save_ptr() { return (void*)&natsm_save; }

// ---------------------------------------------------------------- sessions

void* natsm_sess_create(uint64_t maxn) { return new SessStore(maxn); }
void natsm_sess_close(void* h) { delete (SessStore*)h; }

uint64_t natsm_sess_register(void* h, uint64_t cid) {
  SessStore* s = (SessStore*)h;
  std::lock_guard<std::mutex> lk(s->mu);
  return sess_register_locked(s, cid);
}

uint64_t natsm_sess_unregister(void* h, uint64_t cid) {
  SessStore* s = (SessStore*)h;
  std::lock_guard<std::mutex> lk(s->mu);
  return sess_unregister_locked(s, cid);
}

// client_registered twin: 1 when present (and refreshes LRU), 0 otherwise.
int natsm_sess_registered(void* h, uint64_t cid) {
  SessStore* s = (SessStore*)h;
  std::lock_guard<std::mutex> lk(s->mu);
  return s->touch(cid) != nullptr ? 1 : 0;
}

int natsm_sess_has_responded(void* h, uint64_t cid, uint64_t sid) {
  SessStore* s = (SessStore*)h;
  std::lock_guard<std::mutex> lk(s->mu);
  NatSession* sess = s->peek(cid);
  return (sess != nullptr && sid <= sess->responded_up_to) ? 1 : 0;
}

// Cached response lookup: 1 found (*value set, *out/dlen hold a malloc'd
// copy of the data payload — empty ⇒ *out NULL), 0 absent.
int natsm_sess_get_response(void* h, uint64_t cid, uint64_t sid,
                            uint64_t* value, uint8_t** out, size_t* dlen) {
  SessStore* s = (SessStore*)h;
  std::lock_guard<std::mutex> lk(s->mu);
  NatSession* sess = s->peek(cid);
  if (sess == nullptr) return 0;
  auto it = sess->history.find(sid);
  if (it == sess->history.end()) return 0;
  *value = it->second.first;
  const std::string& d = it->second.second;
  *dlen = d.size();
  if (d.empty()) {
    *out = nullptr;
  } else {
    *out = (uint8_t*)malloc(d.size());
    memcpy(*out, d.data(), d.size());
  }
  return 1;
}

void natsm_sess_add_response(void* h, uint64_t cid, uint64_t sid,
                             uint64_t value, const uint8_t* data,
                             size_t dlen) {
  SessStore* s = (SessStore*)h;
  std::lock_guard<std::mutex> lk(s->mu);
  NatSession* sess = s->peek(cid);
  if (sess == nullptr) return;  // evicted since lookup: drop (see .py note)
  sess->history.emplace(sid,
                        std::make_pair(value, std::string((const char*)data,
                                                          dlen)));
}

void natsm_sess_clear_to(void* h, uint64_t cid, uint64_t sid) {
  SessStore* s = (SessStore*)h;
  std::lock_guard<std::mutex> lk(s->mu);
  NatSession* sess = s->peek(cid);
  if (sess != nullptr) sess_clear_to_locked(sess, sid);
}

uint64_t natsm_sess_len(void* h) {
  SessStore* s = (SessStore*)h;
  std::lock_guard<std::mutex> lk(s->mu);
  return (uint64_t)s->order.size();
}

long long natsm_sess_save(void* h, uint8_t** out) {
  SessStore* s = (SessStore*)h;
  std::string b;
  {
    std::lock_guard<std::mutex> lk(s->mu);
    sess_save_locked(s, b);
  }
  *out = (uint8_t*)malloc(b.size() ? b.size() : 1);
  memcpy(*out, b.data(), b.size());
  return (long long)b.size();
}

int natsm_sess_recover(void* h, const uint8_t* data, size_t len) {
  SessStore* s = (SessStore*)h;
  std::list<NatSession> order;
  std::unordered_map<uint64_t, std::list<NatSession>::iterator> idx;
  size_t pos = 0;
  uint64_t n;
  if (!get_uvarint(data, len, pos, n)) return -1;
  for (uint64_t i = 0; i < n; i++) {
    NatSession sess;
    uint64_t hn;
    if (!get_uvarint(data, len, pos, sess.client_id) ||
        !get_uvarint(data, len, pos, sess.responded_up_to) ||
        !get_uvarint(data, len, pos, hn))
      return -1;
    for (uint64_t j = 0; j < hn; j++) {
      uint64_t sid, val, dl;
      if (!get_uvarint(data, len, pos, sid) ||
          !get_uvarint(data, len, pos, val) ||
          !get_uvarint(data, len, pos, dl) || dl > len - pos)
        return -1;
      // insert_or_assign, not emplace: a duplicate series id (corrupted
      // image) must keep the LAST occurrence like Python's dict load
      sess.history.insert_or_assign(
          sid, std::make_pair(val, std::string((const char*)data + pos, dl)));
      pos += dl;
    }
    // duplicate client_id (only reachable from a corrupted/adversarial
    // image — save() can't produce one): mirror SessionManager.load's
    // OrderedDict semantics exactly — the FIRST occurrence keeps its
    // position, the value is replaced — so both planes load any image
    // to the identical store
    auto found = idx.find(sess.client_id);
    if (found != idx.end()) {
      *found->second = std::move(sess);
    } else {
      order.push_back(std::move(sess));
      idx[order.back().client_id] = std::prev(order.end());
    }
  }
  std::lock_guard<std::mutex> lk(s->mu);
  s->order = std::move(order);
  s->idx = std::move(idx);
  return 0;
}

// zlib.crc32 of the save image (== SessionManager.hash()).
uint64_t natsm_sess_hash(void* h) {
  SessStore* s = (SessStore*)h;
  std::string b;
  {
    std::lock_guard<std::mutex> lk(s->mu);
    sess_save_locked(s, b);
  }
  return (uint64_t)crc32ieee(0, (const uint8_t*)b.data(), b.size());
}

// The fast lane's one-call apply for a session-managed entry, mirroring
// StateMachineManager._handle_session_entry exactly.  Returns the
// completion status: 0 completed (*result set), 1 rejected, 2 ignored
// (client already responded — the future is NOT completed, matching
// Node.apply_update's `ignored` arm).  A cached response that carries a
// data payload (a history entry imported from a Python-era apply whose
// Result had data bytes) is returned through *pay_out/*pay_len — a
// malloc'd copy the caller owns — and rides the completion side-channel
// instead of forcing an eject (round-4: status 3 punt → eject per
// retry, which cost any data-bearing SM its exactly-once fast path).
int natsm_sess_apply(void* sess_h, void* kv_h, uint64_t cid, uint64_t sid,
                     uint64_t responded_to, const uint8_t* cmd, size_t len,
                     uint64_t* result, uint8_t** pay_out, size_t* pay_len) {
  SessStore* s = (SessStore*)sess_h;
  *result = 0;
  if (pay_out != nullptr) {
    *pay_out = nullptr;
    *pay_len = 0;
  }
  std::unique_lock<std::mutex> lk(s->mu);
  if (sid == kSeriesRegister) {
    *result = sess_register_locked(s, cid);
    return *result == 0 ? 1 : 0;
  }
  if (sid == kSeriesUnregister) {
    *result = sess_unregister_locked(s, cid);
    return *result == 0 ? 1 : 0;
  }
  NatSession* sess = s->touch(cid);
  if (sess == nullptr) return 1;  // not registered: reject
  if (sid <= sess->responded_up_to) return 2;  // already responded
  auto it = sess->history.find(sid);
  if (it != sess->history.end()) {  // duplicate: cached response
    *result = it->second.first;
    const std::string& p = it->second.second;
    if (!p.empty() && pay_out != nullptr) {
      *pay_out = (uint8_t*)malloc(p.size());
      if (*pay_out == nullptr) return 1;  // OOM: reject, never corrupt
      memcpy(*pay_out, p.data(), p.size());
      *pay_len = p.size();
    }
    return 0;
  }
  // first sight: apply through the shared KV, then record the response.
  // The store lock is held across the update so a concurrent snapshot
  // save cannot capture the response without the SM mutation (the KV has
  // its own mutex; lock order sess->kv is the only one used).
  *result = natsm_update(kv_h, cmd, len);
  sess->history.emplace(sid, std::make_pair(*result, std::string()));
  if (responded_to > 0) sess_clear_to_locked(sess, responded_to);
  return 0;
}

void* natsm_sess_apply_ptr() { return (void*)&natsm_sess_apply; }
void* natsm_sess_save_ptr() { return (void*)&natsm_sess_save; }

}  // extern "C"
