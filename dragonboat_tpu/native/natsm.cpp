// Native C-ABI state machine: the in-memory KV test/bench SM implemented
// in C++ so ENROLLED fast-lane groups can apply committed entries without
// the GIL (attacking the measured ~40us/write Python apply rim — PERF.md).
//
// Role model: the reference's KVTest SM (internal/tests/kvtest.go:85) — an
// in-memory map with deterministic snapshot serialization — but exposed
// through a minimal C ABI so BOTH planes share one instance:
//
//   - the native replication core calls `natsm_update` directly from its
//     apply path (function pointer handed over at enrollment);
//   - the Python adapter (native/natsm.py NativeKVStateMachine) fronts the
//     same handle for the scalar path: lookups, post-eject applies,
//     snapshot save/recover.
//
// Command format matches the Python test SMs: "key=value" sets, the result
// is the map size after the set (deterministic across replicas).  The
// internal mutex makes cross-plane access safe; per-group apply ORDER is
// the replication layer's contract (native applies only past the
// enrollment barrier; ejects drain before the scalar plane resumes).
//
// Build: make -C dragonboat_tpu/native  (libnatsm.so)

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <string>

namespace {

struct KV {
  std::mutex mu;
  // std::map: ordered iteration gives deterministic snapshots/hashes
  // without a sort pass at save time
  std::map<std::string, std::string> m;
};

// crc32 (IEEE, same table the WAL/wire paths use)
uint32_t crc_table[256];
struct CrcInit {
  CrcInit() {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      crc_table[i] = c;
    }
  }
} crc_init;

uint32_t crc32ieee(uint32_t crc, const uint8_t* p, size_t n) {
  crc = ~crc;
  for (size_t i = 0; i < n; i++) crc = crc_table[(crc ^ p[i]) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

void put_u32(std::string& b, uint32_t v) {
  for (int i = 0; i < 4; i++) b.push_back((char)((v >> (8 * i)) & 0xFF));
}
uint32_t get_u32(const uint8_t* p) {
  return (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
         ((uint32_t)p[3] << 24);
}

}  // namespace

extern "C" {

void* natsm_kv_create() { return new KV(); }

void natsm_close(void* h) { delete (KV*)h; }

// Apply one command; returns the SM result value (map size after the set,
// matching the Python KVSM/CountSM convention).  Unparseable commands are
// applied as a no-op returning the current size (never crash: a committed
// entry must not wedge the apply loop).
uint64_t natsm_update(void* h, const uint8_t* cmd, size_t len) {
  KV* kv = (KV*)h;
  const uint8_t* eq = (const uint8_t*)memchr(cmd, '=', len);
  std::lock_guard<std::mutex> lk(kv->mu);
  if (eq != nullptr) {
    kv->m[std::string((const char*)cmd, eq - cmd)] =
        std::string((const char*)eq + 1, len - (eq - cmd) - 1);
  }
  return (uint64_t)kv->m.size();
}

// Point lookup; returns value length and a malloc'd copy in *out (caller
// frees via natsm_buf_free), or -1 when the key is absent.
long long natsm_lookup(void* h, const uint8_t* q, size_t qlen, uint8_t** out) {
  KV* kv = (KV*)h;
  std::lock_guard<std::mutex> lk(kv->mu);
  auto it = kv->m.find(std::string((const char*)q, qlen));
  if (it == kv->m.end()) return -1;
  *out = (uint8_t*)malloc(it->second.size() ? it->second.size() : 1);
  memcpy(*out, it->second.data(), it->second.size());
  return (long long)it->second.size();
}

// Deterministic state hash (reference monkey.go GetHash role).
uint64_t natsm_hash(void* h) {
  KV* kv = (KV*)h;
  std::lock_guard<std::mutex> lk(kv->mu);
  uint32_t c = 0;
  for (auto& [k, v] : kv->m) {
    c = crc32ieee(c, (const uint8_t*)k.data(), k.size());
    c = crc32ieee(c, (const uint8_t*)"\x00", 1);
    c = crc32ieee(c, (const uint8_t*)v.data(), v.size());
    c = crc32ieee(c, (const uint8_t*)"\x01", 1);
  }
  return ((uint64_t)kv->m.size() << 32) | c;
}

// Serialize the full state (count, then length-prefixed k/v pairs, ordered)
// into a malloc'd buffer; returns its size.
long long natsm_save(void* h, uint8_t** out) {
  KV* kv = (KV*)h;
  std::string b;
  {
    std::lock_guard<std::mutex> lk(kv->mu);
    put_u32(b, (uint32_t)kv->m.size());
    for (auto& [k, v] : kv->m) {
      put_u32(b, (uint32_t)k.size());
      b += k;
      put_u32(b, (uint32_t)v.size());
      b += v;
    }
  }
  *out = (uint8_t*)malloc(b.size() ? b.size() : 1);
  memcpy(*out, b.data(), b.size());
  return (long long)b.size();
}

// Replace the state from a natsm_save image; 0 ok, -1 malformed.
int natsm_recover(void* h, const uint8_t* data, size_t len) {
  KV* kv = (KV*)h;
  std::map<std::string, std::string> m;
  size_t pos = 0;
  if (len < 4) return -1;
  uint32_t n = get_u32(data);
  pos = 4;
  for (uint32_t i = 0; i < n; i++) {
    if (pos + 4 > len) return -1;
    uint32_t kl = get_u32(data + pos);
    pos += 4;
    if (kl > len - pos) return -1;
    std::string k((const char*)data + pos, kl);
    pos += kl;
    if (pos + 4 > len) return -1;
    uint32_t vl = get_u32(data + pos);
    pos += 4;
    if (vl > len - pos) return -1;
    m[std::move(k)] = std::string((const char*)data + pos, vl);
    pos += vl;
  }
  std::lock_guard<std::mutex> lk(kv->mu);
  kv->m = std::move(m);
  return 0;
}

void natsm_buf_free(uint8_t* p) { free(p); }

// The update entry point as a raw pointer, for handing to the replication
// core (natr_enroll's sm_update parameter) through Python without the two
// libraries linking against each other.
void* natsm_update_ptr() { return (void*)&natsm_update; }

}  // extern "C"
