"""Python front for the native C-ABI state machine (``natsm.cpp``).

:class:`NativeKVStateMachine` implements the regular user SM protocol
(update/lookup/save_snapshot/recover_from_snapshot/close — the contract of
``statemachine.py``) over a C++ KV instance, the analog of the reference's
KVTest SM (``internal/tests/kvtest.go:85``).  One instance is shared by
both planes:

- the **scalar plane** calls through this adapter (ctypes) exactly like
  any Python SM — lookups, post-eject applies, snapshot save/recover;
- the **native fast lane** applies committed entries directly in C++
  (``natraft.cpp apply_native``) via the raw function pointer exposed as
  :attr:`natsm_update_fn`, with no GIL on the apply path.

``Node._maybe_enroll`` detects the ``natsm_handle`` attribute and attaches
the instance to the enrolled group.
"""
from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional

from ..statemachine import Result

_lib = None
_lib_mu = threading.Lock()


def _load():
    global _lib
    with _lib_mu:
        if _lib is not None:
            return _lib
        lib_dir = (
            os.environ.get("DBTPU_NATIVE_LIB_DIR")
            or os.path.dirname(__file__)
        )  # see native/__init__.py (TSAN build override)
        path = os.path.join(lib_dir, "libnatsm.so")
        if not os.path.exists(path) and lib_dir == os.path.dirname(__file__):
            # build on demand like the sibling libraries (__init__.py);
            # override dirs are load-only
            import subprocess

            subprocess.run(
                ["make", "-C", os.path.dirname(__file__), "libnatsm.so"],
                capture_output=True,
            )
        lib = ctypes.CDLL(path)
        lib.natsm_kv_create.restype = ctypes.c_void_p
        lib.natsm_update.restype = ctypes.c_uint64
        lib.natsm_update.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t
        ]
        lib.natsm_lookup.restype = ctypes.c_longlong
        lib.natsm_lookup.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ]
        lib.natsm_hash.restype = ctypes.c_uint64
        lib.natsm_hash.argtypes = [ctypes.c_void_p]
        lib.natsm_save.restype = ctypes.c_longlong
        lib.natsm_save.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8))
        ]
        lib.natsm_recover.restype = ctypes.c_int
        lib.natsm_recover.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t
        ]
        lib.natsm_buf_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
        lib.natsm_close.argtypes = [ctypes.c_void_p]
        lib.natsm_update_ptr.restype = ctypes.c_void_p
        # session store (exactly-once dedup shared by both planes)
        lib.natsm_sess_create.restype = ctypes.c_void_p
        lib.natsm_sess_create.argtypes = [ctypes.c_uint64]
        lib.natsm_sess_close.argtypes = [ctypes.c_void_p]
        for fn in (lib.natsm_sess_register, lib.natsm_sess_unregister):
            fn.restype = ctypes.c_uint64
            fn.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.natsm_sess_registered.restype = ctypes.c_int
        lib.natsm_sess_registered.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.natsm_sess_has_responded.restype = ctypes.c_int
        lib.natsm_sess_has_responded.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64
        ]
        lib.natsm_sess_get_response.restype = ctypes.c_int
        lib.natsm_sess_get_response.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_size_t),
        ]
        lib.natsm_sess_add_response.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_uint64, ctypes.c_char_p, ctypes.c_size_t,
        ]
        lib.natsm_sess_clear_to.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64
        ]
        lib.natsm_sess_len.restype = ctypes.c_uint64
        lib.natsm_sess_len.argtypes = [ctypes.c_void_p]
        lib.natsm_sess_save.restype = ctypes.c_longlong
        lib.natsm_sess_save.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8))
        ]
        lib.natsm_sess_recover.restype = ctypes.c_int
        lib.natsm_sess_recover.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t
        ]
        lib.natsm_sess_hash.restype = ctypes.c_uint64
        lib.natsm_sess_hash.argtypes = [ctypes.c_void_p]
        lib.natsm_sess_apply_ptr.restype = ctypes.c_void_p
        lib.natsm_save_ptr.restype = ctypes.c_void_p
        lib.natsm_sess_save_ptr.restype = ctypes.c_void_p
        _lib = lib
        return lib


def available() -> bool:
    try:
        return _load() is not None
    except OSError:
        return False


class NativeKVStateMachine:
    """Regular (in-memory) user SM backed by the native KV instance."""

    def __init__(self, cluster_id: int, node_id: int) -> None:
        self._lib = _load()
        self.cluster_id = cluster_id
        self.node_id = node_id
        #: raw handle + update fn pointer for natr_attach_sm
        self.natsm_handle: int = self._lib.natsm_kv_create()
        self.natsm_update_fn: int = self._lib.natsm_update_ptr()
        # exactly-once session store, shared by both planes: the RSM
        # manager detects these attributes and swaps its Python
        # SessionManager for a :class:`NativeSessionManager` fronting the
        # same handle the enrolled native core applies through
        from ..settings import Hard

        self.natsm_sess_handle: int = self._lib.natsm_sess_create(
            Hard.lru_max_session_count
        )
        self.natsm_sess_apply_fn: int = self._lib.natsm_sess_apply_ptr()
        # image serializers for natr_capture_sm: snapshots of enrolled
        # groups are taken natively at a consistent applied index instead
        # of ejecting the group once per snapshot_entries window
        self.natsm_save_fn: int = self._lib.natsm_save_ptr()
        self.natsm_sess_save_fn: int = self._lib.natsm_sess_save_ptr()

    # ---- user SM protocol (scalar plane) ----

    def update(self, cmd: bytes) -> Result:
        v = self._lib.natsm_update(self.natsm_handle, bytes(cmd), len(cmd))
        return Result(value=v)

    def lookup(self, query):
        if query is None:
            # whole-state probe (bench/CounterSM convention): entry count
            return int(self._lib.natsm_hash(self.natsm_handle) >> 32)
        q = query.encode() if isinstance(query, str) else bytes(query)
        out = ctypes.POINTER(ctypes.c_uint8)()
        n = self._lib.natsm_lookup(self.natsm_handle, q, len(q), ctypes.byref(out))
        if n < 0:
            return None
        try:
            return bytes(ctypes.string_at(out, n)).decode()
        finally:
            self._lib.natsm_buf_free(out)

    def get_hash(self) -> int:
        return int(self._lib.natsm_hash(self.natsm_handle))

    def save_snapshot(self, w, files, done) -> None:
        out = ctypes.POINTER(ctypes.c_uint8)()
        n = self._lib.natsm_save(self.natsm_handle, ctypes.byref(out))
        try:
            data = ctypes.string_at(out, n)
        finally:
            self._lib.natsm_buf_free(out)
        w.write(len(data).to_bytes(8, "little") + data)

    def recover_from_snapshot(self, r, files, done) -> None:
        n = int.from_bytes(r.read(8), "little")
        data = r.read(n)
        if self._lib.natsm_recover(self.natsm_handle, data, len(data)) != 0:
            raise ValueError("malformed native SM snapshot image")

    def close(self) -> None:
        if self.natsm_handle:
            self._lib.natsm_close(self.natsm_handle)
            self.natsm_handle = 0
        if self.natsm_sess_handle:
            self._lib.natsm_sess_close(self.natsm_sess_handle)
            self.natsm_sess_handle = 0


class _NativeSession:
    """Session proxy with the surface :class:`rsm.session.Session` exposes
    to ``_handle_session_entry`` (has_responded / get_response /
    add_response / clear_to), executing against the native store.  Only
    materialized by :meth:`NativeSessionManager.client_registered`, which
    has already refreshed the LRU slot — these calls deliberately do NOT
    move it again (Python semantics)."""

    __slots__ = ("_lib", "_h", "client_id")

    def __init__(self, lib, handle: int, client_id: int) -> None:
        self._lib = lib
        self._h = handle
        self.client_id = client_id

    def has_responded(self, series_id: int) -> bool:
        return bool(
            self._lib.natsm_sess_has_responded(self._h, self.client_id, series_id)
        )

    def get_response(self, series_id: int):
        value = ctypes.c_uint64()
        out = ctypes.POINTER(ctypes.c_uint8)()
        dlen = ctypes.c_size_t()
        ok = self._lib.natsm_sess_get_response(
            self._h, self.client_id, series_id,
            ctypes.byref(value), ctypes.byref(out), ctypes.byref(dlen),
        )
        if not ok:
            return None, False
        data = b""
        if out:
            try:
                data = bytes(ctypes.string_at(out, dlen.value))
            finally:
                self._lib.natsm_buf_free(out)
        return Result(value=int(value.value), data=data), True

    def add_response(self, series_id: int, result: Result) -> None:
        d = result.data or b""
        self._lib.natsm_sess_add_response(
            self._h, self.client_id, series_id, result.value, d, len(d)
        )

    def clear_to(self, series_id: int) -> None:
        self._lib.natsm_sess_clear_to(self._h, self.client_id, series_id)


class NativeSessionManager:
    """Drop-in for :class:`rsm.session.SessionManager` over the native
    store owned by a :class:`NativeKVStateMachine` — both planes dedup
    against the SAME state, so enroll/eject transitions carry no session
    hand-off.  Serialization and hash are byte-identical to the Python
    manager's (``natsm_sess_save`` mirrors ``SessionManager.save``), so
    snapshots interop across plane and SM kinds."""

    def __init__(self, user_sm: "NativeKVStateMachine") -> None:
        self._lib = user_sm._lib
        # keep the SM alive: it owns the handle's lifetime
        self._owner = user_sm
        self._h = user_sm.natsm_sess_handle

    def __len__(self) -> int:
        return int(self._lib.natsm_sess_len(self._h))

    def register_client_id(self, client_id: int) -> Result:
        return Result(value=int(self._lib.natsm_sess_register(self._h, client_id)))

    def unregister_client_id(self, client_id: int) -> Result:
        return Result(
            value=int(self._lib.natsm_sess_unregister(self._h, client_id))
        )

    def client_registered(self, client_id: int) -> Optional[_NativeSession]:
        if not self._lib.natsm_sess_registered(self._h, client_id):
            return None
        return _NativeSession(self._lib, self._h, client_id)

    def update_required(self, session, series_id: int):
        if session.has_responded(series_id):
            return None, False
        cached, ok = session.get_response(series_id)
        if ok:
            return cached, False
        return None, True

    def add_response(self, session, series_id: int, result: Result) -> None:
        session.add_response(series_id, result)

    def save(self) -> bytes:
        out = ctypes.POINTER(ctypes.c_uint8)()
        n = self._lib.natsm_sess_save(self._h, ctypes.byref(out))
        try:
            return bytes(ctypes.string_at(out, n))
        finally:
            self._lib.natsm_buf_free(out)

    def recover_image(self, data: bytes) -> None:
        """In-place snapshot restore (the native handle stays shared with
        the replication core, so the store is replaced by content, not by
        identity — the manager-swap the Python path does on recover)."""
        if self._lib.natsm_sess_recover(self._h, bytes(data), len(data)) != 0:
            raise ValueError("malformed native session image")

    def hash(self) -> int:
        return int(self._lib.natsm_sess_hash(self._h))
