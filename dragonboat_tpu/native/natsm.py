"""Python front for the native C-ABI state machine (``natsm.cpp``).

:class:`NativeKVStateMachine` implements the regular user SM protocol
(update/lookup/save_snapshot/recover_from_snapshot/close — the contract of
``statemachine.py``) over a C++ KV instance, the analog of the reference's
KVTest SM (``internal/tests/kvtest.go:85``).  One instance is shared by
both planes:

- the **scalar plane** calls through this adapter (ctypes) exactly like
  any Python SM — lookups, post-eject applies, snapshot save/recover;
- the **native fast lane** applies committed entries directly in C++
  (``natraft.cpp apply_native``) via the raw function pointer exposed as
  :attr:`natsm_update_fn`, with no GIL on the apply path.

``Node._maybe_enroll`` detects the ``natsm_handle`` attribute and attaches
the instance to the enrolled group.
"""
from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional

from ..statemachine import Result

_lib = None
_lib_mu = threading.Lock()


def _load():
    global _lib
    with _lib_mu:
        if _lib is not None:
            return _lib
        path = os.path.join(os.path.dirname(__file__), "libnatsm.so")
        if not os.path.exists(path):
            # build on demand like the sibling libraries (__init__.py)
            import subprocess

            subprocess.run(
                ["make", "-C", os.path.dirname(__file__), "libnatsm.so"],
                capture_output=True,
            )
        lib = ctypes.CDLL(path)
        lib.natsm_kv_create.restype = ctypes.c_void_p
        lib.natsm_update.restype = ctypes.c_uint64
        lib.natsm_update.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t
        ]
        lib.natsm_lookup.restype = ctypes.c_longlong
        lib.natsm_lookup.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ]
        lib.natsm_hash.restype = ctypes.c_uint64
        lib.natsm_hash.argtypes = [ctypes.c_void_p]
        lib.natsm_save.restype = ctypes.c_longlong
        lib.natsm_save.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8))
        ]
        lib.natsm_recover.restype = ctypes.c_int
        lib.natsm_recover.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t
        ]
        lib.natsm_buf_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
        lib.natsm_close.argtypes = [ctypes.c_void_p]
        lib.natsm_update_ptr.restype = ctypes.c_void_p
        _lib = lib
        return lib


def available() -> bool:
    try:
        return _load() is not None
    except OSError:
        return False


class NativeKVStateMachine:
    """Regular (in-memory) user SM backed by the native KV instance."""

    def __init__(self, cluster_id: int, node_id: int) -> None:
        self._lib = _load()
        self.cluster_id = cluster_id
        self.node_id = node_id
        #: raw handle + update fn pointer for natr_attach_sm
        self.natsm_handle: int = self._lib.natsm_kv_create()
        self.natsm_update_fn: int = self._lib.natsm_update_ptr()

    # ---- user SM protocol (scalar plane) ----

    def update(self, cmd: bytes) -> Result:
        v = self._lib.natsm_update(self.natsm_handle, bytes(cmd), len(cmd))
        return Result(value=v)

    def lookup(self, query):
        if query is None:
            # whole-state probe (bench/CounterSM convention): entry count
            return int(self._lib.natsm_hash(self.natsm_handle) >> 32)
        q = query.encode() if isinstance(query, str) else bytes(query)
        out = ctypes.POINTER(ctypes.c_uint8)()
        n = self._lib.natsm_lookup(self.natsm_handle, q, len(q), ctypes.byref(out))
        if n < 0:
            return None
        try:
            return bytes(ctypes.string_at(out, n)).decode()
        finally:
            self._lib.natsm_buf_free(out)

    def get_hash(self) -> int:
        return int(self._lib.natsm_hash(self.natsm_handle))

    def save_snapshot(self, w, files, done) -> None:
        out = ctypes.POINTER(ctypes.c_uint8)()
        n = self._lib.natsm_save(self.natsm_handle, ctypes.byref(out))
        try:
            data = ctypes.string_at(out, n)
        finally:
            self._lib.natsm_buf_free(out)
        w.write(len(data).to_bytes(8, "little") + data)

    def recover_from_snapshot(self, r, files, done) -> None:
        n = int.from_bytes(r.read(8), "little")
        data = r.read(n)
        if self._lib.natsm_recover(self.natsm_handle, data, len(data)) != 0:
            raise ValueError("malformed native SM snapshot image")

    def close(self) -> None:
        if self.natsm_handle:
            self._lib.natsm_close(self.natsm_handle)
            self.natsm_handle = 0
