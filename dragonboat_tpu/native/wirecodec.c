/* Native accelerator for the wire codec hot path.
 *
 * The reference ships hand-optimized marshal paths for its wire types
 * (raftpb/raft_optimized.go); this is the analogous native component for
 * the TPU build's codec (dragonboat_tpu/wire/codec.py).  Only the
 * per-field varint plumbing moves to C — object construction and the
 * rarely-used types (snapshots, memberships) stay in Python.  codec.py
 * falls back to the pure-Python path when this module is unavailable.
 *
 * Exposed functions (all operate on bytes-like objects / bytearrays):
 *   parse_message_fields(data, pos) ->
 *       (mtype, flags, to, frm, cluster_id, term, log_term, log_index,
 *        commit, hint, hint_high, nentries, newpos)
 *   parse_entry_fields(data, pos) ->
 *       (term, index, etype, key, client_id, series_id, responded_to,
 *        cmd_start, cmd_end, newpos)   # cmd bounds, zero-copy slicing in py
 *   encode_message_header(bytearray, mtype, flags, to, frm, cluster_id,
 *        term, log_term, log_index, commit, hint, hint_high, nentries)
 *   encode_entry_fields(bytearray, term, index, etype, key, client_id,
 *        series_id, responded_to, cmd_bytes)
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

static PyObject *CodecError;

/* ---- varint helpers ---------------------------------------------------- */

static int read_uvarint(const unsigned char *buf, Py_ssize_t len,
                        Py_ssize_t *pos, uint64_t *out) {
    uint64_t result = 0;
    int shift = 0;
    Py_ssize_t p = *pos;
    while (1) {
        if (p >= len) return -1;
        unsigned char b = buf[p++];
        /* uint64 exactly: the 10th byte may contribute only one bit —
         * reject (don't truncate) overflow, identical to the pure-Python
         * decoder so the same bytes can never decode differently */
        if (shift == 63 && (b & 0x7F) > 1) return -1;
        result |= ((uint64_t)(b & 0x7F)) << shift;
        if (!(b & 0x80)) break;
        shift += 7;
        if (shift > 63) return -1;
    }
    *pos = p;
    *out = result;
    return 0;
}

static int write_uvarint(PyObject *ba, uint64_t v) {
    unsigned char tmp[10];
    int n = 0;
    while (1) {
        unsigned char b = v & 0x7F;
        v >>= 7;
        if (v) {
            tmp[n++] = b | 0x80;
        } else {
            tmp[n++] = b;
            break;
        }
    }
    Py_ssize_t old = PyByteArray_GET_SIZE(ba);
    if (PyByteArray_Resize(ba, old + n) < 0) return -1;
    memcpy(PyByteArray_AS_STRING(ba) + old, tmp, n);
    return 0;
}

/* ---- decode ------------------------------------------------------------ */

static PyObject *parse_message_fields(PyObject *self, PyObject *args) {
    Py_buffer view;
    Py_ssize_t pos;
    if (!PyArg_ParseTuple(args, "y*n", &view, &pos)) return NULL;
    const unsigned char *buf = view.buf;
    Py_ssize_t len = view.len;
    uint64_t f[11];  /* mtype,to,frm,cid,term,log_term,log_index,commit,
                        hint,hint_high,nentries */
    unsigned char flags;
    if (pos < 0 || read_uvarint(buf, len, &pos, &f[0]) < 0) goto trunc;
    if (pos >= len) goto trunc;
    flags = buf[pos++];
    for (int i = 1; i < 11; i++)
        if (read_uvarint(buf, len, &pos, &f[i]) < 0) goto trunc;
    PyBuffer_Release(&view);
    return Py_BuildValue(
        "(KBKKKKKKKKKKn)",
        (unsigned long long)f[0], flags,
        (unsigned long long)f[1], (unsigned long long)f[2],
        (unsigned long long)f[3], (unsigned long long)f[4],
        (unsigned long long)f[5], (unsigned long long)f[6],
        (unsigned long long)f[7], (unsigned long long)f[8],
        (unsigned long long)f[9], (unsigned long long)f[10], pos);
trunc:
    PyBuffer_Release(&view);
    PyErr_SetString(CodecError, "truncated Message");
    return NULL;
}

static PyObject *parse_entry_fields(PyObject *self, PyObject *args) {
    Py_buffer view;
    Py_ssize_t pos;
    if (!PyArg_ParseTuple(args, "y*n", &view, &pos)) return NULL;
    const unsigned char *buf = view.buf;
    Py_ssize_t len = view.len;
    uint64_t f[7]; /* term,index,etype,key,client_id,series_id,responded_to */
    uint64_t cmdlen;
    if (pos < 0) goto trunc;
    for (int i = 0; i < 7; i++)
        if (read_uvarint(buf, len, &pos, &f[i]) < 0) goto trunc;
    if (read_uvarint(buf, len, &pos, &cmdlen) < 0) goto trunc;
    if (cmdlen > (uint64_t)(len - pos)) goto trunc;
    {
        Py_ssize_t cmd_start = pos, cmd_end = pos + (Py_ssize_t)cmdlen;
        PyBuffer_Release(&view);
        return Py_BuildValue(
            "(KKKKKKKnnn)",
            (unsigned long long)f[0], (unsigned long long)f[1],
            (unsigned long long)f[2], (unsigned long long)f[3],
            (unsigned long long)f[4], (unsigned long long)f[5],
            (unsigned long long)f[6], cmd_start, cmd_end, cmd_end);
    }
trunc:
    PyBuffer_Release(&view);
    PyErr_SetString(CodecError, "truncated Entry");
    return NULL;
}

/* ---- encode ------------------------------------------------------------ */

/* Exact unsigned conversion: raises on negative / >= 2**64 (matching the
 * pure-Python path's CodecError on negative varints, so a mixed fleet
 * cannot produce divergent bytes for the same object). */
static int as_u64(PyObject *o, unsigned long long *out) {
    unsigned long long v = PyLong_AsUnsignedLongLong(o);
    if (v == (unsigned long long)-1 && PyErr_Occurred()) {
        PyErr_Clear();
        PyErr_SetString(CodecError, "field out of uint64 range");
        return -1;
    }
    *out = v;
    return 0;
}

static PyObject *encode_message_header(PyObject *self, PyObject *args) {
    PyObject *ba, *o[11];
    unsigned long long mtype, to, frm, cid, term, log_term, log_index,
        commit, hint, hint_high, nentries;
    unsigned char flags;
    if (!PyArg_ParseTuple(args, "O!OBOOOOOOOOOO", &PyByteArray_Type, &ba,
                          &o[0], &flags, &o[1], &o[2], &o[3], &o[4], &o[5],
                          &o[6], &o[7], &o[8], &o[9], &o[10]))
        return NULL;
    if (as_u64(o[0], &mtype) < 0 || as_u64(o[1], &to) < 0 ||
        as_u64(o[2], &frm) < 0 || as_u64(o[3], &cid) < 0 ||
        as_u64(o[4], &term) < 0 || as_u64(o[5], &log_term) < 0 ||
        as_u64(o[6], &log_index) < 0 || as_u64(o[7], &commit) < 0 ||
        as_u64(o[8], &hint) < 0 || as_u64(o[9], &hint_high) < 0 ||
        as_u64(o[10], &nentries) < 0)
        return NULL;
    if (write_uvarint(ba, mtype) < 0) return NULL;
    {
        Py_ssize_t old = PyByteArray_GET_SIZE(ba);
        if (PyByteArray_Resize(ba, old + 1) < 0) return NULL;
        PyByteArray_AS_STRING(ba)[old] = (char)flags;
    }
    if (write_uvarint(ba, to) < 0 || write_uvarint(ba, frm) < 0 ||
        write_uvarint(ba, cid) < 0 || write_uvarint(ba, term) < 0 ||
        write_uvarint(ba, log_term) < 0 || write_uvarint(ba, log_index) < 0 ||
        write_uvarint(ba, commit) < 0 || write_uvarint(ba, hint) < 0 ||
        write_uvarint(ba, hint_high) < 0 || write_uvarint(ba, nentries) < 0)
        return NULL;
    Py_RETURN_NONE;
}

static PyObject *encode_entry_fields(PyObject *self, PyObject *args) {
    PyObject *ba, *o[7];
    unsigned long long term, index, etype, key, client_id, series_id,
        responded_to;
    Py_buffer cmd;
    if (!PyArg_ParseTuple(args, "O!OOOOOOOy*", &PyByteArray_Type, &ba, &o[0],
                          &o[1], &o[2], &o[3], &o[4], &o[5], &o[6], &cmd))
        return NULL;
    if (as_u64(o[0], &term) < 0 || as_u64(o[1], &index) < 0 ||
        as_u64(o[2], &etype) < 0 || as_u64(o[3], &key) < 0 ||
        as_u64(o[4], &client_id) < 0 || as_u64(o[5], &series_id) < 0 ||
        as_u64(o[6], &responded_to) < 0) {
        PyBuffer_Release(&cmd);
        return NULL;
    }
    if (write_uvarint(ba, term) < 0 || write_uvarint(ba, index) < 0 ||
        write_uvarint(ba, etype) < 0 || write_uvarint(ba, key) < 0 ||
        write_uvarint(ba, client_id) < 0 || write_uvarint(ba, series_id) < 0 ||
        write_uvarint(ba, responded_to) < 0 ||
        write_uvarint(ba, (uint64_t)cmd.len) < 0) {
        PyBuffer_Release(&cmd);
        return NULL;
    }
    {
        Py_ssize_t old = PyByteArray_GET_SIZE(ba);
        if (PyByteArray_Resize(ba, old + cmd.len) < 0) {
            PyBuffer_Release(&cmd);
            return NULL;
        }
        memcpy(PyByteArray_AS_STRING(ba) + old, cmd.buf, cmd.len);
    }
    PyBuffer_Release(&cmd);
    Py_RETURN_NONE;
}

/* ---- module ------------------------------------------------------------ */

static PyMethodDef Methods[] = {
    {"parse_message_fields", parse_message_fields, METH_VARARGS, NULL},
    {"parse_entry_fields", parse_entry_fields, METH_VARARGS, NULL},
    {"encode_message_header", encode_message_header, METH_VARARGS, NULL},
    {"encode_entry_fields", encode_entry_fields, METH_VARARGS, NULL},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "dbtpu_wirecodec", NULL, -1, Methods,
};

PyMODINIT_FUNC PyInit_dbtpu_wirecodec(void) {
    PyObject *m = PyModule_Create(&moduledef);
    if (!m) return NULL;
    CodecError = PyErr_NewException("dbtpu_wirecodec.CodecError",
                                    PyExc_ValueError, NULL);
    Py_XINCREF(CodecError);
    if (PyModule_AddObject(m, "CodecError", CodecError) < 0) {
        Py_XDECREF(CodecError);
        Py_DECREF(m);
        return NULL;
    }
    return m;
}
