"""Per-replica node runtime: binds Peer + RSM + snapshotter + queues.

Reference: ``node.go`` — ``stepNode`` pulls queued inputs into raft,
``processRaftUpdate``/``commitRaftUpdate`` execute the resulting ``Update``
(messages out before fsync, entries to LogDB, committed entries to the apply
queue), snapshot task lifecycle, log compaction, tick handling and the
``rsm.INode`` callbacks completing pending requests.
"""
from __future__ import annotations

import struct as _struct
import threading
import time
from functools import lru_cache
from typing import Dict, List, Optional

from .client import Session
from .config import Config
from .events import SystemEvent, SystemEventType
from .logdb import LogReader
from .logger import get_logger
from .queue import EntryQueue
from .quiesce import QuiesceManager
from .requests import (
    ClusterClosedError,
    InvalidOperationError,
    PayloadTooBigError,
    PendingConfigChange,
    PendingLeaderTransfer,
    PendingProposal,
    PendingReadIndex,
    PendingSnapshot,
    RequestResult,
    RequestResultCode,
    RequestState,
    SystemBusyError,
)
from .rsm import (
    MembershipState,
    SSReqType,
    SSRequest,
    StateMachine,
    Task,
    TaskQueue,
)
from .rsm.encoded import get_encoded_payload, to_dio_compression_type
from .rsm.statemachine import SnapshotIgnored
from .raft.peer import Peer, PeerAddress
from .server.message import MessageQueue
from .settings import Soft
from .snapshotter import Snapshotter
from .statemachine import Result
from .wire import (
    ConfigChange,
    ConfigChangeType,
    Entry,
    EntryType,
    Membership,
    Message,
    MessageType,
    Snapshot,
    State,
    SystemCtx,
    Update,
    is_empty_snapshot,
)

plog = get_logger("node")
MT = MessageType

# length-prefix header packer for native batch appends, cached per length:
# a pipelined burst is almost always one payload size repeated, and the
# per-entry ``struct.pack`` (plus the in-function ``import struct``) was a
# measured leaf in PROFILE_e2e.txt's propose path
_pack_len = lru_cache(maxsize=1024)(_struct.Struct("<I").pack)
# wire types the native fast lane serves (natraft.cpp handle_fast)
_FAST_WIRE_TYPES = frozenset(
    (MT.REPLICATE, MT.REPLICATE_RESP, MT.HEARTBEAT, MT.HEARTBEAT_RESP,
     MT.READ_INDEX, MT.READ_INDEX_RESP)
)


class Node:
    """Reference ``node.go:58`` ``node``."""

    def __init__(
        self,
        nh,  # NodeHost (duck-typed: send_message, send_snapshot_message, engine)
        config: Config,
        logdb,
        logreader: LogReader,
        snapshotter: Snapshotter,
        sm: StateMachine,
        tick_millisecond: int,
    ):
        self.nh = nh
        self.config = config
        self.cluster_id = config.cluster_id
        self.node_id = config.node_id
        self.logdb = logdb
        self.logreader = logreader
        self.snapshotter = snapshotter
        self.sm = sm
        self.tick_millisecond = tick_millisecond
        self._entry_ct = to_dio_compression_type(config.entry_compression)
        self.raft_mu = threading.RLock()
        self.peer: Optional[Peer] = None
        # input queues
        self.entry_q = EntryQueue(Soft.incoming_proposal_queue_length)
        self.mq = MessageQueue(Soft.received_message_queue_length)
        # pending request trackers
        self.pending_proposals = PendingProposal()
        self.pending_reads = PendingReadIndex()
        self.pending_config_change = PendingConfigChange()
        self.pending_snapshot = PendingSnapshot()
        self.pending_leader_transfer = PendingLeaderTransfer()
        # apply pipeline
        self.to_apply = TaskQueue()
        self.quiesce_mgr = QuiesceManager(
            self.cluster_id, self.node_id, config.election_rtt, config.quiesce
        )
        self._stopped = threading.Event()
        self._initialized = threading.Event()
        self.current_tick = 0
        # lazy tick delivery: nodes whose raft clock is NOT host-driven
        # (native fast lane, device tick kernel) skip the per-tick wakeup
        # from the tick worker and catch up on elapsed ticks — read from
        # the NodeHost's global tick counter — at their next step.  This
        # turns the tick worker's per-RTT Python cost from O(groups) into
        # O(scalar-clocked groups), the scaling axis the reference covers
        # with quiesce (quiesce.go) and the device engine covers with one
        # fused tick dispatch for the whole mass.
        self._seen_tick = nh.tick_count
        # True while this group's latest update sits in the engine's commit
        # pipeline; the step worker skips the group until the committer
        # clears it (per-group round ordering, see engine._Committer)
        self.commit_inflight = False
        # native replication fast lane (fastlane.py / native/natraft.cpp):
        # while fast_lane is True the Python raft object is frozen and the
        # native core owns the group's steady-state data plane
        self.fastlane = None  # FastLaneManager, set by NodeHost
        self.fast_lane = False
        # compartmentalized host plane (hostplane.py, set by NodeHost when
        # ExpertConfig.host_compartments is on): propose/propose_batch
        # stage through the striped ingress batcher instead of taking the
        # entry_q lock + step wakeup per call.  None keeps the direct path
        # bit-identical.
        self.ingress = None
        # cross-plane request tracer (obs/trace.py, ISSUE 9; set by
        # NodeHost when NodeHostConfig.trace_sample_every > 0): propose/
        # read allocate a sampled trace context on the future and the
        # pipeline stages stamp it as the request passes.  None (default)
        # keeps every request path bit-identical.
        self.tracer = None
        # replication attribution plane (obs/replattr.py, ISSUE 14; set
        # by NodeHost alongside the tracer): sampled proposals' REPLICATE
        # fan-outs carry a ReplTrace context and the leader decomposes
        # each commit's quorum close per peer.  None (default) keeps the
        # send/ack paths bit-identical.
        self.replattr = None
        # device-engine effect flags (written by the coordinator round
        # thread, max-merged/idempotent, applied under raftMu by
        # _apply_offload_effects on a step worker).  _off_mu guards the
        # writer-vs-swap-and-clear race: without it a flag written between
        # the consumer's load and its clearing store is silently lost, and
        # the engine's edge-triggered commit reporting never resends it.
        self._off_mu = threading.Lock()
        self._off_commit = 0
        self._off_election = None
        self._off_hb = False
        self._off_elect = False
        self._off_demote = False
        # device read plane: quorum-confirmed ReadIndex ctxs awaiting the
        # scalar prefix release, and fallback echoes for ctxs the device
        # is not tracking (slot overflow / stale) — both applied under
        # raftMu with the leader/term guards intact
        self._off_reads: list = []
        self._off_read_echoes: list = []
        # device-plane observability (ISSUE 5): set by the coordinator
        # when obs is enabled; _apply_offload_effects counts delivered
        # effects under dragonboat_node_offload_applied_total{kind=...}.
        # None (the default) keeps the apply path untouched.
        self.obs_registry = None
        # device state machine (devsm, ISSUE 11): set by NodeHost when a
        # DeviceKVStateMachine group registers (Config.device_kv on the
        # tpu engine).  None keeps every path below bit-identical.  The
        # release floor is the highest DEVICE commit watermark pending
        # reads have been released at — the plane's shadow fallback gates
        # host-apply catch-up on it.
        self.devsm_plane = None
        self.devsm_release_floor = 0
        # cluster health plane (obs/health.py, ISSUE 13): the sampler
        # flips _health_track on its first pass over this node, and
        # offload_commit then records the highest DEVICE commit
        # watermark seen (the sample's dev_commit column).  False (the
        # default, health plane off) keeps offload_commit bit-identical
        # but for this one latch check — the trace=None precedent.
        self._health_track = False
        self._dev_commit_seen = 0
        self._natsm_attached = False  # native C-ABI SM wired to the lane
        self._next_enroll_try = 0.0
        self._tick_count_pending = 0
        # last auto-compacted watermark, consumed by request_compaction
        # (reference snapshotState.compactedTo, swap-to-zero semantics);
        # the lock makes the swap atomic against _compact_log's store
        # (the reference uses atomic.SwapUint64)
        self._compacted_to = 0
        self._compacted_to_mu = threading.Lock()
        self._snapshotting = threading.Lock()
        self._apply_serial = threading.Lock()
        self.leader_id = 0
        self._delete_required = False

    # ---- startup (reference startRaft/replayLog node.go:292,573) ----

    def start(
        self,
        addresses: List[PeerAddress],
        initial: bool,
        new_node: bool,
        seed: Optional[int] = None,
    ) -> None:
        self.snapshotter.process_orphans()
        self.peer = Peer.launch(
            self.config, self.logreader, None, addresses, initial, new_node,
            seed=seed,
        )
        # metrics + LeaderUpdated forwarding (reference event.go:37)
        self.peer.raft.events = getattr(self, "peer_raft_events", None)
        # leader-lease instruments (ISSUE 10; set by NodeHost when
        # enable_metrics is on and the group has read_lease): the raft
        # lease hooks gate on obs `is not None`, so metrics-off hosts
        # never touch the registry
        lease_obs = getattr(self, "lease_obs", None)
        if lease_obs is not None and self.peer.raft.lease is not None:
            self.peer.raft.lease.obs = lease_obs
        # hierarchical-commit instruments (ISSUE 18; set by NodeHost when
        # enable_metrics is on and the group has hier_commit): same
        # gate-on-`is not None` discipline as the lease instruments
        hier_obs = getattr(self, "hier_obs", None)
        if hier_obs is not None and self.peer.raft.hier is not None:
            self.peer.raft.hier.obs = hier_obs
        # wall-clock lease guard (ISSUE 17; set by NodeHost when
        # Config.read_lease and NodeHostConfig.lease_wall_guard): the
        # host's tick period in seconds — validity then also requires
        # wall-fresh quorum acks, so tick starvation expires the lease
        # instead of extending it
        lease_wall_s = getattr(self, "lease_wall_s", None)
        if lease_wall_s is not None and self.peer.raft.lease is not None:
            self.peer.raft.lease.tick_interval_s = lease_wall_s
        # replication attribution (ISSUE 14): the raft-level ack/commit
        # hooks gate on `replattr is not None`, so trace-off hosts never
        # touch the plane
        if self.replattr is not None:
            self.peer.raft.replattr = self.replattr
        # TPU quorum plugin (ExpertConfig.quorum_engine): stage hot-path
        # tallying to the device engine and register this group's row
        coord = getattr(self, "quorum_coordinator", None)
        if coord is not None:
            self.peer.raft.offload = coord
            # device-tick mode: the tick kernel owns election/heartbeat/
            # check-quorum firing; quiesce-enabled groups keep scalar ticks
            # (their idle detection is host-side state)
            if coord.drive_ticks and not self.config.quiesce:
                self.peer.raft.device_ticks = True
            coord.register(self)
            # device state machine (devsm, ISSUE 11): Config.device_kv +
            # a DeviceKVStateMachine factory moves the group's apply
            # plane into the fused program — entry ops offload at append
            # (raft.device_kv) and reads serve from device state once the
            # plane binds at a leadership promotion
            dsm_sm = getattr(self, "devsm_sm", None)
            if dsm_sm is not None:
                plane = coord.devsm_plane()
                plane.register(self.cluster_id, dsm_sm)
                self.devsm_plane = plane
                self.peer.raft.device_kv = True
        # queue initial recovery so the apply worker restores the newest
        # local snapshot before any new entries apply.  The WAKEUP is the
        # caller's job AFTER registering the node (reference
        # nodehost.go:1584-1587 clusters.Store -> csi++ -> setApplyReady):
        # signalling here races the busy apply workers, which consume the
        # ready bit, find no node in their map, and silently drop it — the
        # node then never initializes (soak-caught restart wedge)
        self.to_apply.enqueue(
            Task(
                cluster_id=self.cluster_id,
                node_id=self.node_id,
                recover=True,
                initial=True,
                new_node=new_node,
            )
        )

    # ---- TPU quorum plugin appliers ----
    #
    # The coordinator round thread only FLAGS effects here (max-merged,
    # idempotent attribute writes under the GIL) and wakes the group; the
    # partitioned step workers apply them under raftMu via
    # _apply_offload_effects.  Applying effects synchronously on the round
    # thread serialized every leader's heartbeat broadcast behind one
    # thread — at 1,365 device-ticked leaders per host that thread needed
    # ~1s of raftMu work per 1s tick, heartbeats stalled, and followers
    # deposed freshly elected leaders (measured at the 4k-group rung).
    # Spreading application across step workers is exactly the
    # reference's partitioned-worker model (execengine.go:654-706).

    def offload_commit(self, q: int, wake: bool = True) -> None:
        """Flag a device-computed commit watermark (applied in
        ``_apply_offload_effects`` where ``log.try_commit`` re-applies the
        current-term rule, raft paper p8, so stale results are rejected
        and commit outputs stay bit-identical to the scalar path).
        ``wake=False`` lets a host-plane-fed coordinator coalesce the
        step wakeup to one per group per round."""
        with self._off_mu:
            if q > self._off_commit:
                self._off_commit = q
            if self._health_track and q > self._dev_commit_seen:
                self._dev_commit_seen = q
        if wake:
            self.nh.engine.set_step_ready(self.cluster_id)

    def offload_election(self, won: bool, term: int, wake: bool = True) -> None:
        """Flag a device-tallied election outcome.  ``term`` pins the
        outcome to the campaign it tallied: a flag staged before the
        campaign restarted at a higher term is discarded at apply time."""
        with self._off_mu:
            self._off_election = (won, term)
        if wake:
            self.nh.engine.set_step_ready(self.cluster_id)

    def offload_read_confirm(
        self, low: int, high: int, term: int, wake: bool = True
    ) -> None:
        """Flag a device-confirmed ReadIndex ctx (kernels.read_confirm
        reached quorum for its slot).  Applied in
        ``_apply_offload_effects`` through ``read_index.release`` — the
        scalar prefix pop — under raftMu with leader/term guards, so a
        stale confirmation (leadership moved since the echo quorum) is
        rejected, never applied."""
        with self._off_mu:
            self._off_reads.append((low, high, term))
        if wake:
            self.nh.engine.set_step_ready(self.cluster_id)

    def offload_read_echo(
        self, from_: int, low: int, high: int, wake: bool = True
    ) -> None:
        """Fallback: a heartbeat echo for a ctx the device read plane is
        NOT tracking (pending-read slot overflow, or the echo raced a
        confirmation).  Re-routed through the scalar tally, which is a
        no-op for unknown ctxs."""
        with self._off_mu:
            self._off_read_echoes.append((from_, low, high))
        if wake:
            self.nh.engine.set_step_ready(self.cluster_id)

    def offload_tick_elect(self, wake: bool = True) -> None:
        with self._off_mu:
            self._off_elect = True
        if wake:
            self.nh.engine.set_step_ready(self.cluster_id)

    def offload_tick_heartbeat(self, wake: bool = True) -> None:
        with self._off_mu:
            self._off_hb = True
        if wake:
            self.nh.engine.set_step_ready(self.cluster_id)

    def offload_tick_demote(self, wake: bool = True) -> None:
        with self._off_mu:
            self._off_demote = True
        if wake:
            self.nh.engine.set_step_ready(self.cluster_id)

    def _apply_offload_effects(self) -> None:
        """Apply flagged device-engine effects (under raftMu, from a step
        worker).  Every effect re-runs its scalar guards, so a stale flag
        is rejected, never applied."""
        r = self.peer.raft
        with self._off_mu:
            commit_q, self._off_commit = self._off_commit, 0
            election, self._off_election = self._off_election, None
            hb, self._off_hb = self._off_hb, False
            elect, self._off_elect = self._off_elect, False
            demote, self._off_demote = self._off_demote, False
            reads, self._off_reads = self._off_reads, []
            echoes, self._off_read_echoes = self._off_read_echoes, []
        m = self.obs_registry
        if m is not None:
            # effects DELIVERED to the apply path (the scalar guards
            # below may still reject stale ones — delivered minus the
            # engine's egress counters bounds the rejection rate)
            name = "dragonboat_node_offload_applied_total"
            if commit_q:
                m.counter_add(name, labels={"kind": "commit"})
            if election is not None:
                m.counter_add(name, labels={"kind": "election"})
            if reads:
                m.counter_add(name, len(reads), labels={"kind": "read_confirm"})
            if echoes:
                m.counter_add(name, len(echoes), labels={"kind": "read_echo"})
            if elect or hb or demote:
                m.counter_add(name, labels={"kind": "tick"})
        if self.fast_lane:
            return  # native core owns the group; flags are stale
        if commit_q and r.is_leader() and r.log.try_commit(commit_q, r.term):
            # device-plane commits attribute too (ISSUE 14): the same
            # close hook the scalar commit site runs, under raftMu with
            # the current voter set — the coordinator already linked the
            # releasing round's span seq via replattr.note_device_round
            r._note_commit()
            if r.hier is not None:
                # hier close attribution (ISSUE 18): scalar matches stay
                # current in offload mode (rp.try_update precedes
                # offload.ack), so the classic kth-largest recomputes
                # here to tell a sub-quorum close from a full-quorum one
                voters = r.voting_members()
                match_of = {nid: rm.match for nid, rm in voters.items()}
                m_sorted = sorted(match_of.values())
                q_classic = m_sorted[len(m_sorted) - r.quorum()]
                r.hier.note_close(via_sub=commit_q > q_classic)
                r.hier.note_far_lag(
                    match_of, voters.keys(), r.log.committed
                )
            r.broadcast_replicate_message()
        if (
            commit_q
            and self.devsm_plane is not None
            and self.devsm_plane.bound(self.cluster_id)
        ):
            # devsm read-release gate (ISSUE 11): on the device plane
            # apply == commit — the fold runs inside the dispatch that
            # advanced this watermark — so pending reads release HERE, at
            # the device watermark, and their lookups serve from device
            # state.  Host apply (which only keeps the shadow warm) is
            # off the read path entirely; the plane's shadow fallback
            # gates on the floor recorded below.
            if commit_q > self.devsm_release_floor:
                self.devsm_release_floor = commit_q
            self.pending_reads.applied(commit_q)
        if reads and r.is_leader():
            for low, high, term in reads:
                # term-pinned like offload_election: a confirmation tallied
                # before leadership moved must not release reads under the
                # new term (become_* rebuilt read_index, so the release is
                # a no-op then anyway — the guard keeps intent explicit)
                if r.term != term:
                    continue
                ctx = SystemCtx(low=low, high=high)
                r.apply_read_releases(r.read_index.release(ctx), ctx)
        if echoes and r.is_leader():
            for from_, low, high in echoes:
                r.handle_read_index_leader_confirmation(
                    Message(from_=from_, hint=low, hint_high=high)
                )
        if election is not None:
            won, term = election
            if r.is_candidate() and r.term == term:
                # hier vote rule (raft/hier.py): the device `won` flag is
                # the classic quorum only — the scalar votes dict (always
                # maintained, handle_vote_resp runs before the offload
                # gate) re-verifies domain intersection here.  The flag
                # re-fires on later rounds, so a held promotion lands
                # once the intersecting grant arrives.
                if won and r.hier_election_ok():
                    r.become_leader()
                    r.broadcast_replicate_message()
                elif won:
                    if r.hier is not None:
                        r.hier.note_election_hold()
                else:
                    r.become_follower(r.term, 0)
        if (elect or hb or demote) and r.device_ticks:
            self._catch_up_and_tick()
        if (
            elect
            and r.device_ticks
            and not r.is_leader()
            and not r.is_observer()
            and not r.is_witness()
            and not r.self_removed()
            and not self.quiesce_mgr.quiesced()
            # scalar clock must agree: it resets synchronously under
            # raftMu on leader contact, so a device row whose staged
            # contact reset is still riding a round cannot disrupt a
            # healthy leader (same pattern as the commit term guard)
            and r.time_for_election()
        ):
            r.election_tick = 0
            r.handle(Message(from_=self.node_id, type=MT.ELECTION))
        if hb and r.device_ticks and r.is_leader():
            r.heartbeat_tick = 0
            r.handle(Message(from_=self.node_id, type=MT.LEADER_HEARTBEAT))
        if demote and r.device_ticks and r.is_leader() and r.check_quorum:
            r.election_tick = 0
            r.handle(Message(from_=self.node_id, type=MT.CHECK_QUORUM))

    def _publish_event(
        self, type: SystemEventType, index: int = 0, from_: int = 0
    ) -> None:
        self.nh.sys_events.publish(
            SystemEvent(
                type=type,
                cluster_id=self.cluster_id,
                node_id=self.node_id,
                index=index,
                from_=from_,
            )
        )

    def initialized(self) -> bool:
        return self._initialized.is_set()

    def wait_initialized(self, timeout: float = 30.0) -> bool:
        return self._initialized.wait(timeout)

    # ---- user request entry points ----

    def _timeout_ticks(self, timeout_s: float) -> int:
        ticks = int(timeout_s * 1000 / self.tick_millisecond)
        return max(ticks, 1)

    # non-cmd entry fields bound (reference settings.EntryNonCmdFieldsSize:
    # 16 u64 fields) used by the payload-size guard
    _ENTRY_NON_CMD_FIELDS_SIZE = 16 * 8

    def _check_user_op(self, payload_len: int = 0) -> None:
        """Witness replicas serve NO user operations (reference
        ``ErrInvalidOperation``, node.go:352-442), and a payload that
        cannot fit ``max_in_mem_log_size`` can never be appended
        (``ErrPayloadTooBig``, node.go:363-367)."""
        if self.config.is_witness:
            raise InvalidOperationError()
        limit = self.config.max_in_mem_log_size
        if limit and payload_len + self._ENTRY_NON_CMD_FIELDS_SIZE > limit:
            raise PayloadTooBigError()

    def propose(
        self, session: Session, cmd: bytes, timeout_s: float
    ) -> RequestState:
        self._check_user_op(len(cmd))
        ing = self.ingress
        if ing is not None:
            # host-plane ingress tier, adaptive for singles: a shard with
            # staged or draining work coalesces this proposal into the
            # batcher's next burst (ordered behind the in-flight ring);
            # a QUIET shard returns None and the proposal stages inline
            # below — the direct path, so a low-rate client never pays
            # the extra thread handoff (the measured on/off latency tax
            # of an always-on ring at window-1 arrival).  The precheck
            # above keeps witness/payload semantics synchronous either
            # way.
            rs = ing.submit_single_if_active(self, session, cmd, timeout_s)
            if rs is not None:
                return rs
        # non-empty commands are stored as ENCODED entries: 1-byte
        # version/compression header (+ snappy when configured) — reference
        # requests.go:1038-1042 + rsm/encoded.go
        tr = self.tracer
        t0 = time.perf_counter() if tr is not None else 0.0
        entry_type = EntryType.APPLICATION
        if cmd:
            cmd = get_encoded_payload(self._entry_ct, cmd)
            entry_type = EntryType.ENCODED
        rs, entry = self.pending_proposals.propose(
            session.client_id, session.series_id, cmd,
            self._timeout_ticks(timeout_s),
        )
        entry.type = entry_type
        entry.responded_to = session.responded_to
        if tr is not None:
            tr.attach_one(rs, self.cluster_id, t0)
        # native fast lane: the index is assigned and the entry staged for
        # replication + WAL entirely in C++ (completion still arrives via
        # the normal apply -> pending_proposals.applied path).  A 0 return
        # means not-enrolled/ejecting: fall back to the scalar queue.
        if self.fast_lane and self.fastlane is not None:
            if self.fastlane.nat.propose(
                self.cluster_id, entry.key, entry.client_id, entry.series_id,
                entry.responded_to, int(entry.type), cmd,
            ):
                if tr is not None:
                    tr.mark(rs, "ingress")
                return rs
        if not self.entry_q.add(entry):
            self.pending_proposals.dropped(entry.key)
            raise SystemBusyError()
        self.nh.engine.set_step_ready(self.cluster_id)
        if tr is not None:
            tr.mark(rs, "ingress")
        return rs

    def propose_batch(
        self, session: Session, cmds: List[bytes], timeout_s: float
    ) -> List[RequestState]:
        """Propose a burst of commands in one pass — semantically identical
        to N :meth:`propose` calls (one entry + one completion future per
        command), amortizing the per-request tracking and, on the native
        fast lane, appending the whole burst under one lock.  Pipelined
        clients (and the e2e benchmark) refill their windows through this;
        the per-request propose path is a first-order cost once replication
        itself is native.  One deviation from the N-calls equivalence:
        the witness/payload precheck is atomic over the whole batch — one
        oversized command rejects the batch up front (nothing partial is
        enqueued), where N calls would submit the small ones first."""
        self._check_user_op(max((len(c) for c in cmds), default=0))
        if not cmds:
            return []
        ing = self.ingress
        if ing is not None:
            # bursts always ride the batcher: they are throughput-driven
            # (pipelined window refills), tolerate the one handoff, and
            # keep the shard active so concurrent singles coalesce
            return ing.submit(self, session, cmds, timeout_s)
        # encode in one pass — empty commands are never re-encoded, and
        # the separate any(enc) scan collapsed into the same loop
        # (PROFILE_e2e.txt propose-path leaves)
        tr = self.tracer
        t0 = time.perf_counter() if tr is not None else 0.0
        ct = self._entry_ct
        enc: List[bytes] = []
        has_encoded = False
        for c in cmds:
            if c:
                enc.append(get_encoded_payload(ct, c))
                has_encoded = True
            else:
                enc.append(c)
        entry_type = EntryType.ENCODED if has_encoded else EntryType.APPLICATION
        states, entries = self.pending_proposals.propose_batch(
            session.client_id, session.series_id, enc,
            self._timeout_ticks(timeout_s),
        )
        for e in entries:
            e.type = entry_type if e.cmd else EntryType.APPLICATION
            e.responded_to = session.responded_to
        if tr is not None:
            tr.attach_all(states, self.cluster_id, t0)
        if self.fast_lane and self.fastlane is not None and all(
            e.type == entry_type for e in entries
        ):
            blob = b"".join(
                _pack_len(len(e.cmd)) + e.cmd for e in entries
            )
            if self.fastlane.nat.propose_batch(
                self.cluster_id, [e.key for e in entries], session.client_id,
                session.series_id, session.responded_to, int(entry_type),
                blob,
            ):
                if tr is not None:
                    for rs in states:
                        tr.mark(rs, "ingress")
                return states
        ok = True
        for i, e in enumerate(entries):
            if ok and not self.entry_q.add(e):
                ok = False
            if not ok:
                # queue full mid-burst: drop the remainder; each dropped
                # future resolves like a single propose hitting a full queue
                self.pending_proposals.dropped(e.key)
        self.nh.engine.set_step_ready(self.cluster_id)
        if tr is not None:
            for rs in states:
                tr.mark(rs, "ingress")
        return states

    def propose_session(self, session: Session, timeout_s: float) -> RequestState:
        self._check_user_op()
        rs, entry = self.pending_proposals.propose(
            session.client_id, session.series_id, b"",
            self._timeout_ticks(timeout_s),
        )
        # register/unregister ride the fast lane like any proposal when
        # the native session store is attached (the native apply handles
        # sid 0 / sid ~0); otherwise the apply-side would eject per
        # session op, so go scalar directly
        if (
            self.fast_lane
            and self._natsm_attached
            and self.fastlane is not None
            and self.fastlane.nat.propose(
                self.cluster_id, entry.key, entry.client_id,
                entry.series_id, entry.responded_to, int(entry.type), b"",
            )
        ):
            return rs
        if not self.entry_q.add(entry):
            self.pending_proposals.dropped(entry.key)
            raise SystemBusyError()
        self.nh.engine.set_step_ready(self.cluster_id)
        return rs

    def read(self, timeout_s: float) -> RequestState:
        self._check_user_op()
        tr = self.tracer
        t0 = time.perf_counter() if tr is not None else 0.0
        rs = self.pending_reads.read(self._timeout_ticks(timeout_s))
        if tr is not None:
            tr.attach_one(rs, self.cluster_id, t0, kind="read")
            tr.mark(rs, "ingress")
        fl = self.fastlane
        if self.fast_lane and fl is not None:
            # native ReadIndex (natraft.cpp): the context rides hinted
            # heartbeats; a quorum of echoes confirms it and the read pump
            # completes the batch.  The ctx covers every read pending at
            # take time (the scalar batching semantics).
            ctx = self.pending_reads.next_ctx()
            if not self.pending_reads.take_pending(ctx):
                return rs  # a concurrent reader's context covers this one
            if fl.nat.read_index(self.cluster_id, ctx.low, ctx.high):
                return rs
            # not the leader: forward natively (READ_INDEX to the leader,
            # confirmation returns as READ_INDEX_RESP through the read
            # pump) so follower reads stay in the lane instead of costing
            # an eject/re-enroll cycle
            if fl.nat.read_fwd(self.cluster_id, ctx.low, ctx.high):
                return rs
            # native cannot serve (ejecting / no current-term commit yet):
            # hand back to scalar raft, which runs the full protocol
            self._count_eject("read")
            self.fast_eject()
            with self.raft_mu:
                if self.peer is not None:
                    self.peer.read_index(ctx)
        self.nh.engine.set_step_ready(self.cluster_id)
        return rs

    def request_config_change(
        self, cc: ConfigChange, timeout_s: float
    ) -> RequestState:
        self._check_user_op()
        if self.fast_lane:
            self.fast_eject()
        rs = self.pending_config_change.request(
            cc, self._timeout_ticks(timeout_s)
        )
        self.nh.engine.set_step_ready(self.cluster_id)
        return rs

    def request_snapshot(self, req: SSRequest, timeout_s: float) -> RequestState:
        self._check_user_op()
        if self.fast_lane:
            self.fast_eject()
        rs = self.pending_snapshot.request(req, self._timeout_ticks(timeout_s))
        self.nh.engine.set_step_ready(self.cluster_id)
        return rs

    def request_leader_transfer(self, target: int, timeout_s: float) -> RequestState:
        self._check_user_op()
        if self.fast_lane:
            self.fast_eject()
        rs = self.pending_leader_transfer.request(
            target, self._timeout_ticks(timeout_s)
        )
        self.nh.engine.set_step_ready(self.cluster_id)
        return rs

    def stale_read(self, query):
        # a witness SM never applies payloads — a lookup would return a
        # silently empty answer for keys committed cluster-wide
        # (reference StaleRead: ErrInvalidOperation on a witness)
        if self.config.is_witness:
            raise InvalidOperationError()
        return self.sm.lookup(query)

    # ---- inbound messages ----

    def enqueue_message(self, m: Message) -> bool:
        """Queue an inbound message WITHOUT the step-ready ping; the router
        signals once per touched group after draining the whole batch."""
        if self._stopped.is_set():
            return False
        if m.type == MT.INSTALL_SNAPSHOT:
            return self.mq.must_add(m)
        return self.mq.add(m)

    def handle_message_batch(self, m: Message) -> None:
        if self.enqueue_message(m):
            self.nh.engine.set_step_ready(self.cluster_id)

    def request_tick(self) -> None:
        """Reference ``nodehost.go`` sendTickMessage: one LocalTick per RTT."""
        self.mq.add(Message(type=MT.LOCAL_TICK))
        self.nh.engine.set_step_ready(self.cluster_id)

    # ---- lazy tick delivery (tick-lite) ----

    def tick_lite(self) -> bool:
        """True when the tick worker may skip this node's per-tick wakeup:
        the raft clock is owned by the native fast lane or the device tick
        kernel, so the only per-tick host work left (pending-request
        timeout GC, tick counters) tolerates batched delivery at the next
        step (``_catch_up_ticks``)."""
        if not self._initialized.is_set():
            return False
        if self.fast_lane:
            return True
        p = self.peer
        return p is not None and p.raft.device_ticks

    def has_pending_requests(self) -> bool:
        """Cheap unlocked check used by the tick worker's staleness sweep:
        a lite node with possibly-timed-out requests gets a wakeup so GC
        runs.  New requests always arrive with their own step wakeup, so a
        racy miss here only delays GC by one sweep period."""
        return (
            self.pending_proposals.has_pending()
            or self.pending_reads.has_pending()
            or self.pending_config_change.pending() is not None
            or self.pending_snapshot.pending() is not None
            or self.pending_leader_transfer.pending() is not None
        )

    def _catch_up_ticks(self) -> int:
        """Elapsed global ticks since this node last stepped (under
        raftMu).  Capped so a long stall delivers enough ticks to fire any
        timeout-driven behavior without looping unboundedly."""
        nt = self.nh.tick_count
        delta = nt - self._seen_tick
        if delta <= 0:
            return 0
        self._seen_tick = nt
        return min(delta, max(4 * self.config.election_rtt, 16))

    def _tracker_ticks(self, delta: int) -> int:
        """How many of a catch-up delta the pending-request clocks get.

        While requests are pending the sweep wakes the node within
        ``lazy_tick_sweep_ticks``, so the tracker clock never lags real
        time by more than that; a larger delta means the backlog predates
        every live request, and delivering it would erode a
        just-registered request's deadline by idle time during which it
        did not exist (it can even expire it instantly)."""
        return min(delta, Soft.lazy_tick_sweep_ticks)

    def _catch_up_and_tick(self) -> None:
        """Shared preamble of the offload_tick_* handlers (under raftMu):
        an idle device-ticked group's scalar clock only advances at step
        time, and the device flag may be its first step in many ticks —
        catch up so the scalar-agreement guards compare a current clock."""
        if self.peer.raft.device_ticks and self.initialized():
            delta = self._catch_up_ticks()
            if delta:
                self._tick(delta, tracker_count=self._tracker_ticks(delta))

    def request_campaign(self) -> None:
        """Immediately start an election on this replica (etcd's
        ``raft.Campaign`` / MsgHup; our ``MT.ELECTION`` is the same local
        message ``raft.go:395`` injects on election timeout).  Used by
        benchmarks/tests for deterministic, fast leader placement instead of
        waiting out a randomized election timeout."""
        self.mq.add(Message(type=MT.ELECTION, from_=self.node_id))
        self.nh.engine.set_step_ready(self.cluster_id)

    def handle_snapshot_status(self, node_id: int, failed: bool) -> bool:
        """Returns True when queued (the feedback tracker retries on
        False — reference pushfunc feedback.go:36)."""
        if not self.mq.add(
            Message(type=MT.SNAPSHOT_STATUS, from_=node_id, reject=failed)
        ):
            return False
        self.nh.engine.set_step_ready(self.cluster_id)
        return True

    def handle_unreachable(self, node_id: int) -> None:
        self.mq.add(Message(type=MT.UNREACHABLE, from_=node_id))
        self.nh.engine.set_step_ready(self.cluster_id)

    # ---- step path (reference stepNode node.go:1099) ----

    def step_node(self) -> Optional[Update]:
        with self.raft_mu:
            if self._stopped.is_set() or self.peer is None:
                return None
            if not self.initialized():
                return None
            if (
                self._off_commit
                or self._off_election is not None
                or self._off_hb
                or self._off_elect
                or self._off_demote
            ):
                self._apply_offload_effects()
            delta = self._catch_up_ticks()
            if self.fast_lane:
                if not self._fast_lane_step(delta):
                    return None
                delta = 0  # consumed by the fast-lane step
            elif not self.peer.raft.device_ticks:
                # scalar-clocked groups receive real LOCAL_TICK messages;
                # the counter sync above only prevents a stale delta from
                # double-delivering after a lite→scalar transition
                delta = 0
            self._handle_events(extra_ticks=delta)
            more = self.to_apply.more_entries_to_apply()
            if self.peer.has_update(more):
                ud = self.peer.get_update(more, self.sm.get_last_applied())
                return ud
            self._maybe_enroll()
            return None

    # ---- native fast lane (fastlane.py) ----

    def _fast_lane_step(self, extra_ticks: int = 0) -> bool:
        """Enrolled-mode step (under raftMu): ticks feed only the pending
        trackers (the native core owns heartbeat/election clocks); queued
        proposals and in-flight fast-path messages are fed to the native
        core directly; anything else forces an eject.  Returns True when
        the caller should continue into the normal scalar step."""
        fl = self.fastlane
        ticks = 0
        others: List[Message] = []
        cur_term = self.peer.raft.term  # frozen while enrolled (any native
        # term change ejects), so this is the native group's term too
        for m in self.mq.get():
            if m.type == MT.LOCAL_TICK:
                ticks += 1
            elif m.type in _FAST_WIRE_TYPES and fl.ingest_message(m):
                pass  # consumed natively (in-flight at enrollment)
            elif (
                m.type == MT.REQUEST_VOTE_RESP and m.term <= cur_term
            ):
                # straggler from the election that preceded enrollment: an
                # enrolled group is never a candidate, so scalar raft would
                # no-op it — ejecting for it (round 3: router ejects) cost
                # enrollment stability for nothing
                fl.count_drop("stale-vote-resp")
            else:
                others.append(m)
        if ticks or extra_ticks:
            self.current_tick += ticks + extra_ticks
            self._tick_trackers(ticks + self._tracker_ticks(extra_ticks))
        # reads registered while (re)enrolling are served natively here
        # (the same protocol Node.read drives; ejecting for them would
        # defeat the native ReadIndex path)
        while self.pending_reads.peep():
            ctx = self.pending_reads.next_ctx()
            if not self.pending_reads.take_pending(ctx):
                break
            if not (
                fl.nat.read_index(self.cluster_id, ctx.low, ctx.high)
                or fl.nat.read_fwd(self.cluster_id, ctx.low, ctx.high)
            ):
                self._count_eject("read-fallback")
                self.fast_eject()
                self.peer.read_index(ctx)
                return True
        # proposals racing an enrollment land in the scalar queue; route
        # them into the native lane in order (indices assigned there)
        entries = self.entry_q.get()
        rest: List[Entry] = []
        for e in entries:
            if rest or e.is_config_change() or not fl.nat.propose(
                self.cluster_id, e.key, e.client_id, e.series_id,
                e.responded_to, int(e.type), e.cmd,
            ):
                rest.append(e)
        if not (others or rest or self._fast_slow_inputs()):
            return False
        self._count_eject(
            "step-msgs:" + ",".join(sorted({m.type.name for m in others}))
            if others else ("step-entries" if rest else "step-slow-input")
        )
        self.fast_eject()
        if rest:
            self.peer.propose_entries(rest)
        if others:
            self._process_messages(others)
        return True

    def _fast_slow_inputs(self) -> bool:
        """Inputs the fast lane cannot serve (checked each enrolled step;
        the user-facing entry points also eject eagerly)."""
        if (
            self.pending_config_change.pending() is not None
            or self.pending_snapshot.pending() is not None
            or self.pending_leader_transfer.pending() is not None
        ):
            return True
        if self.snapshot_due():
            if self._natsm_attached:
                # enrolled native-SM groups snapshot IN PLACE: the native
                # core captures a consistent kv+session image at its
                # applied index (natr_capture_sm) and the save runs on
                # the snapshot pool — no eject, no scalar exile.  The
                # reference's concurrent SMs never stall apply for a save
                # either (statemachine.go:552-814); this is the regular-
                # SM analog for the fast lane.
                self._save_snapshot_required()
            else:
                return True
        return False

    def snapshot_due(self) -> bool:
        """Applied delta crossed ``snapshot_entries`` (reference
        ``saveSnapshotRequired``) — the one predicate shared by the
        periodic-save trigger, the enrollment gate, and the fast lane's
        completion-pump eject trigger; a divergence between those sites
        would desynchronize eject from re-enroll."""
        se = self.config.snapshot_entries
        return bool(
            se
            and self.sm.get_last_applied() - self.sm.get_snapshot_index()
            >= se
        )

    def _maybe_enroll(self) -> None:
        """Enroll this group into the native fast lane (under raftMu, at a
        step instant with no pending raft Update — so the in-memory log is
        fully persisted and there are no queued messages).  Mid-flight
        state is allowed: the uncommitted/unapplied tail, per-peer progress
        and the apply watermark are captured into the native core
        (natraft.cpp's enrollment contract), so groups re-enter the lane
        under live load after an eject."""
        fl = self.fastlane
        if fl is None or not fl.enabled or self.fast_lane:
            return
        import time as _time

        now = _time.monotonic()
        if now < self._next_enroll_try:
            return
        self._next_enroll_try = now + 0.1
        r = self.peer.raft
        if not (r.is_leader() or (r.is_follower() and r.leader_id != 0)):
            return
        # observer/witness-BEARING groups enroll (observers become
        # non-voting native replication targets; witnesses vote natively
        # and receive metadata-only entries); observer/witness REPLICAS
        # themselves stay on the scalar path
        if r.is_observer() or r.is_witness():
            return
        if len(r.remotes) < 2:
            return
        if len(r.remotes) + len(r.observers) + len(r.witnesses) > 16:
            return
        if (
            r.has_pending_config_change()
            or r.leader_transfering()
            or self.config.quiesce
        ):
            return
        log = r.log
        li = log.last_index()
        if log.entries_to_save() or log.inmem.snapshot is not None:
            return
        if r.msgs or r.dropped_entries or r.dropped_read_indexes or r.ready_to_read:
            return
        # a ReadIndex context mid-confirmation in scalar raft (e.g. one
        # re-driven by a previous eject) would freeze until timeout if the
        # group enrolled now — its confirmation runs through scalar steps
        if r.read_index.has_pending_request():
            return
        if self._fast_slow_inputs() or self.pending_reads.peep():
            return
        if self._snapshotting.locked():
            return
        committed, processed = log.committed, log.processed
        try:
            # every index a native tally can newly commit must carry the
            # current term (raft paper p8 holds structurally in the core)
            if committed < li and (
                log.term(committed + 1) != r.term or log.term(li) != r.term
            ):
                return
        except Exception:
            return
        from .raft.remote import RemoteState

        peers = []
        min_next = li + 1
        # role: 1 = voter, 0 = observer, 2 = witness (natr_enroll contract)
        members = [(nid, r.remotes[nid], 1) for nid in sorted(r.remotes)]
        members += [
            (nid, r.observers[nid], 0) for nid in sorted(r.observers)
        ]
        members += [
            (nid, r.witnesses[nid], 2) for nid in sorted(r.witnesses)
        ]
        for nid, rp, role in members:
            if nid == self.node_id:
                continue
            if rp.state == RemoteState.SNAPSHOT or rp.match > li:
                return
            addr = self.nh.node_registry.resolve(self.cluster_id, nid)
            if addr is None:
                return
            slot = fl.slot_for(addr)
            if slot < 0:
                return
            nxt = min(max(rp.next, rp.match + 1), li + 1)
            min_next = min(min_next, nxt)
            peers.append((nid, slot, rp.match, nxt, role))
        # the native log must cover everything a resend or an apply
        # hand-off can still need
        log_first = min(processed + 1, min_next)
        if log_first < log.first_index():
            return  # tail partially compacted away: wait for idle
        try:
            prev_term = log.term(log_first - 1) if log_first > 1 else 0
        except Exception:
            return
        tail_entries = (
            log.get_entries(log_first, li + 1, 1 << 62) if li >= log_first else []
        )
        if len(tail_entries) != li - log_first + 1:
            return
        from .wire.codec import encode_entry_into

        buf = bytearray()
        for e in tail_entries:
            encode_entry_into(buf, e)
        hb_ms = max(1, self.config.heartbeat_rtt * self.tick_millisecond)
        elect_ms = max(10, 2 * self.config.election_rtt * self.tick_millisecond)
        # register BEFORE enroll: the native round thread may emit an apply
        # span for this group the instant enroll inserts it (enrolling with
        # committed > processed re-emits the unapplied window), and a span
        # arriving before registration would be dropped — wedging applied
        # below commit and timing out every later linearizable read (the
        # round-3 chaos failure)
        fl.register_node(self)
        ok = fl.nat.enroll(
            self.cluster_id,
            self.node_id,
            term=r.term,
            vote=r.vote,
            leader_id=r.leader_id,
            is_leader=r.is_leader(),
            last_index=li,
            commit=committed,
            processed=processed,
            log_first=log_first,
            prev_term=prev_term,
            shard=self.cluster_id % fl.n_shards,
            hb_period_ms=hb_ms,
            elect_timeout_ms=elect_ms,
            term_commit_ok=(
                r.is_leader() and r.has_committed_entry_at_current_term()
            ),
            peers=peers,
            tail=bytes(buf),
        )
        if ok:
            self.fast_lane = True
            fl.note_enrolled(self.cluster_id)
            self._maybe_attach_native_sm(fl)
        else:
            fl.unregister_node(self)

    def _maybe_attach_native_sm(self, fl) -> None:
        """If the user SM is a native C-ABI instance (natsm.py), let the
        enrolled group apply committed entries in C++ — the apply/notify
        rim was the measured ~40us/write Python cost (PERF.md)."""
        if self.sm.on_disk:
            return
        user = getattr(self.sm.managed, "sm", None)
        handle = getattr(user, "natsm_handle", 0)
        fn = getattr(user, "natsm_update_fn", 0)
        if handle and fn:
            # flag BEFORE attach, applied-read AFTER the flag: an apply
            # finishing in the window then still calls note_applied (the
            # native side takes max, so a racing lift is never clobbered);
            # flag-first with a late read closes the barrier-never-lifts
            # TOCTOU
            self._natsm_attached = True
            if not fl.nat.attach_sm(
                self.cluster_id, handle, fn, self.sm.get_last_applied(),
                # session store: lets session-managed entries (register/
                # dedup/unregister) apply natively too — the RSM manager
                # already fronts the same store for the scalar plane
                getattr(user, "natsm_sess_handle", 0),
                getattr(user, "natsm_sess_apply_fn", 0),
                # image serializers: periodic snapshots capture natively
                # (natr_capture_sm) instead of ejecting the group
                getattr(user, "natsm_save_fn", 0),
                getattr(user, "natsm_sess_save_fn", 0),
            ):
                self._natsm_attached = False

    def _count_eject(self, reason: str) -> None:
        if self.fastlane is not None:
            self.fastlane.count_eject(reason)

    def fast_eject(
        self, contact_lost: bool = False, reenroll_backoff: bool = False
    ) -> None:
        """Hand the group back from the native core to scalar raft.

        Rebuilds exactly the state the Python raft object would have had:
        log watermarks (committed/processed), a fresh saved in-memory tail,
        the stable-log window in the LogReader (entries were persisted by
        the native core), per-remote progress, and the persisted-state
        caches (the native core wrote State/MaxIndex records directly, so
        the Python rdbcache must be refreshed to match the disk)."""
        fl = self.fastlane
        if fl is None:
            return
        with self.raft_mu:
            if not self.fast_lane:
                return
            try:
                st = fl.eject_locked(self)
            except IOError:
                # WAL tail flush failed during the handoff: the LogDB holds
                # records the scalar state cannot account for.  Resuming
                # would reuse persisted indices — fail the replica instead
                # (the rest of the group continues; restart replays the log)
                plog.critical(
                    "%s fast-lane eject failed on WAL error; stopping replica",
                    self.describe(),
                )
                self.fast_lane = False
                self._natsm_attached = False
                fl.note_ejected(self.cluster_id)
                self._stopped.set()
                return
            self.fast_lane = False
            was_natsm = self._natsm_attached
            self._natsm_attached = False
            fl.note_ejected(self.cluster_id)
            if st is None or self.peer is None:
                return
            if was_natsm:
                # native applies bypassed notify_raft_last_applied; catch
                # raft's applied view up or has_config_change_to_apply()
                # (committed > applied) would silently refuse every
                # campaign after the eject — the failover wedge
                self.peer.notify_raft_last_applied(self.sm.get_last_applied())
            r = self.peer.raft
            log = r.log
            # stable window: native entries are in the LogDB already
            _, prev_last = self.logreader.get_range()
            if st.last_index > prev_last:
                self.logreader.set_range(
                    prev_last + 1, st.last_index - prev_last
                )
            from .raft.inmemory import InMemory
            from .raft.remote import RemoteState

            log.inmem = InMemory(st.last_index, log.inmem.rl)
            log.committed = st.commit
            log.processed = st.commit
            for nid, (match, _next) in st.peers.items():
                # observers/witnesses enroll as flagged peers; restore
                # their progress into the matching membership dict
                rp = (
                    r.remotes.get(nid)
                    or r.observers.get(nid)
                    or r.witnesses.get(nid)
                )
                if rp is None:
                    continue
                rp.match = match
                rp.next = match + 1
                rp.state = RemoteState.RETRY
                rp.active = True
            selfrp = r.remotes.get(self.node_id)
            if selfrp is not None:
                selfrp.try_update(st.last_index)
            r.reset_match_value_array()
            self.peer.prev_state = State(
                term=st.term, vote=st.vote, commit=st.commit
            )
            # refresh the Python-side persisted-state caches to the records
            # the native core wrote (else a later suppressed write would
            # leave disk stale, or a redundant one would be re-issued)
            self.logdb.refresh_cached_state(
                self.cluster_id,
                self.node_id,
                st.term,
                st.vote,
                st.commit,
                st.last_index,
            )
            # the device quorum row (if the TPU plugin is live) went stale
            # while the native core advanced commits; rebuild it
            coord = getattr(self, "quorum_coordinator", None)
            if coord is not None:
                coord.register(self)
            # pending native ReadIndex contexts died with the native
            # group; re-drive them through the scalar protocol (duplicate
            # confirmations are harmless) so in-flight reads don't strand
            if r.is_leader():
                for ctx in self.pending_reads.pending_ctxs():
                    self.peer.read_index(ctx)
            if contact_lost or reenroll_backoff:
                # the native clock already waited out the election window
                # with zero leader contact — without this the group would
                # re-enroll (leader_id still set, log quiescent), reset the
                # native contact clock and ping-pong forever instead of
                # ever campaigning.  reenroll_backoff ejects (commit-stall
                # watchdog, inbound REQUEST_VOTE) need the same grace: on
                # a netsplit follower the watchdog fires BEFORE the
                # contact-loss eject (the readers_live gate defers contact
                # loss while no bytes flow anywhere), and a peer's vote
                # request is dropped by the §6 lease while the frozen
                # election clock still reads "leader heard recently" — in
                # both shapes an instant re-enroll resets every native
                # liveness clock and the group ping-pongs forever with
                # the election clock never running (the partition_tcp
                # no-leader stall)
                import time as _time

                self._next_enroll_try = _time.monotonic() + 2.0 * (
                    2 * self.config.election_rtt * self.tick_millisecond
                ) / 1000.0
                if contact_lost and r.is_follower():
                    # zero leader contact is proven; scalar raft may
                    # campaign immediately.  NOT on the backoff-only
                    # shapes: the leader may be alive (flow-control
                    # wedge), and the grace window alone lets the scalar
                    # clock age past the vote-drop lease — heartbeats
                    # keep resetting it if the leader is actually there
                    r.election_tick = r.randomized_election_timeout
        self.nh.engine.set_step_ready(self.cluster_id)

    def _handle_events(self, extra_ticks: int = 0) -> None:
        self._handle_received_messages(extra_ticks)
        self._handle_read_index()
        self._handle_config_change()
        self._handle_proposals()
        self._handle_leader_transfer()
        self._handle_snapshot_request()

    def _handle_received_messages(self, extra_ticks: int = 0) -> None:
        self._process_messages(self.mq.get(), extra_ticks)

    def _process_messages(self, msgs, extra_ticks: int = 0) -> None:
        # lazy catch-up ticks represent time that elapsed BEFORE this step
        # — deliver them ahead of the messages so term-filter guards that
        # read the election clock (the section-6 vote-drop lease,
        # raft.py drop_request_vote_from_high_term_node) compare a current
        # clock, exactly as the offload_tick_* handlers do
        if extra_ticks:
            self._tick(
                extra_ticks, tracker_count=self._tracker_ticks(extra_ticks)
            )
        ticks = 0
        for m in msgs:
            if m.type == MT.LOCAL_TICK:
                ticks += 1
            elif m.type == MT.QUIESCE:
                self.quiesce_mgr.try_enter_quiesce()
            elif m.type == MT.UNREACHABLE:
                # local report from the transport, not a wire message
                # (reference node.go:1257-1286 handleReceivedMessages)
                self.peer.report_unreachable_node(m.from_)
            elif m.type == MT.SNAPSHOT_STATUS:
                self.peer.report_snapshot_status(m.from_, m.reject)
            elif m.type == MT.ELECTION:
                # local campaign request (request_campaign); must go through
                # Peer.campaign — Peer.handle rejects local message types.
                # Only honored when locally injected: a wire message must
                # not be able to force a follower to campaign against a
                # healthy leader (reference treats ELECTION as local-only)
                if m.from_ == self.node_id:
                    self.quiesce_mgr.record_activity(m.type)
                    self.peer.campaign()
            else:
                if self.quiesce_mgr.enabled:
                    self.quiesce_mgr.record_activity(m.type)
                if m.type == MT.INSTALL_SNAPSHOT and m.snapshot is not None:
                    self._handle_install_snapshot(m)
                else:
                    self.peer.handle(m)
        if ticks:
            self._tick(ticks)
        if self.quiesce_mgr.just_entered_quiesce():
            self._broadcast_quiesce()

    def _handle_install_snapshot(self, m: Message) -> None:
        # record arrival; raft decides whether to accept (restore path)
        self.peer.handle(m)

    def _broadcast_quiesce(self) -> None:
        for nid in list(self.peer.raft.remotes):
            if nid != self.node_id:
                self.nh.send_message(
                    Message(
                        type=MT.QUIESCE,
                        cluster_id=self.cluster_id,
                        from_=self.node_id,
                        to=nid,
                    )
                )

    def _tick(self, count: int, tracker_count: Optional[int] = None) -> None:
        for _ in range(count):
            self.current_tick += 1
            self.quiesce_mgr.increase_quiesce_tick()
            if self.quiesce_mgr.quiesced():
                self.peer.quiesced_tick()
            else:
                self.peer.tick()
        self._tick_trackers(count if tracker_count is None else tracker_count)
        self._update_leader_info()

    def _tick_trackers(self, count: int) -> None:
        """Advance the pending-request timeout clocks only — the raft clock
        itself is owned by the native core while the group is enrolled."""
        for _ in range(count):
            self.pending_proposals.tick()
            self.pending_reads.tick()
            self.pending_config_change.tick()
            self.pending_snapshot.tick()
            self.pending_leader_transfer.tick()

    def _update_leader_info(self) -> None:
        lid = self.peer.raft.leader_id
        if lid != self.leader_id:
            self.leader_id = lid

    def _handle_proposals(self) -> None:
        entries = self.entry_q.get()
        if entries:
            self.quiesce_mgr.record_activity(MT.PROPOSE)
            self.peer.propose_entries(entries)
            tr = self.tracer
            if tr is not None:
                tr.mark_entries(entries, "raft_step")

    def _handle_read_index(self) -> None:
        if self.pending_reads.peep():
            ctx = self.pending_reads.next_ctx()
            if self.pending_reads.take_pending(ctx):
                self.quiesce_mgr.record_activity(MT.READ_INDEX)
                self.peer.read_index(ctx)

    def _handle_config_change(self) -> None:
        cc = self.pending_config_change.take()
        if cc is not None:
            rs = self.pending_config_change.pending()
            key = rs.key if rs is not None else 0
            self.quiesce_mgr.record_activity(MT.CONFIG_CHANGE_EVENT)
            self.peer.propose_config_change(cc, key)

    def _handle_leader_transfer(self) -> None:
        target = self.pending_leader_transfer.take()
        if target is not None:
            self.peer.request_leader_transfer(target)
            # completion is observed via leader change, not a raft ack
            self.pending_leader_transfer.notify(
                RequestResult(code=RequestResultCode.COMPLETED)
            )

    def _handle_snapshot_request(self) -> None:
        req = self.pending_snapshot.take()
        if req is not None:
            self.to_apply.enqueue(
                Task(
                    cluster_id=self.cluster_id,
                    node_id=self.node_id,
                    save=True,
                    ss_request=req,
                )
            )
            self.nh.engine.set_apply_ready(self.cluster_id)

    # ---- update execution (reference processRaftUpdate node.go:1058) ----

    def process_dropped(self, ud: Update) -> None:
        for e in ud.dropped_entries:
            if e.is_config_change():
                # reference node.go: dropped config changes notify their
                # own single-slot tracker so Sync* wrappers can retry
                rs = self.pending_config_change.pending()
                if rs is not None and rs.key == e.key:
                    self.pending_config_change.notify(
                        RequestResult(code=RequestResultCode.DROPPED)
                    )
            else:
                self.pending_proposals.dropped(e.key)
        if ud.dropped_read_indexes:
            self.pending_reads.dropped(ud.dropped_read_indexes)

    def send_replicate_messages(self, ud: Update) -> None:
        """Replicate messages go out BEFORE the fsync (thesis §10.2.1,
        reference ``execengine.go:954-961``)."""
        ra = self.replattr
        if ra is not None and self.fastlane is None:
            # replication tracing (ISSUE 14): sampled proposals' fan-out
            # messages get a per-peer ReplTrace context and open a
            # commit record.  Gated off under the native fast lane —
            # its C readers own the wire and do not speak the trace
            # extension (enrolled groups bypass this path anyway).
            tr = self.tracer
            if tr is not None:
                ra.attach_sends(self.cluster_id, ud.messages, tr)
        for m in ud.messages:
            if m.type == MT.REPLICATE:
                self.nh.send_message(m)

    def process_raft_update(self, ud: Update) -> None:
        # a restore update can carry BOTH the snapshot and the log tail
        # past it: the snapshot must move the logreader window FIRST or the
        # append trips the gap check and the committer retries the same
        # update forever (soak-caught: restarted follower wedged with
        # "gap in log" after a streamed snapshot install).  Reference
        # ordering: node.go applySnapshotAndUpdate runs the snapshot half
        # before entry processing.
        if not is_empty_snapshot(ud.snapshot):
            try:
                self.logreader.apply_snapshot(ud.snapshot)
            except Exception as e:  # SnapshotOutOfDate
                plog.warning("%s apply_snapshot: %s", self.describe(), e)
        self.logreader.append(ud.entries_to_save)
        for m in ud.messages:
            if m.type == MT.REPLICATE:
                continue
            if m.type == MT.INSTALL_SNAPSHOT:
                self.nh.send_snapshot_message(m)
            else:
                ctx = m.trace
                if ctx is not None and ctx.t_append:
                    # follower half of a sampled replication (ISSUE 14):
                    # this loop runs AFTER the committer's fsync, so the
                    # appended entries the ack covers are durable here —
                    # stamp the fsync point and the ack hand-off, and
                    # file the leg locally so this host's dump renders
                    # the follower side of the flow
                    now = time.time()
                    if not ctx.t_fsync:
                        ctx.t_fsync = now
                    if not ctx.t_ack:
                        ctx.t_ack = now
                        tr = self.tracer
                        if tr is not None:
                            tr.add_repl_leg(ctx)
                self.nh.send_message(m)
        if ud.ready_to_reads:
            self.pending_reads.add_ready(ud.ready_to_reads)
            # devsm groups release at the device watermark too (floor is
            # 0 everywhere else — the max is the identity then)
            self.pending_reads.applied(
                max(self.sm.get_last_applied(), self.devsm_release_floor)
            )
        self._apply_snapshot_and_update(ud)
        self._save_snapshot_required()

    def _apply_snapshot_and_update(self, ud: Update) -> None:
        if not is_empty_snapshot(ud.snapshot):
            ss = ud.snapshot
            plog.info(
                "%s installing snapshot index %d", self.describe(), ss.index
            )
            # the logreader window already moved at the top of
            # process_raft_update (before the entry append)
            self.to_apply.enqueue(
                Task(
                    cluster_id=self.cluster_id,
                    node_id=self.node_id,
                    recover=True,
                    ss=ss,
                    index=ss.index,
                )
            )
            self.nh.engine.set_apply_ready(self.cluster_id)
        if ud.committed_entries:
            self.to_apply.enqueue(
                Task(
                    cluster_id=self.cluster_id,
                    node_id=self.node_id,
                    entries=ud.committed_entries,
                )
            )
            self.nh.engine.set_apply_ready(self.cluster_id)
        if ud.more_committed_entries:
            self.nh.engine.set_step_ready(self.cluster_id)

    def _save_snapshot_required(self) -> None:
        """Auto snapshot every ``snapshot_entries`` applied (reference
        ``node.go:605`` ``saveSnapshotRequired``)."""
        if not self.snapshot_due():
            return
        # held until the queued PERIODIC save completes (_save_snapshot
        # releases it), so duplicate save tasks never pile up
        if not self._snapshotting.acquire(blocking=False):
            return
        self.to_apply.enqueue(
            Task(
                cluster_id=self.cluster_id,
                node_id=self.node_id,
                save=True,
                ss_request=SSRequest(type=SSReqType.PERIODIC),
            )
        )
        self.nh.engine.set_apply_ready(self.cluster_id)

    def commit_raft_update(self, ud: Update) -> None:
        with self.raft_mu:
            if self.peer is not None:
                self.peer.commit(ud)

    # ---- apply path (reference processApplies / handleTask) ----

    def handle_apply_tasks(self) -> None:
        # serialized: the engine's apply workers already serialize per
        # group among themselves, but the fast lane's apply pump calls
        # this inline too — an unsynchronized drain would interleave
        # get_all() batches and apply entries out of order
        with self._apply_serial:
            self._handle_apply_tasks_locked()

    def _handle_apply_tasks_locked(self) -> None:
        tasks = self.to_apply.get_all()
        for t in tasks:
            if self._stopped.is_set():
                return
            if t.save:
                # snapshot saves run on the dedicated pool (reference
                # execengine.go:240-635) so a slow user save_snapshot never
                # blocks the other groups sharing this apply worker; the
                # regular-SM save/update lock in rsm.StateMachine keeps the
                # image consistent against concurrent applies
                self.nh.engine.submit_snapshot(
                    lambda t=t: self._save_snapshot(t)
                )
            elif t.stream:
                self.nh.engine.submit_snapshot(
                    lambda t=t: self._stream_snapshot(t)
                )
            elif t.recover:
                self._recover_from_snapshot(t)
            else:
                self.sm.handle([t])
                applied = self.sm.get_last_applied()
                with self.raft_mu:
                    if self.peer is not None:
                        self.peer.notify_raft_last_applied(applied)
                self.sm.set_batched_last_applied(applied)
                self.pending_reads.applied(applied)
                if self._natsm_attached and self.fastlane is not None:
                    # lift the native-SM attach barrier: the native plane
                    # applies only past what Python has applied
                    self.fastlane.nat.note_applied(self.cluster_id, applied)
                self.nh.engine.set_step_ready(self.cluster_id)

    def _try_capture_save(self, req: SSRequest):
        """Snapshot an ENROLLED native-SM group from a consistent image
        captured by the native core (``natr_capture_sm``) — the no-eject
        periodic-snapshot path.  Returns ``(ss, env)`` or ``None`` to fall
        back to the scalar ``sm.save`` flow (which requires the group to
        be off the fast lane).  Exported requests stay scalar: the export
        flow's env/finalize handling expects the standard savable."""
        fl = self.fastlane
        if (
            fl is None
            or not self.fast_lane
            or not self._natsm_attached
            or req.exported
            or self.sm.on_disk
        ):
            return None
        # membership must be captured atomically with the capture index:
        # snapshot it BEFORE the native capture, then verify the
        # config-change id did not move while the capture ran (a racing
        # fast_eject + config-change apply in that window would otherwise
        # label the image with membership newer than its index).  The
        # pre-capture view is consistent with the captured index exactly
        # when the ccid is unchanged — config changes only apply on the
        # Python plane, which the enrolled lane holds off.
        pre_members = self.sm.get_membership()
        cap = fl.nat.capture_sm(self.cluster_id)
        if cap is None or (
            self.sm.get_membership().config_change_id
            != pre_members.config_change_id
        ):
            # cannot capture (no save fn on the attached SM / attach
            # barrier still in flight / mid-eject), or membership moved
            # under the capture: restore the pre-capture behavior —
            # leave the lane FIRST, because a scalar sm.save() while
            # native applies keep mutating the shared state would label
            # the image with a stale index (double-apply after recovery)
            if self.fast_lane:
                self._count_eject("snapshot-due")
                self.fast_eject()
            return None
        index, term, kv_image, sess_image = cap
        # entries through the captured index are durable (native applies
        # only run past the local fsync watermark) but the Python-side
        # LogReader window froze at enrollment; extend it (monotonic,
        # atomic vs a racing fast_eject) so create_snapshot/compaction
        # accept the new snapshot index
        self.logreader.extend_to(index)
        return self.sm.save_from_capture(
            req, index, term, kv_image, sess_image, membership=pre_members
        )

    def _save_snapshot(self, t: Task) -> None:
        req = t.ss_request
        # only user-initiated requests may resolve the pending-snapshot slot;
        # PERIODIC failures must not complete an unrelated user request
        user_req = req.type in (SSReqType.USER_REQUESTED, SSReqType.EXPORTED)
        try:
            try:
                cap = self._try_capture_save(req)
                ss, env = cap if cap is not None else self.sm.save(req)
            except SnapshotIgnored:
                if user_req:
                    self.pending_snapshot.notify(
                        RequestResult(code=RequestResultCode.REJECTED)
                    )
                return
            except Exception as e:
                plog.error("%s snapshot save failed: %s", self.describe(), e)
                if user_req:
                    self.pending_snapshot.notify(
                        RequestResult(code=RequestResultCode.ABORTED)
                    )
                return
            if req.exported:
                # promote tmp → final inside the user's export dir; keep the
                # flag file — ImportSnapshot reads it (tools/import.go:130)
                try:
                    env.finalize_snapshot()
                except Exception as e:
                    plog.error("%s export finalize failed: %s", self.describe(), e)
                    env.remove_tmp_dir()
                    self.pending_snapshot.notify(
                        RequestResult(code=RequestResultCode.ABORTED)
                    )
                    return
                self.pending_snapshot.notify(
                    RequestResult(
                        code=RequestResultCode.COMPLETED, snapshot_index=ss.index
                    )
                )
                return
            try:
                self.snapshotter.commit(ss, env)
                self._publish_event(SystemEventType.SNAPSHOT_CREATED, index=ss.index)
            except FileExistsError:
                env.remove_tmp_dir()
                if user_req:
                    self.pending_snapshot.notify(
                        RequestResult(code=RequestResultCode.REJECTED)
                    )
                return
            try:
                self.logreader.create_snapshot(ss)
            except Exception as e:
                plog.warning("%s create_snapshot: %s", self.describe(), e)
                if user_req:
                    self.pending_snapshot.notify(
                        RequestResult(code=RequestResultCode.ABORTED)
                    )
                return
            self._compact_log(ss, req)
            self.snapshotter.compact()
            self._publish_event(SystemEventType.SNAPSHOT_COMPACTED, index=ss.index)
            if req.type == SSReqType.USER_REQUESTED:
                self.pending_snapshot.notify(
                    RequestResult(
                        code=RequestResultCode.COMPLETED, snapshot_index=ss.index
                    )
                )
        finally:
            if req.type == SSReqType.PERIODIC:
                self._snapshotting.release()

    # ---- on-disk SM snapshot streaming (reference node.go:718-738) ----

    def push_stream_snapshot_request(self, to: int) -> None:
        """Queue a stream-to-follower task (reference
        ``pushStreamSnapshotRequest``)."""
        self.to_apply.enqueue(
            Task(
                cluster_id=self.cluster_id,
                node_id=self.node_id,
                stream=True,
                stream_to=to,
                ss_request=SSRequest(type=SSReqType.STREAMING),
            )
        )
        self.nh.engine.set_apply_ready(self.cluster_id)

    def _stream_snapshot(self, t: Task) -> None:
        to = t.stream_to
        sink = self.nh.transport.get_stream_sink(self.cluster_id, to)
        if sink is None:
            plog.warning(
                "%s no stream sink for %d (unreachable/at capacity)",
                self.describe(), to,
            )
            # report failure so the remote leaves Snapshot state eventually
            self.nh._snapshot_status(self.cluster_id, to, True)
            return
        try:
            self.sm.stream(sink, to, self.nh.nhconfig.get_deployment_id())
        except Exception as e:  # noqa: BLE001
            plog.error("%s streaming to %d failed: %s", self.describe(), to, e)
            sink.stop()

    def _compact_log(self, ss: Snapshot, req: SSRequest) -> None:
        """Reference ``node.go:689-716``: keep ``compaction_overhead``
        entries behind the snapshot."""
        overhead = (
            req.compaction_overhead
            if req.override_compaction_overhead
            else self.config.compaction_overhead
        )
        if ss.index <= overhead:
            return
        compact_to = ss.index - overhead
        try:
            self.logreader.compact(compact_to)
        except Exception:
            return
        self.logdb.remove_entries_to(self.cluster_id, self.node_id, compact_to)
        with self._compacted_to_mu:
            self._compacted_to = compact_to
        self._publish_event(SystemEventType.LOG_COMPACTED, index=compact_to)

    def _recover_from_snapshot(self, t: Task) -> None:
        if t.initial:
            # restart path: newest local snapshot, if any
            ss = self.snapshotter.get_most_recent_snapshot()
            if ss is not None and not ss.is_empty():
                t = Task(
                    cluster_id=self.cluster_id,
                    node_id=self.node_id,
                    recover=True,
                    ss=ss,
                )
                self.sm.recover(t)
                self._publish_event(
                    SystemEventType.SNAPSHOT_RECOVERED, index=ss.index
                )
            if self.sm.on_disk:
                self.sm.open()
            # reference node.go:1382-1410 setInitialStatus: raft must learn
            # the recovered applied index or has_config_change_to_apply()
            # (committed > applied) suppresses elections forever on a node
            # whose log tail is empty (e.g. after ImportSnapshot repair)
            applied = self.sm.get_last_applied()
            if applied:
                with self.raft_mu:
                    if self.peer is not None:
                        self.peer.notify_raft_last_applied(applied)
                self.sm.set_batched_last_applied(applied)
                self.pending_reads.applied(applied)
            self._initialized.set()
            self._publish_event(SystemEventType.NODE_READY)
            self.nh.engine.set_step_ready(self.cluster_id)
            return
        try:
            self.sm.recover(t)
        except Exception as e:
            plog.error("%s recover failed: %s", self.describe(), e)
            raise
        if t.ss is not None:
            self._publish_event(
                SystemEventType.SNAPSHOT_RECOVERED, index=t.ss.index
            )
        applied = self.sm.get_last_applied()
        with self.raft_mu:
            if self.peer is not None:
                self.peer.notify_raft_last_applied(applied)
        self.sm.set_batched_last_applied(applied)
        self.nh.engine.set_step_ready(self.cluster_id)

    # ---- rsm.INodeProxy callbacks ----

    def node_ready(self) -> None:
        self.nh.engine.set_step_ready(self.cluster_id)

    def apply_update(
        self,
        entry: Entry,
        result: Result,
        rejected: bool,
        ignored: bool,
        notify_read: bool,
    ) -> None:
        if not ignored and entry.key:
            self.pending_proposals.applied(
                entry.key, entry.client_id, entry.series_id, result, rejected
            )

    def apply_config_change(
        self, cc: ConfigChange, key: int, rejected: bool
    ) -> None:
        with self.raft_mu:
            if self.peer is None:
                return
            if rejected:
                self.peer.reject_config_change()
            else:
                self.peer.apply_config_change(cc)
                self._on_config_change_applied(cc)
                self._publish_event(
                    SystemEventType.MEMBERSHIP_CHANGED, from_=cc.node_id
                )
        rs = self.pending_config_change.pending()
        if rs is not None and rs.key == key and key != 0:
            code = (
                RequestResultCode.REJECTED
                if rejected
                else RequestResultCode.COMPLETED
            )
            self.pending_config_change.notify(RequestResult(code=code))

    def _on_config_change_applied(self, cc: ConfigChange) -> None:
        if cc.type in (
            ConfigChangeType.ADD_NODE,
            ConfigChangeType.ADD_OBSERVER,
            ConfigChangeType.ADD_WITNESS,
        ):
            self.nh.node_registry.add(self.cluster_id, cc.node_id, cc.address)
        elif cc.type == ConfigChangeType.REMOVE_NODE:
            self.nh.node_registry.remove(self.cluster_id, cc.node_id)
            if cc.node_id == self.node_id:
                self._delete_required = True

    def restore_remotes(self, ss: Snapshot) -> None:
        with self.raft_mu:
            if self.peer is not None:
                self.peer.restore_remotes(ss)
        for nid, addr in ss.membership.addresses.items():
            if nid != self.node_id:
                self.nh.node_registry.add(self.cluster_id, nid, addr)

    def should_stop(self) -> bool:
        return self._stopped.is_set()

    # ---- status / shutdown ----

    def get_membership(self) -> Membership:
        return self.sm.get_membership()

    def get_leader_id(self):
        with self.raft_mu:
            if self.peer is None:
                return 0, False
            lid = self.peer.raft.leader_id
            return lid, lid != 0

    def is_leader(self) -> bool:
        with self.raft_mu:
            return self.peer is not None and self.peer.raft.is_leader()

    def lease_status(self) -> Optional[dict]:
        """Leader-lease snapshot (ISSUE 10): ``None`` when the group runs
        without ``Config.read_lease``; else the lease's plain-int stats
        plus whether it is currently valid and its remaining ticks —
        read under raftMu so the view is consistent."""
        with self.raft_mu:
            if self.peer is None:
                return None
            r = self.peer.raft
            lease = r.lease
            if lease is None:
                return None
            d = lease.stats()
            remaining = 0
            if r.is_leader():
                remaining = lease.remaining(
                    r.tick_count, r.quorum(), r.voting_members(), r.node_id
                )
            d["held"] = remaining > 0
            d["remaining_ticks"] = max(remaining, 0)
            return d

    def health_snapshot(self, lock_timeout: float = 0.05) -> dict:
        """One health-sample row for this group (obs/health.py, ISSUE
        13): raft plane (state/term/leader/commit/applied), request
        pressure, reachability (check-quorum leaders), device commit
        watermark, lease and devsm status.  Low-rate caller contract:
        ``raft_mu`` is acquired with ``lock_timeout`` (``<= 0`` =
        non-blocking) — a contended group reports ``busy: True`` with
        only the lock-free fields rather than stalling the tick worker
        behind a long step.  The SAMPLER owns the whole-pass budget:
        it shrinks ``lock_timeout`` as its deadline approaches, so a
        host full of contended groups degrades to busy rows instead of
        n_groups × timeout of tick-worker stall."""
        self._health_track = True
        d = {
            "node_id": self.node_id,
            "pending_proposals": self.pending_proposals.has_pending(),
            "pending_reads": self.pending_reads.has_pending(),
            "applied": self.sm.get_last_applied(),
            "dev_commit": self._dev_commit_seen,
            "fast_lane": self.fast_lane,
        }
        plane = self.devsm_plane
        if plane is not None:
            dv = plane.health_snapshot(self.cluster_id)
            if dv is not None:
                dv["release_floor"] = self.devsm_release_floor
                d["devsm"] = dv
        if lock_timeout > 0:
            acquired = self.raft_mu.acquire(timeout=lock_timeout)
        else:
            acquired = self.raft_mu.acquire(blocking=False)
        if not acquired:
            d["busy"] = True
            return d
        try:
            peer = self.peer
            if peer is None:
                d["busy"] = True
                return d
            r = peer.raft
            d["state"] = r.state.name
            d["term"] = r.term
            d["leader_id"] = r.leader_id
            d["committed"] = r.log.committed
            voters = r.voting_members()
            d["voters"] = len(voters)
            d["quorum"] = r.quorum()
            if r.is_leader() and r.check_quorum:
                # reachability from the check-quorum activity flags: set
                # on every response, cleared once per election window —
                # only meaningful where that refresh loop runs (a
                # non-check-quorum leader's flags latch True forever)
                d["reachable"] = sum(
                    1
                    for nid, rp in voters.items()
                    if nid == r.node_id or rp.is_active()
                )
                # the ids behind the count: quorum_at_risk actuation
                # (obs/recovery.py) evicts exactly these
                d["unreachable_ids"] = [
                    nid
                    for nid, rp in voters.items()
                    if nid != r.node_id and not rp.is_active()
                ]
            lease = r.lease
            if lease is not None:
                ls = lease.stats()
                remaining = 0
                if r.is_leader():
                    remaining = lease.remaining(
                        r.tick_count, r.quorum(), voters, r.node_id
                    )
                ls["held"] = remaining > 0
                ls["remaining_ticks"] = max(remaining, 0)
                d["lease"] = ls
        finally:
            self.raft_mu.release()
        return d

    def request_compaction(self) -> threading.Event:
        """User-requested LogDB compaction up to the last auto-compacted
        watermark (reference ``node.go:912-927`` requestCompaction —
        swap-to-zero, so back-to-back requests don't recompact).  Raises
        RejectedError when nothing has been compacted since the last
        request."""
        with self._compacted_to_mu:
            compact_to, self._compacted_to = self._compacted_to, 0
        if compact_to == 0:
            from .requests import RejectedError

            raise RejectedError("nothing to compact")
        # the compaction worker publishes LOGDB_COMPACTED on completion
        # (logdb.on_compaction, wired by NodeHost)
        return self.logdb.compact_entries_to(
            self.cluster_id, self.node_id, compact_to
        )

    def describe(self) -> str:
        return f"node {self.cluster_id}:{self.node_id}"

    def requested_stop(self) -> bool:
        return self._stopped.is_set()

    def stop(self) -> None:
        if self.fast_lane:
            # clean shutdown: flush the native WAL tail and reclaim the
            # scalar state (a crash without this is still raft-safe — only
            # unreplicated, unacked proposals are lost)
            try:
                self.fast_eject()
            except Exception:
                plog.exception("%s fast-lane eject on stop", self.describe())
        self._stopped.set()
        self.sm.stopc.stop()
        self.entry_q.close()
        self.mq.close()
        self.pending_proposals.close()
        self.pending_reads.close()
        self.pending_config_change.close()
        self.pending_snapshot.close()
        self.pending_leader_transfer.close()
        self.sm.offloaded()
