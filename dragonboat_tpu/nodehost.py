"""NodeHost: the public facade hosting many raft groups in one process.

Reference: ``nodehost.go`` — lifecycle (``NewNodeHost``, ``StartCluster`` ×3
SM kinds, ``StopCluster``), request APIs (sync/async propose, linearizable
read, membership changes, snapshots, leader transfer), the cluster registry
with its change counter, tick fan-out and incoming-message routing.
"""
from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional, Tuple

from . import vfs
from .client import Session
from .events import (
    RaftEventListener,
    SysEventListener,
    SystemEvent,
    SystemEventType,
)
from .config import Config, NodeHostConfig
from .engine import Engine
from .logdb import LogReader, open_logdb
from .logger import get_logger
from .node import _FAST_WIRE_TYPES, Node
from .raft.peer import PeerAddress
from .requests import (
    ClusterAlreadyExistError,
    ClusterNotFoundError,
    RejectedError,
    RequestResult,
    RequestState,
    TimeoutError_,
)
from .settings import Soft
from .rsm import (
    SSReqType,
    SSRequest,
    StateMachine,
    from_concurrent_sm,
    from_on_disk_sm,
    from_regular_sm,
)
from .snapshotter import Snapshotter
from .statemachine import Result
from .transport import Registry, Transport, create_transport
from .wire import (
    Bootstrap,
    ConfigChange,
    ConfigChangeType,
    Membership,
    Message,
    MessageBatch,
    MessageType,
    StateMachineType,
)

plog = get_logger("nodehost")


@dataclass
class ClusterInfo:
    """Snapshot of one managed Raft cluster node (reference
    ``nodehost.go:163`` ``ClusterInfo``)."""

    cluster_id: int = 0
    node_id: int = 0
    nodes: Dict[int, str] = field(default_factory=dict)
    observers: Dict[int, str] = field(default_factory=dict)
    witnesses: Dict[int, str] = field(default_factory=dict)
    config_change_index: int = 0
    state_machine_type: StateMachineType = StateMachineType.REGULAR
    is_leader: bool = False
    is_observer: bool = False
    is_witness: bool = False
    pending: bool = False  # nothing applied yet — details unavailable


@dataclass
class NodeHostInfo:
    """Host-wide inventory (reference ``nodehost.go:193``
    ``NodeHostInfo``): the managed clusters plus every (cluster, node)
    with raft state in the LogDB."""

    raft_address: str = ""
    cluster_info_list: list = field(default_factory=list)
    log_info: list = field(default_factory=list)  # [(cluster_id, node_id)]


class NodeHost:
    """Reference ``nodehost.go:246`` ``NodeHost``."""

    def __init__(self, nhconfig: NodeHostConfig):
        nhconfig.validate()
        nhconfig.prepare()
        self.nhconfig = nhconfig
        self._mu = threading.Lock()
        self._clusters: Dict[int, Node] = {}
        self._csi = 0  # cluster-set change counter (reference clusterMu.csi)
        self._stopped = threading.Event()
        # global tick counter (lazy tick delivery): nodes with native/
        # device-owned raft clocks read this at step time instead of
        # receiving one LOCAL_TICK message per RTT each
        self.tick_count = 0
        # filesystem the snapshot paths go through (ExpertConfig.fs lets
        # tests run diskless via vfs.MemFS or inject faults via vfs.ErrorFS,
        # which is auto-detected like the reference nodehost.go:321-327)
        self._fs = nhconfig.expert.fs or nhconfig.fs or vfs.DEFAULT
        self._capture_panics = vfs.is_error_fs(self._fs)
        # event/metrics plumbing (reference event.go; delivery thread
        # nodehost.go:1748-1769)
        self.sys_events = SysEventListener(nhconfig.system_event_listener)
        self.raft_events = RaftEventListener(
            nhconfig.raft_event_listener, enabled=nhconfig.enable_metrics
        )
        # shared leader-lease instruments (ISSUE 10), created lazily by
        # the first lease-enabled group when enable_metrics is on
        self._lease_obs = None
        # shared hierarchical-commit instruments (ISSUE 18), created
        # lazily by the first hier-enabled group when enable_metrics is on
        self._hier_obs = None
        # storage
        in_memory = nhconfig.node_host_dir == ":memory:"
        # directory management: deployment-id layout + flock + compat flag
        # file (reference internal/server/context.go:73-378).  A second
        # NodeHost on the same dir fails fast; a changed hard setting
        # refuses to open instead of corrupting data.
        self.server_ctx = None
        if not in_memory:
            from .server.context import ServerContext

            self.server_ctx = ServerContext(nhconfig)
            did = nhconfig.get_deployment_id()
            data_dir, _ = self.server_ctx.create_nodehost_dir(did)
            self.server_ctx.lock_nodehost_dir()
            self.server_ctx.check_nodehost_dir(
                did, nhconfig.raft_address, "nativekv"
            )
        # shard-count priority: expert override > logdb config.  Aligning
        # shards with the step-worker count reproduces the reference's
        # DoubleFixedPartitioner geometry (server/partition.go:59): one
        # worker round → one shard → one fsynced write batch
        shards = nhconfig.expert.logdb_shards or nhconfig.logdb_config.shards
        if nhconfig.logdb_factory is not None:
            self.logdb = nhconfig.logdb_factory(nhconfig)
        elif in_memory:
            self.logdb = open_logdb("", shards=shards)
        else:
            self.logdb = open_logdb(
                os.path.join(data_dir, "logdb"),
                shards=shards,
                fsync=nhconfig.logdb_config.fsync,
            )
        # delayed snapshot-status feedback (reference feedback.go:23-129):
        # transport-reported send status is parked and released to raft
        # later; the follower's SNAPSHOT_RECEIVED ack accelerates it.
        # Created before the transport so an early inbound message can't
        # race the attribute into existence.
        from .feedback import SnapshotFeedback

        self.snapshot_feedback = SnapshotFeedback(
            self._push_snapshot_status,
            push_delay_ms=Soft.snapshot_status_push_delay_ms,
        )
        # transport.  The listener accepts connections the moment it binds,
        # and a restarted host's peers reconnect INSTANTLY under load — the
        # router must drop inbound batches until construction completes
        # (raft resends cover the gap; round-4 soak: dispatching into a
        # half-built NodeHost killed receiver threads with AttributeError)
        self._router_ready = False
        self._router_gated_drops = 0
        # quorum_engine="auto" may need a probe dispatch (a killable
        # subprocess, up to 60s against a hung tunneled backend).  Run it
        # BEFORE the listener binds whenever the fast lane cannot be on —
        # inside the gated window it would silently black-hole inbound
        # traffic for the whole probe
        expert = nhconfig.expert
        self._probe_ok = None
        if expert.quorum_engine == "auto" and not expert.fast_lane:
            self._probe_ok = self._dispatch_within_budget()
        self.node_registry = Registry()
        self.transport: Transport = create_transport(
            nhconfig,
            self.node_registry,
            self._message_router,
            self._snapshot_status,
            unreachable_handler=self._unreachable,
            snapshot_dir_fn=self.snapshot_dir,
            sys_events=self.sys_events,
            snapshot_received_handler=self._snapshot_received,
            # the dragonboat_transport_* families land in THIS host's
            # registry (ISSUE 14 satellite) so the /metrics endpoint and
            # write_health_metrics actually expose them
            metrics_registry=self.raft_events.registry,
        )
        self.logdb.on_compaction = lambda cid, nid: self.sys_events.publish(
            SystemEvent(
                type=SystemEventType.LOGDB_COMPACTED, cluster_id=cid, node_id=nid
            )
        )
        # native replication fast lane (ExpertConfig.fast_lane): enrolled
        # groups' steady-state replication runs in C++ (fastlane.py).
        # Built BEFORE the engine choice: "auto" depends on it.
        self.fastlane = None
        if expert.fast_lane:
            from .fastlane import FastLaneManager

            mgr = FastLaneManager(self)
            if mgr.enabled:
                self.fastlane = mgr
                # netsplit injection coverage for the paths that do NOT
                # ride the native streams (snapshot jobs, chunks,
                # Python-socket sends) — see fastlane.set_partition
                self.transport.partition_filter = mgr.is_partitioned
        # TPU quorum plugin (the north star's plugin/tpuquorum boundary):
        # "tpu" routes hot-path tallying through the batched device engine;
        # "scalar" leaves the pure-host path untouched; "auto" picks by
        # deployment shape + measured dispatch budget (r4 A/B at rung 3:
        # with the fast lane at ~1.0 enrollment duty the device engine's
        # per-tick dispatches are pure CPU competition — 6.3k vs 8.8k w/s —
        # so auto uses the device only when the lane is NOT carrying
        # steady state, and only when a dispatch fits the latency budget)
        self.quorum_coordinator = None
        engine_choice = expert.quorum_engine
        if engine_choice == "auto":
            if self.fastlane is not None:
                engine_choice = "scalar"
            elif self._probe_ok is not None:
                engine_choice = "tpu" if self._probe_ok else "scalar"
            else:
                # fast lane requested but could not enable, and no
                # pre-listener probe ran: probing NOW would black-hole
                # inbound traffic behind the router gate for up to the
                # probe timeout — default to scalar instead (the log
                # makes the unusual configuration visible)
                plog.warning(
                    "quorum_engine=auto: fast lane unavailable and no "
                    "pre-listener probe; defaulting to scalar"
                )
                engine_choice = "scalar"
            plog.info(
                "quorum_engine=auto resolved to %s (fast_lane=%s)",
                engine_choice, self.fastlane is not None,
            )
        self.quorum_engine_resolved = engine_choice
        # aggregate health sampling (ISSUE 20): resolved BEFORE the
        # coordinator so the engine's telemetry-fold latch flips ahead of
        # warmup — the warmed fused program set then already includes the
        # fold instead of paying a recompile on first use.
        health_aggregate = nhconfig.health_aggregate or (
            os.environ.get("DBTPU_HEALTH_AGGREGATE", "")
            in ("1", "true", "on")
        )
        if engine_choice == "tpu":
            from .tpuquorum import TpuQuorumCoordinator

            self.quorum_coordinator = TpuQuorumCoordinator(
                capacity=expert.engine_block_groups
                or Soft.quorum_engine_block_groups,
                mesh_devices=expert.engine_mesh_devices,
                compilation_cache_dir=(
                    nhconfig.compilation_cache_dir or None
                ),
                telem=health_aggregate,
            )
            if nhconfig.enable_metrics:
                # device-plane observability rides the same flag as the
                # raft event metrics: the flight recorder plus the
                # engine/coordinator instrument families land in this
                # host's registry, so write_health_metrics exposes
                # device-plane health next to the node/transport counters
                self.quorum_coordinator.enable_obs(
                    registry=self.raft_events.registry
                )
            if expert.engine_warm_fused:
                # AOT warm-compile of the fused program set, AFTER the
                # obs wiring above so the warmup spans/metrics land in
                # this host's registry.  Background + niced: the round
                # thread keeps using the already-compiled single-round
                # programs until the readiness latch flips, so proposals
                # issued during warmup never block on compilation.
                self.quorum_coordinator.start_warmup()
        # compartmentalized host plane (ISSUE 8): proposal ingress
        # batcher + cross-shard group-commit WAL + decoupled apply/egress
        # executors.  Built BEFORE the engine (the committers persist
        # through its flusher, apply readiness routes to its pool); OFF by
        # default — nothing below is constructed and the scalar host path
        # stays bit-identical.
        self.hostplane = None
        # multi-process host tier (hostproc/, ISSUE 12): worker
        # processes behind shared-memory staging rings for the ingress
        # encode, the WAL redo-journal fsync cycle and the spawnable-SM
        # apply tier.  host_workers > 0 implies the compartmentalized
        # plane (the workers are its stages' execution resources); a
        # failed spawn degrades to the in-process plane with a log line
        # — never a failed NodeHost.
        self.hostproc = None
        if expert.host_workers > 0:
            from .hostproc.control import HostProcPlane

            try:
                self.hostproc = HostProcPlane(
                    workers=expert.host_workers,
                    encode_lanes=expert.host_ingress_shards or 2,
                )
            except Exception:
                plog.exception(
                    "hostproc spawn failed; in-process host plane"
                )
                self.hostproc = None
        if expert.host_compartments or self.hostproc is not None:
            from .hostplane import HostPlane

            self.hostplane = HostPlane(
                self.logdb,
                self._clusters.get,  # GIL-atomic dict get; None while
                # starting/stopped — the pool just skips the wakeup
                ingress_shards=expert.host_ingress_shards,
                ingress_ring=expert.host_ingress_ring,
                wal_window_ms=expert.host_wal_window_ms,
                apply_workers=expert.host_apply_workers,
                egress_workers=expert.host_egress_workers,
                # ErrorFS fault injection must reach the journaled
                # mode's actual durability point — but ONLY the
                # fault-injection vfs is threaded through: the journal
                # otherwise stays on the raw OS path next to the shard
                # stores (which never ride the snapshot vfs), keeping
                # write and REPLAY (open_logdb, raw OS) on one medium
                fs=self._fs if vfs.is_error_fs(self._fs) else None,
                hostproc=self.hostproc,
                wal_journal_mode=expert.host_wal_journal,
            )
            if nhconfig.enable_metrics:
                self.hostplane.enable_obs(
                    registry=self.raft_events.registry
                )
                if self.hostproc is not None:
                    self.hostproc.enable_obs(
                        registry=self.raft_events.registry
                    )
            if self.quorum_coordinator is not None:
                # the device-plane coordinator feeds the same tier: its
                # round fan-out coalesces step wakeups through the plane
                self.quorum_coordinator.hostplane = self.hostplane
        # cross-plane request tracing (obs/trace.py, ISSUE 9): allocate a
        # sampled 1-in-N trace context at propose/read time and stamp it
        # through ingress → raft step → WAL → device round → apply →
        # egress.  OFF by default (trace_sample_every=0 and no env):
        # nothing below is constructed and every request path keeps its
        # bit-identical trace=None latch.
        self.tracer = None
        self.replattr = None
        trace_n = nhconfig.trace_sample_every
        if not trace_n:
            try:
                trace_n = int(os.environ.get("DBTPU_TRACE_SAMPLE", "0") or 0)
            except ValueError:
                # degrade like DBTPU_TRACE_STALL_MS: a malformed env var
                # must not fail every NodeHost construction
                plog.warning("malformed DBTPU_TRACE_SAMPLE; tracing off")
                trace_n = 0
        if trace_n > 0:
            from .obs.trace import Tracer

            self.tracer = Tracer(
                sample_every=trace_n,
                registry=self.raft_events.registry,
                recorder=(
                    self.quorum_coordinator.flight_recorder
                    if self.quorum_coordinator is not None else None
                ),
            )
            self.tracer.host = nhconfig.raft_address
            # replication attribution (obs/replattr.py, ISSUE 14): the
            # cross-host half of the tracer — sampled proposals carry a
            # ReplTrace over the wire and each commit's quorum close is
            # decomposed per peer.  Lives and dies with the tracer; peer
            # rows label by latency class when an injector is installed
            # (transport.latency, read dynamically — monkey.set_latency
            # may arrive after construction).
            from .obs.replattr import ReplAttr

            self.replattr = ReplAttr(
                host=nhconfig.raft_address,
                registry=self.raft_events.registry,
                recorder=(
                    self.quorum_coordinator.flight_recorder
                    if self.quorum_coordinator is not None else None
                ),
            )
            self.replattr.resolver = self.node_registry.resolve

            def _peer_class(addr: str):
                inj = self.transport.latency
                if inj is not None:
                    # per-pair asymmetric overrides reclassify the link
                    # (ISSUE 18 bugfix — a near peer behind an injected
                    # slow link must not label "near" in closer/laggard
                    # rows); peer_class falls back to the static domain
                    peer_class = getattr(inj, "peer_class", None)
                    if peer_class is not None:
                        return peer_class(nhconfig.raft_address, addr)
                    domain_of = getattr(inj, "domain_of", None)
                    if domain_of is not None:
                        return domain_of(addr)
                return None

            self.replattr.class_of = _peer_class
            self.tracer.replattr = self.replattr
            if self.quorum_coordinator is not None:
                self.quorum_coordinator.tracer = self.tracer
                self.quorum_coordinator.replattr = self.replattr
        # cluster health plane (obs/health.py, ISSUE 13): low-rate
        # per-group/host health sampling + anomaly detectors + the live
        # scrape endpoint.  OFF by default (health_sample_ms=0 and no
        # env): nothing below is constructed — no sampler, no listener,
        # no dragonboat_health_* families — and the request paths keep
        # their bit-identical latches.
        self.health = None
        self.metrics_server = None
        health_ms = nhconfig.health_sample_ms
        if not health_ms:
            try:
                health_ms = int(
                    os.environ.get("DBTPU_HEALTH_SAMPLE_MS", "0") or 0
                )
            except ValueError:
                plog.warning("malformed DBTPU_HEALTH_SAMPLE_MS; health off")
                health_ms = 0
        if health_aggregate and self.quorum_coordinator is None:
            # the fold lives in the device quorum kernels; on a scalar
            # host the knob is inert (visible, not fatal — the devprof
            # inert-knob precedent)
            plog.warning(
                "health_aggregate set but no tpu quorum engine; "
                "aggregate sampling off"
            )
            health_aggregate = False
        if health_aggregate and health_ms <= 0:
            plog.warning(
                "health_aggregate set but the health plane is off "
                "(health_sample_ms=0); aggregate sampling off"
            )
        if health_ms > 0:
            from .obs.health import HealthSampler

            self.health = HealthSampler(
                self,
                sample_ms=health_ms,
                registry=self.raft_events.registry,
                recorder=self.flight_recorder,
                aggregate=health_aggregate,
            )
        # closed-loop recovery plane (obs/recovery.py, ISSUE 17): the
        # health detectors actuate guard-railed remediations.  OFF by
        # default (auto_recover=False and no env): nothing constructed,
        # no subscriber registered on the sampler (its ``_subs`` latch
        # stays None — asserted structurally in tests/test_recovery.py).
        self.recovery = None
        auto_recover = nhconfig.auto_recover or (
            os.environ.get("DBTPU_AUTO_RECOVER", "") in ("1", "true", "on")
        )
        if auto_recover:
            if self.health is None:
                # actuation without detection is meaningless; degrade
                # loudly (the devprof inert-knob precedent)
                plog.warning(
                    "auto_recover set but the health plane is off "
                    "(health_sample_ms=0); recovery off"
                )
            else:
                from .obs.recovery import RecoveryController

                dry = nhconfig.auto_recover_dry_run or (
                    os.environ.get("DBTPU_RECOVER_DRY_RUN", "")
                    in ("1", "true", "on")
                )
                self.recovery = RecoveryController(
                    self,
                    self.health,
                    dry_run=dry,
                    registry=self.raft_events.registry,
                    **dict(nhconfig.auto_recover_knobs),
                )
        # device capacity & profiling plane (obs/devprof.py, ISSUE 15):
        # HBM ledger + capacity model, warm-set program registry,
        # sampled device-time estimator and on-demand jax.profiler
        # capture windows over the batched quorum engine.  OFF by
        # default (device_profile=0 and no env): nothing constructed,
        # the engine keeps its bit-identical _devprof=None latch.
        self.devprof = None
        devprof_n = nhconfig.device_profile
        if not devprof_n:
            try:
                devprof_n = int(
                    os.environ.get("DBTPU_DEVICE_PROFILE", "0") or 0
                )
            except ValueError:
                plog.warning("malformed DBTPU_DEVICE_PROFILE; devprof off")
                devprof_n = 0
        if devprof_n > 0:
            if self.quorum_coordinator is None:
                # the plane profiles the DEVICE engine; on a scalar host
                # the knob is inert (visible, not fatal — the health
                # plane's degrade precedent)
                plog.warning(
                    "device_profile set but no tpu quorum engine; "
                    "devprof off"
                )
            else:
                from .obs.devprof import DevProf

                base = nhconfig.node_host_dir
                self.devprof = DevProf(
                    registry=self.raft_events.registry,
                    recorder=self.flight_recorder,
                    sample_every=devprof_n,
                    artifact_dir=(
                        base if base and base != ":memory:" else None
                    ),
                )
                self.quorum_coordinator.enable_devprof(self.devprof)
        metrics_addr = nhconfig.metrics_addr or os.environ.get(
            "DBTPU_METRICS_ADDR", ""
        )
        if metrics_addr:
            from .obs.health import MetricsServer

            try:
                self.metrics_server = MetricsServer(self, metrics_addr)
            except (OSError, ValueError) as e:
                # a taken port (OSError) or a malformed addr (ValueError
                # — possibly from the ENV fallback, which no config
                # validation covers) must not fail the whole NodeHost:
                # the raft planes are fine, only the scrape surface is
                # not (the DBTPU_HEALTH_SAMPLE_MS degrade precedent)
                plog.warning(
                    "metrics endpoint unavailable on %s: %r",
                    metrics_addr, e,
                )
        # engine
        workers = expert.step_worker_count or 4
        self.engine = Engine(
            self._get_nodes,
            self.logdb,
            step_workers=workers,
            apply_workers=workers,
            get_csi=self._get_csi,
            hostplane=self.hostplane,
        )
        if self.tracer is not None:
            self.engine.tracer = self.tracer
        # opt-in SIGUSR2 live-debug dump (ISSUE 9 satellite): the
        # handler sets the flag; the tick worker performs the dump
        self._dump_sig_old = None
        self._dump_requested = False
        if nhconfig.dump_signal:
            self._install_dump_signal()
        # ticks
        self._tick_thread = threading.Thread(
            target=self._tick_worker_main, name="tick-worker", daemon=True
        )
        self._tick_thread.start()
        self._router_ready = True

    @staticmethod
    def _dispatch_within_budget(budget_ms: float = 5.0) -> bool:
        """Probe one tiny batched-engine dispatch round trip.  A tunneled
        backend costs ~70ms per dispatch (r2 measurement) — useless for a
        per-tick engine targeting <5ms commit p99; a local backend costs
        ~0.2ms.  Only runs for quorum_engine="auto" without the fast lane.

        Runs in a KILLABLE subprocess: backend init can HANG (not just
        fail) when a tunneled device is unreachable, and NodeHost
        construction must never block on it."""
        import subprocess
        import sys as _sys

        code = (
            "import time\n"
            "from dragonboat_tpu.ops.engine import BatchedQuorumEngine\n"
            "eng = BatchedQuorumEngine(8, 3, event_cap=16)\n"
            "eng.add_group(1, node_ids=[1, 2, 3], self_id=1)\n"
            "eng.set_leader(1, term=1, term_start=1, last_index=1)\n"
            "eng.step(do_tick=True)\n"
            "ts = []\n"
            "for _ in range(3):\n"
            "    t0 = time.perf_counter(); eng.step(do_tick=True)\n"
            "    ts.append(time.perf_counter() - t0)\n"
            "ts.sort(); print(ts[1] * 1e3)\n"
        )
        try:
            r = subprocess.run(
                [_sys.executable, "-c", code],
                capture_output=True, text=True, timeout=60.0,
            )
            if r.returncode != 0 or not r.stdout.strip():
                plog.warning(
                    "auto-engine dispatch probe failed: rc=%s", r.returncode
                )
                return False
            p50_ms = float(r.stdout.strip().splitlines()[-1])
            plog.info("auto-engine dispatch probe: p50 %.2fms", p50_ms)
            return p50_ms <= budget_ms
        except Exception as e:
            plog.warning("auto-engine dispatch probe failed: %r", e)
            return False

    # ---- dirs ----

    def snapshot_dir(self, cluster_id: int, node_id: int) -> str:
        if self.server_ctx is None:
            base = os.path.join(
                "/tmp", "dragonboat-tpu-mem",
                self.raft_address().replace(":", "_"),
            )
            return os.path.join(
                base, "snapshot", f"{cluster_id:020d}-{node_id:020d}"
            )
        return self.server_ctx.get_snapshot_dir(
            self.nhconfig.get_deployment_id(), cluster_id, node_id
        )

    def raft_address(self) -> str:
        return self.nhconfig.raft_address

    # ---- health metrics / observability ----

    @property
    def metrics_registry(self):
        """The registry this host's metrics publish into (raft events,
        transport, system events, and — when ``enable_metrics`` wired the
        device plane — the ``dragonboat_device_*``/``dragonboat_coord_*``
        families)."""
        return self.raft_events.registry

    def write_health_metrics(self, out) -> None:
        """Prometheus text exposition of this host's registry (reference
        ``WriteHealthMetrics``, ``nodehost.go``)."""
        self.raft_events.registry.write_health_metrics(out)

    @property
    def flight_recorder(self):
        """The device-plane flight recorder (None unless a quorum
        coordinator is running with observability enabled)."""
        qc = self.quorum_coordinator
        return qc.flight_recorder if qc is not None else None

    def dump_trace(self, path: Optional[str] = None,
                   limit: Optional[int] = None) -> dict:
        """Export the sampled request traces as Chrome-trace / Perfetto
        JSON (one proposal = one flow across host threads and device
        rounds; linked flight-recorder spans render on a
        ``device-plane`` track).  Requires tracing
        (``NodeHostConfig.trace_sample_every`` / ``DBTPU_TRACE_SAMPLE``).
        Returns the trace dict; also writes it to ``path`` when given —
        load the file at https://ui.perfetto.dev or about://tracing."""
        if self.tracer is None:
            raise RuntimeError(
                "tracing is off — set NodeHostConfig.trace_sample_every"
            )
        d = self.tracer.export_chrome(limit=limit)
        if path:
            with open(path, "w") as f:
                json.dump(d, f)
        return d

    def profile_device(
        self, ms: float = 1000.0, path: Optional[str] = None
    ) -> str:
        """Open an on-demand ``jax.profiler`` capture window for ``ms``
        milliseconds (obs/devprof.py, ISSUE 15) and return the artifact
        directory — written beside the ``dump_trace``/``debug_dump``
        artifacts so ``tools/trace_merge.py`` sessions and device
        profiles are collected from one place (load the result at
        https://ui.perfetto.dev).  Requires the device profiling plane
        (``NodeHostConfig.device_profile`` / ``DBTPU_DEVICE_PROFILE``);
        one window at a time — the profiler is process-global."""
        if self.devprof is None:
            raise RuntimeError(
                "device profiling is off — set "
                "NodeHostConfig.device_profile"
            )
        return self.devprof.capture(ms, path=path)

    def health_report(self) -> dict:
        """Aggregated cluster-health verdict (obs/health.py, ISSUE 13):
        open detector events, per-detector open/close counts, and the
        recovery-time attribution percentiles (failover / worker-respawn
        / devsm-rebind) derived from open→close durations.  ``status``
        is ``"ok"`` unless any detector is open — the ``/healthz``
        endpoint serves exactly this dict (503 while degraded).  With
        the health plane off the report is a plain ok stub."""
        if self.health is None:
            return {"status": "ok", "health_plane": "off"}
        return self.health.report()

    def recovery_report(self) -> dict:
        """Closed-loop recovery actuation report (obs/recovery.py,
        ISSUE 17): executed/dry-run actions per detector, skip reasons,
        flap-suppressed keys and the guardrail knobs.  A plain off stub
        while ``auto_recover`` is off."""
        if self.recovery is None:
            return {"enabled": False, "recovery_plane": "off"}
        return self.recovery.report()

    def debug_dump(self, path: Optional[str] = None) -> str:
        """Write the flight-recorder ring plus any in-flight/completed
        sampled traces (and the health sample ring when the health
        plane is on) to a timestamped JSON file (the SIGUSR2 handler's
        body; callable directly).  Returns the path written."""
        d = {
            "time": time.time(),
            "raft_address": self.raft_address(),
            "recorder": (
                self.flight_recorder.to_json()
                if self.flight_recorder is not None else None
            ),
            "traces": (
                self.tracer.to_json() if self.tracer is not None else None
            ),
            "replattr": (
                self.replattr.summary()
                if self.replattr is not None else None
            ),
            "health": (
                self.health.to_json(limit=64)
                if self.health is not None else None
            ),
            "devprof": (
                self.devprof.to_json()
                if self.devprof is not None else None
            ),
        }
        if path is None:
            base = self.nhconfig.node_host_dir
            if not base or base == ":memory:":
                import tempfile

                base = tempfile.gettempdir()
            path = os.path.join(
                base,
                time.strftime("dbtpu-dump-%Y%m%d-%H%M%S.json"),
            )
        with open(path, "w") as f:
            json.dump(d, f, indent=1, default=str)
        plog.warning("debug dump written to %s", path)
        return path

    def _install_dump_signal(self) -> None:
        """Opt-in SIGUSR2 → :meth:`debug_dump` (live soak/chaos debugging
        without attaching a debugger).  The handler only SETS A FLAG —
        the dump runs on the tick worker: signal handlers execute on the
        main thread mid-frame, and dumping inline would re-acquire
        non-reentrant tracer/recorder locks the interrupted frame may
        already hold (self-deadlock).  Signal handlers only install from
        the main thread; elsewhere the opt-in degrades to a warning."""
        import signal as _signal

        def _handler(signum, frame):
            self._dump_requested = True

        try:
            self._dump_sig_old = _signal.signal(_signal.SIGUSR2, _handler)
        except (ValueError, OSError, AttributeError) as e:
            plog.warning("SIGUSR2 dump handler unavailable: %r", e)

    # ---- cluster registry ----

    def _get_nodes(self) -> Tuple[int, Dict[int, Node]]:
        with self._mu:
            # None entries are in-flight start_cluster reservations
            return self._csi, {
                k: v for k, v in self._clusters.items() if v is not None
            }

    def _get_csi(self) -> int:
        # GIL-atomic int read; lets engine workers skip the locked
        # dict copy in _get_nodes when the cluster set hasn't changed
        return self._csi

    def get_node(self, cluster_id: int) -> Node:
        # lock-free read (GIL-atomic dict get): this sits on the propose
        # hot path, once per client request
        n = self._clusters.get(cluster_id)
        if n is None:
            raise ClusterNotFoundError(f"cluster {cluster_id} not found")
        return n

    def has_cluster(self, cluster_id: int) -> bool:
        with self._mu:
            return cluster_id in self._clusters

    # ---- lifecycle (reference StartCluster nodehost.go:440-520,1509) ----

    def start_cluster(
        self,
        initial_members: Dict[int, str],
        join: bool,
        create_sm: Callable,
        config: Config,
    ) -> None:
        self._start_cluster(
            initial_members, join, create_sm, config, StateMachineType.REGULAR
        )

    def start_concurrent_cluster(
        self, initial_members, join, create_sm, config: Config
    ) -> None:
        self._start_cluster(
            initial_members, join, create_sm, config, StateMachineType.CONCURRENT
        )

    def start_on_disk_cluster(
        self, initial_members, join, create_sm, config: Config
    ) -> None:
        self._start_cluster(
            initial_members, join, create_sm, config, StateMachineType.ON_DISK
        )

    def _start_cluster(
        self,
        initial_members: Dict[int, str],
        join: bool,
        create_sm: Callable,
        config: Config,
        smtype: StateMachineType,
    ) -> None:
        config.validate()
        cluster_id, node_id = config.cluster_id, config.node_id
        if join and initial_members:
            raise ValueError("addresses given for a joining node")
        if not join and not initial_members:
            # the reference only rejects this for NEW nodes
            # (nodehost.go:1509 startCluster): a restarting node passes
            # empty members + join=False and resumes from its bootstrap
            # record
            if self.logdb.get_bootstrap_info(cluster_id, node_id) is None:
                raise ValueError("addresses not given for an initial member")
        with self._mu:
            if cluster_id in self._clusters:
                raise ClusterAlreadyExistError(str(cluster_id))
            # reserve the id under the lock so a concurrent start of the
            # same cluster fails instead of silently double-starting
            self._clusters[cluster_id] = None
        try:
            self._build_and_start_node(
                initial_members, join, create_sm, config, smtype
            )
        except BaseException:
            self._unreserve_cluster(cluster_id)
            raise

    def _build_and_start_node(
        self,
        initial_members: Dict[int, str],
        join: bool,
        create_sm: Callable,
        config: Config,
        smtype: StateMachineType,
    ) -> None:
        cluster_id, node_id = config.cluster_id, config.node_id
        # bootstrap record (reference bootstrapCluster nodehost.go:1479)
        bs = self.logdb.get_bootstrap_info(cluster_id, node_id)
        new_node = bs is None
        if bs is None:
            bs = Bootstrap(
                addresses=dict(initial_members), join=join, type=int(smtype)
            )
            self.logdb.save_bootstrap_info(cluster_id, node_id, bs)
        elif bs.type not in (int(StateMachineType.UNKNOWN), int(smtype)):
            raise ValueError("SM type changed across restarts")
        members = bs.addresses if not bs.join else initial_members
        # register peer addresses
        for nid, addr in (members or {}).items():
            self.node_registry.add(cluster_id, nid, addr)
        self.node_registry.add(cluster_id, node_id, self.raft_address())
        # build the node
        logreader = LogReader.load(cluster_id, node_id, self.logdb)
        snapshotter = Snapshotter(
            self.snapshot_dir(cluster_id, node_id), cluster_id, node_id,
            self.logdb, fs=self._fs,
        )
        # hostproc apply tier (ISSUE 12): a REGULAR state machine whose
        # factory registered as process-spawnable runs inside an apply
        # worker behind a ProcStateMachine proxy — update/lookup/snapshot
        # become shared-memory round trips off this process's GIL.
        # Never wraps: witness replicas (no real SM work), device_kv
        # groups (the devsm plane IS their apply offload), or factories
        # that did not opt in.  The wrap decision is taken BEFORE
        # construction so the user machine is built exactly once, on
        # whichever side actually hosts it.  Worker crash ⇒ the proxy
        # rebuilds in-process from its snapshot+redo buffer,
        # exactly-once.
        proc_spec = None
        if (
            self.hostproc is not None
            and self.hostproc.offload_default
            and smtype == StateMachineType.REGULAR
            and not config.is_witness
            and not config.device_kv
        ):
            from .hostproc import spawnable_spec

            proc_spec = spawnable_spec(create_sm)
        if proc_spec is not None:
            from .hostproc.sm import ProcStateMachine

            usersm = ProcStateMachine(
                self.hostproc, proc_spec, cluster_id, node_id, create_sm
            )
        else:
            usersm = create_sm(cluster_id, node_id)
        if smtype == StateMachineType.REGULAR:
            managed = from_regular_sm(usersm)
        elif smtype == StateMachineType.CONCURRENT:
            managed = from_concurrent_sm(usersm)
        else:
            managed = from_on_disk_sm(usersm)
        node = Node(
            nh=self,
            config=config,
            logdb=self.logdb,
            logreader=logreader,
            snapshotter=snapshotter,
            sm=None,  # set below (circular)
            tick_millisecond=self.nhconfig.rtt_millisecond,
        )
        sm = StateMachine(
            managed,
            snapshotter,
            node,
            cluster_id,
            node_id,
            ordered_config_change=config.ordered_config_change,
            is_witness=config.is_witness,
            snapshot_compression=config.snapshot_compression,
        )
        node.sm = sm
        addresses = [
            PeerAddress(node_id=nid, address=a) for nid, a in (members or {}).items()
        ]
        node.peer_raft_events = self.raft_events
        node.quorum_coordinator = self.quorum_coordinator
        # device state machine registration (devsm, ISSUE 11), gated
        # default-OFF: both the config flag AND the SM's device_kv marker
        # must be present, and only the tpu engine has a coordinator to
        # serve it — anything else leaves the SM a plain host machine
        node.devsm_sm = (
            usersm
            if (
                config.device_kv
                and getattr(usersm, "device_kv", False)
                and self.quorum_coordinator is not None
                and smtype == StateMachineType.REGULAR
            )
            else None
        )
        node.fastlane = self.fastlane
        if config.read_lease and self.nhconfig.enable_metrics:
            # leader-lease instruments (ISSUE 10): one shared LeaseObs
            # per host — the dragonboat_lease_* families land in the same
            # registry write_health_metrics exposes.  Lazy: hosts with no
            # lease-enabled group never register the families.
            if self._lease_obs is None:
                from .lease import LeaseObs

                self._lease_obs = LeaseObs(self.raft_events.registry)
            node.lease_obs = self._lease_obs
        if config.hier_commit and self.nhconfig.enable_metrics:
            # hierarchical-commit instruments (ISSUE 18): one shared
            # HierObs per host, the LeaseObs pattern — lazy so hosts
            # with no hier-enabled group never register the families
            if self._hier_obs is None:
                from .raft.hier import HierObs

                self._hier_obs = HierObs(self.raft_events.registry)
            node.hier_obs = self._hier_obs
        if config.read_lease and self.nhconfig.lease_wall_guard:
            # wall-clock lease guard (ISSUE 17): bound lease validity by
            # monotonic wall time so a starved tick loop cannot
            # overextend it past the majority's wall-time election
            node.lease_wall_s = self.nhconfig.rtt_millisecond / 1000.0
        if self.hostplane is not None:
            node.ingress = self.hostplane.ingress
            node.pending_proposals.set_egress(self.hostplane.egress)
            node.pending_reads.set_egress(self.hostplane.egress)
        if self.tracer is not None:
            node.tracer = self.tracer
            node.pending_reads._tracer = self.tracer
            node.replattr = self.replattr
        node.start(addresses, initial=not join and new_node, new_node=new_node)
        with self._mu:
            self._clusters[cluster_id] = node
            self._csi += 1
        # signal only AFTER the store + csi bump: the workers reload their
        # node maps on csi change, so the wakeup now always finds the node
        # (the apply signal drives the queued initial-recovery task)
        self.engine.set_apply_ready(cluster_id)
        self.engine.set_step_ready(cluster_id)

    def _unreserve_cluster(self, cluster_id: int) -> None:
        with self._mu:
            if self._clusters.get(cluster_id) is None:
                self._clusters.pop(cluster_id, None)

    def stop_cluster(self, cluster_id: int) -> None:
        with self._mu:
            node = self._clusters.get(cluster_id)
            if node is None:
                # absent, or an in-flight start reservation — don't pop it
                raise ClusterNotFoundError(str(cluster_id))
            del self._clusters[cluster_id]
            self._csi += 1
        if self.quorum_coordinator is not None:
            self.quorum_coordinator.unregister(cluster_id)
        node.stop()
        self.sys_events.publish(
            SystemEvent(
                type=SystemEventType.NODE_UNLOADED,
                cluster_id=cluster_id,
                node_id=node.node_id,
            )
        )

    def stop_node(self, cluster_id: int, node_id: int) -> None:
        self.stop_cluster(cluster_id)

    def stop(self) -> None:
        if self._stopped.is_set():
            return
        self._stopped.set()
        self.sys_events.publish(
            SystemEvent(type=SystemEventType.NODE_HOST_SHUTTING_DOWN)
        )
        if self.metrics_server is not None:
            # first: a scrape arriving mid-teardown must not race the
            # planes it reads
            self.metrics_server.stop()
            self.metrics_server = None
        if self.recovery is not None:
            # before the nodes: an in-flight remediation (config change,
            # transfer) must drain while its group still exists
            self.recovery.stop()
        with self._mu:
            nodes = list(self._clusters.values())
            self._clusters.clear()
            self._csi += 1
        for n in nodes:
            if n is not None:
                n.stop()
        if self.fastlane is not None:
            self.fastlane.stop()
        self.engine.stop()
        if self.hostplane is not None:
            # after engine.stop(): the committers (joined there) are the
            # flusher's riders — stopping the flusher first would strand
            # an in-flight flush
            self.hostplane.stop()
        if self.hostproc is not None:
            # after hostplane.stop(): every worker-tier caller (batcher
            # encode, WAL sink, SM proxies) is quiesced, so the workers'
            # drain-and-stop sees an empty backlog
            self.hostproc.stop()
        if self.devprof is not None:
            # before the coordinator: an open jax.profiler window must
            # close while the engine it observes still exists
            self.devprof.stop()
            self.devprof = None
        if self.quorum_coordinator is not None:
            self.quorum_coordinator.stop()
        self.transport.stop()
        self.logdb.close()
        if self.server_ctx is not None:
            self.server_ctx.stop()
        if self.tracer is not None:
            self.tracer.close()
        if self._dump_sig_old is not None:
            import signal as _signal

            try:
                _signal.signal(_signal.SIGUSR2, self._dump_sig_old)
            except (ValueError, OSError):
                pass
            self._dump_sig_old = None
        self.sys_events.stop()

    # ---- proposals / reads (reference SyncPropose :523, SyncRead :548) ----

    def get_noop_session(self, cluster_id: int) -> Session:
        return Session.noop_session(cluster_id)

    def propose(
        self, session: Session, cmd: bytes, timeout: float
    ) -> RequestState:
        node = self.get_node(session.cluster_id)
        return node.propose(session, cmd, timeout)

    def propose_batch(
        self, session: Session, cmds, timeout: float
    ) -> list:
        """Burst-propose: one completion future per command (see
        ``Node.propose_batch``)."""
        node = self.get_node(session.cluster_id)
        return node.propose_batch(session, cmds, timeout)

    def sync_propose(
        self, session: Session, cmd: bytes, timeout: float = 5.0
    ) -> Result:
        r = self._sync_retry(
            lambda t: self.propose(session, cmd, t), timeout
        )
        _raise_on_failure(r)
        if not session.is_noop_session():
            session.proposal_completed()
        return r.result

    def read_index(self, cluster_id: int, timeout: float) -> RequestState:
        return self.get_node(cluster_id).read(timeout)

    def sync_read(self, cluster_id: int, query, timeout: float = 5.0):
        r = self._sync_retry(
            lambda t: self.read_index(cluster_id, t), timeout,
            retry_timeout=True,
        )
        _raise_on_failure(r)
        return self.get_node(cluster_id).sm.lookup(query)

    def _sync_retry(
        self, submit, timeout: float, retry_timeout: bool = False
    ) -> RequestResult:
        """Retry dropped requests until the deadline (reference
        ``nodehost.go`` execute-on-temporary-error pattern in Sync* APIs).

        ``retry_timeout=True`` additionally splits the budget into short
        attempts and retries attempts that time out — safe only for
        idempotent requests (reads): a request forwarded to a dead leader
        is silently lost and would otherwise burn the whole budget.
        """
        deadline = time.monotonic() + timeout
        attempt_cap = (
            max(20 * self.nhconfig.rtt_millisecond / 1000.0, 0.25)
            if retry_timeout
            else timeout
        )
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return RequestResult()  # TIMEOUT
            attempt = min(remaining, attempt_cap)
            rs = submit(attempt)
            r = rs.wait(attempt)
            if r.dropped or (retry_timeout and r.timeout):
                time.sleep(self.nhconfig.rtt_millisecond / 1000.0)
                continue
            return r

    def request_compaction(self, cluster_id: int, node_id: int):
        """User-requested LogDB compaction (reference ``nodehost.go:980``
        ``RequestCompaction``).  Returns a ``threading.Event`` set when
        the compaction completes.  For a cluster already removed from
        this host (e.g. after ``remove_data``) the whole log range is
        compacted; for a live node, compaction runs up to the last
        auto-compacted watermark (RejectedError when there is none)."""
        with self._mu:
            node = self._clusters.get(cluster_id)
            starting = node is None and cluster_id in self._clusters
        if starting:
            # in-flight start_cluster reservation (the None placeholder):
            # NOT removed data — refuse rather than full-range compact a
            # cluster that is coming up
            raise ClusterNotFoundError(f"cluster {cluster_id} is starting")
        if node is None:
            # removed via remove_data: compact everything it left behind
            return self.logdb.compact_entries_to(
                cluster_id, node_id, (1 << 64) - 1
            )
        if node.node_id != node_id:
            raise ClusterNotFoundError(f"{cluster_id}:{node_id}")
        done = node.request_compaction()
        self.engine.set_step_ready(cluster_id)
        return done

    def has_node_info(self, cluster_id: int, node_id: int) -> bool:
        """True when this host holds bootstrap state for the replica
        (reference ``nodehost.go:1319`` ``HasNodeInfo``)."""
        return self.logdb.get_bootstrap_info(cluster_id, node_id) is not None

    def get_node_host_info(self, skip_log_info: bool = False) -> "NodeHostInfo":
        """Details of this host and every Raft cluster it manages
        (reference ``nodehost.go:1333`` ``GetNodeHostInfo``)."""
        infos = []
        with self._mu:
            # skip in-flight start_cluster reservations (None placeholders)
            nodes = [n for n in self._clusters.values() if n is not None]
        for n in nodes:
            try:
                m = n.sm.get_membership()
                pending = not m.addresses and not m.observers and not m.witnesses
                infos.append(ClusterInfo(
                    cluster_id=n.cluster_id,
                    node_id=n.node_id,
                    nodes=dict(m.addresses),
                    observers=dict(m.observers),
                    witnesses=dict(m.witnesses),
                    config_change_index=m.config_change_id,
                    state_machine_type=n.sm.sm_type,
                    is_leader=n.is_leader(),
                    is_observer=n.config.is_observer,
                    is_witness=n.config.is_witness,
                    pending=pending,
                ))
            except Exception:  # a node racing stop: report it as pending
                infos.append(ClusterInfo(
                    cluster_id=n.cluster_id, node_id=n.node_id, pending=True
                ))
        log_info = [] if skip_log_info else self.logdb.list_node_info()
        return NodeHostInfo(
            raft_address=self.raft_address(),
            cluster_info_list=infos,
            log_info=[(ni.cluster_id, ni.node_id) for ni in log_info],
        )

    def stale_read(self, cluster_id: int, query):
        return self.get_node(cluster_id).stale_read(query)

    # ---- sessions (reference SyncGetSession/SyncCloseSession) ----

    def sync_get_session(self, cluster_id: int, timeout: float = 5.0) -> Session:
        s = Session.new_session(cluster_id)
        s.prepare_for_register()
        node = self.get_node(cluster_id)
        rs = node.propose_session(s, timeout)
        r = rs.wait(timeout)
        _raise_on_failure(r)
        if r.result.value != s.client_id:
            raise RejectedError("session registration rejected")
        s.prepare_for_propose()
        return s

    def sync_close_session(self, s: Session, timeout: float = 5.0) -> None:
        s.prepare_for_unregister()
        node = self.get_node(s.cluster_id)
        rs = node.propose_session(s, timeout)
        r = rs.wait(timeout)
        _raise_on_failure(r)

    # ---- membership (reference RequestAddNode :1133 etc.) ----

    def request_add_node(
        self, cluster_id: int, node_id: int, address: str,
        config_change_index: int = 0, timeout: float = 5.0,
    ) -> RequestState:
        cc = ConfigChange(
            type=ConfigChangeType.ADD_NODE,
            node_id=node_id,
            address=address,
            config_change_id=config_change_index,
        )
        return self.get_node(cluster_id).request_config_change(cc, timeout)

    def request_delete_node(
        self, cluster_id: int, node_id: int,
        config_change_index: int = 0, timeout: float = 5.0,
    ) -> RequestState:
        cc = ConfigChange(
            type=ConfigChangeType.REMOVE_NODE,
            node_id=node_id,
            config_change_id=config_change_index,
        )
        return self.get_node(cluster_id).request_config_change(cc, timeout)

    def request_add_observer(
        self, cluster_id: int, node_id: int, address: str,
        config_change_index: int = 0, timeout: float = 5.0,
    ) -> RequestState:
        cc = ConfigChange(
            type=ConfigChangeType.ADD_OBSERVER,
            node_id=node_id,
            address=address,
            config_change_id=config_change_index,
        )
        return self.get_node(cluster_id).request_config_change(cc, timeout)

    def request_add_witness(
        self, cluster_id: int, node_id: int, address: str,
        config_change_index: int = 0, timeout: float = 5.0,
    ) -> RequestState:
        cc = ConfigChange(
            type=ConfigChangeType.ADD_WITNESS,
            node_id=node_id,
            address=address,
            config_change_id=config_change_index,
        )
        return self.get_node(cluster_id).request_config_change(cc, timeout)

    def sync_request_add_node(self, cluster_id, node_id, address,
                              config_change_index=0, timeout=5.0) -> None:
        r = self._sync_retry(
            lambda t: self.request_add_node(
                cluster_id, node_id, address, config_change_index, t
            ),
            timeout,
        )
        _raise_on_failure(r)

    def sync_request_delete_node(self, cluster_id, node_id,
                                 config_change_index=0, timeout=5.0) -> None:
        r = self._sync_retry(
            lambda t: self.request_delete_node(
                cluster_id, node_id, config_change_index, t
            ),
            timeout,
        )
        _raise_on_failure(r)

    def sync_request_add_observer(self, cluster_id, node_id, address,
                                  config_change_index=0, timeout=5.0) -> None:
        r = self._sync_retry(
            lambda t: self.request_add_observer(
                cluster_id, node_id, address, config_change_index, t
            ),
            timeout,
        )
        _raise_on_failure(r)

    def sync_request_add_witness(self, cluster_id, node_id, address,
                                 config_change_index=0, timeout=5.0) -> None:
        r = self._sync_retry(
            lambda t: self.request_add_witness(
                cluster_id, node_id, address, config_change_index, t
            ),
            timeout,
        )
        _raise_on_failure(r)

    def sync_get_cluster_membership(
        self, cluster_id: int, timeout: float = 5.0
    ) -> Membership:
        r = self._sync_retry(
            lambda t: self.read_index(cluster_id, t), timeout,
            retry_timeout=True,
        )
        _raise_on_failure(r)
        return self.get_node(cluster_id).get_membership()

    # ---- snapshots / leadership ----

    def request_snapshot(
        self, cluster_id: int, export_path: str = "",
        override_compaction_overhead: bool = False,
        compaction_overhead: int = 0, timeout: float = 5.0,
    ) -> RequestState:
        req = SSRequest(
            type=SSReqType.EXPORTED if export_path else SSReqType.USER_REQUESTED,
            path=export_path,
            override_compaction_overhead=override_compaction_overhead,
            compaction_overhead=compaction_overhead,
        )
        return self.get_node(cluster_id).request_snapshot(req, timeout)

    def sync_request_snapshot(self, cluster_id: int, timeout: float = 5.0) -> int:
        rs = self.request_snapshot(cluster_id, timeout=timeout)
        r = rs.wait(timeout)
        _raise_on_failure(r)
        return r.snapshot_index

    def request_leader_transfer(self, cluster_id: int, target: int) -> None:
        self.get_node(cluster_id).request_leader_transfer(target, 5.0)

    def get_leader_id(self, cluster_id: int) -> Tuple[int, bool]:
        return self.get_node(cluster_id).get_leader_id()

    def lease_status(self, cluster_id: int) -> Optional[dict]:
        """Leader-lease snapshot for one group (ISSUE 10): ``None`` when
        the group runs without ``Config.read_lease``; else held/remaining
        plus the local-vs-fallback read counters (``Node.lease_status``)."""
        return self.get_node(cluster_id).lease_status()

    def wal_status(self) -> Optional[dict]:
        """Group-commit WAL strategy snapshot (ISSUE 12 satellite, the
        ``lease_status`` pattern): ``None`` without the compartmentalized
        host plane; else the chosen journal strategy (mode / engaged /
        probe cost / pacing window), the journal's byte/fsync counters
        and whether durability currently runs through the hostproc WAL
        worker (``worker_sink``)."""
        if self.hostplane is None:
            return None
        return self.hostplane.wal.status()

    # ---- data management ----

    def remove_data(self, cluster_id: int, node_id: int) -> None:
        """Reference ``NodeHost.RemoveData``: only valid once the node is
        stopped."""
        with self._mu:
            if cluster_id in self._clusters:
                raise RuntimeError("cluster still running")
        self.logdb.remove_node_data(cluster_id, node_id)

    def get_node_user(self, cluster_id: int) -> Node:
        return self.get_node(cluster_id)

    # ---- message plumbing ----

    def send_message(self, m: Message) -> None:
        """Route an outbound raft message: local delivery when the target
        node lives on this host (reference ``nodehost.go:1792``)."""
        if m.to == 0:
            return
        target = self.node_registry.resolve(m.cluster_id, m.to)
        if target == self.raft_address():
            node = self._clusters.get(m.cluster_id)
            if node is not None and node.node_id == m.to:
                node.handle_message_batch(m)
            return
        # with the fast lane active, ALL raft messages for a remote ride
        # its single ordered native stream — mixing the Python transport's
        # sockets with the fast plane's reorders entries across
        # eject/re-enroll transitions and forces gap ejects
        if self.fastlane is not None and self.fastlane.send_message(m):
            return
        self.transport.send(m)

    def send_snapshot_message(self, m: Message) -> None:
        target = self.node_registry.resolve(m.cluster_id, m.to)
        if target == self.raft_address():
            node = self._clusters.get(m.cluster_id)
            if node is not None and node.node_id == m.to:
                node.handle_message_batch(m)
                return
        # on-disk SMs stream their live state through a per-transfer job
        # instead of chunking a snapshot file (reference nodehost.go:1796:
        # witness/in-memory -> file send; on-disk -> stream)
        sender = self._clusters.get(m.cluster_id)
        witness = m.snapshot is not None and m.snapshot.witness
        if sender is not None and sender.sm.on_disk and not witness:
            sender.push_stream_snapshot_request(m.to)
            return
        if not self.transport.send_snapshot(m):
            self._snapshot_status(m.cluster_id, m.to, True)

    def _message_router(self, batch: MessageBatch) -> None:
        """Reference ``messageHandler`` ``nodehost.go:2013``.

        Messages are queued first and step-readiness is signalled once per
        touched group — a batch regularly carries several messages for the
        same group and per-message wakeups are measurable overhead."""
        if not self._router_ready:
            # mid-construction: drop, the senders retry.  Visible, not
            # silent — a long gated window looks like a dead peer
            self._router_gated_drops += 1
            if self._router_gated_drops == 1:
                plog.warning(
                    "inbound batch dropped: NodeHost still constructing"
                )
            return
        touched = {}
        src = batch.source_address
        for m in batch.requests:
            ctx = m.trace
            if ctx is not None:
                # replication tracing (ISSUE 14): inbound stamp in THIS
                # host's clock.  First touch is the follower's
                # ``repl_recv``; the same context echoed back on the ack
                # lands here again on the leader as the ack-receive.
                if not ctx.t_recv:
                    ctx.t_recv = time.time()
                elif not ctx.t_ack_recv:
                    ctx.t_ack_recv = time.time()
            if m.type == MessageType.SNAPSHOT_RECEIVED:
                # follower's ack for a sent snapshot: accelerates the
                # parked status release; never delivered to raft
                # (reference nodehost.go:2039-2044)
                self.snapshot_feedback.confirm(
                    m.cluster_id, m.from_, self._now_ms()
                )
                continue
            node = self._clusters.get(m.cluster_id)
            if node is None or node.node_id != m.to:
                continue
            if src:
                # learn the sender's address so replies route before
                # membership is applied locally (reference nodes.go)
                self.node_registry.add_remote(m.cluster_id, m.from_, src)
            # a non-fast message reaching Python for a fast-lane group means
            # the native core could not serve it: complete the eject handoff
            # FIRST so the scalar raft state is current when it handles the
            # message (fastlane.py eject protocol).  Fast-wire types are
            # NOT ejected for: they are frames that raced (re)enrollment
            # through the leftover pump — the enrolled step feeds them to
            # the native core in mq order (node._fast_lane_step), which was
            # the dominant round-3 eject storm (router:REPLICATE /
            # router:HEARTBEAT ~2-3k per rank, enrollment duty ~1/3)
            if node.fast_lane and m.type not in _FAST_WIRE_TYPES:
                if (
                    m.type is MessageType.REQUEST_VOTE_RESP
                    and m.term <= node.peer.raft.term
                ):
                    # straggler from the pre-enrollment election: an
                    # enrolled group is never a candidate, so scalar raft
                    # would no-op it — not worth an eject (term read is
                    # lock-free but safe: a racing campaign bumps the term,
                    # making a stale resp stale still)
                    if self.fastlane is not None:
                        self.fastlane.count_drop("router-stale-vote-resp")
                    continue
                if self.fastlane is not None:
                    self.fastlane.count_eject(f"router:{m.type.name}")
                # a REQUEST_VOTE reaching an enrolled follower means an
                # election is in progress (a netsplit peer campaigning).
                # Without the re-enroll backoff the group re-enrolls
                # within one step — before the scalar election clock ages
                # past the §6 vote-drop lease (frozen while enrolled, and
                # leader_id is still the stale pre-split leader) — so the
                # vote is dropped and every native liveness clock resets:
                # the candidate's own retries keep the group enrolled
                # forever (the partition_tcp no-leader stall)
                node.fast_eject(
                    reenroll_backoff=m.type is MessageType.REQUEST_VOTE
                )
            if node.enqueue_message(m):
                touched[m.cluster_id] = None
        engine = self.engine
        for cid in touched:
            engine.set_step_ready(cid)

    def _now_ms(self) -> int:
        return int(time.monotonic() * 1000)

    def _snapshot_status(self, cluster_id: int, node_id: int, failed: bool):
        """Transport finished a snapshot send: park the status with the
        feedback tracker instead of reporting to raft immediately
        (reference messageHandler.HandleSnapshotStatus nodehost.go:2063)."""
        self.snapshot_feedback.add_status(
            cluster_id, node_id, failed, self._now_ms()
        )

    def _push_snapshot_status(
        self, cluster_id: int, node_id: int, failed: bool
    ) -> bool:
        node = self._clusters.get(cluster_id)
        if node is None:
            return True  # group gone; nothing to deliver
        return node.handle_snapshot_status(node_id, failed)

    def _snapshot_received(self, cluster_id: int, node_id: int, from_: int) -> None:
        """A streamed/chunked snapshot finished arriving: ack the sender so
        its feedback tracker releases the status quickly (reference
        messageHandler.HandleSnapshot nodehost.go:2090)."""
        self.send_message(
            Message(
                type=MessageType.SNAPSHOT_RECEIVED,
                cluster_id=cluster_id,
                from_=node_id,
                to=from_,
            )
        )

    def _unreachable(self, cluster_id: int, node_id: int) -> None:
        node = self._clusters.get(cluster_id)
        if node is not None:
            node.handle_unreachable(node_id)

    # ---- ticks (reference tickWorkerMain nodehost.go:1725) ----

    def _tick_worker_main(self) -> None:
        interval = self.nhconfig.rtt_millisecond / 1000.0
        ticks = 0
        sweep = Soft.lazy_tick_sweep_ticks
        while not self._stopped.wait(interval):
            ticks += 1
            self.tick_count += 1
            now_tick = self.tick_count
            with self._mu:
                nodes = list(self._clusters.values())
            for n in nodes:
                if n is None:
                    continue
                if n.tick_lite():
                    # lazy delivery: the native core / device tick kernel
                    # owns this group's raft clock; wake it only when its
                    # pending-request GC could be overdue.  This is the
                    # O(groups)→O(active) tick-cost cut that lets one
                    # process hold tens of thousands of groups (reference
                    # quiesce.go solves the same scaling axis).
                    if (
                        now_tick - n._seen_tick >= sweep
                        and n.has_pending_requests()
                    ):
                        self.engine.set_step_ready(n.cluster_id)
                else:
                    n.request_tick()
            if self.quorum_coordinator is not None:
                # one device tick round per RTT for ALL registered groups
                self.quorum_coordinator.request_tick()
            tracer = self.tracer
            if tracer is not None:
                # stage-level stall watchdog (ISSUE 9): a sampled request
                # stuck >stall_ms in one stage auto-dumps its partial
                # trace + the recorder ring.  Fast path (nothing sampled
                # in flight) is two dict truthiness checks per RTT.
                tracer.check_stalls()
            replattr = self.replattr
            if replattr is not None:
                # expire commit records that will never close (dropped
                # proposals, lost quorums).  Fast path (no open records)
                # is one dict truthiness check per RTT.
                replattr.sweep()
            health = self.health
            if health is not None:
                # cluster health plane (ISSUE 13): one low-rate sample
                # per health_sample_ms cadence, detectors included.
                # Fast path (cadence not elapsed) is one float compare
                # per RTT; sample failures are swallowed inside.
                health.maybe_sample()
            if self._dump_requested:
                # SIGUSR2 arrived: run the dump HERE, not in the signal
                # handler (non-reentrant locks; see _install_dump_signal)
                self._dump_requested = False
                try:
                    self.debug_dump()
                except Exception:
                    plog.exception("SIGUSR2 debug dump failed")
            self.snapshot_feedback.push_ready(self._now_ms())
            if ticks % max(1, int(1.0 / max(interval, 0.001))) == 0:
                self.transport.tick()


def _raise_on_failure(r: RequestResult) -> None:
    if r.completed:
        return
    if r.timeout:
        raise TimeoutError_("request timed out")
    if r.rejected:
        raise RejectedError("request rejected")
    if r.dropped:
        raise RejectedError("request dropped")
    if r.terminated:
        raise ClusterNotFoundError("cluster terminated")
    raise RejectedError(f"request failed: {r.code}")

