"""Device-plane observability: flight recorder + quorum-engine metrics.

The fused ``(K,G,P)`` dispatch path is the system's hot core but was a
runtime black box: round-loop behavior, dispatch latency, staging depth,
recycle churn and read-slot occupancy were visible only in offline bench
artifacts, and both the sharded-XLA deadlock and the contact-loss stall
were diagnosed by printf archaeology.  This package gives the device
plane its own telemetry surface (the per-component metrics argument of
the compartmentalization line in PAPERS.md; BlackWater's "failure
handling lives on cheap continuous telemetry"):

- :mod:`recorder` — a lock-light fixed-size ring of per-dispatch span
  records (rounds in block, staged ack/vote/recycle/read counts, upload
  bytes, dispatch/egress wall time, multidev-mutex wait, egress rows and
  reads released, gate reason), dumpable as JSON on demand and
  AUTO-dumped when a span trips the stall threshold — the round-gate
  watchdog and the multi-device dispatch-lock wait feed the same check;
- :mod:`instruments` — ``EngineObs`` / ``CoordObs``: counters, gauges
  and latency histograms published into the existing
  :class:`dragonboat_tpu.events.MetricsRegistry`, so
  ``write_health_metrics`` exposes device-plane health next to the
  transport/node counters;
- :mod:`trace` — cross-plane REQUEST tracing (ISSUE 9): a sampled
  1-in-N of proposals/reads carries a per-stage trace context through
  ingress → raft step → WAL → device round → apply → egress, with
  stage histograms, a Perfetto/Chrome-trace export
  (``NodeHost.dump_trace``) and a stage-level stall watchdog that
  dumps the stuck request's partial trace plus this recorder's ring.
- :mod:`health` — the cluster health plane (ISSUE 13): continuous
  per-group/host health sampling into a rolling ring, anomaly
  detectors with open/close events and recovery-time attribution
  (``dragonboat_health_*`` families, ``NodeHost.health_report``), and
  the live scrape endpoint (``/metrics``, ``/healthz``,
  ``/debug/health``, ``/debug/trace``, ``/debug/devprof``).
- :mod:`recovery` — the closed-loop recovery plane (ISSUE 17): a
  RecoveryController subscribed to detector OPEN events drives
  guard-railed remediations (quorum_at_risk → evict dead voter +
  promote standing observer / add standby witness, leader_flap →
  transfer away from flapping hosts, devsm_rebind → force device
  release, commit_stall → fast-lane redrive; worker_flap
  observe-only), rate-limited per group, cooldown-gated, flap-damped,
  with a dry-run mode (``dragonboat_recovery_*`` families,
  ``NodeHost.recovery_report``).
- :mod:`devprof` — the device capacity & profiling plane (ISSUE 15):
  the HBM memory ledger + capacity model
  (``dragonboat_devprof_hbm_bytes{plane,artifact}``, max groups per
  device), the warm-set program registry (per-program XLA cost/memory
  analysis), a sampled device-time estimator with fused padding-waste
  accounting, and on-demand ``jax.profiler`` capture windows
  (``NodeHost.profile_device``).

Overhead contract (the ``_read_plane_used`` precedent; PR 3 took a −43%
host-path regression from ungated per-transition work): observability is
OFF by default.  ``BatchedQuorumEngine._obs`` stays ``None`` and every
hot-path site gates on a plain ``is not None`` attribute check, so an
obs-off engine keeps a bit-identical host path and eager-op set
(regression axis: ``bench._run_obs_axis`` asserts obs-on throughput
within 5% of obs-off).  The module-level latch below flips newly
constructed engines/coordinators on (tests, bench axes); live wiring
goes through ``NodeHostConfig.enable_metrics`` →
``TpuQuorumCoordinator.enable_obs``.
"""
from __future__ import annotations

import threading
from typing import Optional

from .recorder import FlightRecorder  # noqa: F401

_mu = threading.Lock()
_enabled = False
_recorder: Optional[FlightRecorder] = None


def enable(
    recorder: Optional[FlightRecorder] = None, stall_ms: Optional[float] = None
) -> FlightRecorder:
    """Flip the module latch: engines/coordinators constructed AFTER this
    call attach instruments automatically (existing instances opt in via
    their ``enable_obs()``).  Returns the recorder new instances share."""
    global _enabled, _recorder
    with _mu:
        if recorder is not None:
            _recorder = recorder
        elif _recorder is None:
            _recorder = FlightRecorder()
        if stall_ms is not None:
            _recorder.stall_ms = float(stall_ms)
        _enabled = True
        return _recorder


def disable() -> None:
    """Drop the latch; already-attached instruments stay attached."""
    global _enabled
    with _mu:
        _enabled = False


def enabled() -> bool:
    return _enabled


def default_recorder() -> FlightRecorder:
    """The shared recorder (created on first use)."""
    global _recorder
    with _mu:
        if _recorder is None:
            _recorder = FlightRecorder()
        return _recorder
